#!/bin/bash
# Serial TPU work queue with relay-wedge-safe recovery — the script form
# of the pattern in .claude/skills/verify/SKILL.md: the single-tenant
# chip behind the axon relay must see ONE process at a time, probes must
# never be timeout-killed (a killed claim resets the relay's recovery
# clock), and queued work must drain serially from the same loop that
# probed. Run ONE instance; pin CPU everywhere else while it lives.
#
# Usage: tpu_queue_loop.sh QUEUE_DIR [LOG]
#   QUEUE_DIR  holds numbered job scripts ([0-9]*.sh), run in lexical
#              order; each moves to QUEUE_DIR/done/ on success. A failed
#              job stays queued and the loop re-probes before retrying.
#              The loop exits when no numbered jobs remain.
#              launchers/queue_r05/ holds the queued-but-unrecorded r05
#              increments (frame-vs-XLA A/B, 20000/32768 board-curve
#              rows, 8k GQA re-record — see results/README.md);
#              launchers/queue_r06/ holds the board-sliced batched A/B
#              grid (B in {8,32,64} x boards in {64^2,128^2,500^2},
#              DESIGN.md §12 — ledger lines ride MOMP_LEDGER). One pass
#              of this loop over a directory drains it when a chip
#              window opens.
#   LOG        append-only log (default /tmp/tpu_queue.log).
#
# Env knobs (tests stub the probe; operators rarely need these):
#   TPUQ_PROBE_CMD  device probe command (default: a python jax.devices()
#                   probe with NO timeout — a hang is fine, a kill is not)
#   TPUQ_SLEEP      seconds between cycles after a failed probe or job
#                   (default 900)
#   TPUQ_SETTLE     seconds between consecutive chip processes (default
#                   60 — back-to-back claims have wedged the relay)
#   TPUQ_LEDGER     run-ledger path exported to jobs as MOMP_LEDGER; after
#                   each successful job the regression sentinel judges the
#                   newest entry (host-side, CPU-pinned — never a chip
#                   claim) and the verdict lands in LOG. Default:
#                   results/ledger.jsonl next to this script's repo;
#                   set empty to disable the ledger+sentinel step.
#   TPUQ_SENTINEL_FATAL  1 = a sentinel "fail" verdict stops the loop
#                   with exit 1 (CI semantics); default 0 = log the
#                   REGRESSION and keep draining (operator semantics —
#                   the queued jobs are usually the fix).
set -u
QUEUE=${1:?usage: tpu_queue_loop.sh QUEUE_DIR [LOG]}
LOG=${2:-/tmp/tpu_queue.log}
# The inner quotes must survive into the variable (the probe is run via
# eval): an unquoted default would hand eval the bare words and die on
# the parenthesis before ever reaching the chip.
PROBE=${TPUQ_PROBE_CMD:-"python -c 'import jax; print(jax.devices())'"}
SLEEP=${TPUQ_SLEEP:-900}
SETTLE=${TPUQ_SETTLE:-60}
# Every sleep in the loop is followed by a chip claim (the probe), so a
# short TPUQ_SLEEP (handy when stubbing the probe in tests) must never
# undercut the settle gap on the failed-job -> re-probe path. Clamp,
# integers only — a non-numeric override is left alone rather than
# guessed at.
case "$SLEEP$SETTLE" in
    *[!0-9]*) ;;
    *) [ "$SLEEP" -lt "$SETTLE" ] && SLEEP=$SETTLE ;;
esac
REPO=$(cd "$(dirname "$0")/.." && pwd)
LEDGER=${TPUQ_LEDGER-"$REPO/results/ledger.jsonl"}
SENTINEL_FATAL=${TPUQ_SENTINEL_FATAL:-0}
[ -n "$LEDGER" ] && export MOMP_LEDGER="$LEDGER"

log() { echo "[$(date -u +%F' '%H:%M:%S)] $*" >>"$LOG"; }

# Judge the newest ledger entry against its rolling baseline. Host-side
# JSONL work: pinned to CPU so it can never claim the chip a queued job
# is settling toward. Returns the sentinel's exit code (0 pass /
# no-baseline, 1 regression, 2 unreadable ledger).
sentinel() {
    [ -n "$LEDGER" ] && [ -f "$LEDGER" ] || return 0
    local verdict rc
    verdict=$(JAX_PLATFORMS=cpu python "$REPO/analysis/regression_sentinel.py" \
        "$LEDGER" 2>>"$LOG")
    rc=$?
    log "sentinel ($rc): $verdict"
    if [ "$rc" -eq 1 ]; then
        log "REGRESSION: newest run regressed vs its ledger baseline"
        if [ "$SENTINEL_FATAL" = "1" ]; then
            log "TPUQ_SENTINEL_FATAL=1; stopping loop"
            exit 1
        fi
    fi
    return "$rc"
}

log "loop start (pid $$, queue $QUEUE)"
while true; do
    remaining=$(ls "$QUEUE"/[0-9]*.sh 2>/dev/null | wc -l)
    if [ "$remaining" -eq 0 ]; then
        log "queue empty; exiting"
        exit 0
    fi
    # The probe is itself a chip claim: honor the settle gap before it,
    # same as between jobs (back-to-back claims have wedged the relay).
    sleep "$SETTLE"
    log "probing devices"
    if eval "$PROBE" >>"$LOG" 2>&1; then
        log "chip up; draining queue"
        drained=1
        for job in "$QUEUE"/[0-9]*.sh; do
            [ -e "$job" ] || continue
            sleep "$SETTLE"
            log "run $job"
            if bash "$job" >>"$LOG" 2>&1; then
                mkdir -p "$QUEUE/done" && mv "$job" "$QUEUE/done/"
                log "done $job"
                sentinel || true
            else
                rc=$?
                if [ "$rc" -eq 75 ]; then
                    # EX_TEMPFAIL: the job preempted itself after
                    # flushing a checkpoint (robust.preempt contract) —
                    # keep it queued; its own --resume continues the
                    # work on the next drain pass.
                    log "PREEMPTED $job (rc 75; checkpoint flushed; kept queued for --resume)"
                else
                    log "FAILED $job (rc $rc, kept queued); re-probing"
                fi
                drained=0
                break
            fi
        done
        # A clean drain pass goes straight back to the (now empty)
        # queue check — the long sleep is for broken states only. Settle
        # first: if jobs remain the next cycle re-probes immediately.
        [ "$drained" -eq 1 ] && { sleep "$SETTLE"; continue; }
    else
        log "probe failed; sleep ${SLEEP}s"
    fi
    sleep "$SLEEP"
done
