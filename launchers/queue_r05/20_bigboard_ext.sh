#!/usr/bin/env bash
# r05 queued increment (results/README.md outage note): extend the
# committed board curve with the 20000^2 and 32768^2 rows (both beyond
# the largest recorded size; 20000 unaligned -> frame path, 32768
# aligned -> fused). --update merges the new rows into the existing CSV.
set -euo pipefail
cd "$(dirname "$0")/../.."

python analysis/sweep_bigboard.py --sizes 20000 32768 --update \
  --out results/life/bigboard_tpu.csv
