#!/usr/bin/env bash
# r05 queued increment (results/README.md outage note): re-record the
# 8k GQA row (kv-heads=2) — the committed row predates the per-hop ring
# engine stamps, so the re-record also lands hop_engine/hop_engine_bwd
# provenance. --update replaces just the seq=8192 row of the GQA CSV.
set -euo pipefail
cd "$(dirname "$0")/../.."

python analysis/sweep_attention.py --seqs 8192 --kv-heads 2 --update \
  --out results/attention/attention_gqa_tpu.csv
