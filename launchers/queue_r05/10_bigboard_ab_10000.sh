#!/usr/bin/env bash
# r05 queued increment (results/README.md outage note): frame-vs-XLA A/B
# at the unaligned 10000^2 board — the natural (padded-frame) dispatcher
# row plus an xla-forced row, merged next to the committed board curve.
# Drained by launchers/tpu_queue_loop.sh; one chip process, exits nonzero
# on any failure so the loop keeps it queued.
set -euo pipefail
cd "$(dirname "$0")/../.."

python analysis/sweep_bigboard.py --ab 10000 --update \
  --out results/life/bigboard_tpu.csv
