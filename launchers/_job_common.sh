# Shared helpers for the job_* multi-process launchers (sourced, not run).
#
# Process topology travels in the environment — JOB_COORDINATOR
# (host:port), JOB_NUM_PROCS, JOB_PROC_ID — the role PBS's $PBS_NODEFILE +
# mpirun played for the reference (/root/reference/3-life/job_life.sh:2-8).
# The framework CLIs consume it via --distributed (apps/_common.py).

# Best-effort free port. Inherent TOCTOU: the port is released before
# rank 0's coordinator binds it, so a concurrent process can steal it in
# between (the failure is loud — the sweep dies or times out, not silent
# corruption). Export JOB_PORT to pin a known-free port instead.
free_port() {
  if [[ -n "${JOB_PORT:-}" ]]; then
    echo "$JOB_PORT"
    return
  fi
  python - <<'EOF'
import socket
s = socket.socket()
s.bind(("localhost", 0))
print(s.getsockname()[1])
s.close()
EOF
}

# run_ranks NP CMD...: spawn NP ranks of CMD on this machine (CPU backend,
# one device per process — the single-machine stand-in for a DCN pod; the
# mechanism tests/test_distributed.py proves) and wait for all of them.
# Under a real scheduler this function is what srun/pbsdsh replaces: each
# rank just runs CMD with the three JOB_* variables exported.
run_ranks() {
  local np="$1"; shift
  local port
  port=$(free_port)
  local pids=() i
  for i in $(seq 0 $((np - 1))); do
    env -u XLA_FLAGS JAX_PLATFORMS=cpu \
      JOB_COORDINATOR="localhost:$port" \
      JOB_NUM_PROCS="$np" JOB_PROC_ID="$i" \
      "$@" &
    pids+=($!)
  done
  local rc=0 pid
  for pid in "${pids[@]}"; do
    wait "$pid" || rc=$?
  done
  return "$rc"
}
