#!/usr/bin/env bash
# Fabric probe at two placements (reference job_single.sh vs job_mult.sh:
# shared-memory vs NIC transport). Here the two interesting placements are
# the single-chip loopback and the full mesh over ICI; multi-host pods add
# a DCN row. Writes out_single.csv / out_mesh.csv for analysis/plot_network.py.
#
# Usage: launchers/run_pingpong.sh [--virtual]
set -euo pipefail
cd "$(dirname "$0")/.."

VFLAG=()
if [[ "${1:-}" == --virtual ]]; then
  VFLAG=(--virtual-devices 8)
fi

python -m mpi_and_open_mp_tpu.apps.pingpong "${VFLAG[@]}" --devices 1 \
  --out out_single.csv --fit
python -m mpi_and_open_mp_tpu.apps.pingpong "${VFLAG[@]}" \
  --out out_mesh.csv --fit
echo "plot with: python analysis/plot_network.py out_single.csv out_mesh.csv"
