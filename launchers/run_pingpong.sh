#!/usr/bin/env bash
# Fabric probe at two placements (reference job_single.sh vs job_mult.sh:
# shared-memory vs NIC transport). Here the two placements are the
# single-chip run and the full local mesh over ICI; multi-host pods add a
# DCN row (launchers/job_pingpong.sh probes the process-boundary analogue).
#
# CAVEAT (single-chip hosts): with --devices 1 the "ring" is a
# self-permute — there is no second ICI endpoint, so the CSV measures the
# on-device dispatch/copy floor, NOT transport (cf. the committed
# results/network/out_tpu_loopback.csv provenance note). The reference's
# shared-memory-vs-NIC contrast needs >=2 real chips; until then the
# meaningful contrast is job_pingpong.sh's single vs mult placements.
#
# Usage: launchers/run_pingpong.sh [--virtual]
set -euo pipefail
cd "$(dirname "$0")/.."

VFLAG=()
if [[ "${1:-}" == --virtual ]]; then
  VFLAG=(--virtual-devices 8)
fi

python -m mpi_and_open_mp_tpu.apps.pingpong "${VFLAG[@]}" --devices 1 \
  --out out_single.csv --fit
python -m mpi_and_open_mp_tpu.apps.pingpong "${VFLAG[@]}" \
  --out out_mesh.csv --fit
echo "plot with: python analysis/plot_network.py out_single.csv out_mesh.csv"
