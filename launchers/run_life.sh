#!/usr/bin/env bash
# Local Life scaling sweep — the TPU-era analogue of the reference's
# run_life.sh (sweep np=1..12, append wall seconds to times.txt, plot with
# plot_life.py). Same contract: one bare-seconds line per device count in
# times.txt; analysis/plot_life.py consumes the result unchanged.
#
# Usage:
#   launchers/run_life.sh [--backend=tpu|mpi] [--cfg=FILE] [--max-dev=N]
#                         [--layout=row|col|cart] [--virtual]
#                         [--times-file=FILE]
#
#   --backend=tpu  (default) run this framework's CLI, sweeping device count
#                  1..max-dev over the real devices. Pass --virtual to run
#                  the sweep on virtual CPU devices instead (required on a
#                  single-chip host when max-dev > 1).
#   --backend=mpi  run the original MPI reference program via mpirun for a
#                  side-by-side baseline. Self-contained: the binary is
#                  built on demand from the reference sources
#                  (mpi_baseline/Makefile, layout-matched variant) when
#                  MPI_LIFE_BIN doesn't already point at one. Needs an MPI
#                  toolchain (mpicc + mpirun) on PATH.
set -euo pipefail
cd "$(dirname "$0")/.."

BACKEND=tpu
CFG=configs/gun_big_500x500.cfg
MAXDEV=8
LAYOUT=row
VIRTUAL=0
TIMES=times.txt
for arg in "$@"; do
  case "$arg" in
    --backend=*)    BACKEND="${arg#*=}" ;;
    --cfg=*)        CFG="${arg#*=}" ;;
    --max-dev=*)    MAXDEV="${arg#*=}" ;;
    --layout=*)     LAYOUT="${arg#*=}" ;;
    --virtual)      VIRTUAL=1 ;;
    --times-file=*) TIMES="${arg#*=}" ;;
    *) echo "unknown arg: $arg" >&2; exit 2 ;;
  esac
done

if [[ "$BACKEND" == mpi ]]; then
  command -v mpirun >/dev/null || { echo "mpirun not found" >&2; exit 3; }
  if [[ -z "${MPI_LIFE_BIN:-}" ]]; then
    case "$LAYOUT" in
      row)  BIN=life_mpi ;;
      col)  BIN=life_col ;;
      cart) BIN=life_cart ;;
      *) echo "--backend=mpi maps layouts row/col/cart only" >&2; exit 2 ;;
    esac
    make -C mpi_baseline "build/$BIN"
    MPI_LIFE_BIN="mpi_baseline/build/$BIN"
  fi
  for np in $(seq 1 "$MAXDEV"); do
    /usr/bin/time -f %e -o "$TIMES" -a \
      mpirun -np "$np" --map-by :OVERSUBSCRIBE "$MPI_LIFE_BIN" "$CFG"
  done
  exit 0
fi

for np in $(seq 1 "$MAXDEV"); do
  VFLAG=()
  if [[ "$VIRTUAL" == 1 ]]; then
    VFLAG=(--virtual-devices "$np")
  fi
  python -m mpi_and_open_mp_tpu.apps.life "$CFG" --layout "$LAYOUT" \
    "${VFLAG[@]}" --devices "$np" --times-file "$TIMES"
done
echo "wrote $TIMES; plot with: python analysis/plot_life.py $TIMES"
