#!/usr/bin/env bash
# Fabric probe at the two reference placements
# (/root/reference/2-network-params/job_single.sh:2 — 2 ranks, 1 node =
# shared-memory transport — vs job_mult.sh:2 — 1 rank on each of 2 nodes =
# NIC transport). The TPU-era contrast: "single" runs both ring members in
# one process (in-process XLA transfers — the ICI stand-in), "mult" runs
# one device per process over the distributed backend (the DCN stand-in).
# Each writes the reference CSV schema (out_single.csv / out_mult.csv) for
# plot.ipynb / analysis/plot_network.py.
#
# Usage:
#   launchers/job_pingpong.sh [--placement=single|mult] [--reps=N]
#                             [--out=FILE]
set -euo pipefail
cd "$(dirname "$0")/.."
source launchers/_job_common.sh

PLACEMENT=mult
REPS=100
MAXPOWER=6
OUT=""
for arg in "$@"; do
  case "$arg" in
    --placement=*) PLACEMENT="${arg#*=}" ;;
    --reps=*)      REPS="${arg#*=}" ;;
    --max-power=*) MAXPOWER="${arg#*=}" ;;
    --out=*)       OUT="${arg#*=}" ;;
    *) echo "unknown arg: $arg" >&2; exit 2 ;;
  esac
done

if [[ "$PLACEMENT" == single ]]; then
  OUT="${OUT:-out_single.csv}"
  env -u XLA_FLAGS python -m mpi_and_open_mp_tpu.apps.pingpong \
    --devices 2 --virtual-devices 2 --reps "$REPS" \
    --max-power "$MAXPOWER" --out "$OUT"
else
  OUT="${OUT:-out_mult.csv}"
  run_ranks 2 python -m mpi_and_open_mp_tpu.apps.pingpong \
    --distributed --reps "$REPS" --max-power "$MAXPOWER" --out "$OUT"
fi
echo "wrote $OUT" >&2
