#!/usr/bin/env bash
# Multi-process quadrature scaling sweep — the analogue of the reference's
# PBS batch script (/root/reference/1-integral/job_integral.sh:2-8, sweep
# np=1..28 of mpi_integral 1e12). N defaults to 1e9 locally; the reference's
# documented 1e12 runs actually computed N mod 2^32 (SURVEY §2 quirks) —
# pass --n=1000000000000 for the true thing on a pod.
#
# Usage:
#   launchers/job_integral.sh [--n=N] [--max-procs=N] [--times-file=FILE]
set -euo pipefail
cd "$(dirname "$0")/.."
source launchers/_job_common.sh

N=1000000000
MAXPROCS=4
TIMES=times_integral_job.txt
for arg in "$@"; do
  case "$arg" in
    --n=*)          N="${arg#*=}" ;;
    --max-procs=*)  MAXPROCS="${arg#*=}" ;;
    --times-file=*) TIMES="${arg#*=}" ;;
    *) echo "unknown arg: $arg" >&2; exit 2 ;;
  esac
done

for np in $(seq 1 "$MAXPROCS"); do
  run_ranks "$np" python -m mpi_and_open_mp_tpu.apps.integral "$N" \
    --devices "$np" --distributed --times-file "$TIMES"
done
echo "wrote $TIMES" >&2
