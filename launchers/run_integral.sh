#!/usr/bin/env bash
# Quadrature scaling sweep (reference run_integral.sh analogue): N=10^12
# trapezoids — for real this time; the reference's atoi truncated it to
# ~3.57e9 (see BASELINE.md). Appends seconds to times.txt.
#
# Usage: launchers/run_integral.sh [--backend=tpu|mpi] [--n=N] [--max-dev=N]
#        [--virtual] [--times-file=FILE]
set -euo pipefail
cd "$(dirname "$0")/.."

BACKEND=tpu
N=1000000000000
MAXDEV=8
VIRTUAL=0
TIMES=times.txt
for arg in "$@"; do
  case "$arg" in
    --backend=*) BACKEND="${arg#*=}" ;;
    --n=*)       N="${arg#*=}" ;;
    --max-dev=*) MAXDEV="${arg#*=}" ;;
    --virtual)   VIRTUAL=1 ;;
    --times-file=*) TIMES="${arg#*=}" ;;
    *) echo "unknown arg: $arg" >&2; exit 2 ;;
  esac
done

if [[ "$BACKEND" == mpi ]]; then
  command -v mpirun >/dev/null || { echo "mpirun not found" >&2; exit 3; }
  if [[ -z "${MPI_INTEGRAL_BIN:-}" ]]; then
    make -C mpi_baseline build/mpi_integral
    MPI_INTEGRAL_BIN=mpi_baseline/build/mpi_integral
  fi
  for np in $(seq 1 "$MAXDEV"); do
    /usr/bin/time -f %e -o "$TIMES" -a \
      mpirun -np "$np" --map-by :OVERSUBSCRIBE "$MPI_INTEGRAL_BIN" "$N"
  done
  exit 0
fi

for np in $(seq 1 "$MAXDEV"); do
  if [[ "$VIRTUAL" == 1 ]]; then
    python -m mpi_and_open_mp_tpu.apps.integral "$N" \
      --virtual-devices "$np" --devices "$np" --times-file "$TIMES"
  else
    python -m mpi_and_open_mp_tpu.apps.integral "$N" \
      --devices "$np" --times-file "$TIMES"
  fi
done
echo "wrote $TIMES"
