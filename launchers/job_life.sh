#!/usr/bin/env bash
# Multi-process (multi-host-style) Life scaling sweep — the analogue of the
# reference's PBS batch script (/root/reference/3-life/job_life.sh:2-8:
# 7 nodes x 4 ppn, sweep np=1..28, one wall-seconds line per np appended to
# times.txt by one rank).
#
# Scheduler-agnostic: each rank is one invocation of the framework CLI with
# --distributed; topology travels in the JOB_* environment (see
# _job_common.sh). Run locally (default) and this script spawns the ranks
# itself; under a real scheduler, have each rank run
#
#   python -m mpi_and_open_mp_tpu.apps.life CFG --distributed ...
#
# with JOB_COORDINATOR/JOB_NUM_PROCS/JOB_PROC_ID exported per rank (e.g.
# srun --export=... or a pbsdsh wrapper) — run_ranks below is exactly the
# part the scheduler replaces.
#
# Usage:
#   launchers/job_life.sh [--cfg=FILE] [--max-procs=N] [--layout=...]
#                         [--times-file=FILE] [--fuse-steps=K]
# --fuse-steps=K exchanges one depth-K halo per K local steps — the lever
# that amortises the (expensive) cross-process exchange, cf. the depth-k
# ghost option discussed at SURVEY.md §7 hard-part (4).
set -euo pipefail
cd "$(dirname "$0")/.."
source launchers/_job_common.sh

CFG=configs/gun_big_500x500.cfg
MAXPROCS=4
LAYOUT=row
TIMES=times_job.txt
FUSE=1
for arg in "$@"; do
  case "$arg" in
    --cfg=*)        CFG="${arg#*=}" ;;
    --max-procs=*)  MAXPROCS="${arg#*=}" ;;
    --layout=*)     LAYOUT="${arg#*=}" ;;
    --times-file=*) TIMES="${arg#*=}" ;;
    --fuse-steps=*) FUSE="${arg#*=}" ;;
    *) echo "unknown arg: $arg" >&2; exit 2 ;;
  esac
done

for np in $(seq 1 "$MAXPROCS"); do
  run_ranks "$np" python -m mpi_and_open_mp_tpu.apps.life "$CFG" \
    --layout "$LAYOUT" --fuse-steps "$FUSE" --distributed \
    --times-file "$TIMES"
done
echo "wrote $TIMES; plot with: python analysis/plot_life.py $TIMES" >&2
