#!/usr/bin/env bash
# Multi-process long-context attention job — the scheduler-shaped
# launcher for the layer with no reference analog (the ring/Ulysses
# drivers), same topology-via-environment contract as the other job_*
# launchers (the role PBS's $PBS_NODEFILE + mpirun played for the
# reference, /root/reference/3-life/job_life.sh:2-8). Each rank holds
# one CPU device; the sp ring's ppermutes cross real process
# boundaries (the DCN-pod stand-in that tests/test_distributed.py
# proves).
#
# Usage:
#   launchers/job_attention.sh [--procs=N] [--variant=ring|ulysses]
#                              [--seq=N] [--heads=N] [--head-dim=N]
#                              [--kv-heads=N] [--layout=contiguous|zigzag]
#                              [--causal] [--grad] [--times-file=FILE]
set -euo pipefail
cd "$(dirname "$0")/.."
source launchers/_job_common.sh

PROCS=2
VARIANT=ring
SEQ=512
HEADS=4
HEADDIM=16
KVHEADS=""
LAYOUT=contiguous
CAUSAL=""
GRAD=""
TIMES=""
for arg in "$@"; do
  case "$arg" in
    --procs=*)      PROCS="${arg#*=}" ;;
    --variant=*)    VARIANT="${arg#*=}" ;;
    --seq=*)        SEQ="${arg#*=}" ;;
    --heads=*)      HEADS="${arg#*=}" ;;
    --head-dim=*)   HEADDIM="${arg#*=}" ;;
    --kv-heads=*)   KVHEADS="${arg#*=}" ;;
    --layout=*)     LAYOUT="${arg#*=}" ;;
    --causal)       CAUSAL=1 ;;
    --grad)         GRAD=1 ;;
    --times-file=*) TIMES="${arg#*=}" ;;
    *) echo "unknown arg: $arg" >&2; exit 2 ;;
  esac
done

extra=()
[[ -n "$KVHEADS" ]] && extra+=(--kv-heads "$KVHEADS")
[[ "$LAYOUT" != contiguous ]] && extra+=(--ring-layout "$LAYOUT")
[[ -n "$CAUSAL" ]] && extra+=(--causal)
[[ -n "$GRAD" ]] && extra+=(--grad)

out=$(run_ranks "$PROCS" python -m mpi_and_open_mp_tpu.apps.attention \
  --distributed --variant "$VARIANT" --seq "$SEQ" --heads "$HEADS" \
  --head-dim "$HEADDIM" --dtype float32 ${extra[@]+"${extra[@]}"})
echo "$out"
if [[ -n "$TIMES" ]]; then
  # The elapsed-seconds contract line (printed by the primary rank
  # only) — matched by shape, since collective-backend banners (Gloo)
  # share stdout and can interleave ahead of it.
  echo "$out" | grep -Em1 '^[0-9]+\.[0-9]+$' >> "$TIMES"
fi
