#!/usr/bin/env bash
# Fault-tolerant serving drain — the queueable (tpu_queue_loop.sh) form
# of the daemon cycle, replacing the reference's PBS qsub-requeue
# workflow (docs/MIGRATION.md): the first pass admits a mixed-shape
# request burst and drains it through serve.daemon under a write-ahead
# ticket journal; ANY death — polite preemption (scheduler SIGTERM, or
# MOMP_CHAOS preempt=K, exit 75 after checkpointing the queue) or an
# impolite kill -9/OOM that runs no handler at all — leaves either the
# drain checkpoint or the journal behind, and the NEXT pass resumes
# whichever survives (WAL first: it is durable at every instruction,
# not just at the drain). No admitted ticket is ever dropped across
# passes. Idempotent by design: rerun until exit 0.
#
# Usage:
#   launchers/job_serve.sh [--requests=N] [--max-batch=B] [--shapes=S]
#                          [--checkpoint=PATH] [--wal=PATH]
#                          [--wal-fsync=POLICY] [--aot-cache=DIR]
#                          [--seed=K]
set -euo pipefail
cd "$(dirname "$0")/.."

REQUESTS=64
MAXBATCH=8
SHAPES=48x48,64x64
CKPT=/tmp/momp_serve_queue.state
WAL=/tmp/momp_serve.wal
WALFSYNC=every-record
AOTDIR="${MOMP_AOT_CACHE:-/tmp/momp_serve_aot}"
SEED=0
for arg in "$@"; do
  case "$arg" in
    --requests=*)   REQUESTS="${arg#*=}" ;;
    --max-batch=*)  MAXBATCH="${arg#*=}" ;;
    --shapes=*)     SHAPES="${arg#*=}" ;;
    --checkpoint=*) CKPT="${arg#*=}" ;;
    --wal=*)        WAL="${arg#*=}" ;;
    --wal-fsync=*)  WALFSYNC="${arg#*=}" ;;
    --aot-cache=*)  AOTDIR="${arg#*=}" ;;
    --seed=*)       SEED="${arg#*=}" ;;
    *) echo "unknown arg: $arg" >&2; exit 2 ;;
  esac
done

if [ -s "$WAL" ] || [ -f "$CKPT" ]; then
  echo "serve state survives ($WAL / $CKPT); resuming drained tickets" >&2
  python -m mpi_and_open_mp_tpu.serve.daemon \
    --requests 0 --resume --wal "$WAL" --wal-fsync "$WALFSYNC" \
    --aot-cache "$AOTDIR" --checkpoint "$CKPT" --verify
else
  python -m mpi_and_open_mp_tpu.serve.daemon \
    --requests "$REQUESTS" --shapes "$SHAPES" --max-batch "$MAXBATCH" \
    --seed "$SEED" --wal "$WAL" --wal-fsync "$WALFSYNC" \
    --aot-cache "$AOTDIR" --checkpoint "$CKPT" --verify
fi
# Only reached on a clean drain (set -e; a preempted pass exits 75
# above, a killed pass never gets here): drop the consumed state —
# journal, its compaction snapshots, checkpoint, and any stamped
# quarantine copies — so the next invocation starts a fresh burst
# instead of re-serving resolved work. The AOT cache is deliberately
# KEPT: executables are state-free and fingerprint-keyed, and a warm
# cache is the whole point — the next burst's first ticket must not
# pay a trace+compile.
rm -f "$CKPT" "$WAL" "$WAL".snap.* "$WAL".corrupt*
