#!/usr/bin/env bash
# Fault-tolerant serving drain — the queueable (tpu_queue_loop.sh) form
# of the daemon cycle, replacing the reference's PBS qsub-requeue
# workflow (docs/MIGRATION.md): the first pass admits a mixed-shape
# request burst and drains it through serve.daemon; a preemption
# (scheduler SIGTERM, or MOMP_CHAOS preempt=K) finishes the in-flight
# batch, checkpoints the pending queue (crash-atomic CRC state file),
# and exits 75 — the queue loop keeps this script queued, and the NEXT
# pass finds the checkpoint and resumes it, so no admitted ticket is
# ever dropped across passes. Idempotent by design: rerun until exit 0.
#
# Usage:
#   launchers/job_serve.sh [--requests=N] [--max-batch=B] [--shapes=S]
#                          [--checkpoint=PATH] [--seed=K]
set -euo pipefail
cd "$(dirname "$0")/.."

REQUESTS=64
MAXBATCH=8
SHAPES=48x48,64x64
CKPT=/tmp/momp_serve_queue.state
SEED=0
for arg in "$@"; do
  case "$arg" in
    --requests=*)   REQUESTS="${arg#*=}" ;;
    --max-batch=*)  MAXBATCH="${arg#*=}" ;;
    --shapes=*)     SHAPES="${arg#*=}" ;;
    --checkpoint=*) CKPT="${arg#*=}" ;;
    --seed=*)       SEED="${arg#*=}" ;;
    *) echo "unknown arg: $arg" >&2; exit 2 ;;
  esac
done

if [ -f "$CKPT" ]; then
  echo "serve checkpoint $CKPT exists; resuming drained tickets" >&2
  python -m mpi_and_open_mp_tpu.serve.daemon \
    --requests 0 --resume --checkpoint "$CKPT" --verify
else
  python -m mpi_and_open_mp_tpu.serve.daemon \
    --requests "$REQUESTS" --shapes "$SHAPES" --max-batch "$MAXBATCH" \
    --seed "$SEED" --checkpoint "$CKPT" --verify
fi
# Only reached on a clean drain (set -e; a preempted pass exits 75
# above): drop the consumed checkpoint so the next invocation starts a
# fresh burst instead of re-serving already-resolved tickets.
rm -f "$CKPT"
