#!/usr/bin/env bash
# r06 queued increment (ISSUE 10): the 128^2 middle point of the
# batched-layout A/B grid — still VMEM-resident in both layouts at
# every batch size, so this row isolates the vector-op win from any
# residency effect. Same three-row + ledger contract as 10_*.sh.
set -euo pipefail
cd "$(dirname "$0")/../.."

python analysis/sweep_bigboard.py --batch-ab 128 --batches 8 32 64 \
  --update --out results/life/batched_ab_tpu.csv
