#!/usr/bin/env bash
# r06 queued increment (ISSUE 10): the reference flagship 500^2 board
# batched. B=64 overflows the conservative bitsliced VMEM gate (two
# planes), so its bitsliced arm runs the halo-fused XLA twin — the row
# that prices the layout beyond the kernel's residency window. Same
# three-row + ledger contract as 10_*.sh.
set -euo pipefail
cd "$(dirname "$0")/../.."

python analysis/sweep_bigboard.py --batch-ab 500 --batches 8 32 64 \
  --update --out results/life/batched_ab_tpu.csv
