#!/usr/bin/env bash
# r06 queued increment (ISSUE 10, DESIGN.md §12): board-sliced vs
# cell-packed batched A/B at the 64^2 small board — the layout's home
# turf, where dispatch amortization and the 32-boards-per-word layout
# stack. Three rows per batch size (bitsliced / cellpacked-native /
# xla-vmapped) on the same seeded stack, plus one ledger entry per
# (n, B) carrying bitsliced_cups + vs_cellpacked for the sentinel.
# Drained by launchers/tpu_queue_loop.sh (which exports MOMP_LEDGER);
# one chip process, exits nonzero on failure so the loop requeues it.
set -euo pipefail
cd "$(dirname "$0")/../.."

python analysis/sweep_bigboard.py --batch-ab 64 --batches 8 32 64 \
  --update --out results/life/batched_ab_tpu.csv
