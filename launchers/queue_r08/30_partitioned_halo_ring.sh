#!/usr/bin/env bash
# r08 queued increment (ISSUE 18, DESIGN.md §20): partitioned halo
# transport everywhere + the ring-attention hop prefetch on the real
# chip. Three legs, one chip process each, sequential:
#   1) the sharded A/B with MOMP_HALO_RDMA=1 — on a multi-chip ring the
#      col/cart Pallas async-remote-copy rungs (x-mirror, two-phase
#      corner exchange) and the partitioned-boundary sweep (:pb1
#      stamps) all run inside the phase; on the 1-chip bench topology
#      the phase reports sharded_ab_error (needs >= 2 devices) and the
#      line still lands — honest provenance either way.
#   2) the split-depth tune: interior fuse depth x boundary depth
#      enumerated independently (MOMP_TUNE_FUSE_DEPTHS=1,2,4,8 — the
#      deep rungs only the chip's exposed transfer can justify), the
#      coupled-depth heuristic always in the race, winners persisted to
#      the plan store for zero-retrace reuse.
#   3) the ring-attention hop-prefetch A/B: double-slot K/V rotation
#      (:pf) vs the single-slot schedule, parity-gated, chain-
#      differenced, exposed-transfer accounting from the rotation-only
#      microbench. Needs >= 3 devices; on one chip the phase reports
#      ring_ab_error and the line still lands.
# Every line lands in MOMP_LEDGER (exported by tpu_queue_loop.sh);
# losing overlap:*/:pb/:pf provenance later flags at the queue loop's
# sentinel gate. Exits nonzero on failure so the loop requeues it.
set -euo pipefail
cd "$(dirname "$0")/../.."

MOMP_HALO_RDMA=1 python bench.py --board 500 --steps 500 \
    --sharded-ab 64 --sharded-board 512

MOMP_HALO_RDMA=1 MOMP_TUNE_FUSE_DEPTHS=1,2,4,8 python bench.py \
    --board 500 --steps 500 --autotune 32 --tune-board 512 \
    --plans "${MOMP_TUNE_PLANS:-results/plans}"

python bench.py --board 500 --steps 500 --ring-ab 64
