#!/usr/bin/env bash
# r08 queued increment (ISSUE 17, DESIGN.md §19): the elastic fleet
# under open-loop load on the real chip. The CPU-mesh curve knees at
# hand-sized rates because every dispatch is a host-side XLA-CPU step;
# on the chip the interesting question inverts — the ~70 ms relay RTT
# per host round trip dominates small batches, so the saturation knee
# measures how well the bucket batcher amortises the tunnel, and
# rejoin_recovery_s prices a REAL recompile warm-up behind the
# warming-heartbeat cover (CPU warms in milliseconds; the chip's
# 20-40 s remote Mosaic compile is the case the cover exists for).
# Two rungs: a modest ladder to find the knee, then the membership
# cycle rides at it automatically (wedge busiest at 25%, REJOIN at
# 45%, drain at 65%) — the line must land loadgen_cycle_ok with
# parity, balanced books, zero acked loss, recovery >= 0.9. Durations
# are generous: open-loop arrivals keep coming during compile stalls,
# which is exactly the honesty the generator exists to enforce. One
# chip process per bench run, sequential; exits nonzero on failure so
# the loop requeues it.
set -euo pipefail
cd "$(dirname "$0")/../.."

python bench.py --board 256 --steps 100 \
    --loadgen 2,4,8,16 --loadgen-duration 20 --loadgen-slo-p99 2.0

python bench.py --board 256 --steps 100 \
    --loadgen 8,16,32 --loadgen-duration 30 --loadgen-slo-p99 1.0
