#!/usr/bin/env bash
# r08 queued increment (ISSUE 16, DESIGN.md §18): the sparse x sharded
# composition on the real chip — the composed-engine A/B against the
# dense sharded runner and the single-device active-tile engine at the
# acceptance geometry (2048², ~1% live), at both the throughput tile
# (64) and the CPU-mesh winner (32), so the chip decides the tile trade
# for itself. On a single-device topology the phase reports
# sparse_sharded_error (needs >= 2 devices) and the line still lands;
# on a ring it must stamp sparse-sharded:row:t<tile> provenance with
# the final board BIT-identical to the dense sharded schedule and
# exchange_skips > 0 (dead-boundary rounds shipping the zero sentinel
# instead of the ppermute payload). Every line lands in MOMP_LEDGER
# (exported by tpu_queue_loop.sh) under the sparse-keyed baseline
# groups, so a later run whose plan silently degrades to dense:*
# (e.g. MOMP_SPARSE_SHARDED=0 left exported) flags at the queue loop's
# sentinel gate as a provenance downgrade. One chip process per bench
# run, sequential; exits nonzero on failure so the loop requeues it.
set -euo pipefail
cd "$(dirname "$0")/../.."

python bench.py --board 500 --steps 500 --sparse-sharded-ab 256 \
    --sparse-board 2048 --sparse-tile 64

python bench.py --board 500 --steps 500 --sparse-sharded-ab 256 \
    --sparse-board 2048 --sparse-tile 32

# Settled-session skip drill (the pool twin of the same bet): a still
# life among active resident sessions must stop dispatching once its
# per-lane fixed point is proven — on the chip that converts the ~70 ms
# relay RTT per skipped step group into zero — while snapshots stay
# oracle-exact (the skip is a proof, not an approximation).
python - <<'PYEOF'
import numpy as np

from mpi_and_open_mp_tpu import stencils
from mpi_and_open_mp_tpu.serve import ServePolicy, ServingDaemon

spec = stencils.get("life")
rng = np.random.default_rng(20260807)
daemon = ServingDaemon(ServePolicy(max_batch=4, max_wait_s=0.0))
boards = {}
for i in range(3):
    board = (rng.random((18, 18)) < 0.3).astype(np.uint8)
    if i == 0:
        board = np.zeros((18, 18), np.uint8)
        board[8:10, 8:10] = 1  # still life: block
    boards[f"s{i}"] = board
    daemon.create_session(f"s{i}", board)
for _ in range(6):
    for sid in boards:
        daemon.step_session(sid, 3)
for sid, board in boards.items():
    np.testing.assert_array_equal(
        daemon.snapshot_session(sid), stencils.oracle_run(spec, board, 18))
skips = daemon.summary()["pool_settled_skips"]
assert skips > 0, "settled still-life session never skipped a dispatch"
print(f"settled drill: {skips} dispatches skipped, all snapshots oracle-exact")
PYEOF
