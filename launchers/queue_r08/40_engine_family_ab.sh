#!/usr/bin/env bash
# r08 queued increment (ISSUE 20, DESIGN.md §22): the wide-radius
# engine-family race on the real chip. The CPU mesh already showed the
# offset table dethroned from radius 4 up (sep ~7x, fft ~19x at radius
# 8 on 128²); the chip decides where ITS crossover sits — the MXU/VPU
# balance, HBM-resident rfft2 plans, and the fused offset ladder's
# chained dispatch all move it, so the sweep runs the full radius
# ladder {1,4,8,16} at a board big enough that the widest kernel still
# has 4x headroom. Every family row is oracle-parity-gated BEFORE it
# is timed (sep/fft at the gate-owned float tolerances, offset
# bit-default) and chain-differenced (K vs 2K dispatch) so the ~70 ms
# relay RTT cancels. Every line lands in MOMP_LEDGER (exported by
# tpu_queue_loop.sh) with the engine_family provenance stamp, so a
# later run whose race silently collapses to the offset table (e.g.
# MOMP_ENGINE_FAMILY=offset left exported) flags at the queue loop's
# sentinel gate as a provenance downgrade, not a throughput blip. One
# chip process per bench run, sequential; exits nonzero on failure so
# the loop requeues it.
set -euo pipefail
cd "$(dirname "$0")/../.."

# The headline sweep: radius ladder at the acceptance geometry. The
# line must record radius_ab_crossover_radius (min radius where a
# non-offset family posts vs_offset >= 1.0) and stamp engine_family
# with the widest radius' winner.
python bench.py --board 500 --steps 500 --radius-ab 64 \
    --radius-board 256 --radius-list 1,4,8,16

# Wider board twin: FFT cost scales n·log n while the offset ladder
# scales r²·n, so the crossover can only move DOWN with board size —
# if it moves up, something (plan cache, padding, layout) regressed.
python bench.py --board 500 --steps 500 --radius-ab 64 \
    --radius-board 512 --radius-list 4,8,16

# Tuner drill: the families must enter the per-shape race and the
# winner must persist + reload through the plan store under the same
# fingerprint the daemon consults, with the sparse fuse-depth axis
# enumerated alongside (heuristic depth-16 clamp always candidate #0).
python - <<'PYEOF'
from mpi_and_open_mp_tpu.tune import runner, space

report = runner.tune("lenia", (2, 64, 64), steps=64)
timed = {m["path"] for m in report["measurements"]}
assert {"stencil:sep", "stencil:fft"} & timed, (
    f"no wide-radius family entered the race: {sorted(timed)}")
assert report["vs_heuristic"] >= 1.0, report["vs_heuristic"]
print(f"lenia tune: winner {report['tuned']['path']} "
      f"at {report['vs_heuristic']}x heuristic")

fuse = space.sparse_fuse_depths(1, space.SPARSE_SHARDED_TILE)
assert fuse[0] == min(space.SPARSE_FUSE_HEURISTIC,
                      space.SPARSE_SHARDED_TILE), fuse
assert len(fuse) > 1, "fuse axis enumerated only the heuristic"
print(f"sparse fuse axis: {fuse}")
PYEOF
