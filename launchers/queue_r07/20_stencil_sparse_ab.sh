#!/usr/bin/env bash
# r07 queued increment (ISSUE 13, DESIGN.md §15): stencil spec
# subsystem on the real chip — one non-life workload through the
# generic engine (gray_scott: two-channel float32, parity-gated
# stencil_steady_cups line), then the sparse active-tile A/B at the
# acceptance geometry (2048^2, ~1% active, tile 64): the sparse engine
# must clear the dense roll path with bit-exact parity, and the line's
# sparse_engine stamp (sparse:t64 vs dense:crossover) is what the
# sentinel ranks, so a silent fallback on-chip flags as a downgrade.
# Both lines land in MOMP_LEDGER (exported by tpu_queue_loop.sh) under
# the workload-keyed baseline groups. One chip process per bench run,
# sequential; exits nonzero on failure so the loop requeues it.
set -euo pipefail
cd "$(dirname "$0")/../.."

python bench.py --workload gray_scott --board 1024 --steps 500

python bench.py --sparse-ab 200 --sparse-board 2048 --sparse-tile 64
