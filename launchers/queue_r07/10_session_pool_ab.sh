#!/usr/bin/env bash
# r07 queued increment (ISSUE 12, DESIGN.md §14): device-resident
# session-pool A/B on the real chip — 32 resident sessions stepped
# through (slab, bit-lane) handles vs the same workload shipped
# board-by-board through the ticket path. On TPU the ship side pays the
# ~70 ms relay RTT per round both ways; the resident side pays it only
# at create, so session_vs_ship here is the number the pool exists for.
# The line lands in MOMP_LEDGER (exported by tpu_queue_loop.sh) stamped
# resident=pool, giving the sentinel its session_* baseline; parity is
# gated in-phase (session_parity) before any number is recorded. One
# chip process; exits nonzero on failure so the loop requeues it.
set -euo pipefail
cd "$(dirname "$0")/../.."

python bench.py --sessions 32
