#!/usr/bin/env bash
# r07 queued increment (ISSUE 14, DESIGN.md §16): the unified autotuner
# on the real chip — bounded measured tuning passes at the acceptance
# geometries (500^2 and 2048^2, B in {8, 32}), each landing a
# heuristic-vs-tuned A/B (tuned_cups / vs_heuristic, >= 1.0 by
# construction: the heuristic's choice is in the race) plus a durable
# momp-plan/1 record whose digest co-locates the plan with the serve
# layer's exported executable. The store persists across queue runs:
# the FIRST pass per config tunes fresh (plan_source=fresh), later
# passes reuse the installed plan with a zero-retrace tune phase
# (plan_source=store) — the sentinel ranks {store, fresh} > heuristic,
# so a plan store that silently stops applying on-chip flags as a
# provenance downgrade. Every line lands in MOMP_LEDGER (exported by
# tpu_queue_loop.sh) under the new plan-keyed baseline groups. One chip
# process per bench run, sequential; exits nonzero on failure so the
# loop requeues it.
set -euo pipefail
cd "$(dirname "$0")/../.."

export MOMP_TUNE_PLANS="${MOMP_TUNE_PLANS:-results/plans_r07}"

python bench.py --board 500 --steps 1000 \
    --autotune 200 --tune-board 500 --tune-batch 8

python bench.py --board 500 --steps 1000 \
    --autotune 200 --tune-board 500 --tune-batch 32

python bench.py --board 2048 --steps 500 \
    --autotune 200 --tune-board 2048 --tune-batch 8

python bench.py --board 2048 --steps 500 \
    --autotune 200 --tune-board 2048 --tune-batch 32
