#!/usr/bin/env bash
# r07 queued increment (ISSUE 15, DESIGN.md §17): persistent halo plans
# on the real chip — the overlap-vs-sequential sharded A/B at the
# acceptance geometries, then the same pass under MOMP_HALO_RDMA=1 so
# the Pallas async-remote-copy ghost rung (overlap:rdma, row layout)
# gets chip coverage the CPU CI cannot give it. On a single-device
# topology the phase reports sharded_ab_error (needs >= 2 devices) and
# the line still lands; on a ring it must stamp overlap:* provenance
# with vs_sequential >= 1.0 and bit-exact parity between the two
# schedules. Every line lands in MOMP_LEDGER (exported by
# tpu_queue_loop.sh) under the halo-keyed baseline groups, so a later
# run whose plan silently degrades to seq:* flags at the queue loop's
# sentinel gate as a provenance downgrade. One chip process per bench
# run, sequential; exits nonzero on failure so the loop requeues it.
set -euo pipefail
cd "$(dirname "$0")/../.."

python bench.py --board 500 --steps 1000 --sharded-ab 64 --sharded-board 512

python bench.py --board 2048 --steps 500 --sharded-ab 64 --sharded-board 2048

MOMP_HALO_RDMA=1 python bench.py --board 500 --steps 500 \
    --sharded-ab 64 --sharded-board 512
