"""Speedup plot from a ``times.txt`` sweep.

Script form of the reference's ``plot_life.py`` analysis
(``/root/reference/3-life/plot_life.py:4-17``): line k of ``times.txt`` is
the wall time at k devices/ranks; the plot is the speedup ``T1/TN`` as a
scatter plus dashed line, saved to ``life_accel.png``. Works on reference-
produced and TPU-produced times files alike (the CLI keeps the format).

Usage: ``python analysis/plot_life.py [times.txt] [out.png]``
"""

from __future__ import annotations

import sys

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402
import numpy as np  # noqa: E402


def load_times(path: str) -> np.ndarray:
    vals = []
    with open(path) as fd:
        for line in fd:
            line = line.strip()
            if not line:
                continue
            try:
                vals.append(float(line))
            except ValueError:
                # The reference's times files can contain gtime error lines
                # ("Command exited with non-zero status 1"); skip them.
                continue
    return np.array(vals)


def plot_speedup(times: np.ndarray, out: str) -> None:
    n = np.arange(1, len(times) + 1)
    speedup = times[0] / times
    fig, ax = plt.subplots(figsize=(7, 5))
    ax.scatter(n, speedup, zorder=3)
    ax.plot(n, speedup, linestyle="--", zorder=2)
    ax.plot(n, n, color="gray", linewidth=0.8, label="ideal")
    ax.set_xlabel("devices")
    ax.set_ylabel("speedup $T_1/T_N$")
    ax.grid(True, alpha=0.3)
    ax.legend()
    fig.tight_layout()
    fig.savefig(out, dpi=120)


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    times_path = argv[0] if argv else "times.txt"
    out = argv[1] if len(argv) > 1 else "life_accel.png"
    times = load_times(times_path)
    if len(times) == 0:
        print(f"{times_path}: no parsable times", file=sys.stderr)
        return 1
    plot_speedup(times, out)
    print(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
