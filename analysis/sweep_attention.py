"""Long-context attention scaling sweep on the real chip.

Times the causal flash attention that carries the long-context layer's
per-shard compute (`parallel.flash_attention` — the same engine
`ring_attention` folds per hop and `ulysses_attention` runs per head
group; on TPU eligible shapes dispatch to the bundled Pallas kernel,
else the jnp-chunked path with its flash custom_vjp backward) across
sequence lengths, forward and backward, in bfloat16 at (8 heads, d=128).

Marginal per-call seconds by the same RTT-cancelling discipline as
`bench.py`: chain R calls in one dispatch — each call's output feeds the
next call's queries so the chain cannot be elided — and difference a
longer chain (R=9 fwd, R=3 bwd) against R=1, best-of-3 each. TFLOP/s counts 2*h*n^2*d (QK^T + PV, causal
half). Emits a CSV:

    seq,fwd_sec,fwd_tflops,bwd_sec,bwd_tflops,differenced,engine,hop_engine,hop_engine_bwd

where `bwd_sec` times one FULL grad step (forward + backward per chain
link — a backward can't run without its forward), `bwd_tflops` uses
the matching fwd+bwd = 3.5x fwd accounting, and `engine` records which
attention engine+block configuration (e.g. `pallas:b1024`, with a
`:kvxG` suffix for the GQA expand dispatch, or `jnp`) produced the
row — a mid-sweep fallback is visible in the artifact. `hop_engine`
records what each K/V hop of a multi-device ring over the same global
operands would dispatch (`context.ring_hop_engine_for`; `local:`-
prefixed on a 1-device mesh) and `hop_engine_bwd` the matching ring
BACKWARD hop engine (`context.ring_hop_bwd_engine_for` — the
`ops.flash_hop_bwd` kernels vs the `_flash_block_grads` jnp fold) —
provenance for relating these single-chip rates to the ring's per-hop
engines, not a timing of the ring itself. `--kv-heads` sweeps a GQA/MQA configuration instead
(TFLOP/s still counts the q-heads, which carry the compute).

Usage: python analysis/sweep_attention.py [--out results/attention/attention_tpu.csv]

``--update`` MERGES into an existing CSV instead of overwriting, keyed
on seq — the r05 8k re-record replaces one row of the committed curve
without re-running the rest of a chip-hour sweep. Rows written under an
older (shorter) schema are padded with empty trailing fields to the
current header, so the merged file stays rectangular.
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

HEADS, DIM = 8, 128


def merge_rows(out_path: str, header: str, new_rows: list[str]) -> list[str]:
    """Header + data rows with ``new_rows`` merged over whatever
    ``out_path`` already holds, keyed on seq (first column) and sorted;
    rows from an older schema are padded to the header's width."""
    ncol = header.count(",") + 1
    merged: dict[int, str] = {}
    if os.path.exists(out_path):
        with open(out_path) as f:
            lines = [ln.strip() for ln in f if ln.strip()]
        for ln in lines[1:]:
            ln += "," * max(0, ncol - 1 - ln.count(","))
            merged[int(ln.split(",")[0])] = ln
    for ln in new_rows:
        merged[int(ln.split(",")[0])] = ln
    return [header] + [merged[k] for k in sorted(merged)]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="results/attention/attention_tpu.csv")
    ap.add_argument("--seqs", type=int, nargs="+",
                    default=[8192, 16384, 32768, 65536, 131072])
    ap.add_argument("--bwd-max", type=int, default=65536,
                    help="longest sequence to also time the backward at")
    ap.add_argument("--engine", choices=("auto", "jnp"), default="auto",
                    help="auto = let flash_attention dispatch to the "
                    "bundled Pallas TPU kernel on eligible shapes; jnp "
                    "= force the chunked XLA engine")
    ap.add_argument("--kv-heads", type=int, default=None,
                    help="GQA/MQA: fewer K/V heads (must divide the "
                    f"fixed {HEADS} q-heads); rows time the GQA engine "
                    "the dispatch picks (expand-to-Pallas within "
                    "budget, folded jnp otherwise) and the gate checks "
                    "that very configuration")
    ap.add_argument("--update", action="store_true",
                    help="merge rows into --out keyed on seq instead of "
                    "overwriting — incremental chip windows / re-records")
    args = ap.parse_args(argv)

    hkv = HEADS if args.kv_heads is None else args.kv_heads
    if hkv < 1 or HEADS % hkv:
        print(f"--kv-heads {hkv} must be a positive divisor of {HEADS}",
              file=sys.stderr)
        return 2

    import jax
    import jax.numpy as jnp
    from jax import lax

    if jax.default_backend() != "tpu":
        print("refusing to record: backend is not TPU", file=sys.stderr)
        return 1

    from mpi_and_open_mp_tpu.parallel import context
    from mpi_and_open_mp_tpu.parallel.context import flash_attention
    from mpi_and_open_mp_tpu.utils.timing import anchor_sync

    if args.engine == "jnp":
        context.disable_tpu_flash()

    rng = np.random.default_rng(0)

    def force_jnp(why: str) -> None:
        context.disable_tpu_flash()
        print(f"pallas engine disabled ({why}); jnp engine takes over",
              file=sys.stderr)

    # Honesty gate — shared with bench.py (context.gated_parity_check):
    # the engine flash_attention dispatches to must match the dense
    # oracle before any of its timings are recorded, with automatic
    # fallback (and re-gate) to the jnp engine on a Pallas failure so a
    # chip window is never lost to a kernel problem. Gated once per
    # DISTINCT engine+block configuration among the swept sequences
    # (for_seq pins each one), and re-run on every mid-sweep engine
    # flip too.
    gate_reps: dict[str, int] = {}
    for n in args.seqs:
        if n <= context._Q_CHUNK:
            # Dispatches the dense reference — the oracle itself;
            # nothing to gate.
            continue
        # Key by the EXACT provenance stamp the row will carry (engine,
        # block edge, GQA form — bf16 shape probes, nothing allocated),
        # so two sequences gate separately iff they dispatch differently.
        sq = jax.ShapeDtypeStruct((HEADS, n, DIM), jnp.bfloat16)
        skv = jax.ShapeDtypeStruct((hkv, n, DIM), jnp.bfloat16)
        gate_reps.setdefault(context.flash_engine_for(sq, skv, skv), n)
    engine = "dense"
    for rep in gate_reps.values():
        ok, engine, notes = context.gated_parity_check(
            HEADS, 2048, DIM, for_seq=rep, kv_heads=hkv)
        for note in notes:
            print(note, file=sys.stderr)
        if not ok:
            print("parity check failed; not recording", file=sys.stderr)
            return 1
    print(f"engine: {engine}", file=sys.stderr)

    @functools.partial(jax.jit, static_argnames=("r",))
    def fwd_chain(q, k, v, r):
        out, _ = lax.scan(
            lambda c, _: (flash_attention(c, k, v, causal=True), None),
            q, None, length=r)
        return out

    @functools.partial(jax.jit, static_argnames=("r",))
    def bwd_chain(q, k, v, r):
        # Unrolled, NOT lax.scan: differentiating THROUGH a scan whose body
        # is the custom_vjp attention makes JAX's scan linearisation stack
        # per-block forward intermediates (masks + K/V blocks, O(seq²)
        # per chain link — 16 GB at 32k) even though the custom backward
        # is what ends up used; the unrolled chain keeps residuals to the
        # declared (q, k, v, o, logsumexp) per link. See the note in
        # parallel/context.py.
        def loss(q_, k_, v_):
            c = q_
            for _ in range(r):
                c = flash_attention(c, k_, v_, causal=True)
            return (c.astype(jnp.float32) ** 2).sum()

        # All three grads: grad wrt q alone lets XLA prune the flash
        # backward's dk+dv pass entirely (custom_vjp outputs are DCE'd),
        # which silently times ~half the backward.
        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    def timed(fn, qkv, r):
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            anchor_sync(fn(*qkv, r=r), fetch_all=True)
            best = min(best, time.perf_counter() - t0)
        return best

    def marginal(fn, qkv, r2=9):
        # r2=3 for the backward: the unrolled chain compiles each link's
        # two bwd scans separately (~linear compile cost in r), and each
        # link's custom_vjp residuals (q, k, v, o + logsumexp) stay live
        # together — three links keep both inside budget while the
        # differenced signal still dominates the one ~70 ms RTT.
        anchor_sync(fn(*qkv, r=1), fetch_all=True)  # compile
        anchor_sync(fn(*qkv, r=r2), fetch_all=True)
        t1, t2 = timed(fn, qkv, 1), timed(fn, qkv, r2)
        if t2 > t1:
            return (t2 - t1) / (r2 - 1), True
        return t1, False

    from mpi_and_open_mp_tpu.utils.timing import write_csv_rows

    header = ("seq,fwd_sec,fwd_tflops,bwd_sec,bwd_tflops,differenced,engine,"
              "hop_engine,hop_engine_bwd")
    rows = [header]

    def flush() -> None:
        if args.update:
            write_csv_rows(args.out, merge_rows(args.out, header, rows[1:]))
        else:
            write_csv_rows(args.out, rows)

    for n in args.seqs:
        qkv = (jnp.asarray(rng.standard_normal((HEADS, n, DIM)),
                           jnp.bfloat16),
               *(jnp.asarray(rng.standard_normal((hkv, n, DIM)),
                             jnp.bfloat16) for _ in range(2)))
        flops = 2 * HEADS * n * n * DIM  # q-heads carry the compute

        def point():
            # Engine recorded per row, SHAPE-aware (a block override
            # that doesn't divide this seq routes it to jnp): a
            # mid-sweep fallback or per-shape downgrade must be visible
            # in the artifact, not only on stderr.
            engine = context.flash_engine_for(*qkv)
            hop = context.ring_hop_engine_for(*qkv, causal=True)
            hop_bwd = context.ring_hop_bwd_engine_for(*qkv, causal=True)
            fwd, diff_f = marginal(fwd_chain, qkv)
            if n <= args.bwd_max:
                # grad runs fwd + bwd; standard fwd+bwd accounting is
                # 3.5x the fwd FLOPs (bwd = 2.5x: 5 block matmuls vs 2).
                # The flash backward's score recompute is NOT counted —
                # achieved useful-FLOP/s only.
                bwd, diff_b = marginal(bwd_chain, qkv, r2=3)
                return (f"{n},{fwd:.5f},{flops / fwd / 1e12:.1f},"
                        f"{bwd:.5f},{3.5 * flops / bwd / 1e12:.1f},"
                        f"{int(diff_f and diff_b)},{engine},{hop},{hop_bwd}")
            return (f"{n},{fwd:.5f},{flops / fwd / 1e12:.1f},,,"
                    f"{int(diff_f)},{engine},{hop},{hop_bwd}")

        try:
            rows.append(point())
        except Exception as e:
            # A shape the Pallas kernel won't take through this stack
            # (VMEM, Mosaic) must not lose the whole sweep: fall back to
            # the jnp engine — re-gated before anything is recorded —
            # for this and later points. (Already-recorded rows are on
            # disk either way, via flush().)
            if not context._TPU_FLASH:
                raise
            force_jnp(f"{type(e).__name__} at seq {n}")
            ok, _, notes = context.gated_parity_check(
                HEADS, 2048, DIM, for_seq=n, kv_heads=hkv)
            for note in notes:
                print(note, file=sys.stderr)
            if not ok:
                print("jnp engine failed the parity gate after fallback;"
                      " not recording further", file=sys.stderr)
                return 1
            rows.append(point())
        flush()
        print(rows[-1], flush=True)

    print(f"wrote {args.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
