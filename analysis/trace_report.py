"""Trace-report CLI: summarise a ``MOMP_TRACE`` JSONL file.

Usage::

    python analysis/trace_report.py /tmp/trace.jsonl          # text tables
    python analysis/trace_report.py /tmp/trace.jsonl --json   # machine form

Text mode prints the per-span phase breakdown, the ring-attention hop
summary (span counts, engines, α+βn transfer fit when the trace carries
two or more hop sizes), recoveries by stamp, and the jit-retrace counters
from the last ``metrics`` snapshot event. ``--json`` emits the same data
as one JSON object (``obs.report.report_dict`` schema) — what the CI
trace cycle asserts against. ``--chrome OUT`` instead exports the spans
to Chrome trace-event JSON (``obs.report.to_chrome``) so the timeline
opens directly in Perfetto / chrome://tracing.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# A trace file is host-side data; nothing here needs (or should claim)
# the TPU. The fit path imports jax transitively, so pin the platform
# before any package import — the sitecustomize default is the TPU
# plugin, and a second TPU process would fight the real workload.
os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpi_and_open_mp_tpu.obs import report  # noqa: E402


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="analysis/trace_report.py")
    p.add_argument("trace", help="MOMP_TRACE JSONL file to summarise "
                   "(with --fleet: a fleet state DIRECTORY)")
    p.add_argument("--json", action="store_true",
                   help="emit the report as one JSON object")
    p.add_argument("--chrome", metavar="OUT",
                   help="write Chrome trace-event JSON (Perfetto-loadable) "
                   "here instead of reporting")
    p.add_argument("--fleet", action="store_true",
                   help="treat the positional as a fleet state dir and "
                   "merge every worker trace + sidecar into one timeline "
                   "(delegates to analysis/fleet_report.py)")
    p.add_argument("--router-trace", default=None, metavar="PATH",
                   help="with --fleet: the parent's own MOMP_TRACE file")
    args = p.parse_args(argv)

    if args.fleet:
        from analysis import fleet_report as fleet_mod

        argv2 = [args.trace]
        if args.router_trace:
            argv2 += ["--router-trace", args.router_trace]
        if args.chrome:
            argv2 += ["--chrome", args.chrome]
        if args.json:
            argv2.append("--json")
        return fleet_mod.main(argv2)

    try:
        records = report.load(args.trace)
    except (OSError, ValueError) as e:
        print(f"trace_report: {e}", file=sys.stderr)
        return 2
    if args.chrome:
        chrome = report.to_chrome(records)
        with open(args.chrome, "w") as fd:
            json.dump(chrome, fd)
        print(f"wrote {len(chrome['traceEvents'])} trace events "
              f"to {args.chrome}")
        return 0
    rep = report.report_dict(records)
    if args.json:
        print(json.dumps(rep))
    else:
        print(report.render(rep))
    return 0


if __name__ == "__main__":
    sys.exit(main())
