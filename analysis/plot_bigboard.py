"""Render the big-board scaling sweep (results/life/bigboard_tpu.csv).

One series — steady-state Gcups vs board edge on one chip — with each
point colored by the native path the serial dispatcher picked (VMEM-
resident / fused tiled / padded frame), log-x. The committed-PNG analog
of the reference's `plot_life.py` speedup rendering, for the board-size
scaling axis (SURVEY §7 step 8).

Colors are the first three slots of the repo's validated categorical
palette (documented all-pairs pass, light mode); identity is also carried
by direct labels, never color alone.

Usage: python analysis/plot_bigboard.py [csv] [out.png]
"""

from __future__ import annotations

import csv
import sys

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402

SURFACE = "#fcfcfb"
TEXT = "#0b0b0b"
TEXT_2 = "#52514e"
GRID = "#e5e4e0"
PATH_COLOR = {  # categorical slots 1-3 (validated all-pairs, light)
    "vmem": "#2a78d6",
    "fused": "#eb6834",
    "frame": "#1baf7a",
    "xla": "#52514e",
}
PATH_LABEL = {
    "vmem": "VMEM-resident loop",
    "fused": "fused tiled kernel",
    "frame": "padded torus frame",
    "xla": "XLA packed loop",
}


def main(argv) -> int:
    src = argv[1] if len(argv) > 1 else "results/life/bigboard_tpu.csv"
    out = argv[2] if len(argv) > 2 else "results/life/bigboard_tpu.png"
    with open(src) as f:
        rows = list(csv.DictReader(f))
    ns = [int(r["n"]) for r in rows]
    gc = [float(r["steady_gcups"]) for r in rows]
    paths = [r["path"] for r in rows]

    fig, ax = plt.subplots(figsize=(7.2, 4.2), dpi=160)
    fig.patch.set_facecolor(SURFACE)
    ax.set_facecolor(SURFACE)
    ax.plot(ns, gc, color=TEXT_2, lw=1.2, zorder=1, alpha=0.5)
    seen = set()
    for n, g, p in zip(ns, gc, paths):
        lbl = PATH_LABEL[p] if p not in seen else None
        seen.add(p)
        ax.scatter([n], [g], s=52, color=PATH_COLOR[p], label=lbl,
                   zorder=3, edgecolors=SURFACE, linewidths=1.5)
    peak = max(range(len(gc)), key=gc.__getitem__)
    notes = [(ns[0], gc[0], f"{ns[0]}² flagship\n{gc[0]:.0f}"),
             (ns[peak], gc[peak], f"peak {gc[peak]:.0f} Gcups")]
    notes += [(n, g, f"{n}² (unaligned)")
              for n, g, p in zip(ns, gc, paths) if p == "frame"]
    for n, g, txt in notes:
        ax.annotate(txt, (n, g), textcoords="offset points",
                    xytext=(6, -14), fontsize=7.5, color=TEXT_2)
    ax.set_xscale("log")
    ax.set_xticks(ns, [str(n) for n in ns], rotation=45, fontsize=8)
    ax.set_xticks([], minor=True)
    ax.set_ylim(0, max(gc) * 1.15)
    ax.set_xlabel("board edge (cells)", color=TEXT, fontsize=9)
    ax.set_ylabel("steady-state Gcups (one chip)", color=TEXT, fontsize=9)
    ax.set_title(
        "Game-of-Life board-size scaling, single TPU chip\n"
        "(differenced steady-state; MPI cluster best = 1.29 Gcups @ 27 ranks)",
        color=TEXT, fontsize=9.5,
    )
    ax.grid(axis="y", color=GRID, lw=0.7, zorder=0)
    for s in ("top", "right"):
        ax.spines[s].set_visible(False)
    for s in ("left", "bottom"):
        ax.spines[s].set_color(GRID)
    ax.tick_params(colors=TEXT_2, labelsize=8)
    leg = ax.legend(loc="lower right", fontsize=8, frameon=False)
    for t in leg.get_texts():
        t.set_color(TEXT)
    fig.tight_layout()
    fig.savefig(out, facecolor=SURFACE)
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
