"""Render the attention scaling sweep (results/attention/attention_tpu.csv).

Two series — forward and backward achieved TFLOP/s vs sequence length on
one chip, log-x. Colors are the first two slots of the repo's validated
categorical palette; both series are direct-labeled as well as legended.

Usage: python analysis/plot_attention.py [csv] [out.png]
"""

from __future__ import annotations

import csv
import sys

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402

SURFACE = "#fcfcfb"
TEXT = "#0b0b0b"
TEXT_2 = "#52514e"
GRID = "#e5e4e0"
C_FWD = "#2a78d6"
C_BWD = "#eb6834"


def main(argv) -> int:
    src = argv[1] if len(argv) > 1 else "results/attention/attention_tpu.csv"
    out = argv[2] if len(argv) > 2 else "results/attention/attention_tpu.png"
    with open(src) as f:
        rows = list(csv.DictReader(f))
    seqs = [int(r["seq"]) for r in rows]
    fwd = [float(r["fwd_tflops"]) for r in rows]
    bwd = [(int(r["seq"]), float(r["bwd_tflops"]))
           for r in rows if r["bwd_tflops"]]

    fig, ax = plt.subplots(figsize=(7.2, 4.0), dpi=160)
    fig.patch.set_facecolor(SURFACE)
    ax.set_facecolor(SURFACE)
    ax.plot(seqs, fwd, color=C_FWD, lw=2, marker="o", ms=7,
            markeredgecolor=SURFACE, markeredgewidth=1.5, label="forward")
    ax.annotate("forward", (seqs[-1], fwd[-1]), textcoords="offset points",
                xytext=(-8, 10), fontsize=8, color=TEXT_2, ha="right")
    if bwd:
        ax.plot([s for s, _ in bwd], [t for _, t in bwd], color=C_BWD,
                lw=2, marker="o", ms=7, markeredgecolor=SURFACE,
                markeredgewidth=1.5, label="backward (flash custom_vjp)")
        ax.annotate("backward", (bwd[-1][0], bwd[-1][1]),
                    textcoords="offset points", xytext=(-8, -16),
                    fontsize=8, color=TEXT_2, ha="right")
    ax.set_xscale("log")
    ax.set_xticks(seqs, [f"{s // 1024}k" for s in seqs], fontsize=8)
    ax.set_xticks([], minor=True)
    ax.set_ylim(0, max(fwd + [t for _, t in bwd]) * 1.2)
    ax.set_xlabel("sequence length (tokens)", color=TEXT, fontsize=9)
    ax.set_ylabel("achieved TFLOP/s (one chip)", color=TEXT, fontsize=9)
    engines = sorted({r.get("engine", "") for r in rows} - {"", None})
    eng = f"engine: {'/'.join(engines)}; " if engines else ""
    ax.set_title(
        "Causal flash attention scaling, bf16, 8 heads × d=128\n"
        f"({eng}marginal per-call, RTT-differenced; fwd+bwd = 3.5× "
        "fwd FLOP accounting)",
        color=TEXT, fontsize=9.5,
    )
    ax.grid(axis="y", color=GRID, lw=0.7, zorder=0)
    for s in ("top", "right"):
        ax.spines[s].set_visible(False)
    for s in ("left", "bottom"):
        ax.spines[s].set_color(GRID)
    ax.tick_params(colors=TEXT_2, labelsize=8)
    if bwd:  # single-series charts carry no legend box (title names it)
        leg = ax.legend(loc="lower right", fontsize=8, frameon=False)
        for t in leg.get_texts():
            t.set_color(TEXT)
    fig.tight_layout()
    fig.savefig(out, facecolor=SURFACE)
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
