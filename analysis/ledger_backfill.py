"""One-shot backfill: committed bench artifacts → the run ledger.

Usage::

    python analysis/ledger_backfill.py                 # repo defaults
    python analysis/ledger_backfill.py --root DIR --ledger OUT.jsonl

Feeds the pre-ledger committed measurements — the driver's end-of-round
``BENCH_r0*.json`` wrappers and the real-chip ``results/bench_tpu_r05.jsonl``
lines — through ``obs.ledger.stamp`` so the regression sentinel has a
real-chip baseline from day one, including the r04/r05 CPU-fallback lines
whose silent ~1000× degradation is the sentinel's founding motivation
(run ``python analysis/regression_sentinel.py results/ledger.jsonl`` and
watch it flag exactly those).

Normalisation: r01–r03 predate the steady-state schema rename
(``life_cups_p46gun_big`` with ``steady_state_cups``); they are mapped
onto the current field names and stamped ``backfill_normalized`` so
nobody mistakes the mapping for an original record. All committed lines
are the flagship workload (500² board, 10 000 steps, uint8, single
chip/host — see results/README.md), so those key fields are filled in
where the old lines omitted them. Timestamps come from the jax warning
lines in each wrapper's ``tail``; the bench_tpu_r05 lines use the
documented 2026-07-31 morning chip window. ``git_sha`` is stamped
``pre-ledger`` — the true SHAs predate this machinery.

Idempotent: entries whose ``source`` is already in the ledger are
skipped, so re-running after a partial append is safe.
"""

from __future__ import annotations

import argparse
import calendar
import glob
import json
import os
import re
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpi_and_open_mp_tpu.obs import ledger  # noqa: E402

# All committed bench lines are the flagship p46gun_big workload.
_FLAGSHIP = {"board": [500, 500], "steps": 10_000, "dtype": "uint8"}

# The r05 chip lines' documented recording window (results/README.md).
_R05_WINDOW_TS = calendar.timegm(time.strptime(
    "2026-07-31 09:00:00", "%Y-%m-%d %H:%M:%S"))

_TS_RE = re.compile(r"(\d{4}-\d{2}-\d{2} \d{2}:\d{2}:\d{2})")


def _ts_from_tail(tail: str, fallback: float) -> float:
    m = _TS_RE.search(tail or "")
    if not m:
        return fallback
    return float(calendar.timegm(
        time.strptime(m.group(1), "%Y-%m-%d %H:%M:%S")))


def _normalize(rec: dict) -> dict:
    """Map a committed bench line onto the current schema + key fields."""
    if rec.get("metric") == "life_cups_p46gun_big":  # r01-r03 old schema
        rec = {
            "metric": "life_steady_cups_p46gun_big",
            "value": rec["steady_state_cups"],
            "unit": rec["unit"],
            "vs_baseline": rec["steady_state_vs_baseline"],
            "end_to_end_sec": rec["elapsed_sec"],
            "end_to_end_cups": rec["value"],
            "end_to_end_vs_baseline": rec["vs_baseline"],
            "steady_is_differenced": True,
            "backend": rec["backend"],
            "impl": rec["impl"],
            "backfill_normalized": True,
        }
    else:
        rec = dict(rec)
    for field, default in _FLAGSHIP.items():
        rec.setdefault(field, default)
    return rec


def _entries_from(root: str) -> list[dict]:
    out = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r0*.json"))):
        name = os.path.basename(path)
        with open(path) as fd:
            wrapper = json.load(fd)
        rec = _normalize(wrapper["parsed"])
        platform = rec.get("backend", "?")
        out.append(ledger.stamp(
            rec, source=f"backfill:{name}",
            platform=platform, device_kind="unrecorded", device_count=1,
            ts=_ts_from_tail(wrapper.get("tail", ""),
                             fallback=float(wrapper.get("n", 0))),
            sha="pre-ledger"))
    chip = os.path.join(root, "results", "bench_tpu_r05.jsonl")
    if os.path.exists(chip):
        with open(chip) as fd:
            for i, line in enumerate(fd, 1):
                line = line.strip()
                if not line:
                    continue
                rec = _normalize(json.loads(line))
                out.append(ledger.stamp(
                    rec, source=f"backfill:results/bench_tpu_r05.jsonl#L{i}",
                    platform=rec.get("backend", "tpu"),
                    device_kind="unrecorded", device_count=1,
                    ts=_R05_WINDOW_TS + 600.0 * (i - 1),
                    sha="pre-ledger"))
    out.sort(key=lambda e: e["ts"])
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="analysis/ledger_backfill.py")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    p.add_argument("--root", default=repo,
                   help="repo root holding BENCH_r0*.json + results/ "
                   "(default: this repo)")
    p.add_argument("--ledger", default=None,
                   help="ledger to append to "
                   "(default: ROOT/results/ledger.jsonl)")
    args = p.parse_args(argv)
    path = args.ledger or os.path.join(args.root, "results", "ledger.jsonl")

    have = set()
    if os.path.exists(path):
        have = {e.get("source") for e in ledger.load(path)}
    entries = _entries_from(args.root)
    added = 0
    for entry in entries:
        if entry["source"] in have:
            continue
        ledger.append(entry, path)
        added += 1
    print(json.dumps({"ledger": path, "backfilled": added,
                      "skipped": len(entries) - added}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
