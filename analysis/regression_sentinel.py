"""Regression sentinel: gate the newest ledger entry against its history.

Usage::

    python analysis/regression_sentinel.py results/ledger.jsonl
    python analysis/regression_sentinel.py LEDGER --n 5 --noise 0.1 \
        --match metric,shape,dtype,steps,batch

Compares the NEWEST non-error ledger entry (``obs.ledger`` schema)
against a rolling median-of-N baseline over the previous entries with the
same workload key, and prints ONE JSON verdict line —
``tpu_queue_loop.sh`` and the CI sentinel job gate on the exit code:

* 0 — ``"pass"`` (every watched rate within the noise floor, no engine
  downgrade) or ``"no-baseline"`` (first run of a configuration).
* 1 — ``"fail"``: a watched rate regressed past the noise floor, or the
  engine/backend provenance downgraded (pallas→jnp, TPU→CPU fallback —
  the exact failure BENCH_r04/r05 recorded silently).
* 2 — unreadable/malformed ledger.

The match key deliberately EXCLUDES topology and engine by default: a run
that fell back to CPU must land in the same comparison group as its
real-chip history (that is the regression), not escape into a fresh key.
Add fields via ``--match`` for per-topology trending instead.

Rates are judged against the MEDIAN of the baseline window (robust to a
single outlier run); provenance against the BEST rank the window reached
(one good run proves the configuration can run that engine, so anything
lower is a downgrade until it ages out of the window). End-to-end wall
seconds are deliberately not watched — they carry the ~70 ms tunnel RTT
(±16 % across identical code, see bench.py), which is noise here; the
steady-state/differenced rates are the signal.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys

# Verdicts are host-side work over a JSONL file; never touch the chip.
os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpi_and_open_mp_tpu.obs import ledger  # noqa: E402

#: Record fields to judge — checked whenever the field is present on
#: the candidate AND at least one baseline record. Throughput numbers
#: are steady-state / differenced (RTT-cancelled); the serve latency
#: percentiles are wall-clock but CPU-mesh-stable (the daemon phase has
#: no device RTT in its latency path on the CI runner). Directions come
#: from :func:`direction_for` — keyed off the metric NAME, so a new
#: bench field gets the right polarity by naming convention instead of
#: silently defaulting to higher-is-better.
WATCH_FIELDS = (
    "value",
    "sharded_steady_cups",
    "batched_cups",
    "batched_steady_cups",
    "batched_requests_per_sec",
    # Board-sliced batched engine (PR 10): the raw rate plus its ratio
    # over the vmapped cell-packed baseline measured in the same process
    # (the ratio is RTT- and machine-noise-cancelled, so a quiet erosion
    # of the layout's advantage trips the sentinel even when absolute
    # rates drift together).
    "bitsliced_cups",
    "vs_cellpacked",
    "attention_32k_causal_tflops",
    "attention_32k_grad_tflops",
    "attention_32k_causal_sec",
    "attention_32k_grad_sec",
    "serve_requests_per_sec",
    "serve_p50_latency_s",
    "serve_p99_latency_s",
    "serve_wal_bytes",
    "serve_wal_fsync_s",
    # AOT warm-start gates (all lower-is-better by the _s suffix rule):
    # cold = trace+compile in the first ticket's path, warm = pure
    # deserialization — a warm first-result that regresses toward cold
    # means the executable cache stopped working.
    "serve_cold_first_result_s",
    "serve_aot_first_result_s",
    "serve_aot_deserialize_s",
    # Sharded fleet (PR 11): aggregate throughput/latency across the
    # 3-worker router, plus the wedge-to-last-rehomed-resolution time
    # from the kill drill — recovery regressing means the heartbeat →
    # WAL replay → re-home ladder got slower (all polarities by name:
    # per_sec higher, _s lower).
    "fleet_requests_per_sec",
    "fleet_p99_latency_s",
    "fleet_kill_recovery_s",
    # Device-resident session pool (PR 12): the resident step rate, its
    # ratio over the ship-boards-every-call baseline measured in the
    # same process (RTT- and noise-cancelled, like vs_cellpacked), the
    # resident-path latency tail, and the pool's eviction count for the
    # phase — evictions climbing at fixed session count means the
    # residency budget or the compactor regressed (``evict`` is in the
    # lower-is-better vocabulary).
    "session_requests_per_sec",
    "session_vs_ship",
    "session_p99_latency_s",
    "pool_evictions",
    # Sparse active-tile engine (PR 13): the sparse rate and its ratio
    # over the dense roll engine measured in the same process (RTT- and
    # noise-cancelled, like vs_cellpacked) — both higher-is-better by
    # the cups/vs naming rules. ``active_frac`` is deliberately NOT
    # watched: it describes the workload's liveness, not the engine's
    # quality (a busier seed board is not a regression); it rides the
    # line as context for the two rates that ARE watched.
    "sparse_cups",
    "sparse_vs_dense",
    # Autotuner (PR 14): the tuned engine's rate and its ratio over the
    # heuristic choice measured in the same process (RTT- and
    # noise-cancelled, like vs_cellpacked; >= 1.0 by construction since
    # the heuristic is in the race) — both higher-is-better by the
    # cups/vs naming rules. A vs_heuristic sliding toward 1.0 means the
    # tuner stopped finding wins; tuned_cups falling means the plan it
    # persists got slower.
    "tuned_cups",
    "vs_heuristic",
    # Persistent halo plans (PR 15): the overlapped sharded rate and its
    # ratio over the sequential schedule measured in the same process
    # (RTT- and noise-cancelled, like vs_heuristic) — both
    # higher-is-better by the cups/vs naming rules. A vs_sequential
    # sliding toward 1.0 means the ghost exchange stopped hiding behind
    # the interior stencil.
    "sharded_overlap_cups",
    "vs_sequential",
    # Sparse x sharded (PR 16): the composed engine's rate and its
    # ratios over the dense sharded schedule and the single-device
    # sparse engine, measured in the same process (RTT- and noise-
    # cancelled, like vs_sequential) — all higher-is-better by the
    # cups/vs naming rules. vs_dense sliding toward 1.0 means per-round
    # cost stopped tracking the live area; vs_single sliding down means
    # the mesh stopped paying for itself. ``active_frac`` stays
    # unwatched here for the same reason as PR 13's.
    "sparse_sharded_cups",
    "sparse_sharded_vs_dense",
    "sparse_sharded_vs_single",
    # Elastic fleet under open-loop load (PR 17): steady-state goodput
    # at the saturation sweep's knee rung (higher by default — "rps"
    # deliberately avoids the _s suffix), the extreme-tail latency at
    # that rung (lower by the latency rule), and the wedge→REJOIN→
    # recovered time from the membership drill (lower by the _s rule) —
    # recovery regressing means the resume-from-WAL + ring re-entry +
    # claim ladder got slower. The per-rung curve rides the JSON line
    # as context; the knee scalars are what the sentinel judges.
    "loadgen_goodput_rps",
    "loadgen_p999_latency_s",
    "rejoin_recovery_s",
    # Ring-attention hop prefetch (PR 18): the prefetched ring's
    # arithmetic rate (higher by the tflops rule) and the per-step K/V
    # transfer time left EXPOSED after the double-slot schedule hides
    # what it can (lower by the _s rule — this is the quantity the
    # prefetch exists to shrink, the attention twin of
    # sharded_exposed_s). ring_exposed_s growing back toward the
    # rotation-priced transfer time means the issue-first schedule
    # stopped hiding the wire; ring_prefetch_tflops falling means the
    # deeper pipeline itself got slower. The engine-provenance side is
    # covered separately: losing the ``:pf`` stamp suffix (the
    # MOMP_RING_PREFETCH kill switch left on) is a downgrade within the
    # pallas tier — see ``_prefetch_rank``.
    "ring_prefetch_tflops",
    "ring_exposed_s",
    # Fleet telemetry plane (PR 19): snapshot loss is the fraction of
    # the per-worker time series the rollup never received (seq gaps +
    # truncated sidecar frames) — growing loss means the shipping path
    # is dropping intervals (lower by the ``loss`` rule). The burn-rate
    # peak at the saturation knee is the long-window error-budget
    # consumption while the SLO is still MET — recorded headroom; a
    # rising peak means the fleet runs ever closer to its budget at the
    # same capacity number (lower by the ``burn`` rule).
    "telemetry_snapshot_loss_frac",
    "loadgen_burn_rate_peak",
    # Wide-radius engine families (PR 20): per-family steady rates from
    # the bench --radius-ab crossover sweep, recorded at the widest
    # parity-clean radius measured (higher by the cups rule), plus the
    # best family-vs-offset ratio over the radius >= 8 cells (higher by
    # default — the ratio is same-process, RTT- and noise-cancelled
    # like vs_heuristic). vs_offset_best sliding toward 1.0 means the
    # restructured aggregation stopped beating the offset walk on the
    # workload it exists for; the kill-switch flip (MOMP_ENGINE_FAMILY=
    # offset left pinned) is caught by the ``engine_family`` provenance
    # field, not a rate.
    "radius_ab_offset_cups",
    "radius_ab_sep_cups",
    "radius_ab_fft_cups",
    "radius_ab_vs_offset_best",
)


def direction_for(field: str) -> str:
    """Judging polarity for a watched metric name.

    Rates (``*per_sec*``, ``*cups*``, ``*tflops*``) are higher-is-better
    and take precedence — ``batched_requests_per_sec`` must NOT fall
    through to the ``_sec`` latency rule. Durations, badness counts and
    overhead volumes (``*latency*``, ``*_sec``/``*_seconds``/``*_s``/
    ``*_bytes`` suffixes, ``shed``/``degrad`` counters) are
    lower-is-better: a p99 that GROWS is the regression, and so is a
    write-ahead-journal durability tax that swells (``serve_wal_bytes``
    volume, ``serve_wal_fsync_s`` sync stall). Telemetry badness is
    lower-is-better too: ``loss`` (snapshot series the rollup never
    saw) and ``burn`` (SLO error-budget consumption rate). Anything
    unrecognised defaults to higher-is-better (the historical
    behaviour for throughput fields).
    """
    if "per_sec" in field or "cups" in field or "tflops" in field:
        return "higher"
    if ("latency" in field or "shed" in field or "degrad" in field
            or "evict" in field or "loss" in field or "burn" in field
            or field.endswith(("_sec", "_seconds", "_s", "_bytes"))):
        return "lower"
    return "higher"

#: Record fields carrying engine provenance, rank-compared for downgrades.
PROVENANCE_FIELDS = ("impl", "batch_engine", "batch_pack_layout",
                     "attention_engine", "attention_hop_engine",
                     "attention_hop_engine_bwd", "sparse_engine",
                     "sharded_halo", "sparse_sharded_engine",
                     "ring_hop_engine", "ring_hop_engine_bwd",
                     "engine_family")

#: ``workload`` joined in PR 13: a heat line and a life line of the same
#: shape are different rules — they must never share a baseline group
#: (pre-stencil entries default to "life" via the ledger key defaults).
DEFAULT_MATCH = ("metric", "shape", "dtype", "steps", "batch", "resident",
                 "workload")

_BACKEND_RANK = {"cpu": 0, "gpu": 1, "tpu": 2}

#: ``plan_source`` vocabulary, rank-compared like backends: a line that
#: ran under a tuned plan (freshly measured or loaded from the store —
#: equally good, both are the tuner's measured choice) regressing to
#: heuristic routing means the plan store silently stopped applying
#: (quarantined plans, a bad MOMP_TUNE_PLANS path, MOMP_TUNE=0 leaking
#: into CI) — exactly the downgrade shape BENCH_r04 hid for backends.
_PLAN_RANK = {"store": 2, "fresh": 2, "heuristic": 1}


def engine_rank(stamp) -> int:
    """Coarse engine tiers: the board-sliced batched layout > repo
    Pallas kernels > packed/fused native paths > jnp/XLA folds (the
    cell-packed ``batch_pack_layout`` vocabulary lands in the bottom
    tier, so ``bitsliced -> cell-packed`` is a downgrade exactly like
    ``pallas -> jnp``). Suffixes (``:b1024``, ``:zz``, ``:bB``) and the
    ``batch:``/``local:`` prefixes don't change the tier. The sparse
    active-tile stamp (``sparse:t<tile>``) sits above everything dense:
    on the mostly-dead workload it serves, a silent flip to
    ``dense:crossover`` is THE downgrade this field exists to catch.
    The halo schedule stamp (``overlap:*`` vs ``seq:*``) ranks overlap
    above every sequential tier: a ``sharded_halo`` flipping from
    ``overlap:deferred`` to ``seq:halo`` (the MOMP_HALO_OVERLAP=0 kill
    switch left on, or a geometry gate silently engaging) is a
    provenance downgrade even when the rates are within noise. The
    engine-family stamps (PR 20) rank ``fft`` above ``sep`` above the
    offset table: on the wide-radius workloads those families exist
    for, an ``fft -> offset`` flip on the same configuration (the
    MOMP_ENGINE_FAMILY=offset kill switch left pinned) is exactly the
    silent O(r^2·n) regression this field exists to catch — ``offset``
    itself falls through to the bottom tier. Matching is exact or
    affixed (``fft``/``fft:*``/``*:fft``) so ``seq:halo`` never reads
    as a ``sep`` stamp."""
    s = str(stamp or "")
    for prefix in ("batch:", "local:"):
        if s.startswith(prefix):
            s = s[len(prefix):]
    if s == "fft" or s.startswith("fft:") or s.endswith(":fft"):
        return 5
    if s == "sep" or s.startswith("sep:") or s.endswith(":sep"):
        return 4
    if s.startswith("sparse"):
        return 5
    if s.startswith("overlap:"):
        return 4
    if s.startswith("bitsliced"):
        return 4
    if "pallas" in s:
        return 3
    if s.startswith(("bitfused", "vmem", "grid", "fused", "frame")):
        return 2
    return 1 if s else 0


def _prefetch_rank(stamp) -> int:
    """Within-tier schedule sub-rank: the ring hop stamps carry a
    trailing ``:pf`` when the double-slot K/V prefetch is engaged
    (``context._ring_prefetch_on``). Losing it at the same engine tier
    — the MOMP_RING_PREFETCH kill switch left on after a chaos drill,
    exactly like MOMP_HALO_OVERLAP's failure shape — is a provenance
    downgrade even when the rates sit inside the noise floor."""
    return 1 if ":pf" in str(stamp or "") else 0


def _provenance_key(stamp):
    """Sort/compare key for provenance stamps: engine tier first, the
    schedule sub-rank as tiebreak (a tier upgrade always wins; a same-
    tier prefetch loss still counts as a downgrade)."""
    return (engine_rank(stamp), _prefetch_rank(stamp))


def _usable(entry: dict) -> bool:
    rec = entry.get("record") or {}
    return "error" not in rec


def _match_key(entry: dict, fields: tuple[str, ...]) -> str:
    return ledger.config_key(entry, fields)


def evaluate(entries: list[dict], *, n: int = 5, noise: float = 0.1,
             match: tuple[str, ...] = DEFAULT_MATCH) -> dict:
    """The verdict dict for the newest usable entry of ``entries``."""
    usable = sorted((e for e in entries if _usable(e)),
                    key=lambda e: e.get("ts", 0.0))
    if not usable:
        return {"sentinel": "momp-regression-sentinel/1",
                "verdict": "no-baseline",
                "reason": "no non-error entries in the ledger"}
    candidate = usable[-1]
    key = _match_key(candidate, match)
    pool = [e for e in usable[:-1] if _match_key(e, match) == key][-n:]
    verdict = {
        "sentinel": "momp-regression-sentinel/1",
        "key": key,
        "candidate_source": candidate.get("source", "?"),
        "candidate_ts": candidate.get("ts"),
        "candidate_git_sha": candidate.get("git_sha", "?"),
        "baseline_n": len(pool),
        "noise_floor": noise,
    }
    if not pool:
        verdict["verdict"] = "no-baseline"
        return verdict

    cand_rec = candidate.get("record") or {}
    regressions, downgrades, checked = [], [], []

    for field in WATCH_FIELDS:
        direction = direction_for(field)
        new = cand_rec.get(field)
        base_vals = [e["record"][field] for e in pool
                     if isinstance((e.get("record") or {}).get(field),
                                   (int, float))]
        if not isinstance(new, (int, float)) or not base_vals:
            continue
        baseline = statistics.median(base_vals)
        if baseline == 0:
            continue
        checked.append(field)
        drop = ((baseline - new) / baseline if direction == "higher"
                else (new - baseline) / abs(baseline))
        if drop > noise:
            regressions.append({
                "field": field, "direction": direction,
                "new": new, "baseline_median": baseline,
                "drop": round(drop, 4),
            })

    # Backend/platform downgrade: the TPU→CPU fallback BENCH_r04 hid.
    new_backend = candidate.get("platform") or cand_rec.get("backend")
    base_backends = [e.get("platform") or (e.get("record") or {}).get(
        "backend") for e in pool]
    base_backends = [b for b in base_backends if b]
    if new_backend and base_backends:
        checked.append("platform")
        best = max(base_backends, key=lambda b: _BACKEND_RANK.get(b, 0))
        if (_BACKEND_RANK.get(new_backend, 0)
                < _BACKEND_RANK.get(best, 0)):
            item = {"field": "platform", "new": new_backend,
                    "baseline_best": best}
            if cand_rec.get("fallback_reason"):
                item["fallback_reason"] = cand_rec["fallback_reason"]
            downgrades.append(item)

    # Plan-provenance downgrade: tuned (store/fresh) -> heuristic means
    # the autotuner's measured decision silently stopped being applied.
    new_plan = cand_rec.get("plan_source")
    base_plans = [(e.get("record") or {}).get("plan_source") for e in pool]
    base_plans = [p for p in base_plans if p in _PLAN_RANK]
    if new_plan in _PLAN_RANK and base_plans:
        checked.append("plan_source")
        best = max(base_plans, key=lambda p: _PLAN_RANK[p])
        if _PLAN_RANK[new_plan] < _PLAN_RANK[best]:
            item = {"field": "plan_source", "new": new_plan,
                    "baseline_best": best}
            if cand_rec.get("fallback_reason"):
                item["fallback_reason"] = cand_rec["fallback_reason"]
            downgrades.append(item)

    for field in PROVENANCE_FIELDS:
        new = cand_rec.get(field)
        base = [(e.get("record") or {}).get(field) for e in pool]
        base = [b for b in base if b is not None]
        if new is None or not base:
            continue
        checked.append(field)
        best = max(base, key=_provenance_key)
        if _provenance_key(new) < _provenance_key(best):
            downgrades.append({"field": field, "new": new,
                               "baseline_best": best})

    verdict.update({
        "checked": checked,
        "regressions": regressions,
        "downgrades": downgrades,
        "verdict": "fail" if (regressions or downgrades) else "pass",
    })
    return verdict


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="analysis/regression_sentinel.py")
    p.add_argument("ledger", help="obs.ledger JSONL file to judge")
    p.add_argument("--n", type=int, default=5, metavar="N",
                   help="rolling baseline window per configuration key "
                   "(median of the last N matching runs; default 5)")
    p.add_argument("--noise", type=float, default=0.1, metavar="FRAC",
                   help="noise floor: drops up to this fraction of the "
                   "baseline median pass (default 0.1)")
    p.add_argument("--match", default=",".join(DEFAULT_MATCH),
                   metavar="FIELDS",
                   help="comma-separated key fields runs must share to be "
                   "comparable (default %(default)s; add 'topology' or "
                   "'engine' for per-topology trending)")
    args = p.parse_args(argv)

    try:
        entries = ledger.load(args.ledger)
    except (OSError, ValueError) as e:
        print(f"regression_sentinel: {e}", file=sys.stderr)
        return 2
    match = tuple(f.strip() for f in args.match.split(",") if f.strip())
    verdict = evaluate(entries, n=args.n, noise=args.noise, match=match)
    print(json.dumps(verdict))
    return 1 if verdict["verdict"] == "fail" else 0


if __name__ == "__main__":
    sys.exit(main())
