"""Fabric probe analysis: log-log latency/bandwidth plots + α+βn fit.

Script form of the reference's ``2-network-params/plot.ipynb`` (cells 1-6):
reads one or more ``size,time`` CSVs (µs per hop), renders time and
bandwidth vs message size on log-log axes, and prints the linear-model fit
α (latency intercept, µs) and 1/β (asymptotic bandwidth, MB/s) per file.

Usage: ``python analysis/plot_network.py out_single.csv [out_mult.csv ...]``
"""

from __future__ import annotations

import os
import sys

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402
import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpi_and_open_mp_tpu.parallel.fabric import fit_alpha_beta  # noqa: E402


def load_csv(path: str) -> list[tuple[int, float]]:
    rows = []
    with open(path) as fd:
        for line in fd:
            line = line.strip()
            if not line or line.startswith("size"):
                continue
            s, t = line.split(",")
            rows.append((int(s), float(t)))
    return rows


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if not argv:
        print("usage: plot_network.py probe.csv [...]", file=sys.stderr)
        return 1
    fig, (ax_t, ax_bw) = plt.subplots(1, 2, figsize=(11, 4.5))
    for path in argv:
        rows = load_csv(path)
        sizes = np.array([r[0] for r in rows], dtype=float)
        times = np.array([r[1] for r in rows], dtype=float)
        label = os.path.basename(path)
        ax_t.loglog(sizes, times, marker="o", label=label)
        ax_bw.loglog(sizes, sizes / times, marker="o", label=label)
        print(f"{label}: {fit_alpha_beta(rows).render()}")
    ax_t.set_xlabel("message size [B]")
    ax_t.set_ylabel("time per hop [µs]")
    ax_bw.set_xlabel("message size [B]")
    ax_bw.set_ylabel("bandwidth [MB/s]")
    for ax in (ax_t, ax_bw):
        ax.grid(True, which="both", alpha=0.3)
        ax.legend()
    fig.tight_layout()
    fig.savefig("network_params.png", dpi=120)
    print("network_params.png")
    return 0


if __name__ == "__main__":
    sys.exit(main())
