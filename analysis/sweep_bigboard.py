"""Big-board Life scaling sweep on the real chip (SURVEY §7 step 8).

The reference's scaling study stops at its 500x500 flagship
(`3-life/p46gun_big.cfg`) swept over MPI ranks; the TPU build's scale-up
axis is BOARD size on one chip — each size exercises whichever native
path the serial dispatcher (`ops.pallas_life.life_run_vmem`) picks:
VMEM-resident packed loop, multi-step-fused tiled kernel, padded-torus
frame (unaligned), or the compiled-XLA packed loop.

Per size: steady-state cell-updates/sec by the same RTT-cancelling
differencing discipline as `bench.py` (time S and 3S steps through the
SAME compiled executable — the step count is a runtime scalar — and
difference), best-of-3 each. Emits a CSV:

    n,steps,path,steady_us_per_step,steady_gcups,differenced

Usage:  python analysis/sweep_bigboard.py [--out results/life/bigboard_tpu.csv]

``--update`` MERGES into an existing CSV instead of overwriting it —
rows key on (n, path), so an incremental chip window (say the 20000/
32768 board-curve extension queued for r05) adds its rows next to the
committed ones instead of clobbering the curve. ``--ab N`` records a
frame-vs-XLA A/B at one size: the natural dispatcher row plus an
``xla-forced`` row driving ``bitlife.life_run_bits_xla`` directly on
the same board, settling how much the padded-frame path actually buys
at unaligned sizes.

``--batch-ab N [N ...]`` (queued for r06) is the batched-layout twin:
per board size and per ``--batches`` B it records three rows on the
SAME seeded stack — the board-sliced engine (DESIGN.md §12), the
cell-packed native dispatch with ``MOMP_BITSLICE`` pinned off, and the
vmapped cell-packed XLA baseline. Rows key on (n, ``<layout>:b<B>``)
so every (size, batch) cell of the A/B grid merges independently, and
each (n, B) pair also lands one ledger entry (``MOMP_LEDGER`` /
``--update`` CSV both) carrying ``bitsliced_cups``/``vs_cellpacked``
so the regression sentinel trends the layout's advantage across chip
windows. All three engines are cross-checked bit-exact on the stack
before any of them is timed.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def measure(n: int, steps: int, runner=None) -> tuple[float, bool]:
    """Steady seconds/step for an n x n board, and whether differenced.

    ``runner`` defaults to the native dispatcher ``life_run_vmem``; the
    A/B mode passes a forced engine (same differencing discipline either
    way — every runner here takes steps as a runtime scalar)."""
    import jax

    from mpi_and_open_mp_tpu.ops.pallas_life import life_run_vmem
    from mpi_and_open_mp_tpu.utils.timing import anchor_sync

    if runner is None:
        runner = life_run_vmem
    rng = np.random.default_rng(46)
    board = jax.device_put(
        (rng.random((n, n)) < 0.3).astype(np.uint8)
    )
    anchor_sync(runner(board, steps), fetch_all=True)  # compile
    anchor_sync(runner(board, 3 * steps), fetch_all=True)

    def timed(s: int) -> float:
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            anchor_sync(runner(board, s), fetch_all=True)
            best = min(best, time.perf_counter() - t0)
        return best

    t1, t3 = timed(steps), timed(3 * steps)
    if t3 > t1:
        return (t3 - t1) / (2 * steps), True
    return t1 / steps, False


def measure_stack(run, steps: int) -> tuple[float, bool]:
    """Steady seconds/step for a prepared batched runner ``run(steps)``.

    Same best-of-3 chained-differencing discipline as :func:`measure`;
    the caller owns the stack and the engine so the three A/B rows of
    one (n, B) cell time the identical boards."""
    from mpi_and_open_mp_tpu.utils.timing import anchor_sync

    anchor_sync(run(steps), fetch_all=True)  # compile
    anchor_sync(run(3 * steps), fetch_all=True)

    def timed(s: int) -> float:
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            anchor_sync(run(s), fetch_all=True)
            best = min(best, time.perf_counter() - t0)
        return best

    t1, t3 = timed(steps), timed(3 * steps)
    if t3 > t1:
        return (t3 - t1) / (2 * steps), True
    return t1 / steps, False


def _cellpacked(pallas_life, stack, steps: int):
    """The cell-packed native dispatch on a stack, with the board-sliced
    layout pinned off for the duration so the ladder can't pick it back
    up — the A/B's control arm."""
    with pallas_life._bitslice_pinned(False):
        return pallas_life.life_run_vmem_batch(stack, steps)


def merge_rows(out_path: str, header: str, new_rows: list[str]) -> list[str]:
    """Header + data rows with ``new_rows`` merged over whatever
    ``out_path`` already holds, keyed on (first column, path column) and
    sorted numerically — the ``--update`` write set."""
    merged: dict[tuple[int, str], str] = {}
    if os.path.exists(out_path):
        with open(out_path) as f:
            lines = [ln.strip() for ln in f if ln.strip()]
        for ln in lines[1:]:
            parts = ln.split(",")
            merged[(int(parts[0]), parts[2])] = ln
    for ln in new_rows:
        parts = ln.split(",")
        merged[(int(parts[0]), parts[2])] = ln
    return [header] + [merged[k] for k in sorted(merged)]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="results/life/bigboard_tpu.csv")
    ap.add_argument(
        "--sizes", type=int, nargs="+",
        # 500 = flagship; 3072 = last VMEM-resident size; 10000 = unaligned
        # (ny % 32 != 0) so it takes the padded-frame path; the rest fused.
        default=[500, 1024, 2048, 3072, 4096, 8192, 10000, 16384],
    )
    ap.add_argument("--ab", type=int, default=None, metavar="N",
                    help="A/B one size instead of the curve: the natural "
                    "dispatcher row plus an xla-forced row on the same "
                    "board (pair with --update to land both next to the "
                    "committed curve)")
    ap.add_argument("--batch-ab", type=int, nargs="+", default=None,
                    metavar="N",
                    help="batched-layout A/B instead of the curve: per "
                    "size and per --batches B, a board-sliced row, a "
                    "cell-packed native row (MOMP_BITSLICE pinned off) "
                    "and a vmapped-XLA row on the same stack; rows key "
                    "(n, <layout>:b<B>) and each (n, B) pair lands one "
                    "ledger entry when MOMP_LEDGER is set")
    ap.add_argument("--batches", type=int, nargs="+", default=[8, 32, 64],
                    metavar="B",
                    help="batch sizes for --batch-ab (default 8 32 64)")
    ap.add_argument("--update", action="store_true",
                    help="merge rows into --out keyed on (n, path) instead "
                    "of overwriting — incremental chip windows")
    args = ap.parse_args(argv)

    import jax

    if jax.default_backend() != "tpu":
        print("refusing to record: backend is not TPU", file=sys.stderr)
        return 1

    from mpi_and_open_mp_tpu.ops.life_ops import life_step_numpy
    from mpi_and_open_mp_tpu.ops.pallas_life import life_run_vmem

    # Honesty gate (same as bench.py): the dispatcher must be bit-exact
    # vs the host oracle before any of its timings are recorded.
    rng = np.random.default_rng(46)
    small = (rng.random((500, 500)) < 0.3).astype(np.uint8)
    got = np.asarray(jax.device_get(life_run_vmem(jax.device_put(small), 8)))
    ref = small.copy()
    for _ in range(8):
        ref = life_step_numpy(ref)
    if not np.array_equal(got, ref):
        print("parity check failed; not recording", file=sys.stderr)
        return 1

    from mpi_and_open_mp_tpu.ops.pallas_life import native_path

    from mpi_and_open_mp_tpu.utils.timing import write_csv_rows

    header = "n,steps,path,steady_us_per_step,steady_gcups,differenced"
    new_rows: list[str] = []

    def flush() -> None:
        # After every point (crash-proof); --update folds the fresh rows
        # over the committed CSV, plain mode rewrites it from scratch.
        if args.update:
            write_csv_rows(args.out, merge_rows(args.out, header, new_rows))
        else:
            write_csv_rows(args.out, [header] + new_rows)
        print(new_rows[-1], flush=True)

    def record(n: int, path_label: str, runner=None) -> None:
        # Aim ~0.5 s of steady compute per base run (floor 100 steps so
        # the fused paths cross several 128-step rounds).
        steps = max(100, min(2_000_000, int(7e11 / (n * n))))
        sec, diff = measure(n, steps, runner)
        gcups = n * n / sec / 1e9
        new_rows.append(
            f"{n},{steps},{path_label},{sec * 1e6:.3f},{gcups:.1f},{int(diff)}"
        )
        flush()

    if args.batch_ab is not None:
        import jax.numpy as jnp

        from mpi_and_open_mp_tpu.ops import bitlife, pallas_life

        if args.out == ap.get_default("out"):
            args.out = "results/life/batched_ab_tpu.csv"
        ledger_out = None
        try:
            from mpi_and_open_mp_tpu.obs import ledger as obs_ledger
            ledger_out = obs_ledger.ledger_path()
        except Exception:
            pass

        for n in args.batch_ab:
            for b in args.batches:
                stack_np = (np.random.default_rng(46 + b).random(
                    (b, n, n)) < 0.3).astype(np.uint8)
                stack = jax.device_put(jnp.asarray(stack_np))
                cp_path = pallas_life.native_path_batch(
                    stack_np.shape, allow_bitsliced=False)
                # Forced board-sliced arm: the Pallas VMEM kernel inside
                # the gate, the halo-fused XLA twin beyond it (still the
                # board-sliced layout — the A/B is layout vs layout,
                # never gated away like the natural dispatcher).
                kern = bitlife.fits_vmem_bitsliced(stack_np.shape)
                engines = [
                    (f"bitsliced:b{b}", lambda s: bitlife
                     .life_run_bitsliced_batch(stack, s, use_kernel=kern)),
                    (f"cellpacked-{cp_path}:b{b}",
                     lambda s: _cellpacked(pallas_life, stack, s)),
                    (f"xla-vmapped:b{b}", lambda s: bitlife
                     .life_run_bits_xla_batch(stack, s)),
                ]
                # Honesty gate per cell: all three engines bit-identical
                # on the stack before any of them is timed (the natural
                # dispatcher is already oracle-gated above, and the
                # bitsliced engine's per-board oracle parity is pinned
                # by tests/test_bitlife.py).
                outs = [np.asarray(jax.device_get(run(8)))
                        for _, run in engines]
                if not (np.array_equal(outs[0], outs[1])
                        and np.array_equal(outs[0], outs[2])):
                    print(f"batch-ab parity failed at n={n} B={b}; "
                          "not recording", file=sys.stderr)
                    return 1
                # ~0.5 s steady compute over the AGGREGATE cell count.
                steps = max(100, min(2_000_000, int(7e11 / (b * n * n))))
                cell_rates = {}
                for label, run in engines:
                    sec, diff = measure_stack(run, steps)
                    gcups = b * n * n / sec / 1e9
                    cell_rates[label.split(":")[0]] = b * n * n / sec
                    new_rows.append(f"{n},{steps},{label},"
                                    f"{sec * 1e6:.3f},{gcups:.1f},{int(diff)}")
                    flush()
                if ledger_out:
                    bs = cell_rates["bitsliced"]
                    cp = cell_rates[f"cellpacked-{cp_path}"]
                    path_nat = pallas_life.native_path_batch(
                        stack_np.shape)
                    rec = {
                        "metric": "life_batched_ab_bigboard",
                        "board": [n, n], "dtype": "uint8",
                        "steps": steps, "batch": b,
                        "batch_engine": "batch:" + path_nat,
                        "batch_pack_layout": pallas_life
                        .batch_pack_layout(stack_np.shape),
                        "impl": "batch:" + path_nat,
                        "bitsliced_cups": round(bs, 1),
                        "cellpacked_native_cups": round(cp, 1),
                        "xla_vmapped_cups": round(
                            cell_rates["xla-vmapped"], 1),
                        "vs_cellpacked": round(bs / cp, 2),
                        "backend": jax.default_backend(),
                        "device_kind": jax.devices()[0].device_kind,
                    }
                    obs_ledger.append(obs_ledger.stamp(
                        rec, source="sweep_bigboard.py",
                        platform=jax.default_backend(),
                        device_count=jax.device_count()), ledger_out)
    elif args.ab is not None:
        from mpi_and_open_mp_tpu.ops import bitlife

        record(args.ab, native_path((args.ab, args.ab)))
        record(args.ab, "xla-forced", runner=bitlife.life_run_bits_xla)
    else:
        for n in args.sizes:
            record(n, native_path((n, n)))

    print(f"wrote {args.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
