"""Fleet trace merge: one Perfetto timeline for the whole fleet.

A cross-process fleet run (``python -m mpi_and_open_mp_tpu.serve.fleet
--dir STATE``) leaves one trace JSONL per worker subprocess
(``worker<i>.trace.jsonl``, plus ``worker<t>.rehome<v>.trace.jsonl`` for
recovery lifetimes), one telemetry sidecar per worker
(``*.telemetry.bin``), and — when the parent ran under ``MOMP_TRACE`` —
the router's own trace with the ``serve.fleet.burn`` /
``serve.fleet.scale`` events. This tool merges them into ONE timeline:

* **Span-id namespacing** — ``obs.trace`` ids are a per-process counter,
  so two workers both emit span id 1; every source file gets its own id
  namespace before the merge (ids and parent links remap together, so
  nesting survives).
* **Per-worker tracks** — each source keeps its own pid, and the merged
  Chrome JSON names each process track after its source
  (``worker0``, ``worker2.rehome1``, ``router``), so the timeline reads
  as one row per worker lifetime.
* **Clock alignment** — telemetry snapshots carry paired (mono, wall)
  stamps sampled together on the heartbeat; the median ``wall - mono``
  per worker is its monotonic→wall offset (``obs.telemetry.
  clock_offset``). Trace ``ts`` values are already wall-clock; the
  offsets map the SIDECAR series onto the same axis, emitted as Perfetto
  counter tracks (queue depth / resolved per worker).

Usage::

    python analysis/fleet_report.py STATE_DIR --chrome merged.json
    python analysis/fleet_report.py STATE_DIR --json
    python analysis/trace_report.py STATE_DIR --fleet   # same thing

The summary JSON answers the drill questions directly: every worker
track present, burn event preceding the scale decision, snapshot loss
per worker bounded to the dead one's last interval.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

# Host-side analysis; never claim the TPU (sitecustomize defaults to it).
os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpi_and_open_mp_tpu.obs import report  # noqa: E402
from mpi_and_open_mp_tpu.obs import telemetry  # noqa: E402

#: Id-namespace stride per source file: far above any real per-process
#: span count, so remapped ids never collide across sources.
_ID_STRIDE = 10_000_000


def discover(state_dir: str, router_trace: str | None = None) -> dict:
    """The fleet run's observability files, by role. Worker stems sort
    so ``worker10`` follows ``worker9`` (and rehome lifetimes follow
    their target's base stem)."""
    traces = sorted(glob.glob(os.path.join(state_dir, "worker*.trace.jsonl")))
    sidecars = sorted(glob.glob(os.path.join(state_dir,
                                             "worker*.telemetry.bin")))
    return {
        "worker_traces": traces,
        "sidecars": sidecars,
        "router_trace": (router_trace if router_trace
                         and os.path.exists(router_trace) else None),
    }


def _label(path: str) -> str:
    """``.../worker2.rehome1.trace.jsonl`` → ``worker2.rehome1``."""
    base = os.path.basename(path)
    for suffix in (".trace.jsonl", ".telemetry.bin"):
        if base.endswith(suffix):
            return base[: -len(suffix)]
    return base


def merge_traces(sources: list[tuple[str, list[dict]]]) -> list[dict]:
    """Merge per-process records under per-source id namespaces. Each
    source's span ids (a per-process counter starting at 1) shift by a
    distinct stride; parent links shift with them, so parentage — and
    therefore Perfetto track assignment — survives the merge intact."""
    merged: list[dict] = []
    for fi, (label, records) in enumerate(sources):
        base = (fi + 1) * _ID_STRIDE
        for r in records:
            r = dict(r)
            if isinstance(r.get("id"), int):
                r["id"] = base + r["id"]
            if isinstance(r.get("parent"), int):
                r["parent"] = base + r["parent"]
            r.setdefault("attrs", {})
            r["attrs"] = dict(r["attrs"] or {}, track=label)
            merged.append(r)
    merged.sort(key=lambda r: r.get("ts", 0.0))
    return merged


def _track_names(sources: list[tuple[str, list[dict]]]) -> dict[int, str]:
    """pid → source label (each subprocess owns its pid; a shared trace
    appended by several runs keeps the label of its first writer)."""
    names: dict[int, str] = {}
    for label, records in sources:
        for r in records:
            pid = r.get("pid")
            if isinstance(pid, int) and pid not in names:
                names[pid] = label
    return names


def to_chrome(sources: list[tuple[str, list[dict]]],
              rollup_series: dict | None = None) -> dict:
    """One Chrome trace-event JSON for the whole fleet: merged spans on
    per-worker (per-pid) tracks, process tracks named after their source
    file, and — when sidecar series are supplied — per-worker Perfetto
    counter tracks (queue depth, resolved) placed on the wall axis via
    the worker's clock offset."""
    merged = merge_traces(sources)
    chrome = report.to_chrome(merged)
    names = _track_names(sources)
    for ev in chrome["traceEvents"]:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            pid = ev.get("pid")
            if pid in names:
                ev["args"]["name"] = f"{names[pid]} (pid {pid})"
    label_pid = {label: pid for pid, label in names.items()}
    for label, series in (rollup_series or {}).items():
        snaps = series.get("snapshots") or []
        offset = telemetry.clock_offset(snaps)
        if offset is None:
            continue
        pid = label_pid.get(label, 0)
        for s in snaps:
            counters = s.get("counters") or {}
            wall_us = (s["mono"] + offset) * 1e6
            for cname in ("depth", "resolved"):
                if cname in counters:
                    chrome["traceEvents"].append({
                        "ph": "C", "name": f"{label}.{cname}",
                        "ts": wall_us, "pid": pid, "tid": 0,
                        "args": {cname: counters[cname]},
                    })
    return chrome


def fleet_report(state_dir: str, router_trace: str | None = None,
                 chrome_out: str | None = None) -> dict:
    """Merge a fleet state dir's traces + sidecars; returns the summary
    dict (and writes the merged Chrome JSON when ``chrome_out``)."""
    from mpi_and_open_mp_tpu.serve.router import FleetRollup

    found = discover(state_dir, router_trace)
    sources: list[tuple[str, list[dict]]] = []
    load_errors: list[str] = []
    for path in found["worker_traces"]:
        try:
            sources.append((_label(path), report.load(path)))
        except (OSError, ValueError) as e:
            # A killed worker's trace may end mid-line; its intact
            # prefix still merges. Fall back to a line-tolerant parse.
            load_errors.append(str(e))
            sources.append((_label(path), _lenient_load(path)))
    if found["router_trace"]:
        try:
            sources.append(("router", report.load(found["router_trace"])))
        except (OSError, ValueError) as e:
            load_errors.append(str(e))
            sources.append(("router", _lenient_load(found["router_trace"])))

    rollup = FleetRollup()
    series: dict[str, dict] = {}
    for path in found["sidecars"]:
        label = _label(path)
        rep = telemetry.read_frames(path)
        rollup.truncated += rep["truncated"]
        for s in rep["snapshots"]:
            rollup.ingest(s, worker=label)
        series[label] = rep

    merged = merge_traces(sources)
    burn_events = [r for r in merged if r.get("kind") == "event"
                   and r.get("name") == "serve.fleet.burn"]
    scale_events = [r for r in merged if r.get("kind") == "event"
                    and r.get("name") == "serve.fleet.scale"]
    burn_precedes_scale = None
    if burn_events and scale_events:
        burn_precedes_scale = (min(e.get("ts", 0.0) for e in burn_events)
                               <= min(e.get("ts", 0.0) for e in scale_events))

    per_worker_loss = {
        label: {"snapshots": len(rep["snapshots"]),
                "truncated": rep["truncated"]}
        for label, rep in series.items()
    }
    summary = {
        "state_dir": state_dir,
        "sources": [label for label, _ in sources],
        "records": len(merged),
        "tracks": sorted({label for label, recs in sources if recs}),
        "load_errors": load_errors,
        "telemetry": rollup.summary() if series else None,
        "clock_offsets": rollup.clock_offsets() if series else None,
        "per_worker_sidecar": per_worker_loss,
        "burn_events": len(burn_events),
        "scale_events": [
            {"ts": e.get("ts"), **(e.get("attrs") or {})}
            for e in scale_events
        ],
        "burn_precedes_scale": burn_precedes_scale,
    }
    if chrome_out:
        chrome = to_chrome(sources, series)
        with open(chrome_out, "w") as fd:
            json.dump(chrome, fd)
        summary["chrome"] = chrome_out
        summary["chrome_events"] = len(chrome["traceEvents"])
    return summary


def _lenient_load(path: str) -> list[dict]:
    """Best-effort record parse: skip unparseable lines instead of
    raising — the shape of a trace file whose writer was killed."""
    records: list[dict] = []
    try:
        with open(path) as fd:
            for line in fd:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict) and "kind" in rec:
                    records.append(rec)
    except OSError:
        pass
    return records


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="analysis/fleet_report.py")
    p.add_argument("state_dir", help="fleet run state dir (--dir)")
    p.add_argument("--router-trace", default=None, metavar="PATH",
                   help="the parent's MOMP_TRACE file (burn/scale events)")
    p.add_argument("--chrome", default=None, metavar="OUT",
                   help="write the merged Perfetto timeline here")
    p.add_argument("--json", action="store_true",
                   help="emit the summary as one JSON object")
    args = p.parse_args(argv)
    if not os.path.isdir(args.state_dir):
        print(f"fleet_report: not a directory: {args.state_dir}",
              file=sys.stderr)
        return 2
    summary = fleet_report(args.state_dir, args.router_trace, args.chrome)
    if args.json:
        print(json.dumps(summary))
    else:
        print(f"fleet: {len(summary['sources'])} trace sources, "
              f"{summary['records']} records, tracks: "
              f"{', '.join(summary['tracks']) or '-'}")
        tel = summary["telemetry"]
        if tel:
            loss = tel["loss"]
            print(f"telemetry: {tel['snapshots']} snapshots, "
                  f"resolved={tel['resolved']} shed={tel['shed']} "
                  f"p50={tel['p50_s']}s p99={tel['p99_s']}s "
                  f"loss={loss['lost']}/{loss['expected']}")
        if summary["scale_events"]:
            print(f"scale decisions: {len(summary['scale_events'])} "
                  f"(burn events: {summary['burn_events']}, "
                  f"burn precedes scale: {summary['burn_precedes_scale']})")
        if summary.get("chrome"):
            print(f"wrote {summary['chrome_events']} trace events to "
                  f"{summary['chrome']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
