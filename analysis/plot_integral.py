"""Quadrature sweep plots: raw times + speedup from a ``times.txt``.

Script form of the reference's ``1-integral/integral_plots.ipynb`` (cells
1-2, rendering ``integral_plot.png``/``integral_plot_accel.png``): line k
of the times file is the wall time at k devices/ranks; render the raw
times and the speedup ``T1/TN`` as scatter plus dashed line. Works on
reference-produced (``integral_out.txt``, ``times.txt`` — gtime error
lines skipped) and TPU-produced times files alike.

Usage: ``python analysis/plot_integral.py [times.txt] [out_prefix]``
writes ``<out_prefix>.png`` (times) and ``<out_prefix>_accel.png``
(speedup); the default prefix is ``integral_plot``.
"""

from __future__ import annotations

import sys

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402
import numpy as np  # noqa: E402

from plot_life import load_times, plot_speedup  # noqa: E402
# (same times.txt dialect and the same T1/TN rendering)


def plot_times(times: np.ndarray, out: str) -> None:
    n = np.arange(1, len(times) + 1)
    fig, ax = plt.subplots(figsize=(7, 5))
    ax.scatter(n, times, zorder=3)
    ax.plot(n, times, linestyle="--", zorder=2)
    ax.set_xlabel("devices")
    ax.set_ylabel("wall time [s]")
    ax.grid(True, alpha=0.3)
    fig.tight_layout()
    fig.savefig(out, dpi=120)


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    times_path = argv[0] if argv else "times.txt"
    prefix = argv[1] if len(argv) > 1 else "integral_plot"
    times = load_times(times_path)
    if len(times) == 0:
        print(f"{times_path}: no parsable times", file=sys.stderr)
        return 1
    plot_times(times, f"{prefix}.png")
    plot_speedup(times, f"{prefix}_accel.png")
    print(f"{prefix}.png")
    print(f"{prefix}_accel.png")
    return 0


if __name__ == "__main__":
    sys.exit(main())
