// Native runtime IO for mpi_and_open_mp_tpu: config parsing + VTK writing.
//
// The reference's runtime layer is compiled C (cfg loader at
// /root/reference/3-life/life2d.c:52-72, VTK writer at
// 3-life/life_mpi.c:120-148); this framework keeps those host-side hot
// paths native as well. Exposed as a plain C ABI for ctypes
// (mpi_and_open_mp_tpu/utils/native.py). Built fresh for this project —
// buffered IO instead of the reference's fscanf/fprintf-per-cell.
//
// Build: make -C native     (produces liblifeio.so)

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

using std::uint8_t;

extern "C" {

// Parse a .cfg file: header[5] = {steps, save_steps, nx, ny, ncells};
// *cells_out = malloc'd flat (i, j) pairs (2*ncells int64), owned by the
// caller via lifeio_free. Returns 0 on success, negative error codes
// otherwise (-1 open, -2 header, -3 dangling coordinate).
int lifeio_load_config(const char *path, long long header[5],
                       long long **cells_out) {
    *cells_out = nullptr;
    FILE *fd = std::fopen(path, "rb");
    if (!fd) return -1;

    std::fseek(fd, 0, SEEK_END);
    long size = std::ftell(fd);
    std::fseek(fd, 0, SEEK_SET);
    std::string text(static_cast<size_t>(size), '\0');
    size_t got = std::fread(text.data(), 1, static_cast<size_t>(size), fd);
    std::fclose(fd);
    text.resize(got);

    std::vector<long long> tokens;
    const char *s = text.c_str();
    char *end = nullptr;
    while (*s) {
        while (*s == ' ' || *s == '\t' || *s == '\n' || *s == '\r') ++s;
        if (!*s) break;
        long long v = std::strtoll(s, &end, 10);
        if (end == s) return -2;  // non-numeric garbage
        tokens.push_back(v);
        s = end;
    }
    if (tokens.size() < 4) return -2;
    size_t ncoords = tokens.size() - 4;
    if (ncoords % 2) return -3;

    for (int k = 0; k < 4; ++k) header[k] = tokens[k];
    long long ncells = static_cast<long long>(ncoords / 2);
    header[4] = ncells;
    if (ncells) {
        auto *cells = static_cast<long long *>(
            std::malloc(sizeof(long long) * ncoords));
        if (!cells) return -4;
        std::memcpy(cells, tokens.data() + 4, sizeof(long long) * ncoords);
        *cells_out = cells;
    }
    return 0;
}

void lifeio_free(long long *p) { std::free(p); }

// Write an ASCII VTK 3.0 STRUCTURED_POINTS snapshot of a (ny, nx) board
// (row-major int32), format-compatible with the reference's output
// (header fields as at 3-life/life_mpi.c:129-140). Single buffered write.
int lifeio_write_vtk(const char *path, const int *board, long long nx,
                     long long ny) {
    std::string out;
    out.reserve(static_cast<size_t>(nx * ny * 2 + 256));
    char header[256];
    std::snprintf(header, sizeof header,
                  "# vtk DataFile Version 3.0\n"
                  "Created by mpi_and_open_mp_tpu\n"
                  "ASCII\n"
                  "DATASET STRUCTURED_POINTS\n"
                  "DIMENSIONS %lld %lld 1\n"
                  "SPACING 1 1 0.0\n"
                  "ORIGIN 0 0 0.0\n"
                  "CELL_DATA %lld\n"
                  "SCALARS life int 1\n"
                  "LOOKUP_TABLE life_table\n",
                  nx + 1, ny + 1, nx * ny);
    out += header;
    char num[24];
    for (long long k = 0; k < nx * ny; ++k) {
        int n = std::snprintf(num, sizeof num, "%d\n", board[k]);
        out.append(num, static_cast<size_t>(n));
    }
    FILE *fd = std::fopen(path, "wb");
    if (!fd) return -1;
    size_t wrote = std::fwrite(out.data(), 1, out.size(), fd);
    std::fclose(fd);
    return wrote == out.size() ? 0 : -2;
}

// Serial Game-of-Life oracle: advance a (ny, nx) uint8 board `steps`
// generations on a periodic torus. Same role as the reference's compiled
// life2d oracle (/root/reference/3-life/life2d.c:104-130): an independent,
// native ground truth the JAX/Pallas kernels are checked against — written
// here as a scanline pass with explicit wrap rows/columns rather than the
// reference's per-cell modular ind() arithmetic.
void lifeio_life_steps(uint8_t *board, long long nx, long long ny,
                       long long steps) {
    std::vector<uint8_t> next(static_cast<size_t>(nx * ny));
    for (long long s = 0; s < steps; ++s) {
        for (long long j = 0; j < ny; ++j) {
            const uint8_t *up = board + ((j - 1 + ny) % ny) * nx;
            const uint8_t *mid = board + j * nx;
            const uint8_t *dn = board + ((j + 1) % ny) * nx;
            uint8_t *out = next.data() + j * nx;
            for (long long i = 0; i < nx; ++i) {
                long long il = (i - 1 + nx) % nx, ir = (i + 1) % nx;
                int n = up[il] + up[i] + up[ir] + mid[il] + mid[ir] +
                        dn[il] + dn[i] + dn[ir];
                out[i] = (n == 3 || (n == 2 && mid[i])) ? 1 : 0;
            }
        }
        std::memcpy(board, next.data(), static_cast<size_t>(nx * ny));
    }
}

}  // extern "C"
