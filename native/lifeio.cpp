// Native runtime IO for mpi_and_open_mp_tpu: config parsing + VTK writing.
//
// The reference's runtime layer is compiled C (cfg loader at
// /root/reference/3-life/life2d.c:52-72, VTK writer at
// 3-life/life_mpi.c:120-148); this framework keeps those host-side hot
// paths native as well. Exposed as a plain C ABI for ctypes
// (mpi_and_open_mp_tpu/utils/native.py). Built fresh for this project —
// buffered IO instead of the reference's fscanf/fprintf-per-cell.
//
// Build: make -C native     (produces liblifeio.so)

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

using std::uint8_t;

extern "C" {

// Parse a .cfg file: header[5] = {steps, save_steps, nx, ny, ncells};
// *cells_out = malloc'd flat (i, j) pairs (2*ncells int64), owned by the
// caller via lifeio_free. Returns 0 on success, negative error codes
// otherwise (-1 open, -2 header, -3 dangling coordinate).
int lifeio_load_config(const char *path, long long header[5],
                       long long **cells_out) {
    *cells_out = nullptr;
    FILE *fd = std::fopen(path, "rb");
    if (!fd) return -1;

    std::fseek(fd, 0, SEEK_END);
    long size = std::ftell(fd);
    std::fseek(fd, 0, SEEK_SET);
    std::string text(static_cast<size_t>(size), '\0');
    size_t got = std::fread(text.data(), 1, static_cast<size_t>(size), fd);
    std::fclose(fd);
    text.resize(got);

    std::vector<long long> tokens;
    const char *s = text.c_str();
    char *end = nullptr;
    while (*s) {
        while (*s == ' ' || *s == '\t' || *s == '\n' || *s == '\r') ++s;
        if (!*s) break;
        long long v = std::strtoll(s, &end, 10);
        if (end == s) return -2;  // non-numeric garbage
        tokens.push_back(v);
        s = end;
    }
    if (tokens.size() < 4) return -2;
    size_t ncoords = tokens.size() - 4;
    if (ncoords % 2) return -3;

    for (int k = 0; k < 4; ++k) header[k] = tokens[k];
    long long ncells = static_cast<long long>(ncoords / 2);
    header[4] = ncells;
    if (ncells) {
        auto *cells = static_cast<long long *>(
            std::malloc(sizeof(long long) * ncoords));
        if (!cells) return -4;
        std::memcpy(cells, tokens.data() + 4, sizeof(long long) * ncoords);
        *cells_out = cells;
    }
    return 0;
}

void lifeio_free(long long *p) { std::free(p); }

// Write an ASCII VTK 3.0 STRUCTURED_POINTS snapshot of a (ny, nx) board
// (row-major int32), format-compatible with the reference's output
// (header fields as at 3-life/life_mpi.c:129-140). Single buffered write.
int lifeio_write_vtk(const char *path, const int *board, long long nx,
                     long long ny) {
    std::string out;
    out.reserve(static_cast<size_t>(nx * ny * 2 + 256));
    char header[256];
    std::snprintf(header, sizeof header,
                  "# vtk DataFile Version 3.0\n"
                  "Created by mpi_and_open_mp_tpu\n"
                  "ASCII\n"
                  "DATASET STRUCTURED_POINTS\n"
                  "DIMENSIONS %lld %lld 1\n"
                  "SPACING 1 1 0.0\n"
                  "ORIGIN 0 0 0.0\n"
                  "CELL_DATA %lld\n"
                  "SCALARS life int 1\n"
                  "LOOKUP_TABLE life_table\n",
                  nx + 1, ny + 1, nx * ny);
    out += header;
    char num[24];
    for (long long k = 0; k < nx * ny; ++k) {
        int n = std::snprintf(num, sizeof num, "%d\n", board[k]);
        out.append(num, static_cast<size_t>(n));
    }
    FILE *fd = std::fopen(path, "wb");
    if (!fd) return -1;
    size_t wrote = std::fwrite(out.data(), 1, out.size(), fd);
    std::fclose(fd);
    return wrote == out.size() ? 0 : -2;
}

// Serial Game-of-Life oracle: advance a (ny, nx) uint8 board `steps`
// generations on a periodic torus. Same role as the reference's compiled
// life2d oracle (/root/reference/3-life/life2d.c:104-130): an independent,
// native ground truth the JAX/Pallas kernels are checked against — written
// here as a scanline pass with explicit wrap rows/columns rather than the
// reference's per-cell modular ind() arithmetic.
void lifeio_life_steps(uint8_t *board, long long nx, long long ny,
                       long long steps) {
    std::vector<uint8_t> next(static_cast<size_t>(nx * ny));
    for (long long s = 0; s < steps; ++s) {
        for (long long j = 0; j < ny; ++j) {
            const uint8_t *up = board + ((j - 1 + ny) % ny) * nx;
            const uint8_t *mid = board + j * nx;
            const uint8_t *dn = board + ((j + 1) % ny) * nx;
            uint8_t *out = next.data() + j * nx;
            for (long long i = 0; i < nx; ++i) {
                long long il = (i - 1 + nx) % nx, ir = (i + 1) % nx;
                int n = up[il] + up[i] + up[ir] + mid[il] + mid[ir] +
                        dn[il] + dn[i] + dn[ir];
                out[i] = (n == 3 || (n == 2 && mid[i])) ? 1 : 0;
            }
        }
        std::memcpy(board, next.data(), static_cast<size_t>(nx * ny));
    }
}

namespace {

// out[i] = v[(i-1+nx) % nx] over a 64-cells/word packed row.
void shift_toward_higher(const std::uint64_t *v, std::uint64_t *out,
                         long long W, long long nx, std::uint64_t last_mask) {
    for (long long w = 0; w < W; ++w)
        out[w] = (v[w] << 1) | (w ? (v[w - 1] >> 63) : 0);
    out[0] |= (v[W - 1] >> ((nx - 1) & 63)) & 1ULL;  // torus wrap
    out[W - 1] &= last_mask;
}

// out[i] = v[(i+1) % nx].
void shift_toward_lower(const std::uint64_t *v, std::uint64_t *out,
                        long long W, long long nx, std::uint64_t last_mask) {
    for (long long w = 0; w < W; ++w)
        out[w] = (v[w] >> 1) | (w + 1 < W ? (v[w + 1] << 63) : 0);
    out[W - 1] &= last_mask;
    out[W - 1] |= (v[0] & 1ULL) << ((nx - 1) & 63);  // torus wrap
}

}  // namespace

// Bit-packed serial oracle: 64 cells per uint64 along x, carry-save-adder
// rule — the host twin of the TPU kernels' bitwise algorithm
// (mpi_and_open_mp_tpu/ops/bitlife.py), ~50x the scalar oracle above on
// big boards. Kept as a SECOND independent native implementation; tests
// cross-check it against both the scalar path and the NumPy oracle.
void lifeio_life_steps_bits(uint8_t *board, long long nx, long long ny,
                            long long steps) {
    const long long W = (nx + 63) / 64;
    const std::uint64_t last_mask =
        (nx % 64) ? ((1ULL << (nx % 64)) - 1) : ~0ULL;
    std::vector<std::uint64_t> cur(static_cast<size_t>(W * ny), 0);
    std::vector<std::uint64_t> nxt(static_cast<size_t>(W * ny), 0);
    for (long long j = 0; j < ny; ++j)
        for (long long i = 0; i < nx; ++i)
            if (board[j * nx + i])
                cur[j * W + i / 64] |= 1ULL << (i % 64);

    std::vector<std::uint64_t> v0(W), v1(W), l0(W), r0(W), l1(W), r1(W);
    for (long long s = 0; s < steps; ++s) {
        for (long long j = 0; j < ny; ++j) {
            const std::uint64_t *up = &cur[((j - 1 + ny) % ny) * W];
            const std::uint64_t *mid = &cur[j * W];
            const std::uint64_t *dn = &cur[((j + 1) % ny) * W];
            for (long long w = 0; w < W; ++w) {
                std::uint64_t a = up[w], b = mid[w], c = dn[w];
                v0[w] = a ^ b ^ c;                  // vertical triple sum,
                v1[w] = (a & b) | (c & (a ^ b));    // 2-bit carry-save
            }
            shift_toward_higher(v0.data(), l0.data(), W, nx, last_mask);
            shift_toward_lower(v0.data(), r0.data(), W, nx, last_mask);
            shift_toward_higher(v1.data(), l1.data(), W, nx, last_mask);
            shift_toward_lower(v1.data(), r1.data(), W, nx, last_mask);
            std::uint64_t *out = &nxt[j * W];
            for (long long w = 0; w < W; ++w) {
                std::uint64_t t0 = l0[w] ^ v0[w] ^ r0[w];
                std::uint64_t k0 =
                    (l0[w] & v0[w]) | (r0[w] & (l0[w] ^ v0[w]));
                std::uint64_t u0 = l1[w] ^ v1[w] ^ r1[w];
                std::uint64_t u1 =
                    (l1[w] & v1[w]) | (r1[w] & (l1[w] ^ v1[w]));
                std::uint64_t t1 = u0 ^ k0;
                std::uint64_t vc = u0 & k0;
                std::uint64_t t2 = u1 ^ vc;
                std::uint64_t t3 = u1 & vc;
                // alive' = T==3 | (alive & T==4), T includes the centre.
                std::uint64_t is3 = t0 & t1 & ~t2 & ~t3;
                std::uint64_t is4 = ~t0 & ~t1 & t2 & ~t3;
                out[w] = (is3 | (mid[w] & is4)) &
                         (w == W - 1 ? last_mask : ~0ULL);
            }
        }
        cur.swap(nxt);
    }
    for (long long j = 0; j < ny; ++j)
        for (long long i = 0; i < nx; ++i)
            board[j * nx + i] =
                static_cast<uint8_t>((cur[j * W + i / 64] >> (i % 64)) & 1);
}

}  // extern "C"
