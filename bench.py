"""Round benchmark: Game-of-Life cell-updates/sec on the p46gun_big workload.

Workload per the reference's scaling benchmark (`3-life/p46gun_big.cfg`):
500x500 periodic torus, 10,000 steps, no intermediate saves = 2.5e9 cell
updates. Baseline: best recorded MPI result, 1.937 s @ 27 ranks = 1.29e9
cups (`6-cartesian/times.txt:27`, see BASELINE.md). The board content is a
fixed-seed random soup — cups is content-independent for a dense stencil.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``value`` is the STEADY-STATE rate — the marginal per-step cups,
differenced between two run lengths so the fixed ~70 ms dispatch round
trip through the tunneled chip cancels (r01-r03 proved the end-to-end
number is ±16% RTT jitter across identical code; the differenced rate
held 1.25-1.29e12). End-to-end time/rate stay as secondary fields.
"""

import argparse
import functools
import json
import os
import sys
import time

import numpy as np


BASELINE_CUPS = 1.29e9
NY = NX = 500
STEPS = 10_000


def _probe_devices(timeout_s: float) -> tuple[bool, str]:
    """One device-discovery probe. The implementation lives in
    ``robust.watchdog.probe_once`` (subprocess probe; a hung child is
    ABANDONED, never killed — a killed mid-claim client wedges the relay
    for hours, see .claude/skills/verify/SKILL.md). This module-level
    indirection stays: tests stub it, and ``probe_devices`` below is
    handed the attribute at call time so the stub keeps working."""
    from mpi_and_open_mp_tpu.robust import watchdog

    return watchdog.probe_once(timeout_s)


def _env_num(name: str, default, cast):
    try:
        return cast(os.environ.get(name, default))
    except ValueError:
        return cast(default)


def _checkpointed_run(args) -> dict:
    """The robustness phase: a checkpointed (optionally resumed) serial
    Life run of the bench workload, CRC-stamped and — when the board is
    small enough to replay on the host — parity-gated against the
    fault-free NumPy oracle. This is what the chaos CI smoke drives:
    under ``MOMP_CHAOS=preempt=k`` the run raises
    :class:`~mpi_and_open_mp_tpu.robust.preempt.Preempted` after flushing
    a checkpoint (main() turns that into exit 75 + ``"resume": true``),
    and the follow-up ``--resume`` invocation must complete bit-identical
    to the oracle.
    """
    import zlib

    from mpi_and_open_mp_tpu.apps.life import find_latest_checkpoint
    from mpi_and_open_mp_tpu.models.life import LifeSim
    from mpi_and_open_mp_tpu.ops.life_ops import life_step_numpy
    from mpi_and_open_mp_tpu.utils.config import config_from_board

    rng = np.random.default_rng(46)  # same board as the headline phases
    board = (rng.random((NY, NX)) < 0.3).astype(np.uint8)
    cfg = config_from_board(board, steps=STEPS, save_steps=0)
    every = args.checkpoint_every or max(1, STEPS // 10)
    kwargs = dict(layout="serial", impl="auto",
                  checkpoint_dir=args.checkpoint_dir,
                  checkpoint_every=every)
    fields = {"checkpoint_every": every}
    if args.resume:
        latest = find_latest_checkpoint(args.checkpoint_dir)
        if latest is None:
            raise RuntimeError(
                f"--resume: no checkpoints in {args.checkpoint_dir!r}")
        path, step = latest
        sim = LifeSim.from_checkpoint(path, cfg, **kwargs)
        fields["resumed_step"] = step
    else:
        sim = LifeSim(cfg, **kwargs)
    final = sim.run()  # raises Preempted on signal / chaos preemption
    crc = zlib.crc32(np.ascontiguousarray(final).tobytes()) & 0xFFFFFFFF
    fields["checkpoint_run_crc32"] = f"{crc:08x}"
    if sim.recoveries:
        fields["checkpoint_run_recovered"] = list(sim.recoveries)
    # Host oracle replay is O(NY*NX*STEPS) python-side — gate it to the
    # smoke sizes; the flagship keeps only the CRC (cross-run comparable).
    if NY * NX * STEPS <= 2**26:
        oracle = board.copy()
        for _ in range(STEPS):
            oracle = life_step_numpy(oracle)
        if not np.array_equal(final, oracle):
            raise RuntimeError(
                "checkpointed run diverged from the fault-free oracle")
        fields["checkpoint_parity"] = True
    return fields


def _batched_phase(batch: int, cups_single: float) -> dict:
    """The request-batched throughput phase (``--batch B``): B DISTINCT
    boards of the bench shape advanced STEPS steps in ONE device
    dispatch through the batched native engines
    (``ops.pallas_life.life_run_vmem_batch``), plus the serve-layer
    micro-batcher driving the same stack shape. Runs on every backend —
    batching amortizes the fixed dispatch cost, which is exactly what
    the CPU-fallback line is dominated by. Honesty discipline matches
    the headline: EVERY board is gated bit-exact against the NumPy
    oracle before any timing is recorded, and the steady rate is
    chain-differenced (the batched step count is a runtime scalar on
    every path, so the chained dispatch reuses the same executable).
    """
    import jax
    import jax.numpy as jnp

    from mpi_and_open_mp_tpu.ops import bitlife, pallas_life
    from mpi_and_open_mp_tpu.ops.life_ops import life_step_numpy
    from mpi_and_open_mp_tpu.serve import ShapeBucketBatcher, retrace_counts
    from mpi_and_open_mp_tpu.utils.timing import anchor_sync

    rng = np.random.default_rng(47)  # distinct per-board soups
    stack = (rng.random((batch, NY, NX)) < 0.3).astype(np.uint8)
    on_tpu = jax.default_backend() == "tpu"
    path = pallas_life.native_path_batch(stack.shape, on_tpu=on_tpu)
    fields = {
        "batch": batch,
        "batch_engine": f"batch:{path}",
        # Closed vocabulary {cell-packed, bitsliced}; the ledger keys on
        # it and the sentinel flags bitsliced -> cell-packed downgrades.
        "batch_pack_layout": pallas_life.batch_pack_layout(
            stack.shape, on_tpu=on_tpu),
    }

    # Per-board honesty gate: the batched engine must be bit-exact on
    # EVERY board of the stack (a fused-over-batch bug could corrupt one
    # board while the rest pass — name the divergent ones).
    stack_j = jnp.asarray(stack)
    got = np.asarray(pallas_life.life_run_vmem_batch(stack_j, 8))
    bad = []
    for b in range(batch):
        ref = stack[b].copy()
        for _ in range(8):
            ref = life_step_numpy(ref)
        if not np.array_equal(got[b], ref):
            bad.append(b)
    if bad:
        fields["batched_error"] = (
            f"parity check failed on boards {bad[:8]} of {batch}")
        return fields
    fields["batched_parity"] = True

    def timed(n, reps=3):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            anchor_sync(pallas_life.life_run_vmem_batch(stack_j, n),
                        fetch_all=True)
            best = min(best, time.perf_counter() - t0)
        return best

    # Compile/warm outside the brackets (the gate above ran n=8; n is a
    # runtime scalar, so this is a warm re-dispatch, not a compile).
    anchor_sync(pallas_life.life_run_vmem_batch(stack_j, STEPS),
                fetch_all=True)
    best = timed(STEPS)
    # Chained differencing, same discipline as measure(): big chains
    # only when the base run is RTT-bound (sub-second); a multi-second
    # CPU run takes the cheapest chain (2x) single-shot.
    rtt_bound = best < 1.0
    mult, reps = (161, 3) if rtt_bound else (2, 1)
    chained = timed(STEPS * mult, reps)
    differenced = chained > best
    steady = (chained - best) / (mult - 1) if differenced else best
    updates = batch * NY * NX * STEPS
    fields.update({
        "batched_cups": round(updates / best, 1),
        "batched_requests_per_sec": round(batch / best, 3),
        "batched_steady_cups": round(updates / steady, 1),
        "batched_is_differenced": differenced,
        # The amortization headline: aggregate end-to-end rate vs the
        # single-board end-to-end rate measured by the headline phase.
        "batched_vs_single": (round(updates / best / cups_single, 2)
                              if cups_single else None),
    })

    if fields["batch_pack_layout"] == "bitsliced":
        # Layout A/B, both sides the same discipline: chain-differenced
        # per-step rate (9x chain, best of 3) with the baseline engine
        # parity-gated first. The baseline is the engine a bitsliced
        # stack would otherwise run — the vmapped cell-packed XLA loop
        # (the daemon's "batch:xla" rung). The ratio is measured in ONE
        # process so RTT and machine noise cancel; the sentinel watches
        # it for quiet erosion of the layout's advantage.
        n0, mult_ab, cells = min(STEPS, 200), 9, batch * NY * NX

        def steady_of(run):
            anchor_sync(run(n0), fetch_all=True)  # warm re-dispatch

            def t(n):
                b = float("inf")
                for _ in range(3):
                    t0 = time.perf_counter()
                    anchor_sync(run(n), fetch_all=True)
                    b = min(b, time.perf_counter() - t0)
                return b

            t1, t2 = t(n0), t(n0 * mult_ab)
            if t2 > t1:
                return (t2 - t1) / (n0 * (mult_ab - 1))
            return t1 / n0

        base8 = np.asarray(bitlife.life_run_bits_xla_batch(stack_j, 8))
        if not np.array_equal(base8, got):
            fields["batched_error"] = (
                "cell-packed baseline diverged from the gated bitsliced "
                "output — layout A/B not recorded")
            return fields
        per_bs = steady_of(
            lambda n: pallas_life.life_run_vmem_batch(stack_j, n))
        per_cp = steady_of(
            lambda n: bitlife.life_run_bits_xla_batch(stack_j, n))
        fields.update({
            "bitsliced_cups": round(cells / per_bs, 1),
            "cellpacked_vmapped_cups": round(cells / per_cp, 1),
            "vs_cellpacked": round(per_cp / per_bs, 2),
        })

    # Serve-layer demo: the SAME B requests through the micro-batcher —
    # one shape bucket, one dispatch, and (steps being runtime) zero new
    # compiles beyond the gate's. The jit.retrace{fn=life_batch_*}
    # counters on the line's metrics snapshot are the proof.
    bat = ShapeBucketBatcher(max_batch=batch)
    for b in range(batch):
        bat.submit(stack[b], 8)
    out = bat.flush()
    fields.update({
        "serve_buckets": len(bat.last_flush_stats),
        "serve_batches": len(bat.last_flush_stats),
        "serve_requests": sum(s.requests for s in bat.last_flush_stats),
        "serve_parity": all(
            np.array_equal(o, g) for o, g in zip(out, got)),
        "batch_retraces": retrace_counts(),
    })
    return fields


def _phase_metrics_delta(key: str, before: dict) -> dict:
    """Per-phase metric scoping (``obs.metrics.delta``): each opt-in
    phase snapshots the registry at entry and publishes only the
    movement IT caused, so ``--batch`` counters cannot bleed into the
    ``--serve`` / ``--loadgen`` sub-objects when phases stack on one
    bench line. The global cumulative snapshot still rides the line
    unchanged (``metrics``)."""
    from mpi_and_open_mp_tpu.obs import metrics as obs_metrics

    if not obs_metrics.metrics_on():
        return {}
    return {f"{key}_phase_metrics":
            obs_metrics.delta(before, obs_metrics.snapshot())}


def _serve_phase(n: int) -> dict:
    """The serving-daemon latency phase (``--serve N``): a seeded
    mixed-shape burst of N requests through the supervised daemon
    (``serve.daemon`` — admission control, per-bucket deadlines, the
    guards recovery ladder), reporting throughput and latency
    percentiles. Honesty discipline matches every other phase: EVERY
    resolved board is gated bit-exact against the NumPy oracle before
    the numbers are recorded, and a shed ticket must carry an explicit
    policy reason. A chaos plan (``MOMP_CHAOS``) drives the same code
    the soak test exercises: ``serve_fail`` faults surface here as
    ``serve_degraded``/``serve_retries``, a ``preempt`` plan raises
    Preempted through main()'s exit-75 contract.
    """
    import tempfile

    from mpi_and_open_mp_tpu.ops.life_ops import life_step_numpy
    from mpi_and_open_mp_tpu.serve import ServePolicy, ServingDaemon
    from mpi_and_open_mp_tpu.serve.queue import DONE

    policy = ServePolicy(max_batch=8, max_depth=max(64, 2 * n),
                         max_wait_s=0.005)

    def burst(wal_path=None, wal_fsync="every-record", aot_dir=None):
        """One seeded burst through a fresh daemon; identical request
        stream every time so the WAL-on/off and AOT-cold/warm deltas
        isolate the journal tax and the warm-start win respectively.
        With ``aot_dir`` the cache attach + preload runs INSIDE the
        timed window — a cold cache honestly pays its export builds
        where a cold daemon would pay its traces. Returns (summary,
        wall, oracle-mismatch count)."""
        shapes = ((48, 48), (64, 64))
        steps = (4, 8)
        aot = None
        t0 = time.perf_counter()
        if aot_dir is not None:
            from mpi_and_open_mp_tpu.serve.aotcache import AOTCache

            aot = AOTCache(aot_dir)
        daemon = ServingDaemon(policy, wal_path=wal_path,
                               wal_fsync=wal_fsync, aot_cache=aot)
        if aot is not None:
            aot.warm([(sh, "uint8") for sh in shapes], policy.max_batch)
        rng = np.random.default_rng(48)
        for i in range(n):
            ny, nx = shapes[i % len(shapes)]
            daemon.submit((rng.random((ny, nx)) < 0.3).astype(np.uint8),
                          steps[i % len(steps)])
        daemon.serve()  # Preempted propagates: the exit-75 contract
        wall = time.perf_counter() - t0
        s = daemon.summary()
        bad = 0
        for t in daemon.queue.tickets():
            if t.state != DONE:
                continue
            ref = np.asarray(t.board).copy()
            for _ in range(t.steps):
                ref = life_step_numpy(ref)
            if not np.array_equal(t.result, ref):
                bad += 1
        if wal_path is not None:
            daemon._wal.close()
        return s, wall, bad

    # The serve_* baseline fields stay WAL-OFF: the regression sentinel
    # trends them against pre-WAL history, which must not silently
    # absorb the durability tax. The tax gets its own serve_wal_*
    # fields from a second identical burst, journaled every-record.
    s, wall, bad = burst()
    fields = {
        "serve_daemon_requests": s["requests"],
        "serve_admitted": s["requests"] - s["shed_reasons"].get(
            "queue-depth", 0) - s["shed_reasons"].get("padding-waste", 0),
        "serve_resolved": s["resolved"],
        "serve_shed": s["shed"],
        "serve_shed_reasons": s["shed_reasons"],
        "serve_degraded": s["degraded"],
        "serve_retries": s["retries"],
        "serve_daemon_batches": s["batches"],
        "serve_daemon_engines": s["engines"],
        "serve_requests_per_sec": (round(s["resolved"] / wall, 2)
                                   if wall > 0 else None),
        "serve_p50_latency_s": s["p50_latency_s"],
        "serve_p99_latency_s": s["p99_latency_s"],
        "serve_daemon_parity": bad == 0,
    }
    if bad:
        fields["serve_daemon_error"] = (
            f"parity check failed on {bad} resolved boards")

    with tempfile.TemporaryDirectory(prefix="momp-bench-wal-") as td:
        ws, wwall, wbad = burst(wal_path=os.path.join(td, "serve.wal"))
    w = ws["wal"]
    fields.update({
        "serve_wal_fsync": w["fsync"],
        "serve_wal_records": w["records"],
        "serve_wal_bytes": w["bytes"],
        "serve_wal_syncs": w["syncs"],
        "serve_wal_fsync_s": w["sync_seconds"],
        "serve_wal_p50_latency_s": ws["p50_latency_s"],
        "serve_wal_p99_latency_s": ws["p99_latency_s"],
        # The durability tax, directly comparable: same seed, same
        # request stream, only the journal differs.
        "serve_wal_p50_delta_s": round(
            ws["p50_latency_s"] - s["p50_latency_s"], 6),
        "serve_wal_p99_delta_s": round(
            ws["p99_latency_s"] - s["p99_latency_s"], 6),
        "serve_wal_parity": wbad == 0,
    })
    if wbad:
        fields["serve_wal_error"] = (
            f"parity check failed on {wbad} resolved boards (WAL run)")

    # The warm-start win, measured the honest way: the SAME burst twice
    # over one cache directory. Burst 1 is the cold process (exports and
    # persists every bucket program inside its timed window); burst 2 is
    # the simulated restart (fresh AOTCache = fresh deserialize, like a
    # requeued daemon). cold_first_result_s is the ISSUE's headline:
    # construction -> first resolved ticket, where trace+compile lands.
    # Baseline serve_* fields above stay AOT-OFF (and WAL-OFF) so the
    # sentinel's history keys don't silently change meaning.
    with tempfile.TemporaryDirectory(prefix="momp-bench-aot-") as td:
        cs, cwall, cbad = burst(aot_dir=td)
        hs, hwall, hbad = burst(aot_dir=td)
    fields.update({
        "serve_cold_first_result_s": cs.get("cold_first_result_s"),
        "serve_aot_first_result_s": hs.get("cold_first_result_s"),
        "serve_aot_hits": hs["aot_hits"],
        "serve_aot_misses": hs["aot_misses"],
        "serve_aot_deserialize_s": hs["aot_deserialize_s"],
        "serve_aot_build_s": cs["aot_build_s"],
        "serve_aot_engines": hs["engines"],
        "serve_aot_p99_latency_s": hs["p99_latency_s"],
        "serve_aot_parity": cbad == 0 and hbad == 0,
    })
    if cbad or hbad:
        fields["serve_aot_error"] = (
            f"parity check failed on {cbad + hbad} resolved boards "
            "(AOT cold/warm runs)")
    return fields


def _fleet_phase(n: int, workers: int) -> dict:
    """The sharded-fleet phase (``--serve N --fleet W``): the same
    seeded burst twice through an in-process W-worker fleet
    (``serve.fleet.Fleet`` — consistent-hash affinity, rolled-up
    admission, per-worker WALs). Burst 1 runs clean and prices the
    aggregate serving surface (``fleet_requests_per_sec`` + tail
    latency). Burst 2 is the kill drill: the busiest worker is wedged
    mid-stream, the router must detect the missed heartbeats, replay
    the victim's journal, and re-home its pending set to the survivors
    — ``fleet_kill_recovery_s`` is wedge-to-last-re-homed-resolved, the
    tail-latency-under-kill number. Honesty discipline as everywhere:
    every resolved board (re-homed included) gates bit-exact against
    the NumPy oracle before anything is recorded, and the fleet books
    must balance (admitted == resolved + shed, re-home moves netted)."""
    import tempfile

    from mpi_and_open_mp_tpu.ops.life_ops import life_step_numpy
    from mpi_and_open_mp_tpu.serve import ServePolicy
    from mpi_and_open_mp_tpu.serve.fleet import Fleet

    policy = ServePolicy(max_batch=8, max_depth=max(64, 2 * n),
                         max_wait_s=0.005)
    shapes = ((48, 48), (64, 64))
    steps = (4, 8)
    sessions = max(4 * workers, 8)

    def burst(fleet, lo=0, hi=None):
        rng = np.random.default_rng(48)
        for i in range(n):
            ny, nx = shapes[i % len(shapes)]
            board = (rng.random((ny, nx)) < 0.3).astype(np.uint8)
            if lo <= i < (n if hi is None else hi):
                fleet.submit(board, steps[i % len(steps)],
                             session=f"s{i % sessions:04d}")

    def parity_bad(fleet) -> int:
        bad = 0
        for t in fleet.resolved_tickets():
            ref = np.asarray(t.board).copy()
            for _ in range(t.steps):
                ref = life_step_numpy(ref)
            if not np.array_equal(t.result, ref):
                bad += 1
        return bad

    fields: dict = {"fleet_workers": workers}
    with tempfile.TemporaryDirectory(prefix="momp-bench-fleet-") as td:
        fleet = Fleet(workers, policy,
                      wal_dir=os.path.join(td, "clean"),
                      heartbeat_interval_s=0.01)
        burst(fleet)
        t0 = time.perf_counter()
        fleet.serve_until_drained()
        wall = time.perf_counter() - t0
        s = fleet.summary()
        bad = parity_bad(fleet)
        fields.update({
            "fleet_requests": s["submitted"],
            "fleet_resolved": s["resolved"],
            "fleet_shed": s["shed"] + s["door_shed"],
            "fleet_steals": s["steals"],
            "fleet_requests_per_sec": (round(s["resolved"] / wall, 2)
                                       if wall > 0 else None),
            "fleet_p50_latency_s": s["p50_latency_s"],
            "fleet_p99_latency_s": s["p99_latency_s"],
            "fleet_books_balance": s["balanced"],
            "fleet_parity": bad == 0,
        })
        if bad:
            fields["fleet_error"] = (
                f"parity check failed on {bad} resolved boards")

        # The kill drill: same seed, fresh fleet; partial progress, then
        # the busiest worker stops heartbeating and the fleet must drain
        # anyway through the wedge->replay->re-home ladder.
        kfleet = Fleet(workers, policy,
                       wal_dir=os.path.join(td, "kill"),
                       heartbeat_interval_s=0.01)
        # Partial progress first (half the burst dispatched clean), then
        # the rest lands and the busiest worker wedges with a loaded
        # queue — the mid-stream death whose pending set the router must
        # recover from the victim's journal.
        burst(kfleet, hi=n // 2)
        kfleet.pump()
        burst(kfleet, lo=n // 2)
        victim = max(kfleet.handles,
                     key=lambda h: h.daemon.queue.depth()).index
        t_kill = time.monotonic()
        kfleet.wedge(victim)
        kfleet.serve_until_drained()
        ks = kfleet.summary()
        kbad = parity_bad(kfleet)
        adopted = kfleet.router.last_rehomed
        recovered_at = [t.resolved_at for t in adopted
                        if t.resolved_at is not None]
        fields.update({
            "fleet_kill_victim": victim,
            "fleet_rehomed": ks["rehomed"],
            "fleet_rehomed_resolved": ks["rehomed_resolved"],
            "fleet_kill_recovery_s": (
                round(max(recovered_at) - t_kill, 4)
                if recovered_at else None),
            "fleet_kill_books_balance": ks["balanced"],
            "fleet_kill_parity": kbad == 0,
        })
        if kbad:
            fields["fleet_kill_error"] = (
                f"parity check failed on {kbad} resolved boards "
                "(kill drill)")
    return fields


def _loadgen_phase(args) -> dict:
    """The elastic-fleet-under-load phase (``--loadgen R1,R2,..``).

    Two drills. (1) **Saturation sweep**: an open-loop Poisson arrival
    schedule (``serve.loadgen`` — arrivals are precomputed, never a
    reaction to completions, so there is no coordinated omission) over
    a mixed scenario (one-shot batch boards, resident-session steps,
    snapshot reads) at each offered rate on a FRESH fleet, judged
    against the declared SLO; ``loadgen_knee_rps`` is the last rung
    that met it — the capacity number — and the whole curve rides the
    line as ``loadgen_curve``. (2) **Membership cycle**: one run at the
    knee rate with the production failure script as scheduled events —
    wedge the busiest worker at 25% of the run, REJOIN it at 45%
    (``rejoin_recovery_s`` prices the resume-from-WAL + bounded ring
    re-entry + claim ladder), gracefully drain another at 65% — and
    the final-quartile goodput must recover to the pre-fault rate
    (``loadgen_cycle_recovery_frac``) with zero acked loss and the
    books balanced across both membership changes. Honesty discipline
    as everywhere: every resolved board gates bit-exact against the
    NumPy oracle, and every resident session's final snapshot gates
    against the oracle at its journaled step total, before anything is
    recorded."""
    import tempfile

    from mpi_and_open_mp_tpu.obs import telemetry as telemetry_mod
    from mpi_and_open_mp_tpu.ops.life_ops import life_step_numpy
    from mpi_and_open_mp_tpu.serve import (
        SLO, ElasticityPolicy, ScenarioMix, ServePolicy, run_open_loop,
        saturation_knee)
    from mpi_and_open_mp_tpu.serve.fleet import Fleet

    rates = [float(r) for r in str(args.loadgen).split(",") if r.strip()]
    workers = args.fleet or 3
    duration = args.loadgen_duration
    slo = SLO(p99_s=args.loadgen_slo_p99, goodput_frac=0.5)
    mix = ScenarioMix(batch=0.7, resident=0.25, snapshot=0.05,
                      shapes=((48, 48), (64, 64)), steps=(2, 4),
                      sessions=max(8, 2 * workers))
    policy = ServePolicy(max_batch=8, max_depth=256, max_wait_s=0.005)

    def parity_bad(fleet) -> int:
        bad = 0
        for t in fleet.resolved_tickets():
            if t.board is None:
                continue  # resident step — gated via the snapshot below
            ref = np.asarray(t.board).copy()
            for _ in range(t.steps):
                ref = life_step_numpy(ref)
            if not np.array_equal(t.result, ref):
                bad += 1
        for sid in list(fleet.router._session_home):
            home = fleet.router._home_worker(sid)
            entry = home.daemon._session_log.get(sid)
            if entry is None:
                bad += 1
                continue
            ref = np.asarray(entry["board"]).copy()
            for _ in range(int(entry["steps"])):
                ref = life_step_numpy(ref)
            if not np.array_equal(fleet.snapshot_session(sid), ref):
                bad += 1
        return bad

    fields: dict = {
        "loadgen_workers": workers,
        "loadgen_rates": rates,
        "loadgen_duration_s": duration,
        "loadgen_slo_p99_s": slo.p99_s,
        "loadgen_slo_goodput_frac": slo.goodput_frac,
    }
    with tempfile.TemporaryDirectory(prefix="momp-bench-loadgen-") as td:
        # -- (1) the saturation sweep: fresh fleet per rung ------------
        reports = []
        rollups = []
        burns = []
        bad = 0
        balanced = True
        for j, rate in enumerate(rates):
            fleet = Fleet(workers, policy,
                          wal_dir=os.path.join(td, f"rung{j}"),
                          heartbeat_interval_s=0.01,
                          telemetry_interval_s=0.02)
            rep = run_open_loop(fleet, rate, duration, mix=mix, slo=slo,
                                seed=17)
            reports.append(rep)
            rollups.append(fleet.router.telemetry)
            burns.append(fleet.burn)
            bad += parity_bad(fleet)
            balanced = balanced and rep.books["balanced"]
        knee = saturation_knee(reports)
        at_knee = next((r for r in reversed(reports) if r.slo_ok),
                       reports[0])
        kroll = rollups[reports.index(at_knee)]
        kburn = burns[reports.index(at_knee)]
        fields.update({
            "loadgen_knee_rps": knee["knee_rps"],
            "loadgen_breach_rps": knee["breach_rps"],
            "loadgen_curve": knee["points"],
            "loadgen_goodput_rps": round(at_knee.goodput_rps, 3),
            "loadgen_p50_latency_s": round(at_knee.p50_s, 6),
            "loadgen_p99_latency_s": round(at_knee.p99_s, 6),
            "loadgen_p999_latency_s": round(at_knee.p999_s, 6),
            "loadgen_shed": dict(at_knee.shed),
            "loadgen_slo_ok": bool(at_knee.slo_ok),
            "loadgen_books_balance": balanced,
            "loadgen_parity": bad == 0,
        })
        if bad:
            fields["loadgen_error"] = (
                f"parity check failed on {bad} resolved boards/sessions "
                "(saturation sweep)")

        # Telemetry plane at the knee: the fleet rollup's merged-bucket
        # quantiles must agree with the loadgen-side exact percentiles
        # within the DECLARED histogram bucket error (adjacent-bucket
        # tolerance — the acceptance gate for the shipped series), and
        # the burn-rate peak at a met SLO is the recorded headroom.
        ksum = kroll.summary() if kroll is not None else {}
        fields.update({
            "telemetry_snapshots": ksum.get("snapshots", 0),
            "telemetry_rollup_rps": ksum.get("resolved_rps", 0.0),
            "telemetry_rollup_p50_s": ksum.get("p50_s"),
            "telemetry_rollup_p99_s": ksum.get("p99_s"),
            "telemetry_rollup_p999_s": ksum.get("p999_s"),
            "telemetry_bucket_rel_err": round(
                telemetry_mod.BUCKET_REL_ERR, 6),
            "telemetry_quantile_agree": (
                kroll is not None and kroll.hist.count > 0
                and kroll.hist.agrees(kroll.quantile(50), at_knee.p50_s)
                and kroll.hist.agrees(kroll.quantile(99), at_knee.p99_s)),
            "telemetry_snapshot_loss_frac": (
                ksum.get("loss", {}).get("frac", 0.0)),
            "loadgen_burn_rate_peak": (
                kburn.summary()["burn_peak_long"]
                if kburn is not None else None),
        })

        # -- (2) the membership cycle at the knee rate -----------------
        cycle_rate = knee["knee_rps"] or rates[0]
        cfleet = Fleet(workers, policy, wal_dir=os.path.join(td, "cycle"),
                       heartbeat_interval_s=0.01,
                       telemetry_interval_s=0.02,
                       # The controller rides the cycle drill so its
                       # verdicts land as recorded telemetry decisions.
                       # Surplus is unreachable (p99 < 0 never holds), so
                       # the controller can only ADD — the drill's single
                       # scripted drain stays the only drain on the books.
                       elasticity=ElasticityPolicy(
                           slo_p99_s=slo.p99_s,
                           slo_goodput_frac=slo.goodput_frac,
                           min_workers=1, max_workers=workers + 2,
                           surplus_p99_frac=0.0))
        drill: dict = {}

        def ev_wedge(fl):
            h = max((w for w in fl.handles
                     if not (w.wedged or w.drained)),
                    key=lambda w: w.daemon.queue.depth())
            drill["victim"] = h.index
            fl.wedge(h.index)

        def ev_rejoin(fl):
            idx = drill["victim"]
            deadline = time.monotonic() + 10.0
            while idx not in fl.router.wedged_workers:
                fl.pump()
                time.sleep(fl.router.heartbeat_interval_s)
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"cycle victim {idx} never declared wedged")
            t0 = time.perf_counter()
            drill["claimed"] = fl.rejoin_worker(idx)
            drill["rejoin_s"] = time.perf_counter() - t0

        def ev_drain(fl):
            live = [w for w in fl.handles
                    if not (w.wedged or w.drained or w.halted)
                    and w.index != drill["victim"]]
            h = max(live, key=lambda w: w.daemon.queue.depth())
            drill["drained"] = h.index
            fl.drain_worker(h.index)

        crep = run_open_loop(
            cfleet, cycle_rate, duration, mix=mix, slo=slo, seed=23,
            events=[(0.25, ev_wedge), (0.45, ev_rejoin),
                    (0.65, ev_drain)])
        cbad = parity_bad(cfleet)
        cs = cfleet.summary()
        # Goodput recovery: resolved-per-second in the pre-fault first
        # quartile vs the post-drain final quartile of the offered
        # window (plus the drain tail for the last requests' results).
        # Anchored on the first submission stamp — the run's own clock
        # zero, after the up-front session creates' compile time.
        done = [t for t in cfleet.resolved_tickets()
                if t.resolved_at is not None]
        t0 = min((t.submitted_at for t in done), default=0.0)
        t_end = max((t.resolved_at for t in done), default=t0)
        pre = [t for t in done if t.resolved_at - t0 < 0.25 * duration]
        post = [t for t in done
                if t.resolved_at - t0 >= 0.75 * duration]
        pre_rate = len(pre) / (0.25 * duration)
        post_win = max(t_end - t0 - 0.75 * duration, 1e-9)
        post_rate = len(post) / post_win
        recovery = post_rate / pre_rate if pre_rate > 0 else None
        zero_loss = (cs["balanced"] and cs["pending"] == 0
                     and cs["in_transit"] == 0)
        fields.update({
            "loadgen_cycle_rate_rps": round(cycle_rate, 3),
            "loadgen_cycle_victim": drill.get("victim"),
            "loadgen_cycle_claimed": drill.get("claimed"),
            "loadgen_cycle_drained": drill.get("drained"),
            "rejoin_recovery_s": (round(drill["rejoin_s"], 4)
                                  if "rejoin_s" in drill else None),
            "loadgen_cycle_goodput_rps": round(crep.goodput_rps, 3),
            "loadgen_cycle_recovery_frac": (round(recovery, 3)
                                            if recovery is not None
                                            else None),
            "loadgen_cycle_rejoins": cs["rejoins"],
            "loadgen_cycle_drains": cs["drains"],
            "loadgen_cycle_zero_acked_loss": zero_loss,
            "loadgen_cycle_books_balance": cs["balanced"],
            "loadgen_cycle_parity": cbad == 0,
            "loadgen_cycle_ok": (
                zero_loss and cbad == 0
                and cs["rejoins"] == 1 and cs["drains"] == 1
                and recovery is not None and recovery >= 0.9),
        })
        if cbad:
            fields["loadgen_cycle_error"] = (
                f"parity check failed on {cbad} resolved "
                "boards/sessions (membership cycle)")

        # The cycle drill's telemetry record: every controller verdict
        # carries the burn-rate window values that triggered it, the
        # wedge shows up as burn alerts, and the surviving workers lose
        # ZERO snapshots (the drain flush ships every last interval).
        csum = cfleet.router.telemetry.summary()
        fields.update({
            "telemetry_cycle_snapshots": csum["snapshots"],
            "telemetry_cycle_loss_frac": csum["loss"]["frac"],
            "telemetry_cycle_burn_alerts": (
                cfleet.burn.summary()["burn_alerts"]
                if cfleet.burn is not None else 0),
            "telemetry_cycle_burn_peak": (
                cfleet.burn.summary()["burn_peak_short"]
                if cfleet.burn is not None else 0.0),
            "telemetry_decisions": len(cfleet.decisions),
            "loadgen_cycle_decisions": cfleet.decisions,
            "telemetry_decisions_have_windows": all(
                "burn_short" in d and "burn_long" in d
                for d in cfleet.decisions),
        })
    return fields


def _sessions_phase(s: int) -> dict:
    """The resident-session phase (``--sessions S``): the device-resident
    A/B that prices what the session pool exists for. Side A (resident):
    S sessions created once into the daemon's ``serve.pool`` — boards
    cross the wire at create, then ``rounds`` rounds of one 4-step
    resident step per session, each round one in-place donated dispatch
    per slab, results never shipped back. Side B (ship): the identical
    workload through the plain ticket path — every round re-ships every
    board to the daemon and fetches the stepped board back, the
    per-request round trip the reference workflow (and PR 5-11 serving)
    always paid. Same seed, same boards, same total Life steps; only the
    residency discipline differs, so ``session_vs_ship`` is an RTT- and
    machine-noise-cancelled ratio (like ``vs_cellpacked``). Honesty
    gate: every final session snapshot must be bit-exact against the
    NumPy oracle advanced ``rounds * steps`` from the seed board before
    any number is recorded. Session creation happens OUTSIDE the timed
    bracket — the phase prices steady-state resident stepping, and the
    one-time create cost is exactly what the ship side pays per round.
    """
    from mpi_and_open_mp_tpu.ops.life_ops import life_step_numpy
    from mpi_and_open_mp_tpu.serve import ServePolicy, ServingDaemon
    from mpi_and_open_mp_tpu.serve.queue import DONE

    shape = (48, 48)
    steps_per_round = 4
    rounds = 8
    policy = ServePolicy(max_batch=8, max_depth=max(64, 4 * s),
                         max_wait_s=0.0)
    rng = np.random.default_rng(48)
    boards0 = {f"sess{i:04d}": (rng.random(shape) < 0.3).astype(np.uint8)
               for i in range(s)}

    # Side A: resident. Creates ship each board once; the timed bracket
    # is pure resident stepping (handle-based submits, in-place slab
    # dispatches, zero result traffic).
    daemon = ServingDaemon(policy)
    for sid, b in boards0.items():
        daemon.create_session(sid, b)
    res_tickets = []
    t0 = time.perf_counter()
    for _ in range(rounds):
        for sid in boards0:
            res_tickets.append(daemon.submit_session(sid, steps_per_round))
        daemon.pump(drain=True)
    res_wall = time.perf_counter() - t0
    res_done = sum(1 for t in res_tickets if t.state == DONE)
    rs = daemon.summary()

    bad = 0
    for sid, b in boards0.items():
        ref = b.copy()
        for _ in range(rounds * steps_per_round):
            ref = life_step_numpy(ref)
        if not np.array_equal(daemon.snapshot_session(sid), ref):
            bad += 1

    # Side B: ship-every-call. The same boards advance the same total
    # steps, but each round round-trips every board through the ticket
    # path (host -> queue -> stacked dispatch -> host), chained so round
    # k+1 ships what round k fetched — the honest no-pool workflow.
    ship = ServingDaemon(policy)
    cur = {sid: b.copy() for sid, b in boards0.items()}
    ship_done = 0
    t0 = time.perf_counter()
    for _ in range(rounds):
        tks = {sid: ship.submit(cur[sid], steps_per_round) for sid in cur}
        ship.pump(drain=True)
        for sid, t in tks.items():
            if t.state == DONE:
                ship_done += 1
                cur[sid] = np.asarray(t.result)
    ship_wall = time.perf_counter() - t0

    res_rate = round(res_done / res_wall, 2) if res_wall > 0 else None
    ship_rate = round(ship_done / ship_wall, 2) if ship_wall > 0 else None
    fields = {
        "resident": "pool",
        "session_count": s,
        "session_rounds": rounds,
        "session_steps_per_round": steps_per_round,
        "session_requests": res_done,
        "session_requests_per_sec": res_rate,
        "ship_requests_per_sec": ship_rate,
        "session_vs_ship": (round(res_rate / ship_rate, 2)
                            if res_rate and ship_rate else None),
        "session_p50_latency_s": rs["p50_latency_s"],
        "session_p99_latency_s": rs["p99_latency_s"],
        "session_dispatches": rs["batches"],
        "pool_sessions": rs["pool_sessions"],
        "pool_hits": rs["pool_hits"],
        "pool_misses": rs["pool_misses"],
        "pool_evictions": rs["pool_evictions"],
        "pool_spills": rs["pool_spills"],
        "pool_compactions": rs["pool_compactions"],
        "session_parity": bad == 0,
    }
    if bad:
        fields["session_error"] = (
            f"snapshot parity failed on {bad} of {s} sessions")
    return fields


def _sparse_seed_board(edge: int, tile: int) -> np.ndarray:
    """The sparse A/B's mostly-dead Life board: blinkers parked in tile
    INTERIORS on a coarse deterministic grid (each keeps its own tile
    active and — via the border-band check — none of its neighbours)
    plus one glider crossing tile boundaries (the pattern that forces
    honest wake-up propagation). Active tile fraction stays well under
    5% at the default 2048/64 geometry."""
    board = np.zeros((edge, edge), dtype=np.uint8)
    ty = edge // tile
    stride = max(3, ty // 3)
    placed = 0
    for j in range(1, ty, stride):
        for i in range(1, ty, stride):
            if placed >= 10:
                break
            cy, cx = j * tile + tile // 2, i * tile + tile // 2
            board[cy, cx - 1:cx + 2] = 1  # horizontal blinker
            placed += 1
    # Glider aimed across tile edges, offset so it never collides with
    # the blinker grid (placed just off the (0, 0) tile's corner).
    gy, gx = tile - 2, tile - 2
    glider = np.array([[0, 1, 0], [0, 0, 1], [1, 1, 1]], dtype=np.uint8)
    board[gy:gy + 3, gx:gx + 3] = glider
    return board


def _sparse_ab_phase(n_steps: int, edge: int, tile: int) -> dict:
    """The sparse active-tile A/B (``--sparse-ab K``): K Life steps of a
    mostly-dead ``edge``² board through ``stencils.sparse.
    ActiveTileEngine`` versus the dense jitted roll engine. Honesty
    discipline matches the headline: the dense engine is parity-gated
    against the NumPy oracle first (8 steps), the sparse final board
    must be bit-identical to the dense final board over the FULL run,
    and both rates are chain-differenced — two run lengths (K and 2K)
    from fresh state, so compile/warm cost cancels on each side. The
    ratio ``sparse_vs_dense`` is measured in one process, so machine
    noise cancels like ``vs_cellpacked``."""
    from mpi_and_open_mp_tpu import stencils
    from mpi_and_open_mp_tpu.stencils.sparse import ActiveTileEngine
    from mpi_and_open_mp_tpu.utils.timing import anchor_sync

    spec = stencils.get("life")
    board = _sparse_seed_board(edge, tile)
    fields = {"sparse_board": edge, "sparse_steps": n_steps,
              "sparse_tile": tile}

    # Oracle gate on the dense side (the sparse side then gates against
    # dense over the full run — transitively oracle-exact).
    got8 = np.asarray(stencils.run_roll(spec, board, 8))
    ref8 = stencils.oracle_run(spec, board, 8)
    if not np.array_equal(got8, ref8):
        fields["sparse_error"] = "dense roll engine failed oracle parity"
        return fields

    def dense_timed(n):
        t0 = time.perf_counter()
        anchor_sync(stencils.run_roll(spec, board, n), fetch_all=True)
        return time.perf_counter() - t0

    # Warm (n is a runtime scalar: one compile covers both lengths).
    anchor_sync(stencils.run_roll(spec, board, n_steps), fetch_all=True)
    d1 = min(dense_timed(n_steps) for _ in range(2))
    d2 = min(dense_timed(2 * n_steps) for _ in range(2))
    dense_per_step = (d2 - d1) / n_steps if d2 > d1 else d1 / n_steps

    def sparse_run(n):
        eng = ActiveTileEngine(spec, board, tile=tile)
        t0 = time.perf_counter()
        out = eng.step(n)
        dt = time.perf_counter() - t0
        return eng, out, dt

    eng1, _, s1 = sparse_run(n_steps)
    eng2, sparse_final, s2 = sparse_run(2 * n_steps)
    sparse_per_step = (s2 - s1) / n_steps if s2 > s1 else s1 / n_steps

    dense_final = np.asarray(stencils.run_roll(spec, board, 2 * n_steps))
    parity = np.array_equal(sparse_final, dense_final)
    fields.update({
        "sparse_parity": parity,
        "sparse_cups": round(edge * edge / sparse_per_step, 1),
        "dense_cups": round(edge * edge / dense_per_step, 1),
        "sparse_vs_dense": round(dense_per_step / sparse_per_step, 2),
        "active_frac": round(eng2.mean_active_frac, 6),
        "sparse_engine": eng2.engine_stamp,
        "sparse_counters": eng2.counters(),
    })
    if not parity:
        fields["sparse_error"] = (
            "sparse final board diverged from the dense engine")
    return fields


def _sharded_ab_phase(args, workload: str) -> dict:
    """The SHARDED HALO A/B (``--sharded-ab K``): K torus steps of a
    ``--sharded-board``² board through the plan-scheduled sharded engine
    (``stencils.engine``), overlap schedule versus forced-sequential
    baseline over the SAME mesh. Honesty discipline matches the sparse
    A/B: the overlap leg is oracle-parity-gated first (8 steps), the seq
    leg must match it bit-exactly, both rates are chain-differenced (K
    and 2K from warm executables, min-of-2), and the two full-run final
    boards must be BIT-identical — the overlap split computes every cell
    with the same arithmetic, only the iteration space is partitioned.
    The exposed-vs-hidden accounting rides a separate exchange-only
    microbench: ``transfer_s`` prices the ghost ppermutes alone per
    round, ``exposed_s`` is the remainder the overlap failed to hide
    behind interior compute, and their ratio is the overlap efficiency
    (``halo.ab`` trace event + the same fields on the line). The
    ``sharded_halo`` stamp is what the overlap leg actually resolved to
    (``overlap:*``, or ``seq:*`` when the ``MOMP_HALO_OVERLAP=0`` kill
    switch or a degenerate geometry downgraded it — the ledger keys on
    it and the sentinel treats that downgrade as a failure)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding

    from mpi_and_open_mp_tpu import stencils
    from mpi_and_open_mp_tpu.obs import trace as obs_trace
    from mpi_and_open_mp_tpu.parallel import haloplan, mesh as mesh_lib
    from mpi_and_open_mp_tpu.stencils import engine as stencil_engine
    from mpi_and_open_mp_tpu.utils.timing import anchor_sync

    n_steps, edge = args.sharded_ab, args.sharded_board
    spec = stencils.get(workload)
    fields = {"sharded_ab_board": edge, "sharded_ab_steps": n_steps}
    if jax.device_count() < 2:
        fields["sharded_ab_error"] = (
            "needs >= 2 devices (the halo exchange engages from 2 "
            "shards); CI runs it under the 8-virtual-device CPU mesh")
        return fields
    mesh = mesh_lib.make_mesh_1d()  # every device on y: row layout
    py = mesh.shape.get("y", 1)
    if edge % py:
        fields["sharded_ab_error"] = (
            f"--sharded-board {edge} does not divide the {py}-way mesh")
        return fields

    rng = np.random.default_rng(46)
    board = spec.init(rng, (edge, edge))

    # Oracle gate on the overlap leg (8 steps, emits the halo.overlap
    # span), then the seq leg (halo.seq span) must match it bit-exactly
    # — transitively oracle-exact. Both schedule stamps ride the line.
    got8 = np.asarray(stencil_engine.run_sharded(
        spec, board, 8, mesh=mesh, layout="row"))
    plan_ovl = stencil_engine.run_sharded.last_plan
    fields["sharded_halo"] = plan_ovl.engine
    if not stencils.parity_ok(spec, got8,
                              stencils.oracle_run(spec, board, 8)):
        fields["sharded_ab_error"] = (
            "overlap schedule failed oracle parity")
        return fields
    seq8 = np.asarray(stencil_engine.run_sharded(
        spec, board, 8, mesh=mesh, layout="row", overlap=False))
    fields["sharded_seq_halo"] = stencil_engine.run_sharded.last_plan.engine
    if not np.array_equal(got8, seq8):
        fields["sharded_ab_error"] = (
            "overlap and sequential schedules diverged at 8 steps")
        return fields

    run_ovl, _ = stencil_engine.make_sharded_runner(
        spec, mesh, "row", (edge, edge))
    run_seq, _ = stencil_engine.make_sharded_runner(
        spec, mesh, "row", (edge, edge), overlap=False)
    pspec = stencil_engine._sharded_pspec("row", spec.channels)
    dev_board = jax.device_put(jnp.asarray(board, spec.dtype),
                               NamedSharding(mesh, pspec))

    def timed(run, n):
        t0 = time.perf_counter()
        anchor_sync(run(dev_board, n), fetch_all=True)
        return time.perf_counter() - t0

    def per_step(run):
        # run() jit-caches per STATIC n: warm both lengths outside the
        # brackets (the 2K warm-up doubles as the full-run final), then
        # chain-difference so the per-dispatch overhead cancels.
        anchor_sync(run(dev_board, n_steps), fetch_all=True)
        final = run(dev_board, 2 * n_steps)
        anchor_sync(final, fetch_all=True)
        t1 = min(timed(run, n_steps) for _ in range(2))
        t2 = min(timed(run, 2 * n_steps) for _ in range(2))
        return ((t2 - t1) / n_steps if t2 > t1 else t1 / n_steps,
                np.asarray(final), t2 > t1)

    ovl_step, ovl_final, ovl_diff = per_step(run_ovl)
    seq_step, seq_final, seq_diff = per_step(run_seq)
    parity = np.array_equal(ovl_final, seq_final)
    cells = edge * edge
    fields.update({
        "sharded_ab_parity": parity,
        "sharded_overlap_cups": round(cells / ovl_step, 1),
        "sharded_seq_cups": round(cells / seq_step, 1),
        "vs_sequential": round(seq_step / ovl_step, 3),
        "sharded_ab_is_differenced": ovl_diff and seq_diff,
    })
    if not parity:
        fields["sharded_ab_error"] = (
            "overlap final board diverged from the sequential schedule")
        return fields

    # Exchange-only microbench: the ghost ppermutes with no stencil
    # behind them, same chained-differencing bracket. The concat keeps
    # the collectives live in the loop (an unused ppermute is dead code
    # XLA may elide); values shift per round, which is irrelevant — this
    # is a pure timing probe on the production ghost shapes.
    depth = plan_ovl.depth

    def exch(block):
        top, bot = haloplan.ghosts_y(block, depth)
        return jnp.concatenate(
            [bot, block[..., depth:-depth, :], top], axis=-2)

    smapped = mesh_lib.shard_map(exch, mesh=mesh, in_specs=pspec,
                                 out_specs=pspec, check_vma=False)

    @jax.jit
    def exch_n(b, n):
        return lax.fori_loop(0, n, lambda _, c: smapped(c), b)

    def exch_timed(n):
        t0 = time.perf_counter()
        anchor_sync(exch_n(dev_board, jnp.int32(n)), fetch_all=True)
        return time.perf_counter() - t0

    anchor_sync(exch_n(dev_board, jnp.int32(n_steps)), fetch_all=True)
    x1 = min(exch_timed(n_steps) for _ in range(2))
    x2 = min(exch_timed(2 * n_steps) for _ in range(2))
    transfer_s = (x2 - x1) / n_steps if x2 > x1 else x1 / n_steps

    # hidden = the seconds the overlap actually saved per round;
    # exposed = the transfer remainder still on the critical path
    # (clamped to the transfer itself: an overlap leg slower than seq
    # exposed the whole exchange, not more than it).
    hidden_s = max(0.0, seq_step - ovl_step)
    exposed_s = min(transfer_s, max(0.0, transfer_s - hidden_s))
    efficiency = (min(1.0, hidden_s / transfer_s)
                  if transfer_s > 0 else 0.0)
    fields.update({
        "sharded_transfer_s": round(transfer_s, 8),
        "sharded_exposed_s": round(exposed_s, 8),
        "sharded_overlap_efficiency": round(efficiency, 4),
    })
    obs_trace.event("halo.ab", workload=spec.name, board=edge,
                    halo=plan_ovl.engine,
                    transfer_s=round(transfer_s, 8),
                    exposed_s=round(exposed_s, 8),
                    efficiency=round(efficiency, 4),
                    vs_sequential=fields["vs_sequential"])

    # PARTITIONED-BOUNDARY sweep (PR 18): the same spec through every
    # layout the transport supports — row, col (x-mirror), cart (two-
    # phase corners) — with the boundary split one step per sub-
    # exchange (fuse=2, boundary=1, the ``:pb1`` stamps). Each leg is
    # parity-gated against the 8-step oracle and required bit-identical
    # to its own forced-sequential coupled twin: partitioning moves
    # signalling, never arithmetic. The row leg also gets a chain-
    # differenced rate against the coupled fuse=2 schedule so the split
    # is priced, not just proven.
    fuse, bs = 2, 1
    engines: dict = {}
    boundary_ok = True
    for lay in ("row", "col", "cart"):
        bmesh = (mesh if lay == "row"
                 else mesh_lib.make_mesh_1d(axis=mesh_lib.AXIS_X)
                 if lay == "col" else mesh_lib.make_mesh_2d())
        bpy, bpx = stencil_engine.mesh_axes_for(lay, bmesh)
        if edge % bpy or edge % bpx:
            engines[lay] = f"skipped: {edge} % ({bpy},{bpx})"
            continue
        got = np.asarray(stencil_engine.run_sharded(
            spec, board, 8, mesh=bmesh, layout=lay, fuse_steps=fuse,
            boundary_steps=bs))
        engines[lay] = stencil_engine.run_sharded.last_plan.engine
        seq = np.asarray(stencil_engine.run_sharded(
            spec, board, 8, mesh=bmesh, layout=lay, fuse_steps=fuse,
            overlap=False))
        if not (np.array_equal(got, seq) and stencils.parity_ok(
                spec, got, stencils.oracle_run(spec, board, 8))):
            boundary_ok = False
            engines[lay] += " PARITY-FAIL"
    fields.update({
        "sharded_boundary_fuse": fuse,
        "sharded_boundary_depth": bs,
        "sharded_boundary_engines": engines,
        "sharded_boundary_parity": boundary_ok,
    })
    if not boundary_ok:
        fields["sharded_ab_error"] = (
            "partitioned-boundary sweep diverged: "
            + json.dumps(engines))
        return fields

    run_pb, _ = stencil_engine.make_sharded_runner(
        spec, mesh, "row", (edge, edge), fuse_steps=fuse,
        boundary_steps=bs)
    run_cpl, _ = stencil_engine.make_sharded_runner(
        spec, mesh, "row", (edge, edge), fuse_steps=fuse)
    pb_step, pb_final, _ = per_step(run_pb)
    cpl_step, cpl_final, _ = per_step(run_cpl)
    fields.update({
        "sharded_boundary_cups": round(cells / pb_step, 1),
        "sharded_boundary_vs_coupled": round(cpl_step / pb_step, 3),
    })
    if not np.array_equal(pb_final, cpl_final):
        fields["sharded_ab_error"] = (
            "partitioned-boundary full run diverged from the coupled "
            "schedule")
    return fields


def _ring_ab_phase(args) -> dict:
    """``_ring_ab_measure`` behind a hop-span opt-out. With a trace sink
    live, ``ring_attention`` reroutes to the hop-by-hop telemetry
    dispatch (``trace.hop_spans_active``): p-1 host-anchored hops — a
    host RTT per hop that would swamp the A/B, and a forward with no
    grad path (the per-hop re-plan differentiates through a bare
    ``pallas_call``, which JVP rejects). The A/B must price the
    production fused dispatch, so the phase pins ``MOMP_TRACE_HOPS=0``
    for its duration; whole-call spans and the ``ring.ab`` event still
    land in the trace."""
    prev = os.environ.get("MOMP_TRACE_HOPS")
    os.environ["MOMP_TRACE_HOPS"] = "0"
    try:
        return _ring_ab_measure(args)
    finally:
        if prev is None:
            os.environ.pop("MOMP_TRACE_HOPS", None)
        else:
            os.environ["MOMP_TRACE_HOPS"] = prev


def _ring_ab_measure(args) -> dict:
    """The RING-ATTENTION HOP-PREFETCH A/B (``--ring-ab R``): R causal
    ring-attention trips over the full device mesh with the double-slot
    K/V hop prefetch engaged (``context._RING_PREFETCH``, ``:pf``
    stamps) versus the single-slot schedule it deepens, on the SAME
    operands. Honesty discipline mirrors ``_sharded_ab_phase``: the
    prefetch leg is dense-oracle parity-gated first, the single-slot
    leg must match it bit-exactly (same folds in the same order — only
    the rotation issue points move), gradients are cross-checked the
    same way, and both rates are chain-differenced (R and 2R calls from
    warm executables, min-of-2). The exposed-vs-hidden accounting rides
    a rotation-only microbench: ``ring_transfer_s`` prices the p-1 K/V
    ppermutes of one trip with no kernel behind them, the single-slot
    baseline is charged the whole transfer (it is the baseline the
    hiding is measured against, exactly like the sharded A/B's forced-
    sequential leg), and ``ring_exposed_s`` is the remainder the
    prefetch failed to hide. The ``ring_hop_engine``/``_bwd`` stamps
    are what the prefetch leg actually dispatched (``…:pf``, or the
    bare kernel stamp when ``MOMP_RING_PREFETCH=0`` downgraded it —
    the sentinel fails that rerun as a provenance downgrade)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from mpi_and_open_mp_tpu.obs import trace as obs_trace
    from mpi_and_open_mp_tpu.parallel import context, mesh as mesh_lib
    from mpi_and_open_mp_tpu.parallel.halo import ring_perm
    from mpi_and_open_mp_tpu.utils.timing import anchor_sync

    n_calls = args.ring_ab
    p = jax.device_count()
    fields = {"ring_ab_calls": n_calls, "ring_ab_devices": p}
    if p < 3:
        fields["ring_ab_error"] = (
            "needs >= 3 devices (a 2-device ring has a single transfer "
            "— nothing to pipeline deeper); CI runs it under the "
            "8-virtual-device CPU mesh")
        return fields

    # 128-token shards at an MXU-width head dim: the one hop shape the
    # interpret-mode kernel takes (block == n_local), so the SAME phase
    # exercises the real hopflash prefetch on the CPU CI mesh
    # (MOMP_PALLAS_INTERPRET=1) and on chip.
    h, d, nl = 4, 128, 128
    n = nl * p
    fields["ring_ab_shape"] = [h, n, d]
    axis = context.AXIS_SP
    mesh = mesh_lib.make_mesh_1d(axis=axis)

    stamp = context.ring_hop_engine_for(
        jax.ShapeDtypeStruct((h, n, d), jnp.float32),
        jax.ShapeDtypeStruct((h, n, d), jnp.float32),
        jax.ShapeDtypeStruct((h, n, d), jnp.float32), p=p, causal=True)
    fields["ring_hop_engine"] = stamp
    fields["ring_hop_engine_bwd"] = context.ring_hop_bwd_engine_for(
        jax.ShapeDtypeStruct((h, n, d), jnp.float32),
        jax.ShapeDtypeStruct((h, n, d), jnp.float32),
        jax.ShapeDtypeStruct((h, n, d), jnp.float32), p=p, causal=True)
    if not stamp.endswith(":pf"):
        fields["ring_ab_error"] = (
            f"hop prefetch not engaged (stamp {stamp}): the A/B needs "
            "the Pallas hop engine (TPU backend, or "
            "MOMP_PALLAS_INTERPRET=1 with 128-token shards) and "
            "MOMP_RING_PREFETCH unset")
        return fields

    rng = np.random.default_rng(48)
    q, k, v = (jnp.asarray(rng.standard_normal((h, n, d)), jnp.float32)
               for _ in range(3))

    def ring(q_, k_, v_):
        return context.ring_attention(q_, k_, v_, mesh=mesh, axis=axis,
                                      causal=True)

    @jax.jit
    def chain(q_, k_, v_, r):
        # Output feeds the next call's queries so the chain can't be
        # elided; K/V are re-rotated around the ring every link.
        return lax.fori_loop(0, r, lambda _, c: ring(c, k_, v_), q_)

    def grads(q_, k_, v_):
        def loss(a, b, c):
            return (ring(a, b, c).astype(jnp.float32) ** 2).sum()

        return jax.grad(loss, argnums=(0, 1, 2))(q_, k_, v_)

    def timed(call):
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            anchor_sync(call(), fetch_all=True)
            best = min(best, time.perf_counter() - t0)
        return best

    def leg():
        fwd = np.asarray(ring(q, k, v))
        g = [np.asarray(x) for x in grads(q, k, v)]
        anchor_sync(chain(q, k, v, jnp.int32(n_calls)), fetch_all=True)
        anchor_sync(chain(q, k, v, jnp.int32(2 * n_calls)),
                    fetch_all=True)
        t1 = timed(lambda: chain(q, k, v, jnp.int32(n_calls)))
        t2 = timed(lambda: chain(q, k, v, jnp.int32(2 * n_calls)))
        per_call = (t2 - t1) / n_calls if t2 > t1 else t1 / n_calls
        return fwd, g, per_call, t2 > t1

    # Parity gate BEFORE any recorded timing: the prefetch leg against
    # the dense oracle, then the single-slot leg bit-identical to it
    # (forward) and matching on gradients. The kill switch is a
    # trace-time flag, so each flip clears the jit caches (same
    # discipline as the MOMP_RING_HOP tests).
    pf_fwd, pf_g, pf_call, pf_diff = leg()
    want = np.asarray(context.attention_reference(q, k, v, causal=True))
    if not np.allclose(pf_fwd, want, rtol=1e-4, atol=1e-4):
        fields["ring_ab_error"] = "prefetch leg failed oracle parity"
        return fields
    prev_pf = context._RING_PREFETCH
    try:
        context._RING_PREFETCH = False
        jax.clear_caches()
        fields["ring_nopf_engine"] = context.ring_hop_engine_for(
            jax.ShapeDtypeStruct((h, n, d), jnp.float32),
            jax.ShapeDtypeStruct((h, n, d), jnp.float32),
            jax.ShapeDtypeStruct((h, n, d), jnp.float32), p=p,
            causal=True)
        nopf_fwd, nopf_g, nopf_call, nopf_diff = leg()
    finally:
        context._RING_PREFETCH = prev_pf
        jax.clear_caches()
    parity = np.array_equal(pf_fwd, nopf_fwd)
    grad_parity = all(
        np.allclose(a, b, rtol=1e-6, atol=1e-6)
        for a, b in zip(pf_g, nopf_g))
    flops = 2 * h * n * n * d  # QK^T + PV, causal half
    fields.update({
        "ring_ab_parity": parity,
        "ring_ab_grad_parity": grad_parity,
        "ring_prefetch_sec": round(pf_call, 6),
        "ring_prefetch_tflops": round(flops / pf_call / 1e12, 4),
        "ring_nopf_sec": round(nopf_call, 6),
        "ring_nopf_tflops": round(flops / nopf_call / 1e12, 4),
        "ring_vs_nopf": round(nopf_call / pf_call, 3),
        "ring_ab_is_differenced": pf_diff and nopf_diff,
    })
    if not parity:
        fields["ring_ab_error"] = (
            "prefetch forward diverged from the single-slot schedule")
        return fields
    if not grad_parity:
        fields["ring_ab_error"] = (
            "prefetch gradients diverged from the single-slot schedule")
        return fields

    # Rotation-only microbench: the p-1 K/V ppermutes of one ring trip
    # with no kernel behind them, same chained-differencing bracket.
    # The tuple carry keeps the collectives live in the loop.
    spec = context._seq_spec(axis)
    sharding = jax.sharding.NamedSharding(mesh, spec)
    kd = jax.device_put(k, sharding)
    vd = jax.device_put(v, sharding)

    def rot(kb, vb):
        perm = ring_perm(p, 1)
        return (lax.ppermute(kb, axis, perm),
                lax.ppermute(vb, axis, perm))

    smapped = mesh_lib.shard_map(rot, mesh=mesh, in_specs=(spec, spec),
                                 out_specs=(spec, spec), check_vma=False)

    @jax.jit
    def rot_n(kb, vb, r):
        return lax.fori_loop(0, r, lambda _, c: smapped(*c), (kb, vb))

    def rot_timed(r):
        t0 = time.perf_counter()
        anchor_sync(rot_n(kd, vd, jnp.int32(r)), fetch_all=True)
        return time.perf_counter() - t0

    hops = (p - 1) * n_calls
    anchor_sync(rot_n(kd, vd, jnp.int32(hops)), fetch_all=True)
    x1 = min(rot_timed(hops) for _ in range(2))
    x2 = min(rot_timed(2 * hops) for _ in range(2))
    per_rot = (x2 - x1) / hops if x2 > x1 else x1 / hops
    transfer_s = per_rot * (p - 1)

    # hidden = the seconds the deeper pipeline actually saved per trip;
    # exposed = the transfer remainder still on the critical path
    # (clamped to the transfer itself). The single-slot baseline is
    # charged the full transfer by the same accounting the sharded A/B
    # charges its forced-sequential leg.
    hidden_s = max(0.0, nopf_call - pf_call)
    exposed_s = min(transfer_s, max(0.0, transfer_s - hidden_s))
    efficiency = (min(1.0, hidden_s / transfer_s)
                  if transfer_s > 0 else 0.0)
    fields.update({
        "ring_transfer_s": round(transfer_s, 8),
        "ring_exposed_s": round(exposed_s, 8),
        "ring_exposed_nopf_s": round(transfer_s, 8),
        "ring_prefetch_efficiency": round(efficiency, 4),
    })
    obs_trace.event("ring.ab", devices=p, shape=[h, n, d],
                    engine=stamp,
                    transfer_s=round(transfer_s, 8),
                    exposed_s=round(exposed_s, 8),
                    efficiency=round(efficiency, 4),
                    vs_nopf=fields["ring_vs_nopf"])
    return fields


def _sparse_sharded_ab_phase(args) -> dict:
    """The SPARSE x SHARDED A/B (``--sparse-sharded-ab K``): K Life
    steps of the mostly-dead ``--sparse-board``² seed board through
    ``stencils.sparse_sharded.SparseShardedEngine`` on the row mesh,
    versus (a) the dense sharded runner on the SAME mesh and (b) the
    single-device ``ActiveTileEngine`` — the composition this engine
    exists for, measured against both parents. Honesty discipline is
    the union of the parents': the sparse-sharded leg is oracle-parity-
    gated first (8 steps), its full-run final board must be
    BIT-identical to the dense sharded schedule's, every leg is
    chain-differenced (K and 2K) from warm state with min-of-2
    brackets, and fresh engines open every host-driven bracket (mask
    state is the engine — reuse would grade a warmer mask). The
    ``sparse_sharded_engine`` stamp is what the run resolved to
    (``sparse-sharded:row:t<tile>``, or ``dense:*`` when the crossover
    or the ``MOMP_SPARSE_SHARDED=0`` kill switch forced dense rounds —
    the ledger keys on it and the sentinel fails the downgrade), and
    the exchange_rounds/exchange_skips counters ride the line so a
    recorded win shows how many rounds shipped no ghost payload."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from mpi_and_open_mp_tpu import stencils
    from mpi_and_open_mp_tpu.parallel import mesh as mesh_lib
    from mpi_and_open_mp_tpu.stencils import engine as stencil_engine
    from mpi_and_open_mp_tpu.stencils.sparse import ActiveTileEngine
    from mpi_and_open_mp_tpu.stencils.sparse_sharded import (
        SparseShardedEngine)
    from mpi_and_open_mp_tpu.utils.timing import anchor_sync

    n_steps, edge, tile = (args.sparse_sharded_ab, args.sparse_board,
                           args.sparse_tile)
    spec = stencils.get("life")
    fields = {"sparse_sharded_board": edge,
              "sparse_sharded_steps": n_steps,
              "sparse_sharded_tile": tile}
    if jax.device_count() < 2:
        fields["sparse_sharded_error"] = (
            "needs >= 2 devices (cross-shard activation engages from 2 "
            "shards); CI runs it under the 8-virtual-device CPU mesh")
        return fields
    mesh = mesh_lib.make_mesh_1d()  # every device on y: row layout
    py = mesh.shape.get("y", 1)
    if edge % py or (edge // py) % tile:
        fields["sparse_sharded_error"] = (
            f"--sparse-board {edge} does not tile the {py}-way mesh "
            f"at --sparse-tile {tile}")
        return fields
    board = _sparse_seed_board(edge, tile)

    def fresh():
        return SparseShardedEngine(spec, board, mesh=mesh, layout="row",
                                   tile=tile)

    # Oracle gate on the sparse-sharded leg (8 steps), before any
    # number is recorded.
    eng8 = fresh()
    eng8.step(8)
    fields["sparse_sharded_engine"] = eng8.engine_stamp
    if not np.array_equal(eng8.snapshot(),
                          stencils.oracle_run(spec, board, 8)):
        fields["sparse_sharded_error"] = (
            "sparse-sharded engine failed oracle parity")
        return fields

    # Dense sharded leg: the same mesh, the same schedule family the
    # sparse rounds gather from — warm both static-n programs, then
    # chain-difference with min-of-2.
    run_dense, _plan = stencil_engine.make_sharded_runner(
        spec, mesh, "row", (edge, edge))
    dev_board = jax.device_put(
        jnp.asarray(board, spec.dtype),
        NamedSharding(mesh, stencil_engine.sharded_pspec(
            "row", spec.channels)))

    def dense_timed(n):
        t0 = time.perf_counter()
        anchor_sync(run_dense(dev_board, n), fetch_all=True)
        return time.perf_counter() - t0

    anchor_sync(run_dense(dev_board, n_steps), fetch_all=True)
    dense_final = run_dense(dev_board, 2 * n_steps)
    anchor_sync(dense_final, fetch_all=True)
    dense_final = np.asarray(dense_final)
    d1 = min(dense_timed(n_steps) for _ in range(2))
    d2 = min(dense_timed(2 * n_steps) for _ in range(2))
    dense_step = (d2 - d1) / n_steps if d2 > d1 else d1 / n_steps

    # Sparse-sharded leg: fresh engine per bracket; one warm run first
    # so the kcap-ladder programs are compiled outside the brackets.
    def sparse_sharded_run(n):
        eng = fresh()
        t0 = time.perf_counter()
        eng.step(n)
        anchor_sync(eng.board, fetch_all=True)
        return eng, time.perf_counter() - t0

    # Warm the FULL 2K trajectory: the rung ladder is trajectory-
    # dependent, and a rung first reached between K and 2K would
    # otherwise compile inside the 2K bracket only — inflating the
    # differenced per-step cost instead of cancelling.
    sparse_sharded_run(2 * n_steps)
    s1 = min(sparse_sharded_run(n_steps)[1] for _ in range(2))
    eng_final, t2a = sparse_sharded_run(2 * n_steps)
    s2 = min(t2a, sparse_sharded_run(2 * n_steps)[1])
    sparse_step = (s2 - s1) / n_steps if s2 > s1 else s1 / n_steps

    # Single-device sparse leg (PR 13's engine): the other parent.
    def single_run(n):
        eng = ActiveTileEngine(spec, board, tile=tile)
        t0 = time.perf_counter()
        eng.step(n)
        return eng, time.perf_counter() - t0

    single_run(n_steps)  # warm
    g1 = min(single_run(n_steps)[1] for _ in range(2))
    g2 = min(single_run(2 * n_steps)[1] for _ in range(2))
    single_step = (g2 - g1) / n_steps if g2 > g1 else g1 / n_steps

    bitident = np.array_equal(eng_final.snapshot(), dense_final)
    cells = edge * edge
    fields.update({
        "sparse_sharded_bitident": bitident,
        "sparse_sharded_cups": round(cells / sparse_step, 1),
        "sparse_sharded_dense_cups": round(cells / dense_step, 1),
        "sparse_sharded_vs_dense": round(dense_step / sparse_step, 2),
        "sparse_sharded_single_cups": round(cells / single_step, 1),
        "sparse_sharded_vs_single": round(single_step / sparse_step, 2),
        "active_frac": round(eng_final.mean_active_frac, 6),
        "sparse_sharded_engine": eng_final.engine_stamp,
        "sparse_sharded_counters": eng_final.counters(),
    })
    if not bitident:
        fields["sparse_sharded_error"] = (
            "sparse-sharded final board diverged from the dense "
            "sharded schedule")
    return fields


def _radius_ab_phase(args) -> dict:
    """The WIDE-RADIUS ENGINE-FAMILY A/B (``--radius-ab K``): K steps
    of an ephemeral lenia spec at every ``--radius-list`` radius on a
    ``--radius-board``² float32 board, racing the three aggregation
    families (``stencils.engine.run_family``) — the O(r²·n) offset
    walk, the rank-k separable row×col pass, the cached-rfft2 circular
    convolution — wherever each family's legality gate admits the spec
    and the ``MOMP_ENGINE_FAMILY`` pin allows it. Honesty discipline is
    the headline's: every (radius, family) leg is oracle-parity-gated
    first (8 steps, at the family's gate-owned tolerance —
    ``parity_tol_for``), then warmed and chain-differenced (K vs 2K,
    min-of-2 brackets; ``n`` is a runtime scalar so one executable
    serves both). The table is the artifact — ``vs_offset`` per row is
    the measured crossover — and the scalars the sentinel watches
    (``radius_ab_*_cups``, ``radius_ab_vs_offset_best``) plus the
    ``engine_family`` stamp (the winner at the widest radius; the
    ledger keys on it, so a kill-switch run stamps ``offset`` and the
    sentinel fails the downgrade) ride the line."""
    from mpi_and_open_mp_tpu import stencils
    from mpi_and_open_mp_tpu.stencils import engine as stencil_engine
    from mpi_and_open_mp_tpu.utils.timing import anchor_sync

    n_steps, edge = args.radius_ab, args.radius_board
    radii = sorted({int(r) for r in str(args.radius_list).split(",")
                    if r.strip()})
    fields = {"radius_ab_board": edge, "radius_ab_steps": n_steps,
              "radius_ab_radii": radii}
    pin = stencil_engine.family_pinned()
    if pin is not None:
        fields["radius_ab_family_pin"] = pin
    rows = []
    rng = np.random.default_rng(46)
    cells = edge * edge
    best_at_widest = None  # (step_sec, family) at the widest radius
    for radius in radii:
        spec = stencils.make_lenia(radius, f"lenia_ab_r{radius}")
        board = spec.init(rng, (edge, edge))
        ref8 = stencils.oracle_run(spec, board, 8)
        steps_by_family = {}
        for fam in stencil_engine.ENGINE_FAMILIES:
            if not stencil_engine.family_allowed(fam):
                continue
            if fam == "sep" and not stencil_engine.separable_supported(
                    spec):
                continue
            if fam == "fft" and not stencil_engine.fft_supported(spec):
                continue
            row = {"radius": radius, "family": fam}
            rows.append(row)
            # Oracle gate at the family's gate-owned tolerance, before
            # any number is recorded for this leg.
            got = np.asarray(stencil_engine.run_family(
                spec, board, 8, fam))
            tol = stencil_engine.parity_tol_for(fam)
            if not stencils.parity_ok(spec, got, ref8, **tol):
                row["parity"] = False
                continue
            row["parity"] = True

            def timed(n, fam=fam):
                t0 = time.perf_counter()
                anchor_sync(stencil_engine.run_family(
                    spec, board, n, fam), fetch_all=True)
                return time.perf_counter() - t0

            timed(2 * n_steps)  # warm (n is runtime: one executable)
            t1 = min(timed(n_steps) for _ in range(2))
            t2 = min(timed(2 * n_steps) for _ in range(2))
            diff = t2 > t1
            step = (t2 - t1) / n_steps if diff else t1 / n_steps
            steps_by_family[fam] = step
            row.update({"cups": round(cells / step, 1),
                        "is_differenced": diff})
        off = steps_by_family.get("offset")
        if off is not None:
            for row in rows:
                if (row["radius"] == radius and row["family"] != "offset"
                        and row["family"] in steps_by_family):
                    row["vs_offset"] = round(
                        off / steps_by_family[row["family"]], 2)
        if steps_by_family:
            step, fam = min((s, f) for f, s in steps_by_family.items())
            best_at_widest = (step, fam)
            for f, s in steps_by_family.items():
                fields[f"radius_ab_{f}_cups"] = round(cells / s, 1)
    fields["radius_ab_table"] = rows
    # The sentinel's headline watch scalar: the best measured speedup of
    # a wide-radius family over the offset walk at radius >= 8. Absent
    # (not 0) when no such leg ran — e.g. MOMP_ENGINE_FAMILY=offset —
    # so the provenance downgrade, not a fake regression, is the signal.
    vs = [row["vs_offset"] for row in rows
          if row.get("vs_offset") is not None and row["radius"] >= 8]
    if vs:
        fields["radius_ab_vs_offset_best"] = max(vs)
    crossed = [row["radius"] for row in rows
               if row.get("vs_offset", 0) >= 1.0]
    fields["radius_ab_crossover_radius"] = (
        min(crossed) if crossed else None)
    if best_at_widest is not None:
        fields["engine_family"] = best_at_widest[1]
    return fields


def _autotune_phase(args, workload: str) -> dict:
    """The AUTOTUNE phase (``--autotune K``): install any persisted
    plans from the store first (validated + parity-gated), then either
    reuse the installed plan for this exact (workload, batch, board)
    config — ``plan_source=store``, the persisted A/B numbers ride the
    line and ``tune_retraces`` (the life_batch retrace DELTA across this
    phase) proves the reuse dispatched without re-tracing — or run one
    bounded measured tuning pass (``tune.runner.tune``) and persist the
    winner: ``plan_source=fresh``. ``MOMP_TUNE=0`` skips the whole
    phase with an explicit ``fallback_reason`` so the sentinel's match
    keys still see every field. The heuristic-vs-tuned A/B is
    ``heuristic_cups`` / ``tuned_cups`` / ``vs_heuristic`` — >= 1.0 by
    construction because the heuristic's own choice is always among the
    timed candidates."""
    from mpi_and_open_mp_tpu.ops import pallas_life
    from mpi_and_open_mp_tpu.serve import retrace_counts
    from mpi_and_open_mp_tpu.tune import plans as tune_plans
    from mpi_and_open_mp_tpu.tune import runner as tune_runner

    shape = (args.tune_batch, args.tune_board, args.tune_board)
    fields = {"tune_board": args.tune_board,
              "tune_batch": args.tune_batch,
              "tune_steps": args.autotune}
    if not pallas_life._tune_enabled():
        return {**fields, "plan_source": "heuristic",
                "fallback_reason": "autotune skipped: MOMP_TUNE=0"}
    before = retrace_counts()
    plans_dir = args.plans or os.environ.get("MOMP_TUNE_PLANS") or None
    store = tune_plans.PlanStore(plans_dir) if plans_dir else None
    if store is not None:
        fields["plans"] = store.install()
        hit = store.lookup(workload, shape)
        if hit is not None:
            heur = hit.get("heuristic") or {}
            fields.update({
                "plan_source": "store",
                "tuned_path": hit["choice"]["path"],
                "tuned_cups": hit["tuned"]["cups"],
                "heuristic_cups": heur.get("cups"),
                "vs_heuristic": hit["vs_heuristic"],
            })
            after = retrace_counts()
            fields["tune_retraces"] = {
                k: after[k] - before.get(k, 0) for k in after
                if after[k] - before.get(k, 0)}
            return fields
    res = tune_runner.tune(workload, shape, steps=args.autotune,
                           store=store)
    heur = res.get("heuristic") or {}
    fields.update({
        "plan_source": "fresh",
        "tuned_path": res["tuned"]["path"],
        "tuned_cups": res["tuned"]["cups"],
        "heuristic_cups": heur.get("cups"),
        "vs_heuristic": res["vs_heuristic"],
        "tune_candidates": len(res["measurements"]),
        "tune_rejected": len(res["rejected"]),
    })
    for k in ("plan_file", "aot_export", "digest"):
        if k in res:
            fields[f"tune_{k}" if k == "digest" else k] = res[k]
    after = retrace_counts()
    fields["tune_retraces"] = {
        k: after[k] - before.get(k, 0) for k in after
        if after[k] - before.get(k, 0)}
    return fields


def _stencil_bench(args, state, *, platform, device_kind, degraded,
                   backend_note) -> int:
    """The non-life headline (``--workload NAME``): the spec-generated
    roll engine over the workload's own seeded board, parity-gated
    against the spec oracle, steady rate chain-differenced exactly like
    the Life headline (run_roll's step count is a runtime scalar, so the
    chained dispatch reuses the executable). No ``vs_baseline`` — the
    reference MPI baseline is a Life measurement."""
    import jax

    from mpi_and_open_mp_tpu import stencils
    from mpi_and_open_mp_tpu.obs import metrics as obs_metrics
    from mpi_and_open_mp_tpu.obs import trace as obs_trace
    from mpi_and_open_mp_tpu.utils.timing import anchor_sync

    spec = stencils.get(args.workload)
    metric = _metric_name(spec.name)
    rng = np.random.default_rng(46)
    board = spec.init(rng, (NY, NX))

    state["phase"] = "parity"
    with obs_trace.span("bench.phase", phase="parity", workload=spec.name):
        got = np.asarray(stencils.run_roll(spec, board, 8))
    ref = stencils.oracle_run(spec, board, 8)
    if not stencils.parity_ok(spec, got, ref):
        print(json.dumps({"metric": metric, "workload": spec.name,
                          "value": 0.0,
                          "unit": "cell_updates_per_sec",
                          "error": "parity check failed",
                          "phase": "parity"}))
        return 1

    # Autotune phase (opt-in via --autotune K): non-life workloads tune
    # through the same machinery (roll vs per-spec Pallas candidates).
    # A failure costs its fields, never the line.
    tuned = {}
    if args.autotune:
        state["phase"] = "autotune"
        with obs_trace.span("bench.phase", phase="autotune",
                            workload=spec.name):
            try:
                tuned = _autotune_phase(args, spec.name)
            except Exception as e:
                tuned = {"plan_source": "heuristic",
                         "tune_error": f"{type(e).__name__}: {e}"[:200]}

    # The sharded halo A/B is workload-generic: heat/gray_scott/
    # wireworld price their own overlap win through the same plan-
    # scheduled engine legs.
    sharded_ab = {}
    if args.sharded_ab:
        state["phase"] = "sharded_ab"
        with obs_trace.span("bench.phase", phase="sharded_ab",
                            workload=spec.name):
            try:
                sharded_ab = _sharded_ab_phase(args, spec.name)
            except Exception as e:
                sharded_ab = {"sharded_ab_board": args.sharded_board,
                              "sharded_ab_error":
                              f"{type(e).__name__}: {e}"[:200]}

    # The ring A/B is workload-generic too: it prices the attention
    # hop-prefetch schedule, not the stencil.
    ring_ab = {}
    if args.ring_ab:
        state["phase"] = "ring_ab"
        with obs_trace.span("bench.phase", phase="ring_ab"):
            try:
                ring_ab = _ring_ab_phase(args)
            except Exception as e:
                ring_ab = {"ring_ab_calls": args.ring_ab,
                           "ring_ab_error":
                           f"{type(e).__name__}: {e}"[:200]}

    # The radius A/B is workload-generic (it sweeps its own ephemeral
    # lenia specs): any headline may carry the crossover table.
    radius_ab = {}
    if args.radius_ab:
        state["phase"] = "radius_ab"
        with obs_trace.span("bench.phase", phase="radius_ab"):
            try:
                radius_ab = _radius_ab_phase(args)
            except Exception as e:
                radius_ab = {"radius_ab_board": args.radius_board,
                             "radius_ab_error":
                             f"{type(e).__name__}: {e}"[:200]}

    state["phase"] = "measure"

    def timed(n, reps=3):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            anchor_sync(stencils.run_roll(spec, board, n), fetch_all=True)
            best = min(best, time.perf_counter() - t0)
        return best

    # Warm re-dispatch (the gate compiled the engine; n is runtime).
    anchor_sync(stencils.run_roll(spec, board, STEPS), fetch_all=True)
    best = timed(STEPS)
    rtt_bound = best < 1.0
    mult, reps = (161, 3) if rtt_bound else (2, 1)
    chained = timed(STEPS * mult, reps)
    differenced = chained > best
    steady = (chained - best) / (mult - 1) if differenced else best
    cups = NY * NX * STEPS / best
    steady_cups = NY * NX * STEPS / steady

    state["phase"] = "report"
    metrics_fields = ({"metrics": obs_metrics.snapshot()}
                      if obs_metrics.metrics_on() else {})
    rec = {
        "metric": metric,
        "value": round(steady_cups, 1),
        "unit": "cell_updates_per_sec",
        "end_to_end_sec": round(best, 4),
        "end_to_end_cups": round(cups, 1),
        "steady_is_differenced": differenced,
        "stencil_parity": True,
        "backend": jax.default_backend(),
        "impl": "roll",
        "workload": spec.name,
        "board": [NY, NX],
        "channels": spec.channels,
        "steps": STEPS,
        "dtype": spec.dtype,
        "platform": platform,
        "device_kind": device_kind,
        "devices": jax.device_count(),
        "degraded": degraded,
        # Plan provenance rides EVERY line like the engine stamps:
        # heuristic unless the autotune phase overrides it below.
        "plan_source": "heuristic",
        **tuned,
        **sharded_ab,
        **ring_ab,
        **radius_ab,
        **metrics_fields,
        **backend_note,
    }
    print(json.dumps(rec))
    _ledger_append(args.ledger, rec, platform=platform,
                   device_kind=device_kind,
                   device_count=jax.device_count())
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--board", type=int, default=None, metavar="N",
                    help="override board edge (e.g. 8192 for the big-grid "
                    "strong-scaling config); default 500 (p46gun_big)")
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--workload", default="life", metavar="NAME",
                    help="stencil workload to bench (a registered "
                    "stencils name: life, heat, gray_scott, wireworld; "
                    "default life). Non-life workloads run the generic "
                    "spec-engine headline (metric stencil_steady_cups_"
                    "<name>, same parity-gate + chained-differencing "
                    "discipline) and support --board/--steps/--trace/"
                    "--ledger/--autotune/--sharded-ab/--radius-ab only "
                    "— the "
                    "life-specific phases "
                    "(--batch/--serve/--sessions/--checkpoint-dir/"
                    "--sparse-ab) are rejected")
    ap.add_argument("--sparse-ab", type=int, default=0, metavar="K",
                    help="also run the SPARSE ACTIVE-TILE A/B (life "
                    "only): K steps of a mostly-dead --sparse-board "
                    "board through stencils.sparse.ActiveTileEngine vs "
                    "the dense jitted roll engine, both sides "
                    "chain-differenced and the sparse result gated "
                    "bit-exact against the dense one, reporting "
                    "sparse_cups / dense_cups / sparse_vs_dense / "
                    "active_frac on the JSON line (runs on every "
                    "backend)")
    ap.add_argument("--sharded-ab", type=int, default=0, metavar="K",
                    help="also run the SHARDED HALO A/B (any workload): "
                    "K torus steps of a --sharded-board² board through "
                    "the plan-scheduled sharded engine (stencils.engine "
                    "+ parallel.haloplan), overlap schedule vs forced-"
                    "sequential baseline on the same mesh, both legs "
                    "oracle-parity-gated, chain-differenced and required "
                    "bit-identical, reporting sharded_overlap_cups / "
                    "sharded_seq_cups / vs_sequential plus the exchange-"
                    "only transfer-vs-exposed accounting on the JSON "
                    "line (needs >= 2 devices — CI uses the 8-virtual-"
                    "device CPU mesh; MOMP_HALO_OVERLAP=0 downgrades the "
                    "sharded_halo stamp to seq:*, which the sentinel "
                    "fails as a provenance downgrade)")
    ap.add_argument("--ring-ab", type=int, default=0, metavar="R",
                    help="also run the RING-ATTENTION HOP-PREFETCH A/B "
                    "(any workload): R causal ring-attention trips over "
                    "the full device mesh, double-slot K/V hop prefetch "
                    "(:pf) vs the single-slot schedule on the same "
                    "operands, prefetch leg oracle-parity-gated, both "
                    "legs chain-differenced and required bit-identical "
                    "forward (gradients cross-checked), reporting "
                    "ring_prefetch_tflops / ring_nopf_tflops / "
                    "ring_vs_nopf plus the rotation-only transfer-vs-"
                    "exposed accounting on the JSON line (needs >= 3 "
                    "devices — CI uses the 8-virtual-device CPU mesh "
                    "with MOMP_PALLAS_INTERPRET=1; MOMP_RING_PREFETCH=0 "
                    "drops the :pf stamp, which the sentinel fails as a "
                    "provenance downgrade)")
    ap.add_argument("--sparse-sharded-ab", type=int, default=0,
                    metavar="K",
                    help="also run the SPARSE x SHARDED A/B (life "
                    "only): K steps of the mostly-dead --sparse-board "
                    "seed through stencils.sparse_sharded."
                    "SparseShardedEngine on the row mesh vs the dense "
                    "sharded runner AND vs the single-device sparse "
                    "engine, all legs chain-differenced, the sparse-"
                    "sharded leg oracle-parity-gated and required "
                    "bit-identical to the dense sharded schedule, "
                    "reporting sparse_sharded_cups / _vs_dense / "
                    "_vs_single / active_frac plus the exchange-skip "
                    "counters on the JSON line (needs >= 2 devices; "
                    "MOMP_SPARSE_SHARDED=0 downgrades the "
                    "sparse_sharded_engine stamp to dense:sharded, "
                    "which the sentinel fails as a provenance "
                    "downgrade)")
    ap.add_argument("--sharded-board", type=int, default=512, metavar="N",
                    help="board edge for the sharded halo A/B (default "
                    "%(default)s; must divide across the mesh's y axis)")
    ap.add_argument("--sparse-board", type=int, default=2048, metavar="N",
                    help="board edge for the sparse A/B (default 2048; "
                    "must be a multiple of --sparse-tile)")
    ap.add_argument("--sparse-tile", type=int, default=64, metavar="T",
                    help="active-tile size for the sparse A/B "
                    "(default 64)")
    ap.add_argument("--radius-ab", type=int, default=0, metavar="K",
                    help="also run the WIDE-RADIUS ENGINE-FAMILY A/B "
                    "(any workload): K steps of an ephemeral lenia spec "
                    "per --radius-list radius on a --radius-board² "
                    "float32 board, racing the offset-table walk vs the "
                    "separable row×col pass vs the cached-rfft2 "
                    "circular convolution (stencils.engine.run_family) "
                    "wherever each family's legality gate admits it, "
                    "every leg oracle-parity-gated at its gate-owned "
                    "tolerance and chain-differenced, reporting the "
                    "radius_ab_table crossover rows plus "
                    "radius_ab_{offset,sep,fft}_cups / "
                    "radius_ab_vs_offset_best and the engine_family "
                    "stamp on the JSON line (runs on every backend; "
                    "MOMP_ENGINE_FAMILY=offset pins the walk, which "
                    "the sentinel fails as a provenance downgrade)")
    ap.add_argument("--radius-board", type=int, default=128, metavar="N",
                    help="board edge for the radius A/B "
                    "(default %(default)s)")
    ap.add_argument("--radius-list", default="1,4,8,16", metavar="R1,R2,..",
                    help="comma list of kernel radii the radius A/B "
                    "sweeps (default %(default)s)")
    ap.add_argument("--checkpoint-dir", default=None, metavar="DIR",
                    help="run the checkpointed robustness phase, writing "
                    "Orbax restart points here")
    ap.add_argument("--checkpoint-every", type=int, default=0, metavar="K",
                    help="checkpoint cadence for that phase "
                    "(default: steps//10)")
    ap.add_argument("--resume", action="store_true",
                    help="continue the checkpointed phase from the latest "
                    "restart point in --checkpoint-dir")
    ap.add_argument("--batch", type=int, default=0, metavar="B",
                    help="also run the BATCHED phase: advance B distinct "
                    "boards of the bench shape in one dispatch through the "
                    "batched native engines (ops.pallas_life."
                    "life_run_vmem_batch) plus a serve-layer bucketing "
                    "demo, reporting aggregate batched_cups / requests "
                    "per sec on the JSON line (runs on every backend)")
    ap.add_argument("--serve", type=int, default=0, metavar="N",
                    help="also run the SERVING-DAEMON phase: a seeded "
                    "mixed-shape burst of N requests through the "
                    "supervised daemon (serve.daemon — admission control, "
                    "deadline flushes, recovery ladder), reporting "
                    "serve_requests_per_sec and p50/p99 latency plus "
                    "shed/degrade counts on the JSON line, then the same "
                    "burst again under the every-record write-ahead "
                    "journal to price the durability tax (serve_wal_* "
                    "fields incl. p50/p99 delta), then a cold/warm pair "
                    "over one durable AOT executable cache to price the "
                    "warm-start win (serve_cold_first_result_s vs "
                    "serve_aot_first_result_s + hit/miss/deserialize "
                    "accounting; runs on every backend; honors "
                    "MOMP_CHAOS)")
    ap.add_argument("--fleet", type=int, default=0, metavar="W",
                    help="with --serve N: also run the SHARDED-FLEET "
                    "phase — the same burst through W in-process worker "
                    "daemons behind the consistent-hash router "
                    "(serve.fleet), clean (fleet_requests_per_sec + "
                    "fleet_p99_latency_s) and then again with the "
                    "busiest worker wedged mid-stream so the "
                    "heartbeat->WAL-replay->re-home ladder is priced "
                    "(fleet_kill_recovery_s); fleet books must balance "
                    "and every re-homed board is oracle-parity-gated")
    ap.add_argument("--loadgen", default=None, metavar="R1,R2,..",
                    help="also run the ELASTIC-FLEET-UNDER-LOAD phase: "
                    "an open-loop Poisson saturation sweep over these "
                    "strictly increasing offered rates (requests/s) "
                    "through a fresh consistent-hash fleet per rung "
                    "(serve.loadgen — arrivals are a precomputed "
                    "schedule, no coordinated omission), reporting the "
                    "saturation knee + goodput + p50/p99/p999 + shed "
                    "breakdown + SLO verdict per rung on the JSON line, "
                    "then one run at the knee rate with the membership "
                    "drill scripted in (wedge busiest at 25%%, REJOIN at "
                    "45%% — rejoin_recovery_s — graceful drain at 65%%): "
                    "final-quartile goodput must recover with zero acked "
                    "loss, balanced books, and oracle parity")
    ap.add_argument("--loadgen-duration", type=float, default=2.0,
                    metavar="S", help="offered-load window per sweep "
                    "rung and for the membership cycle "
                    "(default %(default)s)")
    ap.add_argument("--loadgen-slo-p99", type=float, default=0.5,
                    metavar="S", help="declared p99 latency SLO bound "
                    "the sweep rungs are judged against "
                    "(default %(default)s)")
    ap.add_argument("--sessions", type=int, default=0, metavar="S",
                    help="also run the RESIDENT-SESSION phase: S "
                    "device-resident sessions in the serving daemon's "
                    "session pool (serve.pool — boards live on device as "
                    "(slab, bit-lane) handles, stepping is in-place "
                    "donated dispatch) vs the identical workload shipped "
                    "board-by-board through the ticket path, reporting "
                    "session_requests_per_sec / ship_requests_per_sec / "
                    "session_vs_ship plus pool hit/miss/evict accounting; "
                    "every final snapshot is oracle-parity-gated (runs on "
                    "every backend)")
    ap.add_argument("--trace", default=None, metavar="PATH",
                    help="write obs span/event JSONL here (sets MOMP_TRACE; "
                    "summarise with analysis/trace_report.py). The timed "
                    "brackets carry no trace hooks — steady-state numbers "
                    "are unaffected by construction")
    ap.add_argument("--ledger", default=None, metavar="PATH",
                    help="append the stamped JSON line to this run ledger "
                    "(obs.ledger schema; default: $MOMP_LEDGER when set). "
                    "Judge it with analysis/regression_sentinel.py")
    ap.add_argument("--autotune", type=int, default=0, metavar="K",
                    help="also run the AUTOTUNE phase (any workload): "
                    "install persisted plans from --plans (validated + "
                    "oracle-parity-gated; plan_source=store reuses the "
                    "recorded A/B with zero retraces), else one bounded "
                    "measured tuning pass over the legal candidate space "
                    "at (--tune-batch, --tune-board²) with K-step "
                    "chained-differencing brackets, persisting the "
                    "winner plus (life) its exported executable under "
                    "one fingerprint digest (plan_source=fresh); "
                    "reports tuned_cups / heuristic_cups / vs_heuristic "
                    "on the JSON line; MOMP_TUNE=0 skips with an "
                    "explicit fallback_reason")
    ap.add_argument("--tune-board", type=int, default=64, metavar="N",
                    help="board edge the autotune phase profiles "
                    "(default %(default)s — small enough for CPU CI; "
                    "the chip launchers pass the production shapes)")
    ap.add_argument("--tune-batch", type=int, default=32, metavar="B",
                    help="stack batch size the autotune phase profiles "
                    "(default %(default)s)")
    ap.add_argument("--plans", default=None, metavar="DIR",
                    help="durable tuned-plan store directory (default "
                    "$MOMP_TUNE_PLANS): momp-plan/1 records keyed by "
                    "the serve/aotcache fingerprint digest, living "
                    "beside the <digest>.aot executables; corrupt/"
                    "stale/parity-failing records quarantine and the "
                    "heuristics serve unchanged")
    args = ap.parse_args(argv)
    if args.ledger is None:
        args.ledger = os.environ.get("MOMP_LEDGER") or None
    if args.resume and not args.checkpoint_dir:
        ap.error("--resume requires --checkpoint-dir")
    if args.fleet and not args.serve:
        ap.error("--fleet requires --serve N")
    if args.loadgen:
        try:
            rates = [float(r) for r in str(args.loadgen).split(",")
                     if r.strip()]
        except ValueError:
            ap.error(f"--loadgen wants a comma list of offered rates, "
                     f"got {args.loadgen!r}")
        if not rates or any(b <= a for a, b in zip(rates, rates[1:])):
            ap.error(f"--loadgen rates must be strictly increasing, "
                     f"got {args.loadgen!r}")
    if args.workload != "life":
        from mpi_and_open_mp_tpu import stencils as _stencils

        try:
            _stencils.get(args.workload)
        except KeyError as e:
            ap.error(str(e))
        for flag, val in (("--batch", args.batch), ("--serve", args.serve),
                          ("--sessions", args.sessions),
                          ("--loadgen", args.loadgen),
                          ("--checkpoint-dir", args.checkpoint_dir),
                          ("--sparse-ab", args.sparse_ab),
                          ("--sparse-sharded-ab", args.sparse_sharded_ab)):
            if val:
                ap.error(f"{flag} is a life-workload phase; "
                         f"--workload {args.workload} runs the stencil "
                         "headline only")
    if args.autotune and args.autotune < 16:
        ap.error("--autotune needs >= 16 steps for the "
                 "chained-differencing bracket")
    if args.sharded_ab and args.sharded_ab < 16:
        ap.error("--sharded-ab needs >= 16 steps for the "
                 "chained-differencing bracket")
    if args.ring_ab and args.ring_ab < 16:
        ap.error("--ring-ab needs >= 16 calls for the "
                 "chained-differencing bracket")
    if args.radius_ab:
        if args.radius_ab < 16:
            ap.error("--radius-ab needs >= 16 steps for the "
                     "chained-differencing bracket")
        try:
            radii = [int(r) for r in str(args.radius_list).split(",")
                     if r.strip()]
        except ValueError:
            ap.error(f"--radius-list wants a comma list of radii, "
                     f"got {args.radius_list!r}")
        if not radii or any(r < 1 for r in radii):
            ap.error(f"--radius-list radii must be positive, "
                     f"got {args.radius_list!r}")
        if args.radius_board < 4 * max(radii):
            ap.error(f"--radius-board {args.radius_board} is too small "
                     f"for radius {max(radii)} (needs >= 4*radius)")
    if args.sparse_ab or args.sparse_sharded_ab:
        if args.sparse_ab and args.sparse_ab < 16:
            ap.error("--sparse-ab needs >= 16 steps for the "
                     "chained-differencing bracket")
        if args.sparse_sharded_ab and args.sparse_sharded_ab < 16:
            ap.error("--sparse-sharded-ab needs >= 16 steps for the "
                     "chained-differencing bracket")
        if args.sparse_tile < 1 or args.sparse_board % args.sparse_tile:
            ap.error(f"--sparse-board {args.sparse_board} must be a "
                     f"positive multiple of --sparse-tile "
                     f"{args.sparse_tile}")
    if args.trace:
        # Before any phase runs, so the sink (append-mode, cached per env
        # value) collects every span of this invocation.
        os.environ["MOMP_TRACE"] = args.trace
    global NY, NX, STEPS
    if args.board:
        NY = NX = args.board
    if args.steps:
        STEPS = args.steps

    # Driver contract: ONE JSON line, always — a failure anywhere prints
    # {"metric", "error", "phase"} and exits nonzero instead of dying on
    # a traceback with no line. A preemption (signal or chaos plan) is
    # the one non-error failure: state is flushed, the line says
    # "resume": true, and the exit code is 75 (EX_TEMPFAIL) so queue
    # loops requeue instead of dropping the job.
    state = {"phase": "probe"}
    try:
        return _bench(args, state)
    except BaseException as e:  # noqa: BLE001 — the line IS the contract
        if isinstance(e, (KeyboardInterrupt, SystemExit)):
            raise
        from mpi_and_open_mp_tpu.robust.preempt import (
            EXIT_PREEMPTED, Preempted)

        rec = {"metric": _metric_name(args.workload),
               "workload": args.workload,
               "error": f"{type(e).__name__}: {e}"[:300],
               "phase": state["phase"]}
        if isinstance(e, Preempted):
            rec["resume"] = True
            print(json.dumps(rec))
            _ledger_append(args.ledger, rec)
            return EXIT_PREEMPTED
        print(json.dumps(rec))
        _ledger_append(args.ledger, rec)
        return 1


def _metric_name(workload: str) -> str:
    """The headline metric for a workload: life keeps its historical
    name (the ledger/sentinel history keys on it); every other stencil
    gets ``stencil_steady_cups_<name>``."""
    return ("life_steady_cups_p46gun_big" if workload == "life"
            else f"stencil_steady_cups_{workload}")


def _ledger_append(path, rec, **stamps) -> None:
    """Best-effort ledger append — a ledger IO failure must never cost
    the bench line or change the exit code (stderr note only)."""
    if not path:
        return
    try:
        from mpi_and_open_mp_tpu.obs import ledger as obs_ledger

        obs_ledger.append(obs_ledger.stamp(rec, **stamps), path)
    except Exception as e:  # noqa: BLE001
        print(f"bench: ledger append failed: {type(e).__name__}: {e}",
              file=sys.stderr)


def _bench(args, state) -> int:
    # Backend watchdog (robust.watchdog): a wedged axon relay (observed
    # after a TPU client was killed mid-claim) makes jax.devices() hang
    # indefinitely IN THIS PROCESS too — probe device discovery in a
    # subprocess first, with bounded exponential backoff when
    # BENCH_PROBE_ATTEMPTS asks for retries, and fall back to CPU
    # (honestly labelled) so the bench records a line instead of hanging
    # the harness.
    from mpi_and_open_mp_tpu.obs import metrics as obs_metrics
    from mpi_and_open_mp_tpu.obs import trace as obs_trace
    from mpi_and_open_mp_tpu.robust import guards, watchdog

    backend_note = {}
    # One knob for the whole fleet: GRAFT_PROBE_TIMEOUT_S (the graft
    # driver's watchdog budget — __graft_entry__.dryrun_multichip) is the
    # default; BENCH_PROBE_TIMEOUT_S still wins when set, so bench can be
    # tuned independently without forking the harness config.
    res = watchdog.probe_devices(
        _env_num("BENCH_PROBE_TIMEOUT_S",
                 _env_num("GRAFT_PROBE_TIMEOUT_S", 240, float), float),
        attempts=_env_num("BENCH_PROBE_ATTEMPTS", 1, int),
        backoff_s=_env_num("BENCH_PROBE_BACKOFF_S", 2.0, float),
        probe=_probe_devices,  # the module attribute — tests stub it
    )
    if not res.ok:
        import jax

        jax.config.update("jax_platforms", "cpu")
        note = res.why + (f" after {res.attempts} attempts"
                          if res.attempts > 1 else "")
        backend_note = {"backend_fallback": (
            f"device discovery failed/hung ({note}); "
            "ran on CPU — not a TPU measurement"
        ), "chip_record": (
            "results/bench_tpu_r05.jsonl holds committed real-chip "
            "bench lines for this round"
        ),
            # The machine-readable twin of the prose above: the sentinel
            # surfaces this string in its downgrade verdict, so the
            # WHY of a degraded line survives into the cross-run record
            # (BENCH_r04/r05 left it implicit).
            "fallback_reason": note}
    import jax

    # Provenance stamps for the line AND the ledger key: what actually
    # ran. On the fallback path the platform is already pinned to cpu, so
    # this first device touch cannot hang; on the healthy path the probe
    # above just proved discovery completes.
    platform = jax.default_backend()
    try:
        device_kind = jax.devices()[0].device_kind
    except Exception:  # noqa: BLE001 — provenance must not kill the line
        device_kind = "unknown"

    if args.workload != "life":
        return _stencil_bench(args, state, platform=platform,
                              device_kind=device_kind, degraded=res.degraded,
                              backend_note=backend_note)

    from mpi_and_open_mp_tpu.models.life import LifeSim
    from mpi_and_open_mp_tpu.ops.life_ops import life_step_numpy
    from mpi_and_open_mp_tpu.utils.config import config_from_board

    rng = np.random.default_rng(46)  # p46 in spirit
    board = (rng.random((NY, NX)) < 0.3).astype(np.uint8)

    # Honesty gate: the timed impl must be bit-exact vs the host oracle.
    state["phase"] = "parity"
    cfg_check = config_from_board(board, steps=8, save_steps=0)
    sim_check = LifeSim(cfg_check, layout="serial", impl="auto")
    # Phase spans (no-op singletons when MOMP_TRACE is unset) bracket the
    # UNTIMED phases only; the chained-dispatch brackets inside measure()
    # stay hook-free so tracing cannot perturb the recorded rates.
    with obs_trace.span("bench.phase", phase="parity"):
        got = sim_check.run(save=False)
    ref = board.copy()
    for _ in range(8):
        ref = life_step_numpy(ref)
    if not np.array_equal(got, ref):
        print(json.dumps({"metric": "life_steady_cups_p46gun_big",
                          "value": 0.0,
                          "unit": "cell_updates_per_sec", "vs_baseline": 0.0,
                          "error": "parity check failed",
                          "phase": "parity"}))
        return 1

    # Robustness phase (opt-in via --checkpoint-dir): checkpointed run
    # with resume/preemption semantics; its fields ride the bench line.
    ckpt_fields = {}
    if args.checkpoint_dir:
        state["phase"] = "checkpoint"
        with obs_trace.span("bench.phase", phase="checkpoint"):
            ckpt_fields = _checkpointed_run(args)

    state["phase"] = "measure"

    def measure(sim):
        """(best_sec, steady_sec, differenced) for STEPS steps.

        Steady-state rate: the single-run number carries one fixed
        host->device dispatch round trip (~70 ms on a tunneled axon chip —
        measured via a scalar fetch; a co-located host pays ~none), which
        swamps the few-ms compute. On the pallas/bitfused paths the step
        count is a runtime scalar, so a mult-x-longer dispatch reuses the
        same executable; differencing the two durations isolates the
        marginal per-step rate. The other impls (roll/halo) jit with a
        STATIC step count, so the chained run is a different compiled
        program: it gets compiled OUTSIDE the timing bracket by a
        discarded warm-up advance (an AOT ``lower().compile()`` does
        not seed the jit call cache), and the chain uses the cheapest
        mult (2) with one rep — these impls run on CPU where a 161x
        chain would grind through 161x the actual steps. Every line is
        differenced now; ``steady_is_differenced: false`` survives only
        as the jitter-anomaly flag (chained run not slower than base).
        """
        sim.warmup()  # compiles the exact stepper the timed loop uses
        best = float("inf")
        for _ in range(3):
            sim.reset()
            sim.sync()  # absorb reset()'s async host->device transfer
            t0 = time.perf_counter()
            sim.step(STEPS)
            sim.sync()
            best = min(best, time.perf_counter() - t0)
        steady, differenced = best, False
        if sim.impl in ("pallas", "bitfused"):
            # RTT-bound sub-second runs: make the differencing signal
            # large vs the ~±10 ms RTT jitter (161x chain ≈ 0.3 s of pure
            # compute at the flagship rate → jitter is <5% of signal) and
            # take best-of-3. Multi-second big-board runs: jitter is
            # negligible and a 6x chain already costs real chip time —
            # single shot.
            rtt_bound = best < 1.0
            mult, reps = (161, 3) if rtt_bound else (6, 1)
            chained = float("inf")
            for _ in range(reps):
                sim.reset()
                sim.sync()
                t0 = time.perf_counter()
                sim.step(STEPS * mult)
                sim.sync()
                chained = min(chained, time.perf_counter() - t0)
            if chained > best:
                steady = (chained - best) / (mult - 1)
                differenced = True
        else:
            from mpi_and_open_mp_tpu.utils.timing import anchor_sync

            mult = 2
            # Compile-and-discard: advance is functional, so this seeds
            # the static-n jit cache for the chained length without
            # touching sim state — the timed dispatch below then reuses
            # the executable, exactly like warmup() does for run().
            anchor_sync(sim._advance(sim.board, STEPS * mult),
                        fetch_all=True)
            sim.reset()
            sim.sync()
            t0 = time.perf_counter()
            sim.step(STEPS * mult)
            sim.sync()
            chained = time.perf_counter() - t0
            if chained > best:
                steady = (chained - best) / (mult - 1)
                differenced = True
        return best, steady, differenced

    cfg = config_from_board(board, steps=STEPS, save_steps=0)
    sim = LifeSim(cfg, layout="serial", impl="auto")
    with obs_trace.span("bench.phase", phase="measure"):
        best, steady, differenced = measure(sim)
    cups = NY * NX * STEPS / best
    steady_cups = NY * NX * STEPS / steady

    # Batched phase (opt-in via --batch): aggregate throughput of B
    # boards per dispatch + the serve-layer bucketing counters. Runs on
    # every backend; a failure costs its fields, never the bench line.
    batched = {}
    if args.batch:
        state["phase"] = "batch"
        m0 = obs_metrics.snapshot()
        with obs_trace.span("bench.phase", phase="batch"):
            try:
                batched = _batched_phase(args.batch, cups)
            except Exception as e:
                batched = {"batch": args.batch,
                           "batched_error": f"{type(e).__name__}: {e}"[:200]}
        batched.update(_phase_metrics_delta("batch", m0))

    # Autotune phase (opt-in via --autotune K): bounded measured tuning
    # pass or persisted-plan reuse; heuristic-vs-tuned A/B fields ride
    # the line. A failure costs its fields, never the bench line.
    tuned = {}
    if args.autotune:
        state["phase"] = "autotune"
        with obs_trace.span("bench.phase", phase="autotune"):
            try:
                tuned = _autotune_phase(args, "life")
            except Exception as e:
                tuned = {"plan_source": "heuristic",
                         "tune_error": f"{type(e).__name__}: {e}"[:200]}

    # Serving-daemon phase (opt-in via --serve N): latency percentiles
    # and shed/degrade accounting from the supervised daemon. A failure
    # costs its fields, never the bench line — EXCEPT a preemption
    # (signal or chaos plan), which follows the global exit-75 contract.
    served = {}
    if args.serve:
        from mpi_and_open_mp_tpu.robust.preempt import Preempted

        state["phase"] = "serve"
        m0 = obs_metrics.snapshot()
        with obs_trace.span("bench.phase", phase="serve"):
            try:
                served = _serve_phase(args.serve)
            except Preempted:
                raise
            except Exception as e:
                served = {"serve_daemon_requests": args.serve,
                          "serve_daemon_error":
                          f"{type(e).__name__}: {e}"[:200]}
        served.update(_phase_metrics_delta("serve", m0))
        if args.fleet:
            state["phase"] = "fleet"
            m0 = obs_metrics.snapshot()
            with obs_trace.span("bench.phase", phase="fleet"):
                try:
                    served.update(_fleet_phase(args.serve, args.fleet))
                except Preempted:
                    raise
                except Exception as e:
                    served.update({"fleet_workers": args.fleet,
                                   "fleet_error":
                                   f"{type(e).__name__}: {e}"[:200]})
            served.update(_phase_metrics_delta("fleet", m0))

    # Elastic-fleet-under-load phase (opt-in via --loadgen R1,R2,..):
    # open-loop saturation sweep + the wedge->REJOIN->drain membership
    # cycle. Same failure contract as the other serve-layer phases.
    if args.loadgen:
        from mpi_and_open_mp_tpu.robust.preempt import Preempted

        state["phase"] = "loadgen"
        m0 = obs_metrics.snapshot()
        with obs_trace.span("bench.phase", phase="loadgen"):
            try:
                served.update(_loadgen_phase(args))
            except Preempted:
                raise
            except Exception as e:
                served.update({"loadgen_rates": args.loadgen,
                               "loadgen_error":
                               f"{type(e).__name__}: {e}"[:200]})
        served.update(_phase_metrics_delta("loadgen", m0))

    # Resident-session phase (opt-in via --sessions S): the device-
    # resident vs ship-every-call A/B through the session pool. Same
    # failure contract as the other serve-layer phases.
    if args.sessions:
        from mpi_and_open_mp_tpu.robust.preempt import Preempted

        state["phase"] = "sessions"
        m0 = obs_metrics.snapshot()
        with obs_trace.span("bench.phase", phase="sessions"):
            try:
                served.update(_sessions_phase(args.sessions))
            except Preempted:
                raise
            except Exception as e:
                served.update({"session_count": args.sessions,
                               "session_error":
                               f"{type(e).__name__}: {e}"[:200]})
        served.update(_phase_metrics_delta("sessions", m0))

    # Sparse active-tile A/B (opt-in via --sparse-ab K): the mostly-dead
    # big-board scaling axis. Same failure contract as the other opt-in
    # phases: an exception costs its fields, never the bench line.
    sparse = {}
    if args.sparse_ab:
        state["phase"] = "sparse"
        with obs_trace.span("bench.phase", phase="sparse"):
            try:
                sparse = _sparse_ab_phase(
                    args.sparse_ab, args.sparse_board, args.sparse_tile)
            except Exception as e:
                sparse = {"sparse_board": args.sparse_board,
                          "sparse_error": f"{type(e).__name__}: {e}"[:200]}

    # Sharded halo-schedule A/B (opt-in via --sharded-ab K): overlap vs
    # forced-sequential through the plan-scheduled engine. Same failure
    # contract as the other opt-in phases.
    sharded_ab = {}
    if args.sharded_ab:
        state["phase"] = "sharded_ab"
        with obs_trace.span("bench.phase", phase="sharded_ab"):
            try:
                sharded_ab = _sharded_ab_phase(args, "life")
            except Exception as e:
                sharded_ab = {"sharded_ab_board": args.sharded_board,
                              "sharded_ab_error":
                              f"{type(e).__name__}: {e}"[:200]}

    # Ring-attention hop-prefetch A/B (opt-in via --ring-ab R): the
    # double-slot K/V rotation schedule vs the single-slot one it
    # deepens. Same failure contract as the other opt-in phases.
    ring_ab = {}
    if args.ring_ab:
        state["phase"] = "ring_ab"
        with obs_trace.span("bench.phase", phase="ring_ab"):
            try:
                ring_ab = _ring_ab_phase(args)
            except Exception as e:
                ring_ab = {"ring_ab_calls": args.ring_ab,
                           "ring_ab_error":
                           f"{type(e).__name__}: {e}"[:200]}

    # Sparse x sharded A/B (opt-in via --sparse-sharded-ab K): the
    # composition of the sparse active-tile mask with the sharded halo
    # exchange. Same failure contract as the other opt-in phases.
    sparse_sharded = {}
    if args.sparse_sharded_ab:
        state["phase"] = "sparse_sharded"
        with obs_trace.span("bench.phase", phase="sparse_sharded"):
            try:
                sparse_sharded = _sparse_sharded_ab_phase(args)
            except Exception as e:
                sparse_sharded = {
                    "sparse_sharded_board": args.sparse_board,
                    "sparse_sharded_error":
                    f"{type(e).__name__}: {e}"[:200]}

    # Wide-radius engine-family A/B (opt-in via --radius-ab K): the
    # offset/sep/fft crossover sweep. Same failure contract as the
    # other opt-in phases.
    radius_ab = {}
    if args.radius_ab:
        state["phase"] = "radius_ab"
        with obs_trace.span("bench.phase", phase="radius_ab"):
            try:
                radius_ab = _radius_ab_phase(args)
            except Exception as e:
                radius_ab = {"radius_ab_board": args.radius_board,
                             "radius_ab_error":
                             f"{type(e).__name__}: {e}"[:200]}

    # Secondary: the SHARDED flagship entry point (row-layout bitfused
    # over a 1-device mesh — all the bench chip has). Since the 1-device
    # serial dispatch, this measures what a user of the sharded API gets
    # on one chip (the serial stepper; sharded_plan says so) — the
    # ppermute-halo exchange machinery itself engages from 2 devices and
    # is validated for correctness by the CPU-mesh suite and
    # dryrun_multichip, not timed here. TPU-only (interpret-mode Pallas
    # would grind on CPU).
    sharded = {}
    if jax.default_backend() == "tpu":
        state["phase"] = "sharded"
        from mpi_and_open_mp_tpu.parallel import mesh as mesh_lib

        sim_sh = LifeSim(cfg, layout="row", impl="bitfused",
                         mesh=mesh_lib.make_mesh_1d(1, axis="y"))
        # Same honesty discipline as the headline: the sharded stepper
        # (whatever path it dispatched to) must be bit-exact vs the host
        # oracle before its timing is recorded.
        sim_sh.step(8)
        sh_ok = np.array_equal(sim_sh.collect(), ref)
        sharded = {
            # The EXECUTED path: a 1-device mesh dispatches to the
            # serial stepper (no neighbours -> no ghost redundancy),
            # labelled "serial-1dev:<path>"; real multi-device meshes
            # report the exchange plan's mode.
            "sharded_plan": getattr(sim_sh, "plan_note", sim_sh._plan.mode),
        }
        if sh_ok:
            _, steady_sh, diff_sh = measure(sim_sh)
            sharded.update({
                "sharded_steady_cups": round(NY * NX / steady_sh * STEPS, 1),
                "sharded_steady_is_differenced": diff_sh,
            })
        else:
            sharded["sharded_error"] = "parity check failed"

        # Long-context layer: 32k-token causal attention forward (8 heads,
        # d=128) through the flash-chunked kernel that carries
        # ring_attention's per-shard compute. Marginal per-call seconds by
        # chaining R calls in one dispatch (output feeds the next call's
        # queries, so the chain can't be elided) and differencing —
        # the same RTT-cancelling discipline as the Life numbers.
        state["phase"] = "attention"
        import jax.numpy as jnp
        from jax import lax as jlax

        from mpi_and_open_mp_tpu.parallel import context
        from mpi_and_open_mp_tpu.parallel.context import flash_attention
        from mpi_and_open_mp_tpu.utils.timing import anchor_sync

        # The shared honesty gate (context.gated_parity_check, same one
        # sweep_attention runs): whichever engine flash_attention
        # dispatches to must match the dense oracle before its timings
        # are recorded, with automatic fallback to the jnp engine.
        # for_seq aims the gate at the exact engine+block configuration
        # the timed 32k operands will dispatch. Unlike the sweep, a
        # total gate failure doesn't abort — the bench line (with the
        # Life numbers already in hand) still prints, carrying the
        # error instead of attention fields.
        attn_ok, _, gate_notes = context.gated_parity_check(
            for_seq=32 * 1024)
        if gate_notes:
            # Recorded even when the gate ultimately passed: an engine
            # downgrade (pallas -> jnp) must be explained in the
            # artifact, not only on a transient stderr.
            sharded["attention_gate_notes"] = "; ".join(gate_notes)
        if not attn_ok:
            sharded["attention_error"] = "parity gate failed on every engine"

        h, n, d = 8, 32 * 1024, 128
        flops = 2 * h * n * n * d  # QK^T + PV, causal half
        qkv = [jnp.asarray(rng.standard_normal((h, n, d)), jnp.bfloat16)
               for _ in range(3)]
        # Shape-aware provenance: the engine the timed 32k operands
        # actually dispatch to (a block override that doesn't divide
        # 32k routes them to jnp even when the gate passed on pallas).
        # The ring-hop stamps (fwd/bwd/zigzag) are emitted in the
        # report phase so they ride EVERY line, CPU fallback included.
        sharded["attention_engine"] = context.flash_engine_for(*qkv)

        @jax.jit
        def chain(q, k, v, r):
            return jlax.fori_loop(
                0, r, lambda _, c: flash_attention(c, k, v, causal=True), q
            )

        def timed(call):
            best_r = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                anchor_sync(call(), fetch_all=True)
                best_r = min(best_r, time.perf_counter() - t0)
            return best_r

        if attn_ok:
            # The gate ran at 2048; the timed shape is 32k — a per-shape
            # kernel failure here must cost the attention fields only,
            # never the already-measured Life numbers.
            try:
                anchor_sync(chain(*qkv, jnp.int32(1)),
                            fetch_all=True)  # compile
                t_1 = timed(lambda: chain(*qkv, jnp.int32(1)))
                t_9 = timed(lambda: chain(*qkv, jnp.int32(9)))
            except Exception as e:
                attn_ok = False
                sharded["attention_error"] = (
                    f"{type(e).__name__}: {e}"[:200])
            else:
                # Same anomaly discipline as measure(): if jitter made
                # the longer chain "faster", report the end-to-end
                # single call un-differenced and flag it, rather than
                # emitting a nonsense marginal rate.
                attn_diff = t_9 > t_1
                attn_sec = (t_9 - t_1) / 8 if attn_diff else t_1
                sharded.update({
                    "attention_32k_causal_sec": round(attn_sec, 5),
                    "attention_32k_causal_tflops": round(
                        flops / attn_sec / 1e12, 1),
                    "attention_is_differenced": attn_diff,
                })

        # Training path: the flash custom_vjp backward, FULL (q, k, v)
        # gradients — grad wrt q alone lets XLA prune the dk+dv pass and
        # overstate the rate. The chain is UNROLLED (python loop, static
        # r): grad through a lax.scan of the custom_vjp stacks O(seq^2)
        # forward intermediates per link (see parallel/context.py).
        @functools.partial(jax.jit, static_argnames=("r",))
        def grad_chain(q, k, v, r):
            def loss(q_, k_, v_):
                c = q_
                for _ in range(r):
                    c = flash_attention(c, k_, v_, causal=True)
                return (c.astype(jnp.float32) ** 2).sum()

            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        try:
            if not attn_ok:
                raise RuntimeError(
                    "attention gate or forward timing failed")
            anchor_sync(grad_chain(*qkv, r=1), fetch_all=True)  # compile
            anchor_sync(grad_chain(*qkv, r=3), fetch_all=True)
            g_1 = timed(lambda: grad_chain(*qkv, r=1))
            g_3 = timed(lambda: grad_chain(*qkv, r=3))
        except Exception as e:  # never lose the whole bench line to this
            sharded["attention_grad_error"] = f"{type(e).__name__}: {e}"[:200]
        else:
            grad_diff = g_3 > g_1
            grad_sec = (g_3 - g_1) / 2 if grad_diff else g_1
            sharded.update({
                # grad_sec times one FULL grad step (forward + backward
                # per chain link — a backward can't run without its
                # forward); TFLOP/s uses the matching fwd+bwd = 3.5x fwd
                # accounting (bwd = 5 block matmuls vs 2).
                "attention_32k_grad_sec": round(grad_sec, 5),
                "attention_32k_grad_tflops": round(
                    3.5 * flops / grad_sec / 1e12, 1),
                "attention_grad_is_differenced": grad_diff,
            })
    # Profile phase: compiled-artifact introspection (obs.profile). The
    # roofline annotation divides the ROLL step's XLA cost model (one
    # dense stencil step at the bench shape — flops + bytes accessed from
    # compiled.cost_analysis(), compiled once, nothing executed) by the
    # measured steady seconds-per-step, so every cups number says how far
    # it sits from the device's compute/bandwidth ceilings. The model fn
    # is stamped on the line: on the packed/Pallas paths this is the
    # algorithmic work of the dense formulation, not the kernel's
    # internal op count. Failures cost the field, never the line.
    state["phase"] = "profile"
    from mpi_and_open_mp_tpu.obs import profile as obs_profile

    prof_fields = {}
    try:
        from mpi_and_open_mp_tpu.ops.life_ops import life_step_roll

        step_cost = obs_profile.cost(
            life_step_roll, jax.ShapeDtypeStruct((NY, NX), np.uint8),
            name="life_step_roll")
        rf = obs_profile.roofline(step_cost["flops"], step_cost["bytes"],
                                  steady / STEPS, device_kind=device_kind)
        rf["model"] = "life_step_roll"
        rf["compile_seconds"] = step_cost["compile_seconds"]
        prof_fields["roofline"] = rf
        obs_profile.record_memory_gauges()
    except Exception as e:  # noqa: BLE001
        prof_fields["roofline_error"] = f"{type(e).__name__}: {e}"[:200]
    if "attention_32k_causal_tflops" in sharded:
        # The attention twin rides only when the fwd timing landed: its
        # FLOPs are exact (2hn²d causal), so the roofline is just the
        # achieved rate over the bf16 peak for this device kind.
        peak_flops, _, _ = obs_profile.peaks_for(device_kind)
        sharded["attention_roofline_pct"] = round(
            100 * sharded["attention_32k_causal_tflops"] * 1e12 / peak_flops,
            3)

    state["phase"] = "report"
    # Sharded-attention engine provenance rides EVERY bench line — CPU
    # fallback and the CI bench-contract run included. The stamps are
    # pure shape analysis over the flagship 32k operands
    # (ShapeDtypeStructs, never device arrays): the forward hop engine,
    # the backward hop engine (ops.flash_hop_bwd vs the
    # _flash_block_grads fold), and the causal-zigzag forward
    # decomposition. Off-chip they honestly read "jnp"/"local:…", and
    # the MOMP_RING_HOP / MOMP_RING_HOP_BWD / MOMP_RING_ZZ escape
    # hatches show up here rather than silently changing the engine.
    from mpi_and_open_mp_tpu.parallel import context as _ctx
    _spec = jax.ShapeDtypeStruct((8, 32 * 1024, 128), jax.numpy.bfloat16)
    sharded["attention_hop_engine"] = _ctx.ring_hop_engine_for(
        _spec, _spec, _spec, causal=True)
    sharded["attention_hop_engine_bwd"] = _ctx.ring_hop_bwd_engine_for(
        _spec, _spec, _spec, causal=True)
    sharded["attention_hop_engine_zz"] = _ctx.ring_hop_engine_for(
        _spec, _spec, _spec, causal=True, layout="zigzag")
    # Trace probe (only when a MOMP_TRACE sink is set): the attention
    # phase above is TPU-only, so a CPU bench run would otherwise produce
    # a trace with no ring spans at all — and the CI trace cycle asserts
    # on exactly those. One tiny ring_attention over the default mesh
    # exercises the traced hop-by-hop dispatch (chaos-free: 2*(p-1) hop
    # spans) or the guarded path (active chaos plan: a recovery event),
    # in milliseconds at this shape. Failures cost a field, never the
    # bench line.
    trace_fields = {}
    if obs_trace.enabled():
        try:
            from mpi_and_open_mp_tpu.parallel import context as _pctx
            from mpi_and_open_mp_tpu.utils.timing import anchor_sync

            p_dev = jax.device_count()
            prng = np.random.default_rng(7)
            h, n, d = 4, 64 * p_dev, 32
            qkv_t = [jax.numpy.asarray(
                prng.standard_normal((h, n, d)), jax.numpy.float32)
                for _ in range(3)]
            anchor_sync(_pctx.ring_attention(*qkv_t, causal=True),
                        fetch_all=True)
            trace_fields["trace_probe"] = f"ring_attention p={p_dev}"
        except Exception as e:
            trace_fields["trace_probe_error"] = (
                f"{type(e).__name__}: {e}"[:200])
    # The registry snapshot rides the line (retraces, hop counts, guard
    # ladder, checkpoint totals) and — when tracing — lands in the trace
    # stream too, so trace_report can summarise retraces offline.
    obs_trace.event("metrics", snapshot=obs_metrics.snapshot())
    metrics_fields = ({"metrics": obs_metrics.snapshot()}
                      if obs_metrics.metrics_on() else {})
    # Self-healed dispatches (robust.guards) must surface in the
    # artifact: a silently recovered engine would launder a fault into a
    # clean-looking measurement line.
    recovered = guards.recovery_log()
    rec = {
        "metric": "life_steady_cups_p46gun_big",
        "value": round(steady_cups, 1),
        "unit": "cell_updates_per_sec",
        "vs_baseline": round(steady_cups / BASELINE_CUPS, 2),
        "end_to_end_sec": round(best, 4),
        "end_to_end_cups": round(cups, 1),
        "end_to_end_vs_baseline": round(cups / BASELINE_CUPS, 2),
        # False = the differencing never beat the base run (non-pallas
        # impl, or a sub-RTT anomaly): value is then the end-to-end rate,
        # not a true marginal per-step rate — don't compare across kinds.
        "steady_is_differenced": differenced,
        "backend": jax.default_backend(),
        "impl": sim.impl,
        # Workload + provenance stamps: the run-ledger configuration key
        # (obs.ledger) and the sentinel's downgrade comparison both read
        # these, so they ride EVERY line, fallback included.
        "board": [NY, NX],
        "steps": STEPS,
        "dtype": "uint8",
        "workload": "life",
        "platform": platform,
        "device_kind": device_kind,
        "devices": jax.device_count(),
        # True whenever the watchdog degraded the run to CPU — the
        # machine-readable twin of backend_fallback.
        "degraded": res.degraded,
        # Plan provenance rides EVERY line like the engine stamps
        # (CPU-fallback lines included): heuristic unless the autotune
        # phase overrides it via **tuned below.
        "plan_source": "heuristic",
        **({"recovered": recovered} if recovered else {}),
        **ckpt_fields,
        **batched,
        **tuned,
        **served,
        **sparse,
        **sharded_ab,
        **ring_ab,
        **sparse_sharded,
        **radius_ab,
        **sharded,
        **prof_fields,
        **trace_fields,
        **metrics_fields,
        **backend_note,
    }
    print(json.dumps(rec))
    _ledger_append(args.ledger, rec, platform=platform,
                   device_kind=device_kind,
                   device_count=jax.device_count())
    return 0


if __name__ == "__main__":
    sys.exit(main())
