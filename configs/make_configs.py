"""Generate the benchmark/fixture config suite (deterministic).

The reference ships a graded set of ``.cfg`` workloads (SURVEY C18): an
empty board, a glider, a small still-life mix, a big oscillator, a gun with
per-step saves, and the headline ``p46gun_big`` scaling config. This script
writes this framework's own equivalents (fresh patterns, same file format
and roles). Run: ``python configs/make_configs.py``.
"""

import os

import numpy as np

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpi_and_open_mp_tpu.utils.config import LifeConfig, save_config

HERE = os.path.dirname(os.path.abspath(__file__))

GLIDER = [(0, 2), (1, 0), (1, 2), (2, 1), (2, 2)]

# Gosper glider gun (36 cells, period 30) — the classic public pattern;
# plays the reference's p46 Twin-bees-shuttle role of a growing workload.
GOSPER_GUN = [
    (0, 4), (0, 5), (1, 4), (1, 5),
    (10, 4), (10, 5), (10, 6), (11, 3), (11, 7), (12, 2), (12, 8),
    (13, 2), (13, 8), (14, 5), (15, 3), (15, 7), (16, 4), (16, 5),
    (16, 6), (17, 5),
    (20, 2), (20, 3), (20, 4), (21, 2), (21, 3), (21, 4), (22, 1),
    (22, 5), (24, 0), (24, 1), (24, 5), (24, 6),
    (34, 2), (34, 3), (35, 2), (35, 3),
]

# Pulsar (period 3, 48 cells) — one 12-cell quadrant reflected 4 ways:
# horizontal triples at dy in {1, 6}, vertical triples at dx in {1, 6}.
_PULSAR_QUAD = [
    (2, 1), (3, 1), (4, 1), (2, 6), (3, 6), (4, 6),
    (1, 2), (1, 3), (1, 4), (6, 2), (6, 3), (6, 4),
]


def pulsar_cells(cx: int, cy: int):
    cells = set()
    for dx, dy in _PULSAR_QUAD:
        for sx in (1, -1):
            for sy in (1, -1):
                cells.add((cx + sx * dx, cy + sy * dy))
    return sorted(cells)


def offset(cells, dx, dy):
    return [(i + dx, j + dy) for i, j in cells]


def write(name, steps, save_steps, nx, ny, cells):
    cfg = LifeConfig(steps, save_steps, nx, ny,
                     np.array(sorted(set(cells)), dtype=np.int64).reshape(-1, 2)
                     if cells else np.zeros((0, 2), dtype=np.int64))
    save_config(os.path.join(HERE, name), cfg)
    print(f"{name}: {nx}x{ny}, {steps} steps, {len(cfg.cells)} cells")


def main():
    # Empty smoke board (role of test.cfg).
    write("test_10x10.cfg", 100, 1, 10, 10, [])
    # Glider on a small torus (periodic-boundary exerciser).
    write("glider_10x10.cfg", 100, 1, 10, 10, GLIDER)
    # Small mixed still-lifes/oscillators on 40x20 (role of conf1.cfg):
    # block, beehive, blinker, glider.
    mix = ([(2, 2), (3, 2), (2, 3), (3, 3)]              # block
           + [(10, 3), (11, 2), (12, 2), (13, 3), (12, 4), (11, 4)]  # beehive
           + [(20, 10), (21, 10), (22, 10)]               # blinker
           + offset(GLIDER, 28, 12))
    write("mix_40x20.cfg", 100, 10, 40, 20, mix)
    # Big oscillator field: 8x8 pulsars tiled on 500x500 (role of big_osc).
    cells = []
    for ty in range(8):
        for tx in range(8):
            cells += pulsar_cells(60 + tx * 48, 60 + ty * 48)
    write("pulsar_field_500x500.cfg", 50, 10, 500, 500, cells)
    # Gun with per-step saves (role of p46gun.cfg).
    write("gun_300x100.cfg", 1000, 1, 300, 100, offset(GOSPER_GUN, 20, 40))
    # Headline scaling benchmark (role of p46gun_big.cfg): 500x500, 10k
    # steps, saves disabled. Content: the gun plus a deterministic soup so
    # the board stays lively at full density.
    rng = np.random.default_rng(46)
    soup = np.argwhere(rng.random((500, 500)) < 0.3)  # (j, i) pairs
    soup_cells = [(int(i), int(j)) for j, i in soup]
    write("gun_big_500x500.cfg", 10000, 999999, 500, 500,
          offset(GOSPER_GUN, 20, 240) + soup_cells)


if __name__ == "__main__":
    main()
