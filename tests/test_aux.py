"""Aux subsystems: checkpoint/resume, halo debug mode, profiler hook."""

import os

import numpy as np
import pytest

from mpi_and_open_mp_tpu.apps import life as life_app
from mpi_and_open_mp_tpu.models.life import LifeSim
from mpi_and_open_mp_tpu.utils.config import config_from_board

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


from conftest import oracle_n  # noqa: E402


def test_resume_from_snapshot_bit_exact(tmp_path, make_board):
    """Run to completion in one go vs. interrupted-and-resumed: identical."""
    board = make_board(32, 40)
    cfg = config_from_board(board, steps=40, save_steps=10)
    out_a = tmp_path / "a"
    full = LifeSim(cfg, layout="row", impl="halo", outdir=out_a).run()

    # Interrupted run: stop after 25 steps (last snapshot at 20).
    out_b = tmp_path / "b"
    sim = LifeSim(cfg, layout="row", impl="halo", outdir=out_b)
    i = 0
    while i < 25:
        if i % cfg.save_steps == 0:
            sim.save_snapshot()
        n = min(cfg.save_steps - i % cfg.save_steps, 25 - i)
        sim.step(n)
        i += n
    latest = life_app.find_latest_snapshot(str(out_b))
    assert latest is not None and latest[1] == 20
    resumed = LifeSim.from_snapshot(
        cfg, latest[0], latest[1], layout="cart", impl="halo", outdir=out_b
    )
    final = resumed.run()
    np.testing.assert_array_equal(final, full)
    np.testing.assert_array_equal(final, oracle_n(board, 40))
    # Resumed run wrote the step-30 snapshot the interrupted run missed.
    assert os.path.exists(out_b / "life_000030.vtk")


def test_resume_cli(tmp_path, capsys, make_board):
    cfg_path = os.path.join(FIXTURES, "glider_10x10.cfg")
    outdir = tmp_path / "vtk"
    assert life_app.main([cfg_path, "--layout", "serial", "--impl", "roll",
                          "--outdir", str(outdir)]) == 0
    capsys.readouterr()
    rc = life_app.main([cfg_path, "--layout", "serial", "--impl", "roll",
                        "--outdir", str(outdir), "--resume"])
    assert rc == 0
    cap = capsys.readouterr()
    assert "resuming from" in cap.err and "life_000075.vtk" in cap.err


def test_resume_cli_no_snapshots(tmp_path, capsys):
    rc = life_app.main([os.path.join(FIXTURES, "glider_10x10.cfg"),
                        "--outdir", str(tmp_path / "none"), "--resume"])
    assert rc == 2


def test_debug_check_passes_and_fails(make_board):
    board = make_board(48, 40)
    cfg = config_from_board(board, steps=4, save_steps=0)
    sim = LifeSim(cfg, layout="cart", impl="halo", fuse_steps=2)
    sim.debug_check()  # must hold on a healthy pipeline
    sim.step(3)
    sim.debug_check()  # and at any intermediate state

    # Sabotage: a wrong advance must be caught.
    healthy = sim._advance
    sim._advance = lambda b, n: healthy(b, n + 1)
    with pytest.raises(AssertionError, match="diverge"):
        sim.debug_check()


def test_profile_flag_writes_trace(tmp_path, capsys):
    prof = tmp_path / "trace"
    rc = life_app.main([os.path.join(FIXTURES, "glider_10x10.cfg"),
                        "--layout", "serial", "--impl", "roll",
                        "--profile", str(prof)])
    assert rc == 0
    # jax.profiler.trace writes plugins/profile/<ts>/*.
    found = list(prof.rglob("*.xplane.pb")) + list(prof.rglob("*.trace.json.gz"))
    assert found, f"no trace artifacts under {prof}"
