"""Cross-run perf ledger, regression sentinel, and artifact backfill.

The contract under test: every bench line lands in the append-only
ledger with enough provenance (git SHA, platform, device kind, topology,
configuration key) that ``analysis/regression_sentinel.py`` can judge a
new run against its own history — flagging steady-rate drops past the
noise floor and engine/backend downgrades (pallas→jnp, TPU→CPU) with a
non-zero exit, while passing identical runs and first-of-a-kind
configurations. The BENCH_r04/r05 CPU-fallback lines recorded a ~1000×
regression with nothing watching; these tests pin the machinery that
makes that a one-command verdict, including on the committed backfilled
ledger where the sentinel must retroactively flag exactly that round.
"""

import json
import os
import sys

import pytest

from mpi_and_open_mp_tpu.obs import ledger

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "analysis"))

import ledger_backfill  # noqa: E402
import regression_sentinel  # noqa: E402


def _entry(value=100.0, *, ts, impl="pallas", platform="tpu",
           source="synthetic", extra=None, record=None):
    """One ledger entry around a minimal flagship-shaped bench record."""
    rec = {
        "metric": "life_steady_cups_p46gun_big",
        "value": value,
        "unit": "cell_updates_per_sec",
        "board": [500, 500],
        "steps": 10_000,
        "dtype": "uint8",
        "backend": platform,
        "impl": impl,
    }
    if extra:
        rec.update(extra)
    if record is not None:
        rec = record
    return ledger.stamp(rec, source=source, platform=platform,
                        device_kind="test-kind", device_count=1,
                        ts=ts, sha="feedcafe")


# ------------------------------------------------------------------ ledger


def test_stamp_schema_and_config_key():
    e = _entry(123.0, ts=10.0)
    assert e["schema"] == "momp-ledger/1"
    assert e["ts"] == 10.0 and e["git_sha"] == "feedcafe"
    assert e["platform"] == "tpu" and e["topology"] == "tpu:1"
    assert e["device_kind"] == "test-kind"
    assert e["key"] == {
        "metric": "life_steady_cups_p46gun_big", "topology": "tpu:1",
        "shape": "500x500", "dtype": "uint8", "steps": 10_000,
        "batch": 0, "batch_pack_layout": "-", "resident": "-",
        "workload": "life", "plan": "-", "halo": "-", "sparse": "-",
        "engine_family": "-", "engine": "pallas",
    }
    # Full key renders in canonical order; any subset stays stable.
    full = ledger.config_key(e)
    assert full.startswith("metric=life_steady_cups_p46gun_big|")
    assert "topology=tpu:1" in full and "engine=pallas" in full
    assert ledger.config_key(e, ("shape", "dtype")) == "shape=500x500|dtype=uint8"


def test_stamp_falls_back_to_record_provenance():
    """Backfilled lines carry their own backend; omitted stamps must not
    invent provenance the artifact never recorded."""
    rec = {"metric": "m", "backend": "tpu", "impl": "roll"}
    e = ledger.stamp(rec, source="backfill:x", ts=1.0, sha="s")
    assert e["platform"] == "tpu"
    assert e["device_kind"] == "unrecorded"
    assert e["key"]["shape"] == "?"


def test_append_load_query_roundtrip(tmp_path):
    path = str(tmp_path / "sub" / "ledger.jsonl")  # parent dirs created
    a = _entry(1.0, ts=1.0)
    b = _entry(2.0, ts=2.0, impl="roll", platform="cpu")
    ledger.append(a, path)
    ledger.append(b, path)
    got = ledger.load(path)
    assert got == [a, b]
    assert ledger.query(got, engine="roll") == [b]
    assert ledger.query(got, topology="tpu:1", engine="pallas") == [a]
    assert ledger.query(got, metric="nope") == []


@pytest.mark.parametrize("line", ["not json {", '{"no_record": true}'])
def test_load_rejects_malformed_lines(tmp_path, line):
    path = tmp_path / "bad.jsonl"
    path.write_text(json.dumps(_entry(1.0, ts=1.0)) + "\n" + line + "\n")
    with pytest.raises(ValueError, match="bad.jsonl:2"):
        ledger.load(str(path))


# ---------------------------------------------------------------- sentinel


def _run_main(tmp_path, entries, *argv):
    path = str(tmp_path / "ledger.jsonl")
    for e in entries:
        ledger.append(e, path)
    return regression_sentinel.main([path, *argv])


def test_sentinel_passes_identical_runs(tmp_path, capsys):
    entries = [_entry(100.0, ts=float(i)) for i in range(4)]
    assert _run_main(tmp_path, entries) == 0
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["verdict"] == "pass"
    assert verdict["baseline_n"] == 3
    assert verdict["regressions"] == [] and verdict["downgrades"] == []
    assert "value" in verdict["checked"]


def test_sentinel_flags_cups_drop(tmp_path, capsys):
    entries = [_entry(100.0, ts=float(i)) for i in range(5)]
    entries.append(_entry(80.0, ts=5.0))  # 20% drop vs noise floor 10%
    assert _run_main(tmp_path, entries, "--noise", "0.1") == 1
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["verdict"] == "fail"
    (reg,) = verdict["regressions"]
    assert reg["field"] == "value" and reg["baseline_median"] == 100.0
    assert reg["drop"] == pytest.approx(0.2)


def test_sentinel_drop_within_noise_floor_passes(tmp_path):
    entries = [_entry(100.0, ts=float(i)) for i in range(3)]
    entries.append(_entry(95.0, ts=3.0))  # 5% < the 10% default floor
    assert _run_main(tmp_path, entries) == 0


def test_sentinel_flags_engine_and_platform_downgrade(tmp_path, capsys):
    """The BENCH_r04/r05 shape: same workload key, value intact, but the
    run fell to CPU and the dense fold — both downgrades must fail the
    verdict and the fallback WHY must survive into it."""
    entries = [_entry(100.0, ts=float(i)) for i in range(3)]
    entries.append(_entry(
        100.0, ts=3.0, impl="roll", platform="cpu",
        extra={"fallback_reason": "discovery hung; probe abandoned"}))
    assert _run_main(tmp_path, entries) == 1
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["verdict"] == "fail" and verdict["regressions"] == []
    fields = {d["field"]: d for d in verdict["downgrades"]}
    assert fields["platform"]["new"] == "cpu"
    assert fields["platform"]["baseline_best"] == "tpu"
    assert fields["platform"]["fallback_reason"].startswith("discovery hung")
    assert fields["impl"]["new"] == "roll"
    assert fields["impl"]["baseline_best"] == "pallas"


def test_sentinel_no_baseline_and_key_isolation(tmp_path, capsys):
    """A first-of-a-kind configuration has nothing to regress against —
    and entries of a DIFFERENT workload key must not become its baseline."""
    other = _entry(1.0, ts=0.0,
                   extra={"board": [64, 64], "steps": 100})
    fresh = _entry(100.0, ts=1.0)
    assert _run_main(tmp_path, [other, fresh]) == 0
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["verdict"] == "no-baseline"
    assert verdict["baseline_n"] == 0


def test_sentinel_skips_error_records(tmp_path, capsys):
    """A crashed run's error line is not a candidate (nothing to judge)
    and not a baseline (its rates never existed)."""
    entries = [_entry(100.0, ts=0.0), _entry(100.0, ts=1.0)]
    entries.append(_entry(0.0, ts=2.0,
                          record={"error": "boom", "phase": "measure",
                                  "metric": "life_steady_cups_p46gun_big",
                                  "board": [500, 500], "steps": 10_000,
                                  "dtype": "uint8", "impl": "pallas"}))
    assert _run_main(tmp_path, entries) == 0
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["verdict"] == "pass"
    assert verdict["candidate_ts"] == 1.0  # the newest NON-error entry


def test_sentinel_unreadable_ledger_exits_2(tmp_path, capsys):
    path = tmp_path / "broken.jsonl"
    path.write_text("junk\n")
    assert regression_sentinel.main([str(path)]) == 2
    assert regression_sentinel.main([str(tmp_path / "missing.jsonl")]) == 2


def test_engine_rank_tiers():
    rank = regression_sentinel.engine_rank
    assert rank("pallas:vmem") == 3
    assert rank("batch:pallas:b1024") == 3
    assert rank("bitfused") == 2 and rank("frame") == 2
    assert rank("local:jnp") == 1 and rank("roll") == 1
    assert rank("jnp") == 1 and rank("batch:xla") == 1
    assert rank(None) == 0 and rank("") == 0


def test_direction_for_name_keying():
    """Polarity comes from the metric NAME: rates are higher-is-better
    even when they end in ``_sec``; latencies and badness counters are
    lower-is-better."""
    d = regression_sentinel.direction_for
    assert d("value") == "higher"
    assert d("batched_cups") == "higher"
    assert d("serve_requests_per_sec") == "higher"  # NOT the _sec rule
    assert d("batched_requests_per_sec") == "higher"
    assert d("attention_32k_grad_tflops") == "higher"
    assert d("attention_32k_causal_sec") == "lower"
    assert d("serve_p50_latency_s") == "lower"
    assert d("serve_p99_latency_s") == "lower"
    assert d("serve_shed") == "lower"
    assert d("serve_degraded") == "lower"
    # The WAL durability-tax fields: swelling journal volume or sync
    # stall is the regression.
    assert d("serve_wal_bytes") == "lower"
    assert d("serve_wal_fsync_s") == "lower"


def test_sentinel_flags_p99_inflation(tmp_path, capsys):
    """Higher-is-WORSE: a serve p99 that grows past the noise floor must
    fail even with every throughput field flat."""
    entries = [_entry(100.0, ts=float(i),
                      extra={"serve_p99_latency_s": 0.05}) for i in range(3)]
    entries.append(_entry(100.0, ts=3.0,
                          extra={"serve_p99_latency_s": 0.12}))
    assert _run_main(tmp_path, entries, "--noise", "0.1") == 1
    verdict = json.loads(capsys.readouterr().out)
    (reg,) = verdict["regressions"]
    assert reg["field"] == "serve_p99_latency_s"
    assert reg["direction"] == "lower" and reg["baseline_median"] == 0.05
    assert reg["drop"] == pytest.approx(1.4)  # (0.12-0.05)/0.05


def test_sentinel_p99_improvement_and_rate_drop(tmp_path, capsys):
    """Both directions, same ledger: a p99 that SHRINKS passes; a
    requests/sec rate that drops fails under the throughput polarity."""
    entries = [_entry(100.0, ts=float(i),
                      extra={"serve_p99_latency_s": 0.05,
                             "serve_requests_per_sec": 200.0})
               for i in range(3)]
    entries.append(_entry(100.0, ts=3.0,
                          extra={"serve_p99_latency_s": 0.01,
                                 "serve_requests_per_sec": 210.0}))
    assert _run_main(tmp_path, entries) == 0

    entries.append(_entry(100.0, ts=4.0,
                          extra={"serve_p99_latency_s": 0.05,
                                 "serve_requests_per_sec": 120.0}))
    assert _run_main(tmp_path, entries) == 1
    verdict = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    fields = {r["field"]: r for r in verdict["regressions"]}
    assert "serve_requests_per_sec" in fields
    assert fields["serve_requests_per_sec"]["direction"] == "higher"
    # The shrunken p99 must not register as a regression either way.
    assert "serve_p99_latency_s" not in fields


# ---------------------------------------------------------------- backfill


def _fake_root(tmp_path):
    root = tmp_path / "root"
    (root / "results").mkdir(parents=True)
    # r01-era wrapper: the OLD schema (end-to-end value as "value",
    # steady rate under "steady_state_cups") + a jax warning in the tail.
    (root / "BENCH_r01.json").write_text(json.dumps({
        "n": 1,
        "parsed": {
            "metric": "life_cups_p46gun_big", "value": 9.0e8,
            "unit": "cell_updates_per_sec", "vs_baseline": 0.7,
            "steady_state_cups": 1.2e9, "steady_state_vs_baseline": 0.93,
            "elapsed_sec": 2.78, "backend": "tpu", "impl": "pallas",
        },
        "tail": "W0000 2026-07-20 10:30:00 something happened",
    }))
    (root / "results" / "bench_tpu_r05.jsonl").write_text(json.dumps({
        "metric": "life_steady_cups_p46gun_big", "value": 1.3e12,
        "unit": "cell_updates_per_sec", "vs_baseline": 1000.0,
        "end_to_end_sec": 0.4, "end_to_end_cups": 6.2e9,
        "end_to_end_vs_baseline": 4.8, "steady_is_differenced": True,
        "backend": "tpu", "impl": "pallas",
    }) + "\n")
    return root


def test_backfill_normalises_old_schema_and_is_idempotent(
        tmp_path, capsys):
    root = _fake_root(tmp_path)
    assert ledger_backfill.main(["--root", str(root)]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["backfilled"] == 2 and out["skipped"] == 0
    entries = ledger.load(out["ledger"])
    assert [e["source"] for e in entries] == [
        "backfill:BENCH_r01.json",
        "backfill:results/bench_tpu_r05.jsonl#L1"]
    old, new = entries
    # The r01 line: renamed onto the current schema, honestly marked.
    assert old["record"]["metric"] == "life_steady_cups_p46gun_big"
    assert old["record"]["value"] == 1.2e9
    assert old["record"]["end_to_end_cups"] == 9.0e8
    assert old["record"]["backfill_normalized"] is True
    assert old["key"]["shape"] == "500x500" and old["key"]["steps"] == 10_000
    assert old["git_sha"] == "pre-ledger"
    # ts extracted from the wrapper tail's warning timestamp.
    import calendar
    import time as _time
    assert old["ts"] == calendar.timegm(
        _time.strptime("2026-07-20 10:30:00", "%Y-%m-%d %H:%M:%S"))
    # The r05 line: current schema passes through un-renamed.
    assert "backfill_normalized" not in new["record"]
    assert new["record"]["value"] == 1.3e12
    # Second run: every source already present, nothing appended.
    assert ledger_backfill.main(["--root", str(root)]) == 0
    out2 = json.loads(capsys.readouterr().out)
    assert out2["backfilled"] == 0 and out2["skipped"] == 2
    assert len(ledger.load(out["ledger"])) == 2


def test_committed_ledger_retro_flags_the_r05_fallback(capsys):
    """The committed backfilled ledger is load-bearing: its newest entry
    is the r05 CPU-fallback driver line, so the sentinel must
    retroactively flag exactly the regression that round recorded
    silently — the value collapse AND both provenance downgrades."""
    path = os.path.join(REPO, "results", "ledger.jsonl")
    entries = ledger.load(path)
    assert len(entries) >= 8
    assert all(e["git_sha"] == "pre-ledger" for e in entries)
    assert regression_sentinel.main([path]) == 1
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["verdict"] == "fail"
    assert {r["field"] for r in verdict["regressions"]} == {"value"}
    assert {d["field"] for d in verdict["downgrades"]} == {"platform",
                                                           "impl"}


# ------------------------------------------------------- bench integration


def test_bench_cpu_line_carries_roofline_and_lands_in_ledger(
        tmp_path, capsys, monkeypatch):
    """The CPU-fallback bench line (probe stubbed to fail — the suite
    never touches a real chip) must carry the new provenance stamps, the
    machine-readable fallback_reason, finite roofline fields, and land in
    the --ledger file as one well-keyed entry the sentinel can read."""
    import math

    sys.path.insert(0, REPO)
    import bench

    monkeypatch.setattr(
        bench, "_probe_devices",
        lambda timeout_s: (False, "stubbed: probe denied"))
    lpath = str(tmp_path / "ledger.jsonl")
    rc = bench.main(["--board", "64", "--steps", "64", "--ledger", lpath])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])

    assert rec["platform"] == "cpu" and rec["backend"] == "cpu"
    assert isinstance(rec["device_kind"], str) and rec["device_kind"]
    assert rec["board"] == [64, 64] and rec["steps"] == 64
    assert rec["dtype"] == "uint8"
    assert rec["fallback_reason"].startswith("stubbed: probe denied")

    rf = rec["roofline"]
    for field in ("flops_per_step", "bytes_per_step", "flops_per_sec",
                  "bytes_per_sec", "flops_pct", "bw_pct", "roofline_pct",
                  "compile_seconds"):
        assert isinstance(rf[field], (int, float)) and math.isfinite(
            rf[field]), (field, rf)
    assert rf["bound"] in ("compute", "memory")
    assert rf["model"] == "life_step_roll"

    cache = [k for k in rec["metrics"]["counters"]
             if k.startswith("profile.cost_cache{")]
    assert cache, rec["metrics"]["counters"]
    gauges = rec["metrics"]["gauges"]
    assert gauges.get("memory.live_buffer_bytes", 0) >= 0
    assert "memory.live_buffer_watermark_bytes" in gauges

    (entry,) = ledger.load(lpath)
    assert entry["source"] == "bench.py"
    assert entry["platform"] == "cpu"
    assert entry["key"]["shape"] == "64x64" and entry["key"]["steps"] == 64
    assert entry["record"]["value"] == rec["value"]


def test_bench_ledger_append_failure_never_costs_the_line(
        tmp_path, capsys, monkeypatch):
    """Ledger IO is best-effort by contract: an unwritable path must cost
    a stderr note only — same line, same exit code."""
    sys.path.insert(0, REPO)
    import bench

    monkeypatch.setattr(
        bench, "_probe_devices",
        lambda timeout_s: (False, "stubbed: probe denied"))
    bad = str(tmp_path / "ledger_as_dir")
    os.makedirs(bad)  # open(path, "a") on a directory raises
    rc = bench.main(["--board", "64", "--steps", "64", "--ledger", bad])
    assert rc == 0
    out = capsys.readouterr()
    rec = json.loads(out.out.strip().splitlines()[-1])
    assert rec["value"] > 0
    assert "ledger append failed" in out.err
