"""Unit-level pins for the packed wrap-halo exchanges.

The LifeSim parity suites prove the packed paths end to end; these tests
pin the exchange layer itself: for every shard, the halo-extended window
``packed_halo_y``/``packed_halo_x`` builds must equal the corresponding
slice of the board's INFINITE PERIODIC TILING (the invariant the fused
kernels rely on — ops/bitlife.py module docs). A regression in the
funnel offsets or mirror refresh shows up here as the exact wrong rows,
not as a far-downstream cell diff.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from conftest import random_board

from mpi_and_open_mp_tpu.ops import bitlife
from mpi_and_open_mp_tpu.parallel import halo, mesh as mesh_lib


def _frame_rows(board, Nyp):
    """The padded frame's row content: board rows then mirror rows."""
    ny = board.shape[0]
    return np.concatenate([board, board[: Nyp - ny]], axis=0)


def test_packed_halo_y_periodic_extension():
    ny, nx, py = 230, 64, 4  # Nyp=256, pad_y=26, nw_s=2 -> h=1
    plan = bitlife.plan_sharded_bits((ny, nx), py, 1, True, False)
    assert plan.pad_y == 26 and plan.h == 1
    board = random_board(np.random.default_rng(3), ny, nx)
    frame = np.zeros((plan.frame[0], nx), np.uint8)
    frame[:ny] = board
    mesh = mesh_lib.make_mesh_1d(py, axis="y")
    packed = jax.device_put(
        bitlife.pack_board_exact(jnp.asarray(frame)),
        NamedSharding(mesh, P("y", None)),
    )
    ext = jax.jit(mesh_lib.shard_map(
        lambda q: halo.packed_halo_y(q, "y", plan.h, pad=plan.pad_y),
        mesh=mesh, in_specs=P("y", None), out_specs=P("y", None),
        check_vma=False,
    ))(packed)
    ext = np.asarray(bitlife.unpack_board_exact(jax.device_get(ext)))

    S, hrows = 32 * plan.nw_s, 32 * plan.h
    frows = _frame_rows(board, plan.frame[0])
    win = S + 2 * hrows
    for i in range(py):
        got = ext[i * win : (i + 1) * win]
        top = (board[ny - hrows : ny] if i == 0
               else frows[i * S - hrows : i * S])
        bot = (board[plan.pad_y : plan.pad_y + hrows] if i == py - 1
               else frows[(i + 1) * S : (i + 1) * S + hrows])
        want = np.concatenate([top, frows[i * S : (i + 1) * S], bot])
        assert np.array_equal(got, want), f"shard {i}"


def test_packed_halo_x_periodic_extension():
    ny, nx, px = 64, 460, 4  # narrow re-pitch: W=120, pad_x=20, hx=100
    plan = bitlife.plan_sharded_bits((ny, nx), 1, px, False, True)
    assert plan.pad_x > 0 and plan.x_sharded
    board = random_board(np.random.default_rng(5), ny, nx)
    frame = np.zeros((ny, plan.frame[1]), np.uint8)
    frame[:, :nx] = board
    mesh = mesh_lib.make_mesh_1d(px, axis="x")
    packed = jax.device_put(
        bitlife.pack_board_exact(jnp.asarray(frame)),
        NamedSharding(mesh, P(None, "x")),
    )
    ext = jax.jit(mesh_lib.shard_map(
        lambda q: halo.packed_halo_x(q, "x", plan.hx, pad=plan.pad_x),
        mesh=mesh, in_specs=P(None, "x"), out_specs=P(None, "x"),
        check_vma=False,
    ))(packed)
    ext = np.asarray(bitlife.unpack_board_exact(jax.device_get(ext)))

    W, hx = plan.W, plan.hx
    fcols = np.concatenate([board, board[:, : plan.pad_x]], axis=1)
    wcols = W + 2 * hx
    for i in range(px):
        got = ext[:, i * wcols : (i + 1) * wcols]
        left = (board[:, nx - hx : nx] if i == 0
                else fcols[:, i * W - hx : i * W])
        right = (board[:, plan.pad_x : plan.pad_x + hx] if i == px - 1
                 else fcols[:, (i + 1) * W : (i + 1) * W + hx])
        want = np.concatenate(
            [left, fcols[:, i * W : (i + 1) * W], right], axis=1)
        assert np.array_equal(got, want), f"shard {i}"


def test_packed_halo_degenerates_to_plain_pad_when_aligned():
    """pad=0 must route through the plain halo_pad_* word/column rings."""
    board = random_board(np.random.default_rng(8), 256, 128)
    mesh = mesh_lib.make_mesh_1d(4, axis="y")
    packed = jax.device_put(
        bitlife.pack_board_exact(jnp.asarray(board)),
        NamedSharding(mesh, P("y", None)),
    )

    def both(q):
        a = halo.packed_halo_y(q, "y", 2, pad=0)
        b = halo.halo_pad_y(q, "y", 2)
        return a, b

    a, b = jax.jit(mesh_lib.shard_map(
        both, mesh=mesh, in_specs=P("y", None),
        out_specs=(P("y", None), P("y", None)), check_vma=False,
    ))(packed)
    assert np.array_equal(np.asarray(a), np.asarray(b))

    mesh_x = mesh_lib.make_mesh_1d(4, axis="x")
    packed_x = jax.device_put(
        bitlife.pack_board_exact(jnp.asarray(board)),
        NamedSharding(mesh_x, P(None, "x")),
    )

    def both_x(q):
        a = halo.packed_halo_x(q, "x", 16, pad=0)
        b = halo.halo_pad_x(q, "x", 16)
        return a, b

    a, b = jax.jit(mesh_lib.shard_map(
        both_x, mesh=mesh_x, in_specs=P(None, "x"),
        out_specs=(P(None, "x"), P(None, "x")), check_vma=False,
    ))(packed_x)
    assert np.array_equal(np.asarray(a), np.asarray(b))
