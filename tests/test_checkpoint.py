"""Orbax checkpoint/restore: sharded save, mesh-shape-agnostic restore."""

import numpy as np

from conftest import oracle_n
from mpi_and_open_mp_tpu.models.life import LifeSim
from mpi_and_open_mp_tpu.utils.config import config_from_board


def test_checkpoint_roundtrip_across_meshes(tmp_path, make_board):
    """Save on a row mesh mid-run; restore onto a cart mesh; finish; the
    result must equal an uninterrupted run and the oracle."""
    board = make_board(48, 40)
    cfg = config_from_board(board, steps=30, save_steps=0)

    sim = LifeSim(cfg, layout="row", impl="halo")
    sim.step(17)
    ckpt = tmp_path / "ckpt"
    sim.save_checkpoint(ckpt)

    resumed = LifeSim.from_checkpoint(ckpt, cfg, layout="cart", impl="halo")
    assert resumed.step_count == 17
    final = resumed.run(save=False)
    np.testing.assert_array_equal(final, oracle_n(board, 30))


def test_cli_checkpoint_and_resume(tmp_path, capsys, make_board):
    import os

    from mpi_and_open_mp_tpu.apps import life as life_app
    from mpi_and_open_mp_tpu.utils.config import save_config

    board = make_board(16, 16)
    cfg = config_from_board(board, steps=20, save_steps=5)
    cfg_path = tmp_path / "run.cfg"
    save_config(cfg_path, cfg)
    out = tmp_path / "vtk"
    ck = tmp_path / "ck"
    rc = life_app.main([str(cfg_path), "--layout", "row", "--outdir", str(out),
                        "--checkpoint-dir", str(ck)])
    assert rc == 0
    assert sorted(os.listdir(ck)) == [f"step_{i:06d}" for i in (0, 5, 10, 15)]
    capsys.readouterr()
    rc = life_app.main([str(cfg_path), "--layout", "cart", "--outdir", str(out),
                        "--checkpoint-dir", str(ck), "--resume"])
    assert rc == 0
    assert "resuming from checkpoint" in capsys.readouterr().err


def test_cli_checkpoint_only_no_outdir(tmp_path, capsys, make_board):
    """--checkpoint-dir without --outdir must still write checkpoints."""
    import os

    from mpi_and_open_mp_tpu.apps import life as life_app
    from mpi_and_open_mp_tpu.utils.config import save_config

    cfg = config_from_board(make_board(16, 16), steps=10, save_steps=5)
    cfg_path = tmp_path / "run.cfg"
    save_config(cfg_path, cfg)
    ck = tmp_path / "ck"
    rc = life_app.main([str(cfg_path), "--layout", "row",
                        "--checkpoint-dir", str(ck)])
    assert rc == 0
    assert sorted(os.listdir(ck)) == ["step_000000", "step_000005"]
    capsys.readouterr()
    rc = life_app.main([str(cfg_path), "--layout", "row",
                        "--checkpoint-dir", str(ck), "--resume"])
    assert rc == 0
    assert "resuming from checkpoint" in capsys.readouterr().err


def test_resume_prefers_newest_state(tmp_path, capsys, make_board):
    """A stale checkpoint dir must not roll back past newer VTK snapshots."""
    import os

    from mpi_and_open_mp_tpu.apps import life as life_app
    from mpi_and_open_mp_tpu.utils.config import save_config

    cfg = config_from_board(make_board(16, 16), steps=20, save_steps=5)
    cfg_path = tmp_path / "run.cfg"
    save_config(cfg_path, cfg)
    out, ck = tmp_path / "vtk", tmp_path / "ck"
    # Short run writes one stale checkpoint at step 0.
    sim = LifeSim(config_from_board(make_board(16, 16), 1, 1),
                  layout="row", checkpoint_dir=ck)
    sim.save_checkpoint(ck / "step_000000")
    # Full run writes VTK snapshots to step 15.
    rc = life_app.main([str(cfg_path), "--layout", "row", "--outdir", str(out)])
    assert rc == 0
    capsys.readouterr()
    rc = life_app.main([str(cfg_path), "--layout", "row", "--outdir", str(out),
                        "--checkpoint-dir", str(ck), "--resume"])
    assert rc == 0
    err = capsys.readouterr().err
    assert "life_000015.vtk (step 15)" in err  # snapshot won over stale ckpt


def test_checkpoint_uneven_board(tmp_path, make_board):
    """Padded storage round-trips: the checkpoint holds the padded array,
    restore crops to the logical shape."""
    board = make_board(50, 37)
    cfg = config_from_board(board, steps=10, save_steps=0)
    sim = LifeSim(cfg, layout="row", impl="roll")
    sim.step(4)
    ckpt = tmp_path / "ckpt"
    sim.save_checkpoint(ckpt)
    resumed = LifeSim.from_checkpoint(ckpt, cfg, layout="col", impl="roll")
    final = resumed.run(save=False)
    np.testing.assert_array_equal(final, oracle_n(board, 10))


def test_checkpoint_restore_onto_2x4_and_single_device(tmp_path, make_board):
    """Save mid-run on the 1x8 row mesh; restore onto a 2x4 cart mesh AND
    onto a single device (serial) — both finish bit-identical to the
    oracle. The mesh-shape-agnostic restore contract, explicitly."""
    from mpi_and_open_mp_tpu.parallel import mesh as mesh_lib

    board = make_board(48, 40)
    cfg = config_from_board(board, steps=100, save_steps=0)
    sim = LifeSim(cfg, layout="row", impl="halo",
                  mesh=mesh_lib.make_mesh_1d(8, axis="y"))
    sim.step(60)
    ck = tmp_path / "ck"
    sim.save_checkpoint(ck)

    cart = LifeSim.from_checkpoint(ck, cfg, layout="cart", impl="halo",
                                   mesh=mesh_lib.make_mesh_2d(2, 4))
    np.testing.assert_array_equal(cart.run(save=False), oracle_n(board, 100))
    serial = LifeSim.from_checkpoint(ck, cfg, layout="serial", impl="roll")
    np.testing.assert_array_equal(serial.run(save=False),
                                  oracle_n(board, 100))


def test_resume_mid_run_bit_identity_vs_straight(tmp_path, make_board):
    """100 straight steps vs 60 + checkpoint + restore + 40: bit-identical
    to each other and to the NumPy oracle — checkpointing must be
    invisible to the simulation trajectory."""
    board = make_board(40, 40)
    cfg = config_from_board(board, steps=100, save_steps=0)
    straight = LifeSim(cfg, layout="row", impl="halo").run(save=False)

    sim = LifeSim(cfg, layout="row", impl="halo")
    sim.step(60)
    ck = tmp_path / "ck"
    sim.save_checkpoint(ck)
    resumed = LifeSim.from_checkpoint(ck, cfg, layout="row", impl="halo")
    assert resumed.step_count == 60
    final = resumed.run(save=False)
    np.testing.assert_array_equal(final, straight)
    np.testing.assert_array_equal(final, oracle_n(board, 100))


def test_save_is_atomic_under_crash(tmp_path, make_board, monkeypatch):
    """A crash mid-write must leave the OLD complete checkpoint at the
    path (the partial lands only at the tmp sibling), and the next save
    must clear the stale sibling and land normally."""
    import os

    import pytest

    from mpi_and_open_mp_tpu.utils import checkpoint

    board = make_board(16, 16)
    cfg = config_from_board(board, steps=10, save_steps=0)
    sim = LifeSim(cfg, layout="row", impl="roll")
    ck = tmp_path / "ck"
    sim.save_checkpoint(ck)
    b0, s0 = checkpoint.restore(ck)

    sim.step(5)

    class Boom:
        def save(self, path, *a, **k):
            os.makedirs(os.fspath(path), exist_ok=True)  # partial tmp tree
            raise RuntimeError("simulated crash mid-write")

    with monkeypatch.context() as m:
        m.setattr(checkpoint, "_checkpointer", lambda: Boom())
        with pytest.raises(RuntimeError, match="simulated crash"):
            sim.save_checkpoint(ck)
    assert os.path.isdir(str(ck) + ".tmp")  # the partial, quarantined

    b1, s1 = checkpoint.restore(ck)  # old tree intact and valid
    np.testing.assert_array_equal(b1, b0)
    assert s1 == s0 == 0

    sim.save_checkpoint(ck)  # stale sibling cleared, new save lands
    _, s2 = checkpoint.restore(ck)
    assert s2 == 5


def test_restore_detects_crc_mismatch(tmp_path, make_board, monkeypatch):
    """The CRC manifest catches silent corruption: a tree whose stored
    CRC disagrees with its board bytes is rejected with a usable error."""
    import pytest

    from mpi_and_open_mp_tpu.utils import checkpoint

    cfg = config_from_board(make_board(16, 16), steps=4, save_steps=0)
    sim = LifeSim(cfg, layout="row", impl="roll")
    ck = tmp_path / "ck"
    with monkeypatch.context() as m:
        m.setattr(checkpoint, "_board_crc",
                  lambda board: np.uint32(0xDEADBEEF))
        sim.save_checkpoint(ck)
    with pytest.raises(ValueError, match="CRC"):
        checkpoint.restore(ck)


def test_restore_corrupt_or_missing_raises_valueerror(tmp_path):
    """Missing and corrupt trees both surface as ValueError with a clear
    message, never a raw Orbax traceback."""
    import pytest

    from mpi_and_open_mp_tpu.utils import checkpoint

    with pytest.raises(ValueError, match="no checkpoint directory"):
        checkpoint.restore(tmp_path / "missing")
    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "junk").write_text("not a checkpoint")
    with pytest.raises(ValueError):
        checkpoint.restore(bad)


def test_state_checkpoint_roundtrip_atomic(tmp_path):
    """The single-file host-state checkpoint (the serving daemon's queue
    snapshot): arbitrary picklable trees round-trip bit-exact, parent
    dirs are created, and a rewrite replaces atomically."""
    from mpi_and_open_mp_tpu.utils import checkpoint

    state = {"schema": "x/1", "boards": [np.arange(12).reshape(3, 4)],
             "n": 7, "names": ("a", "b")}
    path = tmp_path / "sub" / "queue.state"
    checkpoint.save_state(path, state)
    got = checkpoint.restore_state(path)
    assert got["n"] == 7 and got["names"] == ("a", "b")
    np.testing.assert_array_equal(got["boards"][0], state["boards"][0])
    checkpoint.save_state(path, {"n": 8})  # overwrite in place
    assert checkpoint.restore_state(path) == {"n": 8}
    assert not (tmp_path / "sub" / "queue.state.tmp").exists()


def test_state_checkpoint_truncation_fails_clean(tmp_path):
    """The satellite regression: a state file truncated at ANY offset —
    inside the magic, inside the length/CRC header, mid-payload, one byte
    short — must raise a clean ValueError naming the failure, never a
    pickle/struct traceback."""
    import pytest

    from mpi_and_open_mp_tpu.utils import checkpoint

    path = tmp_path / "q.state"
    checkpoint.save_state(
        path, {"pending": [{"board": np.ones((8, 8), np.uint8), "steps": 3}]})
    blob = path.read_bytes()
    head = len(checkpoint.STATE_MAGIC) + checkpoint._STATE_HEADER.size
    assert len(blob) > head + 8
    cuts = {3: "magic",  # inside the magic line
            len(checkpoint.STATE_MAGIC) + 4: "truncated",  # inside header
            head + (len(blob) - head) // 2: "truncated",  # mid-payload
            len(blob) - 1: "truncated"}  # one byte short
    for cut, expect in cuts.items():
        trunc = tmp_path / f"cut_{cut}.state"
        trunc.write_bytes(blob[:cut])
        with pytest.raises(ValueError, match=expect):
            checkpoint.restore_state(trunc)


def test_state_checkpoint_fsyncs_parent_directory(tmp_path, monkeypatch):
    """The satellite regression: rename-based atomicity is only durable
    once the DIRECTORY inode holding the new name is synced —
    ``save_state`` must fsync the parent dir after ``os.replace``, not
    just the file bytes before it."""
    import os
    import stat

    from mpi_and_open_mp_tpu.utils import checkpoint

    synced_dirs = []
    real_fsync = os.fsync

    def spy_fsync(fd):
        if stat.S_ISDIR(os.fstat(fd).st_mode):
            synced_dirs.append(os.path.realpath(f"/proc/self/fd/{fd}")
                               if os.path.exists(f"/proc/self/fd/{fd}")
                               else "<dir>")
        return real_fsync(fd)

    monkeypatch.setattr(os, "fsync", spy_fsync)
    path = tmp_path / "sub" / "queue.state"
    checkpoint.save_state(path, {"n": 1})
    assert synced_dirs, "save_state never fsynced a directory fd"
    assert any(d.endswith("sub") or d == "<dir>" for d in synced_dirs)


def test_state_checkpoint_garbage_crc_and_missing(tmp_path):
    import pytest

    from mpi_and_open_mp_tpu.utils import checkpoint

    garbage = tmp_path / "garbage.state"
    garbage.write_bytes(b"not a checkpoint at all, just bytes\n" * 3)
    with pytest.raises(ValueError, match="magic"):
        checkpoint.restore_state(garbage)

    path = tmp_path / "q.state"
    checkpoint.save_state(path, {"n": 1})
    blob = bytearray(path.read_bytes())
    blob[-1] ^= 0xFF  # flip one payload byte: CRC must catch it
    flipped = tmp_path / "flipped.state"
    flipped.write_bytes(bytes(blob))
    with pytest.raises(ValueError, match="CRC"):
        checkpoint.restore_state(flipped)

    with pytest.raises(ValueError, match="no readable"):
        checkpoint.restore_state(tmp_path / "missing.state")


def test_state_checkpoint_version_skew_fails_clean(tmp_path):
    """A well-formed envelope from an UNKNOWN magic/version — the file a
    future (or foreign) writer leaves behind — must raise the same clean
    ValueError as garbage, never a pickle/struct traceback: the resume
    ladder's quarantine-and-fall-through depends on that contract."""
    import pickle
    import struct
    import zlib

    import pytest

    from mpi_and_open_mp_tpu.utils import checkpoint

    payload = pickle.dumps({"v": 2})
    for magic in (b"MOMP-STATE/2\n", b"MOMP-STATE/9\n", b"OTHER-FMT/1\n"):
        skew = tmp_path / f"skew-{magic[:4].decode()}.state"
        skew.write_bytes(magic
                         + struct.pack(">QI", len(payload),
                                       zlib.crc32(payload))
                         + payload)
        with pytest.raises(ValueError, match="magic"):
            checkpoint.restore_state(skew)


def test_quarantine_unique_stamped_copies(tmp_path):
    """utils.checkpoint.quarantine: every call moves the artifact to a
    DISTINCT stamped sibling — repeated corruptions never clobber an
    earlier forensic copy — and a missing source is a clean None."""
    from mpi_and_open_mp_tpu.utils import checkpoint

    src = tmp_path / "artifact.bin"
    names = []
    for i in range(3):
        src.write_bytes(f"corruption #{i}".encode())
        dst = checkpoint.quarantine(src)
        assert dst is not None and not src.exists()
        names.append(dst)
    assert len(set(names)) == 3
    contents = sorted(open(n, "rb").read() for n in names)
    assert contents == [b"corruption #0", b"corruption #1",
                        b"corruption #2"]
    assert all(".corrupt." in n for n in names)

    assert checkpoint.quarantine(src) is None  # nothing there
    src.write_bytes(b"x")
    labeled = checkpoint.quarantine(src, label="stale")
    assert labeled is not None and ".stale." in labeled


def test_checkpoint_resume_bitfused_padded_frame(tmp_path, make_board):
    """Mid-run checkpoint/resume through the packed path on an unaligned
    board: the stored state is the PADDED frame (mirror rows included);
    restore must crop to the logical board, re-pad for the resuming
    mesh/impl, and continue bit-exact — including resuming onto a
    DIFFERENT layout's frame geometry."""
    from mpi_and_open_mp_tpu.parallel import mesh as mesh_lib

    board = make_board(100, 130)
    cfg = config_from_board(board, steps=80, save_steps=0)
    mesh = mesh_lib.make_mesh_2d(2, 4)
    sim = LifeSim(cfg, layout="row", impl="bitfused", mesh=mesh)
    sim.step(45)  # crosses the k_max=32 round boundary before saving
    ckpt = tmp_path / "bit_ck"
    sim.save_checkpoint(ckpt)

    for layout, impl in [("row", "bitfused"), ("cart", "bitfused"),
                         ("col", "roll")]:
        resumed = LifeSim.from_checkpoint(
            ckpt, cfg, layout=layout, impl=impl, mesh=mesh)
        assert resumed.step_count == 45
        got = resumed.run(save=False)
        np.testing.assert_array_equal(
            got, oracle_n(board, 80), err_msg=f"{layout}/{impl}")
