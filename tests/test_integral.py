"""Quadrature parity: sharded psum sum vs serial vs closed form (π)."""

import numpy as np
import pytest

from mpi_and_open_mp_tpu.models.integral import Integral
from mpi_and_open_mp_tpu.parallel import mesh as mesh_lib

PI = float(np.pi)


@pytest.mark.parametrize("n", [10, 1000, 100_000])
def test_serial_converges_to_pi(n):
    mesh = mesh_lib.make_mesh_1d(1, axis="i")
    val = Integral(n, mesh=mesh).compute()
    # Trapezoid error for sqrt(4-x^2) is dominated by the singular
    # derivative at x=2: O(n^-1.5).
    assert abs(val - PI) < max(5.0 * n**-1.5, 1e-5)


@pytest.mark.parametrize("n", [1000, 12_345, 999_983])
def test_sharded_matches_serial(n):
    """8-way psum reduction == 1-device sum (the reference's star-reduce
    parity, integral.c:39-43), modulo f32 summation order."""
    serial = Integral(n, mesh=mesh_lib.make_mesh_1d(1, axis="i")).compute()
    sharded = Integral(n, mesh=mesh_lib.make_mesh_1d(8, axis="i")).compute()
    assert sharded == pytest.approx(serial, rel=2e-6)
    assert abs(sharded - PI) < 1e-3


def test_large_n_int64_no_truncation():
    """N beyond 2^32 must not wrap (the reference's atoi quirk is fixed)."""
    n = (1 << 32) + 7
    integral = Integral(n)
    assert integral.n == n


def test_large_n_accuracy_kahan():
    """At N=1e8 (763 chunks/device) the Kahan accumulator must hold the
    result near f32 noise, not drift with chunk count."""
    val = Integral(10**8, mesh=mesh_lib.make_mesh_1d(8, axis="i")).compute()
    assert abs(val - PI) < 2e-5


def test_warmup_and_reset_roundtrip(make_board=None):
    from mpi_and_open_mp_tpu.models.life import LifeSim
    from mpi_and_open_mp_tpu.utils.config import config_from_board
    import numpy as np

    board = (np.random.default_rng(3).random((16, 16)) < 0.4).astype(np.uint8)
    cfg = config_from_board(board, steps=7, save_steps=3)
    sim = LifeSim(cfg, layout="row", impl="halo")
    assert sim._segment_lengths() == [1, 3]
    sim.warmup()
    np.testing.assert_array_equal(sim.collect(), board)  # state untouched
    sim.step(5)
    sim.reset()
    assert sim.step_count == 0
    np.testing.assert_array_equal(sim.collect(), board)


def test_invalid_n():
    with pytest.raises(ValueError):
        Integral(0)


def test_custom_interval():

    mesh = mesh_lib.make_mesh_1d(8, axis="i")
    val = Integral(100_000, a=0.0, b=1.0, f=lambda x: x * x, mesh=mesh).compute()
    assert val == pytest.approx(1.0 / 3.0, abs=1e-5)
