"""Test harness: force JAX onto 8 virtual CPU devices.

Mirrors the SURVEY §4 test strategy: "multi-node" behaviour is exercised
without a TPU pod by running every sharded code path on a virtual 8-device
CPU mesh (``--xla_force_host_platform_device_count``). This must run before
any backend is initialised; the environment's sitecustomize pre-imports jax
and pins ``jax_platforms`` to the TPU plugin, so we re-pin to cpu here
(backends initialise lazily, so this is still early enough).
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _assert_virtual_mesh():
    assert jax.default_backend() == "cpu"
    assert len(jax.devices()) == 8, "tests expect 8 virtual CPU devices"


@pytest.fixture
def rng():
    return np.random.default_rng(20260729)


def random_board(rng, ny, nx, density=0.35):
    return (rng.random((ny, nx)) < density).astype(np.uint8)


def multiprocess_cpu_supported() -> bool:
    """Whether the installed jaxlib can compile cross-process SPMD on the
    CPU backend. The 0.4.x line cannot ("Multiprocess computations aren't
    implemented on the CPU backend" at compile time); the real
    ``jax.distributed`` two-process tests need >= 0.5."""
    import jaxlib

    return tuple(int(x) for x in jaxlib.__version__.split(".")[:2]) >= (0, 5)


@pytest.fixture
def make_board(rng):
    def _make(ny, nx, density=0.35):
        return random_board(rng, ny, nx, density)

    return _make


def oracle_n(board, n):
    """Advance ``board`` ``n`` steps through the NumPy oracle (shared by the
    parity tests; the single source of ground truth)."""
    from mpi_and_open_mp_tpu.ops.life_ops import life_step_numpy

    b = np.asarray(board)
    for _ in range(n):
        b = life_step_numpy(b)
    return b
