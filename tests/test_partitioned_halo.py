"""PR 18: partitioned halo transport everywhere — satellites.

The tentpole contract (``parallel/haloplan.py``): the Pallas
async-remote-copy rung covers every layout (row, col's x-mirror, cart's
two-phase corner exchange) and the boundary itself can be partitioned
into per-edge sub-rounds (``boundary_steps < fuse_steps``, the
``MPI_Pready`` analogue of arxiv 2508.13370) — all bit-exact to the
sequential oracle. CPU CI executes the RDMA *schedule* through a
``ppermute`` stand-in with identical semantics (predecessor's forward
edge, successor's backward edge), so the exchange order, corner
assembly, and chaos hooks are exercised here and only the DMA transport
itself is chip-gated (``launchers/queue_r08``). Chaos must reach every
new exchange (a corrupted ghost diverges the run; the LifeSim guard
ladder recovers with ``:recovered`` provenance), and the tuner's
independent interior x boundary depth axis must keep the coupled-depth
heuristic in the race (``vs_heuristic >= 1.0`` by construction) and
persist winners. Runs on the 8-virtual-CPU-device mesh from conftest.
"""

import os
import sys

import numpy as np
import pytest

import jax
from jax import lax

from conftest import oracle_n
from mpi_and_open_mp_tpu import stencils
from mpi_and_open_mp_tpu.models.life import LifeSim
from mpi_and_open_mp_tpu.obs import ledger
from mpi_and_open_mp_tpu.parallel import halo, haloplan, mesh as mesh_lib
from mpi_and_open_mp_tpu.robust import chaos
from mpi_and_open_mp_tpu.stencils import engine as stencil_engine
from mpi_and_open_mp_tpu.utils.config import config_from_board

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_chaos_and_plans():
    """Chaos plans are trace-time and the halo plan cache keys on the
    env flags but NOT on the backend (the backend cannot change in a
    real process) — tests that fake the backend must drop their cached
    ``overlap:rdma`` plans on the way out."""
    haloplan._plan.cache_clear()
    yield
    haloplan._plan.cache_clear()
    chaos.reset()


def _fake_edge_pair(fwd_edge, bwd_edge, axis_name, p, *, collective_id):
    """``ppermute`` stand-in for the Pallas RDMA kernel — the same
    contract (returns the predecessor's ``fwd_edge`` and the successor's
    ``bwd_edge``) so the CPU mesh executes the RDMA schedule, corner
    assembly, and chaos wrappers; only the DMA transport is swapped."""
    return (lax.ppermute(fwd_edge, axis_name, halo.ring_perm(p, 1)),
            lax.ppermute(bwd_edge, axis_name, halo.ring_perm(p, -1)))


def _arm_rdma(monkeypatch):
    """Opt the plan into the RDMA rung on the CPU mesh: flag on, backend
    faked (the engine choice lives inside the cached plan derivation),
    transport stubbed."""
    monkeypatch.setenv(haloplan.ENV_RDMA, "1")
    monkeypatch.setattr(haloplan.jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(haloplan, "_rdma_edge_pair", _fake_edge_pair)
    haloplan._plan.cache_clear()


# ------------------------------------------------------------ plan derivation


def test_partitioned_plan_stamps_and_legality():
    plan = haloplan.plan_halo("row", (8, 1), (64, 64), 1, 4,
                              boundary_steps=2)
    assert plan.overlap and plan.engine == "overlap:deferred:pb2"
    assert plan.boundary_steps == 2 and plan.fuse_steps == 4

    coupled = haloplan.plan_halo("row", (8, 1), (64, 64), 1, 4)
    assert coupled.boundary_steps == 4
    assert coupled.engine == "overlap:deferred"

    with pytest.raises(ValueError, match="must divide"):
        haloplan.plan_halo("row", (8, 1), (64, 64), 1, 4,
                           boundary_steps=3)
    with pytest.raises(ValueError, match="coupled boundary"):
        haloplan.plan_halo("row", (8, 1), (64, 64), 1, 4,
                           boundary_steps=2, pack_layout="packed")


def test_partitioned_plan_degrades_coupled(monkeypatch):
    """Kill switch / degenerate geometry resets the boundary axis too:
    a sequential plan has one exchange per round by definition."""
    monkeypatch.setenv(haloplan.ENV_OVERLAP, "0")
    plan = haloplan.plan_halo("row", (8, 1), (64, 64), 1, 4,
                              boundary_steps=2)
    assert not plan.overlap and plan.engine == "seq:halo"
    assert plan.boundary_steps == plan.fuse_steps == 4
    monkeypatch.delenv(haloplan.ENV_OVERLAP)
    haloplan._plan.cache_clear()
    shallow = haloplan.plan_halo("row", (8, 1), (6, 64), 1, 4,
                                 boundary_steps=2)
    assert not shallow.overlap and "empty interior" in shallow.why


# --------------------------------------- partitioned-boundary bit identity


@pytest.mark.parametrize("layout", ["row", "col", "cart"])
@pytest.mark.parametrize("workload", sorted(stencils.names()))
def test_partitioned_boundary_bit_equals_sequential(workload, layout):
    """The satellite invariant: for every registry spec and layout the
    partitioned round (fuse=2, per-edge depth 1, ``:pb1``) reassembles
    bit-identically to the forced-sequential schedule (ulp-identically
    for wide-radius float tap sums, which may reassociate) and passes
    the oracle gate — partitioning moves message boundaries, not
    values."""
    spec = stencils.get(workload)
    # Wide-radius specs (lenia r=8): the round's full fused depth is
    # fuse(2)*radius, and overlap needs every layout's min shard (s/4)
    # to keep a non-empty interior past 2*that — else the plan legally
    # gates out to seq and the :pb1 assertion below is moot.
    s = max(48, 20 * spec.radius)
    board = spec.init(np.random.default_rng(46), (s, s))
    mesh = mesh_lib.make_mesh_2d(4, 2)
    got = np.asarray(stencil_engine.run_sharded(
        spec, board, 6, mesh=mesh, layout=layout, fuse_steps=2,
        boundary_steps=1))
    plan = stencil_engine.run_sharded.last_plan
    assert plan.overlap and plan.engine.endswith(":pb1")
    seq = np.asarray(stencil_engine.run_sharded(
        spec, board, 6, mesh=mesh, layout=layout, fuse_steps=2,
        overlap=False))
    if spec.radius > 1 and spec.is_float:
        # A wide-radius float tap sum (lenia: 288 adds per cell) may
        # legally reassociate between the boundary-strip and full-shard
        # programs; the agreement bound is ulp-level, not bit-level
        # (measured 0.5 ulp at the seams).
        np.testing.assert_allclose(
            got, seq, rtol=0, atol=4 * np.finfo(np.float32).eps)
    else:
        np.testing.assert_array_equal(got, seq)
    assert stencils.parity_ok(spec, got,
                              stencils.oracle_run(spec, board, 6))


def test_partitioned_deep_fuse_with_remainder_round():
    """fuse=4 split into depth-2 sub-rounds, 10 steps: two partitioned
    rounds plus a depth-2 remainder round (its own coupled plan)."""
    spec = stencils.get("life")
    board = spec.init(np.random.default_rng(47), (48, 48))
    mesh = mesh_lib.make_mesh_2d(4, 2)
    got = np.asarray(stencil_engine.run_sharded(
        spec, board, 10, mesh=mesh, layout="cart", fuse_steps=4,
        boundary_steps=2))
    assert stencil_engine.run_sharded.last_plan.engine.endswith(":pb2")
    seq = np.asarray(stencil_engine.run_sharded(
        spec, board, 10, mesh=mesh, layout="cart", fuse_steps=4,
        overlap=False))
    np.testing.assert_array_equal(got, seq)
    assert stencils.parity_ok(
        spec, got, stencils.oracle_run(spec, board, 10))


# --------------------------------------------------- RDMA rung on the mesh


@pytest.mark.parametrize("layout", ["row", "col", "cart"])
@pytest.mark.parametrize("boundary", [None, 1])
def test_rdma_schedule_bit_parity_every_layout(monkeypatch, layout,
                                               boundary):
    """The RDMA rung's schedule for every layout — col's x-mirror,
    cart's two-phase corner exchange — coupled and partitioned, through
    the ppermute transport stand-in: stamped ``overlap:rdma[:pb1]`` and
    bit-identical to the sequential oracle."""
    _arm_rdma(monkeypatch)
    spec = stencils.get("life")
    board = spec.init(np.random.default_rng(48), (48, 48))
    mesh = mesh_lib.make_mesh_2d(4, 2)
    got = np.asarray(stencil_engine.run_sharded(
        spec, board, 6, mesh=mesh, layout=layout, fuse_steps=2,
        boundary_steps=boundary))
    plan = stencil_engine.run_sharded.last_plan
    want_stamp = "overlap:rdma" + (":pb1" if boundary else "")
    assert plan.engine == want_stamp
    seq = np.asarray(stencil_engine.run_sharded(
        spec, board, 6, mesh=mesh, layout=layout, fuse_steps=2,
        overlap=False))
    np.testing.assert_array_equal(got, seq)
    assert stencils.parity_ok(spec, got,
                              stencils.oracle_run(spec, board, 6))


# ------------------------------------------------- cart corner, every rung


def _corner_glider_board(edge=64):
    """A glider aimed straight through the (4, 2) cart mesh's interior
    shard corner at (16, 32): it crosses the y edge, the x edge, and the
    diagonal corner words within ~12 steps — the exact cells the
    two-phase exchange forwards without a third transfer."""
    b = np.zeros((edge, edge), np.uint8)
    glider = np.array([[0, 1, 0],
                       [0, 0, 1],
                       [1, 1, 1]], np.uint8)  # travels down-right
    b[10:13, 26:29] = glider
    return b


@pytest.mark.parametrize("rdma", [False, True])
@pytest.mark.parametrize("schedule", ["seq", "coupled", "partitioned"])
def test_cart_corner_glider_every_schedule(monkeypatch, rdma, schedule):
    """Acceptance: a glider crossing the 2-D shard corner stays
    bit-equal to the sequential oracle under every (rdma, overlap,
    partitioned-boundary) combination, across fused-round boundaries
    (24 steps of fuse=2 rounds, plus a 7-step run with a remainder
    round)."""
    if rdma:
        _arm_rdma(monkeypatch)
    spec = stencils.get("life")
    board = _corner_glider_board()
    mesh = mesh_lib.make_mesh_2d(4, 2)
    kw = {"seq": {"overlap": False},
          "coupled": {},
          "partitioned": {"boundary_steps": 1}}[schedule]
    for steps in (7, 24):
        got = np.asarray(stencil_engine.run_sharded(
            spec, board, steps, mesh=mesh, layout="cart", fuse_steps=2,
            **kw))
        np.testing.assert_array_equal(
            got[0] if got.ndim == 3 else got, oracle_n(board, steps))
    plan = stencil_engine.run_sharded.last_plan
    if schedule != "seq":
        assert plan.overlap
        assert plan.engine.startswith(
            "overlap:rdma" if rdma else "overlap:deferred")


# ----------------------------------------------------------- chaos coverage


def test_chaos_corrupts_partitioned_col_exchange(monkeypatch,
                                                 make_board):
    """``_chaos_ghost`` reaches the partitioned per-edge sends (the
    ``x-part`` sub-rounds): a corrupted ghost with guards off must
    diverge the run — the fault is injected, not absorbed. (Dense
    random board: every shard edge carries live cells, so a faulted
    ghost must change the outcome.)"""
    spec = stencils.get("life")
    board = make_board(48, 48)
    mesh = mesh_lib.make_mesh_2d(4, 2)
    clean = np.asarray(stencil_engine.run_sharded(
        spec, board, 6, mesh=mesh, layout="col", fuse_steps=2,
        boundary_steps=1))
    monkeypatch.setenv("MOMP_CHAOS", "halo=corrupt;noguard")
    chaos.reset()
    jax.clear_caches()
    hurt = np.asarray(stencil_engine.run_sharded(
        spec, board, 6, mesh=mesh, layout="col", fuse_steps=2,
        boundary_steps=1))
    assert not np.array_equal(clean, hurt)


def test_chaos_corrupts_rdma_cart_corner_exchange(monkeypatch,
                                                  make_board):
    """The two-phase corner exchange funnels through the same chaos
    hook: a corrupted phase-2 (x) ghost — which carries the corner
    words — diverges the cart run on the RDMA rung."""
    _arm_rdma(monkeypatch)
    spec = stencils.get("life")
    board = make_board(48, 48)
    mesh = mesh_lib.make_mesh_2d(4, 2)
    clean = np.asarray(stencil_engine.run_sharded(
        spec, board, 6, mesh=mesh, layout="cart", fuse_steps=2))
    monkeypatch.setenv("MOMP_CHAOS", "halo=corrupt;noguard")
    chaos.reset()
    jax.clear_caches()
    hurt = np.asarray(stencil_engine.run_sharded(
        spec, board, 6, mesh=mesh, layout="cart", fuse_steps=2))
    assert not np.array_equal(clean, hurt)


def test_chaos_col_halo_recovers_with_provenance(monkeypatch, make_board):
    """Guard ladder over the col layout's deferred overlap exchange:
    the consistency probe catches the corrupted x ghost and the
    suppressed re-trace recovers bit-identically, stamping
    ``:recovered`` provenance."""
    board = make_board(64, 64)
    cfg = config_from_board(board, steps=12, save_steps=4)
    monkeypatch.setenv("MOMP_CHAOS", "halo=corrupt;seed=3")
    chaos.reset()
    sim = LifeSim(cfg, layout="col", impl="halo",
                  mesh=mesh_lib.make_mesh_1d(8, axis="x"))
    final = sim.run(save=False)
    np.testing.assert_array_equal(final, oracle_n(board, 12))
    assert sim.recoveries and "recovered" in sim.recoveries[0]


def test_chaos_cart_rdma_recovers_with_provenance(monkeypatch,
                                                  make_board):
    """Same ladder on the cart RDMA rung (two-phase corner exchange via
    the transport stand-in): recovery must re-trace with injection
    suppressed and land bit-identical."""
    _arm_rdma(monkeypatch)
    board = make_board(64, 64)
    cfg = config_from_board(board, steps=12, save_steps=4)
    monkeypatch.setenv("MOMP_CHAOS", "halo=corrupt;seed=5")
    chaos.reset()
    sim = LifeSim(cfg, layout="cart", impl="halo",
                  mesh=mesh_lib.make_mesh_2d(4, 2))
    final = sim.run(save=False)
    np.testing.assert_array_equal(final, oracle_n(board, 12))
    assert sim.recoveries and "recovered" in sim.recoveries[0]


# ------------------------------------------------ tuner depth axis + store


def test_sharded_fuse_depths_env_override(monkeypatch):
    from mpi_and_open_mp_tpu.tune import space

    monkeypatch.delenv("MOMP_TUNE_FUSE_DEPTHS", raising=False)
    assert space.sharded_fuse_depths() == (1, 2)
    monkeypatch.setenv("MOMP_TUNE_FUSE_DEPTHS", "4")
    assert space.sharded_fuse_depths() == (1, 4)  # heuristic stays in
    monkeypatch.setenv("MOMP_TUNE_FUSE_DEPTHS", "8,2,2")
    assert space.sharded_fuse_depths() == (1, 2, 8)
    assert space._boundary_depths(4) == (4, 2, 1)


def test_tune_sharded_depth_axis_and_heuristic_race(tmp_path,
                                                    monkeypatch):
    """The tuner enumerates interior x boundary depths independently
    (legality-gated), always races the coupled-depth heuristic
    (vs_heuristic >= 1.0 by construction — the heuristic is IN the
    race), and persists the winning depths for zero-retrace reuse."""
    from mpi_and_open_mp_tpu.tune import space, tune_sharded
    from mpi_and_open_mp_tpu.tune.plans import PlanStore

    monkeypatch.setenv("MOMP_TUNE_FUSE_DEPTHS", "1,2")
    mesh = mesh_lib.make_mesh_2d(4, 2)
    cands = space.sharded_candidates("life", (64, 64), mesh)
    pairs = {(c.axis_order, c.fuse_steps, c.boundary_steps)
             for c in cands if c.halo_overlap == "overlap"}
    for lo in ("row", "col", "cart"):
        assert {(lo, 1, 1), (lo, 2, 2), (lo, 2, 1)} <= pairs

    store = PlanStore(tmp_path)
    res = tune_sharded("life", (64, 64), mesh=mesh, steps=16,
                       store=store)
    assert res["vs_heuristic"] >= 1.0
    assert res["heuristic"]["halo_overlap"] == "overlap"
    assert res["heuristic"]["fuse_steps"] == 1
    assert {"fuse_steps", "boundary_steps"} <= set(res["tuned"])

    fresh = PlanStore(tmp_path)
    fresh.install()
    hit = fresh.lookup_sharded("life", (64, 64))
    assert hit is not None
    assert {"fuse_steps", "boundary_steps"} <= set(hit["choice"])


# ------------------------------------------------- sentinel ring provenance


def test_sentinel_ring_fields_polarity():
    sys.path.insert(0, os.path.join(REPO, "analysis"))
    import regression_sentinel

    assert "ring_prefetch_tflops" in regression_sentinel.WATCH_FIELDS
    assert "ring_exposed_s" in regression_sentinel.WATCH_FIELDS
    assert regression_sentinel.direction_for(
        "ring_prefetch_tflops") == "higher"
    assert regression_sentinel.direction_for("ring_exposed_s") == "lower"
    assert "ring_hop_engine" in regression_sentinel.PROVENANCE_FIELDS
    assert "ring_hop_engine_bwd" in regression_sentinel.PROVENANCE_FIELDS
    # :pf is a tiebreak WITHIN the pallas tier, not a new tier.
    key = regression_sentinel._provenance_key
    assert key("pallas:b128:pf") > key("pallas:b128")
    assert (regression_sentinel.engine_rank("pallas:b128:pf")
            == regression_sentinel.engine_rank("pallas:b128"))


def test_sentinel_fails_pf_loss_not_pf_gain():
    """Losing the ``:pf`` suffix at the same engine tier (the
    MOMP_RING_PREFETCH=0 rerun) is a provenance downgrade the sentinel
    fails; gaining it is not."""
    sys.path.insert(0, os.path.join(REPO, "analysis"))
    import regression_sentinel

    def entry(ts, stamp):
        rec = {"metric": "m", "value": 100.0, "board": [64, 64],
               "dtype": "uint8", "steps": 100, "batch": 0,
               "ring_hop_engine": stamp}
        return ledger.stamp(rec, platform="cpu", device_count=8, ts=ts,
                            sha="deadbee")

    entries = [entry(float(i), "pallas:b128:pf") for i in range(3)]
    entries.append(entry(3.0, "pallas:b128"))
    verdict = regression_sentinel.evaluate(entries)
    assert verdict["verdict"] == "fail"
    (down,) = [d for d in verdict["downgrades"]
               if d["field"] == "ring_hop_engine"]
    assert down["new"] == "pallas:b128"
    assert down["baseline_best"] == "pallas:b128:pf"

    entries = [entry(float(i), "pallas:b128") for i in range(3)]
    entries.append(entry(3.0, "pallas:b128:pf"))
    verdict = regression_sentinel.evaluate(entries)
    assert not [d for d in verdict.get("downgrades", [])
                if d["field"] == "ring_hop_engine"]


# --------------------------------------------------------- bench --ring-ab


def test_bench_ring_ab_phase(monkeypatch, tmp_path):
    """The hop-prefetch A/B end-to-end on the conftest mesh (interpret
    mode): oracle gate, pf-vs-single-slot bit parity both directions,
    chained-differenced rates, rotation-priced exposed accounting, and
    the kill-switch refusal that downgrades the stamps. Runs with a
    live trace sink: with tracing on, ring_attention reroutes to the
    hop-by-hop telemetry dispatch (host RTT per hop, no grad path) —
    the phase must pin MOMP_TRACE_HOPS=0 so the A/B prices the
    production fused schedule, and must restore the env after."""
    from types import SimpleNamespace

    from mpi_and_open_mp_tpu.parallel import context

    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    import bench

    jax.clear_caches()
    monkeypatch.setattr(context, "_PALLAS_INTERPRET", True)
    monkeypatch.setenv("MOMP_TRACE", str(tmp_path / "ring_trace.jsonl"))
    monkeypatch.delenv("MOMP_TRACE_HOPS", raising=False)
    args = SimpleNamespace(ring_ab=16)
    try:
        fields = bench._ring_ab_phase(args)
    finally:
        jax.clear_caches()
    assert "ring_ab_error" not in fields, fields
    assert "MOMP_TRACE_HOPS" not in os.environ
    assert fields["ring_hop_engine"].startswith("pallas:")
    assert fields["ring_hop_engine"].endswith(":pf")
    assert fields["ring_hop_engine_bwd"].endswith(":pf")
    assert fields["ring_nopf_engine"] == fields["ring_hop_engine"][:-3]
    assert fields["ring_ab_parity"] is True
    assert fields["ring_ab_grad_parity"] is True
    assert fields["ring_prefetch_tflops"] > 0
    assert fields["ring_vs_nopf"] > 0
    assert 0.0 <= fields["ring_exposed_s"] <= fields["ring_transfer_s"]
    assert fields["ring_exposed_nopf_s"] == fields["ring_transfer_s"]
    assert 0.0 <= fields["ring_prefetch_efficiency"] <= 1.0

    # Kill switch: the phase refuses to bless a non-prefetch run and the
    # downgraded stamps ride the line for the sentinel.
    monkeypatch.setattr(context, "_RING_PREFETCH", False)
    jax.clear_caches()
    try:
        fields = bench._ring_ab_phase(args)
    finally:
        jax.clear_caches()
    assert "not engaged" in fields["ring_ab_error"]
    assert not fields["ring_hop_engine"].endswith(":pf")
