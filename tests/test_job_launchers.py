"""The multi-host-style job launchers (launchers/job_*.sh) end to end.

The analogue of the reference's PBS batch layer (``3-life/job_life.sh``,
``2-network-params/job_mult.sh``): each script drives N real
``jax.distributed`` processes on this machine (CPU backend, one device per
process — the single-machine stand-in for a DCN pod) and produces the same
artifacts the reference's cluster runs committed (times.txt lines, CSV
rows). Heavier than unit tests (each rank is a full JAX runtime), so the
sweeps are kept minimal.
"""

import os
import subprocess

import pytest

import conftest


_needs_mp_cpu = pytest.mark.skipif(
    not conftest.multiprocess_cpu_supported(),
    reason="installed jaxlib's CPU backend cannot compile multi-process SPMD")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, *args, timeout=240):
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    return subprocess.run(
        [os.path.join(REPO, "launchers", script), *args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO,
    )


@_needs_mp_cpu
def test_job_life_two_process_sweep(tmp_path):
    """np=1..2 Life sweep: each np appends exactly ONE wall-seconds line
    (rank-0-only output discipline), consumable by analysis/plot_life.py."""
    times = tmp_path / "times.txt"
    r = _run("job_life.sh",
             "--cfg=tests/fixtures/rpentomino_40x32.cfg",
             "--max-procs=2", f"--times-file={times}")
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr}"
    lines = times.read_text().strip().splitlines()
    assert len(lines) == 2, lines
    assert all(float(x) > 0 for x in lines)


@_needs_mp_cpu
def test_job_pingpong_mult_placement(tmp_path):
    """The 2-process fabric probe (the reference's job_mult.sh placement)
    writes the reference CSV schema from rank 0."""
    out = tmp_path / "out_mult.csv"
    r = _run("job_pingpong.sh", "--placement=mult", "--reps=5",
             "--max-power=2", f"--out={out}")
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr}"
    rows = out.read_text().strip().splitlines()
    assert rows[0] == "size,time" and len(rows) == 4
    sizes = [int(line.split(",")[0]) for line in rows[1:]]
    assert sizes == [1, 10, 100]
    assert all(float(line.split(",")[1]) > 0 for line in rows[1:])


@_needs_mp_cpu
def test_job_integral_two_process(tmp_path):
    times = tmp_path / "times_int.txt"
    r = _run("job_integral.sh", "--n=1000000", "--max-procs=2",
             f"--times-file={times}")
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr}"
    lines = times.read_text().strip().splitlines()
    assert len(lines) == 2
    assert all(float(x) >= 0 for x in lines)


@_needs_mp_cpu
def test_job_attention_zigzag_grad(tmp_path):
    """The long-context job launcher: 2 real processes running the
    striped/zigzag causal ring with GQA and the flash backward; the
    primary rank's parity check passes and exactly one elapsed-seconds
    line lands in the times file (Gloo banners share stdout, so the
    launcher matches the contract line by shape)."""
    times = tmp_path / "times_att.txt"
    r = _run("job_attention.sh", "--procs=2", "--variant=ring",
             "--layout=zigzag", "--seq=256", "--heads=4", "--kv-heads=2",
             "--head-dim=16", "--causal", "--grad",
             f"--times-file={times}")
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr}"
    assert "parity ok" in r.stderr
    lines = times.read_text().strip().splitlines()
    assert len(lines) == 1 and float(lines[0]) > 0

def test_tpu_queue_loop_drains_and_exits(tmp_path):
    """The wedge-safe chip-work queue (launchers/tpu_queue_loop.sh) with
    a stubbed probe: numbered jobs run in order through one loop, move
    to done/ on success, and the loop exits once the queue is empty."""
    q = tmp_path / "queue"
    q.mkdir()
    (q / "01_a.sh").write_text("echo A >> %s/order\n" % tmp_path)
    (q / "02_b.sh").write_text("echo B >> %s/order\n" % tmp_path)
    log = tmp_path / "log"
    r = subprocess.run(
        [os.path.join(REPO, "launchers", "tpu_queue_loop.sh"),
         str(q), str(log)],
        env={**os.environ, "TPUQ_PROBE_CMD": "true", "TPUQ_SLEEP": "0",
             "TPUQ_SETTLE": "0"},
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr}\n{log.read_text()}"
    assert (tmp_path / "order").read_text() == "A\nB\n"
    assert sorted(p.name for p in (q / "done").iterdir()) == [
        "01_a.sh", "02_b.sh"]
    assert "queue empty; exiting" in log.read_text()


def test_tpu_queue_loop_keeps_failed_job_queued(tmp_path):
    """A failing job stays in the queue (the loop re-probes instead of
    dropping chip work); no jobs after it run in that drain pass."""
    import signal
    import time

    q = tmp_path / "queue"
    q.mkdir()
    (q / "01_bad.sh").write_text("exit 1\n")
    (q / "02_never.sh").write_text("echo RAN >> %s/ran\n" % tmp_path)
    log = tmp_path / "log"
    p = subprocess.Popen(
        [os.path.join(REPO, "launchers", "tpu_queue_loop.sh"),
         str(q), str(log)],
        env={**os.environ, "TPUQ_PROBE_CMD": "true", "TPUQ_SLEEP": "1",
             "TPUQ_SETTLE": "0"})
    try:
        deadline = time.time() + 30
        while time.time() < deadline:
            if log.exists() and "FAILED" in log.read_text():
                break
            time.sleep(0.2)
    finally:
        p.send_signal(signal.SIGTERM)
        p.wait(timeout=10)
    text = log.read_text()
    assert "FAILED" in text and str(q / "01_bad.sh") in text
    assert (q / "01_bad.sh").exists()          # kept queued
    assert not (tmp_path / "ran").exists()     # later job not reached
