"""The multi-host-style job launchers (launchers/job_*.sh) end to end.

The analogue of the reference's PBS batch layer (``3-life/job_life.sh``,
``2-network-params/job_mult.sh``): each script drives N real
``jax.distributed`` processes on this machine (CPU backend, one device per
process — the single-machine stand-in for a DCN pod) and produces the same
artifacts the reference's cluster runs committed (times.txt lines, CSV
rows). Heavier than unit tests (each rank is a full JAX runtime), so the
sweeps are kept minimal.
"""

import os
import subprocess


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, *args, timeout=240):
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    return subprocess.run(
        [os.path.join(REPO, "launchers", script), *args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO,
    )


def test_job_life_two_process_sweep(tmp_path):
    """np=1..2 Life sweep: each np appends exactly ONE wall-seconds line
    (rank-0-only output discipline), consumable by analysis/plot_life.py."""
    times = tmp_path / "times.txt"
    r = _run("job_life.sh",
             "--cfg=tests/fixtures/rpentomino_40x32.cfg",
             "--max-procs=2", f"--times-file={times}")
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr}"
    lines = times.read_text().strip().splitlines()
    assert len(lines) == 2, lines
    assert all(float(x) > 0 for x in lines)


def test_job_pingpong_mult_placement(tmp_path):
    """The 2-process fabric probe (the reference's job_mult.sh placement)
    writes the reference CSV schema from rank 0."""
    out = tmp_path / "out_mult.csv"
    r = _run("job_pingpong.sh", "--placement=mult", "--reps=5",
             "--max-power=2", f"--out={out}")
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr}"
    rows = out.read_text().strip().splitlines()
    assert rows[0] == "size,time" and len(rows) == 4
    sizes = [int(line.split(",")[0]) for line in rows[1:]]
    assert sizes == [1, 10, 100]
    assert all(float(line.split(",")[1]) > 0 for line in rows[1:])


def test_job_integral_two_process(tmp_path):
    times = tmp_path / "times_int.txt"
    r = _run("job_integral.sh", "--n=1000000", "--max-procs=2",
             f"--times-file={times}")
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr}"
    lines = times.read_text().strip().splitlines()
    assert len(lines) == 2
    assert all(float(x) >= 0 for x in lines)


def test_job_attention_zigzag_grad(tmp_path):
    """The long-context job launcher: 2 real processes running the
    striped/zigzag causal ring with GQA and the flash backward; the
    primary rank's parity check passes and exactly one elapsed-seconds
    line lands in the times file (Gloo banners share stdout, so the
    launcher matches the contract line by shape)."""
    times = tmp_path / "times_att.txt"
    r = _run("job_attention.sh", "--procs=2", "--variant=ring",
             "--layout=zigzag", "--seq=256", "--heads=4", "--kv-heads=2",
             "--head-dim=16", "--causal", "--grad",
             f"--times-file={times}")
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr}"
    assert "parity ok" in r.stderr
    lines = times.read_text().strip().splitlines()
    assert len(lines) == 1 and float(lines[0]) > 0
