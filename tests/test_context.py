"""Sequence/context parallelism: ring attention + Ulysses parity.

Mirrors the framework's Life parity discipline (SURVEY §4): the sharded
implementation must match the single-device oracle on the virtual 8-device
CPU mesh, across shapes, dtypes, masks, and under differentiation.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpi_and_open_mp_tpu.parallel import mesh as mesh_lib
from mpi_and_open_mp_tpu.parallel.context import (
    attention_reference,
    ring_attention,
    ulysses_attention,
)


def _qkv(rng, h, n, d, dtype=jnp.float32):
    shape = (h, n, d)
    q = jnp.asarray(rng.standard_normal(shape), dtype)
    k = jnp.asarray(rng.standard_normal(shape), dtype)
    v = jnp.asarray(rng.standard_normal(shape), dtype)
    return q, k, v


@pytest.fixture(scope="module")
def sp_mesh():
    return mesh_lib.make_mesh_1d(8, axis="sp")


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("h,n,d", [(4, 128, 32), (1, 64, 16), (3, 256, 8)])
def test_ring_attention_parity(rng, sp_mesh, causal, h, n, d):
    q, k, v = _qkv(rng, h, n, d)
    got = ring_attention(q, k, v, mesh=sp_mesh, causal=causal)
    want = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("h,hkv,n,d", [(4, 4, 128, 32), (4, 2, 256, 16),
                                       (2, 1, 144, 8)])
def test_ring_attention_zigzag_parity(rng, sp_mesh, causal, h, hkv, n, d):
    """The striped/zigzag causal-balanced layout is bit-for-bit the same
    attention: operands permuted by zigzag_shard, outputs un-permuted by
    zigzag_unshard, must match the dense oracle on natural order — the
    positions the masks see are the layout's only degree of freedom."""
    from mpi_and_open_mp_tpu.parallel.context import (
        zigzag_shard, zigzag_unshard)

    p = sp_mesh.shape["sp"]
    q = jnp.asarray(rng.standard_normal((h, n, d)), jnp.float32)
    k, v = (jnp.asarray(rng.standard_normal((hkv, n, d)), jnp.float32)
            for _ in range(2))
    qz, kz, vz = (zigzag_shard(x, p) for x in (q, k, v))
    got = zigzag_unshard(
        ring_attention(qz, kz, vz, mesh=sp_mesh, causal=causal,
                       layout="zigzag"), p)
    want = attention_reference(
        q, jnp.repeat(k, h // hkv, axis=0),
        jnp.repeat(v, h // hkv, axis=0), causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("n", [256, 272])  # 272: nl=34, half=17 -> padded
def test_ring_attention_zigzag_chunked(rng, sp_mesh, small_chunks, n):
    """Causal zigzag through the CHUNKED half-folders (fwd + grads): a
    tiny _Q_CHUNK forces the per-half q scans, 272 additionally makes
    the halves non-chunk-multiples so the padding rules fire."""
    from mpi_and_open_mp_tpu.parallel.context import (
        zigzag_shard, zigzag_unshard)

    small_chunks(8)
    p = sp_mesh.shape["sp"]
    h, hkv, d = 4, 2, 8
    q = jnp.asarray(rng.standard_normal((h, n, d)), jnp.float32)
    k, v = (jnp.asarray(rng.standard_normal((hkv, n, d)), jnp.float32)
            for _ in range(2))
    qz, kz, vz = (zigzag_shard(x, p) for x in (q, k, v))

    got = zigzag_unshard(
        ring_attention(qz, kz, vz, mesh=sp_mesh, causal=True,
                       layout="zigzag"), p)
    want = attention_reference(
        q, jnp.repeat(k, h // hkv, axis=0),
        jnp.repeat(v, h // hkv, axis=0), causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)

    def loss_zig(a, b, c):
        return jnp.sum(ring_attention(a, b, c, mesh=sp_mesh, causal=True,
                                      layout="zigzag") ** 2)

    def loss_nat(a, b, c):
        return jnp.sum(attention_reference(
            a, jnp.repeat(b, h // hkv, axis=0),
            jnp.repeat(c, h // hkv, axis=0), causal=True) ** 2)

    g_zig = jax.grad(loss_zig, argnums=(0, 1, 2))(qz, kz, vz)
    g_nat = jax.grad(loss_nat, argnums=(0, 1, 2))(q, k, v)
    for gz, gn in zip(g_zig, g_nat):
        np.testing.assert_allclose(np.asarray(zigzag_unshard(gz, p)),
                                   np.asarray(gn), rtol=1e-4, atol=1e-4)


def test_ring_attention_zigzag_grads(rng, sp_mesh):
    """Zigzag gradients through the ring flash backward match the dense
    oracle's, related by the zigzag permutation (dx_zig = dx_nat[perm])."""
    from mpi_and_open_mp_tpu.parallel.context import (
        zigzag_shard, zigzag_unshard)

    p = sp_mesh.shape["sp"]
    h, n, d = 2, 128, 16
    q, k, v = _qkv(rng, h, n, d)
    qz, kz, vz = (zigzag_shard(x, p) for x in (q, k, v))

    def loss_zig(a, b, c):
        return jnp.sum(ring_attention(a, b, c, mesh=sp_mesh, causal=True,
                                      layout="zigzag") ** 2)

    def loss_nat(a, b, c):
        return jnp.sum(attention_reference(a, b, c, causal=True) ** 2)

    g_zig = jax.grad(loss_zig, argnums=(0, 1, 2))(qz, kz, vz)
    g_nat = jax.grad(loss_nat, argnums=(0, 1, 2))(q, k, v)
    for gz, gn in zip(g_zig, g_nat):
        np.testing.assert_allclose(np.asarray(zigzag_unshard(gz, p)),
                                   np.asarray(gn), rtol=1e-4, atol=1e-4)


def test_ring_attention_zigzag_validation(rng, sp_mesh):
    from mpi_and_open_mp_tpu.parallel.context import (
        zigzag_order, zigzag_shard, zigzag_unshard)

    # seq 136 splits over 8 devices (17 each) but not into 16 half-chunks.
    q, k, v = _qkv(rng, 2, 136, 8)
    with pytest.raises(ValueError, match="zigzag"):
        ring_attention(q, k, v, mesh=sp_mesh, layout="zigzag")
    with pytest.raises(ValueError, match="unknown ring layout"):
        ring_attention(*_qkv(rng, 2, 128, 8), mesh=sp_mesh, layout="typo")
    # The permutation pair is an exact inverse.
    x = jnp.arange(3 * 64 * 4, dtype=jnp.float32).reshape(3, 64, 4)
    np.testing.assert_array_equal(
        np.asarray(zigzag_unshard(zigzag_shard(x, 8), 8)), np.asarray(x))
    # The cached permutations are frozen: a caller mutating the returned
    # array must fail loudly, not silently poison every later shard.
    with pytest.raises(ValueError):
        zigzag_order(64, 8)[0] = 1
    # Shard 0 of 4 owns half-chunks (0, 7): natural slots 0..7 and 56..63.
    order = np.asarray(zigzag_order(64, 4))
    np.testing.assert_array_equal(order[:16],
                                  list(range(8)) + list(range(56, 64)))


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_parity(rng, sp_mesh, causal):
    q, k, v = _qkv(rng, 8, 128, 32)
    got = ulysses_attention(q, k, v, mesh=sp_mesh, causal=causal)
    want = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_ring_vs_ulysses_agree(rng, sp_mesh):
    q, k, v = _qkv(rng, 8, 256, 16)
    a = ring_attention(q, k, v, mesh=sp_mesh, causal=True)
    b = ulysses_attention(q, k, v, mesh=sp_mesh, causal=True)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-5, atol=1e-5)


def test_ring_attention_bf16(rng, sp_mesh):
    # bf16 inputs, fp32 accumulation: loose tolerance vs the fp32 oracle.
    q, k, v = _qkv(rng, 2, 128, 32, dtype=jnp.bfloat16)
    got = ring_attention(q, k, v, mesh=sp_mesh, causal=True)
    assert got.dtype == jnp.bfloat16
    want = attention_reference(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32),
        causal=True)
    np.testing.assert_allclose(
        np.asarray(got, dtype=np.float32), np.asarray(want),
        rtol=0.05, atol=0.05)


def test_ring_attention_grad_parity(rng, sp_mesh):
    # Static ring trip count => fori_loop lowers to scan => reverse-mode
    # differentiable; gradients must match the oracle's.
    q, k, v = _qkv(rng, 2, 64, 16)

    def loss_sharded(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh=sp_mesh, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    g_got = jax.grad(loss_sharded, argnums=(0, 1, 2))(q, k, v)
    g_want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(g_got, g_want):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)


def test_ulysses_attention_grad_parity(rng, sp_mesh):
    q, k, v = _qkv(rng, 8, 64, 16)

    def loss_sharded(q, k, v):
        return jnp.sum(
            ulysses_attention(q, k, v, mesh=sp_mesh, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    g_got = jax.grad(loss_sharded, argnums=(0, 1, 2))(q, k, v)
    g_want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(g_got, g_want):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)


def test_ring_attention_output_sharded(rng, sp_mesh):
    # The result must stay sequence-sharded — no host gather mid-pipeline.
    q, k, v = _qkv(rng, 2, 128, 16)
    out = ring_attention(q, k, v, mesh=sp_mesh)
    assert len(out.sharding.device_set) == 8
    shard_shapes = {s.data.shape for s in out.addressable_shards}
    assert shard_shapes == {(2, 16, 16)}


def test_seq_not_divisible_raises(rng, sp_mesh):
    q, k, v = _qkv(rng, 2, 100, 16)
    with pytest.raises(ValueError, match="not divisible"):
        ring_attention(q, k, v, mesh=sp_mesh)


def test_ulysses_heads_not_divisible_raises(rng, sp_mesh):
    q, k, v = _qkv(rng, 3, 128, 16)
    with pytest.raises(ValueError, match="heads not divisible"):
        ulysses_attention(q, k, v, mesh=sp_mesh)


@pytest.fixture
def small_chunks(monkeypatch):
    """Shrink _Q_CHUNK so the chunked paths run at test sizes, and clear
    the jit caches: the global is baked in at trace time and is NOT part
    of the cache key, so a stale trace from an unpatched test with the
    same signature would silently bypass the chunked code."""
    from mpi_and_open_mp_tpu.parallel import context

    def set_chunk(n):
        monkeypatch.setattr(context, "_Q_CHUNK", n)
        jax.clear_caches()

    yield set_chunk
    jax.clear_caches()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_chunked_parity(rng, sp_mesh, causal, small_chunks):
    small_chunks(16)  # n_local = 64 -> 4 chunks of 16
    q, k, v = _qkv(rng, 2, 512, 16)
    got = ring_attention(q, k, v, mesh=sp_mesh, causal=causal)
    want = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_ring_attention_chunked_nondivisible(rng, sp_mesh, small_chunks):
    """n_local = 72 is not a multiple of the 16-row chunk: the padded-q
    path must still match (no divisibility cliff)."""
    small_chunks(16)
    q, k, v = _qkv(rng, 2, 576, 16)
    got = ring_attention(q, k, v, mesh=sp_mesh, causal=True)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("hq,hkv,n", [(4, 2, 512), (8, 1, 512),
                                      (4, 2, 456)])
def test_ring_gqa_folded_chunked_parity(rng, sp_mesh, hq, hkv, n,
                                        small_chunks):
    """Multi-hop ring with GQA folded rows AND per-fold q chunking (the
    un-expanded-K/V ring path), incl. gradients. n=456 makes n_local=57
    a NON-multiple of the chunk, exercising the g-scaled folded padding
    and the `nl * g` slice."""
    small_chunks(16)  # n_local = 64 (or 57) -> 4 folded chunks
    d = 8
    q = jnp.asarray(rng.standard_normal((hq, n, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((hkv, n, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((hkv, n, d)), jnp.float32)
    g = hq // hkv
    kr, vr = jnp.repeat(k, g, axis=0), jnp.repeat(v, g, axis=0)
    got = ring_attention(q, k, v, mesh=sp_mesh, causal=True)
    want = attention_reference(q, kr, vr, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)

    g_got = jax.grad(
        lambda q_, k_, v_: jnp.sum(
            ring_attention(q_, k_, v_, mesh=sp_mesh, causal=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_want = jax.grad(
        lambda q_, k_, v_: jnp.sum(attention_reference(
            q_, jnp.repeat(k_, g, axis=0), jnp.repeat(v_, g, axis=0),
            causal=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for gg, gw, name in zip(g_got, g_want, "qkv"):
        np.testing.assert_allclose(np.asarray(gg), np.asarray(gw),
                                   rtol=1e-4, atol=1e-4,
                                   err_msg=f"d{name}")


def test_ulysses_attention_chunked_parity(rng, sp_mesh, small_chunks):
    small_chunks(32)  # n_global = 512 -> 16 chunks
    q, k, v = _qkv(rng, 8, 512, 16)
    got = ulysses_attention(q, k, v, mesh=sp_mesh, causal=True)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_ulysses_attention_chunked_nondivisible(rng, sp_mesh, small_chunks):
    """n_global = 520 pads to a chunk multiple; padded k positions must be
    masked out of the softmax, padded q rows discarded."""
    small_chunks(32)
    q, k, v = _qkv(rng, 8, 520, 16)
    got = ulysses_attention(q, k, v, mesh=sp_mesh, causal=False)
    want = attention_reference(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_single_device_ring_delegates_chunked(rng, small_chunks):
    """p=1 rings take the doubly-chunked local path (with causal k-block
    skipping) — parity incl. a non-multiple length."""
    from mpi_and_open_mp_tpu.parallel import mesh as mesh_lib

    small_chunks(16)
    mesh1 = mesh_lib.make_mesh_1d(1, axis="sp")
    for n in (64, 72):
        q, k, v = _qkv(rng, 2, n, 8)
        got = ring_attention(q, k, v, mesh=mesh1, causal=True)
        want = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_chunked_grad_parity(rng, sp_mesh, causal,
                                            small_chunks):
    small_chunks(16)
    q, k, v = _qkv(rng, 2, 256, 8)

    def loss_sharded(q, k, v):
        return jnp.sum(
            ring_attention(q, k, v, mesh=sp_mesh, causal=causal) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=causal) ** 2)

    g_got = jax.grad(loss_sharded, argnums=(0, 1, 2))(q, k, v)
    g_want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(g_got, g_want):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)


def test_ring_attention_bf16_grad(rng, sp_mesh):
    """bf16 primals through the ring flash backward: bf16 grads out
    (f32 accumulation inside), loose tolerance vs the f32 oracle."""
    q, k, v = _qkv(rng, 2, 128, 16, dtype=jnp.bfloat16)

    def loss(q_, k_, v_):
        return jnp.sum(ring_attention(
            q_, k_, v_, mesh=sp_mesh, causal=True).astype(jnp.float32) ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(
        lambda a, b, c: jnp.sum(attention_reference(a, b, c, causal=True)
                                ** 2),
        argnums=(0, 1, 2))(q.astype(jnp.float32), k.astype(jnp.float32),
                           v.astype(jnp.float32))
    for got, want, nm in zip(g, gf, "qkv"):
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(got, dtype=np.float32),
                                   np.asarray(want), rtol=0.1, atol=0.1,
                                   err_msg=f"d{nm}")


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("n", [96, 72])
def test_flash_backward_parity(rng, causal, n, small_chunks):
    """The custom flash backward (recompute-from-logsumexp, two chunked
    passes) must match autodiff of the dense oracle — including causal
    block skipping and a non-multiple length (n=72 pads the last chunk)."""
    from mpi_and_open_mp_tpu.parallel.context import _attention_chunked

    small_chunks(16)
    q, k, v = _qkv(rng, 3, n, 8)

    def loss_flash(q, k, v):
        return jnp.sum(_attention_chunked(q, k, v, causal) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=causal) ** 2)

    g_got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for got, want, name in zip(g_got, g_want, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4,
            err_msg=f"d{name}")


@pytest.mark.parametrize("hq,hkv", [(4, 2), (8, 1)])
@pytest.mark.parametrize("causal", [False, True])
def test_flash_gqa_folded_parity(rng, hq, hkv, causal, small_chunks):
    """The GQA fold path (query groups folded into the row axis, K/V
    un-expanded) through the flash forward AND custom backward, at a
    chunked non-multiple length — vs the dense oracle on repeated K/V."""
    from mpi_and_open_mp_tpu.parallel.context import _attention_chunked

    small_chunks(16)
    n, d = 72, 8
    q = jnp.asarray(rng.standard_normal((hq, n, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((hkv, n, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((hkv, n, d)), jnp.float32)
    g = hq // hkv

    def loss_flash(q_, k_, v_):
        return jnp.sum(_attention_chunked(q_, k_, v_, causal) ** 2)

    def loss_ref(q_, k_, v_):
        return jnp.sum(attention_reference(
            q_, jnp.repeat(k_, g, axis=0), jnp.repeat(v_, g, axis=0),
            causal=causal) ** 2)

    got = _attention_chunked(q, k, v, causal)
    want = attention_reference(q, jnp.repeat(k, g, axis=0),
                               jnp.repeat(v, g, axis=0), causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    g_got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gg, gw, name in zip(g_got, g_want, "qkv"):
        np.testing.assert_allclose(np.asarray(gg), np.asarray(gw),
                                   rtol=1e-4, atol=1e-4,
                                   err_msg=f"d{name}")


def test_flash_backward_bf16_dtypes(rng, small_chunks):
    """bf16 primals get bf16 gradients (f32 accumulation inside)."""
    from mpi_and_open_mp_tpu.parallel.context import _attention_chunked

    small_chunks(16)
    q, k, v = _qkv(rng, 2, 64, 8, dtype=jnp.bfloat16)
    g = jax.grad(
        lambda q_: jnp.sum(_attention_chunked(
            q_, k, v, True).astype(jnp.float32) ** 2))(q)
    assert g.dtype == jnp.bfloat16
    gf = jax.grad(
        lambda q_: jnp.sum(attention_reference(
            q_.astype(jnp.float32), k.astype(jnp.float32),
            v.astype(jnp.float32), causal=True) ** 2))(q.astype(jnp.float32))
    np.testing.assert_allclose(np.asarray(g, dtype=np.float32),
                               np.asarray(gf), rtol=0.1, atol=0.1)


def test_flash_backward_residuals_bounded(rng, small_chunks):
    """The flash backward's memory contract: grad of an (unrolled) chain
    of chunked-attention calls must not materialise any O(seq²) array —
    residuals are (q, k, v, o, logsumexp) per call, recompute does the
    rest. Checked structurally on the jaxpr (every intermediate shape
    bounded below the full score matrix), which is what OOM'd on real
    HBM before the custom_vjp existed."""
    import re
    from functools import reduce

    from mpi_and_open_mp_tpu.parallel.context import _attention_chunked

    small_chunks(16)
    h, n, d = 2, 96, 8
    q, k, v = _qkv(rng, h, n, d)

    def loss(q_):
        c = q_
        for _ in range(3):
            c = _attention_chunked(c, k, v, True)
        return jnp.sum(c ** 2)

    s = str(jax.make_jaxpr(jax.grad(loss))(q))
    score_elems = h * n * n  # full (h, n, n) score matrix
    for m in set(re.findall(r"(?:f32|f16|bf16|bool|pred)\[([0-9,]+)\]", s)):
        dims = [int(x) for x in m.split(",") if x]
        assert reduce(lambda a, b: a * b, dims, 1) < score_elems, (
            f"O(seq^2) intermediate [{m}] in the flash-backward jaxpr")


def test_ring_backward_no_mask_residuals(rng, sp_mesh):
    """The ring backward remats its block updates with the allow-mask
    built INSIDE from position vectors: no boolean mask of block size
    (h, n_local, n_local) may survive as a saved residual in the grad
    jaxpr — a passed-in mask used to be stacked across hops."""
    import re
    from functools import reduce

    h, n, d = 2, 256, 8
    nl = n // 8
    q, k, v = _qkv(rng, h, n, d)

    def loss(q_, k_, v_):
        return jnp.sum(ring_attention(q_, k_, v_, mesh=sp_mesh,
                                      causal=True) ** 2)

    s = str(jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v))
    block_elems = h * nl * nl
    for m in set(re.findall(r"(?:bool|pred)\[([0-9,]+)\]", s)):
        dims = [int(x) for x in m.split(",") if x]
        # One live block mask (the in-backward recompute) is fine; a
        # hop-stacked residual (p, h, nl, nl) is the regression.
        assert reduce(lambda a, b: a * b, dims, 1) <= block_elems, (
            f"stacked mask boolean [{m}] in the ring-backward jaxpr")


def test_ring_flash_backward_residuals_bounded(rng, sp_mesh):
    """The ring backward's memory contract: custom_vjp residuals are
    (q, k, v, o, logsumexp) per shard and the backward recomputes one
    (h, n_local, n_local) block at a time while counter-rotating K/V —
    so NO intermediate in the sharded grad jaxpr may exceed one block
    (= here also the global input size). A hop-stacked residual
    (p, h, nl, nl) — what remat-autodiff used to linearise out of the
    fori_loop — is an order of magnitude over the bound."""
    import re
    from functools import reduce

    h, n, d = 2, 512, 8
    nl = n // 8
    q, k, v = _qkv(rng, h, n, d)

    def loss(q_, k_, v_):
        return jnp.sum(ring_attention(q_, k_, v_, mesh=sp_mesh,
                                      causal=True) ** 2)

    s = str(jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v))
    block_elems = h * nl * nl
    for m in set(re.findall(r"(?:f32|f16|bf16|bool|pred)\[([0-9,]+)\]", s)):
        dims = [int(x) for x in m.split(",") if x]
        assert reduce(lambda a, b: a * b, dims, 1) <= block_elems, (
            f"intermediate [{m}] exceeds one score block in the ring "
            "flash-backward jaxpr")


def test_ulysses_chunked_grad_parity(rng, sp_mesh, small_chunks):
    """The flash backward through shard_map + all_to_all (the Ulysses
    training path)."""
    small_chunks(16)
    q, k, v = _qkv(rng, 8, 256, 8)

    def loss_sharded(q, k, v):
        return jnp.sum(
            ulysses_attention(q, k, v, mesh=sp_mesh, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    g_got = jax.grad(loss_sharded, argnums=(0, 1, 2))(q, k, v)
    g_want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(g_got, g_want):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("hkv", [1, 2, 8])
def test_gqa_kv_head_broadcast(rng, sp_mesh, hkv):
    """GQA/MQA: fewer K/V heads broadcast across query-head groups, for
    both variants, vs an oracle fed the explicitly repeated K/V.
    hkv=8 with hq=16 exercises Ulysses' un-expanded-on-the-wire path
    (hkv % p == 0); hkv in {1, 2} exercises its pre-expansion fallback
    and the ring's per-fold local expansion."""
    hq, n, d = (16, 128, 16) if hkv == 8 else (8, 128, 16)
    q = jnp.asarray(rng.standard_normal((hq, n, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((hkv, n, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((hkv, n, d)), jnp.float32)
    kr = jnp.repeat(k, hq // hkv, axis=0)
    vr = jnp.repeat(v, hq // hkv, axis=0)
    want = attention_reference(q, kr, vr, causal=True)
    for fn in (ring_attention, ulysses_attention):
        got = fn(q, k, v, mesh=sp_mesh, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)


def test_gqa_indivisible_heads_raises(rng, sp_mesh):
    q, k, v = _qkv(rng, 8, 128, 16)
    with pytest.raises(ValueError, match="not a multiple"):
        ring_attention(q, k[:3], v[:3], mesh=sp_mesh)


def test_flash_attention_public_api(rng, small_chunks):
    """The exported single-device flash engine: chunked, GQA, grads."""
    from mpi_and_open_mp_tpu.parallel import flash_attention

    small_chunks(16)
    q = jnp.asarray(rng.standard_normal((4, 72, 8)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, 72, 8)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, 72, 8)), jnp.float32)
    got = flash_attention(q, k, v, causal=True)
    want = attention_reference(q, jnp.repeat(k, 2, axis=0),
                               jnp.repeat(v, 2, axis=0), causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    k3 = jnp.asarray(rng.standard_normal((3, 72, 8)), jnp.float32)
    with pytest.raises(ValueError, match="not a multiple"):
        flash_attention(q, k3, k3)


def test_pallas_dispatch_routing(rng, monkeypatch):
    """The TPU flash-kernel dispatch predicate: routes only equal-head,
    128-multiple-seq, MXU-width-dim, matching-float shapes, only on a
    TPU backend, and only while the engine flag is up — the CPU/oracle
    path must never see the Pallas kernel."""
    from mpi_and_open_mp_tpu.parallel import context

    def qkv(hq=4, hkv=4, n=1024, d=128, dt=jnp.bfloat16, kdt=None):
        q = jnp.zeros((hq, n, d), dt)
        k = jnp.zeros((hkv, n, d), kdt or dt)
        return q, k, jnp.zeros((hkv, n, d), kdt or dt)

    # On the real (cpu) test backend: never eligible.
    assert not context._pallas_flash_eligible(*qkv())

    monkeypatch.setattr(context.jax, "default_backend", lambda: "tpu")
    assert context._pallas_flash_eligible(*qkv())
    # GQA is never DIRECTLY eligible (the kernel wants equal heads)...
    assert not context._pallas_flash_eligible(*qkv(hkv=2))
    # ...but the dispatch plan expands budget-fitting K/V to reach the
    # kernel (chip-measured ~2.7x over the folded jnp path), and the
    # provenance stamp says so.
    assert context._flash_dispatch_plan(*qkv(hkv=2)) == (
        "expand", 1024, 1024, 2)
    assert context.flash_engine_for(*qkv(hkv=2)) == "pallas:b1024:kvx2"
    # Over the expand budget (2 GiB combined K+V) GQA stays on the
    # folded jnp engine. Shape probes only — nothing this size is
    # allocated.
    big = [jax.ShapeDtypeStruct((h, 1 << 20, 128), jnp.bfloat16)
           for h in (8, 2, 2)]
    assert context._flash_dispatch_plan(*big) is None
    assert context.flash_engine_for(*big) == "jnp"
    assert not context._pallas_flash_eligible(*qkv(n=1000))  # seq % 128
    assert not context._pallas_flash_eligible(*qkv(d=64))  # head dim
    assert not context._pallas_flash_eligible(
        *qkv(dt=jnp.float16))  # dtype
    assert not context._pallas_flash_eligible(
        *qkv(kdt=jnp.float32))  # mixed dtypes
    # Auto block: largest chip-validated edge dividing the sequence
    # within the b*d budget AND leaving >= _MIN_GRID programs per grid
    # axis (8k at b1024 measured an 8x8-grid backward collapse — see the
    # _MIN_GRID note), stamped into the shape-aware provenance.
    assert context._flash_block_for(32768) == 1024
    assert context._flash_block_for(16384) == 1024  # grid floor exactly met
    assert context._flash_block_for(8192) == 512  # b1024 would leave 8x8
    # The floor applies at EVERY edge now (the 8k starvation finding
    # extrapolates: a starved grid is a grid property, not a b1024
    # property), so 2k-4k step down to the occupancy-floored edge.
    assert context._flash_block_for(4096) == 256
    assert context._flash_block_for(2048) == 128
    # Sequences too short for ANY edge to form a _MIN_GRID grid take the
    # largest fitting block rather than drop to jnp.
    assert context._flash_block_for(1536) == 512
    assert context._flash_block_for(1280) == 256
    assert context._flash_block_for(384) == 128
    assert context._flash_block_for(32768, d=256) == 512  # budget scales
    assert context._flash_block_for(32768, d=1024) == 128
    assert context._flash_block_for(32768, d=2048) == 0  # no block fits
    assert not context._pallas_flash_eligible(*qkv(d=2048))
    assert context.flash_engine_for(*qkv(n=1024)) == "pallas:b1024"
    assert context.flash_engine_for(*qkv(n=1000)) == "jnp"
    # At or below the chunk size the dispatch short-circuits to the
    # dense reference before any engine — provenance must say so.
    assert context.flash_engine_for(*qkv(n=512)) == "dense"

    # The gate's module-internal force pins the auto choice (so a small
    # gate run exercises a larger timed sequence's configuration)...
    monkeypatch.setattr(context, "_FORCED_BLOCK", 512)
    assert context._flash_block_for(32768) == 512

    # Block-size override tightens the divisibility requirement.
    monkeypatch.setenv("MOMP_FLASH_BLOCK", "512")
    assert context._pallas_flash_eligible(*qkv(n=1024))
    assert not context._pallas_flash_eligible(*qkv(n=1280))  # % 512
    monkeypatch.setattr(context, "_FORCED_BLOCK", 256)
    assert context._flash_block_for(32768) == 512  # ...but env wins
    monkeypatch.setattr(context, "_FORCED_BLOCK", 0)
    # Bad knob values fail loudly with the knob's name, once.
    for bad in ("128k", "96", "-128"):
        monkeypatch.setenv("MOMP_FLASH_BLOCK", bad)
        with pytest.raises(ValueError, match="MOMP_FLASH_BLOCK"):
            context._flash_block_override()
    monkeypatch.delenv("MOMP_FLASH_BLOCK")

    # The backward edge is decoupled: its own knob pins the eight
    # dq/dkv blocks while the forward keeps its auto choice, and the
    # provenance stamp carries both only when they differ.
    monkeypatch.setenv("MOMP_FLASH_BLOCK_BWD", "512")
    assert context._flash_block_for(32768) == 1024
    assert context._flash_bwd_block_for(32768) == 512
    assert context._flash_dispatch_plan(*qkv(n=1024)) == (
        "direct", 1024, 512, 1)
    assert context.flash_engine_for(*qkv(n=1024)) == "pallas:b1024:bw512"
    # ...and the backward edge tightens divisibility on its own axis.
    assert not context._pallas_flash_eligible(*qkv(n=1280))  # % 512
    for bad in ("64", "100"):
        monkeypatch.setenv("MOMP_FLASH_BLOCK_BWD", bad)
        with pytest.raises(ValueError, match="MOMP_FLASH_BLOCK_BWD"):
            context._flash_block_override_bwd()
    monkeypatch.delenv("MOMP_FLASH_BLOCK_BWD")
    # The gate's module-internal backward force mirrors the env knob;
    # unpinned, the backward follows the forward choice exactly.
    monkeypatch.setattr(context, "_FORCED_BLOCK_BWD", 256)
    assert context._flash_bwd_block_for(32768) == 256
    monkeypatch.setattr(context, "_FORCED_BLOCK_BWD", 0)
    assert context._flash_bwd_block_for(32768) == 1024

    monkeypatch.setattr(context, "_TPU_FLASH", False)
    assert not context._pallas_flash_eligible(*qkv())  # kill switch


def test_gated_parity_check_cpu():
    """The recorders' shared honesty gate on the CPU (jnp) engine:
    passes clean for equal-head and GQA/MQA configurations — the GQA
    form checks the gate's group-summed oracle gradients — and reports
    the engine the timed shape will use."""
    from mpi_and_open_mp_tpu.parallel import context

    ok, engine, notes = context.gated_parity_check(n=640)
    assert ok and engine == "jnp" and notes == []
    ok, engine, notes = context.gated_parity_check(n=640, kv_heads=2)
    assert ok and engine == "jnp" and notes == []
    # MQA, with a for_seq (no-op off-TPU: flag-level engine is jnp).
    ok, engine, _ = context.gated_parity_check(
        n=640, kv_heads=1, for_seq=32768)
    assert ok and engine == "jnp"


def test_ring_attention_default_mesh(rng):
    q, k, v = _qkv(rng, 2, 64, 8)
    got = ring_attention(q, k, v, causal=False)
    want = attention_reference(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# The per-hop Pallas ring engine (tentpole): routing, merge math, and
# end-to-end interpret-mode parity on the virtual mesh.


@pytest.fixture
def pallas_interpret(monkeypatch):
    """Force the Pallas engine in interpret mode: flip the trace-time
    module flag and clear jit caches on both sides — the flag is not
    part of any jit cache key, so stale traces from the other setting
    must not be reused (in either direction)."""
    from mpi_and_open_mp_tpu.parallel import context

    jax.clear_caches()
    monkeypatch.setattr(context, "_PALLAS_INTERPRET", True)
    yield context
    jax.clear_caches()


def test_merge_partials_exact(rng):
    """The online-softmax combine of two NORMALISED partials over
    disjoint key sets is the softmax over their union — the identity
    that lets per-hop flash partials fold in any order. Checked exactly
    against the one-shot softmax, and for associativity."""
    from mpi_and_open_mp_tpu.parallel.context import _merge_partials

    h, n, m, d = 2, 16, 24, 8
    q = jnp.asarray(rng.standard_normal((h, n, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((h, m, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((h, m, d)), jnp.float32)

    def partial(ks, vs):
        s = jnp.einsum("hqd,hkd->hqk", q, ks) / np.sqrt(d)
        L = jax.scipy.special.logsumexp(s, axis=-1)
        o = jnp.einsum("hqk,hkd->hqd", jnp.exp(s - L[..., None]), vs)
        return o, L

    o1, L1 = partial(k[:, :10], v[:, :10])
    o2, L2 = partial(k[:, 10:18], v[:, 10:18])
    o3, L3 = partial(k[:, 18:], v[:, 18:])
    want_o, want_L = partial(k, v)

    o12, L12 = _merge_partials(o1, L1, o2, L2)
    got_o, got_L = _merge_partials(o12, L12, o3, L3)
    np.testing.assert_allclose(np.asarray(got_o), np.asarray(want_o),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got_L), np.asarray(want_L),
                               rtol=1e-6, atol=1e-6)
    # Associative: fold (2,3) first instead.
    o23, L23 = _merge_partials(o2, L2, o3, L3)
    alt_o, alt_L = _merge_partials(o1, L1, o23, L23)
    np.testing.assert_allclose(np.asarray(alt_o), np.asarray(got_o),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(alt_L), np.asarray(got_L),
                               rtol=1e-6, atol=1e-6)


def test_ring_hop_engine_routing(monkeypatch):
    """ring_hop_engine_for: per-hop provenance judged at per-SHARD
    granularity — the kernel on eligible hop blocks (GQA via the expand
    form), the jnp fold for causal zigzag / ineligible hop shapes /
    under the MOMP_RING_HOP kill switch, the local engine at p=1."""
    from mpi_and_open_mp_tpu.parallel import context

    def qkv(h=4, hkv=4, n=8192, d=128):
        q = jnp.zeros((h, n, d), jnp.bfloat16)
        k = jnp.zeros((hkv, n, d), jnp.bfloat16)
        return q, k, jnp.zeros((hkv, n, d), jnp.bfloat16)

    # On the real (cpu) test backend hops are jnp — same predicate as
    # the local dispatch, applied to the hop block shape.
    assert context.ring_hop_engine_for(*qkv(), p=8) == "jnp"

    monkeypatch.setattr(context.jax, "default_backend", lambda: "tpu")
    # 8k global over 8 devices -> 1k hop blocks. p >= 3 rings run the
    # double-slot hop prefetch by default and the stamp says so.
    assert context.ring_hop_engine_for(*qkv(), p=8) == "pallas:b1024:pf"
    # GQA hops expand locally per hop; the stamp says so.
    assert (context.ring_hop_engine_for(*qkv(hkv=2), p=8)
            == "pallas:b1024:kvx2:pf")
    # Causal zigzag decomposes each hop into half-chunk kernel calls:
    # eligibility and block edge are judged on the (h, nl/2, d) half
    # shape and the stamp says so (1k hop blocks -> 512 halves).
    # Non-causal zigzag has no masks, so it takes the contiguous form.
    assert context.ring_hop_engine_for(
        *qkv(), p=8, causal=True, layout="zigzag") == "pallas:b512:zz:pf"
    assert context.ring_hop_engine_for(
        *qkv(), p=8, causal=False, layout="zigzag") == "pallas:b1024:pf"
    # MOMP_RING_ZZ=0 pins causal zigzag (and only it) to the jnp fold.
    monkeypatch.setattr(context, "_RING_ZZ", False)
    assert context.ring_hop_engine_for(
        *qkv(), p=8, causal=True, layout="zigzag") == "jnp"
    assert context.ring_hop_engine_for(*qkv(), p=8) == "pallas:b1024:pf"
    monkeypatch.setattr(context, "_RING_ZZ", True)
    # MOMP_RING_PREFETCH=0 drops back to the single-slot schedule (and
    # only that — the hop kernel stays); a 2-device ring has a single
    # transfer, so it never stamps :pf regardless of the gate.
    monkeypatch.setattr(context, "_RING_PREFETCH", False)
    assert context.ring_hop_engine_for(*qkv(), p=8) == "pallas:b1024"
    monkeypatch.setattr(context, "_RING_PREFETCH", True)
    assert context.ring_hop_engine_for(*qkv(n=2048), p=2) \
        == "pallas:b1024"
    # Hop blocks that fail the kernel predicate (seq % 128) fall back.
    assert context.ring_hop_engine_for(*qkv(n=8 * 1000), p=8) == "jnp"
    # A 1-device ring never enters the ring body: local provenance.
    assert (context.ring_hop_engine_for(*qkv(), p=1)
            == "local:pallas:b512")
    # Kill switch pins the ring to the jnp fold oracle.
    monkeypatch.setattr(context, "_RING_HOP", False)
    assert context.ring_hop_engine_for(*qkv(), p=8) == "jnp"


def test_ring_hop_bwd_engine_routing(monkeypatch):
    """ring_hop_bwd_engine_for: the ring BACKWARD's per-hop provenance —
    the repo-owned hop kernels on eligible contiguous hop shapes (edge
    capped at flash_hop_bwd.MAX_BLOCK), the jnp _flash_block_grads fold
    for causal zigzag / ineligible shapes / under MOMP_RING_HOP_BWD=0
    or MOMP_RING_HOP=0, the local engine at p=1."""
    from mpi_and_open_mp_tpu.parallel import context

    def qkv(h=4, hkv=4, n=8192, d=128):
        q = jnp.zeros((h, n, d), jnp.bfloat16)
        k = jnp.zeros((hkv, n, d), jnp.bfloat16)
        return q, k, jnp.zeros((hkv, n, d), jnp.bfloat16)

    assert context.ring_hop_bwd_engine_for(*qkv(), p=8) == "jnp"

    monkeypatch.setattr(context.jax, "default_backend", lambda: "tpu")
    # 1k hop blocks: the forward edge is b1024, the hop backward caps
    # at the kernels' VMEM-budget MAX_BLOCK (512). The K/V trip
    # prefetches exactly as the forward's — the stamp carries :pf.
    assert context.ring_hop_bwd_engine_for(*qkv(), p=8) == "pallas:b512:pf"
    # GQA hops expand per hop, like the forward engine.
    assert (context.ring_hop_bwd_engine_for(*qkv(hkv=2), p=8)
            == "pallas:b512:kvx2:pf")
    # Causal zigzag gradients stay on the jnp fold (the half-chunk
    # decomposition is forward-only); non-causal zigzag is maskless.
    assert context.ring_hop_bwd_engine_for(
        *qkv(), p=8, causal=True, layout="zigzag") == "jnp"
    assert context.ring_hop_bwd_engine_for(
        *qkv(), p=8, causal=False, layout="zigzag") == "pallas:b512:pf"
    # MOMP_RING_PREFETCH=0: single-slot K/V trip, kernel hops stay.
    monkeypatch.setattr(context, "_RING_PREFETCH", False)
    assert context.ring_hop_bwd_engine_for(*qkv(), p=8) == "pallas:b512"
    monkeypatch.setattr(context, "_RING_PREFETCH", True)
    assert context.ring_hop_bwd_engine_for(*qkv(n=8 * 1000), p=8) == "jnp"
    assert (context.ring_hop_bwd_engine_for(*qkv(), p=1)
            == "local:pallas:b512")
    # MOMP_RING_HOP_BWD=0: backward hops fold, forward hops keep the
    # kernel. MOMP_RING_HOP=0 pins both.
    monkeypatch.setattr(context, "_RING_HOP_BWD", False)
    assert context.ring_hop_bwd_engine_for(*qkv(), p=8) == "jnp"
    assert context.ring_hop_engine_for(*qkv(), p=8) == "pallas:b1024:pf"
    monkeypatch.setattr(context, "_RING_HOP_BWD", True)
    monkeypatch.setattr(context, "_RING_HOP", False)
    assert context.ring_hop_bwd_engine_for(*qkv(), p=8) == "jnp"


def test_ring_hop_pinned_pins_both_directions():
    """The chaos-recovery pin (_ring_hop_pinned(False)) must pin BOTH
    hop engines AND the hop prefetch: the :recovered re-dispatch
    promises the full single-slot jnp fold oracle, forward and
    backward."""
    from mpi_and_open_mp_tpu.parallel import context

    assert context._RING_HOP and context._RING_HOP_BWD
    assert context._RING_PREFETCH
    with context._ring_hop_pinned(False):
        assert not context._RING_HOP
        assert not context._RING_HOP_BWD
        assert not context._RING_PREFETCH
        assert not context._ring_prefetch_on(8)
    assert context._RING_HOP and context._RING_HOP_BWD
    assert context._RING_PREFETCH


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("hkv", [4, 2])
def test_ring_hop_flash_interpret_parity(rng, sp_mesh, pallas_interpret,
                                         causal, hkv):
    """End-to-end ring attention with the per-hop Pallas engine engaged
    (interpret mode, 8-virtual-device mesh): forward AND grads must
    match both the dense oracle and the jnp fold it replaced. hkv=2
    exercises the per-hop GQA expand and the folded-L handoff to the
    travelling-dk/dv backward."""
    context = pallas_interpret
    h, n, d = 4, 8 * 128, 128  # 128-per-shard hops: interpret-eligible
    q = jnp.asarray(rng.standard_normal((h, n, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((hkv, n, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((hkv, n, d)), jnp.float32)
    sp_mesh_p = sp_mesh.shape["sp"]

    stamp = context.ring_hop_engine_for(q, k, v, p=sp_mesh_p, causal=causal)
    assert stamp == ("pallas:b128:pf" if hkv == h
                     else "pallas:b128:kvx2:pf")

    kr = jnp.repeat(k, h // hkv, axis=0)
    vr = jnp.repeat(v, h // hkv, axis=0)

    got = ring_attention(q, k, v, mesh=sp_mesh, causal=causal)
    want = attention_reference(q, kr, vr, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)

    # Against the jnp fold oracle it replaced (kill switch flips the
    # trace-time routing; caches cleared so the flip is honoured).
    try:
        context._RING_HOP = False
        jax.clear_caches()
        fold = ring_attention(q, k, v, mesh=sp_mesh, causal=causal)
    finally:
        context._RING_HOP = True
        jax.clear_caches()
    np.testing.assert_allclose(np.asarray(got), np.asarray(fold),
                               rtol=1e-4, atol=1e-4)

    # Grads: the hop engine feeds its merged (o, L) into the same
    # travelling-dk/dv ring backward (the kernel's own vjp is never
    # entered — it is broken under 0.4.37 interpret, so passing proves
    # the custom_vjp contract held).
    def loss(fn, q_, k_, v_):
        return jnp.sum(fn(q_, k_, v_) ** 2)

    g_got = jax.grad(loss, argnums=(1, 2, 3))(
        lambda a, b, c: ring_attention(a, b, c, mesh=sp_mesh,
                                       causal=causal), q, k, v)
    g_want = jax.grad(loss, argnums=(1, 2, 3))(
        lambda a, b, c: attention_reference(
            a, jnp.repeat(b, h // hkv, axis=0),
            jnp.repeat(c, h // hkv, axis=0), causal=causal), q, k, v)
    for got_g, want_g in zip(g_got, g_want):
        assert got_g.shape == want_g.shape
        np.testing.assert_allclose(np.asarray(got_g), np.asarray(want_g),
                                   rtol=1e-3, atol=1e-3)


def test_pallas_flash_interpret_shard_map_single_device(rng,
                                                        pallas_interpret):
    """A 1-device sp mesh with the Pallas dispatch force-engaged
    (interpret mode): shard_map + _pallas_flash must compile together
    and match the dense oracle — the minimal on-chip local dispatch,
    runnable without hardware. Forward only: 0.4.37's interpret
    discharge rule breaks in the kernel backward, which is exactly why
    the ring keeps its own custom_vjp."""
    context = pallas_interpret
    h, n, d = 2, 1024, 128  # n > _Q_CHUNK so the dense short-circuit
    q = jnp.asarray(rng.standard_normal((h, n, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((h, n, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((h, n, d)), jnp.float32)

    # Interpret mode skips the backend check but still wants blk == seq.
    assert context.flash_engine_for(q, k, v) == "pallas:b1024"
    mesh1 = mesh_lib.make_mesh_1d(1, axis="sp")
    got = ring_attention(q, k, v, mesh=mesh1, causal=True)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# The ring BACKWARD hop kernels and the causal-zigzag forward hop
# dispatch (tentpole): block-level kernel parity vs the jnp oracle
# arithmetic, end-to-end interpret parity on the virtual mesh, and the
# MOMP_RING_HOP_BWD / MOMP_RING_ZZ escape hatches.


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("blk", [128, 256])
def test_hop_flash_block_grads_kernel_parity(rng, causal, blk):
    """ops.flash_hop_bwd.hop_block_grads (interpret mode, multi-tile
    grids included) against _flash_block_grads — THE jnp oracle
    arithmetic every ring hop gradient folds with. Same L/D statistics,
    same masking semantics, so the kernels may replace the fold
    block-for-block."""
    from mpi_and_open_mp_tpu.ops import flash_hop_bwd
    from mpi_and_open_mp_tpu.parallel.context import (
        _flash_block_grads, _mask_from_pos)

    h, n, d = 2, 256, 128
    scale = 1.0 / np.sqrt(d)
    q, k, v, do = (jnp.asarray(rng.standard_normal((h, n, d)),
                               jnp.float32) for _ in range(4))
    s = jnp.einsum("hqd,hkd->hqk", q, k) * scale
    if causal:
        s = jnp.where(jnp.tril(jnp.ones((n, n), bool)), s, -1e30)
    L = jax.scipy.special.logsumexp(s, axis=-1)
    o = jnp.einsum("hqk,hkd->hqd", jnp.exp(s - L[..., None]), v)
    D = jnp.sum(do * o, axis=-1)

    pos = jnp.arange(n)
    mask = _mask_from_pos(pos, pos, None, causal)
    want = _flash_block_grads(q, do, L, D, k, v, mask, scale)
    got = flash_hop_bwd.hop_block_grads(
        q, do, flash_hop_bwd.lane_broadcast(L),
        flash_hop_bwd.lane_broadcast(D), k, v, causal=causal, blk=blk,
        interpret=True)
    for name, a, b in zip("dq dk dv".split(), got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4, err_msg=name)


@pytest.mark.parametrize("hkv", [4, 2])
def test_ring_hop_bwd_kill_switch_matches_kernel(rng, sp_mesh,
                                                 pallas_interpret, hkv):
    """MOMP_RING_HOP_BWD=0 must reach the jnp _flash_block_grads fold
    while the FORWARD hops keep the kernel — and the two backward
    engines must agree on the gradients (the fold is the kernel path's
    parity oracle). hkv=2 exercises the per-hop GQA expand and the
    group-summed travelling accumulators."""
    context = pallas_interpret
    h, n, d = 4, 8 * 128, 128
    q = jnp.asarray(rng.standard_normal((h, n, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((hkv, n, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((hkv, n, d)), jnp.float32)
    p = sp_mesh.shape["sp"]

    want_stamp = ("pallas:b128:pf" if hkv == h
                  else "pallas:b128:kvx2:pf")
    assert context.ring_hop_bwd_engine_for(
        q, k, v, p=p, causal=True) == want_stamp

    def loss(q_, k_, v_):
        return jnp.sum(
            ring_attention(q_, k_, v_, mesh=sp_mesh, causal=True) ** 2)

    g_kernel = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    try:
        context._RING_HOP_BWD = False
        jax.clear_caches()
        assert context.ring_hop_bwd_engine_for(
            q, k, v, p=p, causal=True) == "jnp"
        assert context.ring_hop_engine_for(
            q, k, v, p=p, causal=True).startswith("pallas:")
        g_fold = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    finally:
        context._RING_HOP_BWD = True
        jax.clear_caches()
    for name, a, b in zip("dq dk dv".split(), g_kernel, g_fold):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4, err_msg=name)


@pytest.mark.parametrize("hkv", [2, 1])
def test_ring_zigzag_hopflash_interpret_parity(rng, sp_mesh,
                                               pallas_interpret, hkv):
    """Causal zigzag with the per-hop Pallas engine engaged (interpret
    mode, 8-virtual-device mesh): the half-chunk kernel decomposition
    must match the dense oracle AND the jnp zigzag fold it replaced
    (MOMP_RING_ZZ=0), forward and grads — the grads additionally prove
    the lo‖hi (o, L) residual handoff to the zigzag jnp backward."""
    from mpi_and_open_mp_tpu.parallel.context import (
        zigzag_shard, zigzag_unshard)

    context = pallas_interpret
    h, d = 2, 128
    p = sp_mesh.shape["sp"]
    n = p * 256  # 256-token shards -> 128-token halves: interpret-eligible
    q = jnp.asarray(rng.standard_normal((h, n, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((hkv, n, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((hkv, n, d)), jnp.float32)

    stamp = context.ring_hop_engine_for(q, k, v, p=p, causal=True,
                                        layout="zigzag")
    assert stamp == ("pallas:b128:zz:pf" if hkv == h
                     else "pallas:b128:kvx2:zz:pf")
    # Zigzag gradients stay on the jnp fold — truthful provenance.
    assert context.ring_hop_bwd_engine_for(
        q, k, v, p=p, causal=True, layout="zigzag") == "jnp"

    qz, kz, vz = (zigzag_shard(x, p) for x in (q, k, v))
    got = zigzag_unshard(
        ring_attention(qz, kz, vz, mesh=sp_mesh, causal=True,
                       layout="zigzag"), p)
    kr = jnp.repeat(k, h // hkv, axis=0)
    vr = jnp.repeat(v, h // hkv, axis=0)
    want = attention_reference(q, kr, vr, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)

    def loss(q_, k_, v_):
        return jnp.sum(ring_attention(q_, k_, v_, mesh=sp_mesh,
                                      causal=True, layout="zigzag") ** 2)

    g_kernel = jax.grad(loss, argnums=(0, 1, 2))(qz, kz, vz)
    # MOMP_RING_ZZ=0: the jnp zigzag fold, fwd + grads, must agree.
    try:
        context._RING_ZZ = False
        jax.clear_caches()
        assert context.ring_hop_engine_for(
            q, k, v, p=p, causal=True, layout="zigzag") == "jnp"
        fold = zigzag_unshard(
            ring_attention(qz, kz, vz, mesh=sp_mesh, causal=True,
                           layout="zigzag"), p)
        g_fold = jax.grad(loss, argnums=(0, 1, 2))(qz, kz, vz)
    finally:
        context._RING_ZZ = True
        jax.clear_caches()
    np.testing.assert_allclose(np.asarray(got), np.asarray(fold),
                               rtol=1e-4, atol=1e-4)
    for name, a, b in zip("dq dk dv".split(), g_kernel, g_fold):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3, err_msg=name)


def test_ring_hop_engines_chaos_recovery_interplay(rng, sp_mesh,
                                                   pallas_interpret,
                                                   monkeypatch):
    """Chaos-recovery interplay with BOTH hop engines engaged: a
    NaN-poisoned kernel hop must re-dispatch onto the full jnp fold
    oracle (the _ring_hop_pinned(False) recovery trace pins forward AND
    backward kernels off), land finite with oracle parity, and record
    the ``:recovered`` stamp."""
    from mpi_and_open_mp_tpu.robust import chaos, guards

    context = pallas_interpret
    h, n, d = 2, 8 * 128, 128
    q, k, v = (jnp.asarray(rng.standard_normal((h, n, d)), jnp.float32)
               for _ in range(3))
    stamp = context.ring_hop_engine_for(
        q, k, v, p=sp_mesh.shape["sp"], causal=True)
    # The poisoned hop is the PREFETCHED one (:pf): recovery must pin
    # the double-slot schedule off along with both kernels.
    assert stamp.startswith("pallas:") and stamp.endswith(":pf")

    monkeypatch.setenv("MOMP_CHAOS", "nan_hop=2;seed=7")
    chaos.reset()
    guards.clear_recovery_log()
    try:
        out = ring_attention(q, k, v, mesh=sp_mesh, causal=True)
    finally:
        monkeypatch.delenv("MOMP_CHAOS")
        chaos.reset()
        jax.clear_caches()
    assert np.isfinite(np.asarray(out)).all()
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-4, atol=1e-4)
    assert any(s.startswith("ring_attention:jnp:recovered")
               for s in guards.recovery_log())
