"""Chaos fabric: fault injection, guards, watchdog, preemption/resume.

Every recovery path in the robust subsystem, exercised on the 8-virtual-
device CPU mesh: the plan parser, the zero-reachability contract when
``MOMP_CHAOS`` is unset, the engine-fallback ladder, ring-attention hop
poisoning (inject-and-diverge under ``noguard``, inject-and-recover with
guards), halo-corruption recovery in ``LifeSim``, simulated and
signal-driven preemption with checkpoint flush + bit-identical resume,
the watchdog backoff, and the bench error-JSON / exit-75 contracts.
"""

import json
import os
import signal
import sys
import threading

import numpy as np
import pytest

import jax.numpy as jnp

from conftest import oracle_n
from mpi_and_open_mp_tpu.models.life import LifeSim
from mpi_and_open_mp_tpu.parallel import context, mesh as mesh_lib
from mpi_and_open_mp_tpu.robust import chaos, guards, preempt, watchdog
from mpi_and_open_mp_tpu.utils.config import config_from_board

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_chaos():
    """Fresh plan cache and recovery log around every test: the plan
    carries runtime state (the preemption latch) keyed on the env raw."""
    chaos.reset()
    guards.clear_recovery_log()
    yield
    chaos.reset()
    guards.clear_recovery_log()


# --------------------------------------------------------------- plan parsing


def test_fault_plan_parses_full_spec():
    plan = chaos.FaultPlan.parse(
        "nan_hop=1;halo=corrupt;delay=0.25;preempt=60;seed=7")
    assert plan.hop_poison == ("nan", 1)
    assert plan.halo_fault == "corrupt"
    assert plan.delay_s == 0.25
    assert plan.preempt_step == 60
    assert plan.seed == 7
    assert plan.guard  # default armed
    plan = chaos.FaultPlan.parse("inf_hop=3;halo=drop;noguard")
    assert plan.hop_poison == ("inf", 3)
    assert plan.halo_fault == "drop"
    assert not plan.guard


@pytest.mark.parametrize("bad", [
    "nan_hop=x", "halo=melt", "delay=-1", "preempt=ten", "bogus=1", "noguard=1",
    "crash=elsewhere:1", "crash=mid-frame:0", "crash=post-admit:x",
])
def test_fault_plan_rejects_bad_tokens(bad):
    with pytest.raises(ValueError, match="MOMP_CHAOS"):
        chaos.FaultPlan.parse(f"seed=1;{bad}")


def test_crash_token_parses_and_arms(monkeypatch):
    plan = chaos.FaultPlan.parse("crash=mid-frame:3")
    assert plan.crash_site == "mid-frame" and plan.crash_at == 3
    assert chaos.FaultPlan.parse("crash=post-admit").crash_at == 1

    monkeypatch.setenv("MOMP_CHAOS", "crash=post-dispatch:2")
    chaos.reset()
    # Wrong site never counts; the right site fires exactly on arrival k.
    assert not chaos.crash_armed("post-admit")
    assert not chaos.crash_armed("post-dispatch")  # arrival 1 of 2
    with chaos.suppressed():
        assert not chaos.crash_armed("post-dispatch")  # inert, no count
    assert chaos.crash_armed("post-dispatch")  # arrival 2: fire
    assert not chaos.crash_armed("post-dispatch")  # never refires


def test_preempt_pending_latch_and_resume_semantics():
    plan = chaos.FaultPlan.parse("preempt=60")
    assert plan.preempt_pending(0) and plan.preempt_pending(59)
    assert not plan.preempt_pending(60)  # a --resume at the preempt step
    assert not plan.preempt_pending(80)  # ... or past it must continue
    plan.preempt_fired = True
    assert not plan.preempt_pending(0)  # in-process refire latch


# --------------------------------------------- zero reachability when unset


def test_no_injection_when_unset(monkeypatch):
    monkeypatch.delenv("MOMP_CHAOS", raising=False)
    chaos.reset()
    assert chaos.active_plan() is None
    assert chaos.trace_key("ring") is None
    assert chaos.hop_poison_spec() is None
    assert chaos.halo_ghost_spec() is None
    assert chaos.dispatch_delay() == 0.0
    # The halo hook is an identity passthrough — the SAME object, no
    # injection ops built.
    from mpi_and_open_mp_tpu.parallel.halo import _chaos_ghost

    ghost = jnp.ones((2, 8))
    assert _chaos_ghost(ghost) is ghost


def test_suppressed_hides_an_active_plan(monkeypatch):
    monkeypatch.setenv("MOMP_CHAOS", "halo=drop")
    chaos.reset()
    assert chaos.active_plan() is not None
    with chaos.suppressed():
        assert chaos.active_plan() is None
        with chaos.suppressed():  # reentrant
            assert chaos.active_plan() is None
        assert chaos.active_plan() is None
    assert chaos.active_plan() is not None


# ------------------------------------------------------------ with_fallback


def test_with_fallback_first_engine_clean():
    out, stamp, notes = guards.with_fallback(
        [("a", lambda: 1), ("b", lambda: 2)], validator=lambda r: r == 1)
    assert (out, stamp, notes) == (1, "a", [])


def test_with_fallback_recovers_with_provenance():
    calls = []

    def bad():
        calls.append("bad")
        raise RuntimeError("boom")

    out, stamp, notes = guards.with_fallback(
        [("a", bad), ("b", lambda: 7)])
    assert out == 7 and stamp == "b:recovered"
    assert any("boom" in n for n in notes)


def test_with_fallback_validator_failure_and_exhaustion():
    # A validator exception counts as a failure, not a crash.
    with pytest.raises(guards.FallbackExhausted) as ei:
        guards.with_fallback(
            [("a", lambda: 1), ("b", lambda: 2)],
            validator=lambda r: (_ for _ in ()).throw(ValueError("nope")))
    assert "nope" in str(ei.value)
    # Falsy results fall through too (the gated_parity_check usage).
    with pytest.raises(guards.FallbackExhausted):
        guards.with_fallback([("a", lambda: False)], validator=bool)


def test_with_fallback_retries_same_engine():
    attempts = []

    def flaky():
        attempts.append(1)
        if len(attempts) < 2:
            raise RuntimeError("transient")
        return 42

    out, stamp, _ = guards.with_fallback([("a", flaky)], retries=2)
    assert out == 42 and stamp == "a:recovered" and len(attempts) == 2


# ----------------------------------------------------------------- watchdog


def test_watchdog_backoff_schedule_capped():
    assert watchdog.backoff_schedule(5, base_s=2.0, cap_s=10.0) == [
        2.0, 4.0, 8.0, 10.0, 10.0]
    assert watchdog.backoff_schedule(0) == []


def test_watchdog_backoff_generator_jitter_seeded_and_bounded():
    """The jittered schedule is a pure generator: seeded draws are
    reproducible, every wait stays within [base*(1-jitter), base] of the
    un-jittered capped-exponential value, and distinct seeds decorrelate
    (the thundering-herd property a requeue loop of several daemons
    needs)."""
    import itertools

    pure = [1.0, 2.0, 4.0, 8.0, 8.0, 8.0]
    assert list(itertools.islice(watchdog.backoff(1.0, 8.0), 6)) == pure
    a = list(itertools.islice(
        watchdog.backoff(1.0, 8.0, jitter=0.5, seed=3), 6))
    b = list(itertools.islice(
        watchdog.backoff(1.0, 8.0, jitter=0.5, seed=3), 6))
    assert a == b  # seeded: same schedule every time
    for got, base in zip(a, pure):
        assert base * 0.5 <= got <= base
    c = list(itertools.islice(
        watchdog.backoff(1.0, 8.0, jitter=0.5, seed=4), 6))
    assert c != a  # different seed, different herd slot
    with pytest.raises(ValueError, match="jitter"):
        next(watchdog.backoff(jitter=1.5))


def test_watchdog_backoff_schedule_jitter_matches_generator():
    import itertools

    want = list(itertools.islice(
        watchdog.backoff(2.0, 60.0, jitter=0.25, seed=9), 4))
    assert watchdog.backoff_schedule(
        4, base_s=2.0, cap_s=60.0, jitter=0.25, seed=9) == want


def test_watchdog_probe_devices_backs_off_then_degrades():
    probes, slept = [], []

    def probe(timeout_s):
        probes.append(timeout_s)
        return False, "still wedged"

    res = watchdog.probe_devices(
        3.0, attempts=3, backoff_s=2.0, cap_s=60.0,
        probe=probe, sleep=slept.append)
    assert not res.ok and res.degraded
    assert res.attempts == 3 and probes == [3.0, 3.0, 3.0]
    assert slept == [2.0, 4.0] and res.waited_s == 6.0
    assert res.why == "still wedged"


def test_watchdog_probe_devices_succeeds_mid_backoff():
    flips = iter([(False, "once"), (True, "")])
    slept = []
    res = watchdog.probe_devices(
        1.0, attempts=4, probe=lambda t: next(flips), sleep=slept.append)
    assert res.ok and not res.degraded and res.attempts == 2
    assert len(slept) == 1


# ------------------------------------------------- ring-attention hop guard


def _ring_operands(n=256):
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.normal(size=(4, n, 64)), jnp.float32)
               for _ in range(3))
    return q, k, v


def test_ring_nan_hop_noguard_diverges(monkeypatch):
    """Injection must actually land: under ``noguard`` the poisoned hop
    reaches the output as NaN — proof the fault isn't a no-op."""
    monkeypatch.setenv("MOMP_CHAOS", "nan_hop=2;noguard")
    chaos.reset()
    q, k, v = _ring_operands()
    out = context.ring_attention(
        q, k, v, mesh=mesh_lib.make_mesh_1d(axis="sp"), causal=True)
    assert not np.isfinite(np.asarray(out)).all()
    assert guards.recovery_log() == []


def test_ring_nan_hop_guard_recovers(monkeypatch):
    """With guards armed the NaN-poisoned hop engine is re-dispatched on
    the jnp fold oracle under suppression: finite output, oracle parity,
    ``:recovered`` provenance in the process log."""
    monkeypatch.setenv("MOMP_CHAOS", "nan_hop=2;seed=5")
    chaos.reset()
    q, k, v = _ring_operands()
    out = context.ring_attention(
        q, k, v, mesh=mesh_lib.make_mesh_1d(axis="sp"), causal=True)
    assert np.isfinite(np.asarray(out)).all()
    want = context.attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(want), atol=2e-5, rtol=1e-5)
    assert any(s.startswith("ring_attention:jnp:recovered")
               for s in guards.recovery_log())


def test_ring_guard_env_clean_pass_no_recovery(monkeypatch):
    """MOMP_GUARD=1 arms validation without chaos: a healthy dispatch
    passes first try and records nothing."""
    monkeypatch.delenv("MOMP_CHAOS", raising=False)
    monkeypatch.setenv("MOMP_GUARD", "1")
    chaos.reset()
    q, k, v = _ring_operands()
    out = context.ring_attention(
        q, k, v, mesh=mesh_lib.make_mesh_1d(axis="sp"), causal=True)
    want = context.attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(want), atol=2e-5, rtol=1e-5)
    assert guards.recovery_log() == []


# ------------------------------------------------------- LifeSim halo guard


def test_halo_drop_noguard_diverges(monkeypatch, make_board):
    """A dropped halo row without guards must corrupt the run — the
    injection-reaches-the-exchange proof for the LifeSim layer."""
    board = make_board(32, 32)
    cfg = config_from_board(board, steps=6, save_steps=0)
    monkeypatch.setenv("MOMP_CHAOS", "halo=drop;noguard")
    chaos.reset()
    sim = LifeSim(cfg, layout="row", impl="halo")
    final = sim.run(save=False)
    assert not np.array_equal(final, oracle_n(board, 6))
    assert sim.recoveries == []


@pytest.mark.parametrize("fault", ["corrupt", "drop"])
def test_halo_fault_guard_recovers_bit_identical(monkeypatch, make_board,
                                                 fault):
    """The consistency probe catches both halo fault kinds (Life output
    is always binary — only the single-step oracle probe can see them)
    and the suppressed re-trace recovers bit-identically."""
    board = make_board(32, 32)
    cfg = config_from_board(board, steps=12, save_steps=4)
    monkeypatch.setenv("MOMP_CHAOS", f"halo={fault};seed=3")
    chaos.reset()
    sim = LifeSim(cfg, layout="row", impl="halo")
    final = sim.run(save=False)
    np.testing.assert_array_equal(final, oracle_n(board, 12))
    assert sim.recoveries and "recovered" in sim.recoveries[0]
    assert guards.recovery_log()


def test_halo_guard_cart_layout(monkeypatch, make_board):
    """Same recovery through the 2-D cart exchange (both axes faulted)."""
    board = make_board(32, 24)
    cfg = config_from_board(board, steps=8, save_steps=0)
    monkeypatch.setenv("MOMP_CHAOS", "halo=corrupt;seed=11")
    chaos.reset()
    sim = LifeSim(cfg, layout="cart", impl="halo")
    final = sim.run(save=False)
    np.testing.assert_array_equal(final, oracle_n(board, 8))
    assert sim.recoveries


# ------------------------------------------------------ preemption + resume


def test_simulated_preemption_checkpoint_resume_bit_identity(
        monkeypatch, make_board, tmp_path):
    """The acceptance cycle: preempt at step 60 with checkpoints every
    20, resume from the flushed checkpoint, finish — bit-identical to an
    uninterrupted 100-step oracle run."""
    board = make_board(32, 32)
    cfg = config_from_board(board, steps=100, save_steps=0)
    ck = tmp_path / "ck"
    monkeypatch.setenv("MOMP_CHAOS", "preempt=60")
    chaos.reset()
    sim = LifeSim(cfg, layout="row", impl="halo",
                  checkpoint_dir=ck, checkpoint_every=20)
    with pytest.raises(preempt.SimulatedPreemption) as ei:
        sim.run()
    assert ei.value.step == 60
    assert ei.value.checkpoint.endswith("step_000060")
    assert sorted(os.listdir(ck)) == [
        "step_000020", "step_000040", "step_000060"]

    # Cross-process resume: fresh plan cache (new latch); the preempt
    # spec still set, but preempt_pending(60) is False — must NOT refire.
    chaos.reset()
    resumed = LifeSim.from_checkpoint(
        ck / "step_000060", cfg, layout="cart", impl="halo",
        checkpoint_dir=ck, checkpoint_every=20)
    assert resumed.step_count == 60
    final = resumed.run()
    np.testing.assert_array_equal(final, oracle_n(board, 100))


def test_preemption_without_checkpoint_dir(monkeypatch, make_board):
    """No checkpoint_dir: the preemption still fires (the run must not
    silently complete under a preempt plan), with no checkpoint path."""
    board = make_board(16, 16)
    cfg = config_from_board(board, steps=20, save_steps=0)
    monkeypatch.setenv("MOMP_CHAOS", "preempt=10;noguard")
    chaos.reset()
    sim = LifeSim(cfg, layout="row", impl="halo")
    with pytest.raises(preempt.SimulatedPreemption) as ei:
        sim.run(save=True)
    assert ei.value.checkpoint is None


def test_sigterm_flushes_checkpoint_and_resumes(monkeypatch, make_board,
                                                tmp_path):
    """A real SIGTERM mid-run: the handler only sets a flag; the loop
    flushes a checkpoint at the next segment boundary and raises
    Preempted(signum=SIGTERM); resume is bit-identical. The chaos delay
    paces segments so the timer lands deterministically mid-run."""
    board = make_board(24, 24)
    cfg = config_from_board(board, steps=100, save_steps=0)
    ck = tmp_path / "ck"
    monkeypatch.setenv("MOMP_CHAOS", "delay=0.05;noguard")
    chaos.reset()
    sim = LifeSim(cfg, layout="row", impl="halo",
                  checkpoint_dir=ck, checkpoint_every=5)
    # Safety net: if the run somehow finishes first, a late SIGTERM must
    # hit this ignore-handler, not pytest's default (process death).
    prev = signal.signal(signal.SIGTERM, lambda *a: None)
    timer = threading.Timer(
        0.12, os.kill, (os.getpid(), signal.SIGTERM))
    try:
        timer.start()
        with pytest.raises(preempt.Preempted) as ei:
            sim.run()
    finally:
        timer.cancel()
        signal.signal(signal.SIGTERM, prev)
    assert ei.value.signum == signal.SIGTERM
    assert 0 < ei.value.step < 100
    assert ei.value.checkpoint and os.path.isdir(ei.value.checkpoint)

    monkeypatch.delenv("MOMP_CHAOS")
    chaos.reset()
    from mpi_and_open_mp_tpu.apps.life import find_latest_checkpoint

    path, step = find_latest_checkpoint(str(ck))
    assert step == ei.value.step
    resumed = LifeSim.from_checkpoint(path, cfg, layout="row", impl="halo")
    np.testing.assert_array_equal(resumed.run(save=False),
                                  oracle_n(board, 100))


def test_flush_on_signal_restores_handlers():
    prev = signal.getsignal(signal.SIGTERM)
    with preempt.flush_on_signal() as watch:
        assert watch.fired is None
        assert signal.getsignal(signal.SIGTERM) is not prev
    assert signal.getsignal(signal.SIGTERM) is prev
    with preempt.flush_on_signal(enabled=False):
        assert signal.getsignal(signal.SIGTERM) is prev  # no-op when off


# ------------------------------------------------------------ fabric delay


def test_fabric_ping_carries_injected_delay(monkeypatch):
    import time as time_lib

    from mpi_and_open_mp_tpu.parallel import fabric

    mesh = mesh_lib.make_mesh_1d(axis="i")
    base = fabric.ping(mesh, 1, reps=2)
    monkeypatch.setenv("MOMP_CHAOS", "delay=0.1;noguard")
    chaos.reset()
    t0 = time_lib.perf_counter()
    delayed = fabric.ping(mesh, 1, reps=2)
    assert time_lib.perf_counter() - t0 >= 0.1
    assert delayed * 2 >= 0.1  # the delay lands INSIDE the timed bracket
    assert delayed > base


# ------------------------------------------------------- bench driver paths


def _import_bench():
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    import bench

    return bench


def test_bench_error_json_carries_phase(tmp_path, capsys, monkeypatch):
    """A failure mid-bench prints {"metric","error","phase"} and exits 1
    instead of dying on a traceback with no line."""
    bench = _import_bench()
    monkeypatch.setattr(bench, "_probe_devices",
                        lambda timeout_s: (False, "stubbed"))
    empty = tmp_path / "empty"
    empty.mkdir()
    rc = bench.main(["--board", "32", "--steps", "16",
                     "--checkpoint-dir", str(empty), "--resume"])
    assert rc == 1
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["metric"] == "life_steady_cups_p46gun_big"
    assert rec["phase"] == "checkpoint"
    assert "no checkpoints" in rec["error"]


def test_bench_chaos_preempt_then_resume(tmp_path, capsys, monkeypatch):
    """The CI chaos smoke, in-process: a chaos preemption exits 75 with
    "resume": true; the --resume invocation completes with oracle parity
    and resumed-step provenance in the bench line."""
    bench = _import_bench()
    monkeypatch.setattr(bench, "_probe_devices",
                        lambda timeout_s: (False, "stubbed"))
    ck = tmp_path / "ck"
    monkeypatch.setenv("MOMP_CHAOS", "preempt=60")
    chaos.reset()
    rc = bench.main(["--board", "48", "--steps", "100",
                     "--checkpoint-dir", str(ck), "--checkpoint-every", "20"])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == preempt.EXIT_PREEMPTED == 75
    assert rec["resume"] is True and rec["phase"] == "checkpoint"
    assert "step 60" in rec["error"]

    monkeypatch.delenv("MOMP_CHAOS")
    chaos.reset()
    rc = bench.main(["--board", "48", "--steps", "100",
                     "--checkpoint-dir", str(ck), "--checkpoint-every", "20",
                     "--resume"])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert rec["resumed_step"] == 60
    assert rec["checkpoint_parity"] is True
    assert rec["degraded"] is True  # stubbed probe -> honest CPU label
    assert "backend_fallback" in rec


def test_bench_resume_requires_checkpoint_dir(capsys):
    bench = _import_bench()
    with pytest.raises(SystemExit) as ei:
        bench.main(["--resume"])
    assert ei.value.code == 2


def test_life_cli_preempt_exits_75(tmp_path, capsys, make_board, monkeypatch):
    """The life CLI translates Preempted to exit 75 (EX_TEMPFAIL) — the
    contract tpu_queue_loop.sh keys its requeue on."""
    from mpi_and_open_mp_tpu.apps import life as life_app
    from mpi_and_open_mp_tpu.utils.config import save_config

    cfg = config_from_board(make_board(16, 16), steps=20, save_steps=0)
    cfg_path = tmp_path / "run.cfg"
    save_config(cfg_path, cfg)
    ck = tmp_path / "ck"
    monkeypatch.setenv("MOMP_CHAOS", "preempt=10;noguard")
    chaos.reset()
    rc = life_app.main([str(cfg_path), "--layout", "row", "--impl", "halo",
                        "--checkpoint-dir", str(ck),
                        "--checkpoint-every", "5"])
    assert rc == 75
    assert "requeue with --resume" in capsys.readouterr().err
    assert "step_000010" in os.listdir(ck)

    monkeypatch.delenv("MOMP_CHAOS")
    chaos.reset()
    capsys.readouterr()
    rc = life_app.main([str(cfg_path), "--layout", "row", "--impl", "halo",
                        "--checkpoint-dir", str(ck), "--resume"])
    assert rc == 0
    assert "resuming from checkpoint" in capsys.readouterr().err
