"""Driver entry-point contracts."""

import sys
import os

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__ as graft

from mpi_and_open_mp_tpu.ops.life_ops import life_step_numpy


def test_entry_jittable_and_correct():
    import jax

    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    np.testing.assert_array_equal(
        np.asarray(out), life_step_numpy(np.asarray(args[0]))
    )


def test_dryrun_multichip_8():
    graft.dryrun_multichip(8)
