"""Multi-process (multi-host-style) runtime: real ``jax.distributed``.

Launches TWO separate Python processes that bootstrap via
``jax.distributed.initialize`` (the framework's ``MPI_Init`` equivalent,
SURVEY §2's comm-backend mapping) and run cross-process collectives over
the Gloo CPU backend — the closest single-machine stand-in for a
multi-host DCN pod. Exercises the same multi-process runtime the
``--distributed`` CLI flag initialises (the flag's argless auto-detect
``initialize()`` needs a real pod environment; here the coordinator is
passed explicitly) and the non-fully-addressable ``collect()`` +
process-0-only snapshot write.
"""

import os
import socket
import subprocess
import sys

import pytest

import conftest

HERE = os.path.dirname(os.path.abspath(__file__))
WORKER = os.path.join(HERE, "_dist_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


pytestmark = pytest.mark.skipif(
    not conftest.multiprocess_cpu_supported(),
    reason="installed jaxlib's CPU backend cannot compile multi-process SPMD")


def test_two_process_distributed_run():
    coord = f"localhost:{_free_port()}"
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(i), "2", coord],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
            text=True,
        )
        for i in range(2)
    ]
    try:
        outs = [p.communicate(timeout=180) for p in procs]
    finally:
        # A worker hung in a collective would otherwise outlive the test,
        # holding the coordinator port.
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
    assert "DIST_OK" in outs[0][0]
