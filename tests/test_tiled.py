"""Row-tiled HBM kernel parity (the 8192^2-class path, interpret mode)."""

import numpy as np
import pytest

import jax.numpy as jnp

from conftest import oracle_n
from mpi_and_open_mp_tpu.ops import pallas_life


@pytest.mark.parametrize("shape", [(16, 128), (48, 40), (100, 250)])
def test_tiled_step_matches_oracle(make_board, shape):
    b = make_board(*shape)
    out = pallas_life.life_step_tiled(jnp.asarray(b))
    np.testing.assert_array_equal(np.asarray(out), oracle_n(b, 1))


def test_tiled_multi_step(make_board):
    b = make_board(64, 96)
    out = pallas_life._run_tiled_jit(
        jnp.asarray(b).astype(jnp.int32),
        jnp.asarray([5], jnp.int32),
        interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(out), oracle_n(b, 5))


def test_tile_rows_divisor_and_cap():
    # 8192 wide, int32: cap = 2^21/(4*8192)-2 = 62 rows; largest divisor
    # of 8192 at or under 62 is 32.
    assert pallas_life._tile_rows(8192, 8192) == 32
    assert pallas_life._tile_rows(100, 250) in range(1, 101)
    assert 100 % pallas_life._tile_rows(100, 250) == 0
    # Small prime ny under the cap: the whole board is one tile.
    assert pallas_life._tile_rows(97, 128) == 97
    # Prime ny over the cap degenerates to 1-row tiles but still divides.
    assert pallas_life._tile_rows(101, 1 << 19) == 1


def test_padded_tiled_kernel_direct(make_board):
    """The row-tiled padded kernel itself (driven directly in interpret
    mode on a small block; the public path only uses it compiled on TPU)."""
    from mpi_and_open_mp_tpu.ops.life_ops import pad_x_wrap, pad_y_wrap

    b = make_board(60, 84, density=0.3)
    padded = pad_x_wrap(pad_y_wrap(jnp.asarray(b))).astype(jnp.int32)
    out = pallas_life._step_tiled_padded(padded, interpret=True)
    np.testing.assert_array_equal(np.asarray(out), oracle_n(b, 1))


def test_life_run_vmem_large_board_fallback(make_board):
    """On non-TPU backends, big boards take the compiled roll loop (never
    interpret-mode Pallas) and stay bit-exact."""
    big = (1056, 1056)
    assert not pallas_life.fits_vmem(big)
    b = make_board(*big, density=0.2)
    out = pallas_life.life_run_vmem(jnp.asarray(b), 2)
    np.testing.assert_array_equal(np.asarray(out), oracle_n(b, 2))


def test_tiled_supported_bounds():
    assert pallas_life.tiled_supported((8192, 8192))
    assert not pallas_life.tiled_supported((8, 1 << 21))
