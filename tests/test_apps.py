"""CLI driver contracts: stdout formats, times.txt accumulation, VTK output."""

import json
import os

import numpy as np

from mpi_and_open_mp_tpu.apps import integral as integral_app
from mpi_and_open_mp_tpu.apps import life as life_app
from mpi_and_open_mp_tpu.apps import pingpong as pingpong_app
from mpi_and_open_mp_tpu.ops.life_ops import life_step_numpy
from mpi_and_open_mp_tpu.utils.config import load_config_py
from mpi_and_open_mp_tpu.utils.vtk import read_vtk

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


def test_life_cli_stdout_contract(tmp_path, capsys):
    cfg_path = os.path.join(FIXTURES, "glider_10x10.cfg")
    outdir = tmp_path / "vtk"
    times = tmp_path / "times.txt"
    rc = life_app.main(
        [cfg_path, "--layout", "row", "--impl", "roll",
         "--outdir", str(outdir), "--times-file", str(times)]
    )
    assert rc == 0
    out = capsys.readouterr().out.strip().split("\n")
    assert len(out) == 1  # ONE line: bare elapsed seconds
    float(out[0])
    # times.txt got the same accumulation the reference launchers produce.
    assert len(times.read_text().strip().split("\n")) == 1
    # Snapshots at the cfg cadence, parity vs oracle.
    cfg = load_config_py(cfg_path)
    b = cfg.board()
    for _ in range(25):
        b = life_step_numpy(b)
    np.testing.assert_array_equal(read_vtk(outdir / "life_000025.vtk"), b)


def test_life_cli_mesh_flag(tmp_path, capsys):
    rc = life_app.main(
        [os.path.join(FIXTURES, "rpentomino_40x32.cfg"),
         "--layout", "cart", "--mesh", "2,4", "--impl", "halo",
         "--fuse-steps", "4"]
    )
    assert rc == 0
    float(capsys.readouterr().out.strip())


def test_integral_cli(capsys):
    rc = integral_app.main(["100000", "--devices", "8", "--print-value"])
    assert rc == 0
    captured = capsys.readouterr()
    float(captured.out.strip())
    assert "3.14" in captured.err


def test_integral_cli_truncate_32bit(capsys):
    rc = integral_app.main(["4294967297", "--truncate-32bit", "--devices", "1"])
    assert rc == 0  # 2^32+1 -> 1 trapezoid after truncation


def test_attention_cli(capsys):
    from mpi_and_open_mp_tpu.apps import attention

    for extra in (["--variant", "ring"], ["--variant", "ulysses"],
                  ["--variant", "ring", "--ring-layout", "zigzag"]):
        rc = attention.main(extra + [
            "--seq", "256", "--heads", "8",
            "--head-dim", "16", "--causal", "--dtype", "float32",
        ])
        assert rc == 0
        out = capsys.readouterr()
        float(out.out.strip().splitlines()[0])  # elapsed-seconds contract
        assert "parity ok" in out.err


def test_pingpong_cli(tmp_path, capsys):
    out_csv = tmp_path / "out.csv"
    rc = pingpong_app.main(
        ["--devices", "2", "--reps", "2", "--max-power", "2",
         "--out", str(out_csv), "--fit"]
    )
    assert rc == 0
    captured = capsys.readouterr()
    lines = captured.out.strip().split("\n")
    assert lines[0] == "size,time"
    # header + sizes 1,10,100 + the --fit JSON tail line
    assert len(lines) == 5
    fit = json.loads(lines[-1])
    assert fit["metric"] == "pingpong_fit"
    assert {"alpha_us", "beta_us_per_byte", "bandwidth_mb_s", "r2",
            "identifiable"} <= fit.keys()
    assert "alpha=" in captured.err
    assert out_csv.exists()
