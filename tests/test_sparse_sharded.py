"""Sparse x sharded engine (PR 16): activation crossing shards, the
dead-boundary exchange skip, the crossover/kill-switch fallbacks, and
the sentinel/ledger provenance plumbing.

Everything runs on the conftest 8-virtual-device CPU mesh; parity is
always against the NumPy oracle (``conftest.oracle_n``) or the dense
sharded runner — the same gates ``bench.py --sparse-sharded-ab`` uses.
"""

import numpy as np
import pytest

from tests.conftest import oracle_n

from mpi_and_open_mp_tpu import stencils
from mpi_and_open_mp_tpu.parallel import mesh as mesh_lib
from mpi_and_open_mp_tpu.stencils import engine as stencil_engine
from mpi_and_open_mp_tpu.stencils import sparse_sharded
from mpi_and_open_mp_tpu.stencils.sparse_sharded import SparseShardedEngine

LIFE = stencils.get("life")

GLIDER = np.array([[0, 1, 0], [0, 0, 1], [1, 1, 1]], np.uint8)


def _mesh(layout):
    if layout == "cart":
        return mesh_lib.make_mesh_2d()
    return mesh_lib.make_mesh_1d(axis="x" if layout == "col" else "y")


def _glider_board():
    """A 128^2 board whose glider crosses every row- and col-shard edge
    over 80 steps (8-way row shards are 16 rows deep; the glider starts
    at the origin corner and walks the diagonal), plus a blinker and a
    block to keep oscillating and settled regions in play."""
    board = np.zeros((128, 128), np.uint8)
    board[1:4, 1:4] = GLIDER
    board[60, 60:63] = 1
    board[100:102, 36:38] = 1
    board[100:102, 38] = 0  # make it a domino -> dies, then quiet
    return board


@pytest.mark.parametrize("layout", ["row", "col", "cart"])
def test_glider_crosses_shard_edges(layout):
    board = _glider_board()
    eng = SparseShardedEngine(LIFE, board, mesh=_mesh(layout),
                              layout=layout, tile=16)
    done = 0
    # Awkward checkpoints on purpose: 5 and 37 land mid-fused-round, so
    # the tail (fuse < engine.fuse) program paths get parity coverage.
    for n in (5, 16, 37, 80):
        eng.step(n - done)
        done = n
        np.testing.assert_array_equal(eng.snapshot(), oracle_n(board, n))
    assert eng.engine_stamp.startswith("sparse-sharded:")
    assert eng.engine_stamp == f"sparse-sharded:{layout}:t16"
    c = eng.counters()
    assert c["sparse_steps"] > 0
    assert c["tiles_skipped"] > c["tiles_stepped"]


def test_exchange_skip_is_bit_exact_and_counted():
    """Interior-only activity: the twin with the skip enabled must ship
    no ghosts on dead-boundary rounds yet stay bit-identical to the
    always-exchange twin (the zero sentinel replaces provably-zero
    ghosts)."""
    board = np.zeros((256, 256), np.uint8)
    # Blinkers in shard interiors (row shards are 32 deep): rows 8 and
    # 72 keep every oscillation >= 4 rows from any shard boundary band.
    board[8, 100:103] = 1
    board[72, 40:43] = 1
    mesh = mesh_lib.make_mesh_1d()
    kw = dict(mesh=mesh, layout="row", tile=32, fuse=4)
    on = SparseShardedEngine(LIFE, board, **kw)
    off = SparseShardedEngine(LIFE, board, exchange_skip=False, **kw)
    on.step(48)
    off.step(48)
    np.testing.assert_array_equal(on.snapshot(), off.snapshot())
    np.testing.assert_array_equal(on.snapshot(), oracle_n(board, 48))
    assert on.counters()["exchange_skips"] > 0
    assert off.counters()["exchange_skips"] == 0
    assert off.counters()["exchange_rounds"] > 0


def test_fused_wake_survives_oscillators():
    """A period-2 blinker with fuse=2: initial-vs-final diffing would
    see identical frames and put the tile to sleep mid-oscillation; the
    consecutive-state wake diff must keep it alive."""
    board = np.zeros((128, 128), np.uint8)
    board[40, 40:43] = 1
    eng = SparseShardedEngine(LIFE, board, mesh=mesh_lib.make_mesh_1d(),
                              layout="row", tile=16, fuse=2)
    eng.step(13)  # odd: ends mid-period
    np.testing.assert_array_equal(eng.snapshot(), oracle_n(board, 13))
    assert eng.active.any(), "oscillating tile fell asleep"


def test_settled_board_stops_dispatching():
    """A still life settles the whole mask; subsequent steps are pure
    bookkeeping (settled_steps) and stay bit-exact."""
    board = np.zeros((128, 128), np.uint8)
    board[40:42, 40:42] = 1  # block
    eng = SparseShardedEngine(LIFE, board, mesh=mesh_lib.make_mesh_1d(),
                              layout="row", tile=16)
    eng.step(96)
    np.testing.assert_array_equal(eng.snapshot(), board)
    assert eng.counters()["settled_steps"] > 0
    assert not eng.active.any()


def test_crossover_falls_back_dense(make_board):
    """A dense random board exceeds the crossover fraction every round:
    all steps run the dense sharded runner, stamped dense:crossover,
    still oracle-exact."""
    board = make_board(128, 128, density=0.35)
    eng = SparseShardedEngine(LIFE, board, mesh=mesh_lib.make_mesh_1d(),
                              layout="row", tile=16, crossover=0.05)
    eng.step(8)
    np.testing.assert_array_equal(eng.snapshot(), oracle_n(board, 8))
    assert eng.engine_stamp == "dense:crossover"
    assert eng.counters()["sparse_steps"] == 0


def test_bit_identity_vs_dense_sharded():
    """The reassembled sparse-sharded board equals the dense sharded
    schedule bit-for-bit — the same gate the bench A/B enforces."""
    board = _glider_board()
    mesh = mesh_lib.make_mesh_1d()
    eng = SparseShardedEngine(LIFE, board, mesh=mesh, layout="row",
                              tile=16)
    eng.step(64)
    run, _plan = stencil_engine.make_sharded_runner(
        LIFE, mesh, "row", board.shape)
    import jax
    from jax.sharding import NamedSharding

    dev = jax.device_put(
        np.asarray(board),
        NamedSharding(mesh, stencil_engine.sharded_pspec("row", 1)))
    np.testing.assert_array_equal(eng.snapshot(), np.asarray(run(dev, 64)))


def test_kill_switch_downgrades_to_dense_sharded(monkeypatch):
    monkeypatch.setenv(sparse_sharded.ENV_SPARSE_SHARDED, "0")
    board = _glider_board()
    eng = SparseShardedEngine(LIFE, board, mesh=mesh_lib.make_mesh_1d(),
                              layout="row", tile=16)
    assert not eng.plan.enabled
    assert sparse_sharded.ENV_SPARSE_SHARDED in eng.plan.why
    eng.step(32)
    np.testing.assert_array_equal(eng.snapshot(), oracle_n(board, 32))
    assert eng.engine_stamp == "dense:sharded"
    assert eng.counters()["sparse_steps"] == 0


def test_plan_gates():
    plan = sparse_sharded.plan_sparse_sharded("row", (8, 1), (16, 128),
                                              1, 32)
    assert not plan.enabled and "divide" in plan.why
    plan = sparse_sharded.plan_sparse_sharded("row", (8, 1), (32, 256),
                                              1, 32)
    assert plan.enabled and plan.engine == "sparse-sharded:row:t32"


def test_tuner_lists_sparse_sharded_candidate():
    from mpi_and_open_mp_tpu.tune import space

    mesh = mesh_lib.make_mesh_1d()
    cands = space.sharded_candidates(
        "life", (8 * space.SPARSE_SHARDED_TILE,
                 8 * space.SPARSE_SHARDED_TILE), mesh)
    paths = [c.path for c in cands]
    assert "sharded:row" in paths, "dense legs must stay in the race"
    assert "sparse_sharded:row" in paths
    sp = next(c for c in cands if c.path == "sparse_sharded:row")
    assert sp.halo_overlap == "sparse"
    # Dense legs enumerate FIRST: the heuristic baseline stays seeded.
    assert paths.index("sharded:row") < paths.index("sparse_sharded:row")


def test_sentinel_and_ledger_plumbing():
    from analysis import regression_sentinel as sentinel
    from mpi_and_open_mp_tpu.obs import ledger

    for f in ("sparse_sharded_cups", "sparse_sharded_vs_dense",
              "sparse_sharded_vs_single"):
        assert f in sentinel.WATCH_FIELDS
        assert sentinel.direction_for(f) == "higher"
    assert "sparse_sharded_engine" in sentinel.PROVENANCE_FIELDS
    # The kill-switch downgrade must be visible to the rank compare.
    assert (sentinel.engine_rank("sparse-sharded:row:t64")
            > sentinel.engine_rank("dense:sharded"))
    assert (sentinel.engine_rank("sparse-sharded:row:t64")
            > sentinel.engine_rank("dense:crossover"))
    assert "sparse" in ledger.KEY_FIELDS
    entry = ledger.stamp({"metric": "m", "board": [64, 64],
                          "sparse_sharded_engine": "sparse-sharded:row:t64"},
                         platform="cpu", device_count=8)
    assert entry["key"]["sparse"] == "sparse-sharded:row:t64"
    # Lines that only ran the single-device sparse phase keep its stamp.
    entry = ledger.stamp({"metric": "m", "board": [64, 64],
                          "sparse_engine": "sparse:t64"},
                         platform="cpu", device_count=8)
    assert entry["key"]["sparse"] == "sparse:t64"
    # Pre-PR-16 entries match new "-" lines through the key defaults.
    old = {"key": {f: "x" for f in ledger.KEY_FIELDS if f != "sparse"}}
    assert "sparse=-" in ledger.config_key(old, ("sparse",))
