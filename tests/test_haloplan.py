"""Persistent halo plans: derivation, overlap/sequential bit-identity, fuzz.

The PR-15 contract (``parallel/haloplan.py``): a frozen plan per (mesh
topology, shard shape, depth, pack layout) splits each fused round into
an interior partition computed while the ghost ``ppermute`` flies and two
boundary strips computed after it lands — and the reassembled shard must
equal the sequential whole-shard round bit-for-bit, for every registry
spec (radius 1), a custom radius-2 spec, multi-channel boards, fuse depth
K in {1, 4}, and the packed bit-sliced twin. Degenerate geometry (1-shard
meshes, shards with no interior) must degrade to the sequential schedule,
not wrap garbage. Runs on the 8-virtual-CPU-device mesh from conftest.
"""

import json
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from conftest import oracle_n
from mpi_and_open_mp_tpu import stencils
from mpi_and_open_mp_tpu.models.life import LifeSim
from mpi_and_open_mp_tpu.parallel import haloplan, mesh as mesh_lib
from mpi_and_open_mp_tpu.robust import chaos
from mpi_and_open_mp_tpu.stencils import engine as stencil_engine
from mpi_and_open_mp_tpu.utils.config import config_from_board

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_chaos_and_plans():
    """Chaos plans are read at trace time and plan tables are global:
    leave both exactly as found (same discipline as test_tune.py)."""
    from mpi_and_open_mp_tpu.ops import pallas_life

    pallas_life.clear_planned_paths()
    yield
    pallas_life.clear_planned_paths()
    chaos.reset()


# ------------------------------------------------------------ plan derivation


def test_plan_stamps_depth_and_cache():
    p = haloplan.plan_halo("row", (4, 1), (64, 128), 1, 1)
    assert p.overlap and p.engine == "overlap:deferred"
    assert p.depth == 1 and p.why == ""
    # Persistent: the same geometry yields the SAME frozen plan object.
    assert haloplan.plan_halo("row", (4, 1), (64, 128), 1, 1) is p
    # Depth is radius * fuse_steps.
    assert haloplan.plan_halo("row", (4, 1), (64, 128), 1, 3).depth == 3
    assert haloplan.plan_halo("row", (4, 1), (64, 128), 2, 3).depth == 6
    # The packed twin carries its own stamp.
    packed = haloplan.plan_halo("row", (2, 1), (128, 128), 32, 1,
                                pack_layout="packed")
    assert packed.overlap and packed.engine == "overlap:packed"


def test_plan_degenerate_geometry_goes_sequential():
    # 1-shard axis: nothing to overlap.
    p = haloplan.plan_halo("row", (1, 1), (64, 128), 1, 1)
    assert not p.overlap and p.engine == "seq:halo" and "1-shard" in p.why
    # Shard too shallow for a non-empty interior (extent <= 2*depth).
    p = haloplan.plan_halo("row", (4, 1), (2, 128), 1, 1)
    assert not p.overlap and "empty interior" in p.why
    # The packed twin downgrades to its own sequential stamp.
    p = haloplan.plan_halo("row", (2, 1), (64, 128), 32, 1,
                           pack_layout="packed")
    assert not p.overlap and p.engine == "seq:packed"
    # col overlaps the x axis: a y-only mesh is 1-shard in x.
    p = haloplan.plan_halo("col", (4, 1), (64, 128), 1, 1)
    assert not p.overlap and "1-shard x" in p.why
    with pytest.raises(ValueError, match="layout"):
        haloplan.plan_halo("diag", (4, 1), (64, 128), 1, 1)


def test_plan_kill_switch_is_part_of_the_cache_key(monkeypatch):
    assert haloplan.plan_halo("row", (4, 1), (64, 128), 1, 1).overlap
    monkeypatch.setenv(haloplan.ENV_OVERLAP, "0")
    p = haloplan.plan_halo("row", (4, 1), (64, 128), 1, 1)
    assert not p.overlap and haloplan.ENV_OVERLAP in p.why
    monkeypatch.delenv(haloplan.ENV_OVERLAP)
    assert haloplan.plan_halo("row", (4, 1), (64, 128), 1, 1).overlap


# ---------------------------------------- overlap vs sequential bit-identity


@pytest.mark.parametrize("layout", ["row", "col", "cart"])
@pytest.mark.parametrize("workload", sorted(stencils.names()))
def test_overlap_bit_equals_sequential_every_spec(workload, layout):
    """The tentpole invariant: for every registry spec (incl. the
    2-channel gray_scott) and every layout, the overlapped schedule's
    board is bit-identical to the forced-sequential schedule AND passes
    the independent oracle gate."""
    spec = stencils.get(workload)
    # Wide-radius specs (lenia r=8) need every layout's min shard to
    # keep a non-empty interior past 2*radius, or the plan legally
    # gates overlap out to seq and the overlap assertion below is moot.
    s = max(48, 12 * spec.radius)
    board = spec.init(np.random.default_rng(46), (s, s))
    mesh = mesh_lib.make_mesh_2d(4, 2)
    got = np.asarray(stencil_engine.run_sharded(
        spec, board, 5, mesh=mesh, layout=layout))
    plan = stencil_engine.run_sharded.last_plan
    assert plan.overlap and plan.engine.startswith("overlap:")
    seq = np.asarray(stencil_engine.run_sharded(
        spec, board, 5, mesh=mesh, layout=layout, overlap=False))
    assert stencil_engine.run_sharded.last_plan.engine == "seq:halo"
    np.testing.assert_array_equal(got, seq)
    assert stencils.parity_ok(spec, got, stencils.oracle_run(spec, board, 5))


@pytest.mark.parametrize("layout", ["row", "cart"])
@pytest.mark.parametrize("workload", ["life", "heat"])
def test_overlap_fused_k4_with_remainder_round(workload, layout):
    """Depth-4 fusion, 10 steps: two full rounds plus a depth-2 remainder
    round (its OWN plan — may legally differ in schedule)."""
    spec = stencils.get(workload)
    board = spec.init(np.random.default_rng(47), (48, 48))
    mesh = mesh_lib.make_mesh_2d(4, 2)
    got = np.asarray(stencil_engine.run_sharded(
        spec, board, 10, mesh=mesh, layout=layout, fuse_steps=4))
    assert stencil_engine.run_sharded.last_plan.overlap
    seq = np.asarray(stencil_engine.run_sharded(
        spec, board, 10, mesh=mesh, layout=layout, fuse_steps=4,
        overlap=False))
    np.testing.assert_array_equal(got, seq)
    assert stencils.parity_ok(
        spec, got, stencils.oracle_run(spec, board, 10))


def _blur2_update(center, agg, xp):
    return (center * 0.5 + agg * 0.01).astype(center.dtype)


@pytest.mark.parametrize("fuse", [1, 2])
def test_overlap_custom_radius2_spec(fuse):
    """Radius-2 coverage (every registry spec is radius 1): an
    unregistered 5x5 float spec, depth up to 4 per round."""
    w = np.ones((5, 5), np.int64)
    w[2, 2] = 0
    spec = stencils.StencilSpec(
        name="blur2", radius=2, dtype="float32",
        weights=tuple(tuple(int(x) for x in row) for row in w),
        update=_blur2_update)
    board = np.random.default_rng(48).random((48, 48)).astype(np.float32)
    mesh = mesh_lib.make_mesh_1d(4, axis="y")
    got = np.asarray(stencil_engine.run_sharded(
        spec, board, 5, mesh=mesh, layout="row", fuse_steps=fuse))
    plan = stencil_engine.run_sharded.last_plan
    assert plan.overlap and plan.depth == 2 * fuse
    seq = np.asarray(stencil_engine.run_sharded(
        spec, board, 5, mesh=mesh, layout="row", fuse_steps=fuse,
        overlap=False))
    np.testing.assert_array_equal(got, seq)
    assert stencils.parity_ok(spec, got, stencils.oracle_run(spec, board, 5))


def test_one_shard_mesh_degrades_to_sequential():
    """The degenerate mesh: overlap must decline (not wrap garbage) and
    the run must still be oracle-exact."""
    spec = stencils.get("life")
    board = spec.init(np.random.default_rng(49), (16, 16))
    mesh = mesh_lib.make_mesh_1d(1, axis="y")
    got = np.asarray(stencil_engine.run_sharded(
        spec, board, 4, mesh=mesh, layout="row"))
    plan = stencil_engine.run_sharded.last_plan
    assert not plan.overlap and "1-shard" in plan.why
    np.testing.assert_array_equal(got, oracle_n(board, 4))


def test_engine_kill_switch_forces_sequential(monkeypatch):
    spec = stencils.get("life")
    board = spec.init(np.random.default_rng(50), (32, 32))
    monkeypatch.setenv(haloplan.ENV_OVERLAP, "0")
    got = np.asarray(stencil_engine.run_sharded(
        spec, board, 4, mesh=mesh_lib.make_mesh_1d(), layout="row"))
    plan = stencil_engine.run_sharded.last_plan
    assert plan.engine == "seq:halo" and haloplan.ENV_OVERLAP in plan.why
    np.testing.assert_array_equal(got, oracle_n(board, 4))


def test_direct_fused_step_schedules_bit_equal(make_board):
    """Unit-level: ``overlap_fused_step`` vs ``sequential_fused_step``
    under the same shard_map, same plan — the two schedules, nothing
    else, k=2."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = stencils.get("life")
    board = make_board(64, 64)
    mesh = mesh_lib.make_mesh_1d()  # 8 shards of (8, 64); depth 2 fits
    plan = haloplan.plan_halo("row", (8, 1), (8, 64), spec.radius, 2)
    assert plan.overlap

    def step_fn(padded):
        return stencil_engine.step_padded(spec, padded, jnp)

    pspec = P("y", None)
    dev = jax.device_put(jnp.asarray(board, spec.dtype),
                         NamedSharding(mesh, pspec))

    def smapped(fn):
        return jax.jit(mesh_lib.shard_map(
            lambda b: fn(plan, step_fn, b), mesh=mesh,
            in_specs=pspec, out_specs=pspec, check_vma=False))

    got = np.asarray(smapped(haloplan.overlap_fused_step)(dev))
    seq = np.asarray(smapped(haloplan.sequential_fused_step)(dev))
    np.testing.assert_array_equal(got, seq)
    np.testing.assert_array_equal(got, oracle_n(board, 2))


# ------------------------------------------------- packed bit-sliced overlap


def test_bitfused_packed_overlap_crosses_round_boundary(make_board):
    """The bit-sliced twin on an exact frame: (640, 128) over a 2-way
    ring is window mode with nw_s=10 > 2h=8 word rows per shard, so the
    plan overlaps — 140 steps crosses the k_max=128 round boundary, so
    the second round's ghost words carry first-round state."""
    board = make_board(640, 128, density=0.35)
    cfg = config_from_board(board, steps=140, save_steps=1000)
    mesh = mesh_lib.make_mesh_1d(2, axis="y")
    sim = LifeSim(cfg, layout="row", impl="bitfused", mesh=mesh)
    assert sim.plan_note == "window+overlap:packed"
    sim.step(140)
    np.testing.assert_array_equal(sim.collect(), oracle_n(board, 140))


def test_bitfused_packed_kill_switch_stays_bit_exact(monkeypatch,
                                                     make_board):
    """MOMP_HALO_OVERLAP=0 on overlap-capable packed geometry: the note
    downgrades to the sequential stamp and the run stays oracle-exact
    (same bits as the overlap run, by transitivity)."""
    board = make_board(640, 128, density=0.35)
    cfg = config_from_board(board, steps=10, save_steps=1000)
    mesh = mesh_lib.make_mesh_1d(2, axis="y")
    monkeypatch.setenv(haloplan.ENV_OVERLAP, "0")
    sim = LifeSim(cfg, layout="row", impl="bitfused", mesh=mesh)
    assert sim.plan_note == "window+seq:packed"
    sim.step(10)
    np.testing.assert_array_equal(sim.collect(), oracle_n(board, 10))


def test_bitfused_packed_ineligible_geometry_keeps_bare_note(make_board):
    # Padded frame (pad_y > 0): the funnel-shift exchange stays
    # sequential and the note stays the historical bare mode string.
    board = make_board(100, 130)
    cfg = config_from_board(board, steps=5, save_steps=1000)
    sim = LifeSim(cfg, layout="row", impl="bitfused",
                  mesh=mesh_lib.make_mesh_2d(2, 4))
    assert "+" not in sim.plan_note
    sim.step(5)
    np.testing.assert_array_equal(sim.collect(), oracle_n(board, 5))
    # Exact frame but no interior (nw_s=2 <= 2h): also bare.
    board = make_board(128, 128)
    cfg = config_from_board(board, steps=5, save_steps=1000)
    sim = LifeSim(cfg, layout="row", impl="bitfused",
                  mesh=mesh_lib.make_mesh_1d(2, axis="y"))
    assert "+" not in sim.plan_note


# ------------------------------------- chaos on padded packed frames (PR 15)


def test_packed_halo_chaos_padded_frame_diverges(monkeypatch, make_board):
    """The blind spot this PR closes: a dropped ghost on a PADDED packed
    frame (pad_y > 0, the funnel-shift path) must corrupt the run —
    proof the injection hook reaches the pad>0 exchange."""
    board = make_board(100, 130)
    cfg = config_from_board(board, steps=6, save_steps=0)
    monkeypatch.setenv("MOMP_CHAOS", "halo=drop;noguard")
    chaos.reset()
    sim = LifeSim(cfg, layout="row", impl="bitfused",
                  mesh=mesh_lib.make_mesh_2d(2, 4))
    final = sim.run(save=False)
    assert not np.array_equal(final, oracle_n(board, 6))
    assert sim.recoveries == []


def test_packed_halo_chaos_padded_frame_recovers(monkeypatch, make_board):
    """Same padded-frame fault with guards armed: the consistency probe
    catches it and the suppressed re-trace recovers bit-identically."""
    board = make_board(100, 130)
    cfg = config_from_board(board, steps=12, save_steps=4)
    monkeypatch.setenv("MOMP_CHAOS", "halo=drop;seed=3")
    chaos.reset()
    sim = LifeSim(cfg, layout="row", impl="bitfused",
                  mesh=mesh_lib.make_mesh_2d(2, 4))
    final = sim.run(save=False)
    np.testing.assert_array_equal(final, oracle_n(board, 12))
    assert sim.recoveries and "recovered" in sim.recoveries[0]


def test_packed_halo_x_chaos_padded_frame_diverges(monkeypatch, make_board):
    """The x twin (``packed_halo_x`` pad > 0): column strips of an
    unaligned board, dropped left ghost."""
    board = make_board(64, 460)
    cfg = config_from_board(board, steps=6, save_steps=0)
    monkeypatch.setenv("MOMP_CHAOS", "halo=drop;noguard")
    chaos.reset()
    sim = LifeSim(cfg, layout="col", impl="bitfused",
                  mesh=mesh_lib.make_mesh_1d(4, axis="x"))
    final = sim.run(save=False)
    assert not np.array_equal(final, oracle_n(board, 6))
    assert sim.recoveries == []


# --------------------------------------------------- tune space integration


def test_axis_orders_legality():
    from mpi_and_open_mp_tpu.tune import space

    assert space.axis_orders(1) == ("row",)
    assert space.axis_orders(8, (8, 1)) == ("row", "col")
    assert space.axis_orders(8, (4, 2)) == ("row", "col", "cart")


def test_sharded_candidates_gate_overlap_per_geometry():
    from mpi_and_open_mp_tpu.tune import space

    mesh = mesh_lib.make_mesh_2d(4, 2)
    cands = space.sharded_candidates("life", (48, 48), mesh)
    by = {(c.axis_order, c.halo_overlap) for c in cands}
    # All three layouts legal, overlap + seq legs each.
    assert by == {(lo, s) for lo in ("row", "col", "cart")
                  for s in ("overlap", "seq")}
    # A shard too shallow for an interior loses only the overlap leg.
    cands = space.sharded_candidates("life", (8, 48), mesh)
    rows = {c.halo_overlap for c in cands if c.axis_order == "row"}
    assert rows == {"seq"}
    # 1-device mesh: nothing shards, no candidates at all.
    assert space.sharded_candidates(
        "life", (48, 48), mesh_lib.make_mesh_1d(1, axis="y")) == []


def test_tune_sharded_seq_baseline_and_store_roundtrip(tmp_path):
    from mpi_and_open_mp_tpu.tune import tune_sharded
    from mpi_and_open_mp_tpu.tune.plans import PlanStore

    store = PlanStore(tmp_path)
    res = tune_sharded("life", (64, 64), mesh=mesh_lib.make_mesh_2d(4, 2),
                       steps=16, store=store)
    # Baseline-first ordering: the historic sequential schedule opens
    # the race, so vs_sequential is measured against it.
    assert res["baseline"]["halo_overlap"] == "seq"
    assert res["vs_sequential"] > 0
    assert {m["halo_overlap"] for m in res["measurements"]} >= {"seq"}
    fresh = PlanStore(tmp_path)
    fresh.install()
    hit = fresh.lookup_sharded("life", (64, 64))
    assert hit is not None
    assert hit["choice"]["path"].startswith("sharded:")

    with pytest.raises(RuntimeError, match="no legal sharded candidate"):
        tune_sharded("life", (64, 64),
                     mesh=mesh_lib.make_mesh_1d(1, axis="y"), steps=16)


# ------------------------------------- ledger / sentinel / report provenance


def test_ledger_stamps_halo_key():
    from mpi_and_open_mp_tpu.obs import ledger

    e = ledger.stamp({"metric": "m", "sharded_halo": "overlap:deferred"},
                     sha="x")
    assert e["key"]["halo"] == "overlap:deferred"
    e = ledger.stamp({"metric": "m"}, sha="x")
    assert e["key"]["halo"] == "-"
    assert "halo" in ledger.KEY_FIELDS


def test_sentinel_ranks_overlap_above_sequential():
    sys.path.insert(0, os.path.join(REPO, "analysis"))
    import regression_sentinel

    rank = regression_sentinel.engine_rank
    assert rank("overlap:deferred") == rank("overlap:packed") == 4
    assert rank("seq:halo") == rank("seq:packed") == 1
    assert rank("overlap:rdma") > rank("seq:halo")
    assert "sharded_halo" in regression_sentinel.PROVENANCE_FIELDS
    assert "vs_sequential" in regression_sentinel.WATCH_FIELDS
    assert "sharded_overlap_cups" in regression_sentinel.WATCH_FIELDS


def test_trace_report_halo_section():
    from mpi_and_open_mp_tpu.obs import report

    records = [
        {"kind": "span", "id": 1, "name": "halo.overlap", "ts": 0.0,
         "dur": 0.5, "attrs": {"engine": "overlap:deferred"}},
        {"kind": "span", "id": 2, "name": "halo.seq", "ts": 0.6,
         "dur": 0.5, "attrs": {"engine": "seq:halo"}},
        {"kind": "event", "id": 3, "name": "halo.ab", "ts": 1.2,
         "attrs": {"transfer_s": 1e-4, "exposed_s": 2e-5,
                   "efficiency": 0.8, "vs_sequential": 1.4}},
    ]
    rep = report.report_dict(records)
    hal = rep["halo"]
    assert hal["overlap_spans"] == 1 and hal["seq_spans"] == 1
    assert "overlap:deferred" in hal["engines"]
    assert hal["ab"]["efficiency"] == 0.8
    text = report.render(rep)
    assert "halo A/B" in text and "efficiency=80.0%" in text


# ----------------------------------------------------- bench --sharded-ab


def test_bench_sharded_ab_phase(monkeypatch):
    """The A/B phase end-to-end on the conftest mesh: overlap and forced
    sequential legs both run, parity-gated, provenance-stamped. (The
    speedup assertion lives in the CI smoke on a bigger board; here we
    only require the measurement to be well-formed.)"""
    from types import SimpleNamespace

    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    import bench

    args = SimpleNamespace(sharded_ab=16, sharded_board=64)
    fields = bench._sharded_ab_phase(args, "life")
    assert "sharded_ab_error" not in fields, fields
    assert fields["sharded_halo"].startswith("overlap:")
    assert fields["sharded_seq_halo"] == "seq:halo"
    assert fields["sharded_ab_parity"] is True
    assert fields["sharded_overlap_cups"] > 0
    assert fields["sharded_seq_cups"] > 0
    assert fields["vs_sequential"] > 0
    assert 0.0 <= fields["sharded_overlap_efficiency"] <= 1.0
    assert fields["sharded_exposed_s"] <= fields["sharded_transfer_s"]
    # PR 18: the partitioned-boundary sweep rides the same phase — all
    # three layouts parity-green under the split boundary, stamped and
    # priced against the coupled schedule.
    assert fields["sharded_boundary_parity"] is True
    for lay in ("row", "col", "cart"):
        assert fields["sharded_boundary_engines"][lay].endswith(":pb1")
    assert fields["sharded_boundary_cups"] > 0
    assert fields["sharded_boundary_vs_coupled"] > 0
    # The kill switch downgrades the stamp on the SAME phase call — the
    # provenance signal the sentinel alarms on.
    monkeypatch.setenv(haloplan.ENV_OVERLAP, "0")
    fields = bench._sharded_ab_phase(args, "life")
    assert fields["sharded_halo"] == "seq:halo"


# ------------------------------------------------ apps/life --resume + plans


def _resume_status_line(err: str) -> dict:
    lines = [ln for ln in err.splitlines() if ln.startswith("{")]
    assert lines, f"no JSON status line on stderr: {err!r}"
    return json.loads(lines[-1])


def test_resume_status_line_carries_plan_source(tmp_path, capsys,
                                                make_board):
    """ROADMAP autotune follow-on (c): a requeued --resume run reports
    how its dispatch was routed — heuristic without a store."""
    from mpi_and_open_mp_tpu.apps import life as life_app
    from mpi_and_open_mp_tpu.utils.config import save_config

    cfg = config_from_board(make_board(16, 16), steps=20, save_steps=5)
    cfg_path = tmp_path / "run.cfg"
    save_config(cfg_path, cfg)
    out = tmp_path / "vtk"
    assert life_app.main([str(cfg_path), "--layout", "row",
                          "--outdir", str(out)]) == 0
    capsys.readouterr()
    assert life_app.main([str(cfg_path), "--layout", "row",
                          "--outdir", str(out), "--resume"]) == 0
    err = capsys.readouterr().err
    assert "resuming from" in err  # the historical prose line survives
    status = _resume_status_line(err)
    assert status["plan_source"] == "heuristic"
    assert "resumed" in status and "plans_installed" not in status


def test_resume_consumes_installed_plans(tmp_path, capsys, make_board):
    """The warm-AND-tuned restart: with a populated --plans store, the
    resumed run installs the records before the first dispatch and the
    status line stamps plan_source=store."""
    from mpi_and_open_mp_tpu.apps import life as life_app
    from mpi_and_open_mp_tpu.tune import tune
    from mpi_and_open_mp_tpu.tune.plans import PlanStore
    from mpi_and_open_mp_tpu.utils.config import save_config

    plans = tmp_path / "plans"
    tune("life", (1, 16, 16), steps=16, store=PlanStore(plans))
    cfg = config_from_board(make_board(16, 16), steps=20, save_steps=5)
    cfg_path = tmp_path / "run.cfg"
    save_config(cfg_path, cfg)
    out = tmp_path / "vtk"
    assert life_app.main([str(cfg_path), "--layout", "row",
                          "--outdir", str(out)]) == 0
    capsys.readouterr()
    assert life_app.main([str(cfg_path), "--layout", "row",
                          "--outdir", str(out), "--resume",
                          "--plans", str(plans)]) == 0
    status = _resume_status_line(capsys.readouterr().err)
    assert status["plans_installed"] >= 1
    assert status["plan_source"] == "store"
    assert status["tuned_path"]
