"""Native C++ IO library: build, bind, and parity with the Python fallback."""

import os
import subprocess

import numpy as np
import pytest

from mpi_and_open_mp_tpu.utils import native
from mpi_and_open_mp_tpu.utils.config import load_config_py, save_config, config_from_board
from mpi_and_open_mp_tpu.utils.vtk import read_vtk, write_vtk_py

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


@pytest.fixture(scope="module", autouse=True)
def built_lib():
    rc = subprocess.run(
        ["make", "-C", os.path.join(REPO, "native")], capture_output=True
    )
    if rc.returncode != 0 or not native.available():
        pytest.skip("native toolchain unavailable")


def test_native_load_matches_python():
    for name in ("glider_10x10.cfg", "empty_10x10.cfg", "rpentomino_40x32.cfg"):
        path = os.path.join(FIXTURES, name)
        py = load_config_py(path)
        nat = native.load_config(path)
        assert (nat.steps, nat.save_steps, nat.nx, nat.ny) == (
            py.steps, py.save_steps, py.nx, py.ny)
        np.testing.assert_array_equal(nat.cells, py.cells)


def test_native_load_errors(tmp_path):
    bad = tmp_path / "bad.cfg"
    bad.write_text("1\n2\n")
    with pytest.raises(ValueError):
        native.load_config(bad)
    dangling = tmp_path / "dangling.cfg"
    dangling.write_text("1\n1\n4 4\n3\n")
    with pytest.raises(ValueError):
        native.load_config(dangling)
    with pytest.raises(ValueError):
        native.load_config(tmp_path / "missing.cfg")


def test_native_vtk_matches_python(tmp_path, make_board):
    board = make_board(13, 21)
    p_native = tmp_path / "native.vtk"
    p_py = tmp_path / "py.vtk"
    native.write_vtk(p_native, board.astype(np.int32))
    write_vtk_py(p_py, board)
    # Byte-identical output from both writers.
    assert p_native.read_bytes() == p_py.read_bytes()
    np.testing.assert_array_equal(read_vtk(p_native), board)


def test_native_oracle_matches_numpy(make_board):
    """Two independent oracles (C++ scanline vs NumPy roll) must agree —
    the strongest form of the reference's serial-parity discipline."""
    from conftest import oracle_n

    for shape in [(10, 10), (17, 23), (64, 48)]:
        b = make_board(*shape)
        np.testing.assert_array_equal(native.life_steps(b, 12), oracle_n(b, 12))
    # Glider translation survives the torus in the native oracle too.
    g = np.zeros((10, 10), np.uint8)
    for i, j in [(0, 2), (1, 0), (1, 2), (2, 1), (2, 2)]:
        g[j, i] = 1
    np.testing.assert_array_equal(
        native.life_steps(g, 40), g  # period 40 on a 10x10 torus
    )


def test_native_bits_oracle_matches(make_board):
    """The bit-packed native oracle (third independent implementation)
    must agree with both the scalar C++ and NumPy oracles — including
    word-boundary widths (63/64/65), sub-word boards, and degenerate
    torus sizes where neighbours alias (nx or ny in {1, 2})."""
    from conftest import oracle_n

    for shape in [(10, 10), (17, 23), (48, 63), (48, 64), (48, 65),
                  (8, 200), (3, 130), (2, 70), (70, 2), (1, 9), (9, 1)]:
        b = make_board(*shape)
        got = native.life_steps(b, 9, bits=True)
        np.testing.assert_array_equal(got, oracle_n(b, 9), err_msg=str(shape))
        np.testing.assert_array_equal(got, native.life_steps(b, 9))


def test_native_roundtrip_config(tmp_path, make_board):
    board = make_board(9, 9)
    cfg = config_from_board(board, 7, 3)
    path = tmp_path / "rt.cfg"
    save_config(path, cfg)
    nat = native.load_config(path)
    np.testing.assert_array_equal(nat.board(), board)
