"""tune/ — the unified autotuner + durable plan store (PR 14).

The claims under test, in the ISSUE's words: one fingerprint digest
holds the chosen plan AND its exported executable side by side
(``<digest>.plan`` / ``<digest>.aot``); every timed candidate is
oracle-parity-gated before it may win and the heuristic's own choice is
always in the race (``vs_heuristic >= 1.0`` by construction); a second
process installs persisted plans with zero life_batch retrace ticks;
corrupt/stale records quarantine via ``utils.checkpoint.quarantine``
and fall back to heuristics; a parity-failing plan is rejected and
NEVER installed; ``MOMP_TUNE=0`` restores pure-heuristic routing
without touching the store. All on the 8-virtual-device CPU mesh.
"""

import glob
import os
from types import SimpleNamespace

import numpy as np
import pytest

from mpi_and_open_mp_tpu import stencils
from mpi_and_open_mp_tpu.obs import ledger, metrics
from mpi_and_open_mp_tpu.ops import pallas_life
from mpi_and_open_mp_tpu.serve import aotcache
from mpi_and_open_mp_tpu.tune import (
    PlanError,
    PlanStore,
    fingerprint_for,
    load_plan,
    save_plan,
    space,
    tune,
)


@pytest.fixture(autouse=True)
def _clean_plan_table():
    """Every test starts and ends with an empty in-process plan table —
    an installed plan leaking across tests would silently reroute every
    later ``native_path_batch`` call."""
    pallas_life.clear_planned_paths()
    yield
    pallas_life.clear_planned_paths()


def _stack(workload: str, shape, seed=46) -> np.ndarray:
    spec = stencils.get(workload)
    b, ny, nx = shape
    rng = np.random.default_rng(seed)
    return np.stack([spec.init(rng, (ny, nx)) for _ in range(b)]).astype(
        spec.np_dtype)


# -- candidate space -------------------------------------------------------


def test_life_candidates_heuristic_first_cpu():
    """The heuristic's own choice is candidate #0 (that ordering is what
    makes vs_heuristic >= 1.0 by construction), and the CPU space is
    exactly the legal set: bitsliced (no min-batch gate — that is the
    heuristic a plan may override) + the always-compilable xla fold."""
    shape = (4, 64, 64)
    cands = space.candidates("life", shape, on_tpu=False)
    paths = [c.path for c in cands]
    assert paths[0] == space.heuristic_path("life", shape, False) == "xla"
    assert sorted(paths) == ["bitsliced", "xla"]
    by = {c.path: c for c in cands}
    assert by["bitsliced"].pack_layout == "bitsliced"
    assert by["bitsliced"].bucket_rounding == space.BUCKET_PLANE32
    assert by["xla"].pack_layout == "cell-packed"
    assert by["xla"].bucket_rounding == space.BUCKET_POW2


def test_stencil_candidates_channels_gate():
    """Single-channel specs race roll vs the spec-generated Pallas
    padded kernel; the 2-channel gray_scott stack is 4-D, outside the
    Pallas batch contract, so roll is its whole space."""
    heat = [c.path for c in space.candidates("heat", (2, 16, 16))]
    assert heat == ["stencil:roll", "stencil:pallas"]
    gs = [c.path for c in space.candidates("gray_scott", (2, 2, 16, 16))]
    assert gs == ["stencil:roll"]
    assert all(space.pack_layout_for(p) == "-" for p in heat)


def test_runner_for_unknown_path_raises():
    with pytest.raises(ValueError, match="unknown"):
        space.runner_for("life", "warp-drive")


def test_run_padded_pallas_batch_parity():
    """The new spec-generic Pallas batch engine (satellite 1) reproduces
    the oracle for both an automaton and a float field."""
    import jax.numpy as jnp

    for workload in ("heat", "wireworld"):
        spec = stencils.get(workload)
        stack = _stack(workload, (3, 16, 16))
        assert stencils.pallas_batch_supported(spec, stack.shape)
        got = np.asarray(stencils.run_padded_pallas_batch(
            spec, jnp.asarray(stack), 5))
        for i in range(stack.shape[0]):
            assert stencils.parity_ok(
                spec, got[i], stencils.oracle_run(spec, stack[i], 5)), \
                workload


# -- the measured tuning pass ----------------------------------------------


def test_tune_vs_heuristic_floor_and_colocation(tmp_path):
    """One bounded pass: winner installed in-process, vs_heuristic >=
    1.0 (the heuristic is in the race, strict < to dethrone), and the
    persisted plan shares ONE digest with the exported executable."""
    store = PlanStore(tmp_path)
    res = tune("life", (8, 16, 16), steps=16, store=store)
    assert res["vs_heuristic"] >= 1.0
    assert res["measurements"][0]["path"] == res["heuristic_path"]
    assert pallas_life.planned_path("life", (8, 16, 16)) \
        == res["tuned"]["path"]
    digest = res["digest"]
    assert os.path.exists(str(tmp_path / (digest + ".plan")))
    assert os.path.exists(str(tmp_path / (digest + ".aot")))
    assert res["plan_file"].endswith(digest + ".plan")
    # The record round-trips and its key IS the aotcache fingerprint.
    rec = load_plan(res["plan_file"])
    assert aotcache.digest_for(rec["key"]) == digest
    assert rec["choice"]["path"] == res["tuned"]["path"]


def test_second_process_install_reuses_plan(tmp_path):
    """A fresh PlanStore (a restarted process's view) validates +
    parity-gates the persisted record and reroutes dispatch with ZERO
    life_batch retrace ticks — the parity gate runs the co-located
    exported executable, not a fresh trace."""
    res = tune("life", (8, 16, 16), steps=16, store=PlanStore(tmp_path))
    pallas_life.clear_planned_paths()
    metrics.reset()
    summary = PlanStore(tmp_path).install()
    assert summary["installed"] == 1 and summary["scanned"] == 1
    assert summary["corrupt"] == summary["stale"] == 0
    assert summary["parity_rejected"] == 0
    assert summary["plans"][0]["path"] == res["tuned"]["path"]
    assert pallas_life.planned_path("life", (8, 16, 16)) \
        == res["tuned"]["path"]
    retraces = {k: v for k, v in metrics.snapshot()["counters"].items()
                if k.startswith("jit.retrace{fn=life_batch")}
    assert retraces == {}


# -- durability: corrupt / stale / parity ----------------------------------


def test_corrupt_plan_quarantined_heuristics_unchanged(tmp_path):
    """A flipped bit anywhere in the frame is corrupt: the record is
    quarantined with a forensic stamp and NOTHING is installed — the
    heuristics serve unchanged."""
    tune("life", (8, 16, 16), steps=16, store=PlanStore(tmp_path))
    pallas_life.clear_planned_paths()
    (plan_file,) = glob.glob(str(tmp_path / "*.plan"))
    size = os.path.getsize(plan_file)
    with open(plan_file, "r+b") as fd:
        fd.seek(size // 2)
        byte = fd.read(1)
        fd.seek(size // 2)
        fd.write(bytes([byte[0] ^ 0xFF]))
    summary = PlanStore(tmp_path).install()
    assert summary["corrupt"] == 1 and summary["installed"] == 0
    assert glob.glob(plan_file + ".corrupt.*")
    assert not os.path.exists(plan_file)
    assert pallas_life.planned_path("life", (8, 16, 16)) is None


def test_stale_plan_quarantined_on_fingerprint_drift(tmp_path):
    """An intact envelope whose stored fingerprint no longer recomputes
    (here: version skew, i.e. the environment moved under the plan) is
    stale — quarantined, never installed."""
    tune("life", (8, 16, 16), steps=16, store=PlanStore(tmp_path))
    pallas_life.clear_planned_paths()
    (plan_file,) = glob.glob(str(tmp_path / "*.plan"))
    rec = load_plan(plan_file)
    save_plan(plan_file, dict(rec, key=dict(rec["key"], jax="0.0.0")))
    summary = PlanStore(tmp_path).install()
    assert summary["stale"] == 1 and summary["installed"] == 0
    assert glob.glob(plan_file + ".stale.*")
    assert pallas_life.planned_path("life", (8, 16, 16)) is None


def test_bad_schema_is_stale_missing_choice_is_corrupt(tmp_path):
    p = str(tmp_path / "x.plan")
    save_plan(p, {"schema": "momp-plan/0", "key": {}, "choice": {}})
    with pytest.raises(PlanError, match="schema") as ei:
        load_plan(p)
    assert ei.value.kind == "stale"
    save_plan(p, {"schema": "momp-plan/1", "key": {}})
    with pytest.raises(PlanError, match="key/choice") as ei:
        load_plan(p)
    assert ei.value.kind == "corrupt"


def test_parity_failing_plan_rejected_never_installed(tmp_path):
    """The last line of defense: a CRC-valid plan whose co-located
    executable computes the WRONG function (identity, not Life) fails
    the install-time oracle gate — the plan is quarantined as
    ``parity`` and never steers a dispatch, whatever it claims to win."""
    import jax
    import jax.numpy as jnp
    from jax import export as jax_export

    shape = (1, 12, 12)
    key = fingerprint_for("life", shape, np.uint8, "xla")
    store = PlanStore(tmp_path)
    plan_file = store.save({
        "schema": "momp-plan/1", "key": key,
        "choice": {"workload": "life", "shape": list(shape),
                   "dtype": "uint8", "path": "xla",
                   "pack_layout": "cell-packed",
                   "bucket_rounding": "pow2", "axis_order": "row"},
        "vs_heuristic": 99.0,
    })
    wrong = jax_export.export(jax.jit(lambda boards, steps: boards))(
        jax.ShapeDtypeStruct(shape, jnp.uint8),
        jax.ShapeDtypeStruct((), jnp.int32))
    aotcache.save_artifact(
        str(tmp_path / (aotcache.digest_for(key) + ".aot")),
        key, wrong.serialize())
    summary = PlanStore(tmp_path).install()
    assert summary["parity_rejected"] == 1 and summary["installed"] == 0
    assert glob.glob(plan_file + ".parity.*")
    assert pallas_life.planned_path("life", shape) is None


# -- dispatch integration --------------------------------------------------


def test_native_path_batch_consults_installed_plan(monkeypatch):
    """A plan may override the BITSLICE_MIN_BATCH heuristic (B=4 <
    min-batch still routes bitsliced when planned) but never a hard
    legality gate (``allow_bitsliced=False`` is the daemon's poisoned-
    layout rung: the plan yields), and ``MOMP_TUNE=0`` restores the
    heuristic without uninstalling anything."""
    shape = (4, 64, 64)
    assert pallas_life.native_path_batch(shape, on_tpu=False) == "xla"
    pallas_life.install_planned_path("life", shape, "bitsliced")
    assert pallas_life.native_path_batch(shape, on_tpu=False) \
        == "bitsliced"
    assert pallas_life.native_path_batch(
        shape, on_tpu=False, allow_bitsliced=False) == "xla"
    monkeypatch.setenv("MOMP_TUNE", "0")
    assert pallas_life.native_path_batch(shape, on_tpu=False) == "xla"
    assert pallas_life.planned_path("life", shape) is None
    monkeypatch.delenv("MOMP_TUNE")
    assert pallas_life.native_path_batch(shape, on_tpu=False) \
        == "bitsliced"
    pallas_life.clear_planned_paths()
    assert pallas_life.native_path_batch(shape, on_tpu=False) == "xla"


def test_kill_switch_short_circuits_install(tmp_path, monkeypatch):
    tune("life", (8, 16, 16), steps=16, store=PlanStore(tmp_path))
    pallas_life.clear_planned_paths()
    monkeypatch.setenv("MOMP_TUNE", "0")
    summary = PlanStore(tmp_path).install()
    assert summary == {"scanned": 0, "installed": 0, "corrupt": 0,
                       "stale": 0, "parity_rejected": 0,
                       "disabled": True, "plans": []}
    assert glob.glob(str(tmp_path / "*.plan"))  # store untouched


def test_daemon_stencil_rung_order_follows_plan():
    """The daemon's non-life ladder: roll-primary by default with the
    Pallas kernel as the suppressed fallback; an installed
    ``stencil:pallas`` plan swaps the rungs so serving dispatches
    exactly the tuner's winner. The 2-channel stack stays roll-only."""
    from mpi_and_open_mp_tpu.serve import ServePolicy, ServingDaemon

    d = ServingDaemon(ServePolicy(max_batch=8))
    heat = stencils.get("heat")
    stack = _stack("heat", (2, 16, 16))
    names = [n for n, _ in d._engines(stack, 4, spec=heat)]
    assert names == ["batch:stencil:heat", "batch:stencil-pallas:heat",
                     "oracle"]
    pallas_life.install_planned_path("heat", stack.shape,
                                     "stencil:pallas")
    names = [n for n, _ in d._engines(stack, 4, spec=heat)]
    assert names == ["batch:stencil-pallas:heat", "batch:stencil:heat",
                     "oracle"]
    gs = stencils.get("gray_scott")
    rng = np.random.default_rng(7)
    gstack = np.stack([gs.init(rng, (12, 12))
                       for _ in range(2)]).astype(gs.np_dtype)
    names = [n for n, _ in d._engines(gstack, 2, spec=gs)]
    assert names == ["batch:stencil:gray_scott", "oracle"]


def test_bench_autotune_phase_fresh_then_store(tmp_path):
    """The ``--autotune`` phase contract end to end: pass 1 tunes fresh
    and persists; pass 2 (clean metrics — a restarted process's view)
    installs from the store and reports an EMPTY life_batch retrace
    delta; the kill switch skips with an explicit fallback_reason."""
    import bench

    args = SimpleNamespace(autotune=16, tune_board=16, tune_batch=8,
                           plans=str(tmp_path))
    out1 = bench._autotune_phase(args, "life")
    assert out1["plan_source"] == "fresh"
    assert out1["vs_heuristic"] >= 1.0
    assert out1["tuned_cups"] > 0 and out1["heuristic_cups"] > 0
    assert out1["plan_file"].endswith(out1["tune_digest"] + ".plan")

    pallas_life.clear_planned_paths()
    metrics.reset()
    out2 = bench._autotune_phase(args, "life")
    assert out2["plan_source"] == "store"
    assert out2["tuned_path"] == out1["tuned_path"]
    assert out2["vs_heuristic"] == out1["vs_heuristic"]
    assert out2["tune_retraces"] == {}
    assert out2["plans"]["installed"] == 1

    os.environ["MOMP_TUNE"] = "0"
    try:
        out3 = bench._autotune_phase(args, "life")
    finally:
        del os.environ["MOMP_TUNE"]
    assert out3["plan_source"] == "heuristic"
    assert "MOMP_TUNE=0" in out3["fallback_reason"]


# -- ledger + sentinel -----------------------------------------------------


def test_ledger_plan_key_field():
    """``plan`` joined KEY_FIELDS: tuned lines carry their plan_source,
    pre-autotuner lines default to "-" on both sides of a match."""
    stamped = ledger.stamp({"metric": "m", "plan_source": "store"})
    assert stamped["key"]["plan"] == "store"
    assert ledger.stamp({"metric": "m"})["key"]["plan"] == "-"
    old = {"key": {"metric": "m"}}  # pre-PR-14 entry: no plan field
    assert "plan=-" in ledger.config_key(old, ("metric", "plan"))


def test_sentinel_fails_plan_source_downgrade(tmp_path):
    """tuned (store) -> heuristic is a provenance downgrade exactly like
    tpu -> cpu: the sentinel fails it and surfaces the candidate's own
    fallback_reason; store <-> fresh is NOT a downgrade."""
    import json
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "analysis"))
    import regression_sentinel

    def entry(ts, plan_source, extra=None):
        rec = {"metric": "m", "value": 100.0, "board": [64, 64],
               "dtype": "uint8", "steps": 100, "batch": 0,
               "plan_source": plan_source, **(extra or {})}
        return ledger.stamp(rec, platform="cpu", device_count=8, ts=ts,
                            sha="deadbee")

    entries = [entry(float(i), "store") for i in range(3)]
    entries.append(entry(3.0, "fresh"))
    verdict = regression_sentinel.evaluate(entries)
    assert verdict["verdict"] == "pass"  # fresh ranks equal to store

    entries.append(entry(
        4.0, "heuristic",
        {"fallback_reason": "autotune skipped: MOMP_TUNE=0"}))
    verdict = regression_sentinel.evaluate(entries)
    assert verdict["verdict"] == "fail"
    (down,) = [d for d in verdict["downgrades"]
               if d["field"] == "plan_source"]
    assert down["new"] == "heuristic" and down["baseline_best"] == "store"
    assert "MOMP_TUNE=0" in down["fallback_reason"]
    assert "plan_source" in verdict["checked"]
    json.dumps(verdict)  # the verdict stays a plain JSON document
