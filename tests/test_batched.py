"""Batched execution layer: B-board engines, batched LifeSim, batched
ring attention, and the serve-layer micro-batcher.

The contract under test everywhere: a batch is B INDEPENDENT problems
sharing one dispatch — every board/request must come out bit-identical
(boards) or numerically identical (attention) to B serial runs. The
Pallas runs are interpret-mode on CPU (same kernel code Mosaic compiles
on TPU); the vmapped XLA paths are the identical compiled code used on
every backend.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from conftest import oracle_n as _oracle

from mpi_and_open_mp_tpu.ops import bitlife, pallas_life


def _soup(ny, nx, seed=0, density=0.4):
    rng = np.random.default_rng(seed)
    return (rng.random((ny, nx)) < density).astype(np.uint8)


def _stack(b, ny, nx, seed=0, density=0.4):
    rng = np.random.default_rng(seed)
    return (rng.random((b, ny, nx)) < density).astype(np.uint8)


SHAPES = [(3, 5), (10, 10), (31, 8), (33, 37), (100, 33)]


# ------------------------------------------------------------- ops layer


@pytest.mark.parametrize("ny,nx", SHAPES)
def test_pack_boards_roundtrip(ny, nx):
    s = _stack(3, ny, nx)
    packed = bitlife.pack_boards(jnp.asarray(s))
    assert packed.shape == (3, bitlife.n_words(ny), nx)
    assert np.array_equal(np.asarray(bitlife.unpack_boards(packed, ny)), s)


@pytest.mark.parametrize("resident", [True, False])
@pytest.mark.parametrize("ny,nx", SHAPES)
def test_vmem_bits_batch_parity(ny, nx, resident):
    # Both kernel forms: whole-stack-resident and grid-over-batch. The
    # serial twin (not the oracle directly) is the sharper check — any
    # divergence is THE batching bug, not a rule bug.
    s = _stack(4, ny, nx, seed=ny * nx)
    got = np.asarray(bitlife.life_run_vmem_bits_batch(
        jnp.asarray(s), 7, interpret=True, resident=resident))
    for b in range(4):
        serial = np.asarray(bitlife.life_run_vmem_bits(
            jnp.asarray(s[b]), 7, interpret=True))
        assert np.array_equal(got[b], serial), f"board {b}"
        assert np.array_equal(got[b], _oracle(s[b], 7)), f"board {b} oracle"


@pytest.mark.parametrize("ny,nx", SHAPES)
def test_xla_bits_batch_parity(ny, nx):
    s = _stack(5, ny, nx, seed=7)
    got = np.asarray(bitlife.life_run_bits_xla_batch(jnp.asarray(s), 6))
    for b in range(5):
        assert np.array_equal(got[b], _oracle(s[b], 6)), f"board {b}"


def test_fused_bits_batch_parity():
    # The fused tiling needs >= 8 packed words (256+ rows).
    assert bitlife.fused_bits_supported((256, 128))
    s = _stack(2, 256, 128, seed=3)
    got = np.asarray(bitlife.life_run_fused_bits_batch(
        jnp.asarray(s), 5, interpret=True))
    for b in range(2):
        assert np.array_equal(got[b], _oracle(s[b], 5)), f"board {b}"


def test_frame_bits_batch_parity():
    # Unaligned shape -> the padded-torus-frame runner.
    assert bitlife.plan_sharded_bits((100, 40), 1, 1, False, False) is not None
    s = _stack(2, 100, 40, seed=9)
    got = np.asarray(bitlife.life_run_frame_bits_batch(
        jnp.asarray(s), 5, interpret=True))
    for b in range(2):
        assert np.array_equal(got[b], _oracle(s[b], 5)), f"board {b}"


def test_fits_vmem_packed_batch_scales_with_b():
    # The batched gate is B x the per-board working set: a shape that
    # fits alone must stop fitting at some batch.
    shape = (3000, 3000)
    assert bitlife.fits_vmem_packed(shape)
    assert bitlife.fits_vmem_packed_batch((1, *shape))
    assert not bitlife.fits_vmem_packed_batch((64, *shape))


def test_native_path_batch_policy():
    # Small-board/large-B: board-sliced planes on EVERY backend (the
    # halo-fused XLA twin is the fastest CPU engine too).
    assert pallas_life.native_path_batch((8, 500, 500), on_tpu=False) \
        == "bitsliced"
    assert pallas_life.native_path_batch((64, 64, 64), on_tpu=True) \
        == "bitsliced"
    # The daemon's fallback pin restores the cell-packed ladder.
    assert pallas_life.native_path_batch(
        (8, 500, 500), on_tpu=False, allow_bitsliced=False) == "xla"
    # Below the minimum batch the plane is mostly padding: cell-packed.
    assert pallas_life.native_path_batch((4, 64, 64), on_tpu=False) == "xla"
    # Off-TPU cell-packed ladder: always the vmapped XLA loop
    # (throughput, not interpret).
    assert pallas_life.native_path_batch((2, 500, 500), on_tpu=False) == "xla"
    # On-TPU cell-packed ladder: whole-stack resident -> grid -> fused
    # -> frame (the bitsliced VMEM gate excludes these big boards).
    assert pallas_life.native_path_batch((2, 100, 100), on_tpu=True) == "vmem"
    big = (64, 3000, 3000)
    assert pallas_life.native_path_batch(big, on_tpu=True) == "vmem-grid"
    assert pallas_life.native_path_batch(
        (2, 16384, 16384), on_tpu=True) == "fused"
    assert pallas_life.native_path_batch(
        (2, 10000, 10000), on_tpu=True) == "frame"


def test_batch_pack_layout_vocabulary_and_kill_switch():
    """batch_pack_layout mirrors native_path_batch (they can never
    disagree); MOMP_BITSLICE=0 (the module gate the env var sets) pins
    every stack back to cell-packed, and the pinned dispatch stays
    bit-exact — the kill switch changes provenance, never answers."""
    assert pallas_life.batch_pack_layout((32, 64, 64)) == "bitsliced"
    assert pallas_life.batch_pack_layout((2, 64, 64)) == "cell-packed"
    assert pallas_life.batch_slice_width((64, 64)) == 32
    assert pallas_life.batch_slice_width((4096, 4096)) is None

    s = jnp.asarray(_stack(32, 20, 24, seed=11))
    fast = np.asarray(pallas_life.life_run_vmem_batch(s, 6))
    with pallas_life._bitslice_pinned(False):
        assert pallas_life.native_path_batch(
            (32, 64, 64), on_tpu=False) == "xla"
        assert pallas_life.batch_pack_layout((32, 64, 64)) == "cell-packed"
        assert pallas_life.batch_slice_width((64, 64)) is None
        pinned = np.asarray(pallas_life.life_run_vmem_batch(s, 6))
    assert np.array_equal(fast, pinned)
    # The pin restores on exit.
    assert pallas_life.batch_pack_layout((32, 64, 64)) == "bitsliced"
    for b in range(32):
        assert np.array_equal(fast[b], _oracle(np.asarray(s)[b], 6))


def test_life_run_vmem_batch_dispatch_parity():
    # The public batched dispatcher (on CPU: the XLA path), vs serial.
    s = _stack(6, 33, 37, seed=1)
    got = np.asarray(pallas_life.life_run_vmem_batch(jnp.asarray(s), 7))
    for b in range(6):
        assert np.array_equal(got[b], _oracle(s[b], 7)), f"board {b}"


def test_batched_steps_is_runtime_scalar():
    # One compiled program per stack shape serves ANY step count — the
    # serve-layer bucketing contract, observable via jit.retrace.
    from mpi_and_open_mp_tpu.obs import metrics

    metrics.reset()
    s = jnp.asarray(_stack(2, 20, 20))
    for n in (1, 3, 9):
        bitlife.life_run_bits_xla_batch(s, n)
    assert metrics.get("jit.retrace", fn="life_batch_xla") == 1
    metrics.reset()


# ----------------------------------------------------------- model layer


def _cfg(ny, nx, steps):
    from mpi_and_open_mp_tpu.utils.config import config_from_board

    return config_from_board(np.zeros((ny, nx), np.uint8), steps=steps,
                             save_steps=0)


@pytest.mark.parametrize("impl", ["auto", "roll"])
def test_lifesim_batched_parity(impl):
    from mpi_and_open_mp_tpu.models.life import LifeSim

    s = _stack(4, 33, 37, seed=2)
    cfg = _cfg(33, 37, 7)
    sim = LifeSim(cfg, layout="serial", impl=impl, initial_board=s)
    assert sim.batch == 4
    sim.run()
    out = np.asarray(sim.collect())
    assert out.shape == (4, 33, 37)
    for b in range(4):
        serial = LifeSim(_cfg(33, 37, 7), layout="serial", impl="roll",
                         initial_board=s[b])
        serial.run()
        assert np.array_equal(out[b], np.asarray(serial.collect())), \
            f"board {b}"
    # The per-board honesty gate must pass on the advanced stack.
    sim.debug_check()


def test_lifesim_batched_auto_picks_batched_dispatcher():
    from mpi_and_open_mp_tpu.models.life import LifeSim

    sim = LifeSim(_cfg(20, 20, 2), layout="serial",
                  initial_board=_stack(3, 20, 20))
    assert sim.impl == "pallas"
    assert sim.plan_note.startswith("batch:")


def test_lifesim_batched_constructor_gates():
    from mpi_and_open_mp_tpu.models.life import LifeSim

    s = _stack(2, 10, 10)
    with pytest.raises(ValueError, match="serial"):
        LifeSim(_cfg(10, 10, 1), layout="row", initial_board=s)
    for kw in (dict(impl="halo"), dict(impl="bitfused"),
               dict(outdir="/tmp/nope"), dict(checkpoint_dir="/tmp/nope")):
        with pytest.raises(ValueError):
            LifeSim(_cfg(10, 10, 1), layout="serial", initial_board=s, **kw)
    with pytest.raises(ValueError, match="expected"):
        LifeSim(_cfg(10, 10, 1), layout="serial",
                initial_board=_stack(2, 11, 10))


# ------------------------------------------------------- attention layer


def _qkv(b, h, hkv, n, d, seed=5):
    rng = np.random.default_rng(seed)
    return (jnp.asarray(rng.standard_normal((b, h, n, d)), jnp.float32),
            jnp.asarray(rng.standard_normal((b, hkv, n, d)), jnp.float32),
            jnp.asarray(rng.standard_normal((b, hkv, n, d)), jnp.float32))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("hkv", [4, 2])
def test_ring_attention_batched_vs_per_request(causal, hkv):
    from mpi_and_open_mp_tpu.parallel import context

    q, k, v = _qkv(3, 4, hkv, 256, 16)
    out = context.ring_attention(q, k, v, causal=causal)
    assert out.shape == q.shape
    for b in range(3):
        ref = context.ring_attention(q[b], k[b], v[b], causal=causal)
        np.testing.assert_allclose(np.asarray(out[b]), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)


def test_flash_attention_batched_vs_per_request():
    from mpi_and_open_mp_tpu.parallel import context

    q, k, v = _qkv(2, 4, 2, 128, 16, seed=6)
    out = context.flash_attention(q, k, v, causal=True)
    for b in range(2):
        ref = context.flash_attention(q[b], k[b], v[b], causal=True)
        np.testing.assert_allclose(np.asarray(out[b]), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)


def test_ring_attention_batched_grads_match():
    from mpi_and_open_mp_tpu.parallel import context

    q, k, v = _qkv(2, 2, 2, 128, 8, seed=8)

    g_batch = jax.grad(
        lambda q_: jnp.sum(context.ring_attention(q_, k, v, causal=True) ** 2)
    )(q)
    for b in range(2):
        g_one = jax.grad(
            lambda q_: jnp.sum(
                context.ring_attention(q_, k[b], v[b], causal=True) ** 2)
        )(q[b])
        np.testing.assert_allclose(np.asarray(g_batch[b]), np.asarray(g_one),
                                   atol=1e-4, rtol=1e-4)


def test_ring_attention_batched_rejects_mismatched_batch():
    from mpi_and_open_mp_tpu.parallel import context

    q, k, v = _qkv(3, 4, 4, 128, 16)
    with pytest.raises(ValueError, match="batch"):
        context.ring_attention(q, k[:2], v, causal=False)
    with pytest.raises(ValueError, match="batch"):
        context.flash_attention(q, k[:2], v)


def test_engine_stamps_carry_batch_suffix():
    # Pure shape analysis — must work on ShapeDtypeStruct probes.
    from mpi_and_open_mp_tpu.parallel import context

    sq = jax.ShapeDtypeStruct((5, 8, 8192, 128), jnp.bfloat16)
    skv = jax.ShapeDtypeStruct((5, 2, 8192, 128), jnp.bfloat16)
    for fn in (context.flash_engine_for,
               lambda *a: context.ring_hop_engine_for(*a, p=8, causal=True),
               lambda *a: context.ring_hop_bwd_engine_for(*a, p=8,
                                                          causal=True)):
        stamp = fn(sq, skv, skv)
        assert stamp.endswith(":b5"), stamp
        # The base stamp is exactly the folded-shape 3D stamp.
        fq = jax.ShapeDtypeStruct((40, 8192, 128), jnp.bfloat16)
        fkv = jax.ShapeDtypeStruct((10, 8192, 128), jnp.bfloat16)
        assert stamp == fn(fq, fkv, fkv) + ":b5"


# ----------------------------------------------------------- serve layer


def test_bucket_batch_size():
    from mpi_and_open_mp_tpu.serve import bucket_batch_size

    assert [bucket_batch_size(n, 8) for n in (1, 2, 3, 4, 5, 7, 8)] == \
        [1, 2, 4, 4, 8, 8, 8]
    assert bucket_batch_size(3, 2) == 2  # cap wins over pow2
    with pytest.raises(ValueError):
        bucket_batch_size(0, 8)


def test_bucket_batch_size_slice_width():
    from mpi_and_open_mp_tpu.serve import bucket_batch_size

    # Plane-multiple rounding for bitsliced-eligible buckets: never more
    # planes of vector work than pow2 (65 -> 96, not 128), one compiled
    # stack shape per plane count.
    assert bucket_batch_size(20, 64, slice_width=32) == 32
    assert bucket_batch_size(32, 64, slice_width=32) == 32
    assert bucket_batch_size(33, 64, slice_width=32) == 64
    assert bucket_batch_size(65, 128, slice_width=32) == 96
    # Below BITSLICE_MIN_BATCH the padded stack would dispatch
    # cell-packed anyway: pow2 (and a lone request must not project 97%
    # padding waste at admission).
    assert bucket_batch_size(1, 64, slice_width=32) == 1
    assert bucket_batch_size(7, 64, slice_width=32) == 8
    assert bucket_batch_size(8, 64, slice_width=32) == 32
    # Width past the cap: the plane can never dispatch whole -> pow2.
    assert bucket_batch_size(5, 8, slice_width=32) == 8
    # None (cell-packed shapes): plain pow2.
    assert bucket_batch_size(20, 64, slice_width=None) == 32


def test_padding_waste_matches_dispatch_width():
    """Admission projects with the SAME denominator the dispatcher pays
    with: width buckets count in plane quanta (a partly-dead plane costs
    what a full one does, so plane padding is never avoidable waste),
    plain ints keep the historical pow2 board-slot math."""
    from mpi_and_open_mp_tpu.serve.policy import padding_waste

    assert padding_waste([5], 8) == padding_waste([(5, None)], 8)
    # ANY count of a width bucket projects zero waste — ceil(r/32)
    # planes is already the minimum dispatch for r requests. This is
    # the cliff guard: request 9 must not project (32-9)/32 = 72%.
    for r in (1, 8, 9, 20, 32, 33, 64):
        assert padding_waste([(r, 32)], 64) == 0.0
    # Mixed buckets: the width bucket contributes its (fully live)
    # plane quanta, the pow2 bucket keeps its board-slot waste — so a
    # bitsliced bucket can never get a cell-packed peer's request shed.
    got = padding_waste([(20, 32), 3], 64)
    assert got == pytest.approx((1 + 4 - 1 - 3) / (1 + 4))
    assert padding_waste([3], 64) == pytest.approx(1 / 4)


def test_batcher_pads_bitsliced_bucket_to_plane():
    """A 20-request 64² bucket under a 64-wide batcher pads to one
    32-board plane (not pow2) and dispatches bitsliced, every result
    oracle-exact."""
    from mpi_and_open_mp_tpu.serve import ShapeBucketBatcher

    bat = ShapeBucketBatcher(max_batch=64)
    boards = [_soup(64, 64, seed=100 + i) for i in range(20)]
    for b in boards:
        bat.submit(b, 3)
    res = bat.flush()
    (stat,) = bat.last_flush_stats
    assert stat.requests == 20 and stat.padded_batch == 32
    assert stat.path == "bitsliced"
    for b, r in zip(boards, res):
        assert np.array_equal(r, _oracle(b, 3))


def test_queue_admission_uses_dispatch_width(make_board):
    """A lone submission to an empty queue must admit even when its
    shape is bitsliced-eligible (the regression the min-batch gate in
    bucket_batch_size exists to prevent)."""
    from mpi_and_open_mp_tpu.serve import ServePolicy
    from mpi_and_open_mp_tpu.serve.queue import ServeQueue

    q = ServeQueue(ServePolicy(max_batch=64, max_padding_frac=0.375))
    t = q.submit(np.asarray(make_board(64, 64)), 4, now=0.0)
    assert t.state == "pending", t.reason
    assert q._slice_width(t.bucket_key) == 32


def test_daemon_engine_ladder_bitsliced_rung():
    """CPU ladder for a bitsliced-eligible stack: the bitsliced rung
    leads, the vmapped-XLA rung and oracle back it (the cell-packed
    native rung is skipped off-TPU — it would duplicate batch:xla), and
    the rungs agree bit-exactly."""
    from mpi_and_open_mp_tpu.serve import ServingDaemon

    d = ServingDaemon.__new__(ServingDaemon)
    d._aot = None
    stack = _stack(32, 16, 16, seed=21)
    rungs = d._engines(stack, 4)
    assert [s for s, _ in rungs] == ["batch:bitsliced", "batch:xla",
                                     "oracle"]
    out = [np.asarray(fn()) for _, fn in rungs]
    assert np.array_equal(out[0], out[2]) and np.array_equal(out[1], out[2])
    # Below the bitsliced gate: plain cell-packed ladder, no dup rung.
    assert [s for s, _ in d._engines(stack[:4], 4)] == \
        ["batch:xla", "batch:xla", "oracle"]


def test_aot_fingerprint_distinguishes_layouts():
    """A cell-packed artifact can never serve a bitsliced bucket: the
    fingerprint (and so the digest/filename) differs between a
    bucket-32 bitsliced stack and any cell-packed keying of the same
    shape, and records the layout vocabulary explicitly."""
    from mpi_and_open_mp_tpu.serve import aotcache

    fp_bs = aotcache.fingerprint((32, 64, 64), np.uint8)
    fp_cp = aotcache.fingerprint((4, 64, 64), np.uint8)
    assert fp_bs["pack_layout"] == "bitsliced"
    assert fp_cp["pack_layout"] == "cell-packed"
    with pallas_life._bitslice_pinned(False):
        fp_pinned = aotcache.fingerprint((32, 64, 64), np.uint8)
    assert fp_pinned["pack_layout"] == "cell-packed"
    assert aotcache.digest_for(fp_pinned) != aotcache.digest_for(fp_bs)
    # Plane multiples join the pow2 bucket enumeration.
    assert 96 in aotcache.bucket_sizes(128)
    assert aotcache.bucket_sizes(8) == [1, 2, 4, 8]


def test_batcher_results_in_submission_order():
    from mpi_and_open_mp_tpu.serve import ShapeBucketBatcher

    bat = ShapeBucketBatcher(max_batch=4)
    boards = [_soup(20, 20, seed=i) for i in range(3)]
    other = _soup(10, 10, seed=9)
    # Interleave shapes so submission order != bucket order.
    t0 = bat.submit(boards[0], 4)
    t1 = bat.submit(other, 2)
    t2 = bat.submit(boards[1], 4)
    t3 = bat.submit(boards[2], 6)  # same shape, different steps
    assert (t0, t1, t2, t3) == (0, 1, 2, 3)
    assert len(bat) == 4
    res = bat.flush()
    assert len(res) == 4 and len(bat) == 0
    assert np.array_equal(res[0], _oracle(boards[0], 4))
    assert np.array_equal(res[1], _oracle(other, 2))
    assert np.array_equal(res[2], _oracle(boards[1], 4))
    assert np.array_equal(res[3], _oracle(boards[2], 6))


def test_batcher_pads_to_pow2_and_counts():
    from mpi_and_open_mp_tpu.obs import metrics
    from mpi_and_open_mp_tpu.serve import ShapeBucketBatcher

    metrics.reset()
    bat = ShapeBucketBatcher(max_batch=8)
    for i in range(3):
        bat.submit(_soup(16, 16, seed=i), 3)
    bat.flush()
    (stat,) = bat.last_flush_stats
    assert stat.requests == 3 and stat.padded_batch == 4
    assert stat.shape == (16, 16) and stat.steps == 3
    assert metrics.get("serve.requests") == 3
    assert metrics.get("serve.batches") == 1
    assert metrics.get("serve.padding") == 1
    metrics.reset()


def test_batcher_rejects_bad_submissions():
    from mpi_and_open_mp_tpu.serve import ShapeBucketBatcher

    bat = ShapeBucketBatcher(max_batch=4)
    with pytest.raises(ValueError, match="2D"):
        bat.submit(_stack(2, 8, 8), 1)
    with pytest.raises(ValueError, match="steps"):
        bat.submit(_soup(8, 8), -1)
    with pytest.raises(ValueError, match="max_batch"):
        ShapeBucketBatcher(max_batch=0)


def test_one_retrace_per_shape_bucket():
    # THE bucketing acceptance: a flush over K shape buckets compiles
    # exactly K programs, and a SECOND flush over the same buckets (any
    # step counts, any request counts up to the same padded size)
    # compiles ZERO more.
    from mpi_and_open_mp_tpu.obs import metrics
    from mpi_and_open_mp_tpu.serve import ShapeBucketBatcher, retrace_counts

    metrics.reset()
    bat = ShapeBucketBatcher(max_batch=4)
    for i in range(4):
        bat.submit(_soup(24, 24, seed=i), 2)
    for i in range(4):
        bat.submit(_soup(12, 40, seed=i), 5)
    bat.flush()
    counts = retrace_counts()
    assert sum(counts.values()) == 2, counts  # one per shape bucket
    # Same buckets again, different step counts: zero new compiles.
    for i in range(4):
        bat.submit(_soup(24, 24, seed=10 + i), 9)
    for i in range(4):
        bat.submit(_soup(12, 40, seed=10 + i), 1)
    bat.flush()
    assert sum(retrace_counts().values()) == 2, retrace_counts()
    metrics.reset()
