"""Open-loop load generation + SLO-driven elasticity, under test.

The contracts: arrival schedules are deterministic, pre-computed, and
never consult the system under test (no coordinated omission); one
open-loop run drives a real in-process fleet and reports goodput +
nearest-rank tails with every resolved result oracle-gated; the sweep
enforces a monotone rate ladder and the knee reads off the last rung
that met the SLO; the hysteresis controller cannot flap — an action
needs a full consecutive streak on one side and any action opens a
cooldown window. Sentinel polarity for the three published fields rides
along, as every bench phase's does.
"""

import os
import sys

import numpy as np
import pytest

from conftest import oracle_n
from mpi_and_open_mp_tpu.serve import (
    SLO,
    Fleet,
    LoadgenReport,
    ScenarioMix,
    ServePolicy,
    arrivals_poisson,
    arrivals_trace,
    run_open_loop,
    saturation_knee,
    sweep,
)
from mpi_and_open_mp_tpu.serve import policy as policy_mod
from mpi_and_open_mp_tpu.serve.queue import DONE

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def sleep(self, s: float) -> None:
        self.t += s


def _fleet(n=2, **kw):
    clk = FakeClock()
    pol = kw.pop("policy", ServePolicy(max_batch=4, max_wait_s=0.0))
    return Fleet(n, pol, clock=clk, sleep=clk.sleep, steal=False, **kw), clk


#: Small boards keep the CPU interpret path fast; two shapes still
#: exercise distinct compiled buckets at the door.
MIX = ScenarioMix(batch=0.6, resident=0.3, snapshot=0.1,
                  shapes=((12, 12), (16, 16)), steps=(2, 4), sessions=3)


# ------------------------------------------------------------- schedules


def test_arrivals_poisson_deterministic_and_rate_true():
    a = arrivals_poisson(50.0, 4.0, seed=3)
    b = arrivals_poisson(50.0, 4.0, seed=3)
    assert a == b  # the schedule is a pure function of (rate, T, seed)
    assert arrivals_poisson(50.0, 4.0, seed=4) != a
    assert all(0 <= x < 4.0 for x in a)
    assert all(y >= x for x, y in zip(a, a[1:]))
    # Poisson count ~ N(200, sqrt(200)): a 5-sigma band never flakes.
    assert 200 - 5 * np.sqrt(200) < len(a) < 200 + 5 * np.sqrt(200)


def test_arrivals_validation():
    with pytest.raises(ValueError, match="rate_rps"):
        arrivals_poisson(0.0, 1.0)
    with pytest.raises(ValueError, match="duration_s"):
        arrivals_poisson(1.0, -1.0)
    assert arrivals_trace([0.0, 0.5, 0.5, 2.0]) == [0.0, 0.5, 0.5, 2.0]
    with pytest.raises(ValueError, match=">= 0"):
        arrivals_trace([-0.1, 0.5])
    with pytest.raises(ValueError, match="non-decreasing"):
        arrivals_trace([0.5, 0.1])


def test_mix_and_slo_validation():
    with pytest.raises(ValueError, match="weight"):
        ScenarioMix(batch=-1.0)
    with pytest.raises(ValueError, match="sum to > 0"):
        ScenarioMix(batch=0.0)
    with pytest.raises(ValueError, match="sessions"):
        ScenarioMix(resident=1.0, sessions=0)
    with pytest.raises(ValueError, match="fill"):
        ScenarioMix(fill=1.5)
    w = ScenarioMix(batch=3.0, resident=1.0, sessions=2).weights()
    np.testing.assert_allclose(w, [0.75, 0.25, 0.0])

    with pytest.raises(ValueError, match="p99_s"):
        SLO(p99_s=0.0)
    with pytest.raises(ValueError, match="p999_s"):
        SLO(p99_s=0.5, p999_s=0.1)
    with pytest.raises(ValueError, match="goodput_frac"):
        SLO(goodput_frac=0.0)
    slo = SLO(p99_s=0.1, p999_s=0.5, goodput_frac=0.9)
    assert slo.verdict(goodput_rps=9.5, offered_rps=10.0,
                       p99_s=0.05, p999_s=0.4)
    # Each bound trips the verdict alone.
    assert not slo.verdict(goodput_rps=9.5, offered_rps=10.0,
                           p99_s=0.2, p999_s=0.4)
    assert not slo.verdict(goodput_rps=9.5, offered_rps=10.0,
                           p99_s=0.05, p999_s=0.6)
    assert not slo.verdict(goodput_rps=8.0, offered_rps=10.0,
                           p99_s=0.05, p999_s=0.4)


# ---------------------------------------------------------- open-loop runs


def test_run_open_loop_mixed_traffic_oracle_gated():
    """One run over the full scenario mix: every request lands, every
    resolved batch ticket and every resident session is bit-exact
    against the oracle, and the report's accounting closes."""
    f, _clk = _fleet(2)
    rep = run_open_loop(f, 40.0, 2.0, mix=MIX, seed=5,
                        slo=SLO(p99_s=10.0, goodput_frac=0.5))
    assert rep.offered == rep.submitted + rep.snapshots > 0
    assert rep.snapshots > 0  # the mix actually exercised all 3 kinds
    assert rep.resolved + sum(rep.shed.values()) == rep.submitted
    assert rep.shed == {}  # nothing sheds this far under the knee
    assert rep.goodput_rps > 0 and rep.books["balanced"]
    assert rep.p50_s <= rep.p99_s <= rep.p999_s
    assert rep.slo_ok
    # Parity: one-shot boards against the NumPy oracle...
    done = [t for h in f.handles for t in h.daemon.queue.tickets()
            if t.state == DONE and t.board is not None]
    assert done
    for t in done:
        np.testing.assert_array_equal(t.result, oracle_n(t.board, t.steps))
    # ... and the resident sessions at their journaled step totals.
    steps_by_sid: dict = {}
    for h in f.handles:
        for t in h.daemon.queue.tickets():
            if t.state == DONE and t.session in rep.resident_boards:
                steps_by_sid[t.session] = (
                    steps_by_sid.get(t.session, 0) + t.steps)
    for sid, board in rep.resident_boards.items():
        np.testing.assert_array_equal(
            f.snapshot_session(sid),
            oracle_n(board, steps_by_sid.get(sid, 0)),
            err_msg=f"resident session {sid} lost parity")


def test_run_open_loop_is_deterministic():
    ra = run_open_loop(_fleet(2)[0], 30.0, 1.5, mix=MIX, seed=9)
    rb = run_open_loop(_fleet(2)[0], 30.0, 1.5, mix=MIX, seed=9)
    assert ra.to_dict() == rb.to_dict()


def test_run_open_loop_submits_on_schedule_not_on_completion():
    """The open-loop property itself: the generator offers every
    scheduled request even when the fleet never finishes one. A
    closed-loop generator would stall at the first unresolved ticket."""
    f, _clk = _fleet(1, policy=ServePolicy(max_batch=4, max_depth=8,
                                           max_wait_s=0.0))
    halted = f.handles[0]
    halted.halted = True  # the lone worker never pumps...

    # ...so drain would hang; run the submission loop only, via a trace
    # whose last instant we stop before (duration caps the loop).
    trace = [i * 0.01 for i in range(30)]
    mix = ScenarioMix(batch=1.0, shapes=((12, 12),), steps=(2,))
    with pytest.raises(RuntimeError, match="failed to drain"):
        run_open_loop(f, 0.0, 0.30, mix=mix, trace=trace,
                      drain_timeout_s=0.5)
    books = f.router.books()
    # Every arrival was offered against the wedged fleet: 8 admitted
    # (the depth budget), the rest shed at the door — none waiting on a
    # completion that never came.
    assert books["submitted"] == 30
    assert books["admitted"] == 8
    assert books["door_shed"] == 22


def test_run_open_loop_fires_events():
    seen = []
    f, _clk = _fleet(2)
    run_open_loop(f, 20.0, 1.0, mix=MIX, seed=2,
                  events=[(0.5, lambda fl: seen.append(("mid", fl))),
                          (0.99, lambda fl: seen.append(("late", fl)))])
    assert [k for k, _ in seen] == ["mid", "late"]
    assert all(fl is f for _, fl in seen)


def test_sweep_monotone_ladder_and_knee():
    with pytest.raises(ValueError, match="strictly increasing"):
        sweep(lambda: _fleet(2)[0], [10.0, 10.0], 1.0)
    with pytest.raises(ValueError, match="at least one rate"):
        sweep(lambda: _fleet(2)[0], [], 1.0)

    reports = sweep(lambda: _fleet(2)[0], [10.0, 20.0], 1.5,
                    mix=MIX, slo=SLO(p99_s=10.0, goodput_frac=0.5),
                    seed=1)
    assert len(reports) == 2
    assert reports[0].offered_rps < reports[1].offered_rps
    knee = saturation_knee(reports)
    assert knee["knee_rps"] == round(reports[1].offered_rps, 3)
    assert knee["breach_rps"] is None
    assert [p["offered_rps"] for p in knee["points"]] == \
        [round(r.offered_rps, 3) for r in reports]


def test_saturation_knee_reads_last_passing_rung():
    def rep(rate, ok):
        return LoadgenReport(
            offered_rps=rate, duration_s=1.0, offered=int(rate),
            submitted=int(rate), resolved=int(rate), snapshots=0,
            shed={}, goodput_rps=rate, p50_s=0.01, p99_s=0.02,
            p999_s=0.03, slo_ok=ok, wall_s=1.0, books={})

    knee = saturation_knee([rep(10, True), rep(20, True),
                            rep(40, False), rep(80, False)])
    assert knee["knee_rps"] == 20.0 and knee["breach_rps"] == 40.0
    knee = saturation_knee([rep(10, False)])
    assert knee["knee_rps"] is None and knee["breach_rps"] == 10.0
    with pytest.raises(ValueError, match="at least one report"):
        saturation_knee([])


# ------------------------------------------------- hysteresis controller


def _ctl(**kw):
    defaults = dict(slo_p99_s=0.1, min_workers=1, max_workers=4,
                    breach_k=3, surplus_k=3, cooldown_k=2)
    defaults.update(kw)
    return policy_mod.ElasticController(
        policy_mod.ElasticityPolicy(**defaults))


def test_controller_needs_consecutive_breaches():
    c = _ctl()
    assert c.observe(p99_s=0.5, depth=9, workers=2) is None
    assert c.observe(p99_s=0.5, depth=9, workers=2) is None
    # One healthy window resets the streak — two separated breaches
    # never add up to an action.
    assert c.observe(p99_s=0.08, depth=9, workers=2) is None
    assert c.observe(p99_s=0.5, depth=9, workers=2) is None
    assert c.observe(p99_s=0.5, depth=9, workers=2) is None
    assert c.observe(p99_s=0.5, depth=9, workers=2) \
        == policy_mod.SCALE_ADD
    assert c.actions == [policy_mod.SCALE_ADD]


def test_controller_cooldown_blocks_back_to_back_actions():
    c = _ctl(breach_k=1, cooldown_k=3)
    assert c.observe(p99_s=0.5, depth=9, workers=2) \
        == policy_mod.SCALE_ADD
    for _ in range(3):  # breach_k=1 satisfied, cooldown holds anyway
        assert c.observe(p99_s=0.5, depth=9, workers=3) is None
    assert c.observe(p99_s=0.5, depth=9, workers=3) \
        == policy_mod.SCALE_ADD
    assert c.actions == [policy_mod.SCALE_ADD] * 2


def test_controller_cannot_flap_on_oscillating_signal():
    c = _ctl()
    for i in range(40):  # alternating breach/surplus windows
        v = (c.observe(p99_s=0.5, depth=9, workers=2) if i % 2
             else c.observe(p99_s=0.0, depth=0, workers=2))
        assert v is None
    assert c.actions == []


def test_controller_respects_worker_bounds():
    c = _ctl(breach_k=1, surplus_k=1, cooldown_k=0)
    assert c.observe(p99_s=0.5, depth=9, workers=4) is None  # at max
    assert c.observe(p99_s=0.0, depth=0, workers=1) is None  # at min
    assert c.observe(p99_s=0.5, depth=9, workers=3) \
        == policy_mod.SCALE_ADD
    assert c.observe(p99_s=0.0, depth=0, workers=2) \
        == policy_mod.SCALE_DRAIN


def test_controller_starvation_counts_as_breach():
    """Zero goodput under offered load is a breach even with an empty
    latency window — the fleet that resolves NOTHING has a perfect p99
    over zero samples, and the controller must not reward it."""
    c = _ctl(breach_k=2, cooldown_k=0)
    for _ in range(2):
        v = c.observe(p99_s=0.0, depth=50, workers=2,
                      goodput_rps=0.0, offered_rps=40.0)
    assert v == policy_mod.SCALE_ADD
    # And a goodput shortfall breaches below the SLO fraction.
    c = _ctl(breach_k=1, cooldown_k=0)
    assert c.observe(p99_s=0.01, depth=0, workers=2, goodput_rps=30.0,
                     offered_rps=40.0) == policy_mod.SCALE_ADD


def test_controller_surplus_needs_empty_queue():
    c = _ctl(surplus_k=1, cooldown_k=0)
    assert c.observe(p99_s=0.0, depth=5, workers=3) is None
    assert c.observe(p99_s=0.0, depth=0, workers=3) \
        == policy_mod.SCALE_DRAIN


# ------------------------------------------------------- sentinel plumbing


def test_sentinel_polarity_for_loadgen_fields():
    sys.path.insert(0, os.path.join(REPO, "analysis"))
    import regression_sentinel as sentinel

    assert sentinel.direction_for("loadgen_goodput_rps") == "higher"
    assert sentinel.direction_for("loadgen_knee_rps") == "higher"
    assert sentinel.direction_for("loadgen_p999_latency_s") == "lower"
    assert sentinel.direction_for("rejoin_recovery_s") == "lower"
    for field in ("loadgen_goodput_rps", "loadgen_p999_latency_s",
                  "rejoin_recovery_s"):
        assert field in sentinel.WATCH_FIELDS
