"""Wide-radius engine families (PR 20): the lenia registry spec, the
separable and FFT aggregation paths racing the offset table, the fuse
depth as a tuned axis, and the sentinel/ledger provenance plumbing.

Everything runs on the conftest 8-virtual-device CPU mesh; parity is
always against the NumPy oracle at the GATE-owned per-family tolerance
(``stencils.parity_tol_for``) — the same gates ``bench.py --radius-ab``
and the plan-store install path use.
"""

import numpy as np
import pytest

from mpi_and_open_mp_tpu import stencils
from mpi_and_open_mp_tpu.ops import pallas_life
from mpi_and_open_mp_tpu.parallel import mesh as mesh_lib
from mpi_and_open_mp_tpu.stencils import engine as stencil_engine
from mpi_and_open_mp_tpu.stencils import spec as spec_mod
from mpi_and_open_mp_tpu.tune import space

LENIA = stencils.get("lenia")


def _board(shape=(32, 32), seed=46):
    return LENIA.init(np.random.default_rng(seed), shape)


# ------------------------------------------------- the lenia registry spec


def test_lenia_registered_wide_radius_float():
    assert LENIA.radius == 8 and LENIA.dtype == "float32"
    assert LENIA.boundary == "torus" and LENIA.channels == 1
    # The Gaussian ring minus its center pixel is exactly rank 2, and
    # the rank is cached on the spec so legality gates never
    # re-factorize per call.
    assert LENIA.separable_rank == 2
    assert stencil_engine.separable_supported(LENIA)
    assert stencil_engine.fft_supported(LENIA)
    # Narrow zero-center tables never factor at rank <= radius, so the
    # legacy specs enumerate exactly as before this PR.
    for name in ("life", "heat", "wireworld"):
        assert stencils.get(name).separable_rank is None
    w = np.asarray(LENIA.weights, np.float64)
    assert w[LENIA.radius, LENIA.radius] == 0.0
    np.testing.assert_allclose(w.sum(), 1.0, atol=1e-12)


def test_register_rejects_nonfinite_weights():
    import dataclasses

    w = np.asarray(spec_mod.make_lenia(4, "lenia_nan").weights,
                   np.float64)
    w[0, 0] = np.nan
    bad = dataclasses.replace(
        spec_mod.make_lenia(4, "lenia_nan"),
        weights=tuple(tuple(float(x) for x in row) for row in w))
    with pytest.raises(ValueError):
        spec_mod.register(bad)
    assert "lenia_nan" not in stencils.names()


# ------------------------------------------ single-device family parity


@pytest.mark.parametrize("family", stencil_engine.ENGINE_FAMILIES)
def test_family_parity_vs_oracle(family):
    board = _board()
    got = np.asarray(stencil_engine.run_family(LENIA, board, 8, family))
    ref = stencils.oracle_run(LENIA, board, 8)
    assert stencils.parity_ok(LENIA, got, ref,
                              **stencil_engine.parity_tol_for(family))


@pytest.mark.parametrize("family", ["sep", "fft"])
def test_family_batch_parity_vs_oracle(family):
    rng = np.random.default_rng(7)
    stack = np.stack([LENIA.init(rng, (24, 40)) for _ in range(3)])
    got = np.asarray(stencil_engine.run_family_batch(
        LENIA, stack, 6, family))
    tol = stencil_engine.parity_tol_for(family)
    for i in range(3):
        assert stencils.parity_ok(
            LENIA, got[i], stencils.oracle_run(LENIA, stack[i], 6), **tol)


def test_fft_tolerance_is_gate_owned():
    """The FFT path is approximate by construction: the parity GATE
    owns the float slack (``parity_tol_for("fft")``), the engine never
    loosens anything itself — the same output rejects under a
    bit-tight gate and passes under the family's declared one."""
    board = _board()
    got = np.asarray(stencil_engine.run_family(LENIA, board, 8, "fft"))
    ref = stencils.oracle_run(LENIA, board, 8)
    assert stencils.parity_ok(LENIA, got, ref,
                              **stencil_engine.parity_tol_for("fft"))
    # A bit-tight gate rejects: the transform really is approximate,
    # and nothing inside the engine hides that from the gate.
    assert not stencils.parity_ok(LENIA, got, ref, rtol=0.0, atol=1e-9)
    with pytest.raises(ValueError):
        stencil_engine.parity_tol_for("warp")  # unknown family


# --------------------------------------------------------------- refusals


def test_separable_refuses_nonfactorizable_weights():
    # heat's 3x3 zero-center table is rank 2 > radius 1: refused.
    heat = stencils.get("heat")
    assert not stencil_engine.separable_supported(heat)
    with pytest.raises(ValueError, match="factor"):
        stencil_engine.run_family(
            heat, heat.init(np.random.default_rng(3), (16, 16)), 2, "sep")
    # A full-rank random wide table refuses too — rank > radius.
    rng = np.random.default_rng(5)
    w = rng.random((5, 5))
    w[2, 2] = 0.0
    import dataclasses

    rand = dataclasses.replace(
        spec_mod.make_lenia(2, "lenia_rand"),
        weights=tuple(tuple(float(x) for x in row) for row in w))
    assert rand.separable_rank is None
    with pytest.raises(ValueError):
        stencil_engine.run_family(
            rand, rand.init(np.random.default_rng(3), (16, 16)), 2, "sep")


def test_fft_refuses_int_dtype_and_narrow_radius():
    life = stencils.get("life")
    assert not stencil_engine.fft_supported(life)  # uint8 rules
    with pytest.raises(ValueError):
        stencil_engine.run_family(
            life, life.init(np.random.default_rng(3), (16, 16)), 2, "fft")
    # The radius floor is an ENUMERATION gate (below it the transform
    # can't win), not a correctness refusal: a forced narrow-radius
    # float run still computes and still passes its parity gate.
    heat = stencils.get("heat")
    assert not stencil_engine.fft_supported(heat)  # radius 1 < minimum
    hboard = heat.init(np.random.default_rng(3), (16, 16))
    got = np.asarray(stencil_engine.run_family(heat, hboard, 4, "fft"))
    assert stencils.parity_ok(heat, got,
                              stencils.oracle_run(heat, hboard, 4),
                              **stencil_engine.parity_tol_for("fft"))
    narrow = spec_mod.make_lenia(stencil_engine.FFT_MIN_RADIUS - 1,
                                 "lenia_narrow")
    assert not stencil_engine.fft_supported(narrow)


def test_sharded_runner_refuses_eagerly():
    mesh = mesh_lib.make_mesh_2d(4, 2)
    heat = stencils.get("heat")
    with pytest.raises(ValueError):
        stencil_engine.make_sharded_runner(
            heat, mesh, "row", (48, 48), family="sep")
    with pytest.raises(ValueError):
        stencil_engine.make_sharded_runner(
            LENIA, mesh, "row", (96, 96), family="warp")


# ------------------------------------------------- sharded family parity


@pytest.mark.parametrize("family", stencil_engine.ENGINE_FAMILIES)
@pytest.mark.parametrize("layout", ["row", "col", "cart"])
def test_sharded_family_parity_every_layout(layout, family):
    """All three families through the PR 15 halo machinery on every
    layout: the halo plan is family-blind (radius-deep ghosts serve
    any aggregation order), parity is at the family's gate tolerance."""
    board = _board((96, 96))
    mesh = mesh_lib.make_mesh_2d(4, 2)
    got = np.asarray(stencil_engine.run_sharded(
        LENIA, board, 4, mesh=mesh, layout=layout, family=family))
    ref = stencils.oracle_run(LENIA, board, 4)
    assert stencils.parity_ok(LENIA, got, ref,
                              **stencil_engine.parity_tol_for(family))


# ------------------------------------- candidate space + the kill switch


def test_stencil_paths_list_families_and_respect_pin(monkeypatch):
    shape = (2, 32, 32)
    paths = space.stencil_paths(LENIA, shape)
    assert paths == ["stencil:roll", "stencil:pallas", "stencil:sep",
                     "stencil:fft"]
    # Narrow specs enumerate exactly as before the families landed.
    heat = stencils.get("heat")
    assert space.stencil_paths(heat, shape) == [
        "stencil:roll", "stencil:pallas"]
    monkeypatch.setenv(stencil_engine.ENV_FAMILY, "offset")
    assert space.stencil_paths(LENIA, shape) == [
        "stencil:roll", "stencil:pallas"]
    monkeypatch.setenv(stencil_engine.ENV_FAMILY, "sep")
    assert space.stencil_paths(LENIA, shape) == [
        "stencil:roll", "stencil:pallas", "stencil:sep"]
    monkeypatch.setenv(stencil_engine.ENV_FAMILY, "warp")
    with pytest.raises(ValueError):
        stencil_engine.family_pinned()


def test_planned_family_neutralized_by_pin(monkeypatch):
    """An installed ``stencil:fft`` plan under ``MOMP_ENGINE_FAMILY=
    offset`` stops steering at the NEXT dispatch — no uninstall, the
    pin is honored at read time."""
    shape = (2, 32, 32)
    pallas_life.clear_planned_paths()
    try:
        pallas_life.install_planned_path("lenia", shape, "stencil:fft")
        assert pallas_life.planned_path("lenia", shape) == "stencil:fft"
        monkeypatch.setenv(stencil_engine.ENV_FAMILY, "offset")
        assert pallas_life.planned_path("lenia", shape) is None
        monkeypatch.setenv(stencil_engine.ENV_FAMILY, "fft")
        assert pallas_life.planned_path("lenia", shape) == "stencil:fft"
    finally:
        pallas_life.clear_planned_paths()


def test_family_for_path_vocabulary():
    assert stencil_engine.family_for_path("stencil:sep") == "sep"
    assert stencil_engine.family_for_path("stencil:fft") == "fft"
    for p in ("stencil:roll", "stencil:pallas", "vmem", "seq:halo"):
        assert stencil_engine.family_for_path(p) == "offset"


# ----------------------------------------------- fuse depth as tuned axis


def test_sparse_fuse_depths_heuristic_first_and_legal(monkeypatch):
    monkeypatch.delenv("MOMP_TUNE_SPARSE_FUSE", raising=False)
    # radius 1, tile 64: heuristic 16 first, then the env defaults
    # minus duplicates; everything within the radius*fuse <= tile clamp.
    assert space.sparse_fuse_depths(1, 64) == (16, 4, 64)
    # radius 8, tile 64: the clamp bites — cap 8 shrinks the heuristic
    # rung itself (exactly what an untuned ctor runs) and gates 16/64.
    assert space.sparse_fuse_depths(8, 64) == (8, 4)
    # A tile the radius fills entirely leaves only depth 1.
    assert space.sparse_fuse_depths(8, 8) == (1,)
    monkeypatch.setenv("MOMP_TUNE_SPARSE_FUSE", "2,32")
    assert space.sparse_fuse_depths(1, 64) == (16, 2, 32)
    for f in space.sparse_fuse_depths(8, 64):
        assert 8 * f <= 64


def test_sharded_candidates_enumerate_fuse_axis():
    mesh = mesh_lib.make_mesh_1d()
    edge = 8 * space.SPARSE_SHARDED_TILE
    cands = space.sharded_candidates("life", (edge, edge), mesh)
    sparse = [c for c in cands if c.path == "sparse_sharded:row"]
    want = space.sparse_fuse_depths(1, space.SPARSE_SHARDED_TILE)
    assert tuple(c.fuse_steps for c in sparse) == want
    # Heuristic depth stays candidate #0 so vs_heuristic >= 1.0 holds.
    assert sparse[0].fuse_steps == min(space.SPARSE_FUSE_HEURISTIC,
                                       space.SPARSE_SHARDED_TILE)
    assert all(c.halo_overlap == "sparse" for c in sparse)


def test_plan_store_persists_sparse_fuse(tmp_path):
    """A sparse-sharded record's tuned fuse depth survives the
    save -> fresh-process install (parity re-gated at the persisted
    tile+fuse geometry) -> lookup_sharded roundtrip."""
    from mpi_and_open_mp_tpu.tune import plans as tune_plans

    shape, tile, fuse = (128, 128), 16, 4
    spec = stencils.get("life")
    key = tune_plans.fingerprint_for(
        "life", shape, spec.np_dtype, "sparse_sharded:row")
    leg = {"path": "sparse_sharded:row", "axis_order": "row",
           "halo_overlap": "sparse", "fuse_steps": fuse,
           "boundary_steps": fuse, "engine": f"sparse-sharded:row:t{tile}",
           "steady_s_per_step": 1e-4, "cups": 1.0, "is_differenced": True}
    record = {
        "schema": tune_plans.PLAN_SCHEMA,
        "key": key,
        "choice": {"workload": "life", "shape": list(shape),
                   "dtype": str(spec.np_dtype),
                   "path": "sparse_sharded:row", "pack_layout": "-",
                   "bucket_rounding": space.BUCKET_POW2,
                   "axis_order": "row", "halo_overlap": "sparse",
                   "fuse_steps": fuse, "boundary_steps": fuse,
                   "mesh_axes": [8, 1], "tile": tile},
        "heuristic": leg, "tuned": leg, "vs_heuristic": 1.0,
        "vs_sequential": 1.0, "steps_budget": 16,
        "measurements": [leg], "rejected": [],
    }
    store = tune_plans.PlanStore(str(tmp_path))
    store.save(record)
    fresh = tune_plans.PlanStore(str(tmp_path))
    summary = fresh.install()
    assert summary["installed"] == 1 and summary["parity_rejected"] == 0
    hit = fresh.lookup_sharded("life", shape)
    assert hit is not None
    assert hit["choice"]["fuse_steps"] == fuse
    assert hit["choice"]["tile"] == tile


def test_tune_lenia_families_race_vs_heuristic(tmp_path):
    """The acceptance invariant: with sep/fft in the race the tuner's
    winner still never loses to the heuristic's own choice (which is
    always among the timed candidates)."""
    from mpi_and_open_mp_tpu.tune import plans as tune_plans
    from mpi_and_open_mp_tpu.tune import runner as tune_runner

    try:
        res = tune_runner.tune("lenia", (2, 32, 32), steps=16,
                               store=tune_plans.PlanStore(str(tmp_path)))
    finally:
        pallas_life.clear_planned_paths()
    timed = {m["path"] for m in res["measurements"]}
    assert {"stencil:roll", "stencil:sep", "stencil:fft"} <= timed
    assert res["vs_heuristic"] >= 1.0


# ----------------------------------------------------- serve daemon rungs


def test_daemon_rungs_list_families_and_follow_plan(monkeypatch):
    """The non-life recovery ladder grows sep/fft rungs for specs that
    support them, keeps the roll rung primary by default, promotes the
    planned family to the front, and drops pinned-out families — all
    with the oracle still last."""
    from mpi_and_open_mp_tpu.serve import ServePolicy, ServingDaemon

    d = ServingDaemon(ServePolicy(max_batch=8))
    rng = np.random.default_rng(7)
    stack = np.stack([LENIA.init(rng, (32, 32)) for _ in range(2)])
    pallas_life.clear_planned_paths()
    try:
        names = [n for n, _ in d._engines(stack, 4, spec=LENIA)]
        assert names == ["batch:stencil:lenia",
                         "batch:stencil-pallas:lenia",
                         "batch:stencil-sep:lenia",
                         "batch:stencil-fft:lenia", "oracle"]
        pallas_life.install_planned_path("lenia", stack.shape,
                                         "stencil:fft")
        names = [n for n, _ in d._engines(stack, 4, spec=LENIA)]
        assert names[0] == "batch:stencil-fft:lenia"
        assert names[-1] == "oracle"
        monkeypatch.setenv(stencil_engine.ENV_FAMILY, "offset")
        names = [n for n, _ in d._engines(stack, 4, spec=LENIA)]
        assert names == ["batch:stencil:lenia",
                         "batch:stencil-pallas:lenia", "oracle"]
    finally:
        pallas_life.clear_planned_paths()


# --------------------------------------- sentinel + ledger provenance


def test_sentinel_and_ledger_plumbing():
    from analysis import regression_sentinel as sentinel
    from mpi_and_open_mp_tpu.obs import ledger

    for f in ("radius_ab_offset_cups", "radius_ab_sep_cups",
              "radius_ab_fft_cups", "radius_ab_vs_offset_best"):
        assert f in sentinel.WATCH_FIELDS
        assert sentinel.direction_for(f) == "higher"
    assert "engine_family" in sentinel.PROVENANCE_FIELDS
    # fft -> offset on the same workload must read as a DOWNGRADE.
    assert (sentinel.engine_rank("fft") > sentinel.engine_rank("offset"))
    assert (sentinel.engine_rank("sep") > sentinel.engine_rank("offset"))
    assert (sentinel.engine_rank("fft") > sentinel.engine_rank("sep"))
    assert (sentinel.engine_rank("batch:stencil:fft")
            > sentinel.engine_rank("batch:stencil:sep"))
    # The halo schedule stamp must NOT collide with the sep matcher.
    assert sentinel.engine_rank("seq:halo") == 1
    assert "engine_family" in ledger.KEY_FIELDS
    entry = ledger.stamp({"metric": "m", "board": [64, 64],
                          "engine_family": "fft"},
                         platform="cpu", device_count=8)
    assert entry["key"]["engine_family"] == "fft"
    entry = ledger.stamp({"metric": "m", "board": [64, 64]},
                         platform="cpu", device_count=8)
    assert entry["key"]["engine_family"] == "-"
    # Pre-PR-20 entries match new "-" lines through the key defaults.
    old = {"key": {f: "x" for f in ledger.KEY_FIELDS
                   if f != "engine_family"}}
    assert "engine_family=-" in ledger.config_key(old, ("engine_family",))
