"""Kernel-level parity: NumPy oracle properties and jnp step equivalence."""

import numpy as np
import pytest

import jax.numpy as jnp

from mpi_and_open_mp_tpu.ops.life_ops import (
    life_step_numpy,
    life_step_padded,
    life_step_roll,
    pad_x_wrap,
    pad_y_wrap,
)


def _glider(ny=10, nx=10):
    b = np.zeros((ny, nx), dtype=np.uint8)
    for i, j in [(0, 2), (1, 0), (1, 2), (2, 1), (2, 2)]:
        b[j, i] = 1
    return b


def test_oracle_empty_stays_empty():
    b = np.zeros((10, 10), np.uint8)
    for _ in range(5):
        b = life_step_numpy(b)
    assert b.sum() == 0


def test_oracle_blinker_period_2():
    b = np.zeros((8, 8), np.uint8)
    b[3, 2:5] = 1
    b1 = life_step_numpy(b)
    b2 = life_step_numpy(b1)
    assert b1.sum() == 3 and not np.array_equal(b1, b)
    np.testing.assert_array_equal(b2, b)


def test_oracle_glider_translates_with_torus_wrap():
    """After 4 steps a glider shifts by (+1, +1); after 40 steps on a 10x10
    torus it returns to the start — exercising the periodic wrap the
    reference bakes into ind() (3-life/life2d.c:9)."""
    b0 = _glider()
    b = b0.copy()
    for _ in range(4):
        b = life_step_numpy(b)
    np.testing.assert_array_equal(b, np.roll(np.roll(b0, 1, axis=0), 1, axis=1))
    for _ in range(36):
        b = life_step_numpy(b)
    np.testing.assert_array_equal(b, b0)


@pytest.mark.parametrize("shape", [(10, 10), (17, 23), (8, 128), (33, 65)])
def test_roll_step_matches_oracle(make_board, shape):
    b = make_board(*shape)
    jb = jnp.asarray(b)
    for _ in range(10):
        b = life_step_numpy(b)
        jb = life_step_roll(jb)
        np.testing.assert_array_equal(np.asarray(jb), b)


@pytest.mark.parametrize("shape", [(12, 16), (9, 11)])
def test_padded_step_matches_oracle(make_board, shape):
    """Self-wrapped padded block (serial torus) must equal the oracle."""
    b = make_board(*shape)
    padded = pad_x_wrap(pad_y_wrap(jnp.asarray(b)))
    out = life_step_padded(padded)
    np.testing.assert_array_equal(np.asarray(out), life_step_numpy(b))


def test_padded_multistep_shrink(make_board):
    """Depth-k halo + k fused steps == k plain steps (halo fusion validity)."""
    b = make_board(16, 16)
    k = 3
    padded = pad_x_wrap(pad_y_wrap(jnp.asarray(b), depth=k), depth=k)
    for _ in range(k):
        padded = life_step_padded(padded)
    ref = b
    for _ in range(k):
        ref = life_step_numpy(ref)
    np.testing.assert_array_equal(np.asarray(padded), ref)
