"""Subprocess driver for the WAL crash-matrix test.

Runs a real serving daemon with a write-ahead journal and a
``MOMP_CHAOS crash=<site>:<k>`` plan armed by the parent test, acking
every ticket whose ``submit()`` RETURNED to a side file (write + flush +
fsync, so the ack record is durable before the parent can read it). The
chaos site hard-kills the process with ``os._exit(137)`` — no atexit, no
finally — and the parent then replays the journal and asserts the
per-fsync-policy loss bound over exactly the acked set.

Usage: ``python _wal_crash_driver.py WAL_PATH FSYNC_POLICY ACK_PATH N
[pool|settled]``

With the optional ``pool`` mode the driver exercises the resident-
session handle lifecycle instead of the ticket path: create N pool
sessions (ack ``C <sid>`` once create returns), two rounds of 2-step
resident steps per session (ack ``S <sid> 2``), one snapshot (``N
<sid>``), one evict (``E <sid>``). The pool chaos sites
(``post-create``/``post-step``/``post-snapshot``/``post-evict``) fire
AFTER the frame is journaled and BEFORE the pool acts, so an acked op is
always durable under ``every-record`` and the parent can assert the
resumed pool matches the acked ledger exactly (plus at most one
journaled-but-unacked op — the at-least-once edge).

The ``settled`` mode is the pool mode with session p0 seeded as a STILL
LIFE (a block) among active random boards, and enough 2-step rounds for
the settled-skip fast path to engage (p0's dispatches stop once its
fixed point is proven). The WAL's STEP frames stay authoritative:
replay re-applies every journaled step and RE-PROVES settledness, so
the parent asserts the resumed p0 snapshot is bit-identical to the
oracle at the acked step count even though some of those steps were
never dispatched by the pre-kill process.

Exits 0 after a clean drain (printing a one-line JSON summary); a
planned crash never reaches that code.
"""

import json
import os
import sys

# The sitecustomize in this environment points jax at the TPU plugin;
# this driver is CPU-only host-side work and must never touch the chip.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from mpi_and_open_mp_tpu.serve import ServePolicy, ServingDaemon

    wal_path, fsync, ack_path = sys.argv[1], sys.argv[2], sys.argv[3]
    n = int(sys.argv[4])
    mode = sys.argv[5] if len(sys.argv) > 5 else ""
    pool_mode = mode in ("pool", "settled")
    policy = ServePolicy(max_batch=4, max_wait_s=0.0)
    daemon = ServingDaemon(policy, wal_path=wal_path, wal_fsync=fsync)
    rng = np.random.default_rng(7)
    with open(ack_path, "ab") as ack:
        def rec(line: str) -> None:
            ack.write((line + "\n").encode())
            ack.flush()
            os.fsync(ack.fileno())

        if pool_mode:
            for i in range(n):
                board = (rng.random((12, 12)) < 0.3).astype(np.uint8)
                if mode == "settled" and i == 0:
                    # p0 is a still life: its dispatches stop once the
                    # pool proves the per-lane fixed point.
                    board = np.zeros((12, 12), np.uint8)
                    board[5:7, 5:7] = 1
                daemon.create_session(f"p{i}", board)
                rec(f"C p{i}")
            # settled mode runs extra rounds: the first round proves
            # p0's fixed point, later rounds exercise the skip path
            # with the chaos site still armed.
            for _ in range(5 if mode == "settled" else 2):
                for i in range(n):
                    daemon.step_session(f"p{i}", 2)
                    rec(f"S p{i} 2")
            daemon.snapshot_session("p0")
            rec("N p0")
            daemon.evict_session(f"p{n - 1}")
            rec(f"E p{n - 1}")
            daemon._wal.sync()
            s = daemon.summary()
            daemon._wal.close()
            print(json.dumps({"sessions": s["pool_sessions"]}))
            return 0

        for i in range(n):
            board = (rng.random((12, 12)) < 0.3).astype(np.uint8)
            t = daemon.submit(board, 2)
            rec(str(t.id))
    daemon.serve()
    s = daemon.summary()
    daemon._wal.close()
    print(json.dumps({"resolved": s["resolved"], "shed": s["shed"]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
