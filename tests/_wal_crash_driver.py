"""Subprocess driver for the WAL crash-matrix test.

Runs a real serving daemon with a write-ahead journal and a
``MOMP_CHAOS crash=<site>:<k>`` plan armed by the parent test, acking
every ticket whose ``submit()`` RETURNED to a side file (write + flush +
fsync, so the ack record is durable before the parent can read it). The
chaos site hard-kills the process with ``os._exit(137)`` — no atexit, no
finally — and the parent then replays the journal and asserts the
per-fsync-policy loss bound over exactly the acked set.

Usage: ``python _wal_crash_driver.py WAL_PATH FSYNC_POLICY ACK_PATH N
[pool|settled]``

With the optional ``pool`` mode the driver exercises the resident-
session handle lifecycle instead of the ticket path: create N pool
sessions (ack ``C <sid>`` once create returns), two rounds of 2-step
resident steps per session (ack ``S <sid> 2``), one snapshot (``N
<sid>``), one evict (``E <sid>``). The pool chaos sites
(``post-create``/``post-step``/``post-snapshot``/``post-evict``) fire
AFTER the frame is journaled and BEFORE the pool acts, so an acked op is
always durable under ``every-record`` and the parent can assert the
resumed pool matches the acked ledger exactly (plus at most one
journaled-but-unacked op — the at-least-once edge).

The ``settled`` mode is the pool mode with session p0 seeded as a STILL
LIFE (a block) among active random boards, and enough 2-step rounds for
the settled-skip fast path to engage (p0's dispatches stop once its
fixed point is proven). The WAL's STEP frames stay authoritative:
replay re-applies every journaled step and RE-PROVES settledness, so
the parent asserts the resumed p0 snapshot is bit-identical to the
oracle at the acked step count even though some of those steps were
never dispatched by the pre-kill process.

The MEMBERSHIP modes run a 3-worker in-process ``Fleet`` instead of
one daemon — here WAL_PATH is a *directory* (one journal per worker).
``rejoin`` wedges worker 0, creates claimable sessions (names hashing
to worker 0, one distinct shape each so every one is its own slab
group), then calls ``rejoin_worker(0)`` — the ``post-rejoin`` chaos
site fires between the handshake halves (dest CREATE+STEP journaled,
source EVICT not). ``drain`` parks a whole pending bucket plus
resident sessions on worker 0 and calls ``drain_worker(0)`` — the
``mid-drain`` site fires between the destination adopt and the
source's ``re-homed`` SHED. Both sites are duplication-not-loss edges:
the parent replays every worker journal and asserts each acked
session appears in >=1 journal (bit-equal create board + step total
wherever it appears twice) and the ticket count over all journals is
bounded by ``acked <= total <= acked + one bucket``.

Exits 0 after a clean drain (printing a one-line JSON summary); a
planned crash never reaches that code.
"""

import json
import os
import sys

# The sitecustomize in this environment points jax at the TPU plugin;
# this driver is CPU-only host-side work and must never touch the chip.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _fleet_mode(wal_dir: str, fsync: str, rec, n: int, mode: str) -> int:
    """The membership crash modes: a 3-worker fleet, worker 0 the
    victim. Every ack below is durable BEFORE the fleet call that can
    crash — the parent's loss bound is judged over exactly this set."""
    import time as _time

    from mpi_and_open_mp_tpu.serve import Fleet, ServePolicy
    from mpi_and_open_mp_tpu.serve.router import ConsistentHashRing

    fleet = Fleet(3, ServePolicy(max_batch=4, max_wait_s=0.0),
                  wal_dir=wal_dir, wal_fsync=fsync,
                  heartbeat_interval_s=0.005, heartbeat_miss_k=2,
                  steal=False)
    # The full 3-worker ring (workers 0..2 all present) — session names
    # are picked by where they hash once worker 0 is BACK on the ring.
    ring3 = ConsistentHashRing(range(3))
    rng = np.random.default_rng(11)

    def names_for(worker: int, count: int, prefix: str) -> list[str]:
        out, j = [], 0
        while len(out) < count:
            name = f"{prefix}{j:03d}"
            if ring3.lookup(name) == worker:
                out.append(name)
            j += 1
        return out

    if mode == "rejoin":
        for i in range(n):
            board = (rng.random((12, 12)) < 0.3).astype(np.uint8)
            fleet.create_session(f"p{i}", board)
            rec(f"C p{i}")
            fleet.step_session(f"p{i}", 2)
            rec(f"S p{i} 2")
        fleet.serve_until_drained(drain=True)
        fleet.wedge(0)
        deadline = _time.monotonic() + 10.0
        while 0 not in fleet.router.wedged_workers:
            _time.sleep(0.02)
            fleet.pump()
            if _time.monotonic() > deadline:
                raise RuntimeError("worker 0 never wedged")
        # Sessions the rejoiner will claim back: names hashing to
        # worker 0 (they land on survivors now — 0 is off the ring),
        # each with a DISTINCT shape so each is its own slab group and
        # the whole-group rule moves it alone.
        for k, name in enumerate(names_for(0, 3, "q")):
            shape = (12 + 2 * (k + 1), 12)
            board = (rng.random(shape) < 0.3).astype(np.uint8)
            fleet.create_session(name, board)
            rec(f"C {name}")
            fleet.step_session(name, 2)
            rec(f"S {name} 2")
        fleet.serve_until_drained(drain=True)
        # The handshake: post-rejoin fires between the claim's halves.
        claimed = fleet.rejoin_worker(0)
        fleet.serve_until_drained(drain=True)
        books = fleet.router.books()
        print(json.dumps({"claimed": claimed,
                          "balanced": books["balanced"],
                          "rejoins": books["rejoins"]}))
        return 0

    if mode == "drain":
        # One whole pending bucket parked at worker 0: same shape/steps,
        # session keys hashing to 0. Acked at submit (journaled ADMIT).
        for name in names_for(0, n, "t"):
            board = (rng.random((12, 12)) < 0.3).astype(np.uint8)
            fleet.submit(board, 2, session=name)
            rec(f"T {name}")
        # Resident sessions on worker 0 with journaled-but-undispatched
        # steps — the drain must finish these locally before the pool
        # migrates.
        for k, name in enumerate(names_for(0, 2, "q")):
            shape = (12 + 2 * (k + 1), 12)
            board = (rng.random(shape) < 0.3).astype(np.uint8)
            fleet.create_session(name, board)
            rec(f"C {name}")
            fleet.step_session(name, 2)
            rec(f"S {name} 2")
        # The handoff: mid-drain fires between the destination adopt
        # and the source's re-homed SHED.
        stats = fleet.drain_worker(0)
        fleet.serve_until_drained(drain=True)
        books = fleet.router.books()
        print(json.dumps({"tickets_moved": stats["tickets_moved"],
                          "sessions_moved": stats["sessions_moved"],
                          "balanced": books["balanced"],
                          "drains": books["drains"]}))
        return 0

    raise ValueError(f"unknown fleet mode {mode!r}")


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from mpi_and_open_mp_tpu.serve import ServePolicy, ServingDaemon

    wal_path, fsync, ack_path = sys.argv[1], sys.argv[2], sys.argv[3]
    n = int(sys.argv[4])
    mode = sys.argv[5] if len(sys.argv) > 5 else ""
    pool_mode = mode in ("pool", "settled")
    if mode in ("rejoin", "drain"):
        with open(ack_path, "ab") as ack:
            def rec(line: str) -> None:
                ack.write((line + "\n").encode())
                ack.flush()
                os.fsync(ack.fileno())

            return _fleet_mode(wal_path, fsync, rec, n, mode)
    policy = ServePolicy(max_batch=4, max_wait_s=0.0)
    daemon = ServingDaemon(policy, wal_path=wal_path, wal_fsync=fsync)
    rng = np.random.default_rng(7)
    with open(ack_path, "ab") as ack:
        def rec(line: str) -> None:
            ack.write((line + "\n").encode())
            ack.flush()
            os.fsync(ack.fileno())

        if pool_mode:
            for i in range(n):
                board = (rng.random((12, 12)) < 0.3).astype(np.uint8)
                if mode == "settled" and i == 0:
                    # p0 is a still life: its dispatches stop once the
                    # pool proves the per-lane fixed point.
                    board = np.zeros((12, 12), np.uint8)
                    board[5:7, 5:7] = 1
                daemon.create_session(f"p{i}", board)
                rec(f"C p{i}")
            # settled mode runs extra rounds: the first round proves
            # p0's fixed point, later rounds exercise the skip path
            # with the chaos site still armed.
            for _ in range(5 if mode == "settled" else 2):
                for i in range(n):
                    daemon.step_session(f"p{i}", 2)
                    rec(f"S p{i} 2")
            daemon.snapshot_session("p0")
            rec("N p0")
            daemon.evict_session(f"p{n - 1}")
            rec(f"E p{n - 1}")
            daemon._wal.sync()
            s = daemon.summary()
            daemon._wal.close()
            print(json.dumps({"sessions": s["pool_sessions"]}))
            return 0

        for i in range(n):
            board = (rng.random((12, 12)) < 0.3).astype(np.uint8)
            t = daemon.submit(board, 2)
            rec(str(t.id))
    daemon.serve()
    s = daemon.summary()
    daemon._wal.close()
    print(json.dumps({"resolved": s["resolved"], "shed": s["shed"]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
