"""Sharded serving fleet: consistent-hash router, re-home ladder, books.

The fleet contracts under test: the ring is deterministic ACROSS
processes (sha256, never Python's salted ``hash``) and movement under
resize is structurally bounded — removing a worker re-homes only its own
sessions, adding one claims only the keys landing on its points; a
wedged worker (missed heartbeats) is declared by the router, its WAL
replayed, and every pending ticket re-homed to survivors with the fleet
books balanced and every re-homed result oracle-exact; a hot shard sheds
at its own door while cold shards keep admitting, and the fleet-wide
rolled-up door refuses what no combination of workers could absorb; work
stealing moves whole buckets only; and the ``kill_worker=<i>:<k>`` chaos
token arms in exactly one worker's process at exactly one dispatch.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from conftest import oracle_n
from mpi_and_open_mp_tpu.robust import chaos
from mpi_and_open_mp_tpu.serve import (
    ConsistentHashRing,
    Fleet,
    ServePolicy,
    TicketWAL,
)
from mpi_and_open_mp_tpu.serve import policy as policy_mod
from mpi_and_open_mp_tpu.serve import wal as wal_mod
from mpi_and_open_mp_tpu.serve.daemon import _parse_backoff
from mpi_and_open_mp_tpu.serve.queue import DONE, PENDING, SHED
from mpi_and_open_mp_tpu.serve.router import affinity_key

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.reset()
    yield
    chaos.reset()


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def sleep(self, s: float) -> None:
        self.t += s


def _fleet(n, policy, clk=None, **kw) -> tuple[Fleet, FakeClock]:
    clk = clk or FakeClock()
    return Fleet(n, policy, clock=clk, sleep=clk.sleep, **kw), clk


def _session_for(fleet: Fleet, worker: int) -> str:
    """A session key whose affinity worker is ``worker``."""
    for i in range(10_000):
        s = f"probe-{i}"
        if fleet.router.target_for(s) == worker:
            return s
    raise AssertionError(f"no session found for worker {worker}")


# ------------------------------------------------------------------- ring


def test_ring_cross_process_determinism():
    """The same (workers, vnodes, seed) ring shards identically in a
    fresh interpreter with a DIFFERENT hash salt — the property the
    fleet CLI leans on when parent and workers each rebuild the ring."""
    keys = [f"s{i:03d}" for i in range(32)]
    ring = ConsistentHashRing(range(5), vnodes=32, seed=9)
    local = [ring.lookup(k) for k in keys]
    code = (
        "import json\n"
        "from mpi_and_open_mp_tpu.serve.router import ConsistentHashRing\n"
        "r = ConsistentHashRing(range(5), vnodes=32, seed=9)\n"
        "print(json.dumps([r.lookup(f's{i:03d}') for i in range(32)]))\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONHASHSEED="271828")
    out = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                         capture_output=True, text=True, env=env)
    assert out.returncode == 0, out.stderr[-800:]
    assert json.loads(out.stdout.strip().splitlines()[-1]) == local


def test_ring_removal_moves_only_the_victims_keys():
    ring = ConsistentHashRing(range(4), vnodes=64, seed=7)
    keys = [f"sess-{i}" for i in range(500)]
    before = {k: ring.lookup(k) for k in keys}
    ring.remove_worker(2)
    for k in keys:
        after = ring.lookup(k)
        if before[k] != 2:
            assert after == before[k]  # untouched — structural bound
        else:
            assert after != 2


def test_ring_addition_claims_only_its_own_points():
    ring = ConsistentHashRing(range(3), vnodes=64, seed=1)
    keys = [f"sess-{i}" for i in range(1000)]
    before = {k: ring.lookup(k) for k in keys}
    ring.add_worker(3)
    moved = [k for k in keys if ring.lookup(k) != before[k]]
    assert all(ring.lookup(k) == 3 for k in moved)
    # Expected movement is keys/(N+1) = 25%; 64 vnodes keep the
    # realized share close (generous statistical bound, seed-pinned).
    assert 0 < len(moved) / len(keys) < 0.45


def test_ring_empty_lookup_raises_and_affinity_key_fallback():
    ring = ConsistentHashRing((), vnodes=8)
    with pytest.raises(RuntimeError, match="no live workers"):
        ring.lookup("s")
    assert affinity_key("sess-a", 7) == "sess-a"
    assert affinity_key(None, 7) == "ticket/7"
    assert affinity_key(None) == "ticket/0"


# ----------------------------------------------------------------- rollup


def test_rollup_depth_adds_per_request_knobs_take_min():
    a = ServePolicy(max_batch=4, max_depth=10, max_padding_frac=0.5,
                    max_wait_s=0.1, request_timeout_s=5.0, max_retries=3,
                    backoff_base_s=0.1, backoff_cap_s=2.0)
    b = ServePolicy(max_batch=8, max_depth=6, max_padding_frac=0.25,
                    max_wait_s=0.2, request_timeout_s=9.0, max_retries=1,
                    backoff_base_s=0.05, backoff_cap_s=4.0)
    r = policy_mod.rollup([a, b])
    assert r.max_depth == 16  # capacity ADDS across the fleet
    assert r.max_batch == 8
    assert r.max_padding_frac == 0.25  # most conservative worker wins
    assert r.max_wait_s == 0.1
    assert r.request_timeout_s == 5.0
    assert r.max_retries == 1
    assert r.backoff_base_s == 0.05 and r.backoff_cap_s == 2.0


# ----------------------------------------------------- wedge + re-home


def test_fleet_wedge_rehomes_from_wal_books_balance(tmp_path, make_board):
    """Kill drill against the journal: halt the busiest worker, let the
    heartbeat ladder declare it, and require zero acked loss — every
    ticket resolves (oracle-exact) or sheds explicitly, with the
    re-homed sheds pairing 1:1 against adoptions."""
    pol = ServePolicy(max_batch=4, max_wait_s=0.05)
    f, clk = _fleet(3, pol, wal_dir=str(tmp_path), steal=False,
                    heartbeat_interval_s=0.02)
    for i in range(18):
        f.submit(make_board(16, 16), (i % 3) + 1, session=f"s{i % 6}")
    victim = max(f.handles, key=lambda h: h.daemon.queue.depth()).index
    depth_before = f.handles[victim].daemon.queue.depth()
    assert depth_before > 0
    f.wedge(victim)
    f.serve_until_drained()
    s = f.summary()
    assert s["balanced"] and s["pending"] == 0
    assert s["wedged"] == [victim]
    assert s["rehomed"] == depth_before == s["rehomed_resolved"]
    assert s["resolved"] == 18 and s["shed"] == 0
    # The victim's journal is idempotent: a second replay finds nothing
    # pending (the re-homed sheds were framed before adoption).
    rep = wal_mod.replay(str(tmp_path / f"worker{victim}.wal"))
    assert rep.pending == []
    # Parity over every resolved ticket, re-homed included.
    for t in f.resolved_tickets():
        np.testing.assert_array_equal(
            t.result, oracle_n(t.board, t.steps),
            err_msg=f"ticket {t.id} lost parity across the re-home")


def test_fleet_wedge_without_journal_rehomes_from_live_queue(make_board):
    pol = ServePolicy(max_batch=4, max_wait_s=0.05)
    f, _ = _fleet(3, pol, steal=False, heartbeat_interval_s=0.02)
    for i in range(12):
        f.submit(make_board(16, 16), 2, session=f"s{i % 4}")
    victim = max(f.handles, key=lambda h: h.daemon.queue.depth()).index
    f.wedge(victim)
    f.serve_until_drained()
    s = f.summary()
    assert s["balanced"] and s["resolved"] == 12 and s["pending"] == 0


def test_slow_pump_round_does_not_false_wedge(make_board):
    """Regression: one worker's dispatch taking far longer than the
    heartbeat horizon (a first-dispatch compile) must not wedge the
    workers that pumped earlier in the same round — liveness is judged
    on the shared post-round beat, not mid-round stamps."""
    pol = ServePolicy(max_batch=4, max_wait_s=0.0)
    f, clk = _fleet(3, pol, steal=False, heartbeat_interval_s=0.02)
    slow = f.handles[1].daemon
    orig = slow.pump

    def glacial_pump(now=None, **kw):
        clk.sleep(5.0)  # ~80x the wedge horizon
        return orig(clk(), **kw)

    slow.pump = glacial_pump
    for i in range(6):
        f.submit(make_board(16, 16), 2, session=f"s{i}")
    f.pump()
    assert not any(h.wedged for h in f.handles)
    # ...while a genuinely dead worker is still declared.
    f.wedge(0)
    for _ in range(6):
        f.pump()
        clk.sleep(0.02)
    assert f.handles[0].wedged and not f.handles[2].wedged


# ------------------------------------------------- admission + stealing


def test_hot_shard_sheds_while_cold_shard_admits(make_board):
    pol = ServePolicy(max_batch=4, max_depth=2, max_wait_s=100.0)
    f, _ = _fleet(2, pol, steal=False)
    hot = _session_for(f, 0)
    cold = _session_for(f, 1)
    b = make_board(16, 16)
    assert f.submit(b, 2, session=hot).state == PENDING
    assert f.submit(b, 2, session=hot).state == PENDING
    t = f.submit(b, 2, session=hot)  # worker 0 at its local depth cap
    assert t.state == SHED and t.reason == policy_mod.SHED_DEPTH
    assert t.id >= 0  # the WORKER door shed it, not the router door
    assert f.submit(b, 2, session=cold).state == PENDING  # cold admits
    assert f.submit(b, 2, session=cold).state == PENDING
    # Fleet-wide rolled-up depth (2+2) is now exhausted: the ROUTER
    # door refuses before any worker sees the request.
    t = f.submit(b, 2, session=cold)
    assert t.state == SHED and t.id < 0
    assert f.router.door_shed.get(policy_mod.SHED_DEPTH) == 1
    assert f.router.books()["balanced"]


def test_steal_moves_oldest_whole_bucket_to_idle_worker(make_board):
    pol = ServePolicy(max_batch=4, max_wait_s=100.0)
    f, clk = _fleet(2, pol, steal=False)
    donor_sess = _session_for(f, 0)
    for _ in range(3):
        f.submit(make_board(16, 16), 2, session=donor_sess)
    for _ in range(2):
        f.submit(make_board(24, 24), 2, session=donor_sess)
    assert [h.daemon.queue.depth() for h in f.handles] == [5, 0]
    moved = f.router.steal(clk())
    # The (16,16) bucket holds the oldest lead ticket — it moves WHOLE;
    # the donor keeps the other bucket.
    assert moved == 3
    assert [h.daemon.queue.depth() for h in f.handles] == [2, 3]
    assert f.router.steals == 1
    assert f.router.steal(clk()) == 0  # nobody idle now
    f.serve_until_drained(drain=True)
    s = f.summary()
    assert s["balanced"] and s["resolved"] == 5


def test_steal_never_splits_or_empties_a_single_bucket(make_board):
    pol = ServePolicy(max_batch=4, max_wait_s=100.0)
    f, clk = _fleet(2, pol, steal=False)
    donor_sess = _session_for(f, 0)
    for _ in range(3):
        f.submit(make_board(16, 16), 2, session=donor_sess)
    # One bucket only: stealing it would just move the wait.
    assert f.router.steal(clk()) == 0
    assert [h.daemon.queue.depth() for h in f.handles] == [3, 0]


# -------------------------------------------------------------- chaos


def test_kill_worker_token_parse_and_validation():
    plan = chaos.FaultPlan.parse("kill_worker=2:3")
    assert plan.kill_worker_idx == 2 and plan.kill_worker_at == 3
    assert chaos.FaultPlan.parse("kill_worker=1").kill_worker_at == 1
    with pytest.raises(ValueError):
        chaos.FaultPlan.parse("kill_worker=-1:2")
    with pytest.raises(ValueError):
        chaos.FaultPlan.parse("kill_worker=0:0")


def test_kill_worker_arms_only_matching_index_at_kth_hit(monkeypatch):
    monkeypatch.setenv("MOMP_CHAOS", "kill_worker=1:2")
    chaos.reset()
    assert not chaos.kill_worker_armed(0)  # wrong worker — never counts
    assert not chaos.kill_worker_armed(None)  # not a fleet worker
    assert not chaos.kill_worker_armed(1)  # dispatch 1 of 2
    assert chaos.kill_worker_armed(1)  # dispatch 2 — fire
    assert not chaos.kill_worker_armed(1)  # one-shot


# ------------------------------------------------------- WAL + CLI knobs


def test_wal_admit_carries_session_through_replay(tmp_path, make_board):
    path = str(tmp_path / "w.wal")
    w = TicketWAL(path)
    b = make_board(8, 8)
    w.admit(0, b, 3, session="sess-a")
    w.admit(1, b, 2)
    w.close()
    rep = wal_mod.replay(path)
    assert [e["session"] for e in rep.pending] == ["sess-a", None]
    # ...and survives a compaction (the snapshot must not forget it).
    w = TicketWAL(path)
    w.compact(rep.pending)
    w.close()
    rep2 = wal_mod.replay(path)
    assert [e["session"] for e in rep2.pending] == ["sess-a", None]


def test_parse_backoff_spec():
    assert _parse_backoff("0.1") == (0.1, 1.0, 0.5)
    assert _parse_backoff("0.1:2.0") == (0.1, 2.0, 0.5)
    assert _parse_backoff("0.1:2.0:0.0") == (0.1, 2.0, 0.0)
    with pytest.raises(ValueError):
        _parse_backoff("1:2:3:4")


def test_daemon_cli_exposes_padding_and_backoff_knobs():
    from mpi_and_open_mp_tpu.serve.daemon import build_parser

    args = build_parser().parse_args(
        ["--requests", "0", "--max-padding-frac", "0.2",
         "--backoff", "0.01:0.5:0.0"])
    assert args.max_padding_frac == 0.2
    assert _parse_backoff(args.backoff) == (0.01, 0.5, 0.0)


# ----------------------------------------------------------- guardrails


def test_fleet_and_router_validation(make_board):
    with pytest.raises(ValueError, match="n_workers"):
        Fleet(0)
    with pytest.raises(ValueError, match="policies"):
        Fleet(2, policies=[ServePolicy()])
    f, clk = _fleet(2, ServePolicy(max_batch=4, max_wait_s=100.0))
    f.wedge(0)
    # check_health never wedges the LAST live worker — re-homing needs
    # a survivor, and a one-worker fleet degraded is better than none.
    clk.sleep(10.0)
    assert f.router.check_health(clk()) == [0]
    clk.sleep(10.0)
    assert f.router.check_health(clk()) == []
    assert not f.handles[1].wedged


def test_sentinel_polarity_for_fleet_fields():
    sys.path.insert(0, os.path.join(REPO, "analysis"))
    import regression_sentinel as rs

    for field in ("fleet_requests_per_sec", "fleet_p99_latency_s",
                  "fleet_kill_recovery_s"):
        assert field in rs.WATCH_FIELDS
    assert rs.direction_for("fleet_requests_per_sec") == "higher"
    assert rs.direction_for("fleet_p99_latency_s") == "lower"
    assert rs.direction_for("fleet_kill_recovery_s") == "lower"


# ------------------------------------------------- live join + pool re-home


def test_add_worker_rerolls_admission_live(make_board):
    """Regression (the satellite's target): joining a worker mid-burst
    must widen the router door's rolled-up depth budget IMMEDIATELY —
    before the fix, the rollup was computed once at construction, so a
    grown fleet kept shedding at yesterday's capacity."""
    from mpi_and_open_mp_tpu.serve import ServingDaemon, WorkerHandle

    pol = ServePolicy(max_batch=4, max_depth=2, max_wait_s=100.0)
    f, clk = _fleet(2, pol, steal=False)
    b = make_board(16, 16)
    # Fill the 2-worker rolled depth (2+2) exactly.
    admitted = 0
    i = 0
    while admitted < 4:
        t = f.submit(b, 2, session=f"fill-{i}")
        admitted += t.state == PENDING
        i += 1
    t = f.submit(b, 2, session="overflow")
    assert t.state == SHED and t.id < 0  # the ROUTER door, pre-worker
    door_shed_before = f.router.door_shed.get(policy_mod.SHED_DEPTH)

    d = ServingDaemon(pol, worker_index=2, clock=clk, sleep=clk.sleep)
    h = WorkerHandle(index=2, daemon=d, last_beat=clk())
    f.router.add_worker(h)
    f.handles.append(h)
    # The door's budget is now 6: capacity that joined admits at once.
    sess = _session_for(f, 2)  # lands on the new worker: no local cap
    assert f.submit(b, 2, session=sess).state == PENDING
    assert f.router.door_shed.get(policy_mod.SHED_DEPTH) == door_shed_before
    with pytest.raises(ValueError, match="already in the fleet"):
        f.router.add_worker(h)
    f.serve_until_drained()
    assert f.summary()["balanced"]


def test_fleet_wedge_rehomes_pool_sessions(tmp_path, make_board):
    """A wedged worker's RESIDENT sessions survive it: the router
    replays the victim's journal, adopts each session at its new ring
    home (one board crosses the wire; the destination device replays
    the advance), closes the victim's books with EVICT frames, and
    every re-homed snapshot stays bit-identical to the oracle."""
    pol = ServePolicy(max_batch=4, max_wait_s=0.0)
    f, clk = _fleet(3, pol, wal_dir=str(tmp_path), steal=False,
                    heartbeat_interval_s=0.02)
    boards = {f"sess-{i}": make_board(16, 16) for i in range(12)}
    for sid, b in boards.items():
        f.create_session(sid, b)
    tickets = [f.step_session(sid, 2) for sid in boards]
    f.serve_until_drained()
    assert all(t.state == DONE for t in tickets)

    victim = f.router.target_for("sess-0")
    moved = [sid for sid in boards if f.router.target_for(sid) == victim]
    f.wedge(victim)
    for _ in range(6):
        f.pump()
        clk.sleep(0.02)
    assert f.handles[victim].wedged
    assert f.router.pool_rehomed == len(moved)
    for sid, b in boards.items():
        assert f.router.target_for(sid) != victim
        np.testing.assert_array_equal(
            f.snapshot_session(sid), oracle_n(b, 2),
            err_msg=f"session {sid} lost parity across the re-home")
    # The victim's journal closed its books: a second replay finds no
    # resident sessions (EVICT framed per adoption), so a recovery
    # worker can never double-adopt.
    rep = wal_mod.replay(str(tmp_path / f"worker{victim}.wal"))
    assert rep.pool_sessions == {}
    # Life goes on at the new homes.
    t = f.step_session("sess-0", 3)
    f.serve_until_drained()
    assert t.state == DONE
    np.testing.assert_array_equal(
        f.snapshot_session("sess-0"), oracle_n(boards["sess-0"], 5))


# ----------------------------------------------- REJOIN + drain + elasticity


def _claimable_sessions(fleet, worker, count, make_board):
    """Session names whose FULL-ring affinity is ``worker``, each a
    DISTINCT shape (its own slab group — the whole-group rule moves it
    alone at rejoin time)."""
    from mpi_and_open_mp_tpu.serve.router import ConsistentHashRing

    full = ConsistentHashRing(sorted({h.index for h in fleet.handles}))
    out, i = {}, 0
    while len(out) < count:
        name = f"claim-{i}"
        i += 1
        if full.lookup(name) == worker:
            # Never 16x16: the claimable sessions must not join the
            # survivors' existing 16x16 slab group (whose lead is the
            # survivor's own session and would pin the whole group).
            shape = 18 + 2 * len(out)
            out[name] = make_board(shape, 16)
    return out


def test_rejoin_reenters_ring_and_claims_bit_exact(tmp_path, make_board):
    """The full REJOIN ladder: wedge → recover → rejoin under the old
    index. Bounded re-entry (victim-affine keys route to it again),
    bit-exact claims (whole slab groups whose lead hashes to the
    rejoiner migrate back, snapshots oracle-identical), warming handle,
    and books that balance across BOTH membership changes."""
    pol = ServePolicy(max_batch=4, max_wait_s=0.0)
    f, clk = _fleet(3, pol, wal_dir=str(tmp_path), steal=False,
                    heartbeat_interval_s=0.02)
    boards = {f"sess-{i}": make_board(16, 16) for i in range(9)}
    for sid, b in boards.items():
        f.create_session(sid, b)
    for sid in boards:
        f.step_session(sid, 2)
    f.serve_until_drained()

    victim = f.router.target_for("sess-0")
    f.wedge(victim)
    for _ in range(6):
        f.pump()
        clk.sleep(0.02)
    assert f.handles[victim].wedged

    # Sessions created while the victim is out, whose affinity on the
    # FULL ring is the victim: the rejoin claim pass must move exactly
    # these back (each its own slab group via a distinct shape).
    claim = _claimable_sessions(f, victim, 3, make_board)
    for sid, b in claim.items():
        f.create_session(sid, b)
        f.step_session(sid, 2)
    f.serve_until_drained()

    with pytest.raises(ValueError, match="is live"):
        f.rejoin_worker((victim + 1) % 3)
    claimed = f.rejoin_worker(victim)
    fresh = next(h for h in f.handles if h.index == victim)
    assert fresh.warming and not fresh.wedged
    assert claimed >= len(claim)
    assert f.router.rejoins == 1
    # Bounded re-entry: the old ring points are back, so victim-affine
    # keys route to the rejoiner again.
    assert f.router.target_for("sess-0") == victim
    # Claims are bit-exact at the rejoiner.
    for sid, b in claim.items():
        assert f.router._home_worker(sid).index == victim
        np.testing.assert_array_equal(
            f.snapshot_session(sid), oracle_n(b, 2),
            err_msg=f"claimed session {sid} lost parity across rejoin")
    # The fleet serves through the rejoiner again, books balanced over
    # the retired lifetime + the new one.
    t = f.step_session("sess-0", 3)
    f.serve_until_drained()
    assert t.state == DONE
    s = f.summary()
    assert s["balanced"] and s["rejoins"] == 1
    assert fresh.warming is False  # first completed pump cleared it


def test_rejoin_warming_worker_not_false_wedged(tmp_path, make_board):
    """The satellite fix: a rejoined worker still deserializing its AOT
    cache (alive, not yet pumping) must be covered by the shared
    post-round beat — before the fix its stale stamp would re-wedge it
    mid-warmup after one horizon."""
    pol = ServePolicy(max_batch=4, max_wait_s=0.0)
    f, clk = _fleet(3, pol, wal_dir=str(tmp_path), steal=False,
                    heartbeat_interval_s=0.02)
    for i in range(6):
        f.submit(make_board(16, 16), 2, session=f"s{i}")
    victim = 0
    f.wedge(victim)
    f.serve_until_drained()
    assert f.handles[victim].wedged

    f.rejoin_worker(victim)
    fresh = next(h for h in f.handles if h.index == victim)
    assert fresh.warming
    # Simulate a long warmup: the rejoiner cannot pump yet, and many
    # wedge horizons pass under live traffic.
    fresh.halted = True
    for i in range(8):
        f.submit(make_board(16, 16), 2, session=f"w{i}")
        f.pump()
        clk.sleep(0.05)  # 2.5 horizons per round
    assert not fresh.wedged, "warming worker was false-wedged"
    # Warmup ends: it pumps, clears the flag, and serves.
    fresh.halted = False
    f.serve_until_drained()
    assert not fresh.warming and not fresh.wedged
    assert f.summary()["balanced"]
    # A worker that is NOT warming still wedges on the same staleness —
    # the cover is for warmup, not amnesty.
    f.wedge(2)
    for _ in range(6):
        f.pump()
        clk.sleep(0.05)
    assert f.handles[2].wedged


def test_steal_in_transit_counted_once_at_door(make_board):
    """The satellite fix: a stolen bucket between release and adopt
    belongs to the FLEET (the in-transit ledger) and to neither queue —
    the door must count it exactly once and the books must balance
    mid-move."""
    pol = ServePolicy(max_batch=4, max_depth=3, max_wait_s=100.0)
    f, clk = _fleet(2, pol, steal=False)
    donor = _session_for(f, 0)
    b16, b24 = make_board(16, 16), make_board(24, 24)
    for _ in range(2):
        f.submit(b16, 2, session=donor)
    f.submit(b24, 2, session=donor)

    moved = f.router.steal(clk(), defer=True)
    assert moved == 2  # the (16,16) bucket parked, not yet adopted
    assert f.router.in_transit_depth() == 2
    assert [h.daemon.queue.depth() for h in f.handles] == [1, 0]
    assert f.pending() == 3  # parked work is still pending work
    books = f.router.books()
    assert books["in_transit"] == 2 and books["balanced"], books

    # The door counts the parked bucket: fleet-wide depth is 3 of a
    # rolled 6, so exactly 3 more admissions fit.
    cold = _session_for(f, 1)
    for _ in range(3):
        assert f.submit(b16, 2, session=cold).state == PENDING
    # The 7th submit targets the DONOR (local depth 1 of 3 — its own
    # door would admit): only the fleet door counting the 2 parked
    # tickets sees depth 6 of the rolled 6 and sheds.
    t = f.submit(b16, 2, session=donor)
    assert t.state == SHED and t.id < 0, (
        "door forgot the in-transit bucket")

    delivered = f.router.deliver_in_transit(clk())
    assert delivered == 2 and f.router.in_transit_depth() == 0
    assert f.router.steals == 1
    f.serve_until_drained(drain=True)
    s = f.summary()
    assert s["balanced"] and s["resolved"] == 6 and s["in_transit"] == 0


def test_steal_in_transit_reroutes_if_thief_dies(make_board):
    """A bucket parked for a thief that wedges mid-transfer re-routes
    by ring affinity instead of evaporating with its recipient."""
    pol = ServePolicy(max_batch=4, max_wait_s=100.0)
    f, clk = _fleet(3, pol, steal=False, heartbeat_interval_s=0.02)
    donor = _session_for(f, 0)
    for _ in range(2):
        f.submit(make_board(16, 16), 2, session=donor)
    f.submit(make_board(24, 24), 2, session=donor)
    moved = f.router.steal(clk(), defer=True)
    assert moved == 2
    thief = f.router._in_transit[0]["thief"]
    f.router.declare_wedged(thief, clk())
    assert f.handles[thief].wedged
    assert f.router.deliver_in_transit(clk()) == 2
    f.serve_until_drained(drain=True)
    s = f.summary()
    assert s["balanced"] and s["resolved"] == 3 and s["pending"] == 0


def test_drain_worker_moves_whole_buckets_zero_loss(tmp_path, make_board):
    """Graceful drain: cordoned at the door, board buckets migrate
    WHOLE (one destination per bucket), resident-step tickets finish
    locally, slab groups move unsplit, and the compacted journal is the
    handoff receipt — a replay finds nothing live. Zero acked loss,
    oracle parity end to end."""
    pol = ServePolicy(max_batch=4, max_wait_s=100.0)
    f, clk = _fleet(3, pol, wal_dir=str(tmp_path), steal=False)
    victim = 0
    vsess = _session_for(f, victim)
    boards = [make_board(16, 16) for _ in range(3)]
    tickets = [f.submit(b, 2, session=vsess) for b in boards]
    assert all(t.state == PENDING for t in tickets)
    assert f.handles[victim].daemon.queue.depth() == 3
    # A resident session on the victim with a journaled, undispatched
    # step the drain must flush locally before the pool moves.
    sb = make_board(16, 16)
    f.create_session(vsess, sb)
    st = f.step_session(vsess, 2)

    stats = f.drain_worker(victim)
    assert f.handles[victim].drained and f.handles[victim].cordoned
    assert stats["tickets_moved"] == 3 and stats["sessions_moved"] == 1
    assert st.state == DONE  # finished locally, never migrated
    # Whole-bucket rule: all three tickets landed at ONE survivor.
    depths = [h.daemon.queue.depth() for h in f.handles
              if h.index != victim]
    assert sorted(depths) == [0, 3]
    # Cordoned at the router door: nothing routes to it anymore.
    assert all(f.router.target_for(f"probe-{i}") != victim
               for i in range(50))
    # The handoff receipt: the drained journal replays to empty.
    rep = wal_mod.replay(str(tmp_path / f"worker{victim}.wal"))
    assert rep.pending == [] and rep.pool_sessions == {}

    f.serve_until_drained(drain=True)
    s = f.summary()
    assert s["balanced"] and s["drains"] == 1
    assert s["drained"] == [victim]
    assert s["resolved"] == 4 and s["pending"] == 0  # zero acked loss
    for t in f.resolved_tickets():
        if t.board is not None:
            np.testing.assert_array_equal(
                t.result, oracle_n(t.board, t.steps),
                err_msg=f"ticket {t.id} lost parity across the drain")
    np.testing.assert_array_equal(f.snapshot_session(vsess),
                                  oracle_n(sb, 2))
    with pytest.raises(ValueError, match="already left"):
        f.drain_worker(victim)


def test_drain_last_survivor_refused():
    f, _clk = _fleet(2, ServePolicy(max_batch=4, max_wait_s=0.0))
    f.drain_worker(0)
    with pytest.raises(RuntimeError, match="no survivors"):
        f.drain_worker(1)


def test_autoscale_adds_on_breach_drains_on_surplus(make_board):
    """The SLO loop end to end: sustained p99 breach grows the fleet
    (after breach_k consecutive breaches, never during cooldown),
    sustained surplus drains it back — and the action log shows two
    clean decisions, not a flap."""
    elastic = policy_mod.ElasticityPolicy(
        slo_p99_s=0.01, min_workers=2, max_workers=3,
        breach_k=2, surplus_k=3, cooldown_k=2)
    pol = ServePolicy(max_batch=4, max_wait_s=0.0)
    f, clk = _fleet(2, pol, steal=False, elasticity=elastic,
                    elastic_window_s=5.0)
    assert f.controller is not None

    # Breach: every resolved ticket waits ~0.05s >> the 0.01s SLO.
    rounds_before = len(f.handles)
    for i in range(4):
        f.submit(make_board(16, 16), 2, session=f"s{i}")
        clk.sleep(0.05)
        f.pump()
    assert len(f.handles) == rounds_before + 1 == 3
    assert f.controller.actions == [policy_mod.SCALE_ADD]
    new = f.handles[-1]
    assert new.index == 2 and not new.wedged  # next free index
    # ... and max_workers caps further growth even under breach.
    for i in range(6):
        f.submit(make_board(16, 16), 2, session=f"b{i}")
        clk.sleep(0.05)
        f.pump()
    assert len(f.handles) == 3

    # Surplus: quiet fleet, p99 window empties, depth zero → after
    # cooldown + surplus_k the shallowest worker drains.
    f.serve_until_drained(drain=True)
    clk.sleep(10.0)  # age the window out
    for _ in range(8):
        f.pump()
        clk.sleep(0.01)
    assert f.controller.actions == [policy_mod.SCALE_ADD,
                                    policy_mod.SCALE_DRAIN]
    assert len(f.router.live_workers()) == 2  # back at min capacity
    assert f.summary()["balanced"]
