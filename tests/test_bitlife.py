"""Bit-packed kernel parity vs the NumPy oracle (SURVEY §4 mechanism 1).

The packed layout has two hazard zones the shapes below target: the
word-crossing single-bit shifts (ny straddling multiples of 32) and the
offset-ghost torus wrap rows. The Pallas runs are interpret-mode on CPU —
the same kernel code Mosaic compiles on TPU; the XLA packed loop is the
identical compiled path used on every backend.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from conftest import oracle_n as _oracle

from mpi_and_open_mp_tpu.ops import bitlife


def _soup(ny, nx, seed=0, density=0.4):
    rng = np.random.default_rng(seed)
    return (rng.random((ny, nx)) < density).astype(np.uint8)


SHAPES = [(3, 5), (10, 10), (30, 8), (31, 8), (32, 8), (33, 37), (100, 33)]


@pytest.mark.parametrize("ny,nx", SHAPES)
def test_pack_roundtrip(ny, nx):
    b = _soup(ny, nx)
    packed = bitlife.pack_board(jnp.asarray(b))
    assert packed.shape == (bitlife.n_words(ny), nx)
    assert np.array_equal(np.asarray(bitlife.unpack_board(packed, ny)), b)


@pytest.mark.parametrize("ny,nx", SHAPES)
def test_vmem_bits_parity(ny, nx):
    b = _soup(ny, nx)
    got = np.asarray(
        bitlife.life_run_vmem_bits(jnp.asarray(b), 7, interpret=True)
    )
    assert np.array_equal(got, _oracle(b, 7)), (ny, nx)


def test_vmem_bits_glider_torus():
    """Period-4 glider translation incl. the torus wrap (SURVEY §4 fixture)."""
    b = np.zeros((10, 10), np.uint8)
    for j, i in [(0, 1), (1, 2), (2, 0), (2, 1), (2, 2)]:
        b[j, i] = 1
    got = np.asarray(
        bitlife.life_run_vmem_bits(jnp.asarray(b), 100, interpret=True)
    )
    assert np.array_equal(got, _oracle(b, 100))
    assert got.sum() == 5


@pytest.mark.parametrize("ny,nx", SHAPES + [(300, 33), (257, 16), (600, 9)])
def test_bits_xla_parity(ny, nx):
    """The compiled-XLA packed loop (big-board dispatch target) across
    word-boundary and multi-word shapes."""
    b = _soup(ny, nx, seed=1)
    got = np.asarray(bitlife.life_run_bits_xla(jnp.asarray(b), 5))
    assert np.array_equal(got, _oracle(b, 5)), (ny, nx)


def test_bits_xla_glider_torus():
    b = np.zeros((10, 10), np.uint8)
    for j, i in [(0, 1), (1, 2), (2, 0), (2, 1), (2, 2)]:
        b[j, i] = 1
    got = np.asarray(bitlife.life_run_bits_xla(jnp.asarray(b), 100))
    assert np.array_equal(got, _oracle(b, 100))
    assert got.sum() == 5


@pytest.mark.parametrize("ny,nx,steps", [(256, 128, 7), (512, 256, 33)])
def test_fused_bits_parity(ny, nx, steps):
    """The multi-step-fused tiled kernel (big-board dispatch target on
    TPU), interpret mode at small aligned shapes: exercises the
    word-aligned wrap halo and in-window multi-step validity."""
    b = _soup(ny, nx, seed=3)
    assert bitlife.fused_bits_supported((ny, nx))
    got = np.asarray(
        bitlife.life_run_fused_bits(jnp.asarray(b), steps, interpret=True)
    )
    assert np.array_equal(got, _oracle(b, steps)), (ny, nx, steps)


@pytest.mark.parametrize("steps", [5, 40])
def test_fused_bits_multitile_seams(steps):
    """Force grid > 1 via a small tile budget so the per-tile DMA offsets
    and inter-tile halo seams run in interpret mode (at production sizes
    they only run compiled on TPU). nw=32 with an 8-word budget -> 4
    tiles; a seam off-by-one corrupts rows at every 256-row boundary."""
    b = _soup(1024, 128, seed=6)
    budget = (8 + 2 * bitlife._FUSE_HALO_WORDS) * 4 * 128
    assert bitlife._fused_tile_words(32, 128, budget) == 8
    got = np.asarray(
        bitlife.life_run_fused_bits(
            jnp.asarray(b), steps, interpret=True, tile_budget_bytes=budget
        )
    )
    assert np.array_equal(got, _oracle(b, steps)), steps


def test_fused_bits_pass_boundary():
    """Step counts straddling FUSE_MAX_STEPS force a second HBM pass whose
    input is the first pass's output."""
    b = _soup(256, 128, seed=4, density=0.3)
    for steps in (bitlife.FUSE_MAX_STEPS, bitlife.FUSE_MAX_STEPS + 1):
        got = np.asarray(
            bitlife.life_run_fused_bits(jnp.asarray(b), steps, interpret=True)
        )
        assert np.array_equal(got, _oracle(b, steps)), steps


@pytest.mark.parametrize("steps", [5, 40, bitlife.FUSE_MAX_STEPS + 2])
def test_fused_bits_column_tiled_serial(steps):
    """Force the serial runner onto the column-tiled 2-D grid (x-wrap
    border + per-tile column windows) with a budget that rules out
    full-width row tiles; seams in BOTH axes are exercised, and the
    largest step count crosses a pass boundary so the inter-pass x-halo
    re-concat runs."""
    b = _soup(512, 512, seed=7)
    budget = 4 * (8 + 8) * (128 + 256)
    assert bitlife._fused_tile_words(16, 512, budget) < 8
    plan = bitlife._col_tile_plan(16, 512, budget)
    assert plan is not None and plan[2] < 512  # genuinely column-tiled
    got = np.asarray(bitlife.life_run_fused_bits(
        jnp.asarray(b), steps, interpret=True, tile_budget_bytes=budget))
    assert np.array_equal(got, _oracle(b, steps)), steps


def test_fused_bits_gate():
    assert bitlife.fused_bits_supported((8192, 8192))
    assert bitlife.fused_bits_supported((16384, 16384))
    # Ultra-wide boards: full-width row tiles don't fit the budget, the
    # column-tiled plan does.
    assert bitlife._fused_tile_words(8192 // 32, 131072) < 8
    assert bitlife.fused_bits_supported((8192, 131072))
    assert not bitlife.fused_bits_supported((250, 128))  # ny % 32 != 0
    assert not bitlife.fused_bits_supported((256, 500))  # nx % 128 != 0
    assert not bitlife.fused_bits_supported((288, 384))  # nw=9: no 8k split
    with pytest.raises(ValueError, match="fused_bits_supported"):
        bitlife.life_run_fused_bits(
            jnp.zeros((288, 384), jnp.uint8), 1, interpret=True
        )


def test_pack_exact_roundtrip():
    b = _soup(96, 33, seed=5)
    packed = bitlife.pack_board_exact(jnp.asarray(b))
    assert packed.shape == (3, 33)
    assert np.array_equal(np.asarray(bitlife.unpack_board_exact(packed)), b)


def test_steps_runtime_scalar_no_retrace():
    """Changing the step count must reuse the compiled kernel (SMEM scalar)."""
    b = jnp.asarray(_soup(20, 20))
    f = bitlife._run_vmem_bits_jit
    bitlife.life_run_vmem_bits(b, 1, interpret=True)
    before = f._cache_size()
    bitlife.life_run_vmem_bits(b, 3, interpret=True)
    assert f._cache_size() == before


def test_bits_xla_steps_runtime_scalar_no_retrace():
    b = jnp.asarray(_soup(40, 24))
    f = bitlife._run_bits_xla_jit
    bitlife.life_run_bits_xla(b, 1)
    before = f._cache_size()
    bitlife.life_run_bits_xla(b, 4)
    assert f._cache_size() == before


def test_empty_board_stays_empty():
    b = np.zeros((40, 12), np.uint8)
    got = np.asarray(
        bitlife.life_run_vmem_bits(jnp.asarray(b), 10, interpret=True)
    )
    assert got.sum() == 0


# ------------------------------------------ padded-frame (unaligned) helpers


def test_take_rows_funnel():
    """take_rows must equal an unpack-slice-repack round trip at every
    bit offset, aligned and not."""
    b = _soup(96, 16, seed=11)
    packed = bitlife.pack_board_exact(jnp.asarray(b))
    for start, h in [(0, 1), (32, 2), (5, 1), (37, 1), (1, 2), (63, 1)]:
        got = np.asarray(bitlife.take_rows(packed, start, h))
        want = np.asarray(bitlife.pack_board_exact(
            jnp.asarray(b[start : start + 32 * h])))
        assert np.array_equal(got, want), (start, h)


@pytest.mark.parametrize("pad", [1, 12, 31, 32, 45, 64])
def test_mirror_tail(pad):
    """The last ``pad`` bit rows become copies of rows [0, pad)."""
    rows = 128
    b = _soup(rows, 16, seed=3)
    packed = bitlife.pack_board_exact(jnp.asarray(b))
    src = bitlife.take_rows(packed, 0, 3)  # rows [0, 96) >= pad + 32
    got = np.asarray(bitlife.unpack_board_exact(
        bitlife.mirror_tail(packed, src, pad)))
    want = b.copy()
    want[rows - pad :] = b[:pad]
    assert np.array_equal(got, want)


@pytest.mark.parametrize("ny,h", [(100, 2), (97, 1), (128, 2), (70, 1)])
def test_wrap_y_padded_matches_logical_torus(ny, h):
    """The local padded wrap must present, in window coordinates, exactly
    the periodic extension of the logical board: mirrors refreshed, top
    border = rows [ny-32h, ny), bottom border = rows [pad, pad+32h)."""
    nw = -(-ny // 32)
    pad = 32 * nw - ny
    b = _soup(ny, 24, seed=9)
    frame = np.zeros((32 * nw, 24), np.uint8)
    frame[:ny] = b
    ext = np.asarray(bitlife.unpack_board_exact(bitlife.wrap_y_padded(
        bitlife.pack_board_exact(jnp.asarray(frame)), ny, h)))
    want = np.concatenate([
        b[ny - 32 * h :],                            # top wrap border
        b, b[:pad],                                  # frame, live mirrors
        np.concatenate([b, b])[pad : pad + 32 * h],  # bottom border: the
        # periodic extension continued past the frame = rows [pad, pad+32h)
    ])
    assert np.array_equal(ext, want)


@pytest.mark.parametrize("steps", [1, 40, 130])
def test_fused_stepper_tiled_unaligned_x(steps):
    """The DMA-tiled kernel with wrap-patched lane rolls (unsharded
    unaligned x): a 768x250 board in a 768x256 frame, tile budget forced
    small enough that the window stepper is rejected and full-width row
    tiles carry the fused rounds."""
    ny, nx = 768, 250
    budget = 20_000
    plan = bitlife.plan_sharded_bits((ny, nx), 1, 1, False, False,
                                     budget=budget)
    assert plan is not None and plan.mode == "tiled"
    assert plan.nx_exact == nx and plan.pad_y == 0
    b = _soup(ny, nx, seed=21)
    frame = np.zeros((ny, plan.W), np.uint8)
    frame[:, :nx] = b
    step = bitlife.make_plan_stepper(plan, interpret=True)
    q = bitlife.pack_board_exact(jnp.asarray(frame))
    rem = steps
    while rem > 0:
        k = min(rem, plan.k_max)
        q = step(jnp.asarray([k], jnp.int32), bitlife.wrap_y(q, plan.h))
        rem -= k
    got = np.asarray(bitlife.unpack_board_exact(q))[:, :nx]
    assert np.array_equal(got, _oracle(b, steps))


def test_plan_window_small_shards():
    """500x500 over an 8-way ring — the geometry every pre-plan gate
    rejected (2-word shards) — must plan onto the window stepper."""
    plan = bitlife.plan_sharded_bits((500, 500), 8, 1, True, False)
    assert plan is not None and plan.mode == "window"
    assert plan.frame == (512, 512) and plan.nw_s == 2 and plan.h == 1
    assert plan.nx_exact == 500 and plan.k_max == 32
    # Hopeless geometry still returns None.
    assert bitlife.plan_sharded_bits((64, 128), 8, 1, True, False) is None
    assert bitlife.plan_sharded_bits((256, 20), 4, 2, True, True) is None


@pytest.mark.parametrize("shape,budget,mode,steps", [
    ((100, 130), bitlife._PACKED_VMEM_LIMIT, "window", 110),
    ((740, 250), 20_000, "tiled", 140),  # pad_y=28 + nx_exact, multi-tile
])
def test_frame_bits_serial_unaligned(shape, budget, mode, steps):
    """The single-device padded-frame runner: unaligned boards through
    the fused kernels (local funnel y wrap + wrap-patched x rolls),
    crossing fused-round boundaries."""
    plan = bitlife.plan_sharded_bits(shape, 1, 1, False, False, budget)
    assert plan is not None and plan.mode == mode, plan
    assert steps > plan.k_max
    b = _soup(*shape, seed=33)
    got = np.asarray(bitlife.life_run_frame_bits(
        jnp.asarray(b), steps, interpret=True, budget=budget))
    assert np.array_equal(got, _oracle(b, steps))


def test_frame_bits_steps_runtime_scalar_no_retrace():
    b = jnp.asarray(_soup(100, 130))
    f = bitlife._run_frame_bits_jit
    bitlife.life_run_frame_bits(b, 2, interpret=True)
    before = f._cache_size()
    bitlife.life_run_frame_bits(b, 7, interpret=True)
    assert f._cache_size() == before


def test_rule_exhaustive_all_512_neighbourhoods():
    """Every 3x3 neighbourhood through the packed rule. On a 3x3 torus a
    cell's 8 neighbours are exactly the other 8 cells, so the 512 board
    configurations enumerate the rule's full truth table — the one test
    that can never be fooled by a lucky soup. Checked via the XLA packed
    step (same _carry_save_rule as the Pallas kernels) against the
    birth-on-3 / survive-on-2-or-3 spec directly, not another oracle."""
    boards = np.stack([
        np.array([(cfg >> b) & 1 for b in range(9)], dtype=np.uint8
                 ).reshape(3, 3)
        for cfg in range(512)
    ])
    for cfg in range(512):
        b = boards[cfg]
        got = np.asarray(bitlife.life_run_bits_xla(jnp.asarray(b), 1))
        n = b.sum() - b[1, 1]  # 8-neighbour count of the centre
        want_centre = 1 if (n == 3 or (b[1, 1] and n == 2)) else 0
        assert got[1, 1] == want_centre, (cfg, b, got)


# ------------------------------------------------- board-sliced batch layout


BATCHES = [1, 31, 32, 33, 64]


@pytest.mark.parametrize("b", BATCHES)
@pytest.mark.parametrize("ny,nx", [(3, 5), (33, 37), (8, 8)])
def test_pack_batch_bits_roundtrip_ragged(b, ny, nx):
    """Exact round trip for any B, plane-width multiples or not; the
    dead high bits of a ragged plane must come back as zeros nowhere —
    they are sliced off, not unpacked."""
    rng = np.random.default_rng(b * 1000 + ny)
    s = (rng.random((b, ny, nx)) < 0.4).astype(np.uint8)
    planes = bitlife.pack_batch_bits(jnp.asarray(s))
    assert planes.shape == (bitlife.n_planes(b), ny, nx)
    assert planes.dtype == jnp.uint32
    assert np.array_equal(
        np.asarray(bitlife.unpack_batch_bits(planes, b)), s)


def test_n_planes():
    assert [bitlife.n_planes(b) for b in (1, 31, 32, 33, 64, 65)] == \
        [1, 1, 1, 2, 2, 3]


@pytest.mark.parametrize("b", BATCHES)
def test_bitsliced_xla_parity_ragged(b):
    """Bit-exact per board vs the NumPy oracle through the halo-fused
    XLA runner, for every board of ragged-B stacks (the acceptance
    criterion verbatim). 13 steps is deliberately not a multiple of the
    halo depth, so the ragged final refresh block runs."""
    rng = np.random.default_rng(b)
    s = (rng.random((b, 16, 20)) < 0.4).astype(np.uint8)
    got = np.asarray(bitlife.life_run_bitsliced_batch(
        jnp.asarray(s), 13, use_kernel=False))
    for i in range(b):
        assert np.array_equal(got[i], _oracle(s[i], 13)), f"board {i}"


@pytest.mark.parametrize("b", [5, 32, 33])
def test_bitsliced_kernel_parity_interpret(b):
    """The Pallas VMEM kernel (interpret mode — the code Mosaic compiles
    on TPU), pltpu.roll gathers vs the oracle."""
    rng = np.random.default_rng(b + 7)
    s = (rng.random((b, 13, 17)) < 0.4).astype(np.uint8)
    got = np.asarray(bitlife.life_run_bitsliced_batch(
        jnp.asarray(s), 6, use_kernel=True, interpret=True))
    for i in range(b):
        assert np.array_equal(got[i], _oracle(s[i], 6)), f"board {i}"


def test_bitsliced_small_board_edges():
    """Degenerate spatial extents (1-wide / 2-wide axes) where the halo
    depth clamps to min(ny, nx) and neighbor rolls alias."""
    for ny, nx in [(1, 8), (8, 1), (2, 2), (3, 3)]:
        rng = np.random.default_rng(ny * 100 + nx)
        s = (rng.random((9, ny, nx)) < 0.5).astype(np.uint8)
        got = np.asarray(bitlife.life_run_bitsliced_batch(
            jnp.asarray(s), 5, use_kernel=False))
        for i in range(9):
            assert np.array_equal(got[i], _oracle(s[i], 5)), (ny, nx, i)


def test_bitsliced_glider_torus_per_board():
    """A glider in board 0, a blinker in board 40 (second plane), empty
    elsewhere: cross-board isolation over 100 steps incl. torus wraps —
    a single leaked bit between planes or boards would kill a pattern."""
    s = np.zeros((48, 10, 10), np.uint8)
    for j, i in [(0, 1), (1, 2), (2, 0), (2, 1), (2, 2)]:
        s[0, j, i] = 1
    s[40, 4, 3:6] = 1
    got = np.asarray(bitlife.life_run_bitsliced_batch(
        jnp.asarray(s), 100, use_kernel=False))
    assert np.array_equal(got[0], _oracle(s[0], 100))
    assert got[0].sum() == 5
    assert np.array_equal(got[40], _oracle(s[40], 100))
    dead = np.delete(got, (0, 40), axis=0)
    assert dead.sum() == 0  # padding + empty boards stay dead


def test_bitsliced_zero_steps_and_dtype():
    s = _soup(16, 16, seed=2).astype(np.int32)[None].repeat(8, axis=0)
    got = bitlife.life_run_bitsliced_batch(jnp.asarray(s), 0,
                                           use_kernel=False)
    assert got.dtype == jnp.int32
    assert np.array_equal(np.asarray(got), s)


def test_bitsliced_steps_runtime_scalar_no_retrace():
    """One compile per plane shape serves ANY step count AND any ragged
    B within the plane — the serve-layer bucketing contract, observable
    via the jit.retrace counter the way the daemon sees it."""
    from mpi_and_open_mp_tpu.obs import metrics

    metrics.reset()
    # 19x21 is unique to this test: the process-wide jit cache must not
    # have seen the plane shape before, or the count would read 0.
    for b in (17, 25, 32):  # same plane count, differing ragged B
        s = jnp.asarray(_soup(19, 21, seed=b)[None].repeat(b, axis=0))
        for n in (1, 4, 9):
            bitlife.life_run_bitsliced_batch(s, n, use_kernel=False)
    assert metrics.get("jit.retrace", fn="life_batch_bitsliced") == 1
    metrics.reset()


def test_fits_vmem_bitsliced_gate():
    # One plane of 500x500 lane-pads to 500x512 words = 1.02 MB: in.
    assert bitlife.fits_vmem_bitsliced((32, 500, 500))
    assert bitlife.fits_vmem_bitsliced((8, 64, 64))
    # Plane count scales the footprint: enough boards push any shape out.
    assert not bitlife.fits_vmem_bitsliced((32 * 64, 500, 500))
    assert not bitlife.fits_vmem_bitsliced((8, 2048, 2048))
