"""Bit-packed kernel parity vs the NumPy oracle (SURVEY §4 mechanism 1).

The packed layout has three hazard zones the shapes below target: the
word-crossing single-bit shifts (ny straddling multiples of 32), the
offset-ghost torus wrap rows, and the tile seams of the HBM row-tiled
variant (forced with tiny ``max_tile_bytes``). All runs are interpret-mode
Pallas on CPU — the same kernel code Mosaic compiles on TPU.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from conftest import oracle_n as _oracle

from mpi_and_open_mp_tpu.ops import bitlife


def _soup(ny, nx, seed=0, density=0.4):
    rng = np.random.default_rng(seed)
    return (rng.random((ny, nx)) < density).astype(np.uint8)


SHAPES = [(3, 5), (10, 10), (30, 8), (31, 8), (32, 8), (33, 37), (100, 33)]


@pytest.mark.parametrize("ny,nx", SHAPES)
def test_pack_roundtrip(ny, nx):
    b = _soup(ny, nx)
    packed = bitlife.pack_board(jnp.asarray(b))
    assert packed.shape == (bitlife.n_words(ny), nx)
    assert np.array_equal(np.asarray(bitlife.unpack_board(packed, ny)), b)


@pytest.mark.parametrize("ny,nx", SHAPES)
def test_vmem_bits_parity(ny, nx):
    b = _soup(ny, nx)
    got = np.asarray(
        bitlife.life_run_vmem_bits(jnp.asarray(b), 7, interpret=True)
    )
    assert np.array_equal(got, _oracle(b, 7)), (ny, nx)


def test_vmem_bits_glider_torus():
    """Period-4 glider translation incl. the torus wrap (SURVEY §4 fixture)."""
    b = np.zeros((10, 10), np.uint8)
    for j, i in [(0, 1), (1, 2), (2, 0), (2, 1), (2, 2)]:
        b[j, i] = 1
    got = np.asarray(
        bitlife.life_run_vmem_bits(jnp.asarray(b), 100, interpret=True)
    )
    assert np.array_equal(got, _oracle(b, 100))
    assert got.sum() == 5


@pytest.mark.parametrize(
    "ny,nx,mtb",
    [(300, 33, 3200), (257, 16, 1600), (600, 9, 900), (700, 20, 2000)],
)
def test_tiled_bits_parity_multitile(ny, nx, mtb):
    """Forced 8-word-row tiles over >8-word boards: exercises tile seams
    and the padded junk words of ``_tiled_bits_kernel`` (nwp > nw for
    several of these shapes)."""
    b = _soup(ny, nx, seed=1)
    got = np.asarray(
        bitlife.life_run_tiled_bits(
            jnp.asarray(b), 5, interpret=True, max_tile_bytes=mtb
        )
    )
    assert np.array_equal(got, _oracle(b, 5)), (ny, nx)


def test_tiled_bits_parity_single_tile():
    b = _soup(64, 24, seed=2)
    got = np.asarray(
        bitlife.life_run_tiled_bits(jnp.asarray(b), 6, interpret=True)
    )
    assert np.array_equal(got, _oracle(b, 6))


def test_steps_runtime_scalar_no_retrace():
    """Changing the step count must reuse the compiled kernel (SMEM scalar)."""
    b = jnp.asarray(_soup(20, 20))
    f = bitlife._run_vmem_bits_jit
    bitlife.life_run_vmem_bits(b, 1, interpret=True)
    before = f._cache_size()
    bitlife.life_run_vmem_bits(b, 3, interpret=True)
    assert f._cache_size() == before


def test_tiled_bits_gate_ultrawide():
    """Ultra-wide boards have no Mosaic-legal in-budget tile split; the
    dispatch gate must reject them (life_run_vmem then falls back to the
    compiled XLA roll loop instead of a VMEM-overflowing kernel)."""
    assert not bitlife.tiled_bits_supported((8192, 131072))
    assert bitlife.tiled_bits_supported((8192, 8192))
    # Lane-unaligned nx compiles in interpret mode only; the hardware
    # dispatch gate must reject it (Mosaic memref_slice lane alignment).
    assert not bitlife.tiled_bits_supported((8192, 500))
    # Single-tile boards still need 8-aligned DMA extents on hardware.
    assert bitlife._tile_words(bitlife.n_words(2048), 2048) % 8 == 0
    with pytest.raises(ValueError, match="tiled_bits_supported"):
        bitlife.life_run_tiled_bits(
            jnp.zeros((40, 12), jnp.uint8), 1, interpret=True,
            max_tile_bytes=64,
        )


def test_empty_board_stays_empty():
    b = np.zeros((40, 12), np.uint8)
    got = np.asarray(
        bitlife.life_run_vmem_bits(jnp.asarray(b), 10, interpret=True)
    )
    assert got.sum() == 0
