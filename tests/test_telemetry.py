"""Fleet telemetry plane: histograms, snapshot rings, burn rate, sidecar
framing, the router rollup, and the merged fleet timeline.

The contract under test: quantiles read off the fixed-bucket histogram
sit within the DECLARED bucket error of the exact sample quantiles; the
snapshot ring is bounded and counts its own evictions; the burn-rate
monitor trips on a real incident (both windows over budget) and is
edge-triggered; the CRC-framed sidecar stream soft-lands on a killed
writer's truncated tail; the rollup's loss accounting states exactly
what never arrived; and an in-process Fleet records every elasticity
decision with the burn windows that triggered it.
"""

import json
import math
import os
import struct
import sys
import time

import numpy as np
import pytest

from mpi_and_open_mp_tpu.obs import metrics, telemetry, trace
from mpi_and_open_mp_tpu.serve.fleet import Fleet
from mpi_and_open_mp_tpu.serve.policy import (
    ElasticityPolicy, ServePolicy, percentile)
from mpi_and_open_mp_tpu.serve.router import FleetRollup


@pytest.fixture(autouse=True)
def _fresh_registry():
    metrics.reset()
    yield
    metrics.reset()


# -- LatencyHist -----------------------------------------------------------


def test_hist_quantiles_within_declared_bucket_error():
    rng = np.random.default_rng(7)
    samples = rng.lognormal(mean=-2.0, sigma=1.0, size=2000).tolist()
    h = telemetry.LatencyHist()
    for v in samples:
        h.observe(v)
    assert h.count == len(samples)
    for q in (50, 99, 99.9):
        exact = percentile(samples, q)
        est = h.quantile(q)
        # The estimate is the holding bucket's upper edge: never below
        # the exact value's bucket, at most one ratio above it.
        assert h.agrees(est, exact), (q, est, exact)
        assert est >= exact * (1 - 1e-9)
        assert est <= exact * telemetry.BUCKET_RATIO * (1 + 1e-9)


def test_hist_empty_overflow_and_nan():
    h = telemetry.LatencyHist()
    assert h.quantile(99) == 0.0
    h.observe(float("nan"))
    assert h.count == 0
    h.observe(1e6)  # past the last edge: overflow bucket, readout = max
    assert h.quantile(99) == 1e6
    assert h.counts[-1] == 1


def test_hist_merge_counts_equals_direct_observation():
    rng = np.random.default_rng(3)
    a, b = telemetry.LatencyHist(), telemetry.LatencyHist()
    whole = telemetry.LatencyHist()
    for i, v in enumerate(rng.exponential(0.1, size=400)):
        (a if i % 2 else b).observe(v)
        whole.observe(v)
    merged = telemetry.LatencyHist()
    merged.merge_counts(a.snapshot_counts(), total=a.total,
                        vmin=a.vmin, vmax=a.vmax)
    # Sparse form too — what actually ships in snapshots.
    sparse = {str(i): n for i, n in enumerate(b.counts) if n}
    merged.merge_counts(sparse, total=b.total, vmin=b.vmin, vmax=b.vmax)
    assert merged.counts == whole.counts
    assert merged.count == whole.count
    assert math.isclose(merged.total, whole.total)
    for q in (50, 99):
        assert merged.quantile(q) == whole.quantile(q)


# -- WorkerTelemetry -------------------------------------------------------


def test_worker_ring_bounded_and_counts_evictions():
    wt = telemetry.WorkerTelemetry(0, interval_s=0.01, capacity=4)
    for k in range(10):
        snap = wt.sample(k * 1.0, {"resolved": k}, force=True)
        assert snap is not None and snap["seq"] == k
    assert len(wt.series()) == 4
    assert wt.dropped == 6
    assert [s["seq"] for s in wt.series()] == [6, 7, 8, 9]


def test_worker_sample_interval_gated_and_delta_shipped():
    wt = telemetry.WorkerTelemetry(1, interval_s=1.0)
    wt.observe_latency(0.01)
    first = wt.sample(10.0, {"resolved": 1})
    assert first is not None and first["hist_count"] == 1
    assert sum(first["hist"].values()) == 1
    assert wt.sample(10.5, {"resolved": 1}) is None  # not due
    wt.observe_latency(0.02)
    wt.observe_latency(0.03)
    second = wt.sample(11.5, {"resolved": 3})
    # Only the NEW observations ship: the bucket delta since last snap.
    assert second is not None and sum(second["hist"].values()) == 2
    assert second["seq"] == 1
    assert second["mono"] == 11.5 and isinstance(second["wall"], float)


# -- BurnRateMonitor -------------------------------------------------------


def test_burn_rate_windows_and_edge_trigger():
    b = telemetry.BurnRateMonitor(slo_p99_s=0.1, goodput_frac=0.9,
                                  short_window_s=1.0, long_window_s=4.0)
    assert b.budget == pytest.approx(0.1)
    assert b.is_bad(0.2) and not b.is_bad(0.05)
    # Healthy traffic: burn well under 1 in both windows.
    for k in range(8):
        win = b.observe(k * 0.5, good=20, bad=0)
        assert not win["alert_edge"]
    assert b.alerts == 0
    # Incident: all-bad intervals push BOTH windows over budget.
    edges = 0
    for k in range(8):
        win = b.observe(4.0 + k * 0.5, good=0, bad=20)
        edges += win["alert_edge"]
    assert edges == 1  # edge-triggered: one crossing, not one per tick
    assert b.alerts == 1
    assert b.peak_short == pytest.approx(1.0 / 0.1)  # all-bad = 10x
    # Recovery then a second incident: a second edge.
    for k in range(16):
        b.observe(8.0 + k * 0.5, good=20, bad=0)
    for k in range(8):
        b.observe(16.0 + k * 0.5, good=0, bad=20)
    assert b.alerts == 2


def test_burn_rate_short_window_trips_before_long():
    b = telemetry.BurnRateMonitor(slo_p99_s=0.1, goodput_frac=0.9,
                                  short_window_s=0.5, long_window_s=4.0)
    for k in range(7):
        b.observe(k * 0.5, good=40, bad=0)
    win = b.observe(3.5, good=0, bad=20)
    # One bad interval: the short window saturates (20 bad of 60 in
    # window = 3.3x budget) but the long window dilutes it across the
    # healthy history (20 of 300 = 0.67x) — no alert yet. Only a
    # SUSTAINED incident trips both.
    assert win["burn_short"] > 1.0
    assert win["burn_long"] < win["burn_short"]
    assert not win["alert_edge"]


def test_burn_monitor_from_slo():
    from mpi_and_open_mp_tpu.serve.loadgen import SLO

    b = telemetry.BurnRateMonitor.from_slo(SLO(p99_s=0.3,
                                               goodput_frac=0.8))
    assert b.slo_p99_s == 0.3
    assert b.budget == pytest.approx(0.2)


# -- sidecar framing -------------------------------------------------------


def _snap(worker, seq, **counters):
    return {"v": telemetry.SNAPSHOT_SCHEMA, "worker": worker, "seq": seq,
            "mono": 100.0 + seq, "wall": 1e9 + seq,
            "counters": counters, "hist": {}, "hist_count": 0}


def test_frame_roundtrip(tmp_path):
    path = str(tmp_path / "w0.telemetry.bin")
    with open(path, "ab") as fd:
        for k in range(5):
            telemetry.write_frame(fd, _snap(0, k, resolved=k))
    rep = telemetry.read_frames(path)
    assert rep["truncated"] == 0
    assert [s["seq"] for s in rep["snapshots"]] == list(range(5))


def test_frame_truncated_tail_soft_lands(tmp_path):
    path = str(tmp_path / "w0.telemetry.bin")
    with open(path, "ab") as fd:
        for k in range(3):
            telemetry.write_frame(fd, _snap(0, k))
    blob = open(path, "rb").read()
    # A kill -9 mid-write: chop the last frame in half.
    open(path, "wb").write(blob[:-20])
    rep = telemetry.read_frames(path)
    assert [s["seq"] for s in rep["snapshots"]] == [0, 1]
    assert rep["truncated"] == 1


def test_frame_crc_corruption_stops_reader(tmp_path):
    path = str(tmp_path / "w0.telemetry.bin")
    with open(path, "ab") as fd:
        for k in range(3):
            telemetry.write_frame(fd, _snap(0, k))
    blob = bytearray(open(path, "rb").read())
    blob[12] ^= 0xFF  # flip a payload byte of frame 0
    open(path, "wb").write(bytes(blob))
    rep = telemetry.read_frames(path)
    assert rep["snapshots"] == []  # reader stops at the first bad CRC
    assert rep["truncated"] == 1


def test_frame_reader_never_allocates_a_corrupt_length(tmp_path):
    path = str(tmp_path / "w0.telemetry.bin")
    open(path, "wb").write(struct.pack("<II", 1 << 30, 0) + b"x" * 64)
    rep = telemetry.read_frames(path)
    assert rep["snapshots"] == [] and rep["truncated"] == 1
    assert telemetry.read_frames(str(tmp_path / "missing.bin")) == {
        "snapshots": [], "truncated": 0, "bytes": 0}


def test_clock_offset_median():
    snaps = [dict(_snap(0, k), mono=100.0 + k, wall=500.0 + k)
             for k in range(5)]
    snaps[2]["wall"] += 3.0  # one jittered exchange: the median rejects it
    assert telemetry.clock_offset(snaps) == pytest.approx(400.0)
    assert telemetry.clock_offset([]) is None


# -- FleetRollup -----------------------------------------------------------


def test_rollup_merges_counters_and_detects_seq_gaps():
    r = FleetRollup()
    for seq in (0, 1, 3):  # seq 2 never arrives
        assert r.ingest(_snap(0, seq, resolved=seq * 2))
    assert r.ingest(_snap(1, 0, resolved=10))
    assert r.counter("resolved") == 6 + 10  # latest per worker
    loss = r.loss()
    assert loss == {"expected": 5, "received": 4, "lost": 1,
                    "truncated": 0, "frac": pytest.approx(0.2)}
    r.truncated += 1  # a chopped sidecar frame charges loss too
    assert r.loss() == {"expected": 6, "received": 4, "lost": 2,
                        "truncated": 1, "frac": pytest.approx(2 / 6)}
    r.truncated -= 1
    assert not r.ingest({"v": 999, "worker": 0, "seq": 9})
    assert r.rejected == 1


def test_rollup_worker_key_override_isolates_lifetimes():
    r = FleetRollup()
    r.ingest(_snap(2, 0, resolved=5))
    r.ingest(_snap(2, 1, resolved=8))
    # A recovery worker re-uses index 2 but restarts seq at 0: under its
    # own key that is a fresh series, not a gap.
    r.ingest(_snap(2, 0, resolved=3), worker="2.rehome1")
    loss = r.loss()
    assert loss["lost"] == 0 and loss["expected"] == 3
    assert r.counter("resolved") == 8 + 3
    assert r.summary()["workers"] == [2, "2.rehome1"]


def test_rollup_quantiles_from_shipped_deltas():
    rng = np.random.default_rng(11)
    r = FleetRollup()
    exact = []
    for w in range(3):
        wt = telemetry.WorkerTelemetry(w, interval_s=0.01)
        for i, v in enumerate(rng.exponential(0.05, size=200)):
            wt.observe_latency(v)
            exact.append(v)
            if i % 50 == 49:
                r.ingest(wt.sample(float(i), {}, force=True))
    assert r.hist.count == len(exact)
    for q in (50, 99):
        assert r.hist.agrees(r.quantile(q), percentile(exact, q))


# -- in-process fleet end-to-end ------------------------------------------


def _run_fleet_burst(fleet, boards=24, steps=2):
    rng = np.random.default_rng(5)
    for k in range(boards):
        fleet.submit((rng.random((32, 32)) < 0.3).astype(np.uint8), steps,
                     session=f"s{k % 6}")
    fleet.serve_until_drained(drain=True)


def test_fleet_ships_snapshots_into_rollup_with_zero_loss(tmp_path):
    fleet = Fleet(2, ServePolicy(max_batch=4, max_wait_s=0.0),
                  heartbeat_interval_s=0.01, telemetry_interval_s=0.005)
    _run_fleet_burst(fleet)
    tel = fleet.router.telemetry
    s = tel.summary()
    assert s["snapshots"] > 0
    assert s["loss"] == {"expected": s["loss"]["expected"],
                         "received": s["loss"]["expected"], "lost": 0,
                         "truncated": 0, "frac": 0.0}
    assert s["resolved"] == 24
    # The rollup's merged quantiles agree with the exact fleet-side
    # percentiles within the declared bucket error.
    lat = [t.latency_s for t in fleet.resolved_tickets()]
    assert tel.hist.count == len(lat)
    assert tel.hist.agrees(tel.quantile(50), percentile(lat, 50))
    assert tel.hist.agrees(tel.quantile(99), percentile(lat, 99))
    assert set(tel.clock_offsets()) == {0, 1}


def test_fleet_telemetry_off_records_nothing():
    fleet = Fleet(2, ServePolicy(max_batch=4, max_wait_s=0.0),
                  heartbeat_interval_s=0.01, telemetry=False)
    _run_fleet_burst(fleet, boards=8)
    assert fleet.burn is None
    assert fleet.router.telemetry.snapshots == 0
    assert fleet.decisions == []


def test_fleet_decisions_carry_burn_windows(tmp_path, monkeypatch):
    sink = tmp_path / "trace.jsonl"
    monkeypatch.setenv("MOMP_TRACE", str(sink))
    trace.reset()
    try:
        fleet = Fleet(
            2, ServePolicy(max_batch=4, max_wait_s=0.0),
            wal_dir=str(tmp_path / "wal"),
            heartbeat_interval_s=0.01, telemetry_interval_s=0.005,
            # A tight SLO every CPU batch breaches: the controller must
            # ADD, and surplus is unreachable so it can never drain.
            # breach_k=1 because a submitted-up-front burst drains in
            # one pump round — there is only one elasticity tick.
            elasticity=ElasticityPolicy(
                slo_p99_s=1e-4, min_workers=1, max_workers=3,
                breach_k=1, surplus_p99_frac=0.0))
        _run_fleet_burst(fleet)
        assert fleet.decisions, "breach never produced a decision"
        for d in fleet.decisions:
            assert d["action"] == "add"
            for key in ("burn_short", "burn_long", "short_window_s",
                        "long_window_s", "p99_s", "depth", "workers"):
                assert key in d, (key, d)
        assert len(fleet.handles) == 3  # capped by max_workers
        # The decisions landed in the trace stream too, after a burn
        # alert (the tick order: telemetry, then elasticity).
        records = [json.loads(ln) for ln in
                   sink.read_text().splitlines() if ln.strip()]
        scales = [r for r in records if r.get("name") == "serve.fleet.scale"]
        burns = [r for r in records if r.get("name") == "serve.fleet.burn"]
        assert len(scales) == len(fleet.decisions)
        assert burns, "SLO-breaching traffic never raised a burn alert"
        assert burns[0]["ts"] <= scales[0]["ts"]
        assert fleet.burn.summary()["burn_alerts"] >= 1
    finally:
        trace.reset()


def test_shipper_writes_frames_and_final_flush(tmp_path):
    path = str(tmp_path / "w.telemetry.bin")
    resolved = []

    def sample():
        return {"resolved": len(resolved), "good": len(resolved),
                "bad": 0}, [v for v in resolved[-2:]]

    shipper = telemetry.SnapshotShipper(path, 7, sample, interval_s=0.01)
    shipper.start()
    for _ in range(3):
        resolved.append(0.01)
        time.sleep(0.03)
    shipper.stop()
    rep = telemetry.read_frames(path)
    assert rep["truncated"] == 0
    assert rep["snapshots"], "shipper never wrote a frame"
    last = rep["snapshots"][-1]
    assert last["counters"]["resolved"] == 3  # stop() force-ships
    seqs = [s["seq"] for s in rep["snapshots"]]
    assert seqs == list(range(len(seqs)))


# -- merged fleet timeline (analysis/fleet_report.py) ----------------------


def _write_trace(path, pid, names, base_ts=1000.0):
    with open(path, "w") as fd:
        for k, name in enumerate(names):
            fd.write(json.dumps({
                "kind": "span", "name": name, "ts": base_ts + k,
                "dur": 0.5, "id": k + 1,
                "parent": k if k else None,
                "pid": pid, "host": "h"}) + "\n")


def test_fleet_report_merges_tracks_with_id_namespacing(tmp_path):
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "analysis"))
    import fleet_report

    d = tmp_path / "state"
    d.mkdir()
    # Two workers, COLLIDING span ids (each process counts from 1).
    _write_trace(str(d / "worker0.trace.jsonl"), 100, ["a", "b"])
    _write_trace(str(d / "worker1.trace.jsonl"), 200, ["a", "c"])
    router = tmp_path / "router.trace.jsonl"
    with open(router, "w") as fd:
        fd.write(json.dumps({"kind": "event", "name": "serve.fleet.burn",
                             "ts": 1500.0, "id": 1, "parent": None,
                             "pid": 300, "host": "h"}) + "\n")
        fd.write(json.dumps({"kind": "event", "name": "serve.fleet.scale",
                             "ts": 1501.0, "id": 2, "parent": None,
                             "pid": 300, "host": "h",
                             "attrs": {"action": "add"}}) + "\n")
    with open(d / "worker0.telemetry.bin", "ab") as fd:
        for k in range(3):
            telemetry.write_frame(fd, _snap(0, k, resolved=k, depth=1))

    summary = fleet_report.fleet_report(
        str(d), router_trace=str(router),
        chrome_out=str(tmp_path / "merged.json"))
    assert summary["tracks"] == ["router", "worker0", "worker1"]
    assert summary["records"] == 6
    assert summary["burn_events"] == 1
    assert summary["burn_precedes_scale"] is True
    assert summary["scale_events"][0]["action"] == "add"
    assert summary["telemetry"]["loss"]["lost"] == 0
    assert "0" in str(summary["clock_offsets"]) or summary["clock_offsets"]

    chrome = json.loads((tmp_path / "merged.json").read_text())
    evs = chrome["traceEvents"]
    # Span ids remapped into per-source namespaces: no two X events from
    # different pids share a span_id.
    xs = [e for e in evs if e.get("ph") == "X"]
    ids = [(e["args"]["span_id"], e["pid"]) for e in xs]
    assert len({i for i, _ in ids}) == len(ids)
    # Parent links survived the remap: worker0's child nests under its
    # own root, in worker0's namespace.
    by_pid = {}
    for i, pid in ids:
        by_pid.setdefault(pid, []).append(i)
    for pid, pid_ids in by_pid.items():
        assert max(pid_ids) - min(pid_ids) < fleet_report._ID_STRIDE
    # Process tracks named after their source files.
    names = {e["args"]["name"] for e in evs
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert any("worker0" in n for n in names)
    assert any("worker1" in n for n in names)
    assert any("router" in n for n in names)
    # Sidecar counters landed as Perfetto counter events on the wall
    # axis via the clock offset.
    counters = [e for e in evs if e.get("ph") == "C"]
    assert any(e["name"] == "worker0.depth" for e in counters)


def test_fleet_report_survives_killed_writer_tail(tmp_path):
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "analysis"))
    import fleet_report

    d = tmp_path / "state"
    d.mkdir()
    _write_trace(str(d / "worker0.trace.jsonl"), 100, ["a"])
    with open(d / "worker1.trace.jsonl", "w") as fd:
        fd.write(json.dumps({"kind": "span", "name": "a", "ts": 1.0,
                             "dur": 0.1, "id": 1, "parent": None,
                             "pid": 200, "host": "h"}) + "\n")
        fd.write('{"kind": "span", "name": "tr')  # killed mid-line
    summary = fleet_report.fleet_report(str(d))
    assert summary["records"] == 2  # the intact prefix still merges
    assert summary["load_errors"]


# -- satellite regressions -------------------------------------------------


def test_trace_report_json_soft_lands_on_empty_and_header_only(tmp_path):
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "analysis"))
    import trace_report

    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    header_only = tmp_path / "header.jsonl"
    header_only.write_text('{"displayTimeUnit": "ms"}\n\n')
    for path in (empty, header_only):
        assert trace_report.main([str(path), "--json"]) == 0, path
        assert trace_report.main([str(path)]) == 0  # text mode too
        out = tmp_path / "chrome.json"
        assert trace_report.main([str(path), "--chrome", str(out)]) == 0
        chrome = json.loads(out.read_text())
        assert chrome["traceEvents"] == []
    from mpi_and_open_mp_tpu.obs import report

    rep = report.report_dict(report.load(str(header_only)))
    assert rep["records"] == 0
    assert rep["phases"]["by_name"] == {}


def test_metrics_label_cardinality_guard():
    for k in range(300):
        metrics.inc("sess.requests", session=f"s{k}")
    snap = metrics.snapshot()
    names = [key for key in snap["counters"] if key.startswith("sess.")]
    assert len(names) == metrics.max_labelsets() == 256
    assert metrics.get(metrics.DROPPED_LABELS) == 300 - 256
    # Existing label sets keep updating under the cap.
    metrics.inc("sess.requests", session="s0")
    assert metrics.snapshot()["counters"]["sess.requests{session=s0}"] == 2
    # Other stores share the guard; the overflow counter itself is
    # label-free and can never be dropped.
    for k in range(300):
        metrics.gauge("sess.depth", k, session=f"s{k}")
        metrics.observe("sess.lat", 0.1, session=f"s{k}")
    snap = metrics.snapshot()
    assert sum(1 for k in snap["gauges"] if k.startswith("sess.")) == 256
    assert sum(1 for k in snap["histograms"] if k.startswith("sess.")) == 256
    metrics.reset()
    metrics.inc("sess.requests", session="s999")  # reset clears the cap
    assert metrics.get("sess.requests", session="s999") == 1


def test_metrics_labelset_cap_env_override(monkeypatch):
    monkeypatch.setenv("MOMP_METRICS_MAX_LABELSETS", "4")
    for k in range(10):
        metrics.inc("m.x", label=f"v{k}")
    assert len(metrics.snapshot()["counters"]) == 5  # 4 + dropped counter
    assert metrics.get(metrics.DROPPED_LABELS) == 6
    monkeypatch.setenv("MOMP_METRICS_MAX_LABELSETS", "bogus")
    assert metrics.max_labelsets() == 256


def test_metrics_delta_scopes_phases():
    metrics.inc("phase.a", 5)
    metrics.observe("lat", 0.1)
    before = metrics.snapshot()
    metrics.inc("phase.b", 3)
    metrics.inc("phase.a", 2)
    metrics.gauge("depth", 7)
    metrics.observe("lat", 0.3)
    d = metrics.delta(before, metrics.snapshot())
    assert d["counters"] == {"phase.a": 2, "phase.b": 3}
    assert d["gauges"] == {"depth": 7}
    assert d["histograms"]["lat"]["count"] == 1
    assert d["histograms"]["lat"]["total"] == pytest.approx(0.3)
    # No movement -> empty delta, so a quiet phase reports nothing.
    snap = metrics.snapshot()
    assert metrics.delta(snap, snap) == {
        "counters": {}, "gauges": {}, "histograms": {}}


def test_sentinel_polarity_for_telemetry_fields():
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "analysis"))
    import regression_sentinel as sentinel

    assert sentinel.direction_for("telemetry_snapshot_loss_frac") == "lower"
    assert sentinel.direction_for("loadgen_burn_rate_peak") == "lower"
    assert "telemetry_snapshot_loss_frac" in sentinel.WATCH_FIELDS
    assert "loadgen_burn_rate_peak" in sentinel.WATCH_FIELDS
    # The rate rules still take precedence over the new keywords.
    assert sentinel.direction_for("burnish_per_sec") == "higher"
