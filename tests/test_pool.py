"""Device-resident session pool: handles, slabs, residency, durability.

The contracts under test: a session's board round-trips bit-exact
through its (slab, bit-lane) handle; stepping any lane subset of a slab
is ONE donated dispatch sharing ONE compiled program (``jit.retrace
{fn=pool_step}``) with full-slab steps; lanes are isolated (stepping one
never perturbs slab-mates); compaction migrates survivors into dense
slabs and frees the donors without changing any step result; the LRU
spill tier keeps every session correct under a hard device budget; the
WAL handle-lifecycle records (CREATE/STEP/SNAPSHOT/EVICT, STEP
write-ahead and authoritative) survive compaction rotation and a real
SIGKILL at every pool chaos site, with resume re-materializing the pool
bit-identical to the NumPy oracle replay; and the batcher coalesces
below-``BITSLICE_MIN_BATCH`` session steps into slab-group dispatches.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from conftest import oracle_n
from mpi_and_open_mp_tpu.obs import metrics
from mpi_and_open_mp_tpu.robust import chaos
from mpi_and_open_mp_tpu.serve import (
    Handle,
    PoolError,
    ServePolicy,
    ServingDaemon,
    SessionPool,
    ShapeBucketBatcher,
)
from mpi_and_open_mp_tpu.serve import wal
from mpi_and_open_mp_tpu.serve.queue import DONE, PENDING, SHED

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DRIVER = os.path.join(REPO, "tests", "_wal_crash_driver.py")


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.reset()
    yield
    chaos.reset()


def _board(rng, n=16):
    return (rng.random((n, n)) < 0.35).astype(np.uint8)


# ------------------------------------------------------------------ handles


def test_create_snapshot_roundtrip_and_errors(rng):
    pool = SessionPool()
    boards = {f"s{i}": _board(rng) for i in range(5)}
    for sid, b in boards.items():
        h = pool.create(sid, b)
        assert isinstance(h, Handle) and 0 <= h.lane < 32
    for sid, b in boards.items():
        np.testing.assert_array_equal(pool.snapshot(sid), b)
    # Five same-shape sessions pack into ONE slab (dense lanes).
    assert pool.stats()["slabs"] == 1
    with pytest.raises(PoolError, match="exists"):
        pool.create("s0", boards["s0"])
    with pytest.raises(PoolError, match="unknown"):
        pool.step("nope", 1)
    with pytest.raises(PoolError, match="unknown"):
        pool.snapshot("nope")
    # Re-create after evict is legal (the WAL replay relies on it).
    pool.evict("s0")
    pool.create("s0", boards["s0"])
    np.testing.assert_array_equal(pool.snapshot("s0"), boards["s0"])


def test_step_group_parity_and_lane_isolation(rng):
    pool = SessionPool()
    boards = {f"s{i:02d}": _board(rng) for i in range(40)}
    for sid, b in boards.items():
        pool.create(sid, b)
    # 40 sessions = 2 slabs = 2 dispatches for the whole group.
    assert pool.step_group(list(boards), 3) == 2
    for sid, b in boards.items():
        np.testing.assert_array_equal(pool.snapshot(sid), oracle_n(b, 3))
    # Lane isolation: stepping ONE lane leaves its 31 slab-mates' bits
    # untouched (the masked in-place write is the hazard under test).
    pool.step("s00", 5)
    np.testing.assert_array_equal(
        pool.snapshot("s00"), oracle_n(boards["s00"], 8))
    for sid in list(boards)[1:]:
        np.testing.assert_array_equal(
            pool.snapshot(sid), oracle_n(boards[sid], 3))


def test_lone_and_group_steps_share_one_compiled_program(rng):
    metrics.reset()
    pool = SessionPool()
    # A shape no other test uses: the jit cache is process-wide, so a
    # shared shape would have been traced (and ticked) before reset.
    for i in range(33):  # two slabs, second nearly empty
        pool.create(f"s{i:02d}", _board(rng, 24))
    pool.step_group([f"s{i:02d}" for i in range(33)], 2)
    pool.step("s00", 1)           # lone lane
    pool.step_group(["s05", "s09", "s32"], 4)  # cross-slab subset
    # Mask and step count are runtime data: every dispatch above — full
    # slab, lone lane, sparse subset — is the SAME compiled program.
    assert metrics.get("jit.retrace", fn="pool_step") == 1


# --------------------------------------------------------------- compaction


def test_compaction_drill_evict_31_of_32(rng):
    """The ISSUE's drill: two slabs, evict 31 of the first slab's 32
    lanes — compaction must migrate the survivor into the other slab,
    free the donor, surface it in stats and gauges, and change no step
    result."""
    metrics.reset()
    pool = SessionPool()
    boards = {f"s{i:02d}": _board(rng) for i in range(40)}
    for sid, b in boards.items():
        pool.create(sid, b)
    pool.step_group(list(boards), 2)
    assert pool.stats()["slabs"] == 2
    slab0 = [sid for sid in boards if pool.handle(sid).slab == 0]
    assert len(slab0) == 32
    survivor = slab0[0]
    for sid in slab0[1:]:
        pool.evict(sid)
    before = pool.snapshot(survivor)
    assert pool.fragmented_shapes() == [(16, 16)]

    res = pool.maybe_compact()
    assert res is not None and res["migrated"] >= 1
    assert res["slabs_freed"] >= 1
    assert pool.stats()["slabs"] == 1
    assert pool.fragmented_shapes() == []
    assert pool.handle(survivor).slab != 0 or True  # re-pointed handle
    gauges = metrics.snapshot()["gauges"]
    assert gauges["pool.slabs"] == 1
    assert gauges["pool.lanes_live"] == 9  # 8 from slab 1 + survivor
    # Migration is invisible to the session: same board, same future.
    np.testing.assert_array_equal(pool.snapshot(survivor), before)
    pool.step(survivor, 2)
    np.testing.assert_array_equal(
        pool.snapshot(survivor), oracle_n(boards[survivor], 4))
    # Idle pool has nothing left to compact.
    assert pool.maybe_compact() is None


# ----------------------------------------------------------- spill tier


def test_lru_spill_and_revival_under_hard_budget(rng):
    # Budget = exactly one 16x16 slab; an 8x8 arrival must spill the
    # whole LRU slab to host before its own slab fits.
    pool = SessionPool(device_budget_bytes=16 * 16 * 4)
    boards = {sid: _board(rng) for sid in ("a", "b", "c")}
    for sid, b in boards.items():
        pool.create(sid, b)
    small = (rng.random((8, 8)) < 0.35).astype(np.uint8)
    pool.create("d", small)
    st = pool.stats()
    assert st["spilled"] == 3 and st["resident"] == 1
    assert st["spills"] == 3
    assert pool.device_bytes() <= 16 * 16 * 4
    # Spilled sessions still snapshot (host copy, no revival)...
    for sid, b in boards.items():
        np.testing.assert_array_equal(pool.snapshot(sid), b)
    assert pool.stats()["revivals"] == 0
    # ...and stepping one revives it (miss + revival), evicting the
    # now-LRU 8x8 tenant to stay under budget.
    pool.step("a", 2)
    st = pool.stats()
    assert st["revivals"] == 1 and st["misses"] == 1
    np.testing.assert_array_equal(pool.snapshot("a"), oracle_n(boards["a"], 2))
    np.testing.assert_array_equal(pool.snapshot("d"), small)
    # A board no budget can hold is a refusal, not a wrong answer.
    with pytest.raises(PoolError, match="budget"):
        pool.create("big", (rng.random((64, 64)) < 0.35).astype(np.uint8))


# ------------------------------------------------------------ WAL lifecycle


def test_wal_pool_records_roundtrip_and_compaction_carry(tmp_path, rng):
    w = wal.TicketWAL(tmp_path / "p.wal")
    b0, b1 = _board(rng), _board(rng)
    w.pool_create("alpha", b0)
    w.pool_create("beta", b1)
    w.pool_step("alpha", 2)
    w.pool_step("alpha", 3)
    w.pool_snapshot("alpha", 5)
    w.pool_evict("beta")
    w.close()

    rep = wal.replay(tmp_path / "p.wal")
    assert rep.counts()["pool_sessions"] == 1
    entry = rep.pool_sessions["alpha"]
    np.testing.assert_array_equal(entry["board"], b0)
    assert entry["steps"] == 5  # STEP frames sum; snapshot is a no-op

    # Compaction rotation carries the pool: the snapshot stores the
    # host mirror, replay of the rotated journal restores it.
    w2 = wal.TicketWAL(tmp_path / "p.wal")
    w2.compact([], pool_sessions={"alpha": entry})
    w2.pool_step("alpha", 1)
    w2.close()
    rep2 = wal.replay(tmp_path / "p.wal")
    assert rep2.pool_sessions["alpha"]["steps"] == 6
    np.testing.assert_array_equal(rep2.pool_sessions["alpha"]["board"], b0)


def test_wal_pool_record_validation(tmp_path, rng):
    w = wal.TicketWAL(tmp_path / "bad.wal")
    w.pool_create("a", _board(rng))
    w.pool_create("a", _board(rng))  # dup-live: replay must refuse
    w.close()
    with pytest.raises(ValueError, match="re-creates live pool session"):
        wal.replay(tmp_path / "bad.wal")

    w = wal.TicketWAL(tmp_path / "bad2.wal")
    w.pool_step("ghost", 2)
    w.close()
    with pytest.raises(ValueError, match="unknown pool session"):
        wal.replay(tmp_path / "bad2.wal")


def test_daemon_resume_rematerializes_pool(tmp_path, rng):
    walp = str(tmp_path / "d.wal")
    dm = ServingDaemon(ServePolicy(max_batch=4, max_wait_s=0.0),
                       wal_path=walp)
    boards = {f"w{i}": _board(rng, 12) for i in range(5)}
    for sid, b in boards.items():
        dm.create_session(sid, b)
    tickets = [dm.submit_session(sid, 2) for sid in boards]
    dm.pump(drain=True)
    assert all(t.state == DONE for t in tickets)
    assert all(t.engine == "pool:bitsliced" for t in tickets)
    dm.step_session("w0", 3)
    dm.evict_session("w4")
    dm._wal.sync()

    dm2, source, detail = ServingDaemon.resume_any(
        wal_path=walp, policy=ServePolicy(max_batch=4, max_wait_s=0.0))
    assert source == "wal"
    assert detail["wal_replay"]["pool_sessions"] == 4
    assert sorted(dm2.sessions()) == ["w0", "w1", "w2", "w3"]
    for sid in dm2.sessions():
        steps = 2 + (3 if sid == "w0" else 0)
        np.testing.assert_array_equal(
            dm2.snapshot_session(sid), oracle_n(boards[sid], steps))
    s = dm2.summary()
    assert s["pool_sessions"] == 4


def test_submit_session_depth_gate_and_unknown(rng):
    dm = ServingDaemon(ServePolicy(max_batch=4, max_depth=2,
                                   max_wait_s=0.0))
    with pytest.raises(ValueError, match="unknown session"):
        dm.submit_session("ghost", 1)
    for i in range(4):
        dm.create_session(f"s{i}", _board(rng, 12))
    states = [dm.submit_session(f"s{i}", 2).state for i in range(4)]
    # Depth 2: two admitted, two door-shed with the policy reason —
    # and a shed resident step never touches the journal or the pool.
    assert states.count(PENDING) == 2 and states.count(SHED) == 2
    dm.pump(drain=True)
    for i in range(4):
        steps = 2 if states[i] == PENDING else 0
        np.testing.assert_array_equal(
            dm.snapshot_session(f"s{i}"),
            oracle_n(dm._session_log[f"s{i}"]["board"], steps))


def test_concurrent_steps_same_session_all_apply(rng):
    # Open-loop traffic parks several steps for ONE session in the same
    # bucket before any pump reaches them. `step_group` ORs lanes into
    # a dispatch mask, so duplicate sessions in one chunk would collapse
    # to a single advance while every ticket resolves DONE — the daemon
    # must split such a chunk into waves of distinct sessions (the
    # loadgen parity gate caught exactly this).
    dm = ServingDaemon(ServePolicy(max_batch=8, max_wait_s=0.0))
    b0, b1 = _board(rng, 16), _board(rng, 16)
    dm.create_session("dup", b0)
    dm.create_session("other", b1)
    tks = [dm.submit_session("dup", 3), dm.submit_session("other", 3),
           dm.submit_session("dup", 3), dm.submit_session("dup", 3)]
    dm.pump(drain=True)
    assert all(t.state == DONE for t in tks)
    np.testing.assert_array_equal(dm.snapshot_session("dup"),
                                  oracle_n(b0, 9))
    np.testing.assert_array_equal(dm.snapshot_session("other"),
                                  oracle_n(b1, 3))


# ------------------------------------------------------------- crash matrix


#: (site, k): where the injected ``os._exit(137)`` lands in the pool
#: lifecycle driver (4 sessions -> 4 creates, 8 steps, 1 snapshot,
#: 1 evict). Every pool site fires AFTER its frame is journaled, BEFORE
#: the pool acts; mid-frame tears a frame mid-write.
POOL_CRASH_CELLS = [("post-create", 3), ("post-step", 5),
                    ("post-snapshot", 1), ("post-evict", 1),
                    ("mid-frame", 6)]


@pytest.mark.parametrize("site,k", POOL_CRASH_CELLS)
def test_pool_crash_matrix_resume_parity(tmp_path, site, k):
    """The residency acceptance gate: a real subprocess daemon running
    the handle lifecycle is hard-killed at every pool chaos site under
    ``every-record`` fsync. Every ACKED op must be durable (creates
    present unless acked-evicted, step sums at least the acked sum —
    at-least-once allows ONE journaled-but-unacked op), and resume must
    re-materialize every surviving session bit-identical to the NumPy
    oracle replay of its journal."""
    walp = str(tmp_path / "pool.wal")
    ackp = str(tmp_path / "acked.ops")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MOMP_CHAOS=f"crash={site}:{k}")
    proc = subprocess.run(
        [sys.executable, DRIVER, walp, "every-record", ackp, "4", "pool"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == chaos.CRASH_EXIT == 137, (
        f"crash never fired: rc={proc.returncode} "
        f"out={proc.stdout!r} err={proc.stderr!r}")

    acked = [ln.split() for ln in open(ackp).read().splitlines() if ln]
    assert acked, "driver acked nothing — the cell tested nothing"
    acked_creates = {op[1] for op in acked if op[0] == "C"}
    acked_evicts = {op[1] for op in acked if op[0] == "E"}
    acked_steps: dict[str, int] = {}
    for op in acked:
        if op[0] == "S":
            acked_steps[op[1]] = acked_steps.get(op[1], 0) + int(op[2])

    rep = wal.replay(walp)
    missing = [sid for sid in acked_creates - acked_evicts
               if sid not in rep.pool_sessions]
    # At most ONE journaled-but-unacked EVICT can outrun its ack (the
    # post-evict cell: frame durable, kill before the ack write).
    assert len(missing) <= 1, (site, missing)
    for sid in acked_evicts:
        assert sid not in rep.pool_sessions, (site, sid)
    for sid, steps in acked_steps.items():
        if sid in rep.pool_sessions:
            got = rep.pool_sessions[sid]["steps"]
            # Exactly the acked sum, or one unacked journaled op more
            # (journal-first: the crash landed between frame and ack).
            assert got in (steps, steps + 2), (site, sid, got, steps)

    d, source, _ = ServingDaemon.resume_any(
        wal_path=walp, policy=ServePolicy(max_batch=4, max_wait_s=0.0))
    assert source == "wal"
    assert sorted(d.sessions()) == sorted(rep.pool_sessions)
    for sid, entry in rep.pool_sessions.items():
        np.testing.assert_array_equal(
            d.snapshot_session(sid),
            oracle_n(np.asarray(entry["board"]), int(entry["steps"])))


def test_pool_driver_clean_run(tmp_path):
    """No chaos plan: the pool driver drains clean, proving the matrix
    cells fail for the right reason (the kill, not the workload)."""
    walp = str(tmp_path / "clean.wal")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("MOMP_CHAOS", None)
    proc = subprocess.run(
        [sys.executable, DRIVER, walp, "every-record",
         str(tmp_path / "a.ops"), "4", "pool"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-800:]
    assert json.loads(proc.stdout.strip().splitlines()[-1])["sessions"] == 3
    rep = wal.replay(walp)
    assert sorted(rep.pool_sessions) == ["p0", "p1", "p2"]
    assert all(e["steps"] == 4 for e in rep.pool_sessions.values())


# ------------------------------------------------------- batcher coalescing


def test_batcher_coalesces_small_session_groups(rng):
    """Satellite: resident steps below BITSLICE_MIN_BATCH coalesce into
    slab-group dispatches — 3 sessions are ONE pool dispatch, and a
    later lone-session flush reuses the SAME compiled program (the mask
    is runtime data)."""
    metrics.reset()
    pool = SessionPool()
    # Fresh shape (see the one-compiled-program test): retrace counters
    # only tick on a genuinely new trace.
    boards = {f"s{i}": _board(rng, 40) for i in range(3)}
    for sid, b in boards.items():
        pool.create(sid, b)
    bat = ShapeBucketBatcher(max_batch=8, pool=pool)
    extra = _board(rng, 40)
    t_board = bat.submit(extra, 2)
    tks = [bat.submit_session(sid, 2) for sid in boards]
    assert len(bat) == 4
    assert ("slab", 0, 2) in bat.bucket_keys()

    out = bat.flush()
    # Submission-order results: the shipped board's result in place,
    # None for resident steps (the board stayed on device).
    assert np.array_equal(out[t_board], oracle_n(extra, 2))
    assert all(out[t] is None for t in tks)
    pool_stats = [s for s in bat.last_flush_stats if s.path == "pool"]
    assert len(pool_stats) == 1 and pool_stats[0].requests == 3
    for sid, b in boards.items():
        np.testing.assert_array_equal(pool.snapshot(sid), oracle_n(b, 2))

    bat.submit_session("s0", 2)  # lone resident step, second flush
    bat.flush()
    assert metrics.get("jit.retrace", fn="pool_step") == 1

    with pytest.raises(ValueError, match="unknown session"):
        bat.submit_session("ghost", 1)
    with pytest.raises(ValueError, match="no session pool"):
        ShapeBucketBatcher().submit_session("s0", 1)


# ---------------------------------------------------------- settled skip


def _still_life(n):
    b = np.zeros((n, n), np.uint8)
    b[n // 2:n // 2 + 2, n // 2:n // 2 + 2] = 1  # block
    return b


def _blinker(n):
    b = np.zeros((n, n), np.uint8)
    b[n // 2, n // 2 - 1:n // 2 + 2] = 1
    return b


def test_settled_group_skips_dispatch():
    """PR 16 satellite: once every session in a slab group is a proven
    fixed point, STEP dispatches stop. steps_applied still advances
    (the WAL contract: journaled steps are authoritative), snapshots
    stay bit-exact, and the skip is counted."""
    pool = SessionPool()
    boards = {f"q{i}": _still_life(18) for i in range(3)}
    for sid, b in boards.items():
        pool.create(sid, b)
    sids = list(boards)
    assert pool.step_group(sids, 2) == 1      # dispatch proves the point
    assert pool.counts["settled_skips"] == 0  # word resolves lazily
    # The next group step resolves the deferred word FIRST, sees every
    # lane settled, and skips without ever dispatching again.
    assert pool.step_group(sids, 2) == 0
    assert pool.step_group(sids, 2) == 0
    assert pool.counts["settled_skips"] == 2
    assert pool.counts["steps_applied"] == 18  # 3 sessions x 6 steps
    for sid, b in boards.items():
        np.testing.assert_array_equal(pool.snapshot(sid), b)


def test_oscillator_never_reads_as_settled():
    """The (prev, cur) carry in the step program: a period-2 blinker
    stepped an EVEN number of steps returns to its start — an
    initial-vs-final diff would call it settled; the consecutive-state
    proof must not."""
    pool = SessionPool()
    pool.create("osc", _blinker(18))
    for _ in range(4):
        assert pool.step_group(["osc"], 2) == 1  # never skipped
    assert pool.counts["settled_skips"] == 0
    np.testing.assert_array_equal(
        pool.snapshot("osc"), oracle_n(_blinker(18), 8))


def test_mixed_slab_group_never_skips():
    """One live session in the group holds the whole dispatch: the
    settled block rides along (lane-masked) and stays bit-exact."""
    pool = SessionPool()
    pool.create("still", _still_life(20))
    pool.create("osc", _blinker(20))
    sids = ["still", "osc"]
    for _ in range(3):
        assert pool.step_group(sids, 2) == 1
    assert pool.counts["settled_skips"] == 0
    np.testing.assert_array_equal(pool.snapshot("still"), _still_life(20))
    np.testing.assert_array_equal(
        pool.snapshot("osc"), oracle_n(_blinker(20), 6))


def test_settled_session_crash_resume_parity(tmp_path):
    """kill -9 with the skip engaged: the driver's still-life p0 stops
    dispatching after its fixed point is proven, then the process dies
    at a post-step chaos site. The WAL's STEP frames are authoritative:
    replay + resume must re-prove settledness and land p0 (and every
    survivor) bit-identical to the oracle at the acked step count —
    steps that were never dispatched pre-kill included."""
    walp = str(tmp_path / "settled.wal")
    ackp = str(tmp_path / "acked.ops")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MOMP_CHAOS="crash=post-step:15")
    proc = subprocess.run(
        [sys.executable, DRIVER, walp, "every-record", ackp, "4",
         "settled"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == chaos.CRASH_EXIT == 137, (
        f"crash never fired: rc={proc.returncode} "
        f"out={proc.stdout!r} err={proc.stderr!r}")
    acked_steps: dict[str, int] = {}
    for ln in open(ackp).read().splitlines():
        op = ln.split()
        if op and op[0] == "S":
            acked_steps[op[1]] = acked_steps.get(op[1], 0) + int(op[2])
    assert acked_steps.get("p0", 0) >= 6, "skip never got to engage"

    rep = wal.replay(walp)
    d, source, _ = ServingDaemon.resume_any(
        wal_path=walp, policy=ServePolicy(max_batch=4, max_wait_s=0.0))
    assert source == "wal"
    for sid, entry in rep.pool_sessions.items():
        np.testing.assert_array_equal(
            d.snapshot_session(sid),
            oracle_n(np.asarray(entry["board"]), int(entry["steps"])))
    # The resumed daemon surfaces the skip counter (summary plumbing).
    assert "pool_settled_skips" in d.summary()


# ------------------------------------------------- sentinel/ledger plumbing


def test_sentinel_polarity_and_ledger_resident_key():
    sys.path.insert(0, os.path.join(REPO, "analysis"))
    import regression_sentinel as sentinel

    from mpi_and_open_mp_tpu.obs import ledger

    assert sentinel.direction_for("session_requests_per_sec") == "higher"
    assert sentinel.direction_for("session_vs_ship") == "higher"
    assert sentinel.direction_for("session_p99_latency_s") == "lower"
    assert sentinel.direction_for("pool_evictions") == "lower"
    for f in ("session_requests_per_sec", "session_vs_ship",
              "session_p99_latency_s", "pool_evictions"):
        assert f in sentinel.WATCH_FIELDS
    assert "resident" in sentinel.DEFAULT_MATCH
    assert "resident" in ledger.KEY_FIELDS

    # A resident line and a ship line must land in different baseline
    # groups; a PRE-resident historical entry (no key field at all)
    # must keep matching new non-resident lines.
    pool_line = ledger.stamp({"metric": "m", "resident": "pool"})
    ship_line = ledger.stamp({"metric": "m"})
    old_line = {"key": {k: v for k, v in ship_line["key"].items()
                        if k != "resident"}}
    match = tuple(sentinel.DEFAULT_MATCH)
    assert (ledger.config_key(pool_line, match)
            != ledger.config_key(ship_line, match))
    assert (ledger.config_key(old_line, match)
            == ledger.config_key(ship_line, match))
