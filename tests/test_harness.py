"""Harness layer: config suite semantics, plot scripts, hello app, launchers."""

import os
import subprocess
import sys

import numpy as np
import pytest

from mpi_and_open_mp_tpu.apps import hello as hello_app
from mpi_and_open_mp_tpu.utils.config import load_config_py

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONFIGS = os.path.join(REPO, "configs")


from conftest import oracle_n  # noqa: E402


def test_config_suite_present_and_parsable():
    expected = {
        "test_10x10.cfg": (10, 10, 0),
        "glider_10x10.cfg": (10, 10, 5),
        "mix_40x20.cfg": (40, 20, 18),
        "pulsar_field_500x500.cfg": (500, 500, 64 * 48),
        "gun_300x100.cfg": (300, 100, 36),
        "gun_big_500x500.cfg": (500, 500, None),
    }
    for name, (nx, ny, ncells) in expected.items():
        cfg = load_config_py(os.path.join(CONFIGS, name))
        assert (cfg.nx, cfg.ny) == (nx, ny), name
        if ncells is not None:
            assert len(cfg.cells) == ncells, name


def test_pulsar_field_period_3():
    cfg = load_config_py(os.path.join(CONFIGS, "pulsar_field_500x500.cfg"))
    b0 = cfg.board()
    assert not np.array_equal(oracle_n(b0, 1), b0)
    np.testing.assert_array_equal(oracle_n(b0, 3), b0)


def test_gosper_gun_emits_gliders():
    cfg = load_config_py(os.path.join(CONFIGS, "gun_300x100.cfg"))
    b0 = cfg.board()
    pop0 = b0.sum()
    pop120 = oracle_n(b0, 120).sum()
    # Period-30 gun: 4 gliders after 120 steps -> +20 cells.
    assert pop120 == pop0 + 4 * 5


def test_gun_full_1000_step_parity():
    """The gun fixture at its FULL configured step budget (SURVEY §4: the
    reference's p46gun runs 1000 steps) through the sharded 2-D engine —
    the longest-horizon parity gate in the suite. By step 1000 the gun's
    glider stream has wrapped the torus and collided with the gun itself,
    so this also exercises long-range wrap interactions."""
    from mpi_and_open_mp_tpu.models.life import LifeSim
    from mpi_and_open_mp_tpu.parallel import mesh as mesh_lib

    cfg = load_config_py(os.path.join(CONFIGS, "gun_300x100.cfg"))
    assert cfg.steps == 1000
    sim = LifeSim(cfg, layout="cart", impl="halo",
                  mesh=mesh_lib.make_mesh_2d(4, 2), fuse_steps=4)
    final = sim.run(save=False)
    np.testing.assert_array_equal(final, oracle_n(cfg.board(), 1000))


def test_mix_still_lifes_stable_block():
    cfg = load_config_py(os.path.join(CONFIGS, "mix_40x20.cfg"))
    b = oracle_n(cfg.board(), 4)
    # The block at (2..3, 2..3) must be untouched.
    assert b[2:4, 2:4].sum() == 4


def test_plot_life_script(tmp_path):
    times = tmp_path / "times.txt"
    times.write_text("30.0\n16.0\nCommand exited with non-zero status 1\n8.0\n")
    out = tmp_path / "accel.png"
    sys.path.insert(0, os.path.join(REPO, "analysis"))
    import plot_life

    rc = plot_life.main([str(times), str(out)])
    assert rc == 0 and out.exists() and out.stat().st_size > 1000
    np.testing.assert_allclose(plot_life.load_times(times), [30.0, 16.0, 8.0])


def test_plot_network_script(tmp_path, monkeypatch, capsys):
    csv = tmp_path / "probe.csv"
    csv.write_text("size,time\n1,2.5\n1000,3.5\n1000000,1002.5\n")
    sys.path.insert(0, os.path.join(REPO, "analysis"))
    import plot_network

    monkeypatch.chdir(tmp_path)
    rc = plot_network.main([str(csv)])
    assert rc == 0
    assert (tmp_path / "network_params.png").exists()
    out = capsys.readouterr().out
    assert "alpha=" in out and "r2=" in out


def test_plot_integral_script(tmp_path):
    """Integral speedup analog of the reference's integral_plots.ipynb
    cells 1-2: raw times + T1/TN accel PNGs from a times file, tolerant
    of gtime error lines."""
    times = tmp_path / "integral_out.txt"
    times.write_text(
        "120.4\n61.0\nCommand exited with non-zero status 1\n31.2\n")
    sys.path.insert(0, os.path.join(REPO, "analysis"))
    import plot_integral

    prefix = tmp_path / "integral_plot"
    rc = plot_integral.main([str(times), str(prefix)])
    assert rc == 0
    for suffix in (".png", "_accel.png"):
        p = tmp_path / f"integral_plot{suffix}"
        assert p.exists() and p.stat().st_size > 1000


def test_plot_bigboard_script(tmp_path):
    csv = tmp_path / "bb.csv"
    csv.write_text(
        "n,steps,path,steady_us_per_step,steady_gcups,differenced\n"
        "500,1000,vmem,0.2,1200.0,1\n"
        "2048,500,fused,2.0,2100.0,1\n"
        "9000,100,frame,60.0,1350.0,1\n")
    out = tmp_path / "bb.png"
    sys.path.insert(0, os.path.join(REPO, "analysis"))
    import plot_bigboard

    rc = plot_bigboard.main(["plot_bigboard", str(csv), str(out)])
    assert rc == 0 and out.exists() and out.stat().st_size > 1000


def test_plot_attention_script_with_and_without_bwd(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "analysis"))
    import plot_attention

    full = tmp_path / "att.csv"
    # New-schema CSV (trailing engine column, possibly mixed mid-sweep).
    full.write_text(
        "seq,fwd_sec,fwd_tflops,bwd_sec,bwd_tflops,differenced,engine\n"
        "8192,0.003,48.0,0.010,47.0,1,pallas\n"
        "16384,0.012,46.0,0.042,45.0,1,jnp\n")
    out = tmp_path / "att.png"
    rc = plot_attention.main(["plot_attention", str(full), str(out)])
    assert rc == 0 and out.stat().st_size > 1000
    # All-forward CSV (e.g. --bwd-max 0): must render, not crash.
    fwd_only = tmp_path / "att_f.csv"
    fwd_only.write_text("seq,fwd_sec,fwd_tflops,bwd_sec,bwd_tflops,"
                        "differenced\n8192,0.003,48.0,,,1\n")
    out2 = tmp_path / "att_f.png"
    rc = plot_attention.main(["plot_attention", str(fwd_only), str(out2)])
    assert rc == 0 and out2.stat().st_size > 1000


def test_sweep_scripts_refuse_off_tpu(tmp_path):
    """The real-chip sweep recorders must refuse to record from a CPU
    backend rather than committing dishonest numbers."""
    sys.path.insert(0, os.path.join(REPO, "analysis"))
    import sweep_attention
    import sweep_bigboard

    for mod in (sweep_bigboard, sweep_attention):
        rc = mod.main(["--out", str(tmp_path / "x.csv")])
        assert rc == 1
        assert not (tmp_path / "x.csv").exists()
    # GQA flag validation fires before the backend refusal; 0 and
    # negative "divisors" are rejected too (0 would silently record a
    # full-MHA sweep under a GQA label).
    for bad in ("3", "0", "-2"):
        rc = sweep_attention.main(
            ["--kv-heads", bad, "--out", str(tmp_path / "x.csv")])
        assert rc == 2


def test_bench_cpu_end_to_end(capsys, monkeypatch):
    """The driver-contract bench runs end-to-end through its CPU
    fallback and prints one valid JSON line with the promised schema
    (the TPU-only sharded/attention extras rightly absent). The
    device-discovery probe is stubbed to fail: the suite must never
    claim (or hang on) the real chip, and the fallback line — bench's
    behaviour on a wedged relay — is exactly what's under test."""
    import json

    sys.path.insert(0, REPO)
    import bench

    monkeypatch.setattr(
        bench, "_probe_devices",
        lambda timeout_s: (False, "stubbed: probe denied"))
    rc = bench.main(["--board", "64", "--steps", "64"])
    assert rc == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(line)
    assert rec["metric"] == "life_steady_cups_p46gun_big"
    assert rec["unit"] == "cell_updates_per_sec"
    assert rec["value"] > 0 and rec["vs_baseline"] > 0
    assert rec["backend"] == "cpu"
    assert "not a TPU measurement" in rec["backend_fallback"]
    # The fallback must point the reader at the committed chip record —
    # and the path it names must actually exist in the repo.
    assert "chip_record" in rec
    named = rec["chip_record"].split()[0]
    assert os.path.exists(os.path.join(REPO, named)), named
    assert "error" not in rec and "sharded_steady_cups" not in rec
    # The ring-hop engine provenance (fwd / bwd / zigzag) rides EVERY
    # line, CPU fallback included — honest "jnp"-family stamps here.
    for key in ("attention_hop_engine", "attention_hop_engine_bwd",
                "attention_hop_engine_zz"):
        stamp = rec[key]
        assert stamp == "jnp" or stamp.startswith(("local:", "pallas:")), (
            key, stamp)


def test_native_path_matches_dispatcher_gates():
    """native_path is the single source of truth the sweeps label rows
    with; pin its decisions at the regime boundaries."""
    from mpi_and_open_mp_tpu.ops.pallas_life import native_path

    assert native_path((500, 500)) == "vmem"
    assert native_path((3072, 3072)) == "vmem"
    assert native_path((8192, 8192)) == "fused"
    assert native_path((10000, 10000)) == "frame"  # ny % 32 != 0
    assert native_path((8192, 8192), on_tpu=False) == "xla"


def test_hello_app(capsys):
    rc = hello_app.main(["--devices", "8"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "ring ok" in out
    assert "device 3 received hello from device 2" in out


def test_run_life_launcher_virtual(tmp_path):
    """End-to-end launcher sweep on the virtual CPU mesh (2 points)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + ":" + env.get("PYTHONPATH", "")
    times_path = tmp_path / "times.txt"
    r = subprocess.run(
        ["bash", os.path.join(REPO, "launchers", "run_life.sh"),
         "--cfg=configs/glider_10x10.cfg", "--max-dev=2", "--virtual",
         "--layout=row", f"--times-file={times_path}"],
        cwd=tmp_path, env=env, capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stderr
    lines = [l for l in times_path.read_text().strip().split("\n") if l]
    assert len(lines) == 2
    for l in lines:
        float(l)


def test_committed_results_layer_parses():
    """The recorded-measurement artifacts under results/ (the analogue of
    the reference's committed times.txt / out_*.csv) must stay consumable
    by the analysis layer."""
    sys.path.insert(0, os.path.join(REPO, "analysis"))
    import plot_life
    import plot_network

    results = os.path.join(REPO, "results")
    for rel in ("life/times_virtual8.txt", "life/times_job2.txt",
                "life/times_job2_fuse10.txt", "integral/times_virtual8.txt"):
        times = plot_life.load_times(os.path.join(results, rel))
        assert len(times) >= 2 and (times > 0).all(), rel
    for rel in ("network/out_single.csv", "network/out_mult.csv",
                "network/out_tpu_loopback.csv"):
        rows = plot_network.load_csv(os.path.join(results, rel))
        assert len(rows) == 7 and rows[0][0] == 1, rel
        assert all(t > 0 for _, t in rows), rel
    import csv as csv_mod

    for rel, col in (("life/bigboard_tpu.csv", "steady_gcups"),
                     ("attention/attention_tpu.csv", "fwd_tflops"),
                     ("attention/attention_gqa_tpu.csv", "fwd_tflops")):
        with open(os.path.join(results, rel)) as f:
            rows = list(csv_mod.DictReader(f))
        assert rows and all(float(r[col]) > 0 for r in rows), rel
    for png in ("life/life_accel_virtual8.png", "network/network_params.png",
                "life/bigboard_tpu.png", "attention/attention_tpu.png",
                "attention/attention_gqa_tpu.png"):
        assert os.path.getsize(os.path.join(results, png)) > 1000, png


def test_mpi_baseline_serial_oracle_builds_and_matches():
    """mpi_baseline/Makefile must compile the reference's serial oracle
    from the read-only reference tree and its VTK output must agree with
    this framework's oracle — the self-contained --backend=mpi
    prerequisite (SURVEY §7 step 7). MPI binaries need mpicc (absent in
    this image); the serial target proves the build plumbing."""
    import shutil
    import tempfile

    ref = "/root/reference"
    if not os.path.isdir(ref):
        pytest.skip("reference tree not present")
    repo = REPO
    r = subprocess.run(
        ["make", "-C", os.path.join(repo, "mpi_baseline"), "life2d",
         f"REF_DIR={ref}"],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stderr
    binary = os.path.join(repo, "mpi_baseline", "build", "life2d")
    with tempfile.TemporaryDirectory() as tmp:
        cfg_path = os.path.join(repo, "configs", "glider_10x10.cfg")
        shutil.copy(cfg_path, tmp)
        r = subprocess.run(
            [binary, "glider_10x10.cfg"], cwd=tmp,
            capture_output=True, text=True, timeout=120,
        )
        assert r.returncode == 0, r.stderr
        from mpi_and_open_mp_tpu.utils.vtk import read_vtk

        cfg = load_config_py(cfg_path)
        got = read_vtk(os.path.join(tmp, "life_000075.vtk"))
        np.testing.assert_array_equal(got, oracle_n(cfg.board(), 75))
