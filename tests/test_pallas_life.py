"""Pallas kernel parity (interpret mode on the CPU test mesh)."""

import numpy as np
import pytest

import jax.numpy as jnp

from mpi_and_open_mp_tpu.models.life import LifeSim
from mpi_and_open_mp_tpu.ops import pallas_life
from mpi_and_open_mp_tpu.ops.life_ops import (
    life_step_numpy,
    pad_x_wrap,
    pad_y_wrap,
)
from mpi_and_open_mp_tpu.utils.config import config_from_board


from conftest import oracle_n  # noqa: E402


@pytest.mark.parametrize("shape,steps", [((16, 16), 8), ((10, 10), 40), ((33, 65), 5)])
def test_vmem_kernel_matches_oracle(make_board, shape, steps):
    b = make_board(*shape)
    out = pallas_life.life_run_vmem(jnp.asarray(b), steps)
    np.testing.assert_array_equal(np.asarray(out), oracle_n(b, steps))
    assert out.dtype == jnp.asarray(b).dtype


def test_vmem_kernel_runtime_step_count_no_recompile(make_board):
    """steps is an SMEM scalar: same compiled kernel for different n."""
    b = jnp.asarray(make_board(16, 16))
    o1 = pallas_life.life_run_vmem(b, 1)
    o3 = pallas_life.life_run_vmem(b, 3)
    np.testing.assert_array_equal(np.asarray(o3), oracle_n(b, 3))
    np.testing.assert_array_equal(np.asarray(o1), oracle_n(b, 1))


def test_vmem_fallback_large_board(make_board):
    from mpi_and_open_mp_tpu.ops import bitlife

    big = (3400, 3400)  # packed bytes > _PACKED_VMEM_LIMIT -> XLA packed loop
    assert not bitlife.fits_vmem_packed(big)
    b = make_board(*big, density=0.2)
    out = pallas_life.life_run_vmem(jnp.asarray(b), 2)
    np.testing.assert_array_equal(np.asarray(out), oracle_n(b, 2))


def test_padded_pallas_step(make_board):
    b = make_board(12, 20)
    padded = pad_x_wrap(pad_y_wrap(jnp.asarray(b)))
    out = pallas_life.life_step_padded_pallas(padded)
    np.testing.assert_array_equal(np.asarray(out), life_step_numpy(b))


@pytest.mark.parametrize("layout", ["row", "col", "cart"])
def test_lifesim_pallas_impl_sharded(make_board, layout):
    board = make_board(48, 40)
    cfg = config_from_board(board, steps=10, save_steps=1000)
    sim = LifeSim(cfg, layout=layout, impl="pallas")
    sim.step(10)
    np.testing.assert_array_equal(sim.collect(), oracle_n(board, 10))


def test_lifesim_pallas_serial(make_board):
    board = make_board(24, 24)
    cfg = config_from_board(board, steps=12, save_steps=1000)
    sim = LifeSim(cfg, layout="serial", impl="pallas")
    sim.step(12)
    np.testing.assert_array_equal(sim.collect(), oracle_n(board, 12))
