"""Serving daemon: admission, deadlines, supervision ladder, drain/resume.

The fault-tolerant serving layer (``serve.policy`` / ``serve.queue`` /
``serve.daemon``) on the 8-virtual-device CPU mesh, against the NumPy
oracle throughout. The contracts under test: a rejected request carries
an explicit shed reason (never silently dropped); a bucket that never
fills still flushes at its max-wait deadline; results hold ticket order
under interleaved buckets; a chaos-injected dispatch fault degrades down
the engine ladder with ``:recovered`` provenance and oracle-exact output;
retry exhaustion and per-request timeouts shed with their own reasons;
a preemption (chaos plan or SIGTERM via the CLI) checkpoints the pending
queue, exits 75, and ``--resume`` restores every admitted ticket — zero
loss across the process boundary; and the chaos soak: every admitted
ticket ends in a result or an explicit shed, requests == resolved + shed.
"""

import json
import os
import sys

import numpy as np
import pytest

from conftest import oracle_n
from mpi_and_open_mp_tpu.robust import chaos, guards, preempt
from mpi_and_open_mp_tpu.serve import (
    SHED_REASONS,
    ServePolicy,
    ServeQueue,
    ServingDaemon,
)
from mpi_and_open_mp_tpu.serve import policy as policy_mod
from mpi_and_open_mp_tpu.serve.queue import DONE, PENDING, SHED

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.reset()
    guards.clear_recovery_log()
    yield
    chaos.reset()
    guards.clear_recovery_log()


class FakeClock:
    """Deterministic monotonic clock; ``sleep`` advances it."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def sleep(self, s: float) -> None:
        self.t += s


def _daemon(policy, clk=None, **kw) -> tuple[ServingDaemon, FakeClock]:
    clk = clk or FakeClock()
    return ServingDaemon(policy, clock=clk, sleep=clk.sleep, **kw), clk


# ------------------------------------------------------------------ policy


def test_padding_waste_math():
    pw = policy_mod.padding_waste
    assert pw([], 8) == 0.0
    assert pw([8], 8) == 0.0  # a full chunk wastes nothing
    assert pw([3], 8) == pytest.approx(1 / 4)  # 3 live in a pow2-4 pad
    assert pw([5], 8) == pytest.approx(3 / 8)
    # 11 = one full 8-chunk + a 3-remainder padded to 4.
    assert pw([11], 8) == pytest.approx(1 / 12)
    assert pw([8, 3], 8) == pytest.approx(1 / 12)  # two buckets, same sum


def test_admit_depth_then_padding():
    pol = ServePolicy(max_batch=8, max_depth=4, max_padding_frac=0.2)
    assert policy_mod.admit(pol, 0, [1]) is None
    assert policy_mod.admit(pol, 4, [5]) == policy_mod.SHED_DEPTH
    # 3 pending in one bucket pads to 4: waste 0.25 > 0.2.
    assert policy_mod.admit(pol, 2, [3]) == policy_mod.SHED_PADDING


def test_percentile_nearest_rank():
    pct = policy_mod.percentile
    assert pct([], 99) == 0.0
    xs = [float(i) for i in range(1, 101)]
    assert pct(xs, 50) == 50.0
    assert pct(xs, 99) == 99.0
    assert pct(xs, 100) == 100.0
    assert pct([7.0], 99) == 7.0


def test_policy_validation():
    with pytest.raises(ValueError, match="max_batch"):
        ServePolicy(max_batch=0)
    with pytest.raises(ValueError, match="max_padding_frac"):
        ServePolicy(max_padding_frac=1.5)
    with pytest.raises(ValueError, match="max_wait_s"):
        ServePolicy(max_wait_s=-1.0)


# ------------------------------------------------------------------- queue


def test_queue_admission_sheds_with_reason(make_board):
    q = ServeQueue(ServePolicy(max_batch=8, max_depth=2))
    t0 = q.submit(make_board(16, 16), 2, now=0.0)
    t1 = q.submit(make_board(16, 16), 2, now=0.0)
    t2 = q.submit(make_board(16, 16), 2, now=0.0)
    assert t0.state == t1.state == PENDING
    assert t2.state == SHED and t2.reason == policy_mod.SHED_DEPTH
    assert q.depth() == 2
    # The rejected ticket is still on the ledger: nothing silently drops.
    assert len(q.tickets()) == 3


def test_queue_deadline_and_chunk_order(make_board):
    """A full chunk is always due; a remainder waits for max_wait; chunks
    come out oldest-lead-ticket first across interleaved buckets."""
    q = ServeQueue(ServePolicy(max_batch=4, max_wait_s=1.0))
    q.submit(make_board(8, 8), 2, now=0.0)  # ticket 0: the starved bucket
    for _ in range(4):  # tickets 1-4: a full chunk of the other shape
        q.submit(make_board(16, 16), 2, now=0.5)
    chunks = q.due_chunks(now=0.6)
    assert [len(c) for c in chunks] == [4]  # remainder not yet due
    assert q.next_deadline() == 1.0
    chunks = q.due_chunks(now=1.0)
    assert [[t.id for t in c] for c in chunks] == [[0], [1, 2, 3, 4]]
    assert q.due_chunks(now=0.0, drain=True)  # drain ignores deadlines


def test_queue_snapshot_restore_roundtrip_and_rejects_foreign(make_board):
    q = ServeQueue(ServePolicy())
    boards = [make_board(12, 12) for _ in range(3)]
    for b in boards:
        q.submit(b, 5, now=0.0)
    snap = q.snapshot()
    q2 = ServeQueue(ServePolicy())
    restored = q2.restore(snap, now=7.0)
    assert [t.steps for t in restored] == [5, 5, 5]
    assert all(t.resumed and t.submitted_at == 7.0 for t in restored)
    for t, b in zip(restored, boards):
        np.testing.assert_array_equal(t.board, b)
    with pytest.raises(ValueError, match="schema"):
        q2.restore({"schema": "something-else"}, now=0.0)
    with pytest.raises(ValueError, match="malformed"):
        q2.restore({"schema": "momp-serve-queue/1",
                    "pending": [{"id": 1}]}, now=0.0)


def test_queue_snapshot_carries_queued_seconds(make_board):
    """The satellite regression: a ticket that sat queued 3 s before the
    drain must NOT restart its latency clock on resume — the snapshot
    carries cumulative queued seconds and ``latency_s`` keeps counting
    from the FIRST submission."""
    q = ServeQueue(ServePolicy())
    q.submit(make_board(8, 8), 1, now=10.0)
    snap = q.snapshot(now=13.0)  # drained after 3 s queued
    assert snap["pending"][0]["queued_s"] == pytest.approx(3.0)

    q2 = ServeQueue(ServePolicy())
    (t,) = q2.restore(snap, now=0.0)  # fresh process, fresh clock
    assert t.queued_before_s == pytest.approx(3.0)
    q2.resolve(t, t.board, "oracle", now=2.0)
    assert t.latency_s == pytest.approx(5.0)  # 3 s before + 2 s after

    # A second drain/restore keeps accumulating, never resets.
    q2._tickets.clear()
    (t2,) = q2.restore(snap, now=5.0)
    snap2 = q2.snapshot(now=9.0)
    assert snap2["pending"][0]["queued_s"] == pytest.approx(7.0)


# ------------------------------------------------------------------ daemon


def test_daemon_zero_requests_noop():
    d, clk = _daemon(ServePolicy())
    d.serve()
    assert d.pump() == 0
    s = d.summary()
    assert s["requests"] == s["resolved"] == s["shed"] == s["batches"] == 0
    assert s["p50_latency_s"] == s["p99_latency_s"] == 0.0


def test_daemon_never_full_bucket_flushes_at_max_wait(make_board):
    """3 requests into a max_batch=8 bucket: nothing is due at submit
    time; serve() sleeps to the deadline and flushes — the padding-vs-p99
    trade in action."""
    d, clk = _daemon(ServePolicy(max_batch=8, max_wait_s=0.5))
    boards = [make_board(16, 16) for _ in range(3)]
    for b in boards:
        d.submit(b, 4)
    assert d.pump() == 0  # not due yet
    d.serve()
    assert clk.t >= 0.5  # the flush waited for the deadline, not forever
    s = d.summary()
    assert s["resolved"] == 3 and s["shed"] == 0 and s["batches"] == 1
    for t, b in zip(d.queue.tickets(), boards):
        assert t.state == DONE and t.engine == "batch:xla"
        np.testing.assert_array_equal(t.result, oracle_n(b, 4))
    assert s["p99_latency_s"] >= 0.5  # latency includes the bucket wait


def test_daemon_ticket_order_stable_under_interleaved_buckets(make_board):
    """Alternating shapes and step counts: every ticket's result must be
    its OWN board's oracle — no cross-bucket or cross-chunk mixups."""
    d, _ = _daemon(ServePolicy(max_batch=4, max_wait_s=0.0))
    shapes = [(16, 16), (24, 16), (16, 16), (24, 16)]
    subs = []
    for i in range(12):
        ny, nx = shapes[i % len(shapes)]
        b = make_board(ny, nx)
        steps = (i % 3) + 1
        subs.append((b, steps, d.submit(b, steps)))
    d.drain()
    assert [t.id for t in d.queue.tickets()] == list(range(12))
    for b, steps, t in subs:
        assert t.state == DONE
        np.testing.assert_array_equal(
            t.result, oracle_n(b, steps),
            err_msg=f"ticket {t.id} shape {b.shape} steps {steps}")


def test_daemon_degrades_on_chaos_fault_with_provenance(
        monkeypatch, make_board):
    """``serve_fail=1``: the primary engine raises once mid-queue; the
    ladder recovers on the suppressed XLA engine, stamps ``:recovered``,
    funnels through the recovery log, and stays oracle-exact."""
    monkeypatch.setenv("MOMP_CHAOS", "serve_fail=1")
    chaos.reset()
    d, _ = _daemon(ServePolicy(max_batch=4, max_wait_s=0.0))
    boards = [make_board(16, 16) for _ in range(4)]
    for b in boards:
        d.submit(b, 3)
    d.serve()
    s = d.summary()
    assert s["resolved"] == 4 and s["degraded"] == 1 and s["retries"] == 0
    assert list(s["engines"]) == ["batch:xla:recovered"]
    assert guards.recovery_log() == ["serve:batch:xla:recovered"]
    for t, b in zip(d.queue.tickets(), boards):
        np.testing.assert_array_equal(t.result, oracle_n(b, 3))


def test_daemon_retry_exhaustion_sheds_dispatch_failed(make_board):
    d, clk = _daemon(ServePolicy(
        max_batch=4, max_wait_s=0.0, max_retries=1,
        backoff_base_s=0.01, backoff_jitter=0.0, request_timeout_s=100.0))

    def boom():
        raise RuntimeError("wedged engine")

    d._engines = lambda stack, steps: [("a", boom), ("b", boom)]
    tickets = [d.submit(make_board(8, 8), 1) for _ in range(2)]
    d.serve()
    s = d.summary()
    assert s["resolved"] == 0 and s["shed"] == 2
    assert s["shed_reasons"] == {policy_mod.SHED_DISPATCH: 2}
    assert s["retries"] == 2  # max_retries + the final exhausted attempt
    assert all(t.reason == policy_mod.SHED_DISPATCH for t in tickets)


def test_daemon_timeout_during_backoff_sheds_timeout(make_board):
    """The retry ladder never sleeps past a member ticket's end-to-end
    budget: a backoff wait that would cross the deadline sheds the chunk
    with the timeout reason instead."""
    d, _ = _daemon(ServePolicy(
        max_batch=4, max_wait_s=0.0, max_retries=5,
        backoff_base_s=5.0, backoff_jitter=0.0, request_timeout_s=1.0))

    def boom():
        raise RuntimeError("still wedged")

    d._engines = lambda stack, steps: [("a", boom)]
    t = d.submit(make_board(8, 8), 1)
    d.serve()
    assert t.state == SHED and t.reason == policy_mod.SHED_TIMEOUT


def test_daemon_sheds_stale_tickets_before_dispatch(make_board):
    """A ticket that aged past its budget while queued is shed at the
    dispatch boundary, not advanced for nobody."""
    d, clk = _daemon(ServePolicy(max_wait_s=0.0, request_timeout_s=1.0))
    t = d.submit(make_board(8, 8), 1)
    clk.t = 5.0
    d.serve()
    assert t.state == SHED and t.reason == policy_mod.SHED_TIMEOUT
    assert d.summary()["batches"] == 0


def test_chaos_preempt_checkpoint_resume_zero_loss(
        monkeypatch, tmp_path, make_board):
    """The tentpole acceptance cycle, in-process: preempt after one
    dispatched batch, pending queue checkpointed, resume restores every
    drained ticket, and ALL 12 admitted requests end resolved with
    oracle parity — an admitted request is never dropped."""
    monkeypatch.setenv("MOMP_CHAOS", "preempt=1")
    chaos.reset()
    ck = tmp_path / "queue.state"
    pol = ServePolicy(max_batch=4, max_wait_s=0.0)
    d, clk = _daemon(pol, checkpoint_path=str(ck))
    boards = [make_board(16, 16) for _ in range(12)]
    for b in boards:
        d.submit(b, 2)
    with pytest.raises(preempt.SimulatedPreemption) as ei:
        d.serve()
    assert ei.value.step == 1 and ei.value.checkpoint == str(ck)
    assert d.summary()["resolved"] == 4 and d.queue.depth() == 8
    assert ck.exists()

    # "Cross-process" resume: chaos spec gone (the CI smoke resumes
    # without MOMP_CHAOS; in-process the latch already blocks a refire).
    monkeypatch.delenv("MOMP_CHAOS")
    chaos.reset()
    d2 = ServingDaemon.resume(str(ck), pol, clock=clk, sleep=clk.sleep)
    assert d2.queue.depth() == 8
    assert all(t.resumed for t in d2.queue.pending())
    d2.serve()
    s2 = d2.summary()
    assert s2["resolved"] == 8 and s2["shed"] == 0
    for t, b in zip(d2.queue.tickets(), boards[4:]):
        np.testing.assert_array_equal(t.board, b)  # payloads survived
        np.testing.assert_array_equal(t.result, oracle_n(b, 2))


def test_resume_rejects_corrupt_checkpoint(tmp_path):
    bad = tmp_path / "garbage.state"
    bad.write_bytes(b"this is not a MOMP-STATE file")
    with pytest.raises(ValueError, match="magic"):
        ServingDaemon.resume(str(bad))
    with pytest.raises(ValueError, match="no readable"):
        ServingDaemon.resume(str(tmp_path / "missing.state"))


def test_resume_any_quarantines_corrupt_checkpoint_and_serves(tmp_path):
    """The ladder half of the version-skew contract: a corrupt/foreign
    drain checkpoint (the strict `resume` above refuses it) must not
    refuse service on the full ladder — resume_any quarantines the bad
    file to a stamped forensic copy and falls through to fresh. TWO
    corrupt resumes keep TWO distinct copies: the evidence of two
    independent corruptions is itself evidence."""
    import glob

    bad = tmp_path / "skewed.state"
    bad.write_bytes(b"MOMP-STATE/9\n" + b"\x00" * 32)  # future version
    d, source, detail = ServingDaemon.resume_any(
        checkpoint_path=str(bad), policy=ServePolicy(max_batch=2))
    assert source == "fresh" and d.queue.depth() == 0
    assert "magic" in detail["checkpoint_error"]
    copies = glob.glob(str(bad) + ".corrupt.*")
    assert len(copies) == 1 and detail["checkpoint_quarantine"] == copies[0]
    assert not bad.exists()  # moved aside, never re-read

    bad.write_bytes(b"second independent corruption")
    d2, source2, detail2 = ServingDaemon.resume_any(
        checkpoint_path=str(bad), policy=ServePolicy(max_batch=2))
    assert source2 == "fresh"
    copies2 = sorted(glob.glob(str(bad) + ".corrupt.*"))
    assert len(copies2) == 2  # the first forensic copy survived
    assert detail2["checkpoint_quarantine"] in copies2


def test_chaos_soak_every_ticket_terminal(monkeypatch, make_board):
    """The soak contract: under mid-queue faults AND admission pressure,
    every submitted ticket ends in exactly one terminal state with either
    a parity-checked result or an explicit policy reason, and the
    accounting closes: requests == resolved + shed."""
    monkeypatch.setenv("MOMP_CHAOS", "serve_fail=3;delay=0.001")
    chaos.reset()
    d, _ = _daemon(ServePolicy(
        max_batch=4, max_depth=10, max_padding_frac=0.5, max_wait_s=0.01,
        backoff_base_s=0.01))
    shapes = [(16, 16), (24, 16)]
    subs = []
    for i in range(16):
        ny, nx = shapes[i % 2]
        b = make_board(ny, nx)
        subs.append((b, d.submit(b, 2)))
    d.serve()
    s = d.summary()
    assert s["requests"] == 16
    assert s["resolved"] + s["shed"] == 16 and s["pending"] == 0
    assert s["shed_reasons"].get(policy_mod.SHED_DEPTH, 0) == 6  # cap 10
    assert s["degraded"] == 3  # every injected fault self-healed
    for b, t in subs:
        assert t.state in (DONE, SHED)
        if t.state == DONE:
            assert t.engine is not None
            np.testing.assert_array_equal(t.result, oracle_n(b, 2))
        else:
            assert t.reason in SHED_REASONS


# --------------------------------------------------------------- CLI + bench


def test_daemon_cli_preempt_exits_75_then_resume_verifies(
        monkeypatch, tmp_path, capsys):
    """The cross-process contract through the CLI: chaos preemption →
    one JSON line, exit 75, checkpoint on disk; ``--resume --verify`` →
    exit 0 with every restored ticket resolved oracle-exact, and the
    two lines' accounting covers the full burst."""
    from mpi_and_open_mp_tpu.serve import daemon as daemon_cli

    ck = tmp_path / "q.state"
    monkeypatch.setenv("MOMP_CHAOS", "preempt=1")
    chaos.reset()
    rc = daemon_cli.main(["--requests", "8", "--max-batch", "4",
                          "--max-wait", "0", "--checkpoint", str(ck),
                          "--seed", "3"])
    line1 = json.loads(capsys.readouterr().out.strip())
    assert rc == preempt.EXIT_PREEMPTED == 75
    assert line1["preempted"] is True and line1["resume"] is True
    assert line1["checkpoint"] == str(ck) and ck.exists()

    monkeypatch.delenv("MOMP_CHAOS")
    chaos.reset()
    rc = daemon_cli.main(["--requests", "0", "--resume",
                          "--checkpoint", str(ck), "--verify"])
    line2 = json.loads(capsys.readouterr().out.strip())
    assert rc == 0
    assert line2["verified"] is True and line2["preempted"] is False
    assert line2["resumed_tickets"] == line2["resolved"]
    assert (line1["resolved"] + line1["shed"]
            + line2["resolved"] + line2["shed"]) == 8


def test_daemon_cli_resume_requires_checkpoint(capsys):
    from mpi_and_open_mp_tpu.serve import daemon as daemon_cli

    with pytest.raises(SystemExit) as ei:
        daemon_cli.main(["--resume"])
    assert ei.value.code == 2


def test_bench_serve_phase_fields(monkeypatch, capsys):
    """``bench.py --serve N``: the daemon phase's latency/shed/degrade
    fields ride the ONE JSON line with the reserved ``serve_daemon_*`` /
    percentile names and a passed parity gate."""
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    import bench

    monkeypatch.setattr(bench, "_probe_devices",
                        lambda timeout_s: (False, "stubbed"))
    rc = bench.main(["--board", "32", "--steps", "16", "--serve", "6"])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["serve_daemon_requests"] == 6
    assert rec["serve_resolved"] + rec["serve_shed"] == 6
    assert rec["serve_daemon_parity"] is True
    assert rec["serve_p99_latency_s"] >= rec["serve_p50_latency_s"] >= 0
    assert rec["serve_requests_per_sec"] > 0
    assert rec["serve_shed_reasons"] == {}
    # The WAL-on second burst prices the durability tax on the same line
    # (baseline serve_* fields stay WAL-off for the sentinel's history).
    assert rec["serve_wal_fsync"] == "every-record"
    assert rec["serve_wal_records"] >= 6 and rec["serve_wal_bytes"] > 0
    assert rec["serve_wal_syncs"] > 0 and rec["serve_wal_fsync_s"] >= 0
    assert rec["serve_wal_parity"] is True
    assert rec["serve_wal_p99_latency_s"] >= rec["serve_wal_p50_latency_s"]
