"""Randomised cross-configuration parity sweep.

Deterministic (seeded) random sampling over the full configuration space
— board shape (divisible, uneven, or planner-shaped unaligned), layout,
mesh factorisation, ALL FOUR impls (roll/halo/pallas/bitfused), fusion
depth, step count — every sample checked bit-exact against the NumPy
oracle. Catches interaction bugs the per-feature tests can miss (e.g. a
layout×fuse×uneven-shape corner, or a packed-frame wrap at one specific
pad); the seed makes failures reproducible. A meta-test pins the sampled
coverage so a sampler edit can't silently drop an impl from the sweep.
"""

import numpy as np
import pytest

from conftest import oracle_n, random_board

from mpi_and_open_mp_tpu.models.life import LifeSim
from mpi_and_open_mp_tpu.ops import bitlife
from mpi_and_open_mp_tpu.parallel import mesh as mesh_lib
from mpi_and_open_mp_tpu.utils.config import config_from_board

MESHES = {
    "serial": [None],
    "row": [(8, 1), (4, 1), (2, 1)],
    "col": [(1, 8), (1, 4), (1, 2)],
    "cart": [(4, 2), (2, 4), (2, 2), (8, 1)],
}
N_CASES = 24


def _sample(rng):
    layout = rng.choice(list(MESHES))
    py, px = MESHES[layout][rng.integers(len(MESHES[layout]))] or (1, 1)
    r = rng.random()
    if layout == "serial":
        ny, nx = int(rng.integers(5, 60)), int(rng.integers(5, 60))
        impl = str(rng.choice(["roll", "pallas"]))
        fuse, steps = 1, int(rng.integers(1, 13))
    elif r < 0.40:  # divisible board: the shard_map impls
        ny = py * int(rng.integers(2, 9))
        nx = px * int(rng.integers(2, 9))
        impl = str(rng.choice(["roll", "halo", "pallas"]))
        fuse = int(rng.integers(1, 4)) if impl in ("halo", "pallas") else 1
        if fuse > min(ny // py, nx // px):
            fuse = 1
        steps = int(rng.integers(1, 13))
    elif r < 0.60:  # small uneven board -> global roll
        ny = int(rng.integers(5, 50))
        nx = int(rng.integers(5, 50))
        impl, fuse, steps = "roll", 1, int(rng.integers(1, 13))
    else:  # planner-shaped boards, any alignment -> packed fused path
        y_sh, x_sh = layout in ("row", "cart"), layout in ("col", "cart")
        plan = None
        for _ in range(8):  # rejection-sample until the planner accepts
            ny = int(rng.integers(64, 200)) * py + int(rng.integers(0, 40))
            nx = int(rng.integers(40, 260)) * px + int(rng.integers(0, 40))
            plan = bitlife.plan_sharded_bits((ny, nx), py, px, y_sh, x_sh)
            if plan is not None:
                break
        if plan is None:  # pathological mesh draw; keep the case useful
            impl, fuse, steps = "roll", 1, int(rng.integers(1, 13))
        else:
            impl, fuse = "bitfused", 1
            # Bias toward crossing a fused-round boundary when k_max is
            # small (h=1 plans); huge-k plans stay single-round to keep
            # the CPU oracle affordable.
            steps = int(rng.integers(1, min(plan.k_max + 12, 60)))
    return layout, (py, px), ny, nx, impl, fuse, steps


def _cases():
    return [
        _sample(np.random.default_rng(46_000 + case))
        for case in range(N_CASES)
    ]


def test_sweep_covers_all_impls():
    """The seeded draw must keep exercising every impl and at least one
    bitfused sample that crosses a fused-round boundary."""
    cases = _cases()
    impls = {c[4] for c in cases}
    assert impls == {"roll", "halo", "pallas", "bitfused"}, impls
    crossing = []
    for layout, (py, px), ny, nx, impl, _, steps in cases:
        if impl != "bitfused":
            continue
        plan = bitlife.plan_sharded_bits(
            (ny, nx), py, px,
            layout in ("row", "cart"), layout in ("col", "cart"))
        if steps > plan.k_max:  # a second round re-consumes round-1 halos
            crossing.append((layout, ny, nx, plan.k_max, steps))
    assert crossing, "no bitfused sample crosses its fused-round boundary"
    assert any(c[0] == "cart" and c[4] == "bitfused" for c in cases)


@pytest.mark.parametrize("case", range(N_CASES))
def test_random_config_parity(case):
    rng = np.random.default_rng(46_000 + case)
    layout, (py, px), ny, nx, impl, fuse, steps = _sample(rng)
    board = random_board(rng, ny, nx, density=float(rng.uniform(0.2, 0.5)))
    mesh = None
    if layout == "row":
        mesh = mesh_lib.make_mesh_1d(py, axis="y")
    elif layout == "col":
        mesh = mesh_lib.make_mesh_1d(px, axis="x")
    elif layout == "cart":
        mesh = mesh_lib.make_mesh_2d(py, px)
    cfg = config_from_board(board, steps=steps, save_steps=0)
    sim = LifeSim(cfg, layout=layout, impl=impl, mesh=mesh, fuse_steps=fuse)
    sim.step(steps)
    np.testing.assert_array_equal(
        sim.collect(), oracle_n(board, steps),
        err_msg=f"{layout} mesh=({py},{px}) {ny}x{nx} {impl} "
                f"fuse={fuse} steps={steps}",
    )
