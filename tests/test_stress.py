"""Randomised cross-configuration parity sweep.

Deterministic (seeded) random sampling over the full configuration space
— board shape (divisible or not), layout, mesh factorisation, impl,
fusion depth, step count — every sample checked bit-exact against the
NumPy oracle. Catches interaction bugs the per-feature tests can miss
(e.g. a layout×fuse×uneven-shape corner); the seed makes failures
reproducible.
"""

import numpy as np
import pytest

from conftest import oracle_n, random_board

from mpi_and_open_mp_tpu.models.life import LifeSim
from mpi_and_open_mp_tpu.parallel import mesh as mesh_lib
from mpi_and_open_mp_tpu.utils.config import config_from_board

MESHES = {
    "serial": [None],
    "row": [(8, 1), (4, 1), (2, 1)],
    "col": [(1, 8), (1, 4), (1, 2)],
    "cart": [(4, 2), (2, 4), (2, 2), (8, 1)],
}


def _sample(rng):
    layout = rng.choice(list(MESHES))
    py, px = MESHES[layout][rng.integers(len(MESHES[layout]))] or (1, 1)
    if rng.random() < 0.7:  # divisible board
        ny = py * int(rng.integers(2, 9))
        nx = px * int(rng.integers(2, 9))
        impl = rng.choice(["roll", "halo"]) if layout != "serial" else "roll"
    else:  # uneven board -> roll only
        ny = int(rng.integers(5, 50))
        nx = int(rng.integers(5, 50))
        impl = "roll"
    fuse = int(rng.integers(1, 4)) if impl == "halo" else 1
    if fuse > min(ny // py, nx // px):
        fuse = 1
    steps = int(rng.integers(1, 13))
    return layout, (py, px), ny, nx, impl, fuse, steps


@pytest.mark.parametrize("case", range(15))
def test_random_config_parity(case):
    rng = np.random.default_rng(46_000 + case)
    layout, (py, px), ny, nx, impl, fuse, steps = _sample(rng)
    board = random_board(rng, ny, nx, density=float(rng.uniform(0.2, 0.5)))
    mesh = None
    if layout == "row":
        mesh = mesh_lib.make_mesh_1d(py, axis="y")
    elif layout == "col":
        mesh = mesh_lib.make_mesh_1d(px, axis="x")
    elif layout == "cart":
        mesh = mesh_lib.make_mesh_2d(py, px)
    cfg = config_from_board(board, steps=steps, save_steps=0)
    sim = LifeSim(cfg, layout=layout, impl=impl, mesh=mesh, fuse_steps=fuse)
    sim.step(steps)
    np.testing.assert_array_equal(
        sim.collect(), oracle_n(board, steps),
        err_msg=f"{layout} mesh=({py},{px}) {ny}x{nx} {impl} "
                f"fuse={fuse} steps={steps}",
    )
