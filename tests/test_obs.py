"""Observability layer: span tracer, metrics registry, traced ring dispatch.

The contract under test mirrors the chaos discipline: everything is OFF
by default (one env check, a shared no-op singleton, an untouched
registry), and when armed the telemetry must tell the truth — hop spans
match the ``2*(p-1)`` ring structure with the same engine stamp
``ring_hop_engine_for`` reports, recovery events match what the guards
actually did, and the traced dispatch stays parity-exact.
"""

import json
import math
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpi_and_open_mp_tpu.obs import metrics, profile, report, trace
from mpi_and_open_mp_tpu.parallel import mesh as mesh_lib
from mpi_and_open_mp_tpu.parallel.context import (
    attention_reference,
    ring_attention,
    ring_hop_engine_for,
)
from mpi_and_open_mp_tpu.utils.timing import Timer


@pytest.fixture(autouse=True)
def _fresh_registry():
    metrics.reset()
    yield
    metrics.reset()


@pytest.fixture
def sink(tmp_path, monkeypatch):
    """Arm a fresh trace sink; tear it down so later tests see it off."""
    path = tmp_path / "trace.jsonl"
    monkeypatch.setenv("MOMP_TRACE", str(path))
    trace.reset()
    yield path
    trace.reset()


@pytest.fixture
def sp_mesh():
    return mesh_lib.make_mesh_1d(8, axis="sp")


def _records(path):
    return [json.loads(line)
            for line in path.read_text().splitlines() if line.strip()]


def _qkv(rng, h, n, d):
    return tuple(jnp.asarray(rng.standard_normal((h, n, d)), jnp.float32)
                 for _ in range(3))


# --------------------------------------------------------------- tracer core


def test_span_nesting_jsonl_roundtrip(sink):
    with trace.span("outer", phase="x") as outer:
        with trace.span("inner", hop=1) as inner:
            assert inner.parent == outer.id
            trace.event("ping", note="hi")
        assert outer.elapsed >= 0.0
    recs = _records(sink)
    assert [r["name"] for r in recs] == ["ping", "inner", "outer"]
    ev, inner_r, outer_r = recs
    for r in recs:  # schema every consumer relies on
        assert {"kind", "name", "ts", "id", "parent", "pid", "host"} <= r.keys()
    assert ev["kind"] == "event"
    assert ev["parent"] == inner_r["id"]  # parented to the innermost span
    assert inner_r["parent"] == outer_r["id"]
    assert outer_r["parent"] is None
    assert inner_r["attrs"] == {"hop": 1}
    assert outer_r["attrs"] == {"phase": "x"}
    assert 0.0 <= inner_r["dur"] <= outer_r["dur"]


def test_span_records_error_and_still_closes(sink):
    with pytest.raises(ValueError):
        with trace.span("doomed"):
            raise ValueError("boom")
    (rec,) = _records(sink)
    assert rec["name"] == "doomed"
    assert rec["error"] == "ValueError"


def test_span_set_updates_attrs_mid_span(sink):
    with trace.span("s", engine="?") as sp:
        sp.set(engine="jnp")
    (rec,) = _records(sink)
    assert rec["attrs"]["engine"] == "jnp"


def test_sink_appends_across_invocations(sink):
    """Two arm/reset cycles share one file — the CI trace cycle runs two
    bench invocations against the same ``MOMP_TRACE`` path."""
    with trace.span("first"):
        pass
    trace.reset()  # simulate process end; env unchanged
    with trace.span("second"):
        pass
    assert [r["name"] for r in _records(sink)] == ["first", "second"]


def test_tracing_off_is_a_shared_noop(monkeypatch, tmp_path):
    monkeypatch.delenv("MOMP_TRACE", raising=False)
    trace.reset()
    assert not trace.enabled()
    assert not trace.hop_spans_active()
    sp = trace.span("anything", attr=1)
    assert sp is trace.NULL  # one shared instance, no allocation
    assert sp is trace.span("other")
    with sp as s:
        assert math.isnan(s.elapsed)
        s.set(x=1).anchor(None)
    trace.event("nothing")  # must not create a sink either
    assert list(tmp_path.iterdir()) == []


def test_hop_spans_opt_out_env(sink, monkeypatch):
    assert trace.hop_spans_active()
    monkeypatch.setenv("MOMP_TRACE_HOPS", "0")
    assert trace.enabled() and not trace.hop_spans_active()


# ------------------------------------------------------------------ metrics


def test_metrics_counters_gauges_histograms():
    metrics.inc("hits")
    metrics.inc("hits", 2)
    metrics.inc("hits", engine="jnp")
    metrics.gauge("depth", 3, axis="y")
    metrics.gauge("depth", 5, axis="y")  # last wins
    metrics.observe("lat", 1.0)
    metrics.observe("lat", 3.0)
    metrics.observe("lat", float("nan"))  # dropped, never poisons min/max
    assert metrics.get("hits") == 3
    assert metrics.get("hits", engine="jnp") == 1
    assert metrics.get("never") == 0
    snap = metrics.snapshot()
    assert snap["counters"]["hits"] == 3
    assert snap["counters"]["hits{engine=jnp}"] == 1
    assert snap["gauges"]["depth{axis=y}"] == 5
    assert snap["histograms"]["lat"] == {
        "count": 2, "total": 4.0, "min": 1.0, "max": 3.0}
    json.dumps(snap)  # the bench-line sub-object must serialise
    metrics.reset()
    assert metrics.snapshot() == {
        "counters": {}, "gauges": {}, "histograms": {}}


def test_metrics_env_kill_switch(monkeypatch):
    monkeypatch.setenv("MOMP_METRICS", "0")
    metrics.inc("hits")
    metrics.gauge("g", 1)
    metrics.observe("h", 1.0)
    assert metrics.snapshot() == {
        "counters": {}, "gauges": {}, "histograms": {}}


def test_metrics_mixed_label_value_types_snapshot():
    metrics.inc("m", hop=1)
    metrics.inc("m", hop="one")
    snap = metrics.snapshot()["counters"]
    assert snap == {"m{hop=1}": 1, "m{hop=one}": 1}


# ----------------------------------------------------------- the span clock


def test_timer_live_elapsed_inside_with():
    with Timer() as t:
        first = t.elapsed
        assert first >= 0.0  # live, not NaN, before __exit__
        time.sleep(0.01)
        assert t.elapsed > first
    frozen = t.elapsed
    time.sleep(0.005)
    assert t.elapsed == frozen  # stops at exit


# ------------------------------------------------- traced ring hop dispatch


def test_traced_ring_parity_and_hop_span_contract(rng, sp_mesh, sink):
    h, n, d = 2, 128, 16
    q, k, v = _qkv(rng, h, n, d)
    p = sp_mesh.shape["sp"]
    got = ring_attention(q, k, v, mesh=sp_mesh, causal=True)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    recs = _records(sink)
    transfers = [r for r in recs if r["name"] == "ring.hop.transfer"]
    folds = [r for r in recs if r["name"] == "ring.hop.fold"]
    roots = [r for r in recs if r["name"] == "ring_attention"]
    # The acceptance contract: 2*(p-1) hop spans per attention step.
    assert len(transfers) == p - 1
    assert len(folds) == p - 1
    assert [r["attrs"]["hop"] for r in transfers] == list(range(1, p))
    assert all(r["attrs"]["bytes"] > 0 for r in transfers)
    (root,) = roots
    assert root["attrs"]["traced_dispatch"] is True
    assert root["attrs"]["devices"] == p
    # Engine honesty: hop spans carry the stamp ring_hop_engine_for
    # reports for the same global operands.
    engine = ring_hop_engine_for(q, k, v, p=p, causal=True)
    assert root["attrs"]["engine"] == engine
    assert all(r["attrs"]["engine"] == engine for r in folds)
    assert all(r["parent"] == root["id"] for r in transfers + folds)
    assert metrics.get("ring.hops.fwd", engine=engine) == p - 1
    assert metrics.get("ring.steps.traced") == 1


def test_traced_ring_noncausal_parity(rng, sp_mesh, sink):
    q, k, v = _qkv(rng, 3, 256, 8)
    got = ring_attention(q, k, v, mesh=sp_mesh, causal=False)
    want = attention_reference(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    recs = _records(sink)
    assert len([r for r in recs if r["name"].startswith("ring.hop.")]) == 14


@pytest.fixture
def pallas_interpret(monkeypatch):
    """Interpret-mode Pallas hop engine (same discipline as
    test_context's fixture: the flag is trace-time, not a jit cache key,
    so caches clear on both sides)."""
    from mpi_and_open_mp_tpu.parallel import context

    jax.clear_caches()
    monkeypatch.setattr(context, "_PALLAS_INTERPRET", True)
    yield context
    jax.clear_caches()


def test_traced_ring_engine_tag_matches_pallas_plan(rng, sp_mesh, sink,
                                                    pallas_interpret):
    h, n, d = 2, 8 * 128, 128  # per-shard 128 = interpret-eligible block
    q, k, v = _qkv(rng, h, n, d)
    p = sp_mesh.shape["sp"]
    engine = ring_hop_engine_for(q, k, v, p=p, causal=True)
    assert engine.startswith("pallas:") and engine.endswith(":pf")
    # The traced decomposition dispatches each hop from the host —
    # rotation, then fold, strictly serial — so there is no prefetch to
    # claim: its spans carry the fused stamp minus the :pf suffix.
    engine = engine[:-len(":pf")]
    got = ring_attention(q, k, v, mesh=sp_mesh, causal=True)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)
    recs = _records(sink)
    folds = [r for r in recs if r["name"] == "ring.hop.fold"]
    assert len(folds) == p - 1
    assert all(r["attrs"]["engine"] == engine for r in folds)
    assert metrics.get("ring.hops.fwd", engine=engine) == p - 1


def test_hop_opt_out_gets_whole_call_span(rng, sp_mesh, sink, monkeypatch):
    monkeypatch.setenv("MOMP_TRACE_HOPS", "0")
    q, k, v = _qkv(rng, 2, 128, 16)
    ring_attention(q, k, v, mesh=sp_mesh, causal=True)
    recs = _records(sink)
    assert [r["name"] for r in recs] == ["ring_attention"]
    assert "traced_dispatch" not in recs[0].get("attrs", {})
    assert metrics.get("ring.steps.traced") == 0


def test_chaos_recovery_lands_in_trace_and_registry(rng, sp_mesh, sink,
                                                    monkeypatch):
    """An injected NaN hop under guards must surface everywhere the ISSUE
    promises: a ``recovery`` trace event (parented to the guarded span),
    the ``recovery{stamp=...}`` counter, and the capped recovery log."""
    from mpi_and_open_mp_tpu.robust import chaos, guards

    q, k, v = _qkv(rng, 2, 128, 16)
    monkeypatch.setenv("MOMP_CHAOS", "nan_hop=1;seed=3")
    chaos.reset()
    guards.reset_recovery_log()
    try:
        out = ring_attention(q, k, v, mesh=sp_mesh, causal=True)
    finally:
        monkeypatch.delenv("MOMP_CHAOS")
        chaos.reset()
        jax.clear_caches()
    assert np.isfinite(np.asarray(out)).all()
    stamp = "ring_attention:jnp:recovered"
    assert guards.recovery_log() == [stamp]
    assert metrics.get("recovery", stamp=stamp) == 1
    recs = _records(sink)
    events = [r for r in recs if r["kind"] == "event"
              and r["name"] == "recovery"]
    assert [e["attrs"]["stamp"] for e in events] == [stamp]
    (span_rec,) = [r for r in recs if r["name"] == "ring_attention"]
    assert span_rec["attrs"]["guarded"] is True
    assert span_rec["attrs"]["engine"] == "jnp:recovered"
    assert events[0]["parent"] == span_rec["id"]
    guards.reset_recovery_log()


# ------------------------------------------------------------ recovery log


def test_recovery_log_ring_buffer_cap():
    from mpi_and_open_mp_tpu.robust import guards

    guards.reset_recovery_log()
    for i in range(300):
        guards.record_recovery(f"s{i}")
    log = guards.recovery_log()
    assert len(log) == guards.RECOVERY_LOG_CAP == 256
    assert log[0] == "s44" and log[-1] == "s299"  # oldest dropped first
    # The registry's own cardinality guard caps distinct stamps at
    # max_labelsets(); the overflow is COUNTED, never silent — 300
    # recoveries are still 300 recoveries on the books.
    kept = sum(metrics.get("recovery", stamp=f"s{i}") for i in range(300))
    assert kept == metrics.max_labelsets() == 256
    assert kept + metrics.get(metrics.DROPPED_LABELS) == 300
    guards.clear_recovery_log()  # the pre-obs alias keeps working
    assert guards.recovery_log() == []


# -------------------------------------------------------- checkpoint spans


def test_checkpoint_save_restore_spans_and_metrics(tmp_path, sink):
    from mpi_and_open_mp_tpu.utils import checkpoint

    board = jnp.asarray(
        np.random.default_rng(1).integers(0, 2, (16, 16), np.uint8))
    path = tmp_path / "ckpt"
    checkpoint.save(path, board, step=7)
    got, step = checkpoint.restore(path)
    assert step == 7 and np.array_equal(got, np.asarray(board))
    names = [r["name"] for r in _records(sink)]
    assert "checkpoint.save" in names and "checkpoint.restore" in names
    snap = metrics.snapshot()
    assert snap["counters"]["checkpoint.saves"] == 1
    assert snap["counters"]["checkpoint.restores"] == 1
    assert snap["counters"]["checkpoint.save.bytes"] == 256
    assert snap["counters"]["checkpoint.restore.bytes"] == 256
    assert snap["histograms"]["checkpoint.save_seconds"]["count"] == 1
    assert snap["histograms"]["checkpoint.restore_seconds"]["count"] == 1


# ------------------------------------------------------------- trace report


def _span(name, id, parent=None, dur=1.0, **attrs):
    rec = {"kind": "span", "name": name, "ts": 0.0, "dur": dur,
           "id": id, "parent": parent, "pid": 1, "host": "h"}
    if attrs:
        rec["attrs"] = attrs
    return rec


def test_report_phases_attention_and_fit():
    recs = [
        _span("ring.hop.transfer", 2, parent=1, dur=10e-6, hop=1, bytes=100),
        _span("ring.hop.fold", 3, parent=1, dur=5e-6, hop=1, engine="jnp"),
        _span("ring.hop.transfer", 4, parent=1, dur=20e-6, hop=2,
              bytes=10_000),
        _span("ring.hop.fold", 5, parent=1, dur=5e-6, hop=2, engine="jnp"),
        _span("ring_attention", 1, dur=50e-6, traced_dispatch=True,
              engine="jnp", devices=3),
    ]
    rep = report.report_dict(recs)
    att = rep["attention"]
    assert att["traced_steps"] == 1
    assert att["hop_spans"] == 4 and att["hop_spans_per_step"] == 4.0
    assert att["engines"] == ["jnp"]
    fit = att["hop_fit"]  # t = alpha + beta*n over (100, 10us), (1e4, 20us)
    assert fit["identifiable"] is True
    assert fit["alpha_us"] == pytest.approx(9.899, rel=1e-3)
    # Share accounting: only the root span counts toward the wall.
    assert rep["phases"]["wall_s"] == pytest.approx(50e-6)
    assert rep["phases"]["by_name"]["ring_attention"]["share"] == 1.0


def test_report_recoveries_and_retraces():
    recs = [
        {"kind": "event", "name": "recovery", "ts": 0, "id": 1,
         "parent": None, "pid": 1, "host": "h",
         "attrs": {"stamp": "ring_attention:jnp:recovered"}},
        {"kind": "event", "name": "metrics", "ts": 0, "id": 2,
         "parent": None, "pid": 1, "host": "h",
         "attrs": {"snapshot": {"counters": {
             "jit.retrace{fn=sharded_attention}": 2,
             "recovery{stamp=ring_attention:jnp:recovered}": 1}}}},
    ]
    rep = report.report_dict(recs)
    assert rep["recoveries"] == {
        "total": 1,
        "by_stamp": {"ring_attention:jnp:recovered": 1}}
    assert rep["retraces"] == {"sharded_attention": 2}
    assert "hop_fit" in rep["attention"]
    assert rep["attention"]["hop_fit"] is None  # no transfer spans
    report.render(rep)  # text mode must not crash on a ring-free trace


def test_report_load_rejects_malformed_lines(tmp_path):
    p = tmp_path / "t.jsonl"
    p.write_text('{"kind": "span", "name": "a"}\nnot json\n')
    with pytest.raises(ValueError, match="t.jsonl:2"):
        report.load(str(p))
    p.write_text('{"kind": "event", "name": "a"}\n\n')
    assert len(report.load(str(p))) == 1


def test_report_end_to_end_on_a_real_trace(rng, sp_mesh, sink):
    """The CLI's own pipeline over a genuinely produced trace: hop span
    arithmetic and JSON serialisability, end to end."""
    q, k, v = _qkv(rng, 2, 128, 16)
    ring_attention(q, k, v, mesh=sp_mesh, causal=True)
    rep = report.report_dict(report.load(str(sink)))
    assert rep["attention"]["traced_steps"] == 1
    assert rep["attention"]["hop_spans"] == 14
    assert rep["attention"]["hop_spans_per_step"] == 14.0
    json.dumps(rep)
    assert "ring_attention" in report.render(rep)


# ------------------------------------------------------------ chrome export


def test_chrome_export_schema_and_track_nesting():
    """Spans → "X" events on per-root tracks: tid is the root ancestor's
    span id, args carry span_id/parent for nesting verification, events
    become "i" instants on their parent's track, and every (pid, host)
    pair gets a process_name metadata row."""
    recs = [
        _span("root_a", 1, dur=50e-6),
        _span("child", 2, parent=1, dur=20e-6, hop=1),
        _span("grandchild", 3, parent=2, dur=10e-6),
        _span("root_b", 9, dur=5e-6),
        {"kind": "event", "name": "recovery", "ts": 1e-6, "id": 4,
         "parent": 2, "pid": 1, "host": "h", "attrs": {"stamp": "s"}},
    ]
    doc = report.to_chrome(recs)
    assert doc["displayTimeUnit"] == "ms"
    evs = doc["traceEvents"]
    by_name = {e["name"]: e for e in evs if e.get("ph") in ("X", "i")}
    # The whole subtree shares root_a's track; root_b has its own.
    assert by_name["root_a"]["tid"] == 1
    assert by_name["child"]["tid"] == 1
    assert by_name["grandchild"]["tid"] == 1
    assert by_name["root_b"]["tid"] == 9
    # Source parentage rides in args, µs in ts/dur.
    assert by_name["grandchild"]["args"]["span_id"] == 3
    assert by_name["grandchild"]["args"]["parent"] == 2
    assert by_name["child"]["dur"] == pytest.approx(20.0)
    # The instant event lands on its parent span's track.
    ev = by_name["recovery"]
    assert ev["ph"] == "i" and ev["tid"] == 1
    assert ev["args"] == {"stamp": "s"}
    meta = [e for e in evs if e.get("ph") == "M"]
    assert [m["args"]["name"] for m in meta] == ["h (pid 1)"]
    # Non-metadata events are time-ordered for stream consumers.
    xs = [e for e in evs if e.get("ph") != "M"]
    assert [e["ts"] for e in xs] == sorted(e["ts"] for e in xs)
    json.dumps(doc)  # must serialise as-is


def test_chrome_export_orphan_parent_roots_its_subtree():
    """A truncated trace (killed process) may reference a parent that
    never flushed — the orphan becomes its own root, not a KeyError."""
    recs = [_span("orphan", 5, parent=404, dur=1e-6)]
    (ev,) = [e for e in report.to_chrome(recs)["traceEvents"]
             if e.get("ph") == "X"]
    assert ev["tid"] == 5


def test_chrome_export_error_span_marked():
    rec = _span("doomed", 1, dur=1e-6)
    rec["error"] = "ValueError"
    (ev,) = [e for e in report.to_chrome([rec])["traceEvents"]
             if e.get("ph") == "X"]
    assert ev["args"]["error"] == "ValueError"


def test_chrome_cli_round_trip_on_real_trace(rng, sp_mesh, sink, tmp_path,
                                             capsys):
    """trace_report --chrome over a genuinely traced ring step: valid
    JSON, all 14 hop events nested (by track + time enclosure) inside
    their ring_attention root — parentage reproduced, as the ISSUE's
    acceptance asks."""
    import os
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "analysis"))
    import trace_report

    q, k, v = _qkv(rng, 2, 128, 16)
    ring_attention(q, k, v, mesh=sp_mesh, causal=True)
    out = tmp_path / "chrome.json"
    assert trace_report.main([str(sink), "--chrome", str(out)]) == 0
    assert "trace events" in capsys.readouterr().out
    doc = json.loads(out.read_text())
    xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    (root,) = [e for e in xs if e["name"] == "ring_attention"]
    hops = [e for e in xs
            if e["name"] in ("ring.hop.transfer", "ring.hop.fold")]
    assert len(hops) == 14
    for e in hops:
        assert e["tid"] == root["args"]["span_id"]
        assert root["ts"] <= e["ts"]
        assert e["ts"] + e["dur"] <= root["ts"] + root["dur"] + 1e-6


# ------------------------------------------------------------------ profile


@pytest.fixture(autouse=True)
def _fresh_cost_cache():
    profile.reset_cost_cache()
    yield
    profile.reset_cost_cache()


def test_profile_cost_finite_and_memoised():
    from mpi_and_open_mp_tpu.ops.life_ops import life_step_roll

    spec = jax.ShapeDtypeStruct((64, 64), np.uint8)
    got = profile.cost(life_step_roll, spec, name="life_step_roll")
    assert got["flops"] > 0 and math.isfinite(got["flops"])
    assert got["bytes"] > 0 and math.isfinite(got["bytes"])
    assert got["compile_seconds"] > 0
    assert got["argument_bytes"] == 64 * 64
    assert metrics.get("profile.cost_cache", result="miss") == 1
    # Same (name, shapes): served from the memo, no recompile.
    again = profile.cost(life_step_roll, spec, name="life_step_roll")
    assert again == got
    assert metrics.get("profile.cost_cache", result="hit") == 1
    hist = metrics.snapshot()["histograms"]
    assert hist["profile.compile_seconds{fn=life_step_roll}"]["count"] == 1
    # A different shape is a different artifact → a second miss.
    profile.cost(life_step_roll, jax.ShapeDtypeStruct((32, 32), np.uint8),
                 name="life_step_roll")
    assert metrics.get("profile.cost_cache", result="miss") == 2


def test_roofline_placement_and_bound():
    rf = profile.roofline(1e6, 1e5, 1e-3, device_kind="TPU v5 lite")
    assert rf["peaks"] == "v5 lite-table"
    assert rf["flops_per_sec"] == pytest.approx(1e9)
    assert rf["flops_pct"] == round(100 * 1e9 / 197e12, 3)
    assert rf["bw_pct"] == round(100 * 1e8 / 819e9, 3)
    # 0.012% bw > 0.0005% flops → the memory ceiling binds.
    assert rf["bound"] == "memory"
    assert rf["roofline_pct"] == rf["bw_pct"]
    for v in rf.values():
        if isinstance(v, float):
            assert math.isfinite(v)
    # Compute-bound case: tiny traffic, huge FLOPs.
    assert profile.roofline(1e12, 1.0, 1e-3,
                            device_kind="cpu")["bound"] == "compute"
    with pytest.raises(ValueError):
        profile.roofline(1.0, 1.0, 0.0)
    with pytest.raises(ValueError):
        profile.roofline(1.0, 1.0, float("nan"))


def test_peaks_env_override(monkeypatch):
    monkeypatch.setenv("MOMP_PEAK_FLOPS", "5e12")
    monkeypatch.setenv("MOMP_PEAK_BYTES_S", "1e11")
    flops, bw, label = profile.peaks_for("weird-part")
    assert (flops, bw) == (5e12, 1e11)
    assert label == "cpu-nominal"  # unknown kind → nominal default label


def test_record_memory_gauges_live_and_watermark():
    buf = jnp.zeros((256, 256), jnp.float32)  # 256KiB held live
    live = profile.record_memory_gauges()
    assert live >= buf.nbytes
    snap = metrics.snapshot()["gauges"]
    assert snap["memory.live_buffer_bytes"] == live
    assert snap["memory.live_buffer_watermark_bytes"] >= live
    del buf
