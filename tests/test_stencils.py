"""Parity + unit pins for the stencil spec subsystem (PR 13).

Three layers:

* Per-spec fuzz: for EVERY registered workload, the jitted generic paths
  (``step_roll``, ``step_padded``, ``run_roll``, ``run_roll_batch``)
  must agree with the spec's NumPy oracle — bit-exact for integer rules,
  tight allclose for floats (``engine.parity_ok``). Life is additionally
  pinned bit-exact against the historical independent oracle
  (``ops.life_ops.life_step_numpy``) so the generic machinery is gated
  against the original truth, not against itself.
* Sparse active-tile engine: glider crossing tile boundaries stays
  bit-exact while most tiles sleep; dense boards fall back past the
  crossover and stamp ``dense:crossover``; settled boards go to zero
  work; the pad ladder and counters are pinned.
* Halo generality: ``halo_pad_y``/``halo_pad_x`` at depth 2, float32,
  and with a leading channel axis — radius-2 and multi-channel sharded
  steps through ``halo_pad_2d`` + ``step_padded`` must reproduce the
  single-device oracle on the 8-virtual-device mesh.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from mpi_and_open_mp_tpu import stencils
from mpi_and_open_mp_tpu.ops import life_ops
from mpi_and_open_mp_tpu.parallel import halo, mesh as mesh_lib
from mpi_and_open_mp_tpu.stencils import engine
from mpi_and_open_mp_tpu.stencils.spec import BOX3
from mpi_and_open_mp_tpu.stencils.sparse import ActiveTileEngine, _pad_count


def _board(spec, rng, ny, nx):
    b = spec.init(rng, (ny, nx))
    assert b.shape == spec.board_shape(ny, nx)
    assert b.dtype == spec.np_dtype
    return b


def _pad_wrap(board, r):
    """Torus halo on the last two axes only (channels ride through)."""
    width = [(0, 0)] * (board.ndim - 2) + [(r, r), (r, r)]
    return np.pad(board, width, mode="wrap")


# --------------------------------------------------------------------------
# Registry surface.


def test_registry_has_the_four_workloads():
    assert set(stencils.names()) >= {"life", "heat", "gray_scott",
                                     "wireworld"}


def test_get_unknown_workload_names_the_registered_set():
    with pytest.raises(KeyError, match="gray_scott"):
        stencils.get("brians_brain")


def test_register_rejects_bad_weights():
    with pytest.raises(ValueError, match="weights shape"):
        stencils.register(stencils.StencilSpec(
            name="bad-shape", radius=2, dtype="float32",
            weights=BOX3, update=lambda c, a, xp: c))
    with pytest.raises(ValueError, match="center must be 0"):
        stencils.register(stencils.StencilSpec(
            name="bad-center", radius=1, dtype="float32",
            weights=((1, 1, 1), (1, 1, 1), (1, 1, 1)),
            update=lambda c, a, xp: c))


# --------------------------------------------------------------------------
# Per-spec oracle parity fuzz: every registered workload, every path.


@pytest.mark.parametrize("name", stencils.names())
@pytest.mark.parametrize("ny,nx", [(24, 32), (17, 23)])
def test_step_roll_matches_oracle(name, ny, nx, rng):
    spec = stencils.get(name)
    board = _board(spec, rng, ny, nx)
    want = board
    got = jnp.asarray(board)
    for step in range(5):
        want = engine.step_numpy(spec, want)
        got = engine.step_roll(spec, got)
        assert engine.parity_ok(spec, got, want), f"{name} step {step}"


@pytest.mark.parametrize("name", stencils.names())
@pytest.mark.parametrize("ny,nx", [(24, 32), (17, 23)])
def test_step_padded_matches_oracle(name, ny, nx, rng):
    spec = stencils.get(name)
    board = _board(spec, rng, ny, nx)
    want = board
    for step in range(3):
        padded = _pad_wrap(want, spec.radius)
        got = engine.step_padded(spec, jnp.asarray(padded))
        want = engine.step_numpy(spec, want)
        assert engine.parity_ok(spec, got, want), f"{name} step {step}"


def test_life_generic_paths_bit_exact_vs_historic_oracle(rng):
    """The acceptance pin: life through the GENERIC engine must equal
    the pre-existing independent oracle exactly, board for board."""
    spec = stencils.get("life")
    board = _board(spec, rng, 48, 64)
    want = board
    for _ in range(8):
        want = life_ops.life_step_numpy(want)
    assert np.array_equal(
        np.asarray(engine.run_roll(spec, jnp.asarray(board), 8)), want)
    padded = _pad_wrap(board, 1)
    assert np.array_equal(
        np.asarray(engine.step_padded(spec, jnp.asarray(padded))),
        life_ops.life_step_numpy(board))


@pytest.mark.parametrize("name", stencils.names())
def test_run_roll_and_batch_match_oracle(name, rng):
    spec = stencils.get(name)
    boards = [_board(spec, rng, 16, 24) for _ in range(3)]
    n = 6
    wants = [engine.oracle_run(spec, b, n) for b in boards]
    for b, w in zip(boards, wants):
        got = engine.run_roll(spec, jnp.asarray(b), n)
        assert engine.parity_ok(spec, got, w), name
    stack = np.stack(boards)
    out = np.asarray(engine.run_roll_batch(spec, jnp.asarray(stack), n))
    for i, w in enumerate(wants):
        assert engine.parity_ok(spec, out[i], w), f"{name} lane {i}"


# --------------------------------------------------------------------------
# Sparse active-tile engine.


def test_pad_count_ladder():
    assert [_pad_count(n) for n in range(1, 17)] == [
        1, 2, 3, 4, 6, 6, 8, 8, 12, 12, 12, 12, 16, 16, 16, 16]
    for n in (1, 5, 33, 100, 1000):
        assert _pad_count(n) >= n


def test_sparse_glider_crossing_tiles_stays_bit_exact():
    spec = stencils.get("life")
    board = np.zeros((256, 256), np.uint8)
    # Glider straddling the (30..32, 30..32) tile corner at tile=32 —
    # it must wake exactly the tiles it enters, never drop cells.
    board[30:33, 30:33] = [[0, 1, 0], [0, 0, 1], [1, 1, 1]]
    eng = ActiveTileEngine(spec, board, tile=32)
    got = eng.step(200)
    want = engine.oracle_run(spec, board, 200)
    assert np.array_equal(got, want)
    c = eng.counters()
    # Step 1 is dense (everything starts active); the rest ride sparse.
    assert c["dense_steps"] == 1 and c["sparse_steps"] == 199
    assert c["tiles_skipped"] > c["tiles_stepped"]
    assert eng.engine_stamp == "sparse:t32"


def test_sparse_dense_board_falls_back_and_stamps_crossover(rng):
    spec = stencils.get("life")
    board = spec.init(rng, (64, 64))  # ~33% live: every tile active
    eng = ActiveTileEngine(spec, board, tile=16, crossover=0.25)
    got = eng.step(4)
    assert np.array_equal(got, engine.oracle_run(spec, board, 4))
    assert eng.dense_steps >= 1
    if eng.sparse_steps == 0:
        assert eng.engine_stamp == "dense:crossover"


def test_sparse_settled_board_does_zero_work():
    spec = stencils.get("life")
    eng = ActiveTileEngine(spec, np.zeros((64, 64), np.uint8), tile=32)
    eng.step(1)  # proves settledness (everything starts active)
    stepped = eng.tiles_stepped
    eng.step(5)
    assert eng.tiles_stepped == stepped  # mask empty: no tile gathered
    assert eng.active_frac == 0.0
    assert np.array_equal(eng.board, np.zeros((64, 64), np.uint8))


def test_sparse_active_frac_decays_to_the_live_region(rng):
    spec = stencils.get("life")
    board = np.zeros((256, 256), np.uint8)
    board[78:81, 80] = 1  # lone blinker, deep in tile (2,2) at tile=32
    eng = ActiveTileEngine(spec, board, tile=32)
    eng.step(10)
    # Border-band activation keeps the blinker's neighbours asleep:
    # exactly one of the 64 tiles stays awake.
    assert eng.active_frac == 1 / 64
    assert 0.0 < eng.mean_active_frac < 0.2


def test_sparse_multichannel_gray_scott_parity(rng):
    spec = stencils.get("gray_scott")
    board = _board(spec, rng, 64, 64)
    eng = ActiveTileEngine(spec, board, tile=32)
    got = eng.step(20)
    want = engine.oracle_run(spec, board, 20)
    assert engine.parity_ok(spec, got, want)


def test_sparse_rejects_bad_geometry(rng):
    spec = stencils.get("life")
    with pytest.raises(ValueError, match="must divide"):
        ActiveTileEngine(spec, np.zeros((60, 64), np.uint8), tile=32)
    with pytest.raises(ValueError, match="does not match"):
        ActiveTileEngine(
            stencils.get("gray_scott"), np.zeros((64, 64), np.float32),
            tile=32)


# --------------------------------------------------------------------------
# Halo generality: depth-2, float dtype, leading channel axis.

#: Radius-2 float diffusion used to exercise depth-2 halo exchange; the
#: weights are an asymmetric-by-distance box so a wrong halo row/column
#: cannot cancel out of the aggregate.
R2 = stencils.StencilSpec(
    name="r2-test", radius=2, dtype="float32",
    weights=((1, 1, 1, 1, 1),
             (1, 2, 2, 2, 1),
             (1, 2, 0, 2, 1),
             (1, 2, 2, 2, 1),
             (1, 1, 1, 1, 1)),
    update=lambda c, a, xp: (c + 0.01 * (a - 24 * c)).astype(c.dtype))


def _sharded_step(spec, board, mesh, in_spec):
    """One torus step via halo_pad_2d + step_padded under shard_map."""
    arr = jax.device_put(jnp.asarray(board), NamedSharding(mesh, in_spec))
    fn = jax.jit(mesh_lib.shard_map(
        lambda blk: engine.step_padded(
            spec, halo.halo_pad_2d(blk, depth=spec.radius)),
        mesh=mesh, in_specs=in_spec, out_specs=in_spec, check_vma=False,
    ))
    return np.asarray(jax.device_get(fn(arr)))


def test_halo_pad_depth2_float_periodic_extension(rng):
    """halo_pad_y/x at depth=2 on float32 must build the exact periodic
    window — the depth-generic analogue of the packed-halo pins."""
    board = rng.random((64, 48)).astype(np.float32)
    mesh = mesh_lib.make_mesh_1d(4, axis="y")
    arr = jax.device_put(
        jnp.asarray(board), NamedSharding(mesh, P("y", None)))
    ext = jax.jit(mesh_lib.shard_map(
        lambda blk: halo.halo_pad_y(blk, "y", 2),
        mesh=mesh, in_specs=P("y", None), out_specs=P("y", None),
        check_vma=False,
    ))(arr)
    ext = np.asarray(jax.device_get(ext))
    S, win = 16, 20  # 64/4 rows per shard, +2 ghost rows each side
    for i in range(4):
        got = ext[i * win:(i + 1) * win]
        rows = np.arange(i * S - 2, (i + 1) * S + 2) % 64
        assert np.array_equal(got, board[rows]), f"shard {i}"


def test_radius2_sharded_step_matches_oracle(rng):
    board = rng.random((64, 64)).astype(np.float32)
    mesh = mesh_lib.make_mesh_2d(4, 2)
    got = _sharded_step(R2, board, mesh, P("y", "x"))
    want = engine.step_numpy(R2, board)
    assert engine.parity_ok(R2, got, want)


@pytest.mark.parametrize("name", ["heat", "wireworld"])
def test_sharded_stencil_step_matches_oracle(name, rng):
    spec = stencils.get(name)
    board = _board(spec, rng, 64, 64)
    mesh = mesh_lib.make_mesh_2d(4, 2)
    got = _sharded_step(spec, board, mesh, P("y", "x"))
    want = engine.step_numpy(spec, board)
    assert engine.parity_ok(spec, got, want)


def test_channel_board_rides_through_sharded_halo(rng):
    """gray_scott's (2, ny, nx) board: channels on the leading axis must
    pass through halo_pad_* untouched while y/x shards exchange ghosts."""
    spec = stencils.get("gray_scott")
    board = _board(spec, rng, 64, 64)
    mesh = mesh_lib.make_mesh_2d(4, 2)
    got = _sharded_step(spec, board, mesh, P(None, "y", "x"))
    want = engine.step_numpy(spec, board)
    assert engine.parity_ok(spec, got, want)
