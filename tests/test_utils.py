"""Config parsing, VTK IO, decomposition, and dims_create semantics."""

import os

import numpy as np
import pytest

from mpi_and_open_mp_tpu.parallel.mesh import decomposition, dims_create
from mpi_and_open_mp_tpu.utils.config import (
    config_from_board,
    load_config_py,
    save_config,
)
from mpi_and_open_mp_tpu.utils.vtk import read_vtk, write_vtk_py

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


def test_load_glider():
    cfg = load_config_py(os.path.join(FIXTURES, "glider_10x10.cfg"))
    assert (cfg.steps, cfg.save_steps, cfg.nx, cfg.ny) == (100, 25, 10, 10)
    board = cfg.board()
    assert board.shape == (10, 10)
    assert board.sum() == 5
    # (i, j) -> board[j, i]
    assert board[2, 0] == 1 and board[0, 1] == 1


def test_load_empty():
    cfg = load_config_py(os.path.join(FIXTURES, "empty_10x10.cfg"))
    assert cfg.cells.shape == (0, 2)
    assert cfg.board().sum() == 0


def test_config_roundtrip(tmp_path, make_board):
    board = make_board(12, 7)
    cfg = config_from_board(board, steps=42, save_steps=6)
    path = tmp_path / "rt.cfg"
    save_config(path, cfg)
    cfg2 = load_config_py(path)
    assert (cfg2.steps, cfg2.save_steps, cfg2.nx, cfg2.ny) == (42, 6, 7, 12)
    np.testing.assert_array_equal(cfg2.board(), board)


def test_vtk_roundtrip(tmp_path, make_board):
    board = make_board(9, 14)
    path = tmp_path / "life_000000.vtk"
    write_vtk_py(path, board)
    np.testing.assert_array_equal(read_vtk(path), board)
    text = path.read_text()
    assert "DIMENSIONS 15 10 1" in text
    assert f"CELL_DATA {9 * 14}" in text


def test_vtk_golden_file(tmp_path):
    """Committed golden frame (the in-repo mirror of the reference's
    `4-life/vtk/life_000000.vtk` artifact): the writer's byte-level
    output for the glider fixture is pinned, so any format drift —
    header, ordering, line endings — fails here even when the reference
    tree is absent. Both writers (Python and, when built, the native
    C++ one) must reproduce it exactly, and the reader must invert it."""
    golden = os.path.join(FIXTURES, "golden_glider_000000.vtk")
    cfg = load_config_py(os.path.join(FIXTURES, "glider_10x10.cfg"))
    np.testing.assert_array_equal(read_vtk(golden), cfg.board())

    ours = tmp_path / "life_000000.vtk"
    write_vtk_py(ours, cfg.board())
    assert ours.read_text() == open(golden).read()

    from mpi_and_open_mp_tpu.utils import native

    if native.available():
        theirs = tmp_path / "life_native.vtk"
        native.write_vtk(theirs, cfg.board())
        got = theirs.read_text().splitlines()
        want = open(golden).read().splitlines()
        assert len(got) == len(want)
        for i, (g, w) in enumerate(zip(got, want)):
            if i == 1:  # creator comment line may differ
                continue
            assert g == w, f"line {i}: {g!r} != {w!r}"


@pytest.mark.parametrize("n,p", [(500, 8), (10, 3), (28, 28), (7, 2), (100, 1)])
def test_decomposition_reference_semantics(n, p):
    """Floor chunks, last shard absorbs the remainder (3-life/life_mpi.c:178-183)."""
    spans = [decomposition(n, p, k) for k in range(p)]
    chunk = n // p
    for k, (start, stop) in enumerate(spans):
        assert start == k * chunk
        if k < p - 1:
            assert stop - start == chunk
    assert spans[-1][1] == n
    # Exact cover, no overlap.
    covered = sorted(i for s, e in spans for i in range(s, e))
    assert covered == list(range(n))


@pytest.mark.parametrize(
    "n,expect",
    [(1, (1, 1)), (4, (2, 2)), (8, (4, 2)), (12, (4, 3)), (7, (7, 1)), (36, (6, 6))],
)
def test_dims_create(n, expect):
    dims = dims_create(n, 2)
    assert dims == expect
    assert dims[0] * dims[1] == n


# ------------------------------------------------------------- anchor_sync


def _probes_captured(monkeypatch):
    """Patch jax.device_get to record what anchor_sync fetches."""
    import jax

    calls = []
    real = jax.device_get

    def spy(x):
        calls.append(x)
        return real(x)

    monkeypatch.setattr(jax, "device_get", spy)
    return calls


def test_anchor_sync_probes_mesh_placed_leaves(monkeypatch):
    """Mesh-placed leaves get ONE batched one-element probe fetch."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from mpi_and_open_mp_tpu.parallel import mesh as mesh_lib
    from mpi_and_open_mp_tpu.utils.timing import anchor_sync

    mesh = mesh_lib.make_mesh_1d(8, axis="y")
    a = jax.device_put(jnp.ones((16, 4)), NamedSharding(mesh, P("y")))
    b = jax.device_put(jnp.ones((8,)), NamedSharding(mesh, P("y")))
    calls = _probes_captured(monkeypatch)
    anchor_sync({"a": a, "b": b})
    assert len(calls) == 1  # batched: one RTT, not one per leaf
    probes = calls[0]
    assert [p.shape for p in probes] == [(1, 1), (1,)]


def test_anchor_sync_skips_single_device_unless_fetch_all(monkeypatch):
    """SingleDeviceSharding leaves are block-only by default (the fetch
    would cost a host RTT inside timing brackets); fetch_all probes them."""
    import jax.numpy as jnp

    from mpi_and_open_mp_tpu.utils.timing import anchor_sync

    x = jnp.ones((4, 4)) + 0  # committed single-device array
    calls = _probes_captured(monkeypatch)
    anchor_sync(x)
    assert calls == []
    anchor_sync(x, fetch_all=True)
    assert len(calls) == 1 and calls[0][0].shape == (1, 1)


def test_anchor_sync_skips_empty_shards_and_non_arrays(monkeypatch):
    """Zero-size shards can't be probed (guard), and non-jax leaves
    (numpy, python scalars) pass through untouched."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from mpi_and_open_mp_tpu.parallel import mesh as mesh_lib
    from mpi_and_open_mp_tpu.utils.timing import anchor_sync

    mesh = mesh_lib.make_mesh_1d(8, axis="y")
    empty = jax.device_put(jnp.zeros((0, 3)), NamedSharding(mesh, P()))
    calls = _probes_captured(monkeypatch)
    anchor_sync({"e": empty, "np": np.ones(3), "i": 7}, fetch_all=True)
    assert calls == []  # nothing probeable -> no fetch at all


def test_vtk_golden_cross_compat_with_reference_artifact(tmp_path):
    """The reference repo commits an actual VTK frame
    (`4-life/vtk/life_000000.vtk` — p46gun_big.cfg at step 0, verified by
    content). Our reader must consume it exactly, and our writer must
    reproduce it byte-for-byte apart from line 2's creator comment — the
    strongest cross-compatibility evidence available: artifacts produced
    by the reference's C writer and by this framework interchange."""
    ref_path = "/root/reference/4-life/vtk/life_000000.vtk"
    ref_cfg = "/root/reference/4-life/p46gun_big.cfg"
    if not os.path.exists(ref_path):
        pytest.skip("reference tree not present")
    # Our parser consumes the reference's own cfg, and our reader its
    # committed frame; the two must agree (the frame is step 0).
    cfg = load_config_py(ref_cfg)
    board = read_vtk(ref_path)
    np.testing.assert_array_equal(board, cfg.board())

    ours = tmp_path / "life_000000.vtk"
    write_vtk_py(ours, board)
    got = ours.read_text().splitlines()
    want = open(ref_path).read().splitlines()
    assert len(got) == len(want)
    for i, (g, w) in enumerate(zip(got, want)):
        if i == 1:  # creator comment line differs by design
            continue
        assert g == w, f"line {i}: {g!r} != {w!r}"


def test_config_cells_wrap_like_reference_ind_macro(tmp_path):
    """Out-of-range and negative cell coordinates wrap onto the torus —
    the reference's loader writes cells through its `ind` macro
    (`3-life/life2d.c:9,69`: `((i+nx)%nx) + ((j+ny)%ny)*nx`), so a cfg
    listing (9,9) on a 4x4 board lights (1,1), and (-1,2) lights (3,2).
    Python's % matches the macro for ANY magnitude, including beyond
    -nx where the macro's single +nx would not — pinned here so a
    future loader rewrite keeps the quirk."""
    p = tmp_path / "wrap.cfg"
    p.write_text("5\n1\n4 4\n9 9\n-1 2\n")
    cfg = load_config_py(p)
    b = cfg.board()
    assert b.sum() == 2
    assert b[1, 1] == 1  # (i=9, j=9) -> (1, 1)
    assert b[2, 3] == 1  # (i=-1, j=2) -> col 3, row 2


def test_write_csv_rows(tmp_path):
    """The sweeps' crash-proof per-point writer: creates the directory,
    rewrites whole, trailing newline (artifact hygiene)."""
    from mpi_and_open_mp_tpu.utils.timing import write_csv_rows

    out = tmp_path / "deep" / "rows.csv"
    write_csv_rows(str(out), ["a,b", "1,2"])
    assert out.read_text() == "a,b\n1,2\n"
    write_csv_rows(str(out), ["a,b", "1,2", "3,4"])  # grows idempotently
    assert out.read_text() == "a,b\n1,2\n3,4\n"
