"""Worker for test_distributed: one jax.distributed process of a 2-host run.

Run as ``python _dist_worker.py <proc_id> <nprocs> <coordinator>``.
Prints ``DIST_OK`` from process 0 on success. Kept as a plain script (not
a test module): it must bootstrap its own JAX runtime before any import
side effects, which cannot happen inside the already-initialised pytest
process.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)  # 1 CPU device per process

proc_id, nprocs, coord = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coord, num_processes=nprocs, process_id=proc_id)

import numpy as np  # noqa: E402

from mpi_and_open_mp_tpu.models.integral import Integral  # noqa: E402
from mpi_and_open_mp_tpu.models.life import LifeSim  # noqa: E402
from mpi_and_open_mp_tpu.ops.life_ops import life_step_numpy  # noqa: E402
from mpi_and_open_mp_tpu.parallel import mesh as mesh_lib  # noqa: E402
from mpi_and_open_mp_tpu.utils.config import config_from_board  # noqa: E402

assert jax.process_count() == nprocs
assert len(jax.devices()) == nprocs  # one device per process, DCN-style

# Cross-process psum through the quadrature model.
mesh = mesh_lib.make_mesh_1d(len(jax.devices()), axis="y")
val = Integral(1_000_000, mesh=mesh).compute()
assert abs(val - np.pi) < 1e-3, val

# Sharded Life run whose halo exchange crosses the process boundary;
# collect() must allgather (the board is not fully addressable).
rng = np.random.default_rng(0)
board = (rng.random((64, 40)) < 0.35).astype(np.uint8)
cfg = config_from_board(board, steps=6, save_steps=0)
sim = LifeSim(cfg, layout="row", impl="halo", mesh=mesh)
sim.step(6)
got = sim.collect()
ref = board.copy()
for _ in range(6):
    ref = life_step_numpy(ref)
assert np.array_equal(got, ref), "multi-process halo step lost parity"

# Sequence-parallel ring attention whose K/V rotations (and the flash
# backward's counter-rotating dk/dv accumulators) cross the process
# boundary — the long-context layer on a real multi-process fabric.
import jax.numpy as jnp  # noqa: E402

from mpi_and_open_mp_tpu.parallel.context import (  # noqa: E402
    attention_reference, ring_attention)

sp_mesh = mesh_lib.make_mesh_1d(len(jax.devices()), axis="sp")
h, n, d = 2, 64, 16
qkv = tuple(jnp.asarray(rng.standard_normal((h, n, d)), jnp.float32)
            for _ in range(3))


def check_local(got, want, what):
    # Outputs span both processes; each process checks the shards it
    # can address against the corresponding slice of the local oracle.
    assert got.addressable_shards, f"{what}: no addressable shard"
    for s in got.addressable_shards:
        assert np.allclose(np.asarray(s.data), want[s.index],
                           rtol=1e-4, atol=1e-4), f"{what} lost parity"


got_a = ring_attention(*qkv, mesh=sp_mesh, causal=True)
want_a = np.asarray(attention_reference(*qkv, causal=True))
check_local(got_a, want_a, "multi-process ring attention")

g_got = jax.jit(jax.grad(
    lambda a, b, c: jnp.sum(
        ring_attention(a, b, c, mesh=sp_mesh, causal=True) ** 2),
    argnums=(0, 1, 2)))(*qkv)
g_want = jax.grad(
    lambda a, b, c: jnp.sum(attention_reference(a, b, c, causal=True) ** 2),
    argnums=(0, 1, 2))(*qkv)
for gg, gw, nm in zip(g_got, g_want, "qkv"):
    check_local(gg, np.asarray(gw),
                f"multi-process ring flash backward d{nm}")

# Striped/zigzag causal-balanced layout across the same real fabric:
# the half-block hops and per-half dk/dv assembly must survive a true
# process-boundary ppermute too.
from mpi_and_open_mp_tpu.parallel.context import (  # noqa: E402
    zigzag_order, zigzag_shard)

sp = sp_mesh.shape["sp"]
qkv_z = tuple(zigzag_shard(x, sp) for x in qkv)
got_z = ring_attention(*qkv_z, mesh=sp_mesh, causal=True, layout="zigzag")
# got_z is in zigzag order; compare each addressable shard against the
# correspondingly-permuted oracle rows (slot -> natural position).
want_z = want_a[:, np.asarray(zigzag_order(n, sp))]
check_local(got_z, want_z, "multi-process zigzag ring attention")

# Snapshot write: collective collect, process-0-only file write.
import tempfile  # noqa: E402

sim.outdir = os.path.join(
    tempfile.gettempdir(), f"dist_vtk_{os.path.basename(coord)}")
path = sim.save_snapshot()
if proc_id == 0:
    from mpi_and_open_mp_tpu.utils.vtk import read_vtk
    assert np.array_equal(read_vtk(path), got)

jax.distributed.shutdown()
if proc_id == 0:
    print("DIST_OK")
