"""Randomised cross-configuration parity sweep for the attention layer.

The attention analogue of tests/test_stress.py: deterministic (seeded)
sampling over variant (ring / ulysses / local-chunked), mesh size, head
count with GQA/MQA kv-head divisors, sequence length (chunk-crossing and
non-multiple), head dim, causality, dtype, and forward-vs-gradient —
every sample checked against the dense single-device oracle (gradients
against autodiff of the oracle). A meta-test pins the sampled coverage
so a sampler edit can't silently drop a variant, the flash backward, or
the GQA path from the sweep.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpi_and_open_mp_tpu.parallel import context, mesh as mesh_lib
from mpi_and_open_mp_tpu.parallel.context import (
    attention_reference,
    flash_attention,
    ring_attention,
    ulysses_attention,
    zigzag_shard,
    zigzag_unshard,
)

N_CASES = 16
_CHUNK = 16  # shrunk _Q_CHUNK so chunked paths engage at test sizes


def _sample(rng):
    variant = str(rng.choice(["ring", "ulysses", "local"]))
    p = int(rng.choice([1, 2, 4, 8])) if variant != "local" else 1
    hkv = int(rng.choice([1, 2, 4]))
    groups = int(rng.choice([1, 2, 4]))
    h = hkv * groups
    if variant == "ulysses" and h % p:
        p = 1
    # n: a chunk-crossing multiple of p, sometimes NOT a chunk multiple.
    base = int(rng.integers(2, 9)) * max(p, 1) * 8
    n = base + (int(rng.integers(1, 8)) * p if rng.random() < 0.4 else 0)
    d = int(rng.choice([4, 8, 16]))
    causal = bool(rng.random() < 0.6)
    dtype = str(rng.choice(["float32", "float32", "bfloat16"]))
    grad = bool(rng.random() < 0.35) and dtype == "float32"
    return variant, p, h, hkv, n, d, causal, dtype, grad


def _cases():
    return [_sample(np.random.default_rng(46_100 + i)) for i in range(N_CASES)]


def test_sweep_covers_the_space():
    cases = _cases()
    variants = {c[0] for c in cases}
    assert variants == {"ring", "ulysses", "local"}, variants
    assert any(c[1] >= 4 for c in cases), "no multi-device mesh sampled"
    assert any(c[3] < c[2] for c in cases), "no GQA case sampled"
    assert any(c[8] for c in cases), "no gradient case sampled"
    assert any(c[4] % _CHUNK for c in cases), "no non-multiple length"
    assert any(c[7] == "bfloat16" for c in cases), "no bf16 case"
    # The flash custom_vjp engages when a gradient case's local sequence
    # exceeds the (shrunk) chunk: ulysses/local see the full n.
    assert any(c[8] and c[0] in ("ulysses", "local") and c[4] > _CHUNK
               for c in cases), "no flash-backward case sampled"
    # The RING flash backward (_ring_flash_bwd: counter-rotating dk/dv
    # accumulators) engages on any multi-device ring gradient case.
    assert any(c[8] and c[0] == "ring" and c[1] > 1
               for c in cases), "no ring-flash-backward case sampled"
    # Ring cases additionally re-run under the striped/zigzag layout
    # whenever it's legal (seq % 2p == 0); the half-block causal hops —
    # forward AND backward — need a legal causal (grad) case to sample.
    def zz_legal(c):
        return c[0] == "ring" and c[1] > 1 and c[4] % (2 * c[1]) == 0
    assert any(zz_legal(c) and c[6] for c in cases), "no causal zigzag run"
    assert any(zz_legal(c) and c[6] and c[8]
               for c in cases), "no causal zigzag gradient run"


@pytest.fixture(autouse=True)
def _small_chunk(monkeypatch):
    monkeypatch.setattr(context, "_Q_CHUNK", _CHUNK)
    jax.clear_caches()
    yield
    jax.clear_caches()


@pytest.mark.parametrize("case", range(N_CASES))
def test_random_attention_parity(case, rng):
    variant, p, h, hkv, n, d, causal, dtype, grad = _sample(
        np.random.default_rng(46_100 + case))
    dt = jnp.dtype(dtype)
    q = jnp.asarray(rng.standard_normal((h, n, d)), dt)
    k = jnp.asarray(rng.standard_normal((hkv, n, d)), dt)
    v = jnp.asarray(rng.standard_normal((hkv, n, d)), dt)
    kr = jnp.repeat(k, h // hkv, axis=0).astype(jnp.float32)
    vr = jnp.repeat(v, h // hkv, axis=0).astype(jnp.float32)
    q32 = q.astype(jnp.float32)

    if variant == "local":
        def fn(q_, k_, v_):
            # Public single-device engine; GQA stays un-expanded.
            return flash_attention(q_, k_, v_, causal=causal)
    else:
        mesh = mesh_lib.make_mesh_1d(p, axis="sp")
        impl = ring_attention if variant == "ring" else ulysses_attention

        def fn(q_, k_, v_):
            return impl(q_, k_, v_, mesh=mesh, causal=causal)

    tag = (f"{variant} p={p} h={h}/{hkv} n={n} d={d} causal={causal} "
           f"{dtype} grad={grad}")
    tol = 1e-4 if dtype == "float32" else 5e-2
    want = attention_reference(q32, kr, vr, causal=causal)
    got = np.asarray(fn(q, k, v), dtype=np.float32)
    np.testing.assert_allclose(got, np.asarray(want), rtol=tol, atol=tol,
                               err_msg=tag)

    if grad:
        def loss(f, q_, k_, v_):
            return jnp.sum(f(q_, k_, v_).astype(jnp.float32) ** 2)

        g_got = jax.grad(lambda *a: loss(fn, *a), argnums=(0, 1, 2))(q, k, v)
        g_want = jax.grad(
            lambda q_, k_, v_: loss(
                lambda a, b, c: attention_reference(
                    a, jnp.repeat(b, h // hkv, axis=0),
                    jnp.repeat(c, h // hkv, axis=0), causal=causal),
                q_, k_, v_),
            argnums=(0, 1, 2))(q32, k.astype(jnp.float32),
                               v.astype(jnp.float32))
        for gg, gw, name in zip(g_got, g_want, "qkv"):
            np.testing.assert_allclose(
                np.asarray(gg, dtype=np.float32), np.asarray(gw),
                rtol=1e-3, atol=1e-3, err_msg=f"{tag} d{name}")

    # Every ring case ALSO runs the striped/zigzag layout when legal
    # (coverage superset — the contiguous sweep above is untouched):
    # permute in, un-permute out, so outputs and autodiff gradients
    # compare directly against the same natural-order oracle.
    if variant == "ring" and p > 1 and n % (2 * p) == 0:
        def fn_zz(q_, k_, v_):
            o = ring_attention(
                zigzag_shard(q_, p), zigzag_shard(k_, p),
                zigzag_shard(v_, p), mesh=mesh, causal=causal,
                layout="zigzag")
            return zigzag_unshard(o, p)

        got_zz = np.asarray(fn_zz(q, k, v), dtype=np.float32)
        np.testing.assert_allclose(got_zz, np.asarray(want), rtol=tol,
                                   atol=tol, err_msg=f"{tag} zigzag")
        if grad:
            g_zz = jax.grad(lambda *a: loss(fn_zz, *a),
                            argnums=(0, 1, 2))(q, k, v)
            for gg, gw, name in zip(g_zz, g_want, "qkv"):
                np.testing.assert_allclose(
                    np.asarray(gg, dtype=np.float32), np.asarray(gw),
                    rtol=1e-3, atol=1e-3, err_msg=f"{tag} zigzag d{name}")
