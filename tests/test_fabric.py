"""Fabric probe: CSV schema, data movement correctness, and the α+β fit."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from mpi_and_open_mp_tpu.parallel import fabric, mesh as mesh_lib


def test_ring_shift_moves_data():
    mesh = mesh_lib.make_mesh_1d(8, axis="i")
    buf = jnp.arange(8, dtype=jnp.int8)
    buf = jax.device_put(buf, NamedSharding(mesh, P("i")))
    out = fabric._ring_shift_loop(buf, axis="i", reps=3, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(out), np.roll(np.arange(8), 3))


def test_sweep_schema_and_csv(tmp_path):
    mesh = mesh_lib.make_mesh_1d(2, axis="i")
    rows = fabric.sweep(mesh, sizes=(1, 10, 100), reps=3)
    assert [s for s, _ in rows] == [1, 10, 100]
    assert all(us > 0 for _, us in rows)
    path = tmp_path / "out.csv"
    fabric.write_csv(path, rows)
    lines = path.read_text().strip().split("\n")
    assert lines[0] == "size,time"
    assert lines[1].startswith("1,")


def test_fit_alpha_beta_recovers_model():
    # Synthetic t = 2.5 + 0.001*n (alpha 2.5us, bandwidth 1000 MB/s).
    rows = [(n, 2.5 + 0.001 * n) for n in (1, 10, 100, 1000, 10**4, 10**5, 10**6)]
    fit = fabric.fit_alpha_beta(rows)
    assert fit.alpha_us == pytest.approx(2.5, rel=1e-6)
    assert fit.bandwidth_mb_s == pytest.approx(1000.0, rel=1e-6)
    assert fit.identifiable
    assert fit.r2 == pytest.approx(1.0, abs=1e-9)


def test_fit_alpha_beta_noise_dominated_flagged():
    """A β ≤ 0 slope (noise-dominated probe, seen on loopback Gloo) must
    come back flagged unidentifiable — with α degraded to the mean
    latency — instead of a numeric "infinite bandwidth"."""
    import math

    rows = [(1, 3200.0), (10, 3100.0), (100, 3300.0), (1000, 3150.0),
            (10**4, 3250.0), (10**5, 3050.0), (10**6, 3000.0)]
    fit = fabric.fit_alpha_beta(rows)
    assert not fit.identifiable
    assert math.isinf(fit.bandwidth_mb_s)
    assert fit.alpha_us == pytest.approx(
        sum(t for _, t in rows) / len(rows))
    assert fit.r2 < 0.9


def test_fit_as_json_identifiable():
    import json

    # t = 5 + 0.01*n: alpha 5us, beta 0.01us/byte -> 100 MB/s.
    rows = [(n, 5.0 + 0.01 * n) for n in (1, 10, 100, 1000, 10**4, 10**5)]
    d = fabric.fit_alpha_beta(rows).as_json()
    assert d["identifiable"] is True
    assert d["alpha_us"] == pytest.approx(5.0, rel=1e-4)
    assert d["bandwidth_mb_s"] == pytest.approx(100.0, rel=1e-4)
    assert d["beta_us_per_byte"] == pytest.approx(0.01, rel=1e-4)
    assert d["r2"] == pytest.approx(1.0, abs=1e-6)
    # The whole point of as_json: strict-parser round trip, no Infinity.
    assert json.loads(json.dumps(d)) == d


def test_fit_as_json_unidentifiable_has_no_infinity():
    import json

    # Decreasing times with size: the fitted slope is strictly negative.
    rows = [(1, 3200.0), (10, 3100.0), (100, 3000.0), (1000, 2900.0)]
    d = fabric.fit_alpha_beta(rows).as_json()
    assert d["identifiable"] is False
    assert d["bandwidth_mb_s"] is None
    assert d["beta_us_per_byte"] == 0.0
    assert "Infinity" not in json.dumps(d)
