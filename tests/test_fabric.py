"""Fabric probe: CSV schema, data movement correctness, and the α+β fit."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from mpi_and_open_mp_tpu.parallel import fabric, mesh as mesh_lib


def test_ring_shift_moves_data():
    mesh = mesh_lib.make_mesh_1d(8, axis="i")
    buf = jnp.arange(8, dtype=jnp.int8)
    buf = jax.device_put(buf, NamedSharding(mesh, P("i")))
    out = fabric._ring_shift_loop(buf, axis="i", reps=3, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(out), np.roll(np.arange(8), 3))


def test_sweep_schema_and_csv(tmp_path):
    mesh = mesh_lib.make_mesh_1d(2, axis="i")
    rows = fabric.sweep(mesh, sizes=(1, 10, 100), reps=3)
    assert [s for s, _ in rows] == [1, 10, 100]
    assert all(us > 0 for _, us in rows)
    path = tmp_path / "out.csv"
    fabric.write_csv(path, rows)
    lines = path.read_text().strip().split("\n")
    assert lines[0] == "size,time"
    assert lines[1].startswith("1,")


def test_fit_alpha_beta_recovers_model():
    # Synthetic t = 2.5 + 0.001*n (alpha 2.5us, bandwidth 1000 MB/s).
    rows = [(n, 2.5 + 0.001 * n) for n in (1, 10, 100, 1000, 10**4, 10**5, 10**6)]
    alpha, bw = fabric.fit_alpha_beta(rows)
    assert alpha == pytest.approx(2.5, rel=1e-6)
    assert bw == pytest.approx(1000.0, rel=1e-6)
