"""End-to-end LifeSim parity across layouts, impls, meshes, and fusion depths.

Every sharded configuration must produce a board bit-identical to the NumPy
oracle — the framework analogue of the reference's serial-vs-MPI VTK parity
(SURVEY §4). Runs on the 8-virtual-CPU-device mesh from conftest.
"""

import os

import numpy as np
import pytest


from mpi_and_open_mp_tpu.models.life import LifeSim
from mpi_and_open_mp_tpu.parallel import mesh as mesh_lib
from mpi_and_open_mp_tpu.utils.config import config_from_board, load_config_py
from mpi_and_open_mp_tpu.utils.vtk import read_vtk

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


from conftest import oracle_n  # noqa: E402


@pytest.mark.parametrize("layout", ["serial", "row", "col", "cart"])
@pytest.mark.parametrize("impl", ["roll", "halo"])
def test_parity_divisible_board(make_board, layout, impl):
    if layout == "serial" and impl == "halo":
        with pytest.raises(ValueError, match="sharded layout"):
            LifeSim(config_from_board(make_board(8, 8), 1, 1),
                    layout="serial", impl="halo")
        return
    board = make_board(48, 40)  # divides 8 (row), 8 (col), and 4x2 (cart)
    cfg = config_from_board(board, steps=20, save_steps=1000)
    sim = LifeSim(cfg, layout=layout, impl=impl)
    sim.step(20)
    np.testing.assert_array_equal(sim.collect(), oracle_n(board, 20))


@pytest.mark.parametrize("layout", ["row", "col", "cart"])
def test_parity_uneven_board_roll(make_board, layout):
    """Non-divisible boards (the reference's last-rank-absorbs-remainder
    case, 3-life/life_mpi.c:178-183) via the global roll step."""
    board = make_board(50, 37)
    cfg = config_from_board(board, steps=15, save_steps=1000)
    sim = LifeSim(cfg, layout=layout, impl="roll")
    sim.step(15)
    np.testing.assert_array_equal(sim.collect(), oracle_n(board, 15))


@pytest.mark.parametrize("fuse", [2, 3, 5])
@pytest.mark.parametrize("layout", ["row", "col", "cart"])
def test_parity_fused_halo_steps(make_board, layout, fuse):
    """Depth-k halo fusion: k local steps per exchange, incl. a non-divisible
    remainder round (17 = 3*5 + 2 etc.)."""
    board = make_board(48, 40)
    cfg = config_from_board(board, steps=17, save_steps=1000)
    sim = LifeSim(cfg, layout=layout, impl="halo", fuse_steps=fuse)
    sim.step(17)
    np.testing.assert_array_equal(sim.collect(), oracle_n(board, 17))


@pytest.mark.parametrize("steps", [5, 130])
def test_parity_bitfused_row_ring(make_board, steps):
    """The packed scale-out path: ppermute 4-word halos + <=128 fused steps
    per round. 130 steps crosses a round boundary, so the second round's
    halo exchange carries first-round state."""
    board = make_board(2048, 128, density=0.35)  # 8 shards x 8 word rows
    cfg = config_from_board(board, steps=steps, save_steps=1000)
    sim = LifeSim(cfg, layout="row", impl="bitfused")
    sim.step(steps)
    np.testing.assert_array_equal(sim.collect(), oracle_n(board, steps))


def test_bitfused_segmented_run_and_debug(make_board, tmp_path):
    """run() with a save cadence drives advance at several segment lengths
    through ONE compiled program (n is a runtime scalar), and the halo
    debug check passes on the live sharded state."""
    board = make_board(2048, 128, density=0.3)
    cfg = config_from_board(board, steps=9, save_steps=4)
    sim = LifeSim(cfg, layout="row", impl="bitfused", outdir=tmp_path)
    sim.debug_check()
    final = sim.run(save=True)
    np.testing.assert_array_equal(final, oracle_n(board, 9))
    assert len(list(tmp_path.glob("*.vtk"))) == 3  # steps 0, 4, 8


@pytest.mark.parametrize("steps", [5, 130])
def test_parity_bitfused_col_strips(make_board, steps):
    """Column-strip bitfused: 128-column ppermute halos along x, local
    y wrap (the py=1 cart case). 8 shards of 1024x128."""
    board = make_board(1024, 1024, density=0.35)
    cfg = config_from_board(board, steps=steps, save_steps=1000)
    sim = LifeSim(cfg, layout="col", impl="bitfused")
    sim.step(steps)
    np.testing.assert_array_equal(sim.collect(), oracle_n(board, steps))


@pytest.mark.parametrize("steps", [5, 130])
def test_parity_bitfused_cart_mesh(make_board, steps):
    """The 2-D cart bitfused path: 128-column x halo + 4-word y halo per
    round (corners via the sequenced exchange), <=128 fused steps. The
    4x2 mesh gives 256x128 shards; 130 steps crosses a round boundary."""
    board = make_board(1024, 256, density=0.35)
    mesh = mesh_lib.make_mesh_2d(4, 2)
    cfg = config_from_board(board, steps=steps, save_steps=1000)
    sim = LifeSim(cfg, layout="cart", impl="bitfused", mesh=mesh)
    sim.step(steps)
    np.testing.assert_array_equal(sim.collect(), oracle_n(board, steps))


@pytest.mark.parametrize(
    "shape,layout,mesh_args,steps",
    [
        # The flagship geometry (3-life/p46gun_big.cfg): 500x500 on the
        # 8-way ring — 512x512 frame, 2-word shards, window stepper,
        # k_max=32 so 40 steps crosses a round boundary.
        ((500, 500), "row", None, 40),
        # 500x500 on the default 2-D mesh: funnel y wrap + mirror x wrap
        # + corners, k_max=96.
        ((500, 500), "cart", (4, 2), 100),
        # Narrow column strips: 8-column re-pitch, shrunken x halo.
        ((500, 500), "col", None, 60),
        # Small unaligned boards, both axes padded (row on a 2-D mesh
        # shards y only; a 2-way ring leaves room for the halo).
        ((100, 130), "row", (2, 4), 40),
        ((100, 300), "cart", (2, 2), 40),
        # Previously gate-rejected aligned-ish shapes, now planned:
        ((2040, 128), "row", None, 140),   # ny % (32*8) != 0
        ((2048, 120), "row", None, 140),   # nx % 128 != 0 (patched rolls)
        ((1024, 192), "cart", (4, 2), 100),  # 96-col shards, narrow pitch
    ],
)
def test_parity_bitfused_unaligned(make_board, shape, layout, mesh_args, steps):
    """Arbitrary board shapes through the packed fused path: the torus
    lives in a word/lane-aligned padded frame with periodic mirrors and
    funnel-shifted wrap halos (ops.bitlife module docs); every
    combination must stay bit-exact across fused-round boundaries."""
    board = make_board(*shape, density=0.35)
    mesh = mesh_lib.make_mesh_2d(*mesh_args) if mesh_args else None
    cfg = config_from_board(board, steps=steps, save_steps=1000)
    sim = LifeSim(cfg, layout=layout, impl="bitfused", mesh=mesh)
    assert steps > sim._plan.k_max, "steps must cross a fused round"
    sim.step(steps)
    np.testing.assert_array_equal(sim.collect(), oracle_n(board, steps))


def test_bitfused_1dev_serial_dispatch(make_board, monkeypatch):
    """A 1-device mesh has no neighbours: the bitfused path dispatches
    to the serial whole-board stepper (no ghost-window redundancy, no
    exchange rounds), sliced out of / re-padded into the plan's frame —
    and must stay bit-exact, including across what would have been
    fused-round boundaries. CPU-gated behind the test flag so the
    interpret suite's machinery coverage is unchanged by default."""
    from mpi_and_open_mp_tpu.models import life as life_mod
    from mpi_and_open_mp_tpu.parallel import mesh as mesh_lib

    board = make_board(100, 130)
    cfg = config_from_board(board, steps=150, save_steps=0)
    mesh = mesh_lib.make_mesh_1d(1, axis="y")

    # Default on CPU: the exchange machinery runs even on 1 device.
    sim_default = LifeSim(cfg, layout="row", impl="bitfused", mesh=mesh)
    assert sim_default.plan_note == sim_default._plan.mode

    monkeypatch.setattr(life_mod, "_BITFUSED_1DEV_SERIAL_ON_CPU", True)
    sim = LifeSim(cfg, layout="row", impl="bitfused", mesh=mesh)
    assert sim.plan_note.startswith("serial-1dev:")
    sim.step(150)  # crosses the machinery's k_max round boundary
    np.testing.assert_array_equal(sim.collect(), oracle_n(board, 150))
    # The sharded-state contract survives: the stored board keeps the
    # plan's frame shape, so snapshots/checkpoints are unaffected.
    assert sim.board.shape == sim._plan.frame


def test_bitfused_gates(make_board):
    with pytest.raises(ValueError, match="sharded layout"):
        LifeSim(config_from_board(make_board(2048, 128), 1, 1),
                layout="serial", impl="bitfused")
    # Genuinely unplannable: 64 rows over 8 shards leaves no room for a
    # fused halo next to the 192 frame-padding rows.
    with pytest.raises(ValueError, match="can't plan"):
        LifeSim(config_from_board(make_board(64, 128), 1, 1),
                layout="row", impl="bitfused")
    # Same on a 2-D mesh: 20-column shards can't feed an 8-column x halo.
    with pytest.raises(ValueError, match="can't plan"):
        LifeSim(config_from_board(make_board(256, 20), 1, 1),
                layout="cart", impl="bitfused",
                mesh=mesh_lib.make_mesh_2d(4, 2))


def test_parity_explicit_meshes(make_board):
    board = make_board(48, 40)
    for py, px in [(2, 4), (8, 1), (1, 8), (2, 2)]:
        mesh = mesh_lib.make_mesh_2d(py, px)
        cfg = config_from_board(board, steps=12, save_steps=1000)
        sim = LifeSim(cfg, layout="cart", impl="halo", mesh=mesh)
        sim.step(12)
        np.testing.assert_array_equal(sim.collect(), oracle_n(board, 12))


def test_auto_impl_selection(make_board):
    cfg = config_from_board(make_board(48, 40), steps=4, save_steps=10)
    assert LifeSim(cfg, layout="row", impl="auto").impl == "halo"
    cfg2 = config_from_board(make_board(50, 37), steps=4, save_steps=10)
    assert LifeSim(cfg2, layout="row", impl="auto").impl == "roll"
    with pytest.raises(ValueError):
        LifeSim(cfg2, layout="row", impl="halo")


def test_auto_selects_bitfused_on_tpu(monkeypatch, make_board):
    """On a TPU backend, auto must route the unaligned flagship geometry
    (500x500, any mesh) onto the packed fused path — construction only,
    so the faked backend never has to compile Mosaic on CPU."""
    import mpi_and_open_mp_tpu.models.life as life_mod

    monkeypatch.setattr(life_mod.jax, "default_backend", lambda: "tpu")
    cfg = config_from_board(make_board(500, 500), steps=4, save_steps=10)
    for layout in ("row", "col", "cart"):
        assert LifeSim(cfg, layout=layout, impl="auto").impl == "bitfused"
    # Geometry the planner rejects still falls back.
    cfg2 = config_from_board(make_board(64, 128), steps=4, save_steps=10)
    assert LifeSim(cfg2, layout="row", impl="auto").impl == "halo"


def test_glider_fixture_end_to_end(tmp_path):
    """Full driver contract: cfg in, VTK snapshots out at the reference's
    cadence (save at i % save_steps == 0, before stepping)."""
    cfg = load_config_py(os.path.join(FIXTURES, "glider_10x10.cfg"))
    outdir = tmp_path / "vtk"
    sim = LifeSim(cfg, layout="serial", impl="roll", outdir=outdir)
    final = sim.run(save=True)
    saved = sorted(os.listdir(outdir))
    assert saved == [f"life_{i:06d}.vtk" for i in (0, 25, 50, 75)]
    # Glider on a 10x10 torus has period 40; after 100 steps it sits at
    # the 60-step phase: shifted by (100//4) % 10 = 5 in both axes.
    start = cfg.board()
    np.testing.assert_array_equal(final, oracle_n(start, 100))
    np.testing.assert_array_equal(
        read_vtk(outdir / "life_000075.vtk"), oracle_n(start, 75)
    )


def test_rpentomino_fixture_all_layouts():
    cfg = load_config_py(os.path.join(FIXTURES, "rpentomino_40x32.cfg"))
    start = cfg.board()
    expect = oracle_n(start, cfg.steps)
    assert expect.sum() > 0  # r-pentomino is long-lived
    for layout in ["row", "col", "cart"]:
        sim = LifeSim(cfg, layout=layout, impl="auto")
        got = sim.run(save=False)
        np.testing.assert_array_equal(got, expect)


def test_empty_fixture():
    cfg = load_config_py(os.path.join(FIXTURES, "empty_10x10.cfg"))
    sim = LifeSim(cfg, layout="row", impl="roll")
    assert sim.run(save=False).sum() == 0
