"""Durable AOT executable cache (serve.aotcache) — the warm-resume
contract and its hardening.

The claims under test, in the ISSUE's words: a second process resumes
from the cache with ZERO ``jit.retrace{fn=life_batch_*}`` ticks and
oracle parity on every resolved ticket; a corrupt/truncated/key-stale
artifact is quarantined and the daemon falls back to a fresh trace with
``aot:*:corrupt``/``aot:*:stale`` provenance, losing nothing; and the
parity gate catches even a CRC-valid artifact that computes wrong
answers. All on the 8-virtual-device CPU mesh — ``jax.export``
serializes the CPU lowering exactly as it would the TPU one.
"""

import glob
import json
import os

import numpy as np
import pytest

from conftest import oracle_n
from mpi_and_open_mp_tpu.obs import metrics
from mpi_and_open_mp_tpu.robust import chaos
from mpi_and_open_mp_tpu.serve import ServePolicy, ServingDaemon
from mpi_and_open_mp_tpu.serve import aotcache


def _life_batch_retraces() -> dict:
    return {k: v for k, v in metrics.snapshot()["counters"].items()
            if k.startswith("jit.retrace{fn=life_batch")}


# -- keying ----------------------------------------------------------------


def test_bucket_sizes_enumeration():
    assert aotcache.bucket_sizes(8) == [1, 2, 4, 8]
    assert aotcache.bucket_sizes(1) == [1]
    assert aotcache.bucket_sizes(6) == [1, 2, 4, 6]  # cap is literal


def test_fingerprint_sensitivity():
    """Every field that can change the compiled program changes the
    digest; identical inputs reproduce it (the filename is the key)."""
    base = aotcache.fingerprint((4, 16, 16), np.uint8)
    assert base["steps"] == aotcache.STEPS_SIGNATURE
    assert base["bucket"] == 4 and base["shape"] == [16, 16]
    assert base["code"] == aotcache.code_fingerprint()
    d = aotcache.digest_for(base)
    assert d == aotcache.digest_for(aotcache.fingerprint((4, 16, 16),
                                                         np.uint8))
    others = [
        aotcache.fingerprint((8, 16, 16), np.uint8),   # bucket
        aotcache.fingerprint((4, 16, 24), np.uint8),   # shape
        aotcache.fingerprint((4, 16, 16), np.int32),   # dtype
        dict(base, jax="0.0.0"),                       # version skew
        dict(base, code="f" * 16),                     # edited kernels
    ]
    digests = {aotcache.digest_for(k) for k in others}
    assert d not in digests and len(digests) == 5


# -- round trip + the zero-retrace guarantee -------------------------------


def test_cold_build_then_warm_hit_zero_retraces(tmp_path, make_board):
    """The tentpole proof: pass 1 builds (ticking the honest compile
    counter once per bucket) and persists; pass 2 — a fresh AOTCache,
    i.e. a restarted process's view — deserializes every program and
    runs it with ZERO life_batch retrace ticks, bit-exact."""
    metrics.reset()
    c1 = aotcache.AOTCache(tmp_path)
    w1 = c1.warm([((16, 16), "uint8")], 4)
    assert w1 == {"hits": 0, "misses": 3, "corrupt": 0, "stale": 0,
                  "parity_failed": 0, "built": 3, "errors": 0,
                  "deserialize_s": 0.0,
                  "build_s": w1["build_s"], "programs": 3}
    assert w1["build_s"] > 0
    assert _life_batch_retraces() == {"jit.retrace{fn=life_batch_xla}": 3}
    assert len(glob.glob(str(tmp_path / "*.aot"))) == 3

    metrics.reset()
    c2 = aotcache.AOTCache(tmp_path)
    w2 = c2.warm([((16, 16), "uint8")], 4)
    assert w2["hits"] == 3 and w2["misses"] == 0 and w2["built"] == 0
    assert w2["deserialize_s"] > 0
    board = make_board(16, 16)
    stack = np.stack([np.asarray(board)] * 2)
    digest, exp, status = c2.ensure(stack.shape, stack.dtype)
    assert status == "memory" and exp is not None
    out = c2.call_verified(digest, stack, 5)
    np.testing.assert_array_equal(out[0], oracle_n(board, 5))
    # steps is a runtime scalar: the SAME program serves other counts.
    out2 = c2.call_verified(digest, stack, 9)
    np.testing.assert_array_equal(out2[0], oracle_n(board, 9))
    assert _life_batch_retraces() == {}


def test_truncated_artifact_quarantined_and_rebuilt(tmp_path):
    aotcache.AOTCache(tmp_path).warm([((12, 12), "uint8")], 1)
    (art,) = glob.glob(str(tmp_path / "*.aot"))
    with open(art, "r+b") as fd:
        fd.truncate(30)  # inside the header
    c = aotcache.AOTCache(tmp_path)
    digest, exp, status = c.ensure((1, 12, 12), np.uint8)
    assert status == "corrupt" and exp is not None  # rebuilt in place
    assert c.stats()["corrupt"] == 1 and c.stats()["built"] == 1
    q = glob.glob(art + ".corrupt.*")
    assert len(q) == 1  # forensic copy, stamped
    assert os.path.exists(art)  # fresh artifact re-persisted
    # And the replacement round-trips clean.
    _, _, status2 = aotcache.AOTCache(tmp_path).ensure((1, 12, 12),
                                                       np.uint8)
    assert status2 == "hit"


def test_stale_key_artifact_rejected(tmp_path):
    """A CRC-valid envelope whose stored fingerprint drifted (here: the
    code hash — edited kernels) is stale, quarantined, rebuilt."""
    key = aotcache.fingerprint((1, 12, 12), np.uint8)
    c0 = aotcache.AOTCache(tmp_path)
    digest, exp, _ = c0.ensure((1, 12, 12), np.uint8)
    path = str(tmp_path / (digest + ".aot"))
    aotcache.save_artifact(path, dict(key, code="0" * 16),
                           exp.serialize())
    c = aotcache.AOTCache(tmp_path)
    _, exp2, status = c.ensure((1, 12, 12), np.uint8)
    assert status == "stale" and exp2 is not None
    assert glob.glob(path + ".stale.*")


def test_parity_gate_catches_wrong_program(tmp_path, make_board):
    """The last line of defense: an artifact that is bit-perfect on disk
    but computes the WRONG function (here: identity instead of Life)
    fails the first-use oracle gate — quarantined, evicted, raised."""
    import jax
    import jax.numpy as jnp
    from jax import export as jax_export

    key = aotcache.fingerprint((1, 12, 12), np.uint8)
    digest = aotcache.digest_for(key)
    wrong = jax_export.export(jax.jit(lambda boards, steps: boards))(
        jax.ShapeDtypeStruct((1, 12, 12), jnp.uint8),
        jax.ShapeDtypeStruct((), jnp.int32))
    path = str(tmp_path / (digest + ".aot"))
    aotcache.save_artifact(path, key, wrong.serialize())

    c = aotcache.AOTCache(tmp_path)
    got, exp, status = c.ensure((1, 12, 12), np.uint8)
    assert got == digest and status == "hit"  # envelope + key check out
    stack = np.asarray(make_board(12, 12))[None]
    with pytest.raises(aotcache.ParityError, match="oracle"):
        c.call_verified(digest, stack, 3)
    assert c.stats()["parity_failed"] == 1
    assert glob.glob(path + ".corrupt.*")  # artifact quarantined
    # Evicted from memory: the next ensure is a rebuild, and it serves.
    _, exp2, status2 = c.ensure((1, 12, 12), np.uint8)
    assert status2 == "miss" and exp2 is not None
    out = c.call_verified(digest, stack, 3)
    np.testing.assert_array_equal(out[0], oracle_n(stack[0], 3))


# -- chaos tokens ----------------------------------------------------------


def test_chaos_token_parse_and_budget(monkeypatch):
    for spec, kind, k in [("aot_corrupt=bitflip:2", "bitflip", 2),
                          ("aot_corrupt=skew", "skew", 1)]:
        plan = chaos.FaultPlan.parse(spec)
        assert (plan.aot_corrupt_kind, plan.aot_corrupt) == (kind, k)
    for bad in ["aot_corrupt=gamma:1", "aot_corrupt=bitflip:0",
                "aot_corrupt="]:
        with pytest.raises(ValueError, match="MOMP_CHAOS"):
            chaos.FaultPlan.parse(bad)

    monkeypatch.setenv("MOMP_CHAOS", "aot_corrupt=bitflip:2")
    chaos.reset()
    assert chaos.take_aot_corrupt() == "bitflip"
    with chaos.suppressed():
        assert chaos.take_aot_corrupt() is None  # recovery writes clean
    assert chaos.take_aot_corrupt() == "bitflip"
    assert chaos.take_aot_corrupt() is None  # budget spent
    chaos.reset()


@pytest.mark.parametrize("kind,status", [("bitflip", "corrupt"),
                                         ("skew", "stale")])
def test_chaos_corrupts_artifact_at_save(tmp_path, monkeypatch, kind,
                                         status):
    """The drill the CI job runs in-process: the plan damages the FIRST
    saved artifact on disk (the saving process's resident program stays
    good), and the next process's load takes exactly the planned
    rejection path, quarantines, rebuilds, and serves."""
    monkeypatch.setenv("MOMP_CHAOS", f"aot_corrupt={kind}:1")
    chaos.reset()
    c1 = aotcache.AOTCache(tmp_path)
    w = c1.warm([((12, 12), "uint8")], 2)
    assert w["built"] == 2  # both programs fine in memory
    monkeypatch.delenv("MOMP_CHAOS")
    chaos.reset()

    c2 = aotcache.AOTCache(tmp_path)
    w2 = c2.warm([((12, 12), "uint8")], 2)
    assert w2[status] == 1 and w2["hits"] == 1 and w2["built"] == 1
    assert len(glob.glob(str(tmp_path / f"*.{status}.*"))) == 1


# -- daemon integration ----------------------------------------------------


def test_daemon_cold_warm_cycle_books_and_provenance(tmp_path,
                                                     make_board):
    """Cold daemon populates the cache and serves through the aot rung;
    a second 'process' (fresh cache + daemon + metrics) serves the same
    shapes warm: all hits, zero retraces, every board oracle-exact,
    books balanced."""
    pol = ServePolicy(max_batch=4, max_wait_s=0.0)
    boards = [make_board(16, 16) for _ in range(6)]

    metrics.reset()
    d1 = ServingDaemon(pol, aot_cache=aotcache.AOTCache(tmp_path))
    d1._aot.warm([((16, 16), "uint8")], pol.max_batch)
    for b in boards:
        d1.submit(b, 3)
    d1.serve(watch_signals=False)
    s1 = d1.summary()
    assert s1["resolved"] == 6 and s1["engines"] == {"aot:xla": 6}
    assert s1["aot_misses"] == 3 and s1["cold_first_result_s"] > 0

    metrics.reset()
    d2 = ServingDaemon(pol, aot_cache=aotcache.AOTCache(tmp_path))
    d2._aot.warm([((16, 16), "uint8")], pol.max_batch)
    for b in boards:
        d2.submit(b, 7)
    d2.serve(watch_signals=False)
    s2 = d2.summary()
    assert s2["requests"] == s2["resolved"] == 6 and s2["shed"] == 0
    assert s2["engines"] == {"aot:xla": 6}
    assert s2["aot_hits"] == 3 and s2["aot_misses"] == 0
    assert s2["aot_deserialize_s"] > 0 and s2["aot_build_s"] == 0
    assert _life_batch_retraces() == {}
    for t, b in zip(d2.queue.tickets(), boards):
        np.testing.assert_array_equal(t.result, oracle_n(b, 7))


def test_daemon_corrupt_cache_falls_back_with_provenance(tmp_path,
                                                         make_board):
    """A rotten artifact mid-cache costs a rebuild, never a ticket: the
    dispatch stamps carry the `aot:*:corrupt` provenance and the whole
    burst still resolves oracle-exact."""
    pol = ServePolicy(max_batch=2, max_wait_s=0.0)
    aotcache.AOTCache(tmp_path).warm([((12, 12), "uint8")], 2)
    for art in glob.glob(str(tmp_path / "*.aot")):
        with open(art, "r+b") as fd:
            fd.seek(60)
            fd.write(b"\xde\xad\xbe\xef")  # CRC breaks on next load
    d = ServingDaemon(pol, aot_cache=aotcache.AOTCache(tmp_path))
    boards = [make_board(12, 12) for _ in range(4)]
    for b in boards:
        d.submit(b, 2)
    d.serve(watch_signals=False)
    s = d.summary()
    assert s["resolved"] == 4 and s["shed"] == 0
    assert set(s["engines"]) <= {"aot:xla:corrupt", "aot:xla"}
    assert "aot:xla:corrupt" in s["engines"]
    assert s["aot_corrupt"] >= 1
    for t, b in zip(d.queue.tickets(), boards):
        np.testing.assert_array_equal(t.result, oracle_n(b, 2))


def test_resume_any_preloads_pending_shapes(tmp_path, make_board):
    """The resume preload phase: a WAL left by a dead daemon resumes
    with the cache attached; every bucket program for the restored
    pending set is resident BEFORE the first dispatch, and the drain
    runs entirely on the aot rung with zero retraces."""
    pol = ServePolicy(max_batch=4, max_wait_s=0.0)
    walp = str(tmp_path / "serve.wal")
    cache_dir = tmp_path / "aot"
    aotcache.AOTCache(cache_dir).warm([((16, 16), "uint8")], 4)

    # Process 1: admits but never dispatches (dies with a populated WAL).
    d1 = ServingDaemon(pol, wal_path=walp)
    boards = [make_board(16, 16) for _ in range(5)]
    for b in boards:
        d1.submit(b, 4)
    d1._wal.sync()

    metrics.reset()
    d2, source, detail = ServingDaemon.resume_any(
        wal_path=walp, policy=pol,
        aot_cache=aotcache.AOTCache(cache_dir))
    assert source == "wal" and d2.queue.depth() == 5
    pre = detail["aot_preload"]
    assert pre["hits"] == 3 and pre["misses"] == 0  # warm: pure deser
    d2.serve(watch_signals=False)
    s = d2.summary()
    assert s["resolved"] == 5 and s["engines"] == {"aot:xla": 5}
    assert _life_batch_retraces() == {}
    for t, b in zip(d2.queue.tickets(), boards):
        np.testing.assert_array_equal(t.result, oracle_n(b, 4))
    d2._wal.close()


def test_daemon_cli_aot_flag_and_env(tmp_path, capsys, monkeypatch):
    """CLI surface: --aot-cache stamps the warm/hit accounting on the
    line; MOMP_AOT_CACHE is the env twin; without either the line
    carries no aot fields (the cache is strictly opt-in)."""
    from mpi_and_open_mp_tpu.serve import daemon as daemon_cli

    cache_dir = str(tmp_path / "aot")
    rc = daemon_cli.main(["--requests", "6", "--max-batch", "4",
                          "--max-wait", "0", "--shapes", "16x16",
                          "--aot-cache", cache_dir, "--verify"])
    line = json.loads(capsys.readouterr().out.strip())
    assert rc == 0 and line["verified"] is True
    assert line["aot_cache"] == os.path.abspath(cache_dir)
    assert line["aot_warm"]["built"] == 3
    assert line["engines"] == {"aot:xla": 6}
    assert line["cold_first_result_s"] > 0

    monkeypatch.setenv("MOMP_AOT_CACHE", cache_dir)
    rc = daemon_cli.main(["--requests", "6", "--max-batch", "4",
                          "--max-wait", "0", "--shapes", "16x16",
                          "--verify"])
    line2 = json.loads(capsys.readouterr().out.strip())
    assert rc == 0 and line2["verified"] is True
    assert line2["aot_warm"]["hits"] == 3  # env twin found the artifacts
    assert line2["aot_misses"] == 0
    monkeypatch.delenv("MOMP_AOT_CACHE")

    rc = daemon_cli.main(["--requests", "2", "--max-batch", "2",
                          "--max-wait", "0", "--shapes", "16x16"])
    line3 = json.loads(capsys.readouterr().out.strip())
    assert rc == 0 and "aot_cache" not in line3 and "aot" not in line3
