"""Write-ahead ticket journal: torn tails, fsync ladder, crash matrix.

The durability contracts under test: ``replay`` reconstructs the exact
pending set from any clean frame prefix and treats a torn/corrupt tail
as truncation, never a traceback (seeded fuzzers over random cuts and
byte flips); the ``every-chunk`` policy buffers in USER space so its
loss bound is honest under SIGKILL; compaction rotates crash-atomically
(either the old self-contained journal or the new snapshot+head is
authoritative, never a mix); the daemon's resume ladder prefers WAL
over drain checkpoint over fresh; and the crash matrix — a real
subprocess hard-killed by ``MOMP_CHAOS crash=<site>:<k>`` at every
instrumented site — proves the per-policy loss bound over exactly the
set of ACKED tickets: zero under ``every-record`` (and, on process
death, under ``off``), at most one chunk under ``every-chunk``.
"""

import glob
import json
import os
import pickle
import subprocess
import sys
import time

import numpy as np
import pytest

from conftest import oracle_n
from mpi_and_open_mp_tpu.robust import chaos
from mpi_and_open_mp_tpu.serve import ServePolicy, ServingDaemon
from mpi_and_open_mp_tpu.serve import wal
from mpi_and_open_mp_tpu.serve.queue import DONE
from mpi_and_open_mp_tpu.utils import checkpoint as checkpoint_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DRIVER = os.path.join(REPO, "tests", "_wal_crash_driver.py")


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.reset()
    yield
    chaos.reset()


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def sleep(self, s: float) -> None:
        self.t += s


def _board(rng, n=12):
    return (rng.random((n, n)) < 0.3).astype(np.uint8)


# ------------------------------------------------------------------ basics


def test_wal_roundtrip_replay(tmp_path, rng):
    w = wal.TicketWAL(tmp_path / "t.wal")
    boards = [_board(rng) for _ in range(3)]
    for i, b in enumerate(boards):
        w.admit(i, b, 2, queued_s=0.5 * i)
    w.dispatch_begin([0, 1])
    w.resolve([0, 1], engine="batch:xla")
    w.shed([7], "queue-depth")  # an id never admitted: terminal-only
    w.close()

    rep = wal.replay(tmp_path / "t.wal")
    assert not rep.truncated and rep.frames == 6
    assert {e["id"] for e in rep.pending} == {2}
    np.testing.assert_array_equal(rep.pending[0]["board"], boards[2])
    assert rep.pending[0]["steps"] == 2
    assert rep.pending[0]["queued_s"] == pytest.approx(1.0)
    assert rep.pending[0]["wall"] == pytest.approx(time.time(), abs=60)
    assert rep.resolved_ids == {0, 1} and rep.shed_ids == {7}
    assert rep.in_flight_ids == set()
    assert rep.counts()["pending"] == 1


def test_wal_open_dispatch_replays_as_in_flight(tmp_path, rng):
    """DISPATCH without a covering RESOLVE = the process died mid-batch:
    the tickets stay pending (redispatch is idempotent) and are reported
    in_flight for the accounting line."""
    w = wal.TicketWAL(tmp_path / "t.wal")
    for i in range(4):
        w.admit(i, _board(rng), 2)
    w.dispatch_begin([0, 1, 2, 3])
    w.close()
    rep = wal.replay(tmp_path / "t.wal")
    assert {e["id"] for e in rep.pending} == {0, 1, 2, 3}
    assert rep.in_flight_ids == {0, 1, 2, 3}


def test_wal_rejects_non_journal_and_inconsistency(tmp_path, rng):
    with pytest.raises(ValueError, match="no readable"):
        wal.replay(tmp_path / "missing.wal")
    bad = tmp_path / "bad.wal"
    bad.write_bytes(b"definitely not a journal\n" * 4)
    with pytest.raises(ValueError, match="magic"):
        wal.replay(bad)

    w = wal.TicketWAL(tmp_path / "dup.wal")
    w.admit(5, _board(rng), 1)
    w.admit(5, _board(rng), 1)  # the writer should never do this
    w.close()
    with pytest.raises(ValueError, match="re-admits"):
        wal.replay(tmp_path / "dup.wal")

    w = wal.TicketWAL(tmp_path / "late.wal")
    w.admit(0, _board(rng), 1)
    w._append("COMPACT", {"generation": 1, "count": 0})  # not at head
    w.close()
    with pytest.raises(ValueError, match="COMPACT"):
        wal.replay(tmp_path / "late.wal")

    w = wal.TicketWAL(tmp_path / "unk.wal")
    w._append("FROB", {"x": 1})
    w.close()
    with pytest.raises(ValueError, match="unknown record type"):
        wal.replay(tmp_path / "unk.wal")

    with pytest.raises(ValueError, match="fsync policy"):
        wal.TicketWAL(tmp_path / "x.wal", fsync="sometimes")


# --------------------------------------------------------------- torn tails


def _parse_frames(path):
    """Independent mini-parser: byte spans + decoded records, so the
    fuzzers can compute the EXPECTED recovery for any prefix."""
    blob = open(path, "rb").read()
    assert blob.startswith(wal.WAL_MAGIC)
    off = len(wal.WAL_MAGIC)
    frames = []
    while off < len(blob):
        length, _crc = wal._FRAME.unpack_from(blob, off)
        end = off + wal._FRAME.size + length
        rtype, rec = pickle.loads(blob[off + wal._FRAME.size:end])
        frames.append({"start": off, "end": end, "rtype": rtype,
                       "rec": rec})
        off = end
    return blob, frames


def _expected_state(frames):
    pending, in_flight, resolved, shed = {}, set(), set(), set()
    for f in frames:
        r = f["rec"]
        if f["rtype"] == "ADMIT":
            pending[r["id"]] = r
        elif f["rtype"] == "DISPATCH":
            in_flight.update(i for i in r["ids"] if i in pending)
        elif f["rtype"] == "RESOLVE":
            for i in r["ids"]:
                pending.pop(i, None)
                in_flight.discard(i)
                resolved.add(i)
        elif f["rtype"] == "SHED":
            for i in r["ids"]:
                pending.pop(i, None)
                in_flight.discard(i)
                shed.add(i)
    return pending, in_flight, resolved, shed


def _build_journal(path, rng):
    w = wal.TicketWAL(path)
    nxt = 0
    for _ in range(5):
        batch = []
        for _ in range(int(rng.integers(2, 5))):
            w.admit(nxt, _board(rng, 8), int(rng.integers(1, 4)))
            batch.append(nxt)
            nxt += 1
        w.dispatch_begin(batch)
        if rng.random() < 0.7:
            w.resolve(batch, engine="batch:xla")
        else:
            w.shed(batch, "dispatch-failed")
    w.admit(nxt, _board(rng, 8), 2)  # leave one genuinely pending
    w.close()


def test_torn_write_fuzzer_random_cuts(tmp_path):
    """Seeded fuzz: the journal truncated at ANY byte offset must replay
    to exactly the state of its complete-frame prefix — never raise,
    never resurrect a terminal ticket, never drop a journaled one."""
    rng = np.random.default_rng(20260805)
    _build_journal(tmp_path / "full.wal", rng)
    blob, frames = _parse_frames(tmp_path / "full.wal")
    ends = {f["end"] for f in frames}

    cuts = sorted({int(c) for c in rng.integers(
        len(wal.WAL_MAGIC), len(blob), size=60)} | {len(blob) - 1})
    for cut in cuts:
        p = tmp_path / "cut.wal"
        p.write_bytes(blob[:cut])
        rep = wal.replay(p)
        keep = [f for f in frames if f["end"] <= cut]
        pending, in_flight, resolved, shed = _expected_state(keep)
        assert {e["id"] for e in rep.pending} == set(pending), f"cut={cut}"
        assert rep.in_flight_ids == in_flight, f"cut={cut}"
        assert rep.resolved_ids == resolved and rep.shed_ids == shed
        assert rep.truncated == (cut not in ends), f"cut={cut}"
        if rep.truncated:
            assert rep.truncated_at == (keep[-1]["end"] if keep
                                        else len(wal.WAL_MAGIC))


def test_torn_write_fuzzer_byte_flips(tmp_path):
    """Seeded fuzz: ONE flipped byte anywhere past the magic truncates
    replay at the frame containing it (CRC32 catches every single-byte
    error) — the clean prefix survives untouched."""
    rng = np.random.default_rng(48)
    _build_journal(tmp_path / "full.wal", rng)
    blob, frames = _parse_frames(tmp_path / "full.wal")

    offs = sorted({int(o) for o in rng.integers(
        len(wal.WAL_MAGIC), len(blob), size=40)})
    for off in offs:
        flipped = bytearray(blob)
        flipped[off] ^= 0x5A
        p = tmp_path / "flip.wal"
        p.write_bytes(bytes(flipped))
        rep = wal.replay(p)
        hit = next(f for f in frames if f["start"] <= off < f["end"])
        keep = [f for f in frames if f["end"] <= hit["start"]]
        pending, in_flight, resolved, shed = _expected_state(keep)
        assert {e["id"] for e in rep.pending} == set(pending), f"off={off}"
        assert rep.resolved_ids == resolved and rep.shed_ids == shed
        assert rep.truncated and rep.truncated_at == hit["start"]


# ------------------------------------------------------------- fsync ladder


def test_every_chunk_buffers_in_user_space(tmp_path, rng):
    """The honesty core of the ``every-chunk`` bound: records buffer in
    the PROCESS (invisible to a reader — exactly what a SIGKILL loses),
    flush at chunk-lifecycle records or a full buffer, and ``sync()``
    forces the rest out."""
    path = tmp_path / "c.wal"
    w = wal.TicketWAL(path, fsync="every-chunk", chunk_records=4)
    for i in range(3):
        w.admit(i, _board(rng), 1)
    assert wal.replay(path).counts()["pending"] == 0  # still buffered
    w.admit(3, _board(rng), 1)  # 4th record fills the buffer
    assert wal.replay(path).counts()["pending"] == 4
    w.admit(4, _board(rng), 1)
    assert wal.replay(path).counts()["pending"] == 4  # buffered again
    w.dispatch_begin([0, 1, 2, 3])  # chunk boundary flushes everything
    rep = wal.replay(path)
    assert rep.counts()["pending"] == 5 and rep.in_flight_ids == {0, 1, 2, 3}
    w.admit(5, _board(rng), 1)
    w.sync()
    assert wal.replay(path).counts()["pending"] == 6
    w.close()


def test_fsync_policy_stats(tmp_path, rng):
    per_record = wal.TicketWAL(tmp_path / "r.wal", fsync="every-record")
    off = wal.TicketWAL(tmp_path / "o.wal", fsync="off")
    for i in range(6):
        per_record.admit(i, _board(rng), 1)
        off.admit(i, _board(rng), 1)
    # +1: opening a fresh journal syncs its magic header (a one-time
    # cost every policy pays — the file's EXISTENCE should be durable).
    assert per_record.stats()["syncs"] == 7
    assert off.stats()["syncs"] == 1  # the header only, never an append
    assert per_record.stats()["records"] == off.stats()["records"] == 6
    assert per_record.stats()["bytes"] == off.stats()["bytes"] > 0
    per_record.close()
    off.close()


# -------------------------------------------------------------- compaction


def test_compaction_rotates_and_replays(tmp_path, rng):
    path = tmp_path / "c.wal"
    w = wal.TicketWAL(path, compact_bytes=1)  # rotate on any traffic
    boards = {i: _board(rng) for i in range(6)}
    for i in range(6):
        w.admit(i, boards[i], 3, queued_s=float(i))
    w.resolve([0, 1], engine="batch:xla")
    assert w.should_compact()
    size_before = os.path.getsize(path)
    w.compact([{"id": i, "board": boards[i], "steps": 3,
                "wall": time.time(), "queued_s": float(i)}
               for i in (2, 3, 4, 5)])
    assert os.path.getsize(path) < size_before
    assert os.path.exists(wal._snap_path(str(path), 1))
    assert w.stats()["compactions"] == 1 and w.stats()["generation"] == 1

    rep = wal.replay(path)
    assert rep.generation == 1 and not rep.truncated
    assert {e["id"] for e in rep.pending} == {2, 3, 4, 5}
    np.testing.assert_array_equal(rep.pending[0]["board"], boards[2])

    # The tail keeps appending after rotation and replays over the snap.
    w.resolve([2, 3], engine="batch:xla")
    rep = wal.replay(path)
    assert {e["id"] for e in rep.pending} == {4, 5}

    # A second rotation unlinks the superseded snapshot.
    w.compact([{"id": 4, "board": boards[4], "steps": 3}])
    assert not os.path.exists(wal._snap_path(str(path), 1))
    assert os.path.exists(wal._snap_path(str(path), 2))
    assert wal.replay(path).counts()["pending"] == 1
    w.close()


def test_compaction_crash_windows(tmp_path, rng):
    """Both halves of the rotation's crash window: an ORPHAN snapshot
    (died between snapshot write and journal swap) is ignored — the old
    self-contained journal stays authoritative; a MISSING/mismatched
    snapshot behind a COMPACT head is a hard ValueError (no safe
    reconstruction) so the resume ladder falls to the drain
    checkpoint."""
    path = tmp_path / "c.wal"
    w = wal.TicketWAL(path)
    for i in range(3):
        w.admit(i, _board(rng), 2)
    w.close()
    # Crash between step (1) and (2): the next-generation snapshot got
    # written but the journal swap never happened.
    checkpoint_mod.save_state(wal._snap_path(str(path), 1), {
        "schema": wal.WAL_SNAP_SCHEMA, "generation": 1, "pending": []})
    rep = wal.replay(path)
    assert rep.generation == 0 and rep.counts()["pending"] == 3

    w = wal.TicketWAL(path, compact_bytes=1)
    w.compact([{"id": 0, "board": _board(rng), "steps": 2}])
    w.close()
    os.unlink(wal._snap_path(str(path), 1))
    with pytest.raises(ValueError, match="snapshot"):
        wal.replay(path)


# ---------------------------------------------------------- daemon + ladder


def _daemon(policy, clk=None, **kw):
    clk = clk or FakeClock()
    return ServingDaemon(policy, clock=clk, sleep=clk.sleep, **kw), clk


def test_daemon_wal_resume_zero_loss_in_flight_redispatch(
        tmp_path, make_board):
    """A daemon that simply VANISHES mid-queue (no drain code runs, one
    batch resolved, one journaled DISPATCH left open): resume_any
    rebuilds every unresolved ticket from the journal — including the
    in-flight batch, redispatched idempotently — and the books balance
    with oracle parity."""
    path = str(tmp_path / "serve.wal")
    pol = ServePolicy(max_batch=4, max_wait_s=0.0)
    d, clk = _daemon(pol, wal_path=path)
    boards = [make_board(16, 16) for _ in range(12)]
    for b in boards:
        d.submit(b, 2)
    chunk = d.queue.due_chunks(clk.t, drain=True)[0]
    d._dispatch_chunk(chunk)  # resolves tickets 0-3, journals RESOLVE
    d._wal.dispatch_begin([4, 5, 6, 7])  # died with this batch open
    # No close(), no drain — the process is gone.

    d2, source, detail = ServingDaemon.resume_any(wal_path=path, policy=pol)
    assert source == "wal"
    assert detail["wal_replay"]["pending"] == 8
    assert detail["wal_replay"]["in_flight"] == 4
    assert detail["wal_replay"]["resolved"] == 4
    assert d2.queue.depth() == 8
    d2.drain()
    s = d2.summary()
    assert s["resolved"] == 8 and s["shed"] == 0 and s["pending"] == 0
    for t, b in zip(d2.queue.tickets(), boards[4:]):
        np.testing.assert_array_equal(t.board, b)
        np.testing.assert_array_equal(t.result, oracle_n(b, 2))
    # The resume rotated the journal: a THIRD process sees only the
    # post-resume truth, with the new process's ids.
    rep = wal.replay(path)
    assert rep.generation >= 1 and rep.counts()["pending"] == 0


def test_daemon_journals_sheds(tmp_path, make_board):
    """A shed is a terminal transition: replay must not resurrect it."""
    path = str(tmp_path / "s.wal")
    d, clk = _daemon(
        ServePolicy(max_wait_s=0.0, request_timeout_s=1.0), wal_path=path)
    d.submit(make_board(8, 8), 1)
    clk.t = 5.0  # ages past the budget while queued
    d.serve()
    rep = wal.replay(path)
    assert rep.counts()["pending"] == 0 and rep.shed_ids == {0}


def test_daemon_wal_queued_seconds_survive_process_gap(
        tmp_path, make_board):
    """Latency honesty across the crash: seconds queued in the dead
    process AND the dead time until restart both land in the resumed
    ticket's latency (via the ADMIT record's wall clock)."""
    path = str(tmp_path / "q.wal")
    w = wal.TicketWAL(path)
    w.admit(0, make_board(8, 8), 1, wall=time.time() - 30.0, queued_s=5.0)
    w.close()
    d2, source, _ = ServingDaemon.resume_any(
        wal_path=path, policy=ServePolicy(max_wait_s=0.0))
    assert source == "wal"
    (t,) = d2.queue.pending()
    assert t.queued_before_s == pytest.approx(35.0, abs=5.0)
    d2.drain()
    assert t.latency_s >= 30.0


def test_resume_any_ladder_order(tmp_path, make_board):
    """WAL beats checkpoint beats fresh; an unreadable WAL is
    quarantined and falls through with the error on the record."""
    pol = ServePolicy(max_wait_s=0.0)
    d, source, detail = ServingDaemon.resume_any(
        wal_path=str(tmp_path / "none.wal"),
        checkpoint_path=str(tmp_path / "none.ck"), policy=pol)
    assert source == "fresh" and d.queue.depth() == 0

    # The fresh rung CREATED none.wal (a daemon journals from birth):
    # an existing journal is authoritative on the next resume, even
    # empty — the checkpoint below it may be stale.
    assert os.path.exists(tmp_path / "none.wal")

    ck = str(tmp_path / "q.ck")
    q = ServingDaemon(pol).queue
    q.submit(make_board(8, 8), 1, 0.0)
    checkpoint_mod.save_state(ck, q.snapshot(0.0))
    d, source, _ = ServingDaemon.resume_any(
        wal_path=str(tmp_path / "sub" / "never.wal"), checkpoint_path=ck,
        policy=pol)
    assert source == "checkpoint" and d.queue.depth() == 1

    walp = str(tmp_path / "q.wal")
    w = wal.TicketWAL(walp)
    for i in range(2):
        w.admit(i, make_board(8, 8), 1)
    w.close()
    d, source, _ = ServingDaemon.resume_any(
        wal_path=walp, checkpoint_path=ck, policy=pol)
    assert source == "wal" and d.queue.depth() == 2

    bad = str(tmp_path / "bad.wal")
    with open(bad, "wb") as fd:
        fd.write(b"garbage, not a journal")
    d, source, detail = ServingDaemon.resume_any(
        wal_path=bad, checkpoint_path=ck, policy=pol)
    assert source == "checkpoint" and "magic" in detail["wal_error"]
    # Quarantined (not appended-to) under a stamped unique name, so a
    # second corrupt resume can never clobber this forensic copy.
    quarantined = glob.glob(bad + ".corrupt.*")
    assert len(quarantined) == 1
    assert detail["wal_quarantine"] == quarantined[0]
    assert d.queue.depth() == 1


def test_daemon_cli_wal_clean_run_and_resume_flags(
        tmp_path, capsys, make_board):
    """CLI surface: --wal journals a clean burst (stats on the line),
    --resume accepts --wal without --checkpoint, and the resumed line
    carries the replay accounting."""
    from mpi_and_open_mp_tpu.serve import daemon as daemon_cli

    walp = str(tmp_path / "cli.wal")
    rc = daemon_cli.main(["--requests", "6", "--max-batch", "4",
                          "--max-wait", "0", "--wal", walp, "--verify"])
    line = json.loads(capsys.readouterr().out.strip())
    assert rc == 0 and line["verified"] is True
    assert line["wal"]["fsync"] == "every-record"
    assert line["wal"]["records"] >= 6 and line["wal"]["syncs"] > 0
    rep = wal.replay(walp)
    assert rep.counts()["pending"] == 0 and len(rep.resolved_ids) == 6

    rc = daemon_cli.main(["--requests", "0", "--resume", "--wal", walp,
                          "--verify"])
    line = json.loads(capsys.readouterr().out.strip())
    assert rc == 0 and line["resume_source"] == "wal"
    assert line["wal_replay"]["pending"] == 0
    assert line["resumed_tickets"] == 0

    with pytest.raises(SystemExit) as ei:
        daemon_cli.main(["--resume"])  # neither --wal nor --checkpoint
    assert ei.value.code == 2


# ------------------------------------------------------------- crash matrix


#: (site, k): where the injected ``os._exit(137)`` lands. post-admit and
#: mid-frame fire inside the submit loop (k-th arrival); post-dispatch
#: fires after the first batch computed, before its RESOLVE journaled.
CRASH_CELLS = [("post-admit", 4), ("mid-frame", 4), ("post-dispatch", 1)]


@pytest.mark.parametrize("fsync", list(wal.FSYNC_POLICIES))
@pytest.mark.parametrize("site,k", CRASH_CELLS)
def test_crash_matrix_loss_bounds(tmp_path, site, k, fsync):
    """THE acceptance gate: a real subprocess daemon hard-killed at every
    instrumented site, under every fsync policy. The loss bound is
    measured over exactly the ACKED set (ids whose submit() returned,
    durably recorded by the driver): zero for every-record, zero on
    process death for off, at most one chunk (chunk_records=max_batch=4)
    for every-chunk. Whatever survived must then resume and drain to
    oracle parity — recovery, not just bookkeeping."""
    walp = str(tmp_path / "crash.wal")
    ackp = str(tmp_path / "acked.ids")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MOMP_CHAOS=f"crash={site}:{k}")
    proc = subprocess.run(
        [sys.executable, DRIVER, walp, fsync, ackp, "6"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == chaos.CRASH_EXIT == 137, (
        f"crash never fired: rc={proc.returncode} "
        f"out={proc.stdout!r} err={proc.stderr!r}")

    acked = {int(line) for line in open(ackp)} if os.path.exists(ackp) \
        else set()
    assert acked, "driver acked nothing — the cell tested nothing"
    rep = wal.replay(walp)
    accounted = ({e["id"] for e in rep.pending}
                 | rep.resolved_ids | rep.shed_ids)
    lost = acked - accounted
    if fsync == "every-chunk":
        assert len(lost) <= 4, (site, fsync, sorted(lost))
    else:  # every-record: durable before ack; off: page cache survives
        assert lost == set(), (site, fsync, sorted(lost))

    # Recovery end-to-end: resume the survivors, drain, oracle parity.
    d, source, detail = ServingDaemon.resume_any(
        wal_path=walp, policy=ServePolicy(max_batch=4, max_wait_s=0.0))
    assert source == "wal"
    assert d.queue.depth() == len(rep.pending)
    d.drain()
    s = d.summary()
    assert s["resolved"] == len(rep.pending) and s["pending"] == 0
    for t in d.queue.tickets():
        assert t.state == DONE
        np.testing.assert_array_equal(
            t.result, oracle_n(t.board, t.steps))


# ------------------------------------------- membership crash matrix (PR 17)


def _run_fleet_driver(tmp_path, mode, momp_chaos=None, n=6):
    wal_dir = str(tmp_path / "fleet")
    os.makedirs(wal_dir, exist_ok=True)
    ackp = str(tmp_path / "acked.txt")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("MOMP_CHAOS", None)
    if momp_chaos:
        env["MOMP_CHAOS"] = momp_chaos
    proc = subprocess.run(
        [sys.executable, DRIVER, wal_dir, "every-record", ackp, str(n),
         mode],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=600)
    return proc, wal_dir, ackp


def _parse_acks(ackp):
    created, steps, tickets = [], {}, 0
    for line in open(ackp):
        parts = line.split()
        if parts[0] == "C":
            created.append(parts[1])
            steps.setdefault(parts[1], 0)
        elif parts[0] == "S":
            steps[parts[1]] += int(parts[2])
        elif parts[0] == "T":
            tickets += 1
    return created, steps, tickets


MEMBERSHIP_CELLS = [("rejoin", "post-rejoin"), ("drain", "mid-drain")]


@pytest.mark.parametrize("mode,site", MEMBERSHIP_CELLS)
def test_membership_crash_duplication_not_loss(tmp_path, mode, site):
    """kill -9 inside the membership handshake — post-rejoin (dest
    CREATE+STEP journaled, source EVICT not) and mid-drain (dest ADMITs
    journaled, source re-homed SHED not). Both edges must duplicate,
    never lose: every acked session appears in >=1 worker journal with
    the acked step total — bit-equal create board and step count
    wherever it appears in two — and the fleet-wide ticket count over
    all journals is bounded by ``acked <= total <= acked + one
    bucket``."""
    proc, wal_dir, ackp = _run_fleet_driver(
        tmp_path, mode, momp_chaos=f"crash={site}:1")
    assert proc.returncode == chaos.CRASH_EXIT == 137, (
        f"crash never fired: rc={proc.returncode} "
        f"out={proc.stdout!r} err={proc.stderr!r}")
    created, steps, acked_tickets = _parse_acks(ackp)
    assert created, "driver acked nothing — the cell tested nothing"

    replays = [wal.replay(os.path.join(wal_dir, f"worker{i}.wal"))
               for i in range(3)]

    # Sessions: zero acked loss, bit-exact wherever duplicated.
    for sid in created:
        copies = [rep.pool_sessions[sid] for rep in replays
                  if sid in rep.pool_sessions]
        assert copies, f"acked session {sid} lost across the crash"
        for c in copies:
            assert int(c["steps"]) == steps[sid], (sid, c["steps"])
            np.testing.assert_array_equal(c["board"], copies[0]["board"])
    if mode == "rejoin":
        # The handshake crashed between its halves: at least one
        # claimed session is journaled at BOTH workers.
        dup = [sid for sid in created if sum(
            sid in rep.pool_sessions for rep in replays) == 2]
        assert dup, "post-rejoin kill left no duplicated session"

    # Tickets: every journal's non-re-homed terminal + pending records,
    # fleet-wide. Duplication (<= one whole bucket) allowed, loss not.
    from mpi_and_open_mp_tpu.serve import SHED_REHOMED

    total = 0
    for rep in replays:
        non_rehomed_shed = sum(
            len(ids) for reason, ids in rep.shed_reasons.items()
            if reason != SHED_REHOMED)
        total += len(rep.pending) + len(rep.resolved_ids) \
            + non_rehomed_shed
    assert acked_tickets <= total <= acked_tickets + 6, (
        mode, acked_tickets, total)


@pytest.mark.parametrize("mode", ["rejoin", "drain"])
def test_membership_clean_run_books_balance(tmp_path, mode):
    """The unkilled control: REJOIN claims its sessions back / drain
    migrates whole buckets + groups, and the fleet books balance across
    the membership change."""
    proc, _wal_dir, _ackp = _run_fleet_driver(tmp_path, mode)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    assert line["balanced"], line
    if mode == "rejoin":
        assert line["rejoins"] == 1 and line["claimed"] >= 3, line
    else:
        assert line["drains"] == 1, line
        assert line["tickets_moved"] == 6, line
        assert line["sessions_moved"] == 2, line
