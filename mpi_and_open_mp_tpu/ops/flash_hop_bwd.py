"""Pallas TPU kernels for the ring-attention HOP backward.

The multi-device ring backward (``parallel/context.py:_ring_flash_bwd``)
keeps its travelling-dk/dv contract: K/V blocks make a second trip
around the ring and every hop recomputes one score block's gradients
from the saved row statistics. These kernels are that per-hop block
gradient — the Pallas engine replacing the jnp ``_flash_block_grads``
fold (which stays the parity oracle and the ineligible-shape fallback).

Why not the bundled kernel's backward
(``jax.experimental.pallas.ops.tpu.flash_attention``)? Two reasons:

* The ring never enters the kernel's own vjp — the custom_vjp wraps the
  whole multi-hop trip, and a hop backward needs exactly one block's
  (dq, dk, dv) against the TRIP's logsumexp, not a full single-device
  backward. The bundled ``_flash_attention_bwd_*`` impls can be bent to
  that (residual trick ``m := L, l := 1``), but:
* jax 0.4.37's interpret-mode discharge rule breaks on their
  ``pl.load(ref, (0, 0, k_slice, slice(None)))`` int-index pattern and
  on ``pltpu.repeat`` — so the CPU-mesh test rig (the only rig the
  repo's parity gates run without hardware) could never execute them.

These kernels therefore use only the idioms the bundled FORWARD
single-step kernel proves safe under both Mosaic and the 0.4.37
interpreter: whole-block ``ref[0]`` reads, plain jnp broadcasting
(``x[:, None]``), ``lax.broadcasted_iota`` masks, ``pl.when``
predication, and output-ref accumulation over the minor grid dimension.

Layout: per-q-head ``(h, n, d)`` operands (GQA K/V pre-expanded by the
caller, plan-budgeted — the ppermutes still carry un-expanded blocks).
The per-row statistics ``L`` (trip logsumexp) and ``D = rowsum(do·o)``
arrive lane-broadcast to ``(h, n, LANES)`` — see :func:`lane_broadcast`
— because a ``(1, blk)`` window would put the rows on lanes; inside the
kernel a lane-reduction (``jnp.max`` over identical lanes, the same op
shape as the forward kernel's row-max) recovers the ``(blk, 1)``
column. Outputs are float32; matmuls run on the MXU in the operands'
dtype with ``preferred_element_type=float32``.

The arithmetic is exactly ``_flash_block_grads``:

    p  = exp(s - L)         (s causal-masked additively before the exp)
    dv = pᵀ do ;  t = p ∘ (do vᵀ - D)
    dq = scale · t k ;  dk = scale · tᵀ q

``causal=True`` is the hop-0 diagonal triangle in LOCAL coordinates
(row block iq, col block ik: keep ``col <= row``); every other unskipped
hop is fully unmasked. Above-diagonal tiles are ``pl.when``-skipped.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Lane width the L/D statistics are broadcast to (the TPU vector lane
# count; the bundled kernel pads its l/m residuals the same way).
LANES = 128

# Score-block temporaries are (blk, blk) f32 and there are ~3 of them
# live (p, dp, t) next to the 6 operand blocks: 512 keeps the footprint
# ~4 MB, comfortably inside VMEM; 1024 would put the temporaries alone
# at 12 MB. Callers cap their block edge here (the single-device
# backward's grid-occupancy floor independently prefers <= 512 edges).
MAX_BLOCK = 512

_NEG = -1e30
_TRANS_B = (((1,), (1,)), ((), ()))   # x @ y.T
_TRANS_A = (((0,), (0,)), ((), ()))   # x.T @ y (contract the q rows)


def lane_broadcast(x):
    """``(h, n)`` row statistic -> ``(h, n, LANES)`` with identical
    lanes, the layout the kernels take L and D in."""
    return jnp.broadcast_to(x[..., None], (*x.shape, LANES))


def _col(x128):
    # (blk, LANES) identical lanes -> (blk, 1): a lane reduction, the
    # same op shape as the forward kernel's row-max (chip-validated),
    # instead of a width-1 lane slice.
    return jnp.max(x128, axis=1)[:, None]


def _block_scores(q, k, scale, causal, iq, ik, blk):
    s = lax.dot_general(q, k, _TRANS_B,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        shape = (blk, blk)
        row = lax.broadcasted_iota(jnp.int32, shape, 0) + iq * blk
        col = lax.broadcasted_iota(jnp.int32, shape, 1) + ik * blk
        s = jnp.where(col <= row, s, _NEG)
    return s


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, l_ref, d_ref, dq_ref, *,
               scale, causal, blk):
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _():
        dq_ref[0] = jnp.zeros_like(dq_ref[0])

    live = iq >= ik if causal else ik >= 0

    @pl.when(live)
    def _():
        k = k_ref[0]
        s = _block_scores(q_ref[0], k, scale, causal, iq, ik, blk)
        p = jnp.exp(s - _col(l_ref[0]))
        dp = lax.dot_general(do_ref[0], v_ref[0], _TRANS_B,
                             preferred_element_type=jnp.float32)
        t = p * (dp - _col(d_ref[0]))
        dq_ref[0] += scale * lax.dot_general(
            t.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, l_ref, d_ref, dk_ref,
                dv_ref, *, scale, causal, blk):
    ik = pl.program_id(1)
    iq = pl.program_id(2)

    @pl.when(iq == 0)
    def _():
        dk_ref[0] = jnp.zeros_like(dk_ref[0])
        dv_ref[0] = jnp.zeros_like(dv_ref[0])

    live = iq >= ik if causal else iq >= 0

    @pl.when(live)
    def _():
        q = q_ref[0]
        do = do_ref[0]
        s = _block_scores(q, k_ref[0], scale, causal, iq, ik, blk)
        p = jnp.exp(s - _col(l_ref[0]))
        dv_ref[0] += lax.dot_general(p.astype(do.dtype), do, _TRANS_A,
                                     preferred_element_type=jnp.float32)
        dp = lax.dot_general(do, v_ref[0], _TRANS_B,
                             preferred_element_type=jnp.float32)
        t = p * (dp - _col(d_ref[0]))
        dk_ref[0] += scale * lax.dot_general(
            t.astype(q.dtype), q, _TRANS_A,
            preferred_element_type=jnp.float32)


def hop_block_grads(q, do, L128, D128, kb, vb, *, causal: bool,
                    blk: int, interpret: bool = False):
    """One hop's block gradients ``(dq, dk, dv)``, all float32.

    ``q``/``do`` ``(h, nq, d)``; ``kb``/``vb`` ``(h, nk, d)`` (GQA
    pre-expanded); ``L128``/``D128`` ``(h, nq, LANES)`` lane-broadcast
    (:func:`lane_broadcast`). ``blk`` must divide both sequence edges
    (and stay within :data:`MAX_BLOCK` for the VMEM footprint the
    kernels were sized for). Two kernel launches: dq accumulates over
    the k-block grid axis, dk/dv over the q-block axis — both via
    output-ref revisiting on the minor ("arbitrary") grid dimension.
    """
    h, nq, d = q.shape
    nk = kb.shape[1]
    if nq % blk or nk % blk or blk > MAX_BLOCK:
        raise ValueError(
            f"hop_block_grads: block {blk} must divide nq={nq} and "
            f"nk={nk} and be <= {MAX_BLOCK}")
    scale = 1.0 / math.sqrt(d)
    f32 = jnp.float32
    sem = pltpu.TPUCompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"))

    qside = pl.BlockSpec((1, blk, d), lambda ih, ia, ib: (ih, ia, 0))
    kside_minor = pl.BlockSpec((1, blk, d), lambda ih, ia, ib: (ih, ib, 0))
    stat = pl.BlockSpec((1, blk, LANES), lambda ih, ia, ib: (ih, ia, 0))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal, blk=blk),
        grid=(h, nq // blk, nk // blk),
        in_specs=[qside, kside_minor, kside_minor, qside, stat, stat],
        out_specs=pl.BlockSpec((1, blk, d), lambda ih, ia, ib: (ih, ia, 0)),
        out_shape=jax.ShapeDtypeStruct((h, nq, d), f32),
        compiler_params=sem,
        interpret=interpret,
    )(q, kb, vb, do, L128, D128)

    # dk/dv: k blocks on the revisited (major) axis, q on the minor.
    qside2 = pl.BlockSpec((1, blk, d), lambda ih, ia, ib: (ih, ib, 0))
    kside2 = pl.BlockSpec((1, blk, d), lambda ih, ia, ib: (ih, ia, 0))
    stat2 = pl.BlockSpec((1, blk, LANES), lambda ih, ia, ib: (ih, ib, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal, blk=blk),
        grid=(h, nk // blk, nq // blk),
        in_specs=[qside2, kside2, kside2, qside2, stat2, stat2],
        out_specs=[
            pl.BlockSpec((1, blk, d), lambda ih, ia, ib: (ih, ia, 0))] * 2,
        out_shape=[jax.ShapeDtypeStruct((h, nk, d), f32)] * 2,
        compiler_params=sem,
        interpret=interpret,
    )(q, kb, vb, do, L128, D128)
    return dq, dk, dv
