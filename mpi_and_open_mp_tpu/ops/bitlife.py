"""Bit-packed Life kernels: 32 cells per uint32 lane, bitwise rule.

The reference's compute kernel spends ~12 arithmetic ops per cell on the
8-neighbour count (``/root/reference/3-life/life2d.c:104-130``). On a TPU
VPU the state is 1 bit, so the idiomatic kernel packs 32 cells into each
uint32 **along y** (the sublane axis) and evaluates the rule with bitwise
carry-save adders — ~35 vector ops per 32 cells ≈ 1.1 ops/cell, and 32x
less VMEM/HBM traffic than an int32 board. This is the framework's fast
path for single-shard boards; it is bit-exact against the NumPy oracle
(tests/test_bitlife.py exercises odd sizes, gliders, and random soups).

Packed layout ("offset-ghost"): bit position ``p`` of the packed column
holds board row ``y = p - 1``; position ``0`` mirrors row ``ny-1`` and
position ``ny+1`` mirrors row ``0`` (the torus ghosts). Each step first
refreshes the two ghost bits from live state, then

* y-neighbours are single-bit shifts across the packed words (cross-word
  carries via a sublane roll),
* x-neighbours are lane rolls with the exact ``nx`` wrap (no padding in x),
* the 8-neighbour count ``N`` is built as 2-bit column sums combined by
  full adders into a mod-8 count (N==8 wraps to 0 and correctly dies —
  see ``_carry_save_rule``), and the rule is ``(n0|alive) & n1 & ~n2``
  (birth-on-3 / survive-on-2-or-3, ``life2d.c:117-123``).

The whole step loop runs inside one ``pallas_call`` with the packed board
VMEM-resident; a 500x500 board packs to 16x500 uint32 = 32 KB. The gate
is the packed bytes times the ~11 live step temporaries against the
~16 MB/core scoped-VMEM budget (see ``_PACKED_VMEM_LIMIT``): ~3200² is
the measured ceiling. Beyond it, aligned boards run the multi-step-fused
tiled kernel (:func:`life_run_fused_bits` — one HBM pass per up-to-128
steps, measured 1.9 Tcups at 8192² on v5e) and anything else
the compiled-XLA packed loop (:func:`life_run_bits_xla`).
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Packed board bytes kept VMEM-resident. The step body holds ~10 live
# same-shape temporaries, so the working set is ~11x the board against the
# ~16 MB/core scoped-VMEM budget; measured on v5e: 1.23 MB packed (3200²)
# compiles, 1.47 MB (3500²) is rejected by Mosaic.
_PACKED_VMEM_LIMIT = 5 << 18


def n_words(ny: int) -> int:
    """Packed sublane words for ``ny`` rows plus the two ghost positions."""
    return (ny + 2 + 31) // 32


def fits_vmem_packed(shape: tuple[int, int]) -> bool:
    ny, nx = shape
    nxp = -(-nx // 128) * 128  # lane padding (see life_run_vmem_bits)
    return n_words(ny) * nxp * 4 <= _PACKED_VMEM_LIMIT


def fits_vmem_packed_batch(shape: tuple[int, int, int]) -> bool:
    """Whether a WHOLE (B, ny, nx) stack fits the packed VMEM budget at
    once — the batched twin of :func:`fits_vmem_packed`, with the working
    set scaled by B (the batched step holds the same ~11 live temporaries,
    each now B boards deep). Stacks past this gate but whose single board
    still fits stream through a grid over the batch axis instead (one
    board resident per program — see :func:`life_run_vmem_bits_batch`)."""
    b, ny, nx = shape
    nxp = -(-nx // 128) * 128
    return b * n_words(ny) * nxp * 4 <= _PACKED_VMEM_LIMIT


def pack_board(board: jnp.ndarray) -> jnp.ndarray:
    """(ny, nx) 0/1 ints -> (n_words(ny), nx) uint32, offset-ghost layout.

    Ghost bits are left zero; the kernel refreshes them at the top of every
    step, so they never need to be materialised here.
    """
    ny, nx = board.shape
    nw = n_words(ny)
    rows = jnp.zeros((nw * 32, nx), dtype=jnp.uint32)
    rows = rows.at[1 : ny + 1, :].set(board.astype(jnp.uint32))
    shifts = jnp.arange(32, dtype=jnp.uint32)[None, :, None]
    return (rows.reshape(nw, 32, nx) << shifts).sum(
        axis=1, dtype=jnp.uint32
    )


def unpack_board(packed: jnp.ndarray, ny: int) -> jnp.ndarray:
    """Inverse of :func:`pack_board`; returns (ny, nx) uint8."""
    nw, nx = packed.shape
    shifts = jnp.arange(32, dtype=jnp.uint32)[None, :, None]
    rows = ((packed[:, None, :] >> shifts) & jnp.uint32(1)).reshape(
        nw * 32, nx
    )
    return rows[1 : ny + 1, :].astype(jnp.uint8)


def _set_word_row(p: jnp.ndarray, w: int, row: jnp.ndarray) -> jnp.ndarray:
    """Replace word-row ``w`` of ``p`` (static index) via concatenation.

    ``p.at[w:w+1].set`` is avoided: when the slice covers a whole axis, its
    lowering closes over an empty i32 array, which ``pallas_call`` rejects
    as a captured constant.
    """
    parts = []
    if w > 0:
        parts.append(p[:w, :])
    parts.append(row)
    if w + 1 < p.shape[0]:
        parts.append(p[w + 1 :, :])
    return jnp.concatenate(parts, axis=0) if len(parts) > 1 else row


def _refresh_ghosts(p: jnp.ndarray, ny: int) -> jnp.ndarray:
    """Rewrite the two torus ghost bits from live board state.

    Position 0 := position ny (board row ny-1); position ny+1 := position 1
    (board row 0). Static word/bit indices — ``ny`` is a trace-time const.
    """
    # np.uint32 literals throughout: concrete jnp scalars would be captured
    # as pallas kernel constants (rejected), and Python ints above 2^31
    # overflow the weak-int32 promotion path.
    w_lo, b_lo = divmod(ny, 32)  # source bit for ghost position 0
    src = (p[w_lo : w_lo + 1, :] >> b_lo) & 1
    p = _set_word_row(p, 0, (p[0:1, :] & np.uint32(0xFFFFFFFE)) | src)
    w_hi, b_hi = divmod(ny + 1, 32)  # target word/bit for ghost top
    src = (p[0:1, :] >> 1) & 1  # position 1 = board row 0
    new_hi = (
        p[w_hi : w_hi + 1, :] & np.uint32(0xFFFFFFFF ^ (1 << b_hi))
    ) | (src << b_hi)
    return _set_word_row(p, w_hi, new_hi)


def _roll_sub(p: jnp.ndarray, shift: int) -> jnp.ndarray:
    nw = p.shape[0]
    if nw == 1:
        return p
    return pltpu.roll(p, shift % nw, 0)


def _carry_save_rule(c, up, dn, roll_left, roll_right) -> jnp.ndarray:
    """The bitwise Life rule given centre/up/down bit columns.

    ``roll_left(x)``/``roll_right(x)`` supply each lane its left/right
    torus neighbour — plain rolls when the array width IS the board
    width, rolls + wrap-column fixup on the lane-padded fast path.

    Counts the 8 NEIGHBOURS ``N`` (centre excluded), mod 8 — the bit-3
    carry only fires at N == 8, which wraps to 000 and correctly dies.
    Excluding the centre is what makes the rule term cheap: birth-on-3 /
    survive-on-2-or-3 becomes ``(n0 | alive) & n1 & ~n2`` (N==3 sets it
    regardless of ``alive``; N==2 needs ``alive`` to supply bit 0), four
    ops versus eight for the centre-included ``T==3 | (alive & T==4)``
    form — ~24 logicals per 32 cells all told. The neighbour columns
    still contribute their full 3-cell sums (``ys``), whose half-adder
    prefix is the centre column's 2-cell sum (``cs``) — shared, so both
    cost 5 ops together. Bit-exactness is pinned by the three-oracle
    parity suite (rule spec ``3-life/life2d.c:104-130``).
    """
    # Column sums: cs = up+dn (centre column, centre EXCLUDED) and
    # ys = up+c+dn (what this column contributes as a NEIGHBOUR column).
    cs0 = up ^ dn
    cs1 = up & dn
    ys0 = cs0 ^ c
    ys1 = cs1 | (cs0 & c)
    # x-neighbours.
    l0 = roll_left(ys0)
    r0 = roll_right(ys0)
    l1 = roll_left(ys1)
    r1 = roll_right(ys1)
    # P = L + R (two 2-bit sums -> 3 bits).
    p0 = l0 ^ r0
    q0 = l0 & r0
    p1x = l1 ^ r1
    p1 = p1x ^ q0
    p2 = (l1 & r1) | (p1x & q0)
    # N = P + cs, bits (n2, n1, n0) = N mod 8.
    n0 = p0 ^ cs0
    rc = p0 & cs0
    n1x = p1 ^ cs1
    n1 = n1x ^ rc
    n2 = p2 ^ ((p1 & cs1) | (n1x & rc))
    # alive' = (N == 3) | (alive & N == 2).
    return (n0 | c) & n1 & ~n2


def _lane_rolls(shape: tuple[int, int], nx: int):
    """``(roll_left, roll_right)`` lane-neighbour rolls with the torus
    wrap at column ``nx``. When the array is wider than ``nx`` (lane
    padding) the two wrap columns are patched explicitly: lane 0's true
    left neighbour is column ``nx-1`` (the roll would hand it a slack
    column), and lane ``nx-1``'s right neighbour is column 0 — slack
    columns carry junk that never feeds a valid column."""
    nxp = shape[1]
    if nxp == nx:
        return (
            lambda x: pltpu.roll(x, 1, 1),
            lambda x: pltpu.roll(x, nx - 1, 1),
        )
    lane = lax.broadcasted_iota(jnp.int32, shape, 1)

    def roll_left(x):
        return jnp.where(lane == 0, x[:, nx - 1 : nx], pltpu.roll(x, 1, 1))

    def roll_right(x):
        return jnp.where(
            lane == nx - 1, x[:, 0:1], pltpu.roll(x, nxp - 1, 1)
        )

    return roll_left, roll_right


def bit_step(p: jnp.ndarray, ny: int, nx: int) -> jnp.ndarray:
    """One Life step on a packed board (ghost refresh + bitwise rule).

    ``p`` may be lane-padded (``p.shape[1] > nx``): Mosaic lane rolls at
    a non-128-multiple width cost ~3.4x (measured 401 vs 1376 Gcups at
    500² vs 512² on v5e), so the runner pads the board to the next lane
    multiple and the wrap columns are patched (see :func:`_lane_rolls`).
    """
    p = _refresh_ghosts(p, ny)
    nw = p.shape[0]
    # y-neighbours: single-bit shifts through the packed words. The junk
    # carried into ghost/slack positions never reaches a live bit.
    dn = (p << 1) | (_roll_sub(p, 1) >> 31)
    up = (p >> 1) | (_roll_sub(p, nw - 1) << 31)
    return _carry_save_rule(p, up, dn, *_lane_rolls(p.shape, nx))


def _vmem_bits_kernel(steps_ref, p_ref, out_ref, *, ny: int, nx: int):
    out_ref[:] = lax.fori_loop(
        0, steps_ref[0], lambda _, p: bit_step(p, ny, nx), p_ref[:]
    )


@functools.partial(jax.jit, static_argnames=("ny", "nx", "interpret"))
def _run_vmem_bits_jit(packed, steps, *, ny: int, nx: int, interpret: bool):
    return pl.pallas_call(
        functools.partial(_vmem_bits_kernel, ny=ny, nx=nx),
        out_shape=jax.ShapeDtypeStruct(packed.shape, packed.dtype),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(steps, packed)


def life_run_vmem_bits(
    board: jnp.ndarray, n: int, *, interpret: bool = False
) -> jnp.ndarray:
    """Advance ``n`` steps with the packed VMEM-resident loop kernel.

    The board is lane-padded to the next multiple of 128 columns before
    packing (see :func:`bit_step` — unaligned lane rolls cost ~3.4x);
    pack/unpack are plain XLA ops fused around the single kernel launch;
    ``n`` is a runtime SMEM scalar (no recompile when it changes).
    """
    ny, nx = board.shape
    dtype = board.dtype
    nxp = -(-nx // 128) * 128
    if nxp != nx:
        board = jnp.pad(board, ((0, 0), (0, nxp - nx)))
    packed = pack_board(board)
    steps = jnp.asarray([n], dtype=jnp.int32)
    out = _run_vmem_bits_jit(packed, steps, ny=ny, nx=nx, interpret=interpret)
    return unpack_board(out, ny)[:, :nx].astype(dtype)


# ------------------------------------------- big boards (fused tiled Pallas)


def pack_board_exact(board: jnp.ndarray) -> jnp.ndarray:
    """(ny, nx) 0/1 ints -> (ny/32, nx) uint32, NO ghost offset.

    Bit ``b`` of word row ``w`` holds board row ``32*w + b``. Requires
    ``ny % 32 == 0``, which makes the torus wrap word-aligned — the fused
    tiled kernel's halo is then plain word rows copied from the opposite
    board edge, no ghost-bit bookkeeping at all.
    """
    ny, nx = board.shape
    assert ny % 32 == 0, ny
    rows = board.astype(jnp.uint32).reshape(ny // 32, 32, nx)
    shifts = jnp.arange(32, dtype=jnp.uint32)[None, :, None]
    return (rows << shifts).sum(axis=1, dtype=jnp.uint32)


def unpack_board_exact(packed: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`pack_board_exact`; returns (ny, nx) uint8."""
    nw, nx = packed.shape
    shifts = jnp.arange(32, dtype=jnp.uint32)[None, :, None]
    rows = ((packed[:, None, :] >> shifts) & jnp.uint32(1)).reshape(
        nw * 32, nx
    )
    return rows.astype(jnp.uint8)


# Halo word rows DMA'd on each side of a tile: 4 words = 128 bit rows of
# valid neighbour state, so up to 128 steps can run on one tile window
# before the outside-in junk front reaches the tile interior (validity
# shrinks 1 bit row per step per side). Also keeps DMA extents 8-aligned
# (tr % 8 == 0 and 2*H == 8).
_FUSE_HALO_WORDS = 4
FUSE_MAX_STEPS = 32 * _FUSE_HALO_WORDS


def _fused_window_step(
    w: jnp.ndarray, nx: int, nx_exact: int | None = None
) -> jnp.ndarray:
    """One Life step over a full tile window (no ghost refresh: y-wrap
    content is real halo rows; the sublane-roll junk entering the two
    outermost bit rows is tracked by the validity argument above).

    ``nx_exact`` set (and < ``nx``) means the window is a lane-padded
    board whose torus wrap must land on the logical column: the lane
    rolls get the same wrap-column patch as :func:`bit_step`, the pad
    columns carry junk that never feeds a valid column, and no x halo
    or validity tracking is needed in the lane dimension at all.
    """
    dn = (w << 1) | (_roll_sub(w, 1) >> 31)
    up = (w >> 1) | (_roll_sub(w, w.shape[0] - 1) << 31)
    wrap = nx if nx_exact is None else nx_exact
    return _carry_save_rule(w, up, dn, *_lane_rolls(w.shape, wrap))


def _fused_tiles_kernel(
    k_ref, hbm_ref, out_ref, scratch, sem, *, tr: int, hx: int = 0,
    cx: int | None = None, nx_exact: int | None = None,
):
    """One program = one (tr, cx-or-full-width) output tile, ``k_ref[0]``
    fused steps.

    DMAs the tile plus ``_FUSE_HALO_WORDS`` halo word rows per side from
    the wrap-extended board, steps the whole window k times in VMEM, and
    writes back only the (still-valid) interior — one HBM read+write pass
    per k steps instead of per step. ``hx`` > 0 means the input carries
    ``hx`` halo columns per side (from an x wrap or a cart-mesh ppermute;
    corner cells arrive via the y-exchange of the x-extended slab) and
    the output slices them off. ``cx`` additionally tiles columns on a
    2-D grid — each program's window is its column range plus the same
    ``hx`` border, read from the extended input at a 128-aligned offset.
    All lane offsets/extents stay 128-aligned, so the value-level x slice
    is vreg-clean.
    """
    i = pl.program_id(0)
    h = _FUSE_HALO_WORDS
    if cx is None:
        w_ext = hbm_ref.shape[1]
        src = hbm_ref.at[pl.ds(i * tr, tr + 2 * h)]
    else:
        j = pl.program_id(1)
        w_ext = cx + 2 * hx
        src = hbm_ref.at[pl.ds(i * tr, tr + 2 * h), pl.ds(j * cx, w_ext)]
    cp = pltpu.make_async_copy(src, scratch, sem)
    cp.start()
    cp.wait()
    w = lax.fori_loop(
        0, k_ref[0],
        lambda _, x: _fused_window_step(x, w_ext, nx_exact), scratch[:]
    )
    out_ref[:] = w[h : h + tr, hx : w_ext - hx]


def _fused_tile_words(
    nw: int, nx: int, tile_budget_bytes: int = _PACKED_VMEM_LIMIT
) -> int:
    """Tile word rows: the largest multiple-of-8 divisor of ``nw`` whose
    halo-extended window fits the VMEM working-set budget (the same
    ~11-temporaries headroom the resident kernel is gated by). 0 = no
    legal split. ``tile_budget_bytes`` exists so tests can force
    multi-tile grids (and their DMA seams) at small shapes."""
    cap = tile_budget_bytes // (4 * nx) - 2 * _FUSE_HALO_WORDS
    best = 0
    for d in range(8, min(cap, nw) + 1, 8):
        if nw % d == 0:
            best = d
    return best


def fused_bits_supported(shape: tuple[int, int]) -> bool:
    """Whether the fused tiled kernel can run ``shape`` compiled: word-
    aligned torus (ny % 32), 128-aligned lane dim (explicit-DMA scratch),
    and a legal tile split — full-width row tiles or the column-tiled
    plan (which also covers ultra-wide boards)."""
    ny, nx = shape
    if ny % 32 or nx % 128:
        return False
    nw = ny // 32
    return _fused_tile_words(nw, nx) >= 8 or _col_tile_plan(nw, nx) is not None


# Column halo for the 2-D (cart) fused path: 128 lanes = 128 cell columns
# per side, matching FUSE_MAX_STEPS (x junk marches 1 column per step).
_FUSE_HALO_X = 128


def _col_tile_plan(
    nw: int, nxl: int, tile_budget_bytes: int = _PACKED_VMEM_LIMIT
):
    """Best ``(amplification, tr, cx)`` column-tiling plan for an ext
    carrying ``_FUSE_HALO_X`` borders, or None. Amplification = redundant
    window area per output area = (tr+2H)/tr * (cx+2HX)/cx; wide boards
    prefer narrower column tiles (taller row tiles fit the VMEM budget),
    e.g. 16384-wide drops from 2.0x (tr=8 full-width) to ~1.2x."""
    best = None
    for cx in range(128, nxl + 1, 128):
        if nxl % cx:
            continue
        w_ext = cx + 2 * _FUSE_HALO_X
        tr = _fused_tile_words(nw, w_ext, tile_budget_bytes)
        if tr < 8:
            continue
        amp = (tr + 2 * _FUSE_HALO_WORDS) / tr * (w_ext / cx)
        if best is None or amp < best[0] - 1e-9:
            best = (amp, tr, cx)
    return best


def make_fused_stepper(
    nw: int,
    nxl: int,
    *,
    interpret: bool,
    tile_budget_bytes: int = _PACKED_VMEM_LIMIT,
    halo_x: int = 0,
    nx_exact: int | None = None,
):
    """Build ``step_call(k, ext) -> (nw, nxl)``: the fused tiled kernel
    over a wrap-extended ``(nw + 2*_FUSE_HALO_WORDS, nxl + 2*halo_x)``
    packed board, running ``k[0]`` fused steps. Shared by the serial
    big-board runner, the row-sharded ring path (``halo_x=0``; halo rows
    arrive by ``ppermute`` instead of a local wrap concat), and the x-
    extended paths (``halo_x=_FUSE_HALO_X``: cart-mesh shards and wide
    serial boards), which additionally column-tile on a 2-D grid when
    that lowers the redundant-window amplification."""
    h = _FUSE_HALO_WORDS
    w_ext = nxl + 2 * halo_x
    if halo_x:
        assert nx_exact is None, "wrap-patched rolls need the full width"
        plan = _col_tile_plan(nw, nxl, tile_budget_bytes)
        if plan is None:
            raise ValueError(
                f"no legal fused tile split for extended shape "
                f"{(nw, w_ext)}; gate callers on fused_bits_supported() / "
                "plan_sharded_bits()"
            )
        _, tr, cx = plan
        grid = (nw // tr, nxl // cx)
        kernel = functools.partial(
            _fused_tiles_kernel, tr=tr, hx=halo_x, cx=cx)
        out_block = pl.BlockSpec(
            (tr, cx), lambda i, j: (i, j), memory_space=pltpu.VMEM)
        scratch_w = cx + 2 * halo_x
    else:
        tr = _fused_tile_words(nw, nxl, tile_budget_bytes)
        if tr < 8:
            raise ValueError(
                f"no legal fused tile split for packed shape {(nw, nxl)}; "
                "gate callers on fused_bits_supported()"
            )
        grid = (nw // tr,)
        kernel = functools.partial(
            _fused_tiles_kernel, tr=tr, nx_exact=nx_exact)
        out_block = pl.BlockSpec(
            (tr, nxl), lambda i: (i, 0), memory_space=pltpu.VMEM)
        scratch_w = nxl
    return pl.pallas_call(
        kernel,
        grid=grid,
        out_shape=jax.ShapeDtypeStruct((nw, nxl), jnp.uint32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=out_block,
        scratch_shapes=[
            pltpu.VMEM((tr + 2 * h, scratch_w), jnp.uint32),
            pltpu.SemaphoreType.DMA(()),
        ],
        interpret=interpret,
    )


def wrap_y(p: jnp.ndarray, h: int = _FUSE_HALO_WORDS) -> jnp.ndarray:
    """Extend a packed board with ``h`` torus-wrap word rows per side —
    the local (single-shard / unsharded-axis) form of the fused kernel's
    y halo. Sharded axes get the same rows via ``ppermute`` instead
    (``halo.halo_pad_y``); both must honour ``_FUSE_HALO_WORDS``."""
    return jnp.concatenate([p[-h:], p, p[:h]], axis=0)


# ------------------------------------- arbitrary shapes (padded torus frame)
#
# The fused kernels above want word-aligned rows and lane-aligned columns.
# To run ANY board (the reference's flagship is 500x500 —
# ``3-life/p46gun_big.cfg:3``) on any mesh, the board is stored in a FRAME
# padded up to (32*py)-row / lane-pitch-column alignment, kept consistent
# with the infinite periodic tiling of the logical board:
#
# * frame rows   [ny, Nyp)  mirror board rows    [0, pad_y)
# * frame cols   [nx, Nxp)  mirror board columns [0, pad_x)  (sharded x)
#
# A window whose content agrees with the periodic tiling evolves every
# cell — mirrors included — exactly as the torus does, so the mirrors
# self-maintain across fused rounds; they are still refreshed from the
# authoritative shard each round (cheap, and fixes the zero-padded initial
# state). The wrap halos are then *unaligned* row/column ranges of the
# frame, extracted with funnel shifts (:func:`take_rows`) outside the
# kernel — the kernel itself never learns the board was unaligned. For
# unsharded x the mirror machinery is unnecessary: the wrap-column-patched
# rolls of :func:`bit_step` (``nx_exact``) give an exact x torus at any
# width.


def take_rows(words: jnp.ndarray, start: int, h: int) -> jnp.ndarray:
    """Bit rows ``[start, start + 32*h)`` of a packed word stack.

    ``start`` is a static bit-row offset. Word-aligned offsets are plain
    slices; anything else funnels each output word from two neighbouring
    input words — the packed-layout form of an unaligned row slice.
    """
    q, b = divmod(start, 32)
    if b == 0:
        return words[q : q + h]
    return (words[q : q + h] >> b) | (words[q + 1 : q + h + 1] << (32 - b))


def mirror_tail(e: jnp.ndarray, src: jnp.ndarray, pad: int) -> jnp.ndarray:
    """Rewrite the last ``pad`` bit rows of frame shard ``e`` with rows
    ``[0, pad)`` of ``src`` — the periodic-mirror refresh: frame rows
    ``[ny, Nyp)`` must copy board rows ``[0, pad_y)`` so every window cut
    from the frame agrees with the torus tiling. ``src`` must carry at
    least ``pad + 32`` bit rows starting at board row 0."""
    nw = e.shape[0]
    q, b = divmod(pad, 32)
    parts = [e[: nw - q - (1 if b else 0)]]
    if b:
        keep = np.uint32((1 << (32 - b)) - 1)
        parts.append(((e[nw - 1 - q] & keep) | (src[0] << (32 - b)))[None])
    if q:
        parts.append(take_rows(src, b, q))
    return jnp.concatenate(parts, axis=0) if len(parts) > 1 else parts[0]


def wrap_y_padded(e: jnp.ndarray, ny: int, h: int) -> jnp.ndarray:
    """Local y-extension of a packed frame taller than the board: refresh
    the mirror rows, then append funnel-shifted torus borders — the
    unaligned generalisation of :func:`wrap_y` (its exact degenerate).
    Callers must honour the :func:`plan_sharded_bits` gate
    ``h + 1 + pad//32 <= nw``."""
    nw = e.shape[0]
    pad = 32 * nw - ny
    if pad == 0:
        return wrap_y(e, h)
    s = h + 1 + pad // 32
    # Top border = board rows [ny - 32h, ny): real rows only — the funnel
    # stops one bit short of the mirror region (checked in tests).
    top = take_rows(e[-s:], 32 * s - pad - 32 * h, h)
    bot = take_rows(e[:s], pad, h)
    e = mirror_tail(e, e[:s], pad)
    return jnp.concatenate([top, e, bot], axis=0)


def make_window_stepper(
    nw: int,
    nxl: int,
    *,
    h: int,
    halo_x: int = 0,
    nx_exact: int | None = None,
    interpret: bool = False,
):
    """Whole-shard fused stepper: the halo-extended window VMEM-resident
    in a single program, ``k_ref[0]`` fused steps, interior write-back.

    The small-shard counterpart of :func:`make_fused_stepper` (whose DMA
    tiles need >=8 word rows): a 500x500 board over an 8-way ring packs
    to 2-word slabs, far below any legal tile split, but the whole
    halo-extended window is then a few KB — exactly the VMEM-resident
    regime. Same calling convention as the tiled stepper.
    """
    # Wrap-patched rolls assume board column 0 sits at lane 0 — an x
    # border would shift it to lane halo_x and silently corrupt the wrap.
    assert halo_x == 0 or nx_exact is None, (
        "wrap-patched rolls need the unextended board width"
    )
    w_ext = nxl + 2 * halo_x

    def kernel(k_ref, ext_ref, out_ref):
        w = lax.fori_loop(
            0, k_ref[0],
            lambda _, x: _fused_window_step(x, w_ext, nx_exact),
            ext_ref[:],
        )
        out_ref[:] = w[h : h + nw, halo_x : halo_x + nxl]

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((nw, nxl), jnp.uint32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )


@dataclasses.dataclass(frozen=True)
class BitPlan:
    """How to run one board/mesh combination through the packed fused
    path: frame padding, halo depths, fuse budget, and stepper kind.
    Produced by :func:`plan_sharded_bits`; consumed by
    :func:`make_plan_stepper` and the model layer's exchange loop."""

    shape: tuple[int, int]   # logical (ny, nx)
    py: int
    px: int
    y_sharded: bool
    x_sharded: bool
    frame: tuple[int, int]   # padded (Nyp, Nxp) — the stored board shape
    pad_y: int
    pad_x: int
    nw_s: int                # packed word rows per shard
    W: int                   # columns per shard
    h: int                   # y halo words per side
    hx: int                  # x halo columns per side (0 = no x border)
    nx_exact: int | None     # wrap-patched roll width (unsharded pad_x>0)
    k_max: int               # fused steps per exchange round
    mode: str                # "window" | "tiled"
    budget: int              # VMEM budget the mode choice was validated at


def plan_sharded_bits(
    shape: tuple[int, int],
    py: int,
    px: int,
    y_sharded: bool,
    x_sharded: bool,
    budget: int = _PACKED_VMEM_LIMIT,
) -> BitPlan | None:
    """Plan the packed fused path for ANY board over a ``(py, px)`` mesh.

    Returns None only when the geometry is genuinely hopeless for halo
    fusion (a shard too small to carry even a 1-word halo next to its
    padding, or a window/tile split that fits no VMEM budget) — callers
    then fall back to the unpacked halo/roll impls. Covers the
    reference's per-step ghost exchange (``3-life/life_mpi.c:198-209``)
    amortised ``k_max``-fold for every shape, not just aligned ones.
    """
    ny, nx = shape
    if ny < 8 or nx < 8:
        return None
    # ---- x axis: lane pitch, pad, halo columns.
    if x_sharded:
        nx_exact = None
        W = -(-nx // (128 * px)) * 128
        pad_x = W * px - nx
        hx = _FUSE_HALO_X
        if W - pad_x < hx:
            # Narrow shards can't feed a full 128-column halo: re-pitch at
            # 8-column granularity (unaligned lane rolls cost ~3.4x but
            # the fused path still wins) and shrink the halo — and with
            # it k_max — to what a neighbour can supply.
            W = -(-nx // (8 * px)) * 8
            pad_x = W * px - nx
            hx = min(_FUSE_HALO_X, W - pad_x)
            if hx < 8:
                return None
    else:
        W = -(-nx // 128) * 128
        pad_x = W - nx
        hx = 0
        nx_exact = nx if pad_x else None
    # ---- y axis: word pitch, pad, halo words, stepper kind. Two pitch
    # attempts: the minimal 1-word (32-row) granularity first, then
    # 8-word granularity — the tiled kernel needs a split tr | nw_s with
    # tr % 8 == 0, which a prime/odd word count can never supply (e.g.
    # 10000 rows -> 313 words), but the frame is OURS to choose: padding
    # to an 8-word multiple guarantees a split at the cost of up to 255
    # extra mirror rows per shard.
    for words_pitch in (1, 8):
        nw_s = -(-ny // (32 * words_pitch * py)) * words_pitch
        pad_y = 32 * nw_s * py - ny
        if pad_y:
            # Wrap funnels read h+1+pad_y//32 words from the neighbour;
            # the shard must hold them (and the wrap-border source rows).
            h = min(_FUSE_HALO_WORDS, nw_s - 1 - pad_y // 32)
        else:
            h = min(_FUSE_HALO_WORDS, nw_s)
        if h < 1:
            continue
        # Stepper kind: whole-window VMEM program when it fits, else the
        # DMA-tiled kernel (needs full-depth halos and lane alignment).
        if (nw_s + 2 * h) * (W + 2 * hx) * 4 <= budget:
            mode = "window"
        elif h == _FUSE_HALO_WORDS and W % 128 == 0:
            if hx:
                if (hx != _FUSE_HALO_X
                        or _col_tile_plan(nw_s, W, budget) is None):
                    continue
            elif _fused_tile_words(nw_s, W, budget) < 8:
                continue
            mode = "tiled"
        else:
            continue
        return BitPlan(
            shape=shape, py=py, px=px,
            y_sharded=y_sharded, x_sharded=x_sharded,
            frame=(32 * nw_s * py, W * px), pad_y=pad_y, pad_x=pad_x,
            nw_s=nw_s, W=W, h=h, hx=hx, nx_exact=nx_exact,
            k_max=min(32 * h, hx or FUSE_MAX_STEPS, FUSE_MAX_STEPS),
            mode=mode, budget=budget,
        )
    return None


def local_wrap_y(plan: BitPlan, q: jnp.ndarray) -> jnp.ndarray:
    """The plan's LOCAL (unsharded-y) torus extension: funnel wrap +
    mirror refresh when the frame is padded, plain word-row wrap when it
    is exact. Shared by the serial frame runner and the model layer's
    col-layout shard body — the unsharded twin of ``halo.packed_halo_y``."""
    if plan.pad_y:
        return wrap_y_padded(q, plan.shape[0], plan.h)
    return wrap_y(q, plan.h)


@functools.partial(
    jax.jit, static_argnames=("ny", "nx", "interpret", "budget")
)
def _run_frame_bits_jit(
    packed, steps, *, ny: int, nx: int, interpret: bool, budget: int
):
    plan = plan_sharded_bits((ny, nx), 1, 1, False, False, budget)
    step_call = make_plan_stepper(plan, interpret=interpret)

    def body(carry):
        q, rem = carry
        k = jnp.minimum(rem, plan.k_max)
        return step_call(k.reshape(1), local_wrap_y(plan, q)), rem - k

    out, _ = lax.while_loop(lambda c: c[1] > 0, body, (packed, steps[0]))
    return out


def life_run_frame_bits(
    board: jnp.ndarray, n: int, *, interpret: bool = False,
    budget: int = _PACKED_VMEM_LIMIT,
) -> jnp.ndarray:
    """Advance ``n`` steps of an UNALIGNED big board on one device via the
    padded torus frame: word-padded rows (periodic mirrors + funnel wrap
    borders, :func:`wrap_y_padded`) and lane-padded columns
    (wrap-patched rolls), stepped by the plan's window or tiled fused
    kernel — the single-device form of the sharded bitfused path, for
    shapes the aligned fused kernel rejects (``ny % 32``/``nx % 128``).
    Measured v5e @ 10000² (r05 bigboard re-record,
    ``results/life/bigboard_tpu.csv``): 66.5 µs/step = 1.50 Tcups
    steady — the any-shape path at scale, with a
    one-HBM-pass-per-128-steps traffic bound the XLA roll loop loses
    once its intermediates spill through HBM (653 vs 242 µs/step at
    16384², ``bit_step_xla`` docstring). An earlier r04 probe recorded
    "37.0 vs 32.6 µs/step" for frame-vs-XLA at this size; 32.6 µs/step
    at 10⁸ cells would be 3.1 Tcups — above the 2.24 peak of the whole
    curve — so that pair was a measurement error (un-differenced timing
    through the relay), and the r05 differenced re-record above replaces
    it. Gate callers on ``plan_sharded_bits(shape, 1, 1, False, False)``.
    """
    ny, nx = board.shape
    plan = plan_sharded_bits((ny, nx), 1, 1, False, False, budget)
    if plan is None:
        raise ValueError(
            f"no padded-frame plan for {board.shape}; gate callers on "
            "plan_sharded_bits()"
        )
    dtype = board.dtype
    frame = jnp.pad(
        board, ((0, plan.frame[0] - ny), (0, plan.frame[1] - nx))
    )
    packed = pack_board_exact(frame)
    steps = jnp.asarray([n], dtype=jnp.int32)
    out = _run_frame_bits_jit(
        packed, steps, ny=ny, nx=nx, interpret=interpret, budget=budget
    )
    return unpack_board_exact(out)[:ny, :nx].astype(dtype)


def make_plan_stepper(plan: BitPlan, *, interpret: bool = False):
    """``step_call(k, ext) -> (nw_s, W)`` for a :class:`BitPlan`: the
    whole-window VMEM program for small shards, the DMA-tiled kernel for
    large ones (tiled at the same budget the planner validated the mode
    choice against). ``ext`` is the ``(nw_s + 2h, W + 2hx)`` halo-extended
    packed shard the model layer assembles each exchange round."""
    if plan.mode == "window":
        return make_window_stepper(
            plan.nw_s, plan.W, h=plan.h, halo_x=plan.hx,
            nx_exact=plan.nx_exact, interpret=interpret,
        )
    return make_fused_stepper(
        plan.nw_s, plan.W, interpret=interpret,
        tile_budget_bytes=plan.budget,
        halo_x=plan.hx, nx_exact=plan.nx_exact,
    )


def plan_overlap_supported(plan: BitPlan) -> bool:
    """Whether the plan's geometry admits the interior/boundary overlap
    split (``parallel.haloplan``): window-mode row shards with an EXACT
    word frame. ``pad_y > 0`` frames exchange funnel-shifted unaligned
    ranges and refresh mirrors — a sequencing the split would have to
    replicate in every partition for no interior gain — and an x-sharded
    plan's y ghosts must ride AFTER the x exchange (corners), so both
    stay on the sequential schedule. ``nw_s > 2h`` keeps the interior
    partition non-empty; 1-shard meshes are the caller's degenerate
    gate (nothing to overlap)."""
    return (plan.mode == "window" and plan.y_sharded
            and not plan.x_sharded and plan.pad_y == 0
            and plan.nw_s > 2 * plan.h)


def make_overlap_steppers(plan: BitPlan, *, interpret: bool = False):
    """``(interior_call, edge_call)`` for the overlapped packed round —
    gate on :func:`plan_overlap_supported`.

    * ``interior_call(k, q) -> (nw_s - 2h, W)``: the RAW packed shard is
      its own window — the outer ``h`` words per side play the halo role
      — so word rows ``[h, nw_s - h)`` compute from purely local data
      while the ghost ``ppermute`` flies.
    * ``edge_call(k, ext3h) -> (h, W)``: a ``3h``-word extension
      (``concat([ghost, q[:2h]])`` / ``(q[-2h:], ghost)``) yields the
      edge partition once the ghost lands.

    Soundness is the window path's own argument: roll-wrap garbage
    enters a window edge and walks ONE bit row per fused step, and every
    valid output bit row sits ``32h >= k_max >= k`` rows from the
    nearest edge — in all three programs. The per-word carry-save ops
    are position-identical to the sequential window's, so the
    reassembled ``concat([edge, interior, edge])`` is bit-exact to
    ``make_plan_stepper``'s result (fuzzed in ``tests/test_haloplan.py``).
    One halo word carries 32 board rows: the overlap win multiplied by
    the packing density."""
    if not plan_overlap_supported(plan):
        raise ValueError(f"plan admits no overlap split: {plan}")
    interior = make_window_stepper(
        plan.nw_s - 2 * plan.h, plan.W, h=plan.h, halo_x=0,
        nx_exact=plan.nx_exact, interpret=interpret,
    )
    edge = make_window_stepper(
        plan.h, plan.W, h=plan.h, halo_x=0,
        nx_exact=plan.nx_exact, interpret=interpret,
    )
    return interior, edge


@functools.partial(
    jax.jit, static_argnames=("interpret", "tile_budget_bytes")
)
def _run_fused_bits_jit(
    packed, steps, *, interpret: bool,
    tile_budget_bytes: int = _PACKED_VMEM_LIMIT,
):
    nw, nx = packed.shape
    h = _FUSE_HALO_WORDS
    # Pick the less-amplified tiling: full-width row tiles (wrap by lane
    # roll, no x border) vs column tiles (x-wrap border + 2-D grid).
    tr_full = _fused_tile_words(nw, nx, tile_budget_bytes)
    amp_full = ((tr_full + 2 * h) / tr_full if tr_full >= 8
                else float("inf"))
    plan = _col_tile_plan(nw, nx, tile_budget_bytes)
    use_cols = plan is not None and plan[0] < amp_full
    halo_x = _FUSE_HALO_X if use_cols else 0
    step_call = make_fused_stepper(
        nw, nx, interpret=interpret, tile_budget_bytes=tile_budget_bytes,
        halo_x=halo_x,
    )

    def body(carry):
        p, rem = carry
        k = jnp.minimum(rem, FUSE_MAX_STEPS)
        if halo_x:
            p = jnp.concatenate([p[:, -halo_x:], p, p[:, :halo_x]], axis=1)
        ext = wrap_y(p, h)
        return step_call(k.reshape(1), ext), rem - k

    out, _ = lax.while_loop(
        lambda c: c[1] > 0, body, (packed, steps[0])
    )
    return out


def life_run_fused_bits(
    board: jnp.ndarray, n: int, *, interpret: bool = False,
    tile_budget_bytes: int = _PACKED_VMEM_LIMIT,
) -> jnp.ndarray:
    """Advance ``n`` steps of a big board with the multi-step-fused tiled
    kernel: each HBM pass DMAs row tiles once (plus a 128-bit-row halo —
    nearly free in the packed layout) and runs up to ``FUSE_MAX_STEPS``
    steps tile-resident in VMEM. HBM traffic per step drops ~100x vs a
    step-per-pass kernel, which is what the big-board regime is bound by.
    """
    dtype = board.dtype
    packed = pack_board_exact(board)
    steps = jnp.asarray([n], dtype=jnp.int32)
    out = _run_fused_bits_jit(
        packed, steps, interpret=interpret,
        tile_budget_bytes=tile_budget_bytes,
    )
    return unpack_board_exact(out).astype(dtype)


# ----------------------------------------------------------- big boards (XLA)


def bit_step_xla(p: jnp.ndarray, ny: int, nx: int) -> jnp.ndarray:
    """One packed Life step as plain XLA ops (``jnp.roll`` shifts).

    The compiled-XLA twin of the Pallas :func:`bit_step`: same ghost
    refresh, same carry-save rule, lane rolls via ``jnp.roll``. No
    lane-alignment or tile-budget constraints at all, and competitive
    while the packed board stays near VMEM scale (measured v5e, marginal
    per-step: 41 µs at 8192² vs the fused kernel's 38 µs) — but once XLA
    must materialise the roll intermediates through HBM it falls off
    (653 µs vs 242 µs at 16384²), which is why aligned big boards
    dispatch to :func:`life_run_fused_bits` first.
    """
    p = _refresh_ghosts(p, ny)
    nw = p.shape[0]
    dn = (p << 1) | (jnp.roll(p, 1, 0) >> 31)
    up = (p >> 1) | (jnp.roll(p, nw - 1, 0) << 31)
    return _carry_save_rule(
        p, up, dn,
        lambda x: jnp.roll(x, 1, 1),
        lambda x: jnp.roll(x, nx - 1, 1),
    )


@functools.partial(jax.jit, static_argnames=("ny",))
def _run_bits_xla_jit(packed, steps, *, ny: int):
    nx = packed.shape[1]
    return lax.fori_loop(
        0, steps[0], lambda _, q: bit_step_xla(q, ny, nx), packed
    )


def life_run_bits_xla(board: jnp.ndarray, n: int) -> jnp.ndarray:
    """Advance ``n`` steps with the compiled-XLA packed loop.

    The dispatch target for boards beyond the Pallas VMEM kernel's budget,
    on every backend and any shape (replaces both an earlier explicit-DMA
    row-tiled Pallas kernel and the unpacked roll fallback — see
    :func:`bit_step_xla`). ``n`` is a runtime scalar; no recompile.
    """
    ny, _ = board.shape
    dtype = board.dtype
    packed = pack_board(board)
    steps = jnp.asarray([n], dtype=jnp.int32)
    out = _run_bits_xla_jit(packed, steps, ny=ny)
    return unpack_board(out, ny).astype(dtype)


# ------------------------------------------------- batched (B-board) engines
#
# Every engine above moves ONE board per device program, so a stream of
# independent small boards is dispatch-bound (~70 ms host-device RTT per
# request through the relay). The batched variants below thread a leading
# batch axis through the same packed machinery — B boards advance in ONE
# dispatch, bit-exact per board vs the serial engines:
#
# * the packed layout gains a leading axis, (B, n_words(ny), nx) — the
#   word/lane axes stay the minor (sublane, lane) pair, so the VPU sees
#   the identical tile shapes and the rolls/adders vectorise over B free;
# * the VMEM kernel has a whole-stack-resident form (gated by
#   :func:`fits_vmem_packed_batch` — B x the working set) and a
#   grid-over-batch form (one board resident per program, the batch axis
#   streamed by the Pallas pipeline) for stacks past that gate;
# * the fused/frame big-board engines run the stack as a sequential
#   ``lax.map`` inside one compiled program: big boards are compute-bound
#   (grid parallelism buys nothing on one core), so one dispatch per
#   stack is the whole win;
# * the XLA packed loop vmaps — pure jnp, compiled on every backend.
#
# ``steps`` stays a runtime SMEM/scalar everywhere, so one compiled
# program per (B, ny, nx) shape serves any step count — the property the
# serve-layer shape bucketing (mpi_and_open_mp_tpu/serve/) relies on.
# Each batched jit body ticks ``jit.retrace{fn=...}`` so the bucketing's
# one-compile-per-bucket claim is observable, not asserted.


def _note_retrace(fn: str) -> None:
    """Tick ``jit.retrace{fn=...}`` — call INSIDE jitted bodies only (a
    jit body runs on cache miss, so the count is compiles, not calls)."""
    from mpi_and_open_mp_tpu.obs import metrics

    metrics.inc("jit.retrace", fn=fn)


def pack_boards(boards: jnp.ndarray) -> jnp.ndarray:
    """(B, ny, nx) 0/1 ints -> (B, n_words(ny), nx) uint32 — the batched
    offset-ghost pack (:func:`pack_board` vmapped over the stack)."""
    return jax.vmap(pack_board)(boards)


def unpack_boards(packed: jnp.ndarray, ny: int) -> jnp.ndarray:
    """Inverse of :func:`pack_boards`; returns (B, ny, nx) uint8."""
    return jax.vmap(lambda p: unpack_board(p, ny))(packed)


def _set_word_row_b(p: jnp.ndarray, w: int, row: jnp.ndarray) -> jnp.ndarray:
    """Batched :func:`_set_word_row`: replace word-row ``w`` (axis 1) of a
    (B, nw, nx) stack via concatenation — same ``.at[]`` avoidance."""
    parts = []
    if w > 0:
        parts.append(p[:, :w, :])
    parts.append(row)
    if w + 1 < p.shape[1]:
        parts.append(p[:, w + 1 :, :])
    return jnp.concatenate(parts, axis=1) if len(parts) > 1 else row


def _refresh_ghosts_b(p: jnp.ndarray, ny: int) -> jnp.ndarray:
    """Batched :func:`_refresh_ghosts`: the ghost word/bit indices are a
    function of ``ny`` alone, so one static slice refreshes all B boards."""
    w_lo, b_lo = divmod(ny, 32)
    src = (p[:, w_lo : w_lo + 1, :] >> b_lo) & 1
    p = _set_word_row_b(p, 0, (p[:, 0:1, :] & np.uint32(0xFFFFFFFE)) | src)
    w_hi, b_hi = divmod(ny + 1, 32)
    src = (p[:, 0:1, :] >> 1) & 1
    new_hi = (
        p[:, w_hi : w_hi + 1, :] & np.uint32(0xFFFFFFFF ^ (1 << b_hi))
    ) | (src << b_hi)
    return _set_word_row_b(p, w_hi, new_hi)


def _roll_sub_b(p: jnp.ndarray, shift: int) -> jnp.ndarray:
    nw = p.shape[1]
    if nw == 1:
        return p
    return pltpu.roll(p, shift % nw, 1)


def _lane_rolls_b(shape: tuple[int, int, int], nx: int):
    """3-D twin of :func:`_lane_rolls`: lane axis 2, same wrap-column
    patch when the stack is lane-padded past the board width."""
    nxp = shape[2]
    if nxp == nx:
        return (
            lambda x: pltpu.roll(x, 1, 2),
            lambda x: pltpu.roll(x, nx - 1, 2),
        )
    lane = lax.broadcasted_iota(jnp.int32, shape, 2)

    def roll_left(x):
        return jnp.where(
            lane == 0, x[:, :, nx - 1 : nx], pltpu.roll(x, 1, 2)
        )

    def roll_right(x):
        return jnp.where(
            lane == nx - 1, x[:, :, 0:1], pltpu.roll(x, nxp - 1, 2)
        )

    return roll_left, roll_right


def bit_step_b(p: jnp.ndarray, ny: int, nx: int) -> jnp.ndarray:
    """One Life step on a (B, nw, nx) packed stack — :func:`bit_step`
    vectorised over the leading batch axis (the word/lane axes stay the
    minor sublane/lane pair, so every roll and adder is the same VPU op,
    B boards deep). Boards never interact: the y rolls are per-board
    (axis 1) and the rule is positionwise."""
    p = _refresh_ghosts_b(p, ny)
    nw = p.shape[1]
    dn = (p << 1) | (_roll_sub_b(p, 1) >> 31)
    up = (p >> 1) | (_roll_sub_b(p, nw - 1) << 31)
    return _carry_save_rule(p, up, dn, *_lane_rolls_b(p.shape, nx))


def _vmem_bits_batch_kernel(steps_ref, p_ref, out_ref, *, ny: int, nx: int):
    out_ref[:] = lax.fori_loop(
        0, steps_ref[0], lambda _, p: bit_step_b(p, ny, nx), p_ref[:]
    )


@functools.partial(
    jax.jit, static_argnames=("ny", "nx", "interpret", "resident")
)
def _run_vmem_bits_batch_jit(
    packed, steps, *, ny: int, nx: int, interpret: bool, resident: bool
):
    _note_retrace("life_batch_vmem")
    b, nw, nxp = packed.shape
    if resident:
        # Whole stack VMEM-resident in one program: gated by
        # fits_vmem_packed_batch (B x the per-board working set).
        return pl.pallas_call(
            functools.partial(_vmem_bits_batch_kernel, ny=ny, nx=nx),
            out_shape=jax.ShapeDtypeStruct(packed.shape, packed.dtype),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            interpret=interpret,
        )(steps, packed)
    # Grid over the batch axis: one board resident per program, the
    # stack streamed through VMEM by the pipeline (per-board gate only).
    return pl.pallas_call(
        functools.partial(_vmem_bits_batch_kernel, ny=ny, nx=nx),
        grid=(b,),
        out_shape=jax.ShapeDtypeStruct(packed.shape, packed.dtype),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, nw, nxp), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, nw, nxp), lambda i: (i, 0, 0)),
        interpret=interpret,
    )(steps, packed)


def life_run_vmem_bits_batch(
    boards: jnp.ndarray, n: int, *, interpret: bool = False,
    resident: bool | None = None,
) -> jnp.ndarray:
    """Advance B stacked boards ``n`` steps in ONE packed VMEM dispatch.

    Same lane padding and runtime-scalar step count as
    :func:`life_run_vmem_bits`. ``resident=None`` picks the whole-stack-
    resident kernel when :func:`fits_vmem_packed_batch` allows and the
    grid-over-batch form otherwise (tests pin either form explicitly);
    callers must gate per-board shapes on :func:`fits_vmem_packed`.
    """
    b, ny, nx = boards.shape
    dtype = boards.dtype
    nxp = -(-nx // 128) * 128
    if nxp != nx:
        boards = jnp.pad(boards, ((0, 0), (0, 0), (0, nxp - nx)))
    if resident is None:
        resident = fits_vmem_packed_batch((b, ny, nx))
    packed = pack_boards(boards)
    steps = jnp.asarray([n], dtype=jnp.int32)
    out = _run_vmem_bits_batch_jit(
        packed, steps, ny=ny, nx=nx, interpret=interpret, resident=resident
    )
    return unpack_boards(out, ny)[:, :, :nx].astype(dtype)


@functools.partial(jax.jit, static_argnames=("ny",))
def _run_bits_xla_batch_jit(packed, steps, *, ny: int):
    _note_retrace("life_batch_xla")
    nx = packed.shape[2]
    step = jax.vmap(lambda q: bit_step_xla(q, ny, nx))
    return lax.fori_loop(0, steps[0], lambda _, q: step(q), packed)


def life_run_bits_xla_batch(boards: jnp.ndarray, n: int) -> jnp.ndarray:
    """Advance B stacked boards with the compiled-XLA packed loop — the
    any-shape any-backend batched engine (:func:`bit_step_xla` vmapped;
    one dispatch, runtime-scalar step count)."""
    _, ny, _ = boards.shape
    dtype = boards.dtype
    packed = pack_boards(boards)
    steps = jnp.asarray([n], dtype=jnp.int32)
    out = _run_bits_xla_batch_jit(packed, steps, ny=ny)
    return unpack_boards(out, ny).astype(dtype)


@functools.partial(
    jax.jit, static_argnames=("interpret", "tile_budget_bytes")
)
def _run_fused_bits_batch_jit(
    packed, steps, *, interpret: bool,
    tile_budget_bytes: int = _PACKED_VMEM_LIMIT,
):
    _note_retrace("life_batch_fused")
    # Sequential scan over the stack, ONE compiled program: fused-regime
    # boards are compute-bound on the core, so batching exists to
    # amortise the dispatch, not to overlap boards. (A vmap would lean on
    # pallas batching rules over the explicit-DMA scratch kernel; the
    # scan keeps the proven single-board program byte-identical.)
    return lax.map(
        lambda p: _run_fused_bits_jit(
            p, steps, interpret=interpret,
            tile_budget_bytes=tile_budget_bytes,
        ),
        packed,
    )


def life_run_fused_bits_batch(
    boards: jnp.ndarray, n: int, *, interpret: bool = False,
    tile_budget_bytes: int = _PACKED_VMEM_LIMIT,
) -> jnp.ndarray:
    """Advance B stacked ALIGNED big boards via the multi-step-fused tiled
    kernel, all boards in one dispatch (see the scan note in the jit)."""
    dtype = boards.dtype
    packed = jax.vmap(pack_board_exact)(boards)
    steps = jnp.asarray([n], dtype=jnp.int32)
    out = _run_fused_bits_batch_jit(
        packed, steps, interpret=interpret,
        tile_budget_bytes=tile_budget_bytes,
    )
    return jax.vmap(unpack_board_exact)(out).astype(dtype)


@functools.partial(
    jax.jit, static_argnames=("ny", "nx", "interpret", "budget")
)
def _run_frame_bits_batch_jit(
    packed, steps, *, ny: int, nx: int, interpret: bool, budget: int
):
    _note_retrace("life_batch_frame")
    return lax.map(
        lambda p: _run_frame_bits_jit(
            p, steps, ny=ny, nx=nx, interpret=interpret, budget=budget
        ),
        packed,
    )


def life_run_frame_bits_batch(
    boards: jnp.ndarray, n: int, *, interpret: bool = False,
    budget: int = _PACKED_VMEM_LIMIT,
) -> jnp.ndarray:
    """Advance B stacked UNALIGNED big boards via the padded-torus frame,
    all boards in one dispatch (same sequential-scan rationale as the
    fused batch). Gate on ``plan_sharded_bits(shape, 1, 1, False, False)``.
    """
    b, ny, nx = boards.shape
    plan = plan_sharded_bits((ny, nx), 1, 1, False, False, budget)
    if plan is None:
        raise ValueError(
            f"no padded-frame plan for {(ny, nx)}; gate callers on "
            "plan_sharded_bits()"
        )
    dtype = boards.dtype
    frames = jnp.pad(
        boards,
        ((0, 0), (0, plan.frame[0] - ny), (0, plan.frame[1] - nx)),
    )
    packed = jax.vmap(pack_board_exact)(frames)
    steps = jnp.asarray([n], dtype=jnp.int32)
    out = _run_frame_bits_batch_jit(
        packed, steps, ny=ny, nx=nx, interpret=interpret, budget=budget
    )
    return jax.vmap(unpack_board_exact)(out)[:, :ny, :nx].astype(dtype)


# --------------------------------------------- board-sliced (bitsliced) layout
#
# Second pluggable pack layout for batched stacks. The cell-packed layout
# above slices SPACE into bits (32 board rows per uint32, one board per
# bitplane), so B boards still cost B times the vector work. Board-sliced
# flips the packing axis: bit ``b`` of every word belongs to board ``b``,
# tensor shape (n_planes, ny, nx) with ``n_planes = ceil(B / 32)`` — one
# VPU op advances up to 32 worlds at once, and the spatial axes stay
# plain, so every neighbour gather is an ordinary torus roll with no
# cross-word carry games and no ghost rows.
#
# Engines (both runtime-scalar steps, ``jit.retrace{fn=
# life_batch_bitsliced}`` observable):
#
# * :func:`_run_bitsliced_pallas_jit` — whole plane stack VMEM-resident,
#   the step loop inside one kernel; spatial gathers are ``pltpu.roll``
#   with the :func:`_lane_rolls_b` wrap-column patch for lane padding.
# * :func:`_run_bitsliced_xla_jit` — the compiled-XLA twin, structured
#   for XLA:CPU fusion rather than as literal rolls: the stack carries a
#   ``_BITSLICE_HALO``-deep wrapped halo, each step is NINE static slices
#   feeding one fused rule + pad kernel (measured ~8x the vmapped
#   cell-packed loop at B=32, 64² on CPU; plain per-step rolls measure
#   only ~1.9x because each roll materialises a concat).
#
# Ragged B zero-pads the high bits; an all-dead plane bit stays dead
# under the rule (N = 0 never births), so padding boards are inert and
# :func:`unpack_batch_bits` simply slices them off.

_BITSLICE_HALO = 4


def n_planes(b: int) -> int:
    """Board-sliced planes for a B-board stack: ``ceil(B / 32)``."""
    return -(-b // 32)


def fits_vmem_bitsliced(shape: tuple[int, int, int]) -> bool:
    """Whether a (B, ny, nx) stack's plane tensor fits the VMEM budget.

    Same arithmetic as :func:`fits_vmem_packed`: lane-padded plane bytes
    against ``_PACKED_VMEM_LIMIT`` (the step loop holds the same ~11
    live temporaries, each ``n_planes`` deep). A 500² board is one
    1.0 MB plane (passes); past ~1000² the cell-packed big-board ladder
    takes over."""
    b, ny, nx = shape
    nxp = -(-nx // 128) * 128
    return n_planes(b) * ny * nxp * 4 <= _PACKED_VMEM_LIMIT


def pack_batch_bits(boards: jnp.ndarray) -> jnp.ndarray:
    """(B, ny, nx) 0/1 ints -> (n_planes, ny, nx) uint32, board-sliced:
    bit ``b % 32`` of plane ``b // 32`` holds board ``b``'s cell. Ragged
    B zero-pads the high bits (inert under the rule — see above)."""
    b, ny, nx = boards.shape
    npl = n_planes(b)
    pad = npl * 32 - b
    if pad:
        boards = jnp.concatenate(
            [boards, jnp.zeros((pad, ny, nx), boards.dtype)], axis=0
        )
    shifts = jnp.arange(32, dtype=jnp.uint32)[None, :, None, None]
    return (
        boards.astype(jnp.uint32).reshape(npl, 32, ny, nx) << shifts
    ).sum(axis=1, dtype=jnp.uint32)


def unpack_batch_bits(planes: jnp.ndarray, b: int) -> jnp.ndarray:
    """Inverse of :func:`pack_batch_bits`; returns (b, ny, nx) uint8."""
    npl, ny, nx = planes.shape
    shifts = jnp.arange(32, dtype=jnp.uint32)[None, :, None, None]
    rows = ((planes[:, None] >> shifts) & jnp.uint32(1)).reshape(
        npl * 32, ny, nx
    )
    return rows[:b].astype(jnp.uint8)


def lane_change_bits(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Per-lane change summary of two (P, ny, nx) bit-sliced slabs: one
    uint32 per plane whose bit ``l`` is set iff lane ``l``'s board
    differs anywhere between ``a`` and ``b`` — an OR-reduction of the
    XOR over both spatial axes, so the whole summary costs one
    elementwise pass and ships 4*P bytes. When ``a`` and ``b`` are
    CONSECUTIVE steps of the same slab, a zero bit is a proven fixed
    point (the next step of an unchanged board is unchanged forever) —
    the predicate the session pool's settled-skip rides."""
    return lax.reduce(a ^ b, jnp.uint32(0), lax.bitwise_or, (1, 2))


def _carry_save_rule9(c, up, dn, lf, rt, ul, ur, dl, dr):
    """:func:`_carry_save_rule` with all eight neighbours supplied as
    operands instead of via roll callbacks — the form the halo-fused XLA
    engine needs, where every neighbour is a static slice of the same
    halo-padded array (so XLA fuses the whole rule, slices included,
    into one elementwise kernel per step). Identical adder tree and
    mod-8 wrap semantics; the column sums just can't share the
    half-adder prefix because the side columns arrive pre-gathered."""
    cs0 = up ^ dn
    cs1 = up & dn
    l0 = ul ^ lf ^ dl
    l1 = (ul & lf) | ((ul ^ lf) & dl)
    r0 = ur ^ rt ^ dr
    r1 = (ur & rt) | ((ur ^ rt) & dr)
    p0 = l0 ^ r0
    q0 = l0 & r0
    p1x = l1 ^ r1
    p1 = p1x ^ q0
    p2 = (l1 & r1) | (p1x & q0)
    n0 = p0 ^ cs0
    rc = p0 & cs0
    n1x = p1 ^ cs1
    n1 = n1x ^ rc
    n2 = p2 ^ ((p1 & cs1) | (n1x & rc))
    return (n0 | c) & n1 & ~n2


def bitsliced_step(planes: jnp.ndarray, nx: int) -> jnp.ndarray:
    """One Life step on a (n_planes, ny, nx-or-lane-padded) stack — the
    roll form shared by the Pallas kernel (and usable under interpret
    mode). The bit axis is batch, so the spatial gathers are plain torus
    rolls: y via sublane rolls, x via :func:`_lane_rolls_b` (exact
    ``nx`` wrap on the lane-padded fast path)."""
    ny = planes.shape[1]
    up = pltpu.roll(planes, ny - 1, 1) if ny > 1 else planes
    dn = pltpu.roll(planes, 1, 1) if ny > 1 else planes
    return _carry_save_rule(
        planes, up, dn, *_lane_rolls_b(planes.shape, nx)
    )


def _bitsliced_kernel(steps_ref, p_ref, out_ref, *, nx: int):
    out_ref[:] = lax.fori_loop(
        0, steps_ref[0], lambda _, p: bitsliced_step(p, nx), p_ref[:]
    )


@functools.partial(jax.jit, static_argnames=("nx", "interpret"))
def _run_bitsliced_pallas_jit(planes, steps, *, nx: int, interpret: bool):
    _note_retrace("life_batch_bitsliced")
    return pl.pallas_call(
        functools.partial(_bitsliced_kernel, nx=nx),
        out_shape=jax.ShapeDtypeStruct(planes.shape, planes.dtype),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(steps, planes)


@jax.jit
def _run_bitsliced_xla_jit(planes, steps):
    """Compiled-XLA bitsliced loop, halo-fused for CPU throughput.

    The stack carries a K-deep wrapped halo (K = ``_BITSLICE_HALO``,
    clamped to the board for tiny shapes). Every K steps the halo is
    rebuilt from the valid centre (two concats); each step reads NINE
    static slices of the halo frame into :func:`_carry_save_rule9` and
    zero-pads the result back to frame shape — slices and pad fuse with
    the rule into one XLA:CPU kernel per step, where per-step torus
    rolls would each materialise a concat. Validity shrinks one ring
    per step and never reaches the centre before the next refresh; the
    pad ring is junk by construction. ``steps`` stays a runtime scalar:
    the block loop is a while over remaining steps, the intra-block
    loop a fori over ``min(rem, K)``."""
    _note_retrace("life_batch_bitsliced")
    _, ny, nx = planes.shape
    k_halo = min(_BITSLICE_HALO, ny, nx)
    nyp, nxp = ny + 2 * k_halo, nx + 2 * k_halo

    def refresh(frame):
        rows = jnp.concatenate(
            [
                frame[:, ny : k_halo + ny],
                frame[:, k_halo : k_halo + ny],
                frame[:, k_halo : 2 * k_halo],
            ],
            axis=1,
        )
        return jnp.concatenate(
            [
                rows[:, :, nx : k_halo + nx],
                rows[:, :, k_halo : k_halo + nx],
                rows[:, :, k_halo : 2 * k_halo],
            ],
            axis=2,
        )

    def halo_step(frame):
        def s(dy, dx):
            return frame[:, 1 + dy : nyp - 1 + dy, 1 + dx : nxp - 1 + dx]

        out = _carry_save_rule9(
            s(0, 0), s(-1, 0), s(1, 0), s(0, -1), s(0, 1),
            s(-1, -1), s(-1, 1), s(1, -1), s(1, 1),
        )
        return jnp.pad(out, ((0, 0), (1, 1), (1, 1)))

    def body(carry):
        frame, rem = carry
        k = jnp.minimum(rem, k_halo)
        frame = refresh(frame)
        frame = lax.fori_loop(0, k, lambda _, f: halo_step(f), frame)
        return frame, rem - k

    frame0 = jnp.pad(
        planes, ((0, 0), (k_halo, k_halo), (k_halo, k_halo))
    )
    frame, _ = lax.while_loop(
        lambda c: c[1] > 0, body, (frame0, steps[0])
    )
    return frame[:, k_halo : k_halo + ny, k_halo : k_halo + nx]


def life_run_bitsliced_batch(
    boards: jnp.ndarray, n: int, *, interpret: bool = False,
    use_kernel: bool | None = None,
) -> jnp.ndarray:
    """Advance B stacked boards ``n`` steps through the board-sliced
    layout in ONE dispatch: pack to bitplanes, run the whole step loop
    compiled, unpack, slice the ragged padding off.

    ``use_kernel=None`` picks the Pallas VMEM kernel on real hardware
    (``interpret=False``) and the halo-fused XLA twin otherwise — on CPU
    the twin IS the fast path, not a consolation (see the section
    comment); tests pin ``use_kernel=True, interpret=True`` to cover the
    kernel itself. The inner jit is keyed on the PLANE shape, so one
    compile per (n_planes, ny, nx) serves every ragged B in the plane
    and every step count."""
    b, ny, nx = boards.shape
    dtype = boards.dtype
    planes = pack_batch_bits(boards)
    steps = jnp.asarray([n], dtype=jnp.int32)
    if use_kernel is None:
        use_kernel = not interpret
    if use_kernel:
        nxp = -(-nx // 128) * 128
        if nxp != nx:
            planes = jnp.pad(planes, ((0, 0), (0, 0), (0, nxp - nx)))
        out = _run_bitsliced_pallas_jit(
            planes, steps, nx=nx, interpret=interpret
        )[:, :, :nx]
    else:
        out = _run_bitsliced_xla_jit(planes, steps)
    return unpack_batch_bits(out, b).astype(dtype)
