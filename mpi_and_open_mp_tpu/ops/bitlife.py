"""Bit-packed Life kernels: 32 cells per uint32 lane, bitwise rule.

The reference's compute kernel spends ~12 arithmetic ops per cell on the
8-neighbour count (``/root/reference/3-life/life2d.c:104-130``). On a TPU
VPU the state is 1 bit, so the idiomatic kernel packs 32 cells into each
uint32 **along y** (the sublane axis) and evaluates the rule with bitwise
carry-save adders — ~50 vector ops per 32 cells ≈ 1.5 ops/cell, and 32x
less VMEM/HBM traffic than an int32 board. This is the framework's fast
path for single-shard boards; it is bit-exact against the NumPy oracle
(tests/test_bitlife.py exercises odd sizes, gliders, and random soups).

Packed layout ("offset-ghost"): bit position ``p`` of the packed column
holds board row ``y = p - 1``; position ``0`` mirrors row ``ny-1`` and
position ``ny+1`` mirrors row ``0`` (the torus ghosts). Each step first
refreshes the two ghost bits from live state, then

* y-neighbours are single-bit shifts across the packed words (cross-word
  carries via a sublane roll),
* x-neighbours are lane rolls with the exact ``nx`` wrap (no padding in x),
* the 9-cell sum ``T`` is built as 2-bit column sums combined by full
  adders into a 4-bit count, and the rule is ``T==3 | (alive & T==4)``
  (the +1-including-centre form of birth-on-3 / survive-on-2-or-3,
  ``life2d.c:117-123``).

The whole step loop runs inside one ``pallas_call`` with the packed board
VMEM-resident; a 500x500 board packs to 16x500 uint32 = 32 KB. The gate
is the packed bytes times the ~11 live step temporaries against the
~16 MB/core scoped-VMEM budget (see ``_PACKED_VMEM_LIMIT``): ~3200² is
the measured ceiling; beyond it the HBM row-tiled kernel takes over.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Packed board bytes kept VMEM-resident. The step body holds ~10 live
# same-shape temporaries, so the working set is ~11x the board against the
# ~16 MB/core scoped-VMEM budget; measured on v5e: 1.23 MB packed (3200²)
# compiles, 1.47 MB (3500²) is rejected by Mosaic.
_PACKED_VMEM_LIMIT = 5 << 18


def n_words(ny: int) -> int:
    """Packed sublane words for ``ny`` rows plus the two ghost positions."""
    return (ny + 2 + 31) // 32


def fits_vmem_packed(shape: tuple[int, int]) -> bool:
    ny, nx = shape
    return n_words(ny) * nx * 4 <= _PACKED_VMEM_LIMIT


def pack_board(board: jnp.ndarray) -> jnp.ndarray:
    """(ny, nx) 0/1 ints -> (n_words(ny), nx) uint32, offset-ghost layout.

    Ghost bits are left zero; the kernel refreshes them at the top of every
    step, so they never need to be materialised here.
    """
    ny, nx = board.shape
    nw = n_words(ny)
    rows = jnp.zeros((nw * 32, nx), dtype=jnp.uint32)
    rows = rows.at[1 : ny + 1, :].set(board.astype(jnp.uint32))
    shifts = jnp.arange(32, dtype=jnp.uint32)[None, :, None]
    return (rows.reshape(nw, 32, nx) << shifts).sum(
        axis=1, dtype=jnp.uint32
    )


def unpack_board(packed: jnp.ndarray, ny: int) -> jnp.ndarray:
    """Inverse of :func:`pack_board`; returns (ny, nx) uint8."""
    nw, nx = packed.shape
    shifts = jnp.arange(32, dtype=jnp.uint32)[None, :, None]
    rows = ((packed[:, None, :] >> shifts) & jnp.uint32(1)).reshape(
        nw * 32, nx
    )
    return rows[1 : ny + 1, :].astype(jnp.uint8)


def _set_word_row(p: jnp.ndarray, w: int, row: jnp.ndarray) -> jnp.ndarray:
    """Replace word-row ``w`` of ``p`` (static index) via concatenation.

    ``p.at[w:w+1].set`` is avoided: when the slice covers a whole axis, its
    lowering closes over an empty i32 array, which ``pallas_call`` rejects
    as a captured constant.
    """
    parts = []
    if w > 0:
        parts.append(p[:w, :])
    parts.append(row)
    if w + 1 < p.shape[0]:
        parts.append(p[w + 1 :, :])
    return jnp.concatenate(parts, axis=0) if len(parts) > 1 else row


def _refresh_ghosts(p: jnp.ndarray, ny: int) -> jnp.ndarray:
    """Rewrite the two torus ghost bits from live board state.

    Position 0 := position ny (board row ny-1); position ny+1 := position 1
    (board row 0). Static word/bit indices — ``ny`` is a trace-time const.
    """
    # np.uint32 literals throughout: concrete jnp scalars would be captured
    # as pallas kernel constants (rejected), and Python ints above 2^31
    # overflow the weak-int32 promotion path.
    w_lo, b_lo = divmod(ny, 32)  # source bit for ghost position 0
    src = (p[w_lo : w_lo + 1, :] >> b_lo) & 1
    p = _set_word_row(p, 0, (p[0:1, :] & np.uint32(0xFFFFFFFE)) | src)
    w_hi, b_hi = divmod(ny + 1, 32)  # target word/bit for ghost top
    src = (p[0:1, :] >> 1) & 1  # position 1 = board row 0
    new_hi = (
        p[w_hi : w_hi + 1, :] & np.uint32(0xFFFFFFFF ^ (1 << b_hi))
    ) | (src << b_hi)
    return _set_word_row(p, w_hi, new_hi)


def _roll_sub(p: jnp.ndarray, shift: int) -> jnp.ndarray:
    nw = p.shape[0]
    if nw == 1:
        return p
    return pltpu.roll(p, shift % nw, 0)


def bit_step(p: jnp.ndarray, ny: int, nx: int) -> jnp.ndarray:
    """One Life step on a packed board (ghost refresh + bitwise rule)."""
    p = _refresh_ghosts(p, ny)
    nw = p.shape[0]
    # y-neighbours: single-bit shifts through the packed words. The junk
    # carried into ghost/slack positions never reaches a live bit.
    dn = (p << 1) | (_roll_sub(p, 1) >> 31)
    up = (p >> 1) | (_roll_sub(p, nw - 1) << 31)
    # 2-bit column sums up+centre+down (carry-save adder).
    ys0 = up ^ p ^ dn
    ys1 = (up & p) | (dn & (up ^ p))
    # x-neighbours: lane rolls with the exact torus wrap at nx.
    l0 = pltpu.roll(ys0, 1, 1)
    r0 = pltpu.roll(ys0, nx - 1, 1)
    l1 = pltpu.roll(ys1, 1, 1)
    r1 = pltpu.roll(ys1, nx - 1, 1)
    # T = left + centre + right column sums: 4-bit 9-cell total.
    t0 = l0 ^ ys0 ^ r0
    k0 = (l0 & ys0) | (r0 & (l0 ^ ys0))
    u0 = l1 ^ ys1 ^ r1
    u1 = (l1 & ys1) | (r1 & (l1 ^ ys1))
    t1 = u0 ^ k0
    v = u0 & k0
    t2 = u1 ^ v
    t3 = u1 & v
    # alive' = (T == 3) | (alive & T == 4), with T including the centre.
    is3 = t0 & t1 & ~t2 & ~t3
    is4 = ~t0 & ~t1 & t2 & ~t3
    return is3 | (p & is4)


def _vmem_bits_kernel(steps_ref, p_ref, out_ref, *, ny: int, nx: int):
    out_ref[:] = lax.fori_loop(
        0, steps_ref[0], lambda _, p: bit_step(p, ny, nx), p_ref[:]
    )


@functools.partial(jax.jit, static_argnames=("ny", "interpret"))
def _run_vmem_bits_jit(packed, steps, *, ny: int, interpret: bool):
    nx = packed.shape[1]
    return pl.pallas_call(
        functools.partial(_vmem_bits_kernel, ny=ny, nx=nx),
        out_shape=jax.ShapeDtypeStruct(packed.shape, packed.dtype),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(steps, packed)


def life_run_vmem_bits(
    board: jnp.ndarray, n: int, *, interpret: bool = False
) -> jnp.ndarray:
    """Advance ``n`` steps with the packed VMEM-resident loop kernel.

    Pack/unpack are plain XLA ops fused around the single kernel launch;
    ``n`` is a runtime SMEM scalar (no recompile when it changes).
    """
    ny, _ = board.shape
    dtype = board.dtype
    packed = pack_board(board)
    steps = jnp.asarray([n], dtype=jnp.int32)
    out = _run_vmem_bits_jit(packed, steps, ny=ny, interpret=interpret)
    return unpack_board(out, ny).astype(dtype)


# --------------------------------------------------------------- tiled (HBM)


def _bit_window_step(b: jnp.ndarray, nx: int) -> jnp.ndarray:
    """Stencil a ``(tr + 2, nx)`` packed word-row window to its ``(tr, nx)``
    interior. Ghost bits must already be valid (see :func:`_refresh_ghosts`);
    y-carries come from the window rows, x-wrap from lane rolls."""
    c = b[1:-1, :]
    dn = (c << 1) | (b[:-2, :] >> 31)
    up = (c >> 1) | (b[2:, :] << 31)
    ys0 = up ^ c ^ dn
    ys1 = (up & c) | (dn & (up ^ c))
    l0 = pltpu.roll(ys0, 1, 1)
    r0 = pltpu.roll(ys0, nx - 1, 1)
    l1 = pltpu.roll(ys1, 1, 1)
    r1 = pltpu.roll(ys1, nx - 1, 1)
    t0 = l0 ^ ys0 ^ r0
    k0 = (l0 & ys0) | (r0 & (l0 ^ ys0))
    u0 = l1 ^ ys1 ^ r1
    u1 = (l1 & ys1) | (r1 & (l1 ^ ys1))
    t1 = u0 ^ k0
    v = u0 & k0
    t2 = u1 ^ v
    t3 = u1 & v
    is3 = t0 & t1 & ~t2 & ~t3
    is4 = ~t0 & ~t1 & t2 & ~t3
    return is3 | (c & is4)


def _tiled_bits_kernel(hbm_ref, out_ref, scratch, sem):
    """One program = one (tr, nx) packed word-row tile.

    The input is the packed board pre-padded with EIGHT word rows above and
    below (content irrelevant: those bits only ever feed ghost or junk
    positions — see the offset-ghost layout notes in the module doc), so
    each tile reads one sublane-aligned contiguous (tr + 16)-row DMA
    (Mosaic requires 8-divisible offsets AND extents for memref slices)
    and slices its (tr + 2) stencil window at value level, where unaligned
    sublane offsets are legal.
    """
    i = pl.program_id(0)
    tr = out_ref.shape[0]
    nx = hbm_ref.shape[1]
    cp = pltpu.make_async_copy(
        hbm_ref.at[pl.ds(i * tr, tr + 16)], scratch, sem
    )
    cp.start()
    cp.wait()
    out_ref[:] = _bit_window_step(scratch[7 : tr + 9, :], nx)


def _tile_words(nw: int, nx: int, max_tile_bytes: int = 1 << 20) -> int:
    """Packed word rows per tile, keeping the scratch window in budget.

    Always a multiple of 8: every explicit-DMA memref slice (offset AND
    extent) must be sublane-aligned on real Mosaic — including the
    single-tile case, whose window is ``tr + 16`` rows of the padded
    carry. The budget covers the full ``(tr + 16, nx)`` scratch window.
    Returns <8 when no in-budget split exists (ultra-wide nx) — callers
    must gate on :func:`tiled_bits_supported`.
    """
    cap = (max_tile_bytes // (4 * nx) - 16) // 8 * 8
    return min(cap, -(-nw // 8) * 8)


def tiled_bits_supported(shape: tuple[int, int]) -> bool:
    """Whether the packed row-tiled kernel can run ``shape`` COMPILED.

    Two hardware constraints (interpret mode has neither, so tests may
    drive unaligned shapes directly): the lane dim must be 128-aligned —
    an explicit-DMA VMEM scratch with a padded lane allocation lowers to
    a lane-unaligned ``memref_slice``, which Mosaic rejects — and the
    tile split must fit the VMEM budget with at least 8 word rows.
    """
    ny, nx = shape
    return nx % 128 == 0 and _tile_words(n_words(ny), nx) >= 8


def _refresh_ghosts_ext(ext: jnp.ndarray, ny: int) -> jnp.ndarray:
    """Ghost refresh on the 8-row-padded carry of the tiled loop.

    Word row ``w`` lives at ``ext`` row ``w + 8``. Implemented as two
    single-row ``dynamic_update_slice`` writes (static indices): inside a
    ``fori_loop`` XLA performs these in place on the loop carry, unlike the
    concatenate-based :func:`_set_word_row`, whose per-step full-array
    copies dominate the step cost at big-board sizes.
    """
    w_lo, b_lo = divmod(ny, 32)  # source bit for ghost position 0
    src = (ext[8 + w_lo : 9 + w_lo, :] >> b_lo) & 1
    row0 = (ext[8:9, :] & np.uint32(0xFFFFFFFE)) | src
    ext = lax.dynamic_update_slice(ext, row0, (8, 0))
    w_hi, b_hi = divmod(ny + 1, 32)  # target word/bit for ghost top
    src = (ext[8:9, :] >> 1) & 1  # position 1 = board row 0
    row_hi = (
        ext[8 + w_hi : 9 + w_hi, :] & np.uint32(0xFFFFFFFF ^ (1 << b_hi))
    ) | (src << b_hi)
    return lax.dynamic_update_slice(ext, row_hi, (8 + w_hi, 0))


@functools.partial(
    jax.jit, static_argnames=("ny", "interpret", "max_tile_bytes")
)
def _run_tiled_bits_jit(
    packed, steps, *, ny: int, interpret: bool, max_tile_bytes: int = 1 << 20
):
    nw, nx = packed.shape
    tr = _tile_words(nw, nx, max_tile_bytes)
    if tr < 8:
        raise ValueError(
            f"no in-budget tile split for packed shape {(nw, nx)}; gate "
            "callers on tiled_bits_supported()"
        )
    nwp = -(-nw // tr) * tr
    # The loop carry is the 8-row-padded board (see _tiled_bits_kernel);
    # padding happens ONCE here, and each step writes the kernel output
    # back into the carry in place (dynamic_update_slice at a static
    # offset). Per-step pad/concatenate copies would dominate the cost.
    ext = jnp.pad(packed, ((8, 8 + (nwp - nw)), (0, 0)))

    step_call = pl.pallas_call(
        _tiled_bits_kernel,
        grid=(nwp // tr,),
        out_shape=jax.ShapeDtypeStruct((nwp, nx), packed.dtype),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(
            (tr, nx), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            pltpu.VMEM((tr + 16, nx), packed.dtype),
            pltpu.SemaphoreType.DMA(()),
        ],
        interpret=interpret,
    )

    def body(_, q):
        out = step_call(_refresh_ghosts_ext(q, ny))
        return lax.dynamic_update_slice(q, out, (8, 0))

    out = lax.fori_loop(0, steps[0], body, ext)
    return out[8 : 8 + nw, :]


def life_run_tiled_bits(
    board: jnp.ndarray,
    n: int,
    *,
    interpret: bool = False,
    max_tile_bytes: int = 1 << 20,
) -> jnp.ndarray:
    """Advance ``n`` steps of a big board with the HBM-resident packed
    row-tiled kernel: one packed read + write pass per step — 1/32nd the
    bandwidth of an unpacked int32 row-tiled stencil."""
    ny, _ = board.shape
    dtype = board.dtype
    packed = pack_board(board)
    steps = jnp.asarray([n], dtype=jnp.int32)
    out = _run_tiled_bits_jit(
        packed, steps, ny=ny, interpret=interpret, max_tile_bytes=max_tile_bytes
    )
    return unpack_board(out, ny).astype(dtype)
