"""Trapezoidal quadrature kernels.

Reference: ``/root/reference/1-integral/integral.c`` — ∫₀² √(4−x²) dx ≈ π by
N trapezoids of width h = 2/N (``integral.c:12-13``), partial sums per rank
(``integral.c:50-53``) hand-reduced to the root with Send/Recv
(``integral.c:39-43``).

TPU-native design: no rank loops — one ``shard_map`` over a 1-D mesh where
each device evaluates its contiguous range as vectorised VPU blocks
(``fori_loop`` over CHUNK-point blocks, tails masked) and the reduction is a
single ``lax.psum``. A grid point ``i ∈ [0, N]`` contributes ``h·w·f(a+i·h)``
with half weight at the two global endpoints — one ``f`` evaluation per
point instead of the reference's two per trapezoid.

Index arithmetic is done in *chunk units* so N up to 10¹²⁺ works without
64-bit device integers (TPU jnp ints are int32 by default): a point is
``(g, r)`` with global chunk id ``g = i // CHUNK`` (≤ N/CHUNK ≈ 7.6M at
N=10¹², exact in int32 AND in f32's 24-bit mantissa) and lane ``r = i %
CHUNK``; its abscissa is ``a + g·(CHUNK·h) + r·h``. This also fixes, rather
than inherits, the reference's 32-bit ``atoi`` truncation of N=10¹²
(``integral.c:12``, SURVEY §2 quirks).

Precision (TPU has no fast f64): per-chunk sums are XLA tree reductions in
f32, and the across-chunk accumulator uses Kahan compensated summation, so
accumulation error stays near f32 ulp level instead of growing with chunk
count. Remaining error is dominated by f32 rounding of the abscissae and of
``f`` itself — observed relative error vs π is ~1e-6 at N=10⁸ and stays at
that order for larger N (each sample's abscissa is exact to ~1.2e-7
relative; the rule error itself falls below f32 noise past N≈10⁶).
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp
from jax import lax

from mpi_and_open_mp_tpu.parallel.halo import axis_size

# Grid points evaluated per loop iteration on each device (VPU-friendly).
CHUNK = 1 << 17


def f_circle(x: jnp.ndarray) -> jnp.ndarray:
    """The reference integrand √(4 − x²)  (``integral.c:7``)."""
    return jnp.sqrt(jnp.maximum(4.0 - x * x, 0.0))


def _chunk_grid(n: int):
    """Static chunk-unit geometry for grid points 0..n."""
    last_chunk = n // CHUNK  # chunk holding point n
    last_lane = n % CHUNK
    n_chunks = last_chunk + 1
    return n_chunks, last_chunk, last_lane


def _block_sum(f: Callable, a: float, h: float, g, n: int) -> jnp.ndarray:
    """Weighted Σ f over the CHUNK points of global chunk ``g`` (traced int32),
    masking lanes past point ``n`` and half-weighting the global endpoints."""
    _, last_chunk, last_lane = _chunk_grid(n)
    r = lax.broadcasted_iota(jnp.int32, (CHUNK, 1), 0).squeeze(-1)
    in_range = (g < last_chunk) | ((g == last_chunk) & (r <= last_lane))
    is_first = (g == 0) & (r == 0)
    is_last = (g == last_chunk) & (r == last_lane)
    w = jnp.where(is_first | is_last, 0.5, 1.0).astype(jnp.float32)
    x = (
        jnp.float32(a)
        + g.astype(jnp.float32) * jnp.float32(CHUNK * h)
        + r.astype(jnp.float32) * jnp.float32(h)
    )
    return jnp.sum(jnp.where(in_range, w * f(x), 0.0))


def trapezoid_shard_sum(
    f: Callable, a: float, b: float, n: int, axis_name: str
) -> jnp.ndarray:
    """Per-device partial trapezoid sum; call inside ``shard_map``.

    Whole chunks are dealt round-robin-free in contiguous ceil-blocks over
    the mesh axis (the TPU version of the reference's ``interval_size =
    ceil(N/size)`` chunking, ``integral.c:34,49``); returns the
    ``lax.psum``-reduced global integral.
    """
    p = axis_size(axis_name)  # static: mesh shape known at trace time
    k = lax.axis_index(axis_name)
    h = (b - a) / n
    n_chunks, _, _ = _chunk_grid(n)
    per = (n_chunks + p - 1) // p  # ceil chunks per device (static)

    def body(c, carry):
        acc, comp = carry  # Kahan: comp carries the lost low-order bits
        g = k.astype(jnp.int32) * per + c  # global chunk id, int32-safe
        val = jnp.where(
            g < n_chunks, _block_sum(f, a, h, g, n), jnp.float32(0.0)
        )
        y = val - comp
        t = acc + y
        comp = (t - acc) - y
        return (t, comp)

    partial, _ = lax.fori_loop(
        0, per, body, (jnp.float32(0.0), jnp.float32(0.0))
    )
    return lax.psum(partial, axis_name) * jnp.float32(h)


def trapezoid_serial(f: Callable, a: float, b: float, n: int) -> jnp.ndarray:
    """Single-device vectorised trapezoid rule (the ``size==1`` fast path,
    ``integral.c:20-29``)."""
    h = (b - a) / n
    n_chunks, _, _ = _chunk_grid(n)

    def body(c, carry):
        acc, comp = carry  # Kahan compensated accumulation
        y = _block_sum(f, a, h, jnp.int32(0) + c, n) - comp
        t = acc + y
        comp = (t - acc) - y
        return (t, comp)

    total, _ = lax.fori_loop(0, n_chunks, body, (jnp.float32(0.0), jnp.float32(0.0)))
    return total * jnp.float32(h)
