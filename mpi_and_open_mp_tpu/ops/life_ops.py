"""Game-of-Life stencil kernels (rule + neighbour counting).

Semantics match the reference oracle ``/root/reference/3-life/life2d.c``:

* Periodic torus: every neighbour index wraps, ``ind(i, j) =
  ((i+nx)%nx) + ((j+ny)%ny)*nx`` (``life2d.c:9``).
* Rule: birth when the 8-neighbour count ``n == 3``; survival when the cell
  is alive and ``n ∈ {2, 3}``; death otherwise (``life2d.c:117-123``).

Boards are ``(ny, nx)`` arrays indexed ``board[j, i]``; cell values are
exactly 0/1 in an integer dtype, so every implementation below is bit-exact
against every other — the parity contract the reference enforces by keeping
an identical rule body across its serial and MPI variants.

Three neighbour-count strategies live here:

* ``life_step_numpy`` — host NumPy oracle (ground truth for tests).
* ``life_step_roll``  — global ``jnp.roll``; on a sharded global array XLA
  lowers the rolls to collective-permutes, so this one step function works
  for ANY board size and ANY mesh without explicit communication code.
* ``life_step_padded`` — per-shard stencil over a halo-padded block, used
  inside ``shard_map`` after an explicit ``lax.ppermute`` halo exchange.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp


def life_rule(alive, neighbours):
    """Conway rule on 0/1 integer arrays; returns same dtype as ``alive``."""
    born = neighbours == 3
    survive = (neighbours == 2) & (alive == 1)
    return (born | survive).astype(alive.dtype)


def life_step_numpy(board: np.ndarray) -> np.ndarray:
    """Host-side oracle step; torus wrap via ``np.roll`` on both axes."""
    board = np.asarray(board)
    n = sum(
        np.roll(np.roll(board, dj, axis=0), di, axis=1)
        for dj in (-1, 0, 1)
        for di in (-1, 0, 1)
        if (dj, di) != (0, 0)
    )
    return life_rule(board, n)


def life_step_roll(board: jnp.ndarray) -> jnp.ndarray:
    """Global torus step via circular shifts.

    Generated from the ``life`` :class:`~..stencils.StencilSpec` since the
    stencil subsystem landed: the all-ones radius-1 box takes the engine's
    separable fast path — 4 rolls instead of 8, the exact roll sequence
    this function carried by hand before — so the step stays bit-identical
    (uint8 sums are order-exact either way). On a sharded array XLA turns
    the axis rolls into ``collective-permute`` over the mesh automatically.
    """
    from mpi_and_open_mp_tpu.stencils import LIFE, step_roll

    return step_roll(LIFE, board, jnp)


def life_step_padded(padded: jnp.ndarray) -> jnp.ndarray:
    """Step the interior of a halo-padded block.

    ``padded`` has shape ``(h + 2, w + 2)``; ghost cells on all four edges
    (and corners) must already hold the correct neighbouring state — either
    from a torus wrap (serial) or a ``ppermute`` halo exchange (sharded;
    the explicit equivalent of the reference's ghost-row ``MPI_Send/Recv``
    at ``3-life/life_mpi.c:198-209``). Returns the ``(h, w)`` interior.
    Generated from the ``life`` spec (pure slicing, so it drops into the
    Pallas kernel and ``shard_map`` bodies unchanged, any radius/dtype).
    """
    from mpi_and_open_mp_tpu.stencils import LIFE, step_padded

    return step_padded(LIFE, padded, jnp)


def pad_x_wrap(block: jnp.ndarray, depth: int = 1) -> jnp.ndarray:
    """Pad the x (last) axis with its own torus wrap (shard owns full
    width). Ellipsis indexing: leading batch/channel axes ride along."""
    return jnp.concatenate(
        [block[..., -depth:], block, block[..., :depth]], axis=-1)


def pad_y_wrap(block: jnp.ndarray, depth: int = 1) -> jnp.ndarray:
    """Pad the y (second-to-last) axis with its own torus wrap (shard owns
    full height). Leading batch/channel axes ride along."""
    return jnp.concatenate(
        [block[..., -depth:, :], block, block[..., :depth, :]], axis=-2)
