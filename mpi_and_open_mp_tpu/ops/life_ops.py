"""Game-of-Life stencil kernels (rule + neighbour counting).

Semantics match the reference oracle ``/root/reference/3-life/life2d.c``:

* Periodic torus: every neighbour index wraps, ``ind(i, j) =
  ((i+nx)%nx) + ((j+ny)%ny)*nx`` (``life2d.c:9``).
* Rule: birth when the 8-neighbour count ``n == 3``; survival when the cell
  is alive and ``n ∈ {2, 3}``; death otherwise (``life2d.c:117-123``).

Boards are ``(ny, nx)`` arrays indexed ``board[j, i]``; cell values are
exactly 0/1 in an integer dtype, so every implementation below is bit-exact
against every other — the parity contract the reference enforces by keeping
an identical rule body across its serial and MPI variants.

Three neighbour-count strategies live here:

* ``life_step_numpy`` — host NumPy oracle (ground truth for tests).
* ``life_step_roll``  — global ``jnp.roll``; on a sharded global array XLA
  lowers the rolls to collective-permutes, so this one step function works
  for ANY board size and ANY mesh without explicit communication code.
* ``life_step_padded`` — per-shard stencil over a halo-padded block, used
  inside ``shard_map`` after an explicit ``lax.ppermute`` halo exchange.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp


def life_rule(alive, neighbours):
    """Conway rule on 0/1 integer arrays; returns same dtype as ``alive``."""
    born = neighbours == 3
    survive = (neighbours == 2) & (alive == 1)
    return (born | survive).astype(alive.dtype)


def life_step_numpy(board: np.ndarray) -> np.ndarray:
    """Host-side oracle step; torus wrap via ``np.roll`` on both axes."""
    board = np.asarray(board)
    n = sum(
        np.roll(np.roll(board, dj, axis=0), di, axis=1)
        for dj in (-1, 0, 1)
        for di in (-1, 0, 1)
        if (dj, di) != (0, 0)
    )
    return life_rule(board, n)


def life_step_roll(board: jnp.ndarray) -> jnp.ndarray:
    """Global torus step via circular shifts.

    Separable form: 4 rolls instead of 8 — row-sum first, then column rolls,
    subtracting the centre. On a sharded array XLA turns the axis-0/axis-1
    rolls into ``collective-permute`` over the mesh automatically.
    """
    rows = board + jnp.roll(board, 1, axis=0) + jnp.roll(board, -1, axis=0)
    n = rows + jnp.roll(rows, 1, axis=1) + jnp.roll(rows, -1, axis=1) - board
    return life_rule(board, n)


def life_step_padded(padded: jnp.ndarray) -> jnp.ndarray:
    """Step the interior of a halo-padded block.

    ``padded`` has shape ``(h + 2, w + 2)``; ghost cells on all four edges
    (and corners) must already hold the correct neighbouring state — either
    from a torus wrap (serial) or a ``ppermute`` halo exchange (sharded;
    the explicit equivalent of the reference's ghost-row ``MPI_Send/Recv``
    at ``3-life/life_mpi.c:198-209``). Returns the ``(h, w)`` interior.
    """
    c = padded[1:-1, 1:-1]
    n = (
        padded[:-2, :-2]
        + padded[:-2, 1:-1]
        + padded[:-2, 2:]
        + padded[1:-1, :-2]
        + padded[1:-1, 2:]
        + padded[2:, :-2]
        + padded[2:, 1:-1]
        + padded[2:, 2:]
    )
    return life_rule(c, n)


def pad_x_wrap(block: jnp.ndarray, depth: int = 1) -> jnp.ndarray:
    """Pad the x (last) axis with its own torus wrap (shard owns full width)."""
    return jnp.concatenate([block[:, -depth:], block, block[:, :depth]], axis=1)


def pad_y_wrap(block: jnp.ndarray, depth: int = 1) -> jnp.ndarray:
    """Pad the y (first) axis with its own torus wrap (shard owns full height)."""
    return jnp.concatenate([block[-depth:, :], block, block[:depth, :]], axis=0)
