from mpi_and_open_mp_tpu.ops.life_ops import (  # noqa: F401
    life_rule,
    life_step_numpy,
    life_step_roll,
    life_step_padded,
    pad_x_wrap,
    pad_y_wrap,
)
