"""Pallas TPU kernels for the Life stencil.

The reference's "native layer" is its compiled C kernels
(``/root/reference/3-life/life_mpi.c:150-176`` and friends); here the native
compute layer is Mosaic-compiled Pallas:

* ``life_run_vmem`` — the flagship single-shard dispatcher. Boards up to
  ~3200² bit-pack into VMEM (``ops.bitlife``) with the ENTIRE step loop
  inside one kernel launch, so 10,000 steps cost one dispatch and zero
  HBM round trips; bigger 128-lane-aligned boards stream through the
  packed HBM row-tiled kernel; anything else takes the compiled XLA roll
  loop. Torus wrap everywhere is circular shifting — exactly the
  reference's ``ind()`` modular indexing (``3-life/life2d.c:9``),
  vectorised on the VPU.
* ``life_step_padded_pallas`` — one stencil step over a halo-padded block,
  used as the per-shard kernel inside the ``shard_map`` halo path.
* ``life_step_tiled`` — int32 HBM row-tiled stencil: a 1-D grid of
  programs DMAs overlapping row-tiles (tile + one ghost row each side,
  torus rows resolved modulo ny) into VMEM scratch. Superseded for
  big boards by the packed ``bitlife`` tiled kernel (1/32nd the
  bandwidth); its unaligned ghost-row DMA slices also only lower in
  interpret mode, so the production dispatch no longer reaches it on
  hardware.

All are bit-exact against the NumPy oracle (integer 0/1 state). On
non-TPU backends the kernels run in Pallas interpret mode so CPU tests
exercise the same code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpi_and_open_mp_tpu.ops import life_ops

# Keep the in-kernel board + temporaries comfortably inside VMEM.
_VMEM_BYTES_LIMIT = 4 * 1024 * 1024


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def fits_vmem(shape: tuple[int, int]) -> bool:
    ny, nx = shape
    return ny * nx * 4 <= _VMEM_BYTES_LIMIT


def tiled_supported(shape: tuple[int, int]) -> bool:
    """Row tiling needs at least one row (plus ghosts) under the tile cap;
    ultra-wide boards (a single int32 row near the VMEM budget) can't."""
    return (1 << 21) // (4 * shape[1]) - 2 >= 1


def life_run_vmem(board: jnp.ndarray, n: int) -> jnp.ndarray:
    """Advance ``n`` steps on one device, picking the fastest native path.

    The board is bit-packed (32 cells/uint32 word — see ``ops.bitlife``):
    packed boards up to ~3200² stay VMEM-resident with the whole step loop
    in one kernel launch (interpret-mode on CPU, so tests exercise the
    production dispatch); bigger boards on TPU run the packed HBM
    row-tiled kernel at 1/32nd the bandwidth of an int32 stencil. ``n`` is
    a runtime scalar (SMEM) — changing it does not recompile.
    """
    from mpi_and_open_mp_tpu.ops import bitlife

    if bitlife.fits_vmem_packed(board.shape):
        return bitlife.life_run_vmem_bits(board, n, interpret=_interpret())
    if not _interpret() and bitlife.tiled_bits_supported(board.shape):
        # Big boards in interpret mode skip to the compiled XLA fallback
        # below — interpret-mode Pallas at that size is impractical.
        return bitlife.life_run_tiled_bits(board, n)
    # Remaining cases — lane-unaligned or ultra-wide big boards, and any
    # big board in interpret mode — get the natively-compiled XLA roll
    # loop: explicit-DMA row tiling needs a 128-aligned lane dim on real
    # Mosaic (see bitlife.tiled_bits_supported), and interpret-mode
    # Pallas is orders of magnitude too slow.
    return _run_roll_fallback(board, jnp.int32(n)).astype(board.dtype)


@jax.jit
def _run_roll_fallback(board, n):
    return lax.fori_loop(0, n, lambda _, b: life_ops.life_step_roll(b), board)


def _tile_rows(ny: int, nx: int, max_tile_bytes: int = 1 << 21) -> int:
    """Largest divisor of ``ny`` keeping a (rows+2, nx) int32 tile under
    ``max_tile_bytes`` (falls back to 1-row tiles; ny is always divisible)."""
    cap = max(1, max_tile_bytes // (4 * nx) - 2)
    best = 1
    for d in range(1, ny + 1):
        if ny % d == 0 and d <= cap:
            best = d
    return best


def _tiled_torus_kernel(hbm_ref, out_ref, scratch, sems):
    """One program = one (Tr, nx) output tile; ghosts fetched mod ny."""
    i = pl.program_id(0)
    tr = out_ref.shape[0]
    ny, nx = hbm_ref.shape
    row0 = i * tr
    top = lax.rem(row0 - 1 + ny, ny)
    bot = lax.rem(row0 + tr, ny)
    copies = [
        pltpu.make_async_copy(
            hbm_ref.at[pl.ds(row0, tr)], scratch.at[pl.ds(1, tr)], sems.at[0]
        ),
        pltpu.make_async_copy(
            hbm_ref.at[pl.ds(top, 1)], scratch.at[pl.ds(0, 1)], sems.at[1]
        ),
        pltpu.make_async_copy(
            hbm_ref.at[pl.ds(bot, 1)], scratch.at[pl.ds(tr + 1, 1)], sems.at[2]
        ),
    ]
    for c in copies:
        c.start()
    for c in copies:
        c.wait()
    b = scratch[:]
    rows = b[:-2, :] + b[1:-1, :] + b[2:, :]  # y-sums on the padded tile
    n = rows + pltpu.roll(rows, 1, 1) + pltpu.roll(rows, nx - 1, 1) - b[1:-1, :]
    out_ref[:] = life_ops.life_rule(b[1:-1, :], n)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _step_tiled_jit(board_i32: jnp.ndarray, *, interpret: bool):
    ny, nx = board_i32.shape
    tr = _tile_rows(ny, nx)
    return pl.pallas_call(
        _tiled_torus_kernel,
        grid=(ny // tr,),
        out_shape=jax.ShapeDtypeStruct((ny, nx), board_i32.dtype),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(
            (tr, nx), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            pltpu.VMEM((tr + 2, nx), board_i32.dtype),
            pltpu.SemaphoreType.DMA((3,)),
        ],
        interpret=interpret,
    )(board_i32)


def life_step_tiled(board: jnp.ndarray) -> jnp.ndarray:
    """One torus step of an HBM-resident board via the row-tiled kernel."""
    dtype = board.dtype
    out = _step_tiled_jit(board.astype(jnp.int32), interpret=_interpret())
    return out.astype(dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _run_tiled_jit(board_i32: jnp.ndarray, steps: jnp.ndarray, *, interpret: bool):
    return lax.fori_loop(
        0,
        steps[0],
        lambda _, b: _step_tiled_jit(b, interpret=interpret),
        board_i32,
    )


def _padded_step_kernel(p_ref, out_ref):
    out_ref[:] = life_ops.life_step_padded(p_ref[:])


def _tiled_padded_kernel(hbm_ref, out_ref, scratch, sem):
    """Row-tiled variant for halo-padded blocks too large for VMEM: ghosts
    are already present in the input (no wrap), so each program just DMAs
    its (tr+2, W) row window and stencils by slicing."""
    i = pl.program_id(0)
    tr = out_ref.shape[0]
    cp = pltpu.make_async_copy(
        hbm_ref.at[pl.ds(i * tr, tr + 2)], scratch, sem
    )
    cp.start()
    cp.wait()
    out_ref[:] = life_ops.life_step_padded(scratch[:])


def life_step_padded_pallas(padded: jnp.ndarray) -> jnp.ndarray:
    """Pallas version of ``ops.life_step_padded``: step the interior of a
    halo-padded ``(h+2, w+2)`` block, returning ``(h, w)``.

    Blocks beyond the VMEM budget switch to a row-tiled grid so per-shard
    sizes of 8192²-class boards work on the shard_map path too.
    """
    h, w = padded.shape[0] - 2, padded.shape[1] - 2
    dtype = padded.dtype
    if not fits_vmem(padded.shape):
        # Over-VMEM blocks take the compiled jnp stencil: a halo-padded
        # block has odd dims by construction, and the explicit-DMA row
        # tiling that would stream it needs sublane/lane-aligned slices on
        # real Mosaic (``_step_tiled_padded`` stays for interpret-mode
        # coverage of the kernel body).
        return life_ops.life_step_padded(padded)
    p32 = padded.astype(jnp.int32)
    out = pl.pallas_call(
        _padded_step_kernel,
        out_shape=jax.ShapeDtypeStruct((h, w), jnp.int32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(p32)
    return out.astype(dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _step_tiled_padded(p32: jnp.ndarray, *, interpret: bool):
    h, w = p32.shape[0] - 2, p32.shape[1] - 2
    tr = _tile_rows(h, w + 2)
    return pl.pallas_call(
        _tiled_padded_kernel,
        grid=(h // tr,),
        out_shape=jax.ShapeDtypeStruct((h, w), jnp.int32),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(
            (tr, w), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            pltpu.VMEM((tr + 2, w + 2), jnp.int32),
            pltpu.SemaphoreType.DMA(()),
        ],
        interpret=interpret,
    )(p32)
