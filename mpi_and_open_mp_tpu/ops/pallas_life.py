"""Pallas TPU kernels for the Life stencil.

The reference's "native layer" is its compiled C kernels
(``/root/reference/3-life/life_mpi.c:150-176`` and friends); here the native
compute layer is Mosaic-compiled Pallas:

* ``life_run_vmem`` — the flagship single-shard dispatcher. Boards up to
  ~3200² bit-pack into VMEM (``ops.bitlife``) with the ENTIRE step loop
  inside one kernel launch, so 10,000 steps cost one dispatch and zero
  HBM round trips; bigger aligned boards run the multi-step-fused tiled
  kernel (``bitlife.life_run_fused_bits``); anything else takes the
  compiled-XLA packed loop (``bitlife.life_run_bits_xla``). Torus wrap
  everywhere is circular shifting — exactly the reference's ``ind()``
  modular indexing (``3-life/life2d.c:9``), vectorised on the VPU.
* ``life_step_padded_pallas`` — one stencil step over a halo-padded block,
  used as the per-shard kernel inside the ``shard_map`` halo path.

(Two earlier big-board paths lived here — an int32 explicit-DMA row-tiled
stencil and an unpacked XLA roll fallback; both were superseded by the
packed fused/XLA pair above and removed rather than kept as dead code.)

All are bit-exact against the NumPy oracle (integer 0/1 state). On
non-TPU backends the kernels run in Pallas interpret mode so CPU tests
exercise the same code path.
"""

from __future__ import annotations

import contextlib
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpi_and_open_mp_tpu.ops import life_ops

# Keep the in-kernel board + temporaries comfortably inside VMEM.
_VMEM_BYTES_LIMIT = 4 * 1024 * 1024

# Board-sliced batched layout (ops.bitlife pack_batch_bits): bit axis =
# batch, 32 boards per uint32 word, one vector op advances every world.
# MOMP_BITSLICE=0 pins every batched dispatch back to the cell-packed
# ladder (the regression sentinel flags that as a provenance downgrade —
# the switch exists for triage, not for quiet production use).
_BITSLICE = os.environ.get("MOMP_BITSLICE", "1") != "0"

# Below this batch the plane is >75% padding and the cell-packed ladder
# (which scales its work with B, not ceil(B/32)) stays competitive.
BITSLICE_MIN_BATCH = 8


@contextlib.contextmanager
def _bitslice_pinned(value: bool):
    """Pin the bitsliced layout gate for one dispatch: the serve
    daemon's guarded fallback rung re-dispatches a poisoned bitsliced
    bucket on the cell-packed ladder by re-planning with the layout
    pinned off (same shape, distinct engine + jit cache key — the flag
    is read at plan time, like ``context._ring_hop_pinned``)."""
    global _BITSLICE
    prev = _BITSLICE
    _BITSLICE = value
    try:
        yield
    finally:
        _BITSLICE = prev


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# Installed tuned plans: (workload, *stack shape) -> engine path — the
# shape rides whole so multi-channel stacks (gray_scott's 4-D (B, C, ny,
# nx)) key cleanly. Populated by tune.plans.PlanStore.install() after
# each record survives its CRC / fingerprint / parity gates; consulted
# by native_path_batch BEFORE the static heuristics. MOMP_TUNE=0 is the
# kill switch — read per call, not at import, so a triage export takes
# effect on the very next dispatch.
_PLANNED_PATHS: dict[tuple, str] = {}


def _tune_enabled() -> bool:
    return os.environ.get("MOMP_TUNE", "1") != "0"


def _plan_key(workload: str, shape) -> tuple:
    return (str(workload), *(int(x) for x in shape))


def install_planned_path(workload: str, shape, path: str) -> None:
    """Install a tuned engine path for one (workload, stack shape).
    Only ``tune.plans`` calls this, AFTER the record passed its
    durability and parity gates — nothing here re-validates."""
    _PLANNED_PATHS[_plan_key(workload, shape)] = str(path)


def planned_path(workload: str, shape) -> str | None:
    """The installed tuned path for (workload, stack shape), or ``None``
    when no plan is installed or ``MOMP_TUNE=0`` pins tuning off. An
    installed ``stencil:sep``/``stencil:fft`` plan whose family the
    ``MOMP_ENGINE_FAMILY`` pin disallows is neutralized the same way —
    the pin takes effect at the NEXT dispatch, no uninstall needed."""
    if not _tune_enabled():
        return None
    path = _PLANNED_PATHS.get(_plan_key(workload, shape))
    if path is not None and path.startswith("stencil:"):
        from mpi_and_open_mp_tpu.stencils import engine as stencil_engine

        if not stencil_engine.family_allowed(
                stencil_engine.family_for_path(path)):
            return None
    return path


def clear_planned_paths() -> None:
    _PLANNED_PATHS.clear()


@contextlib.contextmanager
def _planned_pinned(workload: str, shape, path: str | None):
    """Pin one (workload, shape) plan entry for the duration — the
    fingerprint trick behind plan/executable co-location: computing the
    AOT fingerprint under the plan's choice pinned IN yields the same
    digest the serving process computes once the plan is installed, so
    ``<digest>.plan`` and ``<digest>.aot`` land side by side. Pinning
    ``None`` removes any entry (how ``tune.space.heuristic_path`` asks
    what the static ladder would do, untouched by the plan under test)."""
    key = _plan_key(workload, shape)
    missing = object()
    prev = _PLANNED_PATHS.get(key, missing)
    if path is None:
        _PLANNED_PATHS.pop(key, None)
    else:
        _PLANNED_PATHS[key] = str(path)
    try:
        yield
    finally:
        if prev is missing:
            _PLANNED_PATHS.pop(key, None)
        else:
            _PLANNED_PATHS[key] = prev


def _planned_legal(
    path: str, shape: tuple[int, int, int], on_tpu: bool,
    allow_bitsliced: bool,
) -> bool:
    """Hard legality for an installed plan's path on THIS process: VMEM
    fits, backend support, and the runtime pins (``MOMP_BITSLICE=0``,
    the daemon's ``allow_bitsliced=False`` fallback rung) all stay
    binding — a plan may override the BITSLICE_MIN_BATCH heuristic, but
    never dispatch an engine that cannot run here."""
    from mpi_and_open_mp_tpu.ops import bitlife

    b, ny, nx = shape
    if path == "bitsliced":
        return (
            allow_bitsliced
            and _BITSLICE
            and bitlife.fits_vmem_bitsliced(shape)
        )
    if path == "vmem":
        return on_tpu and bitlife.fits_vmem_packed_batch(shape)
    if path == "vmem-grid":
        return on_tpu and bitlife.fits_vmem_packed((ny, nx))
    if path == "fused":
        return on_tpu and bitlife.fused_bits_supported((ny, nx))
    if path == "frame":
        return on_tpu and bitlife.plan_sharded_bits(
            (ny, nx), 1, 1, False, False
        ) is not None
    return path == "xla"


def fits_vmem(shape: tuple[int, int]) -> bool:
    ny, nx = shape
    return ny * nx * 4 <= _VMEM_BYTES_LIMIT


def native_path(shape: tuple[int, int], on_tpu: bool = True) -> str:
    """Which native path :func:`life_run_vmem` dispatches ``shape`` to:
    ``"vmem"`` (whole-board VMEM-resident packed loop), ``"fused"``
    (multi-step-fused tiled kernel), ``"frame"`` (padded-torus-frame
    runner for unaligned big boards), or ``"xla"`` (compiled-XLA packed
    loop). The single source of truth for the dispatch decision — the
    recorded-results sweeps label their rows with this."""
    from mpi_and_open_mp_tpu.ops import bitlife

    if bitlife.fits_vmem_packed(shape):
        return "vmem"
    if on_tpu:
        # Interpret-mode Pallas at big-board sizes is impractical; CPU
        # takes the XLA loop (the fused kernels are covered in interpret
        # mode by tests at small shapes).
        if bitlife.fused_bits_supported(shape):
            return "fused"
        if bitlife.plan_sharded_bits(shape, 1, 1, False, False) is not None:
            return "frame"
    return "xla"


def native_path_batch(
    shape: tuple[int, int, int], on_tpu: bool = True,
    allow_bitsliced: bool = True,
) -> str:
    """Which batched native path :func:`life_run_vmem_batch` dispatches a
    (B, ny, nx) stack to — the single source of truth for batched
    LAYOUT and path, as :func:`native_path` is for single boards:
    ``"bitsliced"`` (board-sliced planes, bit axis = batch — Pallas
    VMEM kernel on hardware, the halo-fused XLA twin elsewhere),
    ``"vmem"`` (whole stack VMEM-resident cell-packed — the gate is B x
    the per-board working set, ``bitlife.fits_vmem_packed_batch``),
    ``"vmem-grid"`` (per-board VMEM-resident, batch axis streamed by a
    Pallas grid), ``"fused"`` / ``"frame"`` (big-board engines, the
    stack scanned inside one program), or ``"xla"`` (vmapped
    compiled-XLA packed loop).

    Small-board/large-B stacks go ``"bitsliced"`` on EVERY backend: B
    boards cost ``ceil(B/32)`` planes of vector work instead of B
    bitplanes, and the XLA twin is the fastest CPU engine too (~8x the
    vmapped cell-packed loop at B=32, 64²). ``MOMP_BITSLICE=0`` (or
    ``allow_bitsliced=False``, the daemon's fallback-rung pin) restores
    the cell-packed ladder. Off-TPU that ladder always lands ``"xla"``:
    a batch exists for THROUGHPUT — interpret mode would grind B boards
    through a Python-level VM while the vmapped packed loop compiles on
    every backend (the batched kernels get their interpret-mode
    coverage from tests/test_batched.py directly).

    An installed tuned plan (``tune/``, keyed by workload + stack
    shape) is consulted FIRST and wins whenever its path is legal for
    this process (:func:`_planned_legal`); the static ladder below is
    the heuristic fallback and the no-plans behavior."""
    from mpi_and_open_mp_tpu.ops import bitlife

    b, ny, nx = shape
    planned = planned_path("life", shape)
    if planned is not None and _planned_legal(
        planned, shape, on_tpu, allow_bitsliced
    ):
        return planned
    if (
        allow_bitsliced
        and _BITSLICE
        and b >= BITSLICE_MIN_BATCH
        and bitlife.fits_vmem_bitsliced(shape)
    ):
        return "bitsliced"
    if on_tpu:
        if bitlife.fits_vmem_packed_batch(shape):
            return "vmem"
        if bitlife.fits_vmem_packed((ny, nx)):
            return "vmem-grid"
        if bitlife.fused_bits_supported((ny, nx)):
            return "fused"
        if bitlife.plan_sharded_bits((ny, nx), 1, 1, False, False) is not None:
            return "frame"
    return "xla"


def batch_pack_layout(
    shape: tuple[int, int, int], on_tpu: bool = True
) -> str:
    """The pack layout :func:`life_run_vmem_batch` uses for a (B, ny,
    nx) stack: ``"bitsliced"`` (bit axis = batch) or ``"cell-packed"``
    (bit axis = space). Derived from :func:`native_path_batch` so the
    two can never disagree; bench lines and the ledger config key
    record this vocabulary."""
    path = native_path_batch(shape, on_tpu=on_tpu)
    return "bitsliced" if path == "bitsliced" else "cell-packed"


def batch_slice_width(
    shape: tuple[int, int], on_tpu: bool = True
) -> int | None:
    """Plane width (32) when (ny, nx) boards can take the bitsliced
    path at some batch size, else ``None``. The serve layer sizes its
    buckets with this: a bitsliced dispatch costs the same for every B
    within a plane, so buckets pad to multiples of 32 (filling planes
    exactly) instead of the pow2 ladder — and admission's
    padding-waste projection must use the SAME width, or tickets get
    shed against the wrong denominator."""
    from mpi_and_open_mp_tpu.ops import bitlife

    ny, nx = shape
    if _BITSLICE and bitlife.fits_vmem_bitsliced(
        (BITSLICE_MIN_BATCH, ny, nx)
    ):
        return 32
    return None


def life_run_vmem_batch(boards: jnp.ndarray, n: int) -> jnp.ndarray:
    """Advance a (B, ny, nx) stack ``n`` steps in ONE dispatch, picking
    the fastest batched native path (see :func:`native_path_batch`).
    Bit-exact per board vs the serial engines; ``n`` is a runtime scalar
    on every path, so one compiled program per stack shape serves any
    step count — the contract the serve-layer bucketing depends on."""
    from mpi_and_open_mp_tpu.ops import bitlife

    path = native_path_batch(boards.shape, on_tpu=not _interpret())
    if path == "bitsliced":
        # Pallas VMEM kernel on hardware; on CPU the halo-fused XLA
        # twin IS the fast path (use_kernel=None picks per backend).
        return bitlife.life_run_bitsliced_batch(
            boards, n, interpret=_interpret()
        )
    if path in ("vmem", "vmem-grid"):
        return bitlife.life_run_vmem_bits_batch(
            boards, n, interpret=_interpret(), resident=(path == "vmem")
        )
    if path == "fused":
        return bitlife.life_run_fused_bits_batch(boards, n)
    if path == "frame":
        return bitlife.life_run_frame_bits_batch(boards, n)
    return bitlife.life_run_bits_xla_batch(boards, n)


def life_run_vmem(board: jnp.ndarray, n: int) -> jnp.ndarray:
    """Advance ``n`` steps on one device, picking the fastest native path.

    The board is bit-packed (32 cells/uint32 word — see ``ops.bitlife``):
    packed boards up to ~3200² stay VMEM-resident with the whole step loop
    in one kernel launch (interpret-mode on CPU, so tests exercise the
    production dispatch); bigger aligned boards run the multi-step-fused
    tiled kernel (one HBM pass per up-to-128 steps); bigger UNALIGNED
    boards take the padded-torus-frame runner (same fused kernels over a
    word/lane-padded frame, ``bitlife.life_run_frame_bits``); anything
    left takes the compiled-XLA packed loop (any shape, any backend).
    ``n`` is a runtime scalar — changing it does not recompile any path.
    """
    from mpi_and_open_mp_tpu.ops import bitlife

    path = native_path(board.shape, on_tpu=not _interpret())
    if path == "vmem":
        return bitlife.life_run_vmem_bits(board, n, interpret=_interpret())
    if path == "fused":
        return bitlife.life_run_fused_bits(board, n)
    if path == "frame":
        return bitlife.life_run_frame_bits(board, n)
    return bitlife.life_run_bits_xla(board, n)


def _padded_step_kernel(p_ref, out_ref):
    out_ref[:] = life_ops.life_step_padded(p_ref[:])


def stencil_step_padded_pallas(spec, padded: jnp.ndarray) -> jnp.ndarray:
    """Spec-generic Pallas twin of :func:`life_step_padded_pallas`: one
    stencil step over a ``radius``-halo-padded block (channels on the
    leading axis ride through), generated from any
    :class:`~..stencils.StencilSpec`.

    The kernel body is ``stencils.engine.step_padded`` — pure slicing +
    the spec's ``update``, the same code the jnp path runs, so Mosaic
    sees a static-shape VPU stencil regardless of rule. Integer specs
    compute in int32 inside the kernel (sub-word dtypes hit Mosaic
    layout gaps — same cast the life kernel carries); float specs stay
    in their native dtype. Over-VMEM blocks take the compiled jnp
    stencil, like the life kernel.
    """
    from mpi_and_open_mp_tpu.stencils import engine as stencil_engine

    r = spec.radius
    h, w = padded.shape[-2] - 2 * r, padded.shape[-1] - 2 * r
    dtype = padded.dtype
    out_shape = (*padded.shape[:-2], h, w)
    if padded.size * 4 > _VMEM_BYTES_LIMIT:
        return stencil_engine.step_padded(spec, padded, jnp)
    compute = dtype if jnp.issubdtype(dtype, jnp.floating) else jnp.int32

    def kernel(p_ref, out_ref):
        out_ref[:] = stencil_engine.step_padded(spec, p_ref[:], jnp)

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(out_shape, compute),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(padded.astype(compute))
    return out.astype(dtype)


def life_step_padded_pallas(padded: jnp.ndarray) -> jnp.ndarray:
    """Pallas version of ``ops.life_step_padded``: step the interior of a
    halo-padded ``(h+2, w+2)`` block, returning ``(h, w)``.

    Blocks beyond the VMEM budget take the compiled jnp stencil instead
    (``life_ops.life_step_padded``) — see the comment below.
    """
    h, w = padded.shape[0] - 2, padded.shape[1] - 2
    dtype = padded.dtype
    if not fits_vmem(padded.shape):
        # Over-VMEM blocks take the compiled jnp stencil: a halo-padded
        # block has odd dims by construction, and the explicit-DMA row
        # tiling that would stream it needs sublane/lane-aligned slices on
        # real Mosaic.
        return life_ops.life_step_padded(padded)
    p32 = padded.astype(jnp.int32)
    out = pl.pallas_call(
        _padded_step_kernel,
        out_shape=jax.ShapeDtypeStruct((h, w), jnp.int32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(p32)
    return out.astype(dtype)
