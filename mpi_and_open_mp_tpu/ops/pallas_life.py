"""Pallas TPU kernels for the Life stencil.

The reference's "native layer" is its compiled C kernels
(``/root/reference/3-life/life_mpi.c:150-176`` and friends); here the native
compute layer is Mosaic-compiled Pallas:

* ``life_run_vmem`` — the flagship single-shard kernel. The whole board
  lives in VMEM (a 500x500 int32 board is 1 MB — far under the ~16 MB/core
  budget) and the ENTIRE step loop runs inside one kernel launch via
  ``lax.fori_loop``, so 10,000 steps cost one dispatch and zero HBM round
  trips. Torus wrap is ``pltpu.roll`` (circular shift) on both axes —
  exactly the reference's ``ind()`` modular indexing
  (``3-life/life2d.c:9``), vectorised on the VPU.
* ``life_step_padded_pallas`` — one stencil step over a halo-padded block,
  used as the per-shard kernel inside the ``shard_map`` halo path.

Both are bit-exact against the NumPy oracle (integer 0/1 state). On
non-TPU backends the kernels run in Pallas interpret mode so CPU tests
exercise the same code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpi_and_open_mp_tpu.ops import life_ops

# Keep the in-kernel board + temporaries comfortably inside VMEM.
_VMEM_BYTES_LIMIT = 4 * 1024 * 1024


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _step_roll_tpu(b: jnp.ndarray) -> jnp.ndarray:
    """One torus step via circular shifts (separable: 4 rolls).

    ``pltpu.roll`` only takes non-negative shifts, so a -1 roll is a
    ``dim - 1`` roll (shapes are static).
    """
    ny, nx = b.shape
    rows = b + pltpu.roll(b, 1, 0) + pltpu.roll(b, ny - 1, 0)
    n = rows + pltpu.roll(rows, 1, 1) + pltpu.roll(rows, nx - 1, 1) - b
    return life_ops.life_rule(b, n)


def _vmem_loop_kernel(steps_ref, board_ref, out_ref):
    out_ref[:] = lax.fori_loop(
        0, steps_ref[0], lambda _, b: _step_roll_tpu(b), board_ref[:]
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def _run_vmem_jit(board_i32: jnp.ndarray, steps: jnp.ndarray, *, interpret: bool):
    return pl.pallas_call(
        _vmem_loop_kernel,
        out_shape=jax.ShapeDtypeStruct(board_i32.shape, board_i32.dtype),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(steps, board_i32)


def fits_vmem(shape: tuple[int, int]) -> bool:
    ny, nx = shape
    return ny * nx * 4 <= _VMEM_BYTES_LIMIT


def life_run_vmem(board: jnp.ndarray, n: int) -> jnp.ndarray:
    """Advance ``n`` steps with the whole board resident in VMEM.

    ``n`` is a runtime scalar (SMEM) — changing it does not recompile.
    Boards too large for VMEM fall back to a jitted roll-step loop; tiling
    large boards across a kernel grid is the multi-shard path's job.
    """
    if not fits_vmem(board.shape):
        return _run_roll_fallback(board, jnp.int32(n))
    dtype = board.dtype
    out = _run_vmem_jit(
        board.astype(jnp.int32),
        jnp.asarray([n], dtype=jnp.int32),
        interpret=_interpret(),
    )
    return out.astype(dtype)


@jax.jit
def _run_roll_fallback(board, n):
    return lax.fori_loop(0, n, lambda _, b: life_ops.life_step_roll(b), board)


def _padded_step_kernel(p_ref, out_ref):
    out_ref[:] = life_ops.life_step_padded(p_ref[:])


def life_step_padded_pallas(padded: jnp.ndarray) -> jnp.ndarray:
    """Pallas version of ``ops.life_step_padded``: step the interior of a
    halo-padded ``(h+2, w+2)`` block, returning ``(h, w)``."""
    h, w = padded.shape[0] - 2, padded.shape[1] - 2
    dtype = padded.dtype
    out = pl.pallas_call(
        _padded_step_kernel,
        out_shape=jax.ShapeDtypeStruct((h, w), jnp.int32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(padded.astype(jnp.int32))
    return out.astype(dtype)
