"""Pallas TPU kernels for the Life stencil.

The reference's "native layer" is its compiled C kernels
(``/root/reference/3-life/life_mpi.c:150-176`` and friends); here the native
compute layer is Mosaic-compiled Pallas:

* ``life_run_vmem`` — the flagship single-shard dispatcher. Boards up to
  ~3200² bit-pack into VMEM (``ops.bitlife``) with the ENTIRE step loop
  inside one kernel launch, so 10,000 steps cost one dispatch and zero
  HBM round trips; bigger aligned boards run the multi-step-fused tiled
  kernel (``bitlife.life_run_fused_bits``); anything else takes the
  compiled-XLA packed loop (``bitlife.life_run_bits_xla``). Torus wrap
  everywhere is circular shifting — exactly the reference's ``ind()``
  modular indexing (``3-life/life2d.c:9``), vectorised on the VPU.
* ``life_step_padded_pallas`` — one stencil step over a halo-padded block,
  used as the per-shard kernel inside the ``shard_map`` halo path.

(Two earlier big-board paths lived here — an int32 explicit-DMA row-tiled
stencil and an unpacked XLA roll fallback; both were superseded by the
packed fused/XLA pair above and removed rather than kept as dead code.)

All are bit-exact against the NumPy oracle (integer 0/1 state). On
non-TPU backends the kernels run in Pallas interpret mode so CPU tests
exercise the same code path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpi_and_open_mp_tpu.ops import life_ops

# Keep the in-kernel board + temporaries comfortably inside VMEM.
_VMEM_BYTES_LIMIT = 4 * 1024 * 1024


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def fits_vmem(shape: tuple[int, int]) -> bool:
    ny, nx = shape
    return ny * nx * 4 <= _VMEM_BYTES_LIMIT


def native_path(shape: tuple[int, int], on_tpu: bool = True) -> str:
    """Which native path :func:`life_run_vmem` dispatches ``shape`` to:
    ``"vmem"`` (whole-board VMEM-resident packed loop), ``"fused"``
    (multi-step-fused tiled kernel), ``"frame"`` (padded-torus-frame
    runner for unaligned big boards), or ``"xla"`` (compiled-XLA packed
    loop). The single source of truth for the dispatch decision — the
    recorded-results sweeps label their rows with this."""
    from mpi_and_open_mp_tpu.ops import bitlife

    if bitlife.fits_vmem_packed(shape):
        return "vmem"
    if on_tpu:
        # Interpret-mode Pallas at big-board sizes is impractical; CPU
        # takes the XLA loop (the fused kernels are covered in interpret
        # mode by tests at small shapes).
        if bitlife.fused_bits_supported(shape):
            return "fused"
        if bitlife.plan_sharded_bits(shape, 1, 1, False, False) is not None:
            return "frame"
    return "xla"


def native_path_batch(
    shape: tuple[int, int, int], on_tpu: bool = True
) -> str:
    """Which batched native path :func:`life_run_vmem_batch` dispatches a
    (B, ny, nx) stack to: ``"vmem"`` (whole stack VMEM-resident — the
    gate is B x the per-board working set,
    ``bitlife.fits_vmem_packed_batch``), ``"vmem-grid"`` (per-board
    VMEM-resident, batch axis streamed by a Pallas grid), ``"fused"`` /
    ``"frame"`` (big-board engines, the stack scanned inside one
    program), or ``"xla"`` (vmapped compiled-XLA packed loop). The
    single source of truth for the batched dispatch decision, as
    :func:`native_path` is for single boards.

    Off-TPU everything goes ``"xla"``: the single-board dispatcher runs
    small boards through interpret-mode Pallas so tests cover the
    production path, but a batch exists for THROUGHPUT — interpret mode
    would grind B boards through a Python-level VM while the vmapped
    packed loop compiles on every backend (the batched kernels get their
    interpret-mode coverage from tests/test_batched.py directly)."""
    from mpi_and_open_mp_tpu.ops import bitlife

    b, ny, nx = shape
    if on_tpu:
        if bitlife.fits_vmem_packed_batch(shape):
            return "vmem"
        if bitlife.fits_vmem_packed((ny, nx)):
            return "vmem-grid"
        if bitlife.fused_bits_supported((ny, nx)):
            return "fused"
        if bitlife.plan_sharded_bits((ny, nx), 1, 1, False, False) is not None:
            return "frame"
    return "xla"


def life_run_vmem_batch(boards: jnp.ndarray, n: int) -> jnp.ndarray:
    """Advance a (B, ny, nx) stack ``n`` steps in ONE dispatch, picking
    the fastest batched native path (see :func:`native_path_batch`).
    Bit-exact per board vs the serial engines; ``n`` is a runtime scalar
    on every path, so one compiled program per stack shape serves any
    step count — the contract the serve-layer bucketing depends on."""
    from mpi_and_open_mp_tpu.ops import bitlife

    path = native_path_batch(boards.shape, on_tpu=not _interpret())
    if path in ("vmem", "vmem-grid"):
        return bitlife.life_run_vmem_bits_batch(
            boards, n, interpret=_interpret(), resident=(path == "vmem")
        )
    if path == "fused":
        return bitlife.life_run_fused_bits_batch(boards, n)
    if path == "frame":
        return bitlife.life_run_frame_bits_batch(boards, n)
    return bitlife.life_run_bits_xla_batch(boards, n)


def life_run_vmem(board: jnp.ndarray, n: int) -> jnp.ndarray:
    """Advance ``n`` steps on one device, picking the fastest native path.

    The board is bit-packed (32 cells/uint32 word — see ``ops.bitlife``):
    packed boards up to ~3200² stay VMEM-resident with the whole step loop
    in one kernel launch (interpret-mode on CPU, so tests exercise the
    production dispatch); bigger aligned boards run the multi-step-fused
    tiled kernel (one HBM pass per up-to-128 steps); bigger UNALIGNED
    boards take the padded-torus-frame runner (same fused kernels over a
    word/lane-padded frame, ``bitlife.life_run_frame_bits``); anything
    left takes the compiled-XLA packed loop (any shape, any backend).
    ``n`` is a runtime scalar — changing it does not recompile any path.
    """
    from mpi_and_open_mp_tpu.ops import bitlife

    path = native_path(board.shape, on_tpu=not _interpret())
    if path == "vmem":
        return bitlife.life_run_vmem_bits(board, n, interpret=_interpret())
    if path == "fused":
        return bitlife.life_run_fused_bits(board, n)
    if path == "frame":
        return bitlife.life_run_frame_bits(board, n)
    return bitlife.life_run_bits_xla(board, n)


def _padded_step_kernel(p_ref, out_ref):
    out_ref[:] = life_ops.life_step_padded(p_ref[:])


def life_step_padded_pallas(padded: jnp.ndarray) -> jnp.ndarray:
    """Pallas version of ``ops.life_step_padded``: step the interior of a
    halo-padded ``(h+2, w+2)`` block, returning ``(h, w)``.

    Blocks beyond the VMEM budget take the compiled jnp stencil instead
    (``life_ops.life_step_padded``) — see the comment below.
    """
    h, w = padded.shape[0] - 2, padded.shape[1] - 2
    dtype = padded.dtype
    if not fits_vmem(padded.shape):
        # Over-VMEM blocks take the compiled jnp stencil: a halo-padded
        # block has odd dims by construction, and the explicit-DMA row
        # tiling that would stream it needs sublane/lane-aligned slices on
        # real Mosaic.
        return life_ops.life_step_padded(padded)
    p32 = padded.astype(jnp.int32)
    out = pl.pallas_call(
        _padded_step_kernel,
        out_shape=jax.ShapeDtypeStruct((h, w), jnp.int32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(p32)
    return out.astype(dtype)
