"""Device-resident session pool: handle-based serving state.

Every serve ticket before this module shipped its full board host →
device and the result back through the ~70 ms-RTT tunnel, while a
bit-sliced step on a 64² board costs microseconds — the wire tax dwarfs
the compute by orders of magnitude at production traffic. The pool
inverts the data flow (the Casper near-memory argument in PAPERS.md:
move compute to where the state lives, not state to the compute): a
live Life session STAYS on device between requests as a
:class:`Handle` — a (slab, bit-lane) pair over a bit-sliced
``(n_planes, ny, nx)`` uint32 slab (the PR 10 board-sliced layout:
bit ``lane % 32`` of plane ``lane // 32`` is one whole board). Boards
cross the wire on exactly three occasions: session **create**, explicit
**snapshot**, and **evict**. Everything in between is a handle-sized
dispatch.

**In-place stepping.** :func:`_pool_step_jit` advances a whole slab
with ``donate_argnums=(0,)`` — the slab buffer is donated, so the
device updates state in place instead of allocating a second slab per
step. The step count is a runtime int32 scalar and the lane selection a
runtime uint32 mask per plane (``(stepped & mask) | (planes & ~mask)``),
so ONE compiled program per plane shape serves every lane subset and
every step count — stepping one lone session and stepping 32 slab-mates
coalesced is the same executable (``jit.retrace{fn=pool_step}``
observable, and the program fingerprint is
``serve.aotcache.fingerprint(..., program="pool-step", donated=True)``
— donation is part of the key because a donated and a non-donated
program are different executables). Lanes NOT in the mask pass through
bit-identically: slab-mates are untouched, which is what makes the
slab a pool and not a batch.

**Settled skip.** Each step dispatch also returns a per-plane settled
word (``ops.bitlife.lane_change_bits`` over the loop's final
consecutive-state pair — a set bit is a PROVEN fixed point, so a
period-k oscillator never reads as settled). The word resolves lazily
before the slab's next step; when every session in a slab group is
settled, the dispatch is skipped outright (``pool.settled_skips``) and
only the logical ``steps_applied`` advances — bit-identical by the
fixed-point argument, and WAL STEP frames stay authoritative because
replay re-proves settledness from the same boards. Any rewrite
(create, revive) clears the flag; it is re-proven, never assumed.

**Lane allocation** is a free-lane bitmap per slab (bit ``l`` set =
lane ``l`` free). Create takes the lowest free lane of the fullest
slab of the board's shape (dense packing keeps masks cheap and
fragmentation low); when no lane is free a new slab allocates against
the hard ``device_budget_bytes`` — and when THAT would breach the
budget, the least-recently-used sessions spill to the host tier until
a lane or the budget frees up.

**Lane compaction.** Evictions leave sparse planes — 31 dead lanes
still pay a full slab of VMEM and a full plane of vector work on every
group step. :meth:`SessionPool.compact` repacks a shape's survivors
32-at-a-time through the EXISTING pack/unpack kernels
(``ops.bitlife.pack_batch_bits`` / ``unpack_batch_bits``) into the
minimum number of slabs and frees the rest; :meth:`maybe_compact` is
the cheap fragmentation trigger the serving daemon polls between
pump rounds ("background" compaction — no thread, same
clock-free discipline as the rest of ``serve/``). Handles move;
sessions don't notice (every lookup resolves ``sid →`` current
handle), and step results are unchanged — the drill test evicts 31 of
32, compacts, and bit-compares the survivor.

**Spill tier.** Spilled sessions live as host boards; the next step
revives them (a ``pool.miss``) through the normal create path.
Snapshots of spilled sessions are served from the host copy without
reviving. The budget is HARD: a revive that cannot spill anything else
(every resident session pinned by the in-flight group) raises rather
than silently over-allocating.

Durability is the caller's job by design: the pool owns device state
and host spill copies, no files. The serving daemon journals
CREATE/STEP/SNAPSHOT/EVICT frames write-ahead (``serve/wal.py``) and
re-materializes the pool on resume from journaled create-boards +
replayed step counts — see ``docs/DESIGN.md`` §14 for the loss bounds.
"""

from __future__ import annotations

import dataclasses
import functools
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

from mpi_and_open_mp_tpu.ops.bitlife import (
    _carry_save_rule9, _note_retrace, lane_change_bits, pack_batch_bits,
    unpack_batch_bits)

#: Boards per bit-plane — the uint32 word width of the sliced layout.
LANES_PER_PLANE = 32

#: Default hard budget for live slab bytes on device. 64 MiB holds
#: ~4000 resident 64² sessions (one 16 KB plane per 32) — far past the
#: CI/bench scales, small next to any real HBM.
DEFAULT_DEVICE_BUDGET = 64 << 20


class PoolError(ValueError):
    """A session-pool contract violation (duplicate create, unknown
    session, a budget too small to hold even one slab)."""


@dataclasses.dataclass(frozen=True)
class Handle:
    """Where a resident session lives: ``lane % 32`` is the bit, ``lane
    // 32`` the plane, inside slab ``slab``. Handles are pool-internal
    coordinates — compaction moves them; sessions are addressed by id."""

    slab: int
    lane: int


@dataclasses.dataclass
class _Slab:
    shape: tuple[int, int]
    planes: object  # jax (P, ny, nx) uint32 array
    free: int  # bitmap over 32*P lanes; bit set = lane free
    lanes: dict[int, str] = dataclasses.field(default_factory=dict)

    @property
    def capacity(self) -> int:
        return int(self.planes.shape[0]) * LANES_PER_PLANE

    @property
    def live(self) -> int:
        return len(self.lanes)


@dataclasses.dataclass
class _Session:
    sid: str
    shape: tuple[int, int]
    handle: Handle | None = None  # None = spilled to host
    host: np.ndarray | None = None  # the board, when spilled
    steps_applied: int = 0
    #: Proven still life: the last dispatch's final step changed nothing
    #: on this lane (consecutive-state equality, NOT same-as-start — a
    #: period-k oscillator that returns to its start is never settled).
    #: False on create/revive; cleared whenever the board is rewritten.
    settled: bool = False


# --------------------------------------------------------------- device ops
#
# Three compiled programs per plane shape — step (donated, masked),
# lane write (donated), lane read — all with runtime-scalar operands so
# lane index, step count, and mask never retrace.


def _torus_step(planes):
    """One Life step on a (P, ny, nx) bit-sliced stack via plain torus
    rolls into the 9-operand carry-save rule — the backend-portable
    form (XLA on CPU, XLA on TPU; the Pallas kernels stay the batch
    engines' fast path). Neighbour at (dy, dx) = roll by (+dy, +dx)."""
    up = jnp.roll(planes, 1, axis=1)
    dn = jnp.roll(planes, -1, axis=1)
    lf = jnp.roll(planes, 1, axis=2)
    rt = jnp.roll(planes, -1, axis=2)
    ul = jnp.roll(up, 1, axis=2)
    ur = jnp.roll(up, -1, axis=2)
    dl = jnp.roll(dn, 1, axis=2)
    dr = jnp.roll(dn, -1, axis=2)
    return _carry_save_rule9(planes, up, dn, lf, rt, ul, ur, dl, dr)


@functools.partial(jax.jit, donate_argnums=(0,))
def _pool_step_jit(planes, steps, mask):
    """Advance the masked lanes ``steps`` Life steps IN PLACE (the slab
    buffer is donated). Unmasked lanes pass through bit-identically.

    Also returns the per-plane SETTLED word: bit ``l`` set iff lane
    ``l`` is masked and its final step was the identity — the loop
    carries ``(prev, cur)`` so the comparison is between consecutive
    states, which proves a true fixed point (an oscillator whose period
    divides ``steps`` returns to its start but fails prev == cur). The
    word costs one XOR/OR reduction on state already in registers; the
    pool uses it to skip future dispatches for all-settled groups."""
    _note_retrace("pool_step")
    prev, cur = jax.lax.fori_loop(
        0, steps, lambda _, c: (c[1], _torus_step(c[1])),
        (planes, planes))
    settled = ~lane_change_bits(prev, cur) & mask
    m = mask[:, None, None]
    return (cur & m) | (planes & ~m), settled


@functools.partial(jax.jit, donate_argnums=(0,))
def _lane_write_jit(planes, board, plane_idx, bitpos):
    """Write one 0/1 board into (plane_idx, bitpos) of a donated slab —
    the create/revive path: only board-sized data crosses the wire."""
    _note_retrace("pool_lane_write")
    bit = jnp.uint32(1) << bitpos
    sel = (jnp.arange(planes.shape[0], dtype=jnp.int32)
           == plane_idx)[:, None, None]
    written = (planes & ~bit) | (board.astype(jnp.uint32) << bitpos)[None]
    return jnp.where(sel, written, planes)


@jax.jit
def _lane_read_jit(planes, plane_idx, bitpos):
    """Read one lane back as a (ny, nx) uint8 board — the snapshot/
    evict path; again only board-sized data moves."""
    _note_retrace("pool_lane_read")
    row = jnp.take(planes, plane_idx, axis=0)
    return ((row >> bitpos) & jnp.uint32(1)).astype(jnp.uint8)


class SessionPool:
    """The device-resident session pool. Host-side manager, clock-free,
    no threads, no IO — slabs, bitmaps, an LRU, and a host spill dict.

    ``planes_per_slab`` sets slab capacity (32 lanes per plane); the
    default of one plane keeps the masked step's wasted work bounded by
    one word of lanes and makes the compaction arithmetic legible.
    """

    def __init__(self, *, device_budget_bytes: int = DEFAULT_DEVICE_BUDGET,
                 planes_per_slab: int = 1):
        if planes_per_slab < 1:
            raise PoolError(
                f"planes_per_slab must be >= 1, got {planes_per_slab}")
        if device_budget_bytes < 1:
            raise PoolError(
                f"device_budget_bytes must be >= 1, got {device_budget_bytes}")
        self._budget = int(device_budget_bytes)
        self._planes_per_slab = int(planes_per_slab)
        self._slabs: dict[int, _Slab] = {}
        self._next_slab = 0
        self._sessions: dict[str, _Session] = {}
        self._lru: OrderedDict[str, None] = OrderedDict()  # resident only
        self._pinned: set[str] = set()  # in-flight group, spill-exempt
        self._program_digests: dict[tuple, str] = {}
        self.counts = {
            "creates": 0, "hits": 0, "misses": 0, "evictions": 0,
            "spills": 0, "revivals": 0, "compactions": 0, "migrated": 0,
            "slabs_freed": 0, "dispatches": 0, "steps_applied": 0,
            "settled_skips": 0,
        }
        # Deferred settled words: slab_id -> (device word array, [(sess,
        # lane)] at dispatch time). Resolved lazily at the NEXT step of
        # the same slab so the fetch never forces a sync on the dispatch
        # hot path (the dispatch itself stays fire-and-forget).
        self._pending_settled: dict[int, tuple] = {}

    # -- geometry ----------------------------------------------------------

    def _slab_bytes(self, shape: tuple[int, int]) -> int:
        ny, nx = shape
        return self._planes_per_slab * ny * nx * 4

    def device_bytes(self) -> int:
        return sum(self._slab_bytes(s.shape) for s in self._slabs.values())

    def _capacity(self) -> int:
        return self._planes_per_slab * LANES_PER_PLANE

    # -- introspection -----------------------------------------------------

    def sessions(self) -> list[str]:
        return list(self._sessions)

    def has(self, sid: str) -> bool:
        return sid in self._sessions

    def handle(self, sid: str) -> Handle | None:
        """The session's CURRENT handle (``None`` when spilled) — a
        grouping hint only; compaction and spills move it."""
        return self._require(sid).handle

    def slab_groups(self) -> dict[int | None, list[str]]:
        """Live sessions grouped by resident slab (``None`` = spilled).
        A membership change (graceful drain, rejoin claim) migrates one
        group as a unit: slab-mates advance under one donated dispatch,
        so scattering them across destinations would split one program
        invocation into several padded ones — the whole-bucket rule of
        the work stealer, applied to resident state."""
        out: dict[int | None, list[str]] = {}
        for sid, s in self._sessions.items():
            key = s.handle.slab if s.handle is not None else None
            out.setdefault(key, []).append(sid)
        return out

    def steps_applied(self, sid: str) -> int:
        return self._require(sid).steps_applied

    def program_digest(self, shape: tuple[int, int]) -> str:
        """The AOT-fingerprint digest of this shape's in-place step
        program — plane shape + ``program="pool-step"`` +
        ``donated=True`` in the key, so a pool executable can never be
        confused with a bucket program for the same stack shape."""
        key = (self._planes_per_slab, *shape)
        if key not in self._program_digests:
            from mpi_and_open_mp_tpu.serve import aotcache

            self._program_digests[key] = aotcache.digest_for(
                aotcache.fingerprint(key, np.uint32, program="pool-step",
                                     donated=True))
        return self._program_digests[key]

    def stats(self) -> dict:
        resident = sum(1 for s in self._sessions.values()
                       if s.handle is not None)
        out = dict(self.counts)
        out.update({
            "sessions": len(self._sessions),
            "resident": resident,
            "spilled": len(self._sessions) - resident,
            "slabs": len(self._slabs),
            "lanes_live": sum(s.live for s in self._slabs.values()),
            "lanes_free": sum(s.capacity - s.live
                              for s in self._slabs.values()),
            "device_bytes": self.device_bytes(),
            "device_budget_bytes": self._budget,
        })
        return out

    def _gauges(self) -> None:
        from mpi_and_open_mp_tpu.obs import metrics

        s = self.stats()
        metrics.gauge("pool.slabs", s["slabs"])
        metrics.gauge("pool.lanes_live", s["lanes_live"])
        metrics.gauge("pool.lanes_free", s["lanes_free"])
        metrics.gauge("pool.device_bytes", s["device_bytes"])
        metrics.gauge("pool.spilled", s["spilled"])

    # -- internals ---------------------------------------------------------

    def _require(self, sid: str) -> _Session:
        try:
            return self._sessions[sid]
        except KeyError:
            raise PoolError(f"unknown session {sid!r}") from None

    def _touch(self, sid: str) -> None:
        self._lru[sid] = None
        self._lru.move_to_end(sid)

    def _alloc_lane(self, shape: tuple[int, int]) -> Handle:
        """A free lane for one board of ``shape``: fullest existing slab
        first (dense packing), else a new slab under the budget, else
        spill LRU sessions until one of those works."""
        while True:
            candidates = [(sl.live, slab_id) for slab_id, sl
                          in self._slabs.items()
                          if sl.shape == shape and sl.free]
            if candidates:
                _, slab_id = max(candidates)
                slab = self._slabs[slab_id]
                lane = (slab.free & -slab.free).bit_length() - 1
                slab.free &= ~(1 << lane)
                return Handle(slab_id, lane)
            if self.device_bytes() + self._slab_bytes(shape) <= self._budget:
                return Handle(self._new_slab(shape), self._take_lane_0(shape))
            if not self._spill_one():
                raise PoolError(
                    f"device budget {self._budget} B cannot hold one "
                    f"{shape} slab ({self._slab_bytes(shape)} B) with "
                    "every resident session pinned")

    def _new_slab(self, shape: tuple[int, int]) -> int:
        ny, nx = shape
        slab_id = self._next_slab
        self._next_slab += 1
        planes = jnp.zeros((self._planes_per_slab, ny, nx), jnp.uint32)
        self._slabs[slab_id] = _Slab(
            shape=shape, planes=planes,
            free=(1 << self._capacity()) - 1)
        return slab_id

    def _take_lane_0(self, shape: tuple[int, int]) -> int:
        slab = self._slabs[self._next_slab - 1]
        slab.free &= ~1
        return 0

    def _write_lane(self, h: Handle, board: np.ndarray) -> None:
        slab = self._slabs[h.slab]
        slab.planes = _lane_write_jit(
            slab.planes, jnp.asarray(board, jnp.uint32),
            jnp.int32(h.lane // LANES_PER_PLANE),
            jnp.uint32(h.lane % LANES_PER_PLANE))

    def _read_lane(self, h: Handle) -> np.ndarray:
        slab = self._slabs[h.slab]
        return np.asarray(_lane_read_jit(
            slab.planes,
            jnp.int32(h.lane // LANES_PER_PLANE),
            jnp.uint32(h.lane % LANES_PER_PLANE)))

    def _free_lane(self, h: Handle) -> None:
        slab = self._slabs[h.slab]
        slab.free |= 1 << h.lane
        slab.lanes.pop(h.lane, None)
        if not slab.lanes:
            del self._slabs[h.slab]
            self._pending_settled.pop(h.slab, None)
            self.counts["slabs_freed"] += 1

    def _spill_one(self) -> bool:
        """Spill the least-recently-used unpinned resident session to
        the host tier; ``False`` when nothing is spillable."""
        from mpi_and_open_mp_tpu.obs import metrics

        for sid in self._lru:
            if sid in self._pinned:
                continue
            sess = self._sessions[sid]
            sess.host = self._read_lane(sess.handle)
            self._free_lane(sess.handle)
            sess.handle = None
            del self._lru[sid]
            self.counts["spills"] += 1
            metrics.inc("pool.spill")
            return True
        return False

    def _resolve_settled(self, slab_id: int) -> None:
        """Fetch a slab's deferred settled word (if one is pending) and
        fan the bits out to the dispatched sessions. Called before the
        slab's next step decision — by then the dispatch that produced
        the word has long completed, so the fetch is not a stall."""
        pending = self._pending_settled.pop(slab_id, None)
        if pending is None:
            return
        word, lanes = pending
        word = np.asarray(word)
        for sess, lane in lanes:
            sess.settled = bool(
                (int(word[lane // LANES_PER_PLANE])
                 >> (lane % LANES_PER_PLANE)) & 1)

    def _resident(self, sid: str) -> _Session:
        """The session, revived onto a lane if it was spilled. Counts
        the pool.hit/pool.miss pair — a miss is exactly one host→device
        board re-materialization."""
        from mpi_and_open_mp_tpu.obs import metrics

        sess = self._require(sid)
        if sess.handle is not None:
            self.counts["hits"] += 1
            metrics.inc("pool.hit")
            self._touch(sid)
            return sess
        self.counts["misses"] += 1
        self.counts["revivals"] += 1
        metrics.inc("pool.miss")
        h = self._alloc_lane(sess.shape)
        self._write_lane(h, sess.host)
        self._slabs[h.slab].lanes[h.lane] = sid
        sess.handle, sess.host = h, None
        sess.settled = False  # re-prove after any rewrite, never carry
        self._touch(sid)
        return sess

    # -- the session lifecycle ---------------------------------------------

    def create(self, sid: str, board: np.ndarray) -> Handle:
        """Admit one live session: the board crosses the wire ONCE,
        into a lane of a bit-sliced slab. Raises on a duplicate id —
        create/evict is the lifecycle, not upsert."""
        from mpi_and_open_mp_tpu.obs import metrics, trace

        if sid in self._sessions:
            raise PoolError(f"session {sid!r} already exists")
        board = np.asarray(board)
        if board.ndim != 2:
            raise PoolError(
                f"create: one 2D board per session, got {board.shape}")
        shape = (int(board.shape[0]), int(board.shape[1]))
        h = self._alloc_lane(shape)
        self._write_lane(h, (board != 0).astype(np.uint32))
        self._slabs[h.slab].lanes[h.lane] = sid
        self._sessions[sid] = _Session(sid=sid, shape=shape, handle=h)
        self._touch(sid)
        self.counts["creates"] += 1
        metrics.inc("pool.create")
        trace.event("pool.create", sid=sid, slab=h.slab, lane=h.lane,
                    shape=f"{shape[0]}x{shape[1]}")
        self._gauges()
        return h

    def step(self, sid: str, steps: int) -> None:
        """Advance ONE session in place — no board moves. A lone step
        and a 32-lane group step share the same compiled program (the
        lane mask is runtime data)."""
        self.step_group([sid], steps)

    def step_group(self, sids: list[str], steps: int) -> int:
        """Advance many sessions ``steps`` steps with as few dispatches
        as their slab placement allows: all lanes sharing a slab ride
        ONE in-place masked dispatch. Returns the dispatch count."""
        from mpi_and_open_mp_tpu.obs import metrics, trace

        steps = int(steps)
        if steps < 0:
            raise PoolError(f"steps must be >= 0, got {steps}")
        if not sids:
            return 0
        self._pinned.update(sids)
        try:
            by_slab: dict[int, list[_Session]] = {}
            for sid in sids:
                sess = self._resident(sid)
                by_slab.setdefault(sess.handle.slab, []).append(sess)
        finally:
            self._pinned.difference_update(sids)
        if steps == 0:
            return 0
        dispatches = skips = 0
        for slab_id, group in by_slab.items():
            slab = self._slabs[slab_id]
            self._resolve_settled(slab_id)
            if all(sess.settled for sess in group):
                # Every lane in the group is a proven fixed point:
                # advancing ANY step count is the identity, so the
                # logical step count moves while the device does
                # nothing. WAL STEP frames stay authoritative — replay
                # re-proves settledness from the board and lands on the
                # same bits whether or not the skip engages.
                skips += 1
                for sess in group:
                    sess.steps_applied += steps
                trace.event("pool.settled_skip", slab=slab_id,
                            lanes=len(group), steps=steps)
                continue
            mask = np.zeros(self._planes_per_slab, np.uint32)
            for sess in group:
                lane = sess.handle.lane
                mask[lane // LANES_PER_PLANE] |= np.uint32(
                    1 << (lane % LANES_PER_PLANE))
            slab.planes, settled = _pool_step_jit(
                slab.planes, jnp.int32(steps), jnp.asarray(mask))
            self._pending_settled[slab_id] = (
                settled, [(sess, sess.handle.lane) for sess in group])
            dispatches += 1
            for sess in group:
                sess.steps_applied += steps
            trace.event("pool.step", slab=slab_id, lanes=len(group),
                        steps=steps)
        self.counts["dispatches"] += dispatches
        self.counts["steps_applied"] += steps * len(sids)
        self.counts["settled_skips"] += skips
        metrics.inc("pool.dispatches", dispatches)
        if skips:
            metrics.inc("pool.settled_skips", skips)
        return dispatches

    def snapshot(self, sid: str) -> np.ndarray:
        """The session's current board, host-side (uint8) — one
        board-sized device→host read for resident sessions, a host copy
        for spilled ones (no revival)."""
        sess = self._require(sid)
        if sess.handle is None:
            return np.array(sess.host, dtype=np.uint8)
        self._touch(sid)
        return self._read_lane(sess.handle)

    def evict(self, sid: str) -> np.ndarray:
        """End the session: its final board comes back (the last wire
        crossing), its lane frees, an emptied slab is released."""
        from mpi_and_open_mp_tpu.obs import metrics, trace

        sess = self._require(sid)
        board = self.snapshot(sid)
        if sess.handle is not None:
            self._free_lane(sess.handle)
            self._lru.pop(sid, None)
        del self._sessions[sid]
        self.counts["evictions"] += 1
        metrics.inc("pool.evict")
        trace.event("pool.evict", sid=sid, steps=sess.steps_applied)
        self._gauges()
        return board

    # -- lane compaction ---------------------------------------------------

    def fragmented_shapes(self) -> list[tuple[int, int]]:
        """Shapes whose live lanes would fit in fewer slabs than they
        occupy — the compaction trigger condition."""
        by_shape: dict[tuple[int, int], tuple[int, int]] = {}
        for slab in self._slabs.values():
            n, live = by_shape.get(slab.shape, (0, 0))
            by_shape[slab.shape] = (n + 1, live + slab.live)
        cap = self._capacity()
        return [shape for shape, (n, live) in by_shape.items()
                if n > max(1, -(-live // cap)) or (n and live == 0)]

    def maybe_compact(self) -> dict | None:
        """Compact iff fragmented — the cheap poll the daemon pump runs
        between rounds; ``None`` when there is nothing to do."""
        return self.compact() if self.fragmented_shapes() else None

    def compact(self) -> dict:
        """Repack every fragmented shape's survivors 32-at-a-time
        through the existing pack/unpack kernels into the minimum slab
        count, free the emptied slabs, and re-point the handles. Step
        results are unchanged — lanes carry whole boards, so a migrated
        session is the same bits in a different word position."""
        from mpi_and_open_mp_tpu.obs import metrics, trace

        migrated = freed = 0
        cap = self._capacity()
        for shape in self.fragmented_shapes():
            slab_ids = sorted(s_id for s_id, sl in self._slabs.items()
                              if sl.shape == shape)
            # Unpack every live lane of the shape (the unpack kernel,
            # one call per donor slab), keyed by session.
            boards: list[np.ndarray] = []
            sids: list[str] = []
            for s_id in slab_ids:
                slab = self._slabs[s_id]
                if slab.lanes:
                    stack = np.asarray(unpack_batch_bits(
                        slab.planes, cap))
                    for lane, sid in sorted(slab.lanes.items()):
                        boards.append(stack[lane])
                        sids.append(sid)
                del self._slabs[s_id]
                # Lanes move: a deferred settled word indexed by the old
                # lane order must not resolve against the new layout.
                # Dropping it is conservative (settled stays False).
                self._pending_settled.pop(s_id, None)
                freed += 1
            # Repack 32*P-at-a-time (the pack kernel) into fresh dense
            # slabs; zero-padded tail lanes stay free.
            for lo in range(0, len(sids), cap):
                chunk_sids = sids[lo:lo + cap]
                chunk = np.stack(boards[lo:lo + cap]).astype(np.uint8)
                slab_id = self._next_slab
                self._next_slab += 1
                pad = cap - len(chunk_sids)
                if pad:
                    chunk = np.concatenate(
                        [chunk, np.zeros((pad, *shape), np.uint8)])
                self._slabs[slab_id] = _Slab(
                    shape=shape,
                    planes=pack_batch_bits(jnp.asarray(chunk)),
                    free=((1 << cap) - 1) & ~((1 << len(chunk_sids)) - 1),
                    lanes={i: sid for i, sid in enumerate(chunk_sids)})
                for i, sid in enumerate(chunk_sids):
                    old = self._sessions[sid].handle
                    if (old.slab, old.lane) != (slab_id, i):
                        migrated += 1
                    self._sessions[sid].handle = Handle(slab_id, i)
                freed -= 1
        self.counts["compactions"] += 1
        self.counts["migrated"] += migrated
        self.counts["slabs_freed"] += max(freed, 0)
        metrics.inc("pool.compactions")
        if migrated:
            metrics.inc("pool.migrated", migrated)
        trace.event("pool.compact", migrated=migrated, freed=freed)
        self._gauges()
        return {"migrated": migrated, "slabs_freed": max(freed, 0),
                "slabs": len(self._slabs)}
